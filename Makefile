PY ?= python

.PHONY: test lint lint-json baseline bench-check observe serve-metrics \
	soak soak-smoke rebalance-smoke service-bench progcheck \
	progcheck-baseline shardcheck shardcheck-baseline check \
	attribution attribution-check racecheck racecheck-baseline \
	kernelcheck kernelcheck-baseline incident-demo storecheck \
	grid-top history

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# regression guard: newest BENCH_r*.json capture vs the BEST committed
# history per guarded metric. Deltas are classified against the
# captures' own min-of-k spreads: WOBBLE (within noise) and WARN pass,
# REGRESSION (beyond max(10%, 2x noise)) = exit 1. `--legacy` restores
# the plain >10% binary gate. See telemetry/regress.py.
bench-check:
	$(PY) scripts/bench_check.py

# metrics plane demo: serve /metrics (OpenMetrics) + /healthz for a
# small in-process drift loop on 127.0.0.1:9100. Scrape with
#   curl localhost:9100/metrics
# Point --journal at StepRecorder JSONL shards to serve a real run
# (repeat the flag to pod-merge shards). See telemetry/metrics.py.
serve-metrics:
	JAX_PLATFORMS=cpu $(PY) scripts/metrics_serve.py --demo --port 9100

# grid observatory smoke: drift demo with the health monitor on, three
# legs on 8 virtual CPU devices. Balanced leg must stay OK (unexpected
# ALERT = exit 1) and writes a Perfetto trace; biased leg must ALERT
# (no alert = exit 2); corruption leg NaN-bursts a probed supervised
# service run and must detect -> page -> bundle -> restore pre-
# corruption (any broken link = exit 3). See telemetry/SCHEMA.md.
observe:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) examples/drift_demo.py --n 16384 --steps 20 \
		--trace observe_trace.json
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) examples/drift_demo.py --n 16384 --steps 20 \
		--bias --expect-alert
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		$(PY) examples/drift_demo.py --n 16384 --steps 20 \
		--corrupt

# service soak gate (bench/config8_soak.py --soak): short CPU soak of
# the fault-tolerant service driver with the snapshot cadence on and
# one injected mid-run crash. Fails (exit 1) unless the supervised
# restore is bit-identical to an uninterrupted run, exactly one restart
# happened, the async-snapshot overhead stays <= 2% of step time
# (min-of-k), and the elastic leg (crash + device loss -> shrink-restore
# onto half the mesh) resumes with an id-sorted particle set identical
# to the uninterrupted run. See mpi_grid_redistribute_tpu/service/.
soak:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		BENCH_SCALE=0.05 \
		$(PY) -m mpi_grid_redistribute_tpu.bench.config8_soak --soak

# CI-speed soak: same gate with a short crash/elastic horizon
# (BENCH_SOAK_STEPS) and few timing reps; the tier-1 suite runs the
# equivalent via tests/test_bench_configs.py so the shrink-restore leg
# is exercised on CPU in every CI pass. The snapshot-overhead budget is
# waived (SOAK_OVERHEAD_MAX) — at smoke scale the min-of-2 timing is
# noise; `make soak` owns the 2% gate.
soak-smoke:
	JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
		BENCH_SCALE=0.02 BENCH_SOAK_STEPS=12 BENCH_SOAK_EVERY=4 \
		BENCH_SOAK_K=2 SOAK_OVERHEAD_MAX=10 \
		$(PY) -m mpi_grid_redistribute_tpu.bench.config8_soak --soak

# CI-speed closed-loop adaptive-rebalance gate (ISSUE 9): twin config4
# drift-bias runs, loop on/off — asserts the imbalance_ratio ALERT
# fired, a rebalance applied, post-rebalance imbalance <= 1.1x, zero
# dropped rows, and the id-sorted particle set is bit-identical to the
# no-rebalance twin. The steady-state ms/step is regress-guarded
# (rebalance_drift_ms, LOWER) against committed captures instead.
rebalance-smoke:
	JAX_PLATFORMS=cpu \
		$(PY) -m mpi_grid_redistribute_tpu.bench.config4_drift --rebalance

# resident chunked-stepping gate (ISSUE 10): eager(chunk=1) vs chunked
# (chunk=16/64) ServiceDriver pps on the 8-vrank CPU mesh (4096 rows,
# one device — the measurement re-executes itself in a subprocess with
# any device forcing stripped), asserting the chunk=64 speedup floor
# (SERVICE_SPEEDUP_MIN, default 1.5x) and chunk-vs-eager final
# particle-set bit-identity. service_pps is regress-guarded against
# committed captures on top.
service-bench:
	JAX_PLATFORMS=cpu \
		$(PY) -m mpi_grid_redistribute_tpu.bench.config10_service --gate

# every analyzer family in --check text mode, driven off the single
# ANALYZERS registry in scripts/check_all.py (gridlint G, progcheck J,
# shardcheck S, attribution, racecheck T, kernelcheck K, incident-demo
# I, storecheck ST) — adding a family is one registry row, not a
# Makefile edit. Exit 0 = clean or
# fully baselined; 1 = new findings or stale baseline entries; 2 =
# usage/parse error. See mpi_grid_redistribute_tpu/analysis/.
lint:
	$(PY) scripts/check_all.py --lint

# one-shot CI umbrella: the same eight analyzers/gates, SARIF runs merged
# into a single analysis_merged.sarif for one code-scanning upload.
# Per-analyzer wall-time is printed so lint growth stays visible;
# `--analyzers NAME[,NAME]` subsets the registry for fast local loops.
check:
	$(PY) scripts/check_all.py

# roofline observatory (ISSUE 14): re-measure the knockout phase tables
# (both engines, both committed shapes) + the XLA cost-model roofline
# report, rewrite telemetry/attribution_baseline.json, and re-render
# the BENCH_CONFIGS.md CPU tables from it. Minutes of CPU.
attribution:
	$(PY) scripts/attribution.py --update-baseline --render

# attribution drift gate (also inside `make check`): structural only —
# snapshot exists, phase names/counts match the live knockout
# definitions, roofline covers every registered program, rendered
# markdown matches the snapshot. Never re-measures.
attribution-check:
	$(PY) scripts/attribution.py --check

# progcheck alone: trace every registered SPMD program on the virtual
# 8-device CPU mesh and gate J001-J004 plus the static wire/footprint
# profile against analysis/progprofile_baseline.json. No chip, no
# compile — make_jaxpr only.
progcheck:
	$(PY) scripts/progcheck.py --check

# refresh the J004 static-cost baseline after an INTENTIONAL wire or
# footprint change (justify the delta in the commit message)
progcheck-baseline:
	$(PY) scripts/progcheck.py --update-baseline

# shardcheck alone: infer per-mesh-axis vary-sets for every registered
# program and gate S001-S003 plus the S004 per-axis ICI/DCN wire
# attribution against progprofile_baseline.json's wire_attribution
# section. Same trace-only machinery as progcheck.
shardcheck:
	$(PY) scripts/shardcheck.py --check

# refresh the S004 wire-attribution baseline after an INTENTIONAL
# re-routing of collectives across the mesh (justify the delta)
shardcheck-baseline:
	$(PY) scripts/shardcheck.py --update-baseline

# racecheck alone: infer the host-thread topology (Thread targets +
# HTTP handler pools), the cross-thread shared-state matrix, and gate
# T001-T005 against analysis/racecheck_baseline.json. Pure ast — no
# jax, nothing scanned is executed. `--list-threads` dumps the
# inferred topology.
racecheck:
	$(PY) scripts/racecheck.py --check

# regenerate the racecheck baseline (then hand-edit each entry's
# justification — a bare regen is not a justification)
racecheck-baseline:
	$(PY) scripts/racecheck.py --write-baseline

# incident observatory smoke (ISSUE 17, also inside `make check`): a
# fault-injected supervised run on the numpy backend must leave
# flight-recorder bundles behind (alert- AND fault-triggered), every
# index.json must carry the triggering step context's trace id, the
# per-rule debounce must hold across restarts, and the frozen journal
# must export to a Perfetto trace with causal flow arrows. See
# telemetry/incident.py and scripts/incident.py.
incident-demo:
	JAX_PLATFORMS=cpu $(PY) scripts/incident_demo.py --check

# journal-store integrity gate (ISSUE 18, also inside `make check`):
# build a demo store through rotation + compaction + retention on a
# deliberately tiny wrapping recorder ring, then gate ST01-ST07 —
# segment sha256s vs the manifest, the count-conservation ledger, seq
# ordering, rotation/retention bounds, compaction exactness, and the
# headline claim: metrics.from_journal over the drained+compacted
# store equals the live recorder's all-time counts after eviction.
# Point it at a real store root to check a run's artifacts:
#   python scripts/storecheck.py /path/to/store
storecheck:
	JAX_PLATFORMS=cpu $(PY) scripts/storecheck.py --check

# one-shot dashboard snapshot over the storecheck demo store (CI-safe;
# live mode: scripts/grid_top.py --store DIR or --url http://host:port)
grid-top:
	JAX_PLATFORMS=cpu $(PY) scripts/storecheck.py --keep .grid_top_demo \
		> /dev/null && \
	JAX_PLATFORMS=cpu $(PY) scripts/grid_top.py \
		--store .grid_top_demo/store --once; \
	rm -rf .grid_top_demo

# run-index view: BENCH_r*.json perf trajectory (+ store runs via
# --stores DIR); `--check capture.json` gates a fresh capture against
# the whole indexed history through regress.classify_capture
history:
	JAX_PLATFORMS=cpu $(PY) scripts/history.py

# kernelcheck alone: capture every registered Pallas kernel's
# pallas_call anatomy via jax.eval_shape (no execution) and gate
# K000-K004 (index-map bounds, scatter coverage/overlap, VMEM
# footprint vs analysis/kernelcheck_baseline.json, lane tiling), then
# run the K005 interpret-mode bit-identity backstop on CPU. The
# ROADMAP item-3 megakernel must pass this gate (with a committed
# footprint row) before it is ever compiled on a chip.
kernelcheck:
	$(PY) scripts/kernelcheck.py --check

# refresh the K003 VMEM-footprint table after an INTENTIONAL blocking
# change (justify the footprint delta in the commit message)
kernelcheck-baseline:
	$(PY) scripts/kernelcheck.py --update-baseline

lint-json:
	$(PY) scripts/gridlint.py mpi_grid_redistribute_tpu/ --format=json

# regenerate the grandfathered-findings file (then hand-edit each
# entry's justification — a bare regen is not a justification)
baseline:
	$(PY) scripts/gridlint.py mpi_grid_redistribute_tpu/ --write-baseline
