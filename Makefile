PY ?= python

.PHONY: test lint lint-json baseline

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# gridlint: AST-based SPMD/JIT invariant checker (G001-G005).
# Exit 0 = clean or fully baselined; 1 = new findings or stale baseline
# entries; 2 = usage/parse error. See mpi_grid_redistribute_tpu/analysis/.
lint:
	$(PY) scripts/gridlint.py mpi_grid_redistribute_tpu/ --check

lint-json:
	$(PY) scripts/gridlint.py mpi_grid_redistribute_tpu/ --format=json

# regenerate the grandfathered-findings file (then hand-edit each
# entry's justification — a bare regen is not a justification)
baseline:
	$(PY) scripts/gridlint.py mpi_grid_redistribute_tpu/ --write-baseline
