"""Resident-slot migration path (parallel/migrate.py) vs the oracle.

Slot order is unspecified (arrivals land in arbitrary holes), so correctness
is *set* equality per shard against a NumPy reference drift loop, plus
conservation and surfaced-overflow accounting (SURVEY.md §4, §5.3).
"""

import numpy as np
import pytest

import jax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.models import nbody
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib


def _rows_set(pos, vel, mask):
    """EXACT bitcast-int row sets: the migrate path only ever moves rows
    (gather/all_to_all/scatter on the fused matrix), so payload bits must
    survive verbatim — a sub-1e-5 corruption in the bitcast fuse/scatter
    path is a bug, not noise (round-2 verdict item 9)."""
    rows = np.concatenate([pos[mask], vel[mask]], axis=1)
    return {tuple(r) for r in rows.view(np.uint32).tolist()}


def _np_drift_reference(domain, grid, pos, vel, alive, dt, n_steps):
    """Reference drift loop: returns per-shard row sets after n_steps.

    The drift arithmetic runs through the same XLA-compiled elementwise
    kernel as the device step (one jit, unsharded) so float32 rounding —
    including any multiply-add contraction — is bit-identical; the
    redistribution bookkeeping stays plain NumPy. The migrate path itself
    only moves rows, so the final sets must match the device EXACTLY."""
    import jax.numpy as jnp

    @jax.jit
    def _drift(p, v):
        return binning.wrap_periodic(p + v * jnp.asarray(dt, p.dtype), domain)

    pos, vel, alive = pos.copy(), vel.copy(), alive.copy()
    for _ in range(n_steps):
        pos[alive] = np.asarray(_drift(pos[alive], vel[alive]))
    dest = binning.rank_of_position(pos, domain, grid, xp=np)
    shard_sets = []
    for r in range(grid.nranks):
        m = alive & (dest == r)
        shard_sets.append(_rows_set(pos, vel, m))
    return shard_sets


@pytest.mark.parametrize("shape", [(2, 2, 2), (4, 2, 1)])
def test_migrate_matches_reference_sets(shape, rng, _devices):
    grid = ProcessGrid(shape)
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 64
    n = R * n_local
    mesh = mesh_lib.make_mesh(grid)

    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.6 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    # start with some holes: ~1/8 of slots dead
    alive = rng.random(n) > 0.125
    # place live rows on their owning shard so the starting state is legal
    dest = binning.rank_of_position(pos, domain, grid, xp=np)
    slot_shard = np.repeat(np.arange(R), n_local)
    alive &= dest == slot_shard

    n_steps = 5
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.07, capacity=n_local, n_local=n_local
    )
    loop = nbody.make_migrate_loop(cfg, mesh, n_steps)
    pos_f, vel_f, alive_f, stats = jax.tree.map(
        np.asarray, loop(pos, vel, alive)
    )
    pos_f = nbody.planar_to_rows(pos_f, 3, mesh.size)
    vel_f = nbody.planar_to_rows(vel_f, 3, mesh.size)

    assert stats.backlog.sum() == 0
    assert stats.dropped_recv.sum() == 0
    assert alive_f.sum() == alive.sum()
    # every step's populations sum to the global total
    assert (stats.population.sum(axis=1) == alive.sum()).all()

    # ownership: every live row sits on the shard that owns its position
    dest_f = binning.rank_of_position(pos_f, domain, grid, xp=np)
    assert (dest_f[alive_f] == slot_shard[alive_f]).all()

    want = _np_drift_reference(
        domain, grid, pos, vel, alive, np.float32(0.07), n_steps
    )
    for r in range(R):
        sl = slice(r * n_local, (r + 1) * n_local)
        got = _rows_set(pos_f[sl], vel_f[sl], alive_f[sl])
        assert got == want[r], f"shard {r} row set mismatch"


def test_migrate_step_stats_and_idempotence(rng, _devices):
    grid = ProcessGrid((2, 2, 2))
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 32
    n = R * n_local
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.0, capacity=8, n_local=n_local
    )
    step = nbody.make_migrate_step(cfg, mesh)

    pos = rng.random((n, 3), dtype=np.float32)
    vel = np.zeros((n, 3), dtype=np.float32)
    # legal start: all rows on owner shard
    dest = binning.rank_of_position(pos, domain, grid, xp=np)
    alive = dest == np.repeat(np.arange(R), n_local)

    out = jax.tree.map(np.asarray, step(pos, vel, alive))
    pos1, vel1, alive1, stats = out
    # dt=0 and legal start: nothing moves
    assert stats.sent.sum() == 0
    assert stats.received.sum() == 0
    assert (alive1 == alive).all()
    assert (pos1[alive] == pos[alive]).all()


def test_migrate_overflow_backlogs_lossless(rng, _devices):
    """All particles head to one full shard: the receiver grants nothing
    (no free slots, nothing to swap), so nothing is sent, nothing drops,
    and every mover stays resident in backlog to retry."""
    grid = ProcessGrid((8, 1, 1))
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 16
    n = R * n_local
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=1.0, capacity=2, n_local=n_local
    )
    step = nbody.make_migrate_step(cfg, mesh)

    # every particle sits at x-center of its slot shard, vel pushes all into
    # shard 0's column
    pos = rng.random((n, 3), dtype=np.float32)
    shard = np.repeat(np.arange(R), n_local)
    pos[:, 0] = (shard + 0.5) / R
    vel = np.zeros((n, 3), dtype=np.float32)
    vel[:, 0] = (0.5 / R) - pos[:, 0]  # land inside shard 0 after dt=1
    alive = np.ones(n, dtype=bool)

    pos1, vel1, alive1, stats = jax.tree.map(
        np.asarray, step(pos, vel, alive)
    )
    sent = stats.sent.sum()
    received = stats.received.sum()
    bl, dr = stats.backlog.sum(), stats.dropped_recv.sum()
    # 7 shards * 16 particles want to move; shard 0 is completely full and
    # has no departures to swap against, so its grants are zero: nothing
    # flies, nothing drops, every mover is backlogged and stays alive.
    assert sent == 0
    assert received == 0
    assert dr == 0
    assert bl == n_local * (R - 1)
    assert alive1.sum() == n  # lossless by construction


def test_migrate_backlog_drains(rng, _devices):
    """Backlogged migrants retry and land on later steps once capacity and
    free slots allow."""
    grid = ProcessGrid((2, 1, 1))
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 32
    n = R * n_local
    mesh = mesh_lib.make_mesh(grid, devices=jax.devices()[:2])
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.0, capacity=4, n_local=n_local
    )
    step = nbody.make_migrate_step(cfg, mesh)

    # shard 0: half its rows positioned in shard 1's half-box (16 movers,
    # capacity 4/step); shard 1: half its slots dead (16 free slots)
    pos = rng.random((n, 3), dtype=np.float32)
    pos[:n_local, 0] = np.where(
        np.arange(n_local) < 16,
        0.75,  # owned by shard 1
        0.25,
    ).astype(np.float32)
    pos[n_local:, 0] = 0.75
    vel = np.zeros((n, 3), dtype=np.float32)
    alive = np.ones(n, dtype=bool)
    alive[n_local + 16 :] = False

    total0 = alive.sum()
    moved = 0
    state = (pos, vel, alive)
    for i in range(4):
        p, v, a, stats = jax.tree.map(np.asarray, step(*state))
        state = (p, v, a)
        assert stats.dropped_recv.sum() == 0
        assert stats.sent.sum() == 4  # capacity-limited every step
        moved += stats.sent.sum()
        assert a.sum() == total0
    assert moved == 16  # the full backlog drained at 4/step


def test_migrate_vranks_full_swap_is_lossless(rng, _devices):
    """Two fully-occupied vranks exchanging every particle must complete
    the swap (arrivals may land in same-step-vacated slots; the fixpoint
    allocation seeds with self-financing pairwise swaps)."""
    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((2, 1, 1))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 8
    n = 2 * n_local
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])

    # vrank 0 owns x in [0, .5), vrank 1 owns [.5, 1); place every row in
    # the OTHER vrank's half-box, zero velocity, zero free slots.
    pos = rng.random((n, 3), dtype=np.float32)
    pos[:n_local, 0] = 0.75
    pos[n_local:, 0] = 0.25
    vel = np.zeros((n, 3), dtype=np.float32)
    alive = np.ones(n, dtype=bool)

    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.0, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, 1, vgrid=vgrid)
    pos_f, vel_f, alive_f, stats = jax.tree.map(
        np.asarray, loop(pos, vel, alive)
    )
    pos_f = nbody.planar_to_rows(pos_f, 3, mesh.size)
    vel_f = nbody.planar_to_rows(vel_f, 3, mesh.size)
    assert stats.dropped_recv.sum() == 0
    assert stats.backlog.sum() == 0
    assert stats.sent.sum() == n
    assert alive_f.sum() == n
    # every row now sits on its owning vrank slab
    assert (pos_f[:n_local, 0] < 0.5).all()
    assert (pos_f[n_local:, 0] >= 0.5).all()


def _slab_full_ranks(dev_grid, vgrid):
    """full-grid rank of each (device, vrank) slab, device-major order."""
    full = ProcessGrid(
        tuple(d * v for d, v in zip(dev_grid.shape, vgrid.shape))
    )
    out = []
    for d in range(dev_grid.nranks):
        dc = dev_grid.cell_of_rank(d)
        for v in range(vgrid.nranks):
            vc = vgrid.cell_of_rank(v)
            cell = tuple(
                dc[a] * vgrid.shape[a] + vc[a] for a in range(len(dc))
            )
            out.append(full.rank_of_cell(cell))
    return full, np.asarray(out)


@pytest.mark.parametrize(
    "dev_shape,v_shape",
    [((1, 1, 1), (2, 2, 2)), ((2, 2, 1), (1, 2, 2)), ((2, 1, 1), (2, 2, 1))],
)
def test_migrate_vranks_matches_reference_sets(dev_shape, v_shape, rng, _devices):
    dev_grid = ProcessGrid(dev_shape)
    vgrid = ProcessGrid(v_shape)
    full, slab_rank = _slab_full_ranks(dev_grid, vgrid)
    R = full.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 64
    n = R * n_local
    mesh = mesh_lib.make_mesh(dev_grid)

    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.6 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    alive = rng.random(n) > 0.125
    # legal start: live rows sit on the slab owning their position
    dest = binning.rank_of_position(pos, domain, full, xp=np)
    slot_slab = np.repeat(slab_rank, n_local)  # device-major slabs
    alive &= dest == slot_slab

    n_steps = 5
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.07, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, n_steps, vgrid=vgrid)
    pos_f, vel_f, alive_f, stats = jax.tree.map(
        np.asarray, loop(pos, vel, alive)
    )
    pos_f = nbody.planar_to_rows(pos_f, 3, mesh.size)
    vel_f = nbody.planar_to_rows(vel_f, 3, mesh.size)

    assert stats.backlog.sum() == 0
    assert stats.dropped_recv.sum() == 0
    assert alive_f.sum() == alive.sum()

    dest_f = binning.rank_of_position(pos_f, domain, full, xp=np)
    assert (dest_f[alive_f] == slot_slab[alive_f]).all()

    want = _np_drift_reference(
        domain, full, pos, vel, alive, np.float32(0.07), n_steps
    )
    for slab in range(R):
        sl = slice(slab * n_local, (slab + 1) * n_local)
        got = _rows_set(pos_f[sl], vel_f[sl], alive_f[sl])
        assert got == want[slab_rank[slab]], f"slab {slab} mismatch"


def test_vranks_cross_device_receive_is_lossless(rng, _devices):
    """Cross-device arrivals are receiver-granted: a nearly-full remote
    slab grants only its free slots, excess movers backlog, and nothing
    ever drops (round-1 verdict weak item 4, closed)."""
    dev_grid = ProcessGrid((2, 1, 1))
    vgrid = ProcessGrid((1, 2, 1))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 32
    n = 4 * n_local
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:2])
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=1.0, capacity=n_local,
        n_local=n_local,
    )

    # slab layout (device-major): 0:(0,0) 1:(0,1) 2:(1,0) 3:(1,1).
    # Fill slab 2 (device 1) completely except `free` slots; aim slab 0's
    # movers (device 0) at slab 2's subdomain -> cross-device pressure.
    free = 4
    pos = np.zeros((n, 3), np.float32)
    vel = np.zeros((n, 3), np.float32)
    alive = np.ones((n,), bool)
    # slab 0 rows sit in (x<0.5, y<0.5); velocity pushes them to x>0.5
    pos[:n_local] = rng.uniform(0.01, 0.45, (n_local, 3)).astype(np.float32)
    vel[:n_local, 0] = 0.5
    # slab 1 (0,1): legal resident rows, y in upper half
    pos[n_local:2*n_local] = rng.uniform(0.01, 0.45, (n_local, 3))
    pos[n_local:2*n_local, 1] += 0.5
    # slab 2 (1,0): x upper half; last `free` slots are holes
    pos[2*n_local:3*n_local] = rng.uniform(0.55, 0.95, (n_local, 3))
    pos[2*n_local:3*n_local, 1] -= 0.5
    pos[2*n_local:3*n_local, 1] %= 0.5
    alive[3*n_local - free:3*n_local] = False
    # slab 3 (1,1): legal
    pos[3*n_local:] = rng.uniform(0.55, 0.95, (n_local, 3))
    loop = nbody.make_migrate_loop(cfg, mesh, 1, vgrid=vgrid)
    p1, v1, a1, stats = jax.tree.map(np.asarray, loop(pos, vel, alive))
    assert stats.dropped_recv.sum() == 0
    assert a1.sum() == alive.sum()  # lossless
    # only `free` movers could land; the rest are backlogged
    assert stats.sent.sum() == free
    assert stats.backlog.sum() == n_local - free


def test_migrate_vranks_full_rotation_cycle_drains(rng, _devices):
    """A pure rotation cycle of length 3 between COMPLETELY full vranks
    at zero free slots — the round-2 documented stall — must now drain
    via the forced cycle swaps (one row per member per step), ending at
    zero backlog with every row on its owner (round-2 verdict item 5)."""
    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((3, 1, 1))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 8
    n = 3 * n_local
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])

    # vrank v owns x in [v/3, (v+1)/3); place EVERY row of vrank v inside
    # vrank (v+1)%3's slab -> 0 -> 1 -> 2 -> 0 rotation, zero holes.
    pos = rng.random((n, 3), dtype=np.float32)
    for v in range(3):
        nxt = (v + 1) % 3
        pos[v * n_local : (v + 1) * n_local, 0] = (
            (nxt + 0.5) / 3.0
        )
    vel = np.zeros((n, 3), dtype=np.float32)
    alive = np.ones(n, dtype=bool)

    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.0, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, n_local, vgrid=vgrid)
    pos_f, vel_f, alive_f, stats = jax.tree.map(
        np.asarray, loop(pos, vel, alive)
    )
    pos_f = nbody.planar_to_rows(pos_f, 3, mesh.size)
    assert stats.dropped_recv.sum() == 0
    assert alive_f.sum() == n
    # one forced swap per member per step: backlog shrinks monotonically
    per_step = stats.backlog.sum(axis=1)
    assert per_step[0] == n - 3  # 3 rows moved on the first step
    assert per_step[-1] == 0, f"cycle did not drain: {per_step}"
    # every row ended on its owning vrank slab
    full = ProcessGrid((3, 1, 1))
    dest_f = binning.rank_of_position(pos_f, domain, full, xp=np)
    assert (dest_f == np.repeat(np.arange(3), n_local)).all()


def test_migrate_vranks_cross_device_cycle_drains(rng, _devices):
    """A pure rotation cycle of length 3 whose members live on TWO
    devices, every vrank completely full at zero free slots — the
    round-3 documented limitation (`no cross-device swap financing`).
    The round-4 global cycle rescue must drain it: the forced remote
    arrival pops the slot the member's forced departure pushed, so the
    cycle drains one row per member per step with zero drops."""
    dev_grid = ProcessGrid((2, 1, 1))
    vgrid = ProcessGrid((2, 1, 1))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 8
    V, R_total = 2, 4
    n = R_total * n_local
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:2])

    # global rank g owns x in [g/4, (g+1)/4); ranks 0 (dev 0) and
    # 2, 3 (dev 1) form the cycle 0 -> 2 -> 3 -> 0 (crossing devices
    # twice); rank 1 is full and static (every row already home).
    pos = rng.random((n, 3), dtype=np.float32)
    cycle = {0: 2, 2: 3, 3: 0}
    for g in range(R_total):
        tgt = cycle.get(g, g)
        pos[g * n_local : (g + 1) * n_local, 0] = (tgt + 0.5) / 4.0
    vel = np.zeros((n, 3), dtype=np.float32)
    alive = np.ones(n, dtype=bool)

    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.0, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, n_local + 2, vgrid=vgrid)
    pos_f, vel_f, alive_f, stats = jax.tree.map(
        np.asarray, loop(pos, vel, alive)
    )
    pos_f = nbody.planar_to_rows(pos_f, 3, mesh.size)
    assert stats.dropped_recv.sum() == 0
    assert alive_f.sum() == n
    per_step = stats.backlog.sum(axis=1)
    assert per_step[-1] == 0, f"cross-device cycle did not drain: {per_step}"
    # every row ended on its owning global rank slab
    full = ProcessGrid((4, 1, 1))
    dest_f = binning.rank_of_position(pos_f, domain, full, xp=np)
    assert (dest_f == np.repeat(np.arange(4), n_local)).all()


def test_migrate_flat_full_rotation_cycle_drains(rng, _devices):
    """Same 3-cycle stall on the flat multi-device path: the all_gather
    cycle rescue must drain it."""
    grid = ProcessGrid((3, 1, 1))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 6
    n = 3 * n_local
    mesh = mesh_lib.make_mesh(grid, devices=jax.devices()[:3])

    pos = rng.random((n, 3), dtype=np.float32)
    for v in range(3):
        nxt = (v + 1) % 3
        pos[v * n_local : (v + 1) * n_local, 0] = (nxt + 0.5) / 3.0
    vel = np.zeros((n, 3), dtype=np.float32)
    alive = np.ones(n, dtype=bool)

    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.0, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, n_local)
    pos_f, vel_f, alive_f, stats = jax.tree.map(
        np.asarray, loop(pos, vel, alive)
    )
    pos_f = nbody.planar_to_rows(pos_f, 3, mesh.size)
    assert stats.dropped_recv.sum() == 0
    assert alive_f.sum() == n
    per_step = stats.backlog.sum(axis=1)
    assert per_step[-1] == 0, f"cycle did not drain: {per_step}"
    dest_f = binning.rank_of_position(pos_f, domain, grid, xp=np)
    assert (dest_f == np.repeat(np.arange(3), n_local)).all()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_migrate_random_pressure_conserves(seed, _devices):
    """Fuzz: random fills, velocities and capacities — alive count is
    invariant and nothing ever drops, on both the flat multi-device path
    and the vrank two-tier path (grant-protocol safety net)."""
    rng = np.random.default_rng(seed)
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = int(rng.integers(24, 72))
    cap = int(rng.integers(2, 10))

    # flat path: 8 devices
    grid = ProcessGrid((2, 2, 2))
    n = grid.nranks * n_local
    pos = rng.random((n, 3)).astype(np.float32)
    vel = (rng.random((n, 3)).astype(np.float32) - 0.5) * 0.8
    alive = rng.random(n) < rng.uniform(0.3, 1.0)
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.3, capacity=cap, n_local=n_local
    )
    mesh = mesh_lib.make_mesh(grid)
    loop = nbody.make_migrate_loop(cfg, mesh, 6)
    _, _, a1, st = jax.tree.map(np.asarray, loop(pos, vel, alive))
    assert st.dropped_recv.sum() == 0
    assert a1.sum() == alive.sum()

    # vrank two-tier path: 2 devices x 4 vranks
    dev_grid = ProcessGrid((2, 1, 1))
    vgrid = ProcessGrid((2, 2, 1))
    vmesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:2])
    vcfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.3, capacity=cap,
        n_local=n_local, local_budget=int(rng.integers(8, 64)),
    )
    vloop = nbody.make_migrate_loop(vcfg, vmesh, 6, vgrid=vgrid)
    _, _, a2, st2 = jax.tree.map(np.asarray, vloop(pos, vel, alive))
    assert st2.dropped_recv.sum() == 0
    assert a2.sum() == alive.sum()


def test_balanced_assignment_properties():
    from mpi_grid_redistribute_tpu.parallel import migrate

    rng = np.random.default_rng(3)
    loads = (rng.lognormal(0.0, 1.5, size=64) * 100).astype(np.int64)
    assign = migrate.balanced_assignment(loads, 8)
    assert len(assign) == 64 and set(assign) == set(range(8))
    bins = np.bincount(np.asarray(assign), weights=loads, minlength=8)
    # LPT guarantee: max bin <= 4/3 OPT; OPT >= mean
    assert bins.max() <= (4 / 3) * max(loads.sum() / 8, loads.max()) + 1
    with pytest.raises(ValueError):
        migrate.balanced_assignment(loads[:4], 8)


def test_migrate_vranks_assignment_matches_reference(rng, _devices):
    """Load-balanced cell->vrank assignment: clustered rows on a 4x4x4
    cell grid run as 8 vranks with uniform slabs sized ~mean load, and
    the engine routes every row to its ASSIGNED vrank (set-equality at
    the bit level vs the reference drift), lossless."""
    from mpi_grid_redistribute_tpu.parallel import migrate

    domain = Domain(0.0, 1.0, periodic=True)
    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((2, 2, 2))
    cells = ProcessGrid((4, 4, 4))
    V = vgrid.nranks
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])

    total = 2048
    pos = (rng.lognormal(-1.0, 1.2, size=(total, 3)) % 1.0).astype(
        np.float32
    )
    cell = binning.rank_of_position(pos, domain, cells, xp=np)
    loads = np.bincount(cell, minlength=cells.nranks)
    assign = migrate.balanced_assignment(loads, V)
    owner = np.asarray(assign)[cell]
    bins = np.bincount(owner, minlength=V)
    assert bins.max() < 2 * total / V  # the balance actually balanced

    n_local = int(bins.max() * 1.5)
    pos_p = np.zeros((V * n_local, 3), np.float32)
    vel_p = np.zeros((V * n_local, 3), np.float32)
    alive = np.zeros((V * n_local,), bool)
    vel = (0.1 * (rng.random((total, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    for v in range(V):
        m = owner == v
        k = int(m.sum())
        pos_p[v * n_local : v * n_local + k] = pos[m]
        vel_p[v * n_local : v * n_local + k] = vel[m]
        alive[v * n_local : v * n_local + k] = True

    n_steps = 5
    dt = 0.07
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=dt, capacity=n_local,
        n_local=n_local, local_budget=2 * n_local,
        cells=cells, assignment=assign,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, n_steps, vgrid=vgrid)
    pos_f, vel_f, alive_f, stats = jax.tree.map(
        np.asarray, loop(pos_p, vel_p, alive)
    )
    pos_f = nbody.planar_to_rows(pos_f, 3, mesh.size)
    vel_f = nbody.planar_to_rows(vel_f, 3, mesh.size)

    assert stats.dropped_recv.sum() == 0
    assert stats.backlog[-1].sum() == 0
    assert alive_f.sum() == total

    # ownership: every live row sits on the vrank its cell is ASSIGNED to
    cell_f = binning.rank_of_position(pos_f, domain, cells, xp=np)
    owner_f = np.asarray(assign)[cell_f]
    slot_v = np.repeat(np.arange(V), n_local)
    assert (owner_f[alive_f] == slot_v[alive_f]).all()

    # bit-level set equality vs the reference drift, grouped by ASSIGNED
    # rank (reference reuses the same XLA drift kernel; see
    # _np_drift_reference)
    import jax.numpy as jnp

    @jax.jit
    def _drift(p, v):
        return binning.wrap_periodic(
            p + v * jnp.asarray(dt, p.dtype), domain
        )

    rp, rv, ra = pos_p.copy(), vel_p.copy(), alive.copy()
    for _ in range(n_steps):
        rp[ra] = np.asarray(_drift(rp[ra], rv[ra]))
    rcell = binning.rank_of_position(rp, domain, cells, xp=np)
    rowner = np.asarray(assign)[rcell]
    for v in range(V):
        sl = slice(v * n_local, (v + 1) * n_local)
        got = _rows_set(pos_f[sl], vel_f[sl], alive_f[sl])
        want = _rows_set(rp, rv, ra & (rowner == v))
        assert got == want, f"vrank {v} row set mismatch"


def test_migrate_assignment_validation(rng, _devices):
    from mpi_grid_redistribute_tpu.parallel import migrate

    domain = Domain(0.0, 1.0, periodic=True)
    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((2, 1, 1))
    cells = ProcessGrid((4, 1, 1))
    with pytest.raises(ValueError, match="together"):
        migrate.shard_migrate_vranks_fn(
            domain, dev_grid, vgrid, 8, assignment=(0, 1, 0, 1)
        )
    with pytest.raises(ValueError, match="entries"):
        migrate.shard_migrate_vranks_fn(
            domain, dev_grid, vgrid, 8, cells=cells, assignment=(0, 1)
        )
    with pytest.raises(ValueError, match="outside"):
        migrate.shard_migrate_vranks_fn(
            domain, dev_grid, vgrid, 8, cells=cells,
            assignment=(0, 1, 2, 1),
        )
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.0, capacity=8, n_local=16,
        cells=cells, assignment=(0, 1, 0, 1),
    )
    with pytest.raises(ValueError, match="vrank path"):
        nbody.make_migrate_loop(cfg, mesh, 1)  # no vgrid
    import dataclasses as _dc

    # single-device scan deposit keys by DEVICE cell (position, not vrank
    # membership), so LPT assignment now composes with it (late round 4)
    cfg2 = _dc.replace(cfg, deposit_shape=(4, 4, 4))
    nbody.make_migrate_loop(cfg2, mesh, 1, vgrid=vgrid)  # must not raise
    # ...but the per-vrank-block paths still cannot serve assignment-
    # decomposed vranks: segment-method deposit, and any multi-device mesh
    cfg3 = _dc.replace(cfg2, deposit_method="segment")
    with pytest.raises(ValueError, match="deposit"):
        nbody.make_migrate_loop(cfg3, mesh, 1, vgrid=vgrid)
    mesh2 = mesh_lib.make_mesh(
        ProcessGrid((2, 1, 1)), devices=jax.devices()[:2]
    )
    cfg4 = _dc.replace(
        cfg2, grid=ProcessGrid((2, 1, 1)),
        cells=ProcessGrid((2, 2, 1)), assignment=(0, 1, 0, 1),
    )
    with pytest.raises(ValueError, match="deposit"):
        nbody.make_migrate_loop(cfg4, mesh2, 1, vgrid=ProcessGrid((1, 2, 1)))


def test_plan_rows_batched_matches_vmapped(rng):
    """The telescoped/flat-take batched plan (round 4) must reproduce the
    per-vrank ``_plan_rows`` bit-for-bit — it feeds the vacated-slot plan
    of the vrank engine, whose landing correctness rides on it."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.parallel import migrate

    for V, S, n, length in [(4, 4, 257, 64), (8, 8, 1024, 300),
                            (3, 7, 50, 128)]:
        seg_counts = rng.integers(0, 30, size=(V, S)).astype(np.int32)
        seg_starts = np.cumsum(
            np.concatenate(
                [rng.integers(0, 5, size=(V, 1)), seg_counts[:, :-1]],
                axis=1,
            ),
            axis=1,
        ).astype(np.int32)
        order = np.stack(
            [rng.permutation(n).astype(np.int32) for _ in range(V)]
        )
        ref_v, ref_t = jax.vmap(
            lambda ss, sc, o: migrate._plan_rows(ss, sc, o, length)
        )(jnp.asarray(seg_starts), jnp.asarray(seg_counts),
          jnp.asarray(order))
        got_v, got_t = migrate._plan_rows_batched(
            jnp.asarray(seg_starts), jnp.asarray(seg_counts),
            jnp.asarray(order), length
        )
        # entries beyond each vrank's total are clipped junk by contract
        # (callers mask by j < total); compare only the meaningful prefix
        ref_v, got_v = np.asarray(ref_v), np.asarray(got_v)
        tot = np.asarray(ref_t)
        assert np.array_equal(tot, np.asarray(got_t))
        for v in range(V):
            k = min(int(tot[v]), length)
            assert np.array_equal(ref_v[v, :k], got_v[v, :k]), (V, S, v)


def test_stack_push_pop_window_matches_gather(rng):
    """Round-4 affine-window pushes: one dynamic slice of the padded plan
    must equal the direct ``vacated[clip(n_in + (w - rel))]`` gather on
    the in-use window entries."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.parallel import migrate

    n, P = 96, 32
    for trial in range(20):
        free_stack = rng.permutation(n).astype(np.int32)
        vacated = rng.integers(0, n, size=P).astype(np.int32)
        n_free = int(rng.integers(0, n))
        n_in = int(rng.integers(0, P // 2))
        n_sent = int(rng.integers(n_in, P))
        n_push = max(n_sent - n_in, 0)
        n_pop = int(rng.integers(0, min(n_free, P - 1) + 1))
        fs2, nf2 = migrate._stack_push_pop(
            jnp.asarray(free_stack), jnp.int32(n_free), jnp.int32(n_pop),
            jnp.int32(n_push), jnp.asarray(vacated), jnp.int32(n_in)
        )
        # reference semantics
        fs_ref = free_stack.copy()
        W = min(P, n)
        win_start = int(np.clip(n_free, 0, max(n - W, 0)))
        rel = n_free - win_start
        for w in range(W):
            if rel <= w < rel + n_push:
                idx = int(np.clip(n_in + (w - rel), 0, P - 1))
                if 0 <= win_start + w < n:
                    fs_ref[win_start + w] = vacated[idx]
        assert int(nf2) == n_free - n_pop + n_push
        assert np.array_equal(np.asarray(fs2), fs_ref), trial


def test_sorted_dest_counts_packed_fallback_boundary(rng):
    """The packed one-word sort (round 4) and the 2-operand fallback must
    agree bit-for-bit; force both paths across the bit-budget boundary."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import binning

    n = 4096  # b = 12 bits -> packed path needs n_dest + 1 <= 2^19
    for n_dest in [7, 64, (1 << 19) - 1, 1 << 19]:
        dest = rng.integers(0, n_dest + 1, size=n).astype(np.int32)
        o, c, b = binning.sorted_dest_counts(jnp.asarray(dest), n_dest)
        iota = np.arange(n)
        ordr = np.lexsort((iota, dest))
        ks = dest[ordr]
        bounds = np.searchsorted(
            ks, np.arange(n_dest + 1), side="left"
        ).astype(np.int32)
        assert np.array_equal(np.asarray(o), ordr), n_dest
        assert np.array_equal(np.asarray(b), bounds), n_dest


def test_vacated_prefix_fast_path_identity(rng):
    """The unclipped vacated-slot fast path (round 4) rests on an exact
    identity: with stayers sorted to the END (sentinel dest key) and
    ``allowed == eff`` (prefix-truncated full counts), the slow plan's
    positions are pos[v, j] = j, so the plan IS ``order[:, :P]``.
    Verify bit-for-bit on sorted-dest instances, and that one clipped
    pair breaks the identity (the engine's cond then takes the slow
    path)."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import binning
    from mpi_grid_redistribute_tpu.parallel import migrate

    V, n, n_dest, M = 5, 512, 5, 96
    dest = rng.integers(0, n_dest, size=(V, n)).astype(np.int32)
    self_id = np.arange(V, dtype=np.int32)
    # mark ~90% as staying (sentinel key n_dest), like the real engine
    stay = rng.random((V, n)) < 0.9
    key = np.where(stay, n_dest, dest).astype(np.int32)
    order, counts, bounds = jax.vmap(
        lambda k: binning.sorted_dest_counts(k, n_dest)
    )(jnp.asarray(key))
    loc_starts = np.asarray(bounds)[:, :n_dest].astype(np.int32)
    full = np.asarray(counts).astype(np.int32)
    # eff = prefix truncation of full counts at budget M (engine formula)
    rel_start = loc_starts - loc_starts[:, :1]
    rel_end = rel_start + full
    eff = np.clip(np.minimum(rel_end, M) - np.minimum(rel_start, M), 0,
                  None).astype(np.int32)
    P = M
    slow, tot = migrate._plan_rows_batched(
        jnp.asarray(loc_starts), jnp.asarray(eff), jnp.asarray(order), P
    )
    slow, tot = np.asarray(slow), np.asarray(tot)
    fast = np.asarray(order)[:, :P]
    for v in range(V):
        k = min(int(tot[v]), P)
        assert np.array_equal(slow[v, :k], fast[v, :k]), v
    # clip one mid-plan pair -> identity must break for that vrank
    clipped = eff.copy()
    v_bad, w_bad = 2, 1
    if clipped[v_bad, w_bad] > 1:
        clipped[v_bad, w_bad] -= 1
        slow2, tot2 = migrate._plan_rows_batched(
            jnp.asarray(loc_starts), jnp.asarray(clipped),
            jnp.asarray(order), P
        )
        slow2, tot2 = np.asarray(slow2), np.asarray(tot2)
        k = min(int(tot2[v_bad]), P)
        assert not np.array_equal(slow2[v_bad, :k], fast[v_bad, :k])


def test_plan_rows_batched_seg_rows_matches_reference(rng):
    """``seg_rows`` mode (round 4 — the arrival plan): segments of one
    plan row read DIFFERENT rows of ``order`` and values come back
    globalized as ``s * n + order[s, pos]``. Reference = the vmapped
    per-destination formulation it replaced, written plainly in NumPy."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.parallel import migrate

    for V, n, M in [(4, 257, 64), (8, 1024, 300), (3, 50, 40)]:
        # per-source segment starts/counts as the engine lays them out:
        # loc_starts[s, w] = start of (s -> w) in source s's sorted
        # space; allowed[s, w] = granted rows of that segment
        counts = rng.integers(0, 20, size=(V, V)).astype(np.int32)
        starts = np.cumsum(
            np.concatenate(
                [rng.integers(0, 3, size=(V, 1)), counts[:, :-1]], axis=1
            ),
            axis=1,
        ).astype(np.int32)
        allowed = np.minimum(
            counts, rng.integers(0, 20, size=(V, V))
        ).astype(np.int32)
        order = np.stack(
            [rng.permutation(n).astype(np.int32) for _ in range(V)]
        )
        got, tot = migrate._plan_rows_batched(
            jnp.asarray(starts.T), jnp.asarray(allowed.T),
            jnp.asarray(order), M,
            seg_rows=jnp.arange(V, dtype=jnp.int32),
        )
        got, tot = np.asarray(got), np.asarray(tot)
        for w in range(V):
            # reference: walk sources in order, take the first
            # allowed[s, w] rows of each (s -> w) segment
            ref = []
            for s in range(V):
                for k in range(int(allowed[s, w])):
                    p = min(max(int(starts[s, w]) + k, 0), n - 1)
                    ref.append(s * n + int(order[s, p]))
            k = min(len(ref), M)
            assert tot[w] == len(ref), (V, w)
            assert np.array_equal(got[w, :k], np.asarray(ref[:k])), (V, w)
