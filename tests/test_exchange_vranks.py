"""Single-device vrank canonical exchange == NumPy oracle, bit level.

The vrank variant (parallel/exchange.vrank_redistribute_fn) emulates R
ranks of the canonical Alltoallv-ordered exchange on one device; its
outputs must be byte-identical to the padded oracle, like the shard_map
path (SURVEY.md §7.4's canonical-order contract).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu import oracle
from mpi_grid_redistribute_tpu.parallel import exchange


@pytest.mark.parametrize("grid_shape", [(2, 2, 2), (4, 2, 1), (1, 1, 1)])
@pytest.mark.parametrize("clustered", [False, True])
def test_vrank_exchange_matches_oracle_bitlevel(rng, grid_shape, clustered):
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid(grid_shape)
    R = grid.nranks
    n_local, cap, out_cap = 300, 120, 400
    n = R * n_local
    if clustered:
        pos = (rng.lognormal(-1.5, 0.5, size=(n, 3)) % 1.0).astype(np.float32)
    else:
        pos = rng.random((n, 3)).astype(np.float32)
    vel = rng.standard_normal((n, 3)).astype(np.float32)
    ids = np.arange(n, dtype=np.int32)
    count = rng.integers(0, n_local + 1, size=R).astype(np.int32)

    fn = exchange.build_redistribute_vranks(domain, grid, cap, out_cap)
    out = fn(
        jnp.asarray(pos).reshape(R, n_local, 3),
        jnp.asarray(count),
        jnp.asarray(vel).reshape(R, n_local, 3),
        jnp.asarray(ids).reshape(R, n_local),
    )
    pos_v, count_v, vel_v, ids_v, stats = out

    pos_o, count_o, (vel_o, ids_o), stats_o = oracle.redistribute_oracle_padded(
        domain, grid, pos, count, [vel, ids], cap, out_cap
    )
    assert np.asarray(pos_v).reshape(-1, 3).tobytes() == pos_o.tobytes()
    assert np.asarray(vel_v).reshape(-1, 3).tobytes() == vel_o.tobytes()
    assert np.asarray(ids_v).reshape(-1).tobytes() == ids_o.tobytes()
    np.testing.assert_array_equal(np.asarray(count_v), count_o)
    np.testing.assert_array_equal(np.asarray(stats.send_counts),
                                  stats_o["send_counts"])
    np.testing.assert_array_equal(np.asarray(stats.dropped_send),
                                  stats_o["dropped_send"])
    np.testing.assert_array_equal(np.asarray(stats.dropped_recv),
                                  stats_o["dropped_recv"])
    np.testing.assert_array_equal(np.asarray(stats.needed_capacity),
                                  stats_o["needed_capacity"])
