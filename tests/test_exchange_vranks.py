"""Single-device vrank canonical exchange == NumPy oracle, bit level.

The vrank variant (parallel/exchange.vrank_redistribute_fn) emulates R
ranks of the canonical Alltoallv-ordered exchange on one device; its
outputs must be byte-identical to the padded oracle, like the shard_map
path (SURVEY.md §7.4's canonical-order contract).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu import oracle
from mpi_grid_redistribute_tpu.parallel import exchange


@pytest.mark.parametrize("grid_shape", [(2, 2, 2), (4, 2, 1), (1, 1, 1)])
@pytest.mark.parametrize("clustered", [False, True])
def test_vrank_exchange_matches_oracle_bitlevel(rng, grid_shape, clustered):
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid(grid_shape)
    R = grid.nranks
    n_local, cap, out_cap = 300, 120, 400
    n = R * n_local
    if clustered:
        pos = (rng.lognormal(-1.5, 0.5, size=(n, 3)) % 1.0).astype(np.float32)
    else:
        pos = rng.random((n, 3)).astype(np.float32)
    vel = rng.standard_normal((n, 3)).astype(np.float32)
    ids = np.arange(n, dtype=np.int32)
    count = rng.integers(0, n_local + 1, size=R).astype(np.int32)

    fn = exchange.build_redistribute_vranks(domain, grid, cap, out_cap)
    out = fn(
        jnp.asarray(pos).reshape(R, n_local, 3),
        jnp.asarray(count),
        jnp.asarray(vel).reshape(R, n_local, 3),
        jnp.asarray(ids).reshape(R, n_local),
    )
    pos_v, count_v, vel_v, ids_v, stats = out

    pos_o, count_o, (vel_o, ids_o), stats_o = oracle.redistribute_oracle_padded(
        domain, grid, pos, count, [vel, ids], cap, out_cap
    )
    assert np.asarray(pos_v).reshape(-1, 3).tobytes() == pos_o.tobytes()
    assert np.asarray(vel_v).reshape(-1, 3).tobytes() == vel_o.tobytes()
    assert np.asarray(ids_v).reshape(-1).tobytes() == ids_o.tobytes()
    np.testing.assert_array_equal(np.asarray(count_v), count_o)
    np.testing.assert_array_equal(np.asarray(stats.send_counts),
                                  stats_o["send_counts"])
    np.testing.assert_array_equal(np.asarray(stats.dropped_send),
                                  stats_o["dropped_send"])
    np.testing.assert_array_equal(np.asarray(stats.dropped_recv),
                                  stats_o["dropped_recv"])
    np.testing.assert_array_equal(np.asarray(stats.needed_capacity),
                                  stats_o["needed_capacity"])


def _to_planar_fused(pos, vel, ids, R, n_local):
    """Host pack: [V, K, n] with pos rows, vel rows, bitcast id row."""
    parts = [
        pos.reshape(R, n_local, 3).transpose(0, 2, 1),
        vel.reshape(R, n_local, 3).transpose(0, 2, 1),
        ids.reshape(R, 1, n_local).view(np.float32),
    ]
    return np.ascontiguousarray(np.concatenate(parts, axis=1))


@pytest.mark.parametrize("grid_shape", [(2, 2, 2), (4, 2, 1), (1, 1, 1)])
@pytest.mark.parametrize("clustered", [False, True])
def test_planar_vrank_exchange_matches_oracle_bitlevel(
    rng, grid_shape, clustered
):
    """The planar [V, K, n] canonical engine produces byte-identical rows,
    order, counts and stats to the padded oracle (and hence to the
    row-major engine) — only the storage layout differs."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid(grid_shape)
    R = grid.nranks
    n_local, cap, out_cap = 300, 120, 400
    n = R * n_local
    if clustered:
        pos = (rng.lognormal(-1.5, 0.5, size=(n, 3)) % 1.0).astype(np.float32)
    else:
        pos = rng.random((n, 3)).astype(np.float32)
    vel = rng.standard_normal((n, 3)).astype(np.float32)
    ids = np.arange(n, dtype=np.int32)
    count = rng.integers(0, n_local + 1, size=R).astype(np.int32)

    fused = _to_planar_fused(pos, vel, ids, R, n_local)
    fn = exchange.build_redistribute_planar_vranks(
        domain, grid, cap, out_cap
    )
    out, count_v, stats = fn(jnp.asarray(fused), jnp.asarray(count))
    out = np.asarray(out)  # [V, 7, out_cap]
    pos_v = out[:, 0:3, :].transpose(0, 2, 1)
    vel_v = out[:, 3:6, :].transpose(0, 2, 1)
    ids_v = out[:, 6, :].view(np.int32)

    pos_o, count_o, (vel_o, ids_o), stats_o = oracle.redistribute_oracle_padded(
        domain, grid, pos, count, [vel, ids], cap, out_cap
    )
    assert np.ascontiguousarray(pos_v).tobytes() == pos_o.tobytes()
    assert np.ascontiguousarray(vel_v).tobytes() == vel_o.tobytes()
    assert np.ascontiguousarray(ids_v).tobytes() == ids_o.tobytes()
    np.testing.assert_array_equal(np.asarray(count_v), count_o)
    np.testing.assert_array_equal(np.asarray(stats.send_counts),
                                  stats_o["send_counts"])
    np.testing.assert_array_equal(np.asarray(stats.dropped_send),
                                  stats_o["dropped_send"])
    np.testing.assert_array_equal(np.asarray(stats.dropped_recv),
                                  stats_o["dropped_recv"])
    np.testing.assert_array_equal(np.asarray(stats.needed_capacity),
                                  stats_o["needed_capacity"])


def test_planar_vrank_positions_only(rng):
    """K = D (no extra fields) also round-trips bit-identically."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 1))
    R, n_local, cap, out_cap = 4, 128, 96, 220
    n = R * n_local
    pos = rng.random((n, 3)).astype(np.float32)
    count = np.full((R,), n_local, np.int32)
    fused = np.ascontiguousarray(
        pos.reshape(R, n_local, 3).transpose(0, 2, 1)
    )
    fn = exchange.build_redistribute_planar_vranks(domain, grid, cap, out_cap)
    out, count_v, stats = fn(jnp.asarray(fused), jnp.asarray(count))
    pos_v = np.asarray(out).transpose(0, 2, 1)
    pos_o, count_o, _, _ = oracle.redistribute_oracle_padded(
        domain, grid, pos, count, [], cap, out_cap
    )
    assert np.ascontiguousarray(pos_v).tobytes() == pos_o.tobytes()
    np.testing.assert_array_equal(np.asarray(count_v), count_o)


def test_planar_vrank_out_capacity_exceeds_pool(rng):
    """out_capacity > V*C + n: the payload pad branch keeps shapes legal
    and the tail zero (regression: found by the package-boundary drive)."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local, cap = 8, 32, 4
    out_cap = 3 * n_local  # 96 > V*C + n = 64
    n = R * n_local
    pos = rng.random((n, 3)).astype(np.float32)
    count = np.full((R,), n_local, np.int32)
    fused = np.ascontiguousarray(
        pos.reshape(R, n_local, 3).transpose(0, 2, 1)
    )
    fn = exchange.build_redistribute_planar_vranks(domain, grid, cap, out_cap)
    out, cnt, stats = fn(jnp.asarray(fused), jnp.asarray(count))
    pos_o, cnt_o, _, st_o = oracle.redistribute_oracle_padded(
        domain, grid, pos, count, [], cap, out_cap
    )
    pos_v = np.ascontiguousarray(np.asarray(out).transpose(0, 2, 1))
    assert pos_v.tobytes() == pos_o.tobytes()
    np.testing.assert_array_equal(np.asarray(cnt), cnt_o)
    np.testing.assert_array_equal(
        np.asarray(stats.dropped_send), st_o["dropped_send"]
    )
