"""End-to-end: JAX mesh backend vs pure-NumPy oracle, bit-level (SURVEY.md §7.2)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_grid_redistribute_tpu import (
    Domain,
    GridRedistribute,
    ProcessGrid,
    redistribute,
)
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

DOMAIN = Domain(0.0, 1.0)


def _inputs(rng, R=8, n_local=400, clustered=False):
    n = R * n_local
    if clustered:
        pos = rng.lognormal(mean=-1.5, sigma=0.5, size=(n, 3)) % 1.0
        pos = pos.astype(np.float32)
    else:
        pos = rng.uniform(0, 1, size=(n, 3)).astype(np.float32)
    ids = np.arange(n, dtype=np.int32)
    vel = rng.normal(size=(n, 3)).astype(np.float32)
    return pos, ids, vel


def _compare(jax_res, np_res):
    np.testing.assert_array_equal(np.asarray(jax_res.count), np_res.count)
    np.testing.assert_array_equal(np.asarray(jax_res.positions), np_res.positions)
    for fj, fn in zip(jax_res.fields, np_res.fields):
        np.testing.assert_array_equal(np.asarray(fj), fn)
    # stats is the same NamedTuple type for both backends; `fallback`
    # (the count-driven engines' per-shard dense-fallback flag, ISSUE 7)
    # is engine-specific observability — None on the dense engines and
    # the numpy oracle — so it is compared only when both sides carry it
    for name in ("send_counts", "recv_counts", "dropped_send",
                 "dropped_recv", "needed_capacity"):
        np.testing.assert_array_equal(
            np.asarray(getattr(jax_res.stats, name)),
            np.asarray(getattr(np_res.stats, name)),
        )
    a, b = jax_res.stats.fallback, np_res.stats.fallback
    if a is not None and b is not None:
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("grid_shape", [(2, 2, 2), (4, 2, 1), (8, 1, 1)])
def test_jax_matches_oracle_bitlevel(rng, grid_shape):
    pos, ids, vel = _inputs(rng)
    kw = dict(domain=DOMAIN, grid=grid_shape, capacity_factor=3.0)
    res_j = redistribute(pos, ids, vel, backend="jax", **kw)
    res_n = redistribute(pos, ids, vel, backend="numpy", **kw)
    _compare(res_j, res_n)
    assert int(np.asarray(res_j.stats.dropped_send).sum()) == 0


def test_conservation_and_ownership(rng):
    from mpi_grid_redistribute_tpu import oracle

    pos, ids, _ = _inputs(rng)
    rd = GridRedistribute(
        DOMAIN, (2, 2, 2), backend="jax", capacity_factor=3.0, out_capacity=800
    )
    res = rd.redistribute(pos, ids)
    counts = np.asarray(res.count)
    assert counts.sum() == pos.shape[0]
    out_cap = res.positions.shape[0] // rd.nranks
    shards = [
        np.asarray(res.positions)[r * out_cap : r * out_cap + counts[r]]
        for r in range(rd.nranks)
    ]
    oracle.assert_ownership(DOMAIN, rd.grid, shards)
    got_ids = np.concatenate(
        [
            np.asarray(res.fields[0])[r * out_cap : r * out_cap + counts[r]]
            for r in range(rd.nranks)
        ]
    )
    np.testing.assert_array_equal(np.sort(got_ids), np.sort(ids))


def test_idempotence(rng):
    pos, _, _ = _inputs(rng)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), backend="jax", capacity_factor=3.0)
    res1 = rd.redistribute(pos)
    res2 = rd.redistribute(res1.positions, count=res1.count)
    np.testing.assert_array_equal(np.asarray(res1.count), np.asarray(res2.count))
    np.testing.assert_array_equal(
        np.asarray(res1.positions), np.asarray(res2.positions)
    )


def test_clustered_overflow_surfaces(rng):
    # on_overflow='ignore' keeps the round-1 surfaced-counter behavior
    pos, ids, _ = _inputs(rng, clustered=True)
    kw = dict(domain=DOMAIN, grid=(2, 2, 2), capacity=60, on_overflow="ignore")
    res_j = redistribute(pos, ids, backend="jax", **kw)
    res_n = redistribute(pos, ids, backend="numpy", **kw)
    _compare(res_j, res_n)
    assert int(np.asarray(res_j.stats.dropped_send).sum()) > 0
    # measured need exceeds the configured capacity and is reported
    assert int(np.asarray(res_j.stats.needed_capacity).max()) > 60


@pytest.mark.parametrize("backend", ["jax", "numpy"])
def test_overflow_grows_and_never_loses(rng, backend):
    # VERDICT round 1 item 4: clustered config-2-style data with default
    # settings must lose zero particles, growing capacity from the
    # measured need in a bounded number of rebuilds.
    pos, ids, _ = _inputs(rng, clustered=True)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), backend=backend, capacity=32)
    builds = []
    orig = rd._run_once

    def counting_run(*args):
        builds.append((rd.capacity, rd.out_capacity))
        return orig(*args)

    rd._run_once = counting_run
    res = rd.redistribute(pos, ids)
    assert int(np.asarray(res.count).sum()) == pos.shape[0]
    assert int(np.asarray(res.stats.dropped_send).sum()) == 0
    assert int(np.asarray(res.stats.dropped_recv).sum()) == 0
    assert 2 <= len(builds) <= 3  # grew, converged fast
    # grown capacity sticks: the next call runs once, no new build
    builds.clear()
    res2 = rd.redistribute(pos, ids)
    assert len(builds) == 1
    assert int(np.asarray(res2.count).sum()) == pos.shape[0]


def test_overflow_raise_mode(rng):
    pos, ids, _ = _inputs(rng, clustered=True)
    rd = GridRedistribute(
        DOMAIN, (2, 2, 2), capacity=32, on_overflow="raise"
    )
    with pytest.raises(RuntimeError, match="dropped"):
        rd.redistribute(pos, ids)
    with pytest.raises(ValueError, match="on_overflow"):
        GridRedistribute(DOMAIN, (2, 2, 2), on_overflow="retry")


def test_more_ranks_than_devices_runs_as_vranks(rng):
    # a 16-rank grid on 8 devices: the jax backend transparently runs the
    # canonical exchange as vmapped virtual ranks on one device,
    # bit-identical to the oracle (SURVEY.md §2 process-grid topology)
    pos, ids, vel = _inputs(rng, R=16, n_local=100)
    kw = dict(domain=DOMAIN, grid=(4, 4, 1), capacity_factor=3.0)
    rd = GridRedistribute(backend="jax", **kw)
    assert rd._vranks
    res_j = rd.redistribute(pos, ids, vel)
    res_n = redistribute(pos, ids, vel, backend="numpy", **kw)
    _compare(res_j, res_n)
    assert int(np.asarray(res_j.count).sum()) == pos.shape[0]


def test_periodic_domain(rng):
    dom = Domain(0.0, 1.0, periodic=True)
    pos, _, _ = _inputs(rng)
    pos = pos + np.float32(1.75)  # everything out of the box; wraps back
    kw = dict(domain=dom, grid=(2, 2, 2), capacity_factor=3.0, out_capacity=800)
    res_j = redistribute(pos, backend="jax", **kw)
    res_n = redistribute(pos, backend="numpy", **kw)
    _compare(res_j, res_n)
    assert int(np.asarray(res_j.count).sum()) == pos.shape[0]


def test_ragged_counts(rng):
    pos, ids, _ = _inputs(rng, n_local=100)
    count = np.asarray(rng.integers(0, 101, size=8), dtype=np.int32)
    kw = dict(domain=DOMAIN, grid=(2, 2, 2), capacity_factor=3.0)
    res_j = redistribute(pos, ids, count=count, backend="jax", **kw)
    res_n = redistribute(pos, ids, count=count, backend="numpy", **kw)
    _compare(res_j, res_n)
    assert int(np.asarray(res_j.count).sum()) == count.sum()


def test_single_rank_grid(rng):
    pos, _, _ = _inputs(rng, R=1, n_local=50)
    res = redistribute(pos, domain=DOMAIN, grid=(1, 1, 1), backend="jax")
    assert int(np.asarray(res.count)[0]) == 50
    np.testing.assert_array_equal(np.asarray(res.positions), pos)


def test_input_validation(rng):
    rd = GridRedistribute(DOMAIN, (2, 2, 2))
    with pytest.raises(ValueError):
        rd.redistribute(np.zeros((10, 3), np.float32))  # not divisible by 8
    with pytest.raises(ValueError):
        rd.redistribute(np.zeros((16, 2), np.float32))  # wrong ndim
    with pytest.raises(ValueError):
        GridRedistribute(DOMAIN, (2, 2, 2), backend="mpi")
    with pytest.raises(ValueError):  # count out of range
        rd.redistribute(
            np.zeros((16, 3), np.float32), count=np.full(8, 3, np.int32)
        )
    with pytest.raises(ValueError):  # negative count
        rd.redistribute(
            np.zeros((16, 3), np.float32), count=np.full(8, -1, np.int32)
        )
    with pytest.raises(ValueError):  # zero out_capacity is rejected, not unset
        GridRedistribute(DOMAIN, (2, 2, 2), out_capacity=0)


def test_near_cubic_shape():
    assert mesh_lib.near_cubic_shape(8) == (2, 2, 2)
    assert mesh_lib.near_cubic_shape(64) == (4, 4, 4)
    assert mesh_lib.near_cubic_shape(16) == (4, 2, 2)
    assert mesh_lib.near_cubic_shape(1) == (1, 1, 1)
    assert mesh_lib.near_cubic_shape(12, ndim=2) == (4, 3)


@pytest.mark.parametrize("grid_shape", [(2, 2, 2), (4, 4, 1)])
def test_planar_and_rowmajor_engines_bitequal(rng, grid_shape):
    """VERDICT round-3 item 1: the public API's default ('auto') routes
    through the planar [K, n] engines — on the shard_map mesh path (R ==
    devices) AND the vrank path (R > devices) — and both engines produce
    byte-identical results to each other and the oracle."""
    R = int(np.prod(grid_shape))
    pos, ids, vel = _inputs(rng, R=R, n_local=200)
    kw = dict(domain=DOMAIN, grid=grid_shape, capacity_factor=3.0)
    rd_auto = GridRedistribute(backend="jax", **kw)
    rd_planar = GridRedistribute(backend="jax", engine="planar", **kw)
    rd_row = GridRedistribute(backend="jax", engine="rowmajor", **kw)
    res_auto = rd_auto.redistribute(pos, ids, vel)
    res_planar = rd_planar.redistribute(pos, ids, vel)
    res_row = rd_row.redistribute(pos, ids, vel)
    res_np = redistribute(pos, ids, vel, backend="numpy", **kw)
    for res in (res_auto, res_planar, res_row):
        _compare(res, res_np)
    # int32 ids crossed the planar engine bitcast and came back exact
    assert res_planar.fields[0].dtype == np.int32


def test_planar_engine_requires_32bit_fields(rng):
    pos, _, _ = _inputs(rng, n_local=64)
    tag = np.arange(pos.shape[0], dtype=np.int16)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), engine="planar")
    with pytest.raises(TypeError, match="32-bit"):
        rd.redistribute(pos, tag)
    with pytest.raises(ValueError, match="engine"):
        GridRedistribute(DOMAIN, (2, 2, 2), engine="fast")


def test_planar_engine_preserves_all_bit_patterns(rng):
    """TPU denormal-flush regression (round 4, found on-chip): bitcast
    int32 payloads below 2^23 are DENORMAL f32 bit patterns, and TPU
    float vector copies flush them to zero (measured through the planar
    pack gather at >= ~3k rows/shard; ops/pallas_overlay.py documents the
    same hazard for its targets). The planar engines therefore transport
    an int32 bitcast view end to end — integer lanes have no FTZ — so
    every 32-bit pattern (denormal ints, NaN payload bits, -0.0)
    survives bit-exactly. On CPU this test is a semantics check; on the
    real chip it is the regression test for the flush."""
    R, n_local = 8, 3200  # size matters: the flush engaged >= ~3k rows
    n = R * n_local
    pos = rng.random((n, 3)).astype(np.float32)
    ids = np.arange(n, dtype=np.int32)  # denormal patterns (< 2^23)
    # adversarial float field: NaN payloads, infinities, denormals, -0.0
    bits = (np.arange(n, dtype=np.uint64) * 2654435761 % (1 << 32)).astype(
        np.uint32
    )
    bits[:4] = [0x7FC00001, 0xFF800000, 0x00000001, 0x80000000]
    weird = bits.view(np.float32)
    kw = dict(domain=DOMAIN, grid=(2, 2, 2), capacity_factor=4.0)
    res_j = redistribute(pos, ids, weird, backend="jax", engine="planar",
                         **kw)
    res_n = redistribute(pos, ids, weird, backend="numpy", **kw)
    assert int(np.asarray(res_j.stats.dropped_send).sum()) == 0
    assert np.asarray(res_j.count).tobytes() == res_n.count.tobytes()
    assert (
        np.asarray(res_j.positions).tobytes() == res_n.positions.tobytes()
    )
    for fj, fn in zip(res_j.fields, res_n.fields):
        assert np.asarray(fj).tobytes() == np.asarray(fn).tobytes()


def test_auto_engine_falls_back_for_non32bit_fields(rng):
    # an int16 tag field: 'auto' silently uses the row-major engine and
    # still matches the oracle bit-level
    pos, _, _ = _inputs(rng, n_local=64)
    tag = (np.arange(pos.shape[0]) % 7).astype(np.int16)
    kw = dict(domain=DOMAIN, grid=(2, 2, 2), capacity_factor=3.0)
    res_j = redistribute(pos, tag, backend="jax", **kw)
    res_n = redistribute(pos, tag, backend="numpy", **kw)
    _compare(res_j, res_n)


def test_grow_deferred_check_is_async_in_steady_state(rng):
    """VERDICT round-2 item 8: after calibration (two clean synchronous
    checks), 'grow' must issue NO blocking stats fetch per call — only
    the every-check_every deferred resolution of an already-materialized
    counter copy."""
    pos, ids, vel = _inputs(rng, n_local=64)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), capacity_factor=16.0,
                          on_overflow="grow", check_every=4)
    # calibration: synchronous checks until two consecutive are clean
    # (the first call may grow once, costing an extra fetch)
    rd.redistribute(pos, vel, ids)
    rd.redistribute(pos, vel, ids)
    rd.redistribute(pos, vel, ids)
    assert rd._clean_checks >= 2
    calibrated_fetches = rd._blocking_fetches
    for _ in range(8):
        rd.redistribute(pos, vel, ids)
    # steady state: zero additional blocking fetches in 8 calls
    assert rd._blocking_fetches == calibrated_fetches
    # deferred checks were scheduled (every 4th call) and stayed clean
    rd.flush_overflow_checks()  # resolves the last window; must not raise


def test_grow_deferred_check_catches_nonsampled_spike(rng):
    """VERDICT round-3 weak item 1 / round-4 item 2: a ONE-call overflow
    on a call that is never itself sampled must still be caught — the
    deferred check reads CUMULATIVE device-side counters, so the window
    read covers every call in it."""
    placed, cnt = _placed_state(rng)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), capacity=1,
                          on_overflow="grow", check_every=4)
    rd.redistribute(placed, count=cnt)
    rd.redistribute(placed, count=cnt)
    assert rd._clean_checks == 2  # calibrated; deferred mode from here
    clustered = placed.copy()
    clustered[:, :] = 0.1  # all rows into rank 0's cell -> drops at cap=1
    # deferred-mode call #1: the ONLY lossy call — and NOT a sampled one
    # (the counter schedule samples every 4th deferred call)
    rd.redistribute(clustered, count=cnt)
    old_cap = rd.capacity
    with pytest.raises(RuntimeError, match="deferred overflow check"):
        for _ in range(8):  # clean calls; a later scheduled read trips
            rd.redistribute(placed, count=cnt)
    assert rd.capacity > old_cap  # grown for subsequent calls
    # resolve the post-raise tail (clean: the only drops were in the
    # already-reported window) so GC does not warn about this instance
    rd.flush_overflow_checks()


def test_grow_flush_covers_partial_window(rng):
    """flush_overflow_checks() must also verify calls made after the last
    scheduled counter copy (the trailing partial window)."""
    placed, cnt = _placed_state(rng)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), capacity=1,
                          on_overflow="grow", check_every=100)
    rd.redistribute(placed, count=cnt)
    rd.redistribute(placed, count=cnt)
    assert rd._clean_checks == 2
    clustered = placed.copy()
    clustered[:, :] = 0.1
    rd.redistribute(clustered, count=cnt)  # lossy; no check ever scheduled
    with pytest.raises(RuntimeError, match="deferred overflow check"):
        rd.flush_overflow_checks()


def _placed_state(rng, R=8, n_local=64):
    """Inputs where every row already sits on its owner shard (zero
    sends), plus the per-rank layout/counts — the calibration-friendly
    state the deferred-check tests share."""
    pos, ids, vel = _inputs(rng, R=R, n_local=n_local)
    from mpi_grid_redistribute_tpu.ops import binning
    grid = ProcessGrid((2, 2, 2))
    dest = binning.rank_of_position(pos, DOMAIN, grid, xp=np)
    counts = np.bincount(dest, minlength=R)
    cap_rows = int(counts.max())
    placed = np.zeros((R * cap_rows, 3), np.float32)
    cnt = np.zeros((R,), np.int32)
    for r in range(R):
        rows = pos[dest == r]
        placed[r * cap_rows : r * cap_rows + len(rows)] = rows
        cnt[r] = len(rows)
    return placed, cnt


def test_grow_context_manager_flushes_lossy_tail(rng):
    """VERDICT round-4 item 6: the `with` form must flush at block exit,
    so a lossy trailing window (never sampled by a scheduled check)
    raises from __exit__ rather than being silently forgotten."""
    placed, cnt = _placed_state(rng)
    with pytest.raises(RuntimeError, match="deferred overflow check"):
        with GridRedistribute(DOMAIN, (2, 2, 2), capacity=1,
                              on_overflow="grow", check_every=100) as rd:
            rd.redistribute(placed, count=cnt)
            rd.redistribute(placed, count=cnt)
            assert rd._clean_checks == 2  # calibrated -> deferred mode
            clustered = placed.copy()
            clustered[:, :] = 0.1  # all rows to rank 0 -> drops at cap=1
            rd.redistribute(clustered, count=cnt)  # lossy tail window


def test_grow_context_manager_clean_exit(rng):
    """A clean loop exits the `with` block without raising or warning."""
    pos, ids, vel = _inputs(rng, n_local=64)
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        with GridRedistribute(DOMAIN, (2, 2, 2), capacity_factor=16.0,
                              on_overflow="grow", check_every=4) as rd:
            for _ in range(8):
                rd.redistribute(pos, vel, ids)
    assert not rd._has_unresolved_windows()


def test_grow_del_warns_on_unflushed_windows(rng):
    """Dropping a calibrated 'grow' instance with unread deferred windows
    must emit a RuntimeWarning pointing at flush_overflow_checks()."""
    placed, cnt = _placed_state(rng)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), capacity=1,
                          on_overflow="grow", check_every=100)
    rd.redistribute(placed, count=cnt)
    rd.redistribute(placed, count=cnt)
    rd.redistribute(placed, count=cnt)  # deferred-mode call, never read
    assert rd._has_unresolved_windows()
    with pytest.warns(RuntimeWarning, match="unresolved deferred"):
        rd.__del__()
    # after a flush, the same instance deletes silently
    rd.flush_overflow_checks()
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        rd.__del__()


def test_grow_deferred_check_detects_late_overflow(rng):
    """A drop that happens after calibration is detected at the next
    deferred checkpoint: capacities grow for subsequent calls and the
    check raises loudly (results in the window are lossy — retroactive
    healing is impossible; never silent)."""
    placed, cnt = _placed_state(rng)
    rd = GridRedistribute(DOMAIN, (2, 2, 2), capacity=1,
                          on_overflow="grow", check_every=1)
    rd.redistribute(placed, count=cnt)
    rd.redistribute(placed, count=cnt)
    assert rd._clean_checks == 2
    # clustered call: everything heads to one rank; capacity=1 drops
    clustered = placed.copy()
    clustered[:, :] = 0.1  # all rows into rank 0's cell
    rd.redistribute(clustered, count=cnt)  # schedules pending counters
    old_cap = rd.capacity
    with pytest.raises(RuntimeError, match="deferred overflow check"):
        rd.redistribute(clustered, count=cnt)
    assert rd.capacity > old_cap  # grown for subsequent calls
    # The raising resolution accounted only through its own snapshot;
    # the raising call's counters were folded in but never read — the
    # instance must still report unresolved windows (and warn at GC)
    # rather than silently dropping that tail.
    assert rd._has_unresolved_windows()
    with pytest.warns(RuntimeWarning, match="unresolved deferred"):
        rd.__del__()
    # idempotent: the later real GC __del__ must not warn a second time
    import warnings as _warnings
    with _warnings.catch_warnings():
        _warnings.simplefilter("error")
        rd.__del__()
