"""Property-based invariants (SURVEY.md §4): conservation, idempotence,
permutation-invariance, periodic round-trips — across random configs."""

import numpy as np
import pytest

import jax

import mpi_grid_redistribute_tpu as gr
from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning


CONFIGS = [
    (Domain(0.0, 1.0, periodic=True), (2, 2, 2)),
    (Domain((-2.0, 0.0, 1.0), (2.0, 4.0, 9.0), periodic=False), (4, 2, 1)),
    (Domain(0.0, 1.0, ndim=2, periodic=(True, False)), (4, 2)),
]


def _shard_sets(res, R, out_cap, ndim):
    out = []
    pos = np.asarray(res.positions)
    count = np.asarray(res.count)
    for r in range(R):
        rows = pos[r * out_cap : r * out_cap + count[r]]
        out.append({tuple(v) for v in rows.tolist()})
    return out


@pytest.mark.parametrize("domain,shape", CONFIGS)
def test_conservation_and_idempotence(domain, shape, rng, _devices):
    grid = ProcessGrid(shape)
    R = grid.nranks
    n_local = 128
    lo = np.asarray(domain.lo, np.float32)
    ext = np.asarray(domain.extent, np.float32)
    pos = (lo + rng.random((R * n_local, domain.ndim)) * ext).astype(
        np.float32
    )
    out_cap = R * n_local
    rd = gr.GridRedistribute(
        domain, grid, capacity_factor=float(R), out_capacity=out_cap
    )
    res = rd.redistribute(pos)
    assert int(np.asarray(res.stats.dropped_send).sum()) == 0
    assert int(np.asarray(res.stats.dropped_recv).sum()) == 0
    assert int(np.asarray(res.count).sum()) == R * n_local  # conservation

    # idempotence: a second redistribute moves nothing and keeps bytes
    res2 = rd.redistribute(res.positions, count=res.count)
    send = np.asarray(res2.stats.send_counts)
    moved = send.sum() - np.trace(send.reshape(R, R))
    assert moved == 0
    assert (
        np.asarray(res2.positions).tobytes()
        == np.asarray(res.positions).tobytes()
    )
    assert (
        np.asarray(res2.count).tobytes() == np.asarray(res.count).tobytes()
    )


@pytest.mark.parametrize("domain,shape", CONFIGS[:2])
def test_permutation_invariance(domain, shape, rng, _devices):
    """Shuffling input rows (within shards) must not change the *set* each
    shard receives."""
    grid = ProcessGrid(shape)
    R = grid.nranks
    n_local = 64
    lo = np.asarray(domain.lo, np.float32)
    ext = np.asarray(domain.extent, np.float32)
    pos = (lo + rng.random((R * n_local, domain.ndim)) * ext).astype(
        np.float32
    )
    out_cap = R * n_local
    rd = gr.GridRedistribute(
        domain, grid, capacity_factor=float(R), out_capacity=out_cap
    )
    res_a = rd.redistribute(pos)

    shuffled = pos.copy()
    for r in range(R):
        sl = slice(r * n_local, (r + 1) * n_local)
        shuffled[sl] = shuffled[sl][rng.permutation(n_local)]
    res_b = rd.redistribute(shuffled)

    assert _shard_sets(res_a, R, out_cap, domain.ndim) == _shard_sets(
        res_b, R, out_cap, domain.ndim
    )


def test_periodic_wrap_roundtrip(rng, _devices):
    """wrap(pos + k*extent) == wrap(pos) bit-for-bit for integer k, and
    binning is invariant under whole-box shifts."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((4, 4, 4))
    pos = rng.random((10000, 3)).astype(np.float32)
    for k in (-2.0, -1.0, 1.0, 3.0):
        shifted = (pos + np.float32(k)).astype(np.float32)
        a = binning.rank_of_position(pos, domain, grid, xp=np)
        b = binning.rank_of_position(shifted, domain, grid, xp=np)
        # float32 addition of k can perturb low bits near cell edges; the
        # overwhelming majority must be identical and every mismatch must
        # be an adjacent-cell edge case
        frac_same = (a == b).mean()
        assert frac_same > 0.999


def test_out_of_box_clamps_nonperiodic(rng, _devices):
    """Non-periodic: out-of-box particles clamp into edge cells, never
    drop (matches reference digitize-clamp semantics, SURVEY.md C2)."""
    domain = Domain(0.0, 1.0, periodic=False)
    grid = ProcessGrid((2, 2, 2))
    pos = (rng.random((8 * 32, 3)).astype(np.float32) - 0.5) * 4.0
    rd = gr.GridRedistribute(
        domain, grid, capacity_factor=8.0, out_capacity=8 * 32
    )
    res = rd.redistribute(pos)
    assert int(np.asarray(res.count).sum()) == 8 * 32
    dest = binning.rank_of_position(pos, domain, grid, xp=np)
    assert set(np.unique(dest)) <= set(range(8))
