"""Metrics plane (telemetry/metrics.py, aggregate.py) — ISSUE 5 gates.

Five contracts, each tested against hand math or a real scrape:

* registry — Counter/Gauge/Histogram semantics (pow2 bucket edges,
  exact-edge placement, label validation, reserved-suffix rejection)
  against hand-computed fixtures;
* exposition — ``render_openmetrics`` output must survive a STRICT
  hand-written OpenMetrics parser (every sample belongs to a declared
  family, counters end ``_total``, buckets are cumulative and
  non-decreasing, ``+Inf`` equals ``_count``, one trailing ``# EOF``),
  and a real HTTP scrape of ``scripts/metrics_serve.py`` must serve it;
* exactness — ``grid_journal_events`` counters equal the recorder's
  all-time counts even after ring eviction, and a merged pod journal's
  ``counts()`` equal the sum of per-shard counts (property-tested on
  random shards);
* purity — the scrape path (metrics.py, aggregate.py) must be loadable
  without jax ever entering ``sys.modules`` (runtime subprocess check;
  gridlint G007 holds the static half in test_gridlint.py);
* gating — the schema-drift gate (journaled kinds vs SCHEMA.md, both
  directions) and the noise-aware bench classifier (r04→r05 wobble must
  pass, a synthetic 2x slowdown must not).
"""

import ast
import importlib.util
import json
import math
import os
import re
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.telemetry import (
    HealthMonitor,
    MergedJournal,
    MetricsRegistry,
    StepRecorder,
    classify_capture,
    classify_delta,
    from_journal,
    merge_journals,
    noise_floor,
    pow2_edges,
)
from mpi_grid_redistribute_tpu.telemetry import metrics as metrics_lib
from mpi_grid_redistribute_tpu.telemetry import regress

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "mpi_grid_redistribute_tpu")
TELEMETRY = os.path.join(PACKAGE, "telemetry")
SERVE = os.path.join(REPO_ROOT, "scripts", "metrics_serve.py")


# ------------------------------------------------------------ hand math


def test_pow2_edges_hand_math():
    assert pow2_edges(0, 3) == (1.0, 2.0, 4.0, 8.0)
    assert pow2_edges(-2, 1) == (0.25, 0.5, 1.0, 2.0)
    edges = pow2_edges(-14, 4)
    assert len(edges) == 19
    assert edges[0] == 2.0 ** -14 and edges[-1] == 16.0


def test_counter_and_gauge_hand_math():
    reg = MetricsRegistry()
    c = reg.counter("hits", "hand-math counter", labelnames=("kind",))
    c.labels(kind="a").inc()
    c.labels(kind="a").inc(2.5)
    c.labels(kind="b").inc(0)
    assert c.labels(kind="a").value == 3.5
    assert c.labels(kind="b").value == 0
    with pytest.raises(ValueError):
        c.labels(kind="a").inc(-1)
    g = reg.gauge("depth", "hand-math gauge")
    g.labels().set(7)
    g.labels().inc(3)
    g.labels().dec(2.5)
    assert g.labels().value == 7.5


def test_histogram_bucket_hand_math():
    reg = MetricsRegistry()
    h = reg.histogram("lat", "hand-math histogram", edges=pow2_edges(0, 3))
    child = h.labels()
    # exact edge values land in their own bucket (le is inclusive)
    for v in (0.5, 1.0, 2.0, 3.0, 8.0, 100.0):
        child.observe(v)
    cum = child.cumulative()
    assert [le for le, _ in cum] == [1.0, 2.0, 4.0, 8.0, math.inf]
    assert [n for _, n in cum] == [2, 3, 4, 5, 6]
    assert child.count == 6
    assert child.sum == pytest.approx(114.5)


def test_histogram_quantile_hand_math():
    h = metrics_lib.Histogram((), pow2_edges(0, 3))  # edges 1,2,4,8
    with pytest.raises(ValueError, match="quantile"):
        h.quantile(0.0)
    with pytest.raises(ValueError, match="quantile"):
        h.quantile(1.5)
    assert h.quantile(0.99) == 0.0  # empty histogram, not an error
    for v in (0.5, 1.0, 3.0, 8.0):
        h.observe(v)
    # bucketed UPPER bound: smallest edge covering ceil(q * count)
    assert h.quantile(0.5) == 1.0   # target 2 of 4 -> le=1 bucket (2)
    assert h.quantile(0.75) == 4.0  # target 3 -> le=4 bucket
    assert h.quantile(1.0) == 8.0
    h.observe(100.0)  # overflow bucket
    assert h.quantile(1.0) == math.inf
    assert h.quantile(0.8) == 8.0   # target 4 of 5 still inside edges


def test_dropped_edges_zero_bucket_keeps_p99_of_zeros_zero():
    # grid_dropped_rows carries an explicit 0 edge: a loss-free window's
    # p99 must be 0, not 1, or the threshold=0 SLO would always breach
    assert metrics_lib.DROPPED_EDGES[0] == 0.0
    h = metrics_lib.Histogram((), metrics_lib.DROPPED_EDGES)
    for _ in range(100):
        h.observe(0)
    assert h.quantile(0.99) == 0.0
    h.observe(3)  # a single lossy step is visible at the tail
    assert h.quantile(1.0) == 4.0


def test_family_shape_and_label_validation():
    reg = MetricsRegistry()
    c = reg.counter("ops", "ops", labelnames=("kind",))
    # same declaration is idempotent, conflicting shape raises
    assert reg.counter("ops", "ops", labelnames=("kind",)) is c
    with pytest.raises(ValueError):
        reg.counter("ops", "ops", labelnames=("other",))
    with pytest.raises(ValueError):
        reg.gauge("ops", "ops")
    # label set must match the declaration exactly
    with pytest.raises(ValueError):
        c.labels(wrong="x")
    with pytest.raises(ValueError):
        c.labels()
    # OpenMetrics reserves the suffixes the renderer appends
    for bad in ("x_total", "x_bucket", "x_sum", "x_count", "x_created"):
        with pytest.raises(ValueError):
            reg.counter(bad, "reserved")
    with pytest.raises(ValueError):
        reg.counter("0bad", "bad name")


def _mixed_recorder():
    rec = StepRecorder(host="h0", pid=7)
    rec.record("migrate_step", step=0, sent=5, received=5, backlog=2,
               dropped_recv=0, population=100)
    rec.record("migrate_step", step=1, sent=3, received=3, backlog=1,
               dropped_recv=1, population=100)
    rec.record("step_time", seconds=0.004)
    rec.record("step_time", seconds=0.006)
    rec.record("fast_path", step=0, taken=1, movers=12, movers_max_rank=4)
    rec.record("fast_path", step=1, taken=0, movers=900, movers_max_rank=300)
    rec.record("alert", rule="backlog_growth", severity="warn", reason="x")
    rec.record("capacity_grow", which="send", old=10, new=20, needed=15,
               dropped=0, call=1)
    rec.record("mover_cap_grow", old=64, new=128, peak_movers=90)
    rec.record("flow_snapshot", steps=2, n_ranks=8, moved_rows_total=42,
               imbalance=1.25, population=[20, 12, 10, 8, 2, 0, 28, 20],
               top_pairs=[[0, 1, 30]])
    return rec


def test_from_journal_hand_math():
    rec = _mixed_recorder()
    reg = from_journal(rec)

    def val(name, **labels):
        return reg.get(name).labels(**labels).value

    assert val("grid_journal_events", kind="migrate_step") == 2
    assert val("grid_journal_events", kind="alert") == 1
    assert val("grid_journal_evicted_events") == 0
    assert val("grid_migrate_rows", direction="sent") == 8
    assert val("grid_migrate_rows", direction="received") == 8
    assert val("grid_migrate_rows", direction="backlog") == 3
    assert val("grid_migrate_rows", direction="dropped_recv") == 1
    assert val("grid_population_rows") == 100
    assert val("grid_backlog_rows") == 1          # latest step
    assert val("grid_fast_path_steps", taken="1") == 1
    assert val("grid_fast_path_steps", taken="0") == 1
    assert val("grid_capacity_rows", which="send") == 20
    assert val("grid_capacity_rows", which="mover") == 128
    assert val("grid_alerts", rule="backlog_growth", severity="warn") == 1
    assert val("grid_flow_moved_rows") == 42
    assert val("grid_flow_imbalance") == 1.25
    assert val("grid_rank_population", vrank="0") == 20
    assert val("grid_rank_population", vrank="5") == 0
    assert val("grid_rank_population", vrank="6") == 28
    assert len(reg.get("grid_rank_population").children()) == 8
    st = reg.get("grid_step_time_seconds").labels()
    assert st.count == 2 and st.sum == pytest.approx(0.010)
    mv = reg.get("grid_movers_per_step").labels()
    assert mv.count == 2 and mv.sum == 912
    # 0.004 and 0.006 both exceed 2^-8 s, land in the le=2^-7 s bucket
    cum = dict(st.cumulative())
    assert cum[2.0 ** -8] == 0 and cum[2.0 ** -7] == 2


def test_from_journal_service_slo_families():
    # the ISSUE 8 SLO surface: step_latency events feed both histograms,
    # restore events feed the corrupt-snapshot counter
    rec = StepRecorder(host="h0", pid=7)
    rec.record("step_latency", step=1, seconds=0.004, dropped=0)
    rec.record("step_latency", step=2, seconds=0.006, dropped=5)
    rec.record("restore", what="state", step=4, path="p",
               snapshots_skipped=2)
    rec.record("restore", what="journal", path="p")  # no skip field: +0
    reg = from_journal(rec)

    lat = reg.get("grid_step_latency_seconds").labels()
    assert lat.count == 2 and lat.sum == pytest.approx(0.010)
    drop = reg.get("grid_dropped_rows").labels()
    assert drop.count == 2
    assert dict(drop.cumulative())[0.0] == 1  # loss-free step visible
    assert drop.quantile(1.0) == 8.0          # the 5-row step's bucket
    assert reg.get("grid_snapshot_corrupt").labels().value == 2

    text = reg.render_openmetrics()
    assert 'grid_dropped_rows_bucket{le="0"} 1' in text
    assert "grid_snapshot_corrupt_total 2" in text
    assert "grid_step_latency_seconds_count 2" in text


def test_journal_counters_exact_after_ring_eviction():
    rec = StepRecorder(capacity=4, host="h0", pid=1)
    for s in range(10):
        rec.record("migrate_step", step=s, sent=1, received=1, backlog=0,
                   dropped_recv=0, population=8)
    assert len(rec.events()) == 4
    reg = from_journal(rec)
    fam = reg.get("grid_journal_events")
    # the counter comes from all-time counts(), NOT the retained window
    assert fam.labels(kind="migrate_step").value == 10
    assert reg.get("grid_journal_evicted_events").labels().value == 6
    assert rec.counts() == {"migrate_step": 10}


# ------------------------------------------- strict OpenMetrics parser

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_][a-zA-Z0-9_]*)"
    r"(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\]|\\.)*\",?)*)\})?"
    r" (\S+)$"
)
_LABEL_RE = re.compile(r"([a-zA-Z_][a-zA-Z0-9_]*)=\"((?:[^\"\\]|\\.)*)\"")


def _parse_openmetrics(text):
    """Strict hand parser: returns {family: (type, {sample_name:
    {labelstr: value}})} and raises AssertionError on any violation."""
    lines = text.splitlines()
    assert lines and lines[-1] == "# EOF", "must terminate with # EOF"
    assert sum(1 for l in lines if l == "# EOF") == 1
    families = {}   # name -> type
    helped = set()
    samples = {}    # family -> {sample name -> {label str -> float}}
    for line in lines[:-1]:
        assert line and not line.isspace(), "no blank lines"
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split(" ", 3)
            assert name not in families, f"duplicate TYPE for {name}"
            assert mtype in ("counter", "gauge", "histogram"), mtype
            families[name] = mtype
            samples[name] = {}
            continue
        if line.startswith("# HELP "):
            name = line.split(" ", 3)[2]
            assert name in families, f"HELP before TYPE for {name}"
            helped.add(name)
            continue
        assert not line.startswith("#"), f"unknown comment: {line}"
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        sname, labelstr, value = m.group(1), m.group(2) or "", m.group(3)
        fval = float(value)  # raises on malformed values
        fam = None
        for base, mtype in families.items():
            expect = {
                "counter": (base + "_total",),
                "gauge": (base,),
                "histogram": (base + "_bucket", base + "_sum",
                              base + "_count"),
            }[mtype]
            if sname in expect:
                fam = base
        assert fam is not None, f"sample {sname} belongs to no family"
        labels = dict(_LABEL_RE.findall(labelstr))
        key = tuple(sorted(labels.items()))
        assert key not in samples[fam].get(sname, {}), (
            f"duplicate sample {sname}{labels}"
        )
        samples[fam].setdefault(sname, {})[key] = fval
    assert helped == set(families), "every family needs a HELP line"
    # histogram invariants: cumulative non-decreasing, +Inf == _count
    for base, mtype in families.items():
        if mtype != "histogram":
            continue
        buckets = samples[base].get(base + "_bucket", {})
        series = {}
        for key, v in buckets.items():
            rest = tuple((k, x) for k, x in key if k != "le")
            le = dict(key)["le"]
            series.setdefault(rest, []).append((le, v))
        for rest, pts in series.items():
            les = [le for le, _ in pts]
            assert les[-1] == "+Inf", "last bucket must be +Inf"
            nums = [float(le) for le in les[:-1]]
            assert nums == sorted(nums), "le values must ascend"
            vals = [v for _, v in pts]
            assert vals == sorted(vals), "bucket counts must be cumulative"
            count = samples[base][base + "_count"][rest]
            assert vals[-1] == count, "+Inf bucket must equal _count"
    return families, samples


def test_render_openmetrics_passes_strict_parser():
    text = from_journal(_mixed_recorder()).render_openmetrics()
    families, samples = _parse_openmetrics(text)
    assert families["grid_journal_events"] == "counter"
    assert families["grid_step_time_seconds"] == "histogram"
    assert families["grid_population_rows"] == "gauge"
    # counters carry the _total suffix on the wire, not in the family
    key = (("kind", "migrate_step"),)
    assert samples["grid_journal_events"]["grid_journal_events_total"][
        key
    ] == 2
    # unsampled gauges render metadata but no misleading 0 samples
    assert samples["grid_flow_moved_rows"]  # sampled here
    text2 = from_journal(StepRecorder(host="h", pid=1)).render_openmetrics()
    fam2, samp2 = _parse_openmetrics(text2)
    assert samp2["grid_flow_moved_rows"] == {}
    assert samp2["grid_population_rows"] == {}
    assert samp2["grid_rank_population"] == {}


def test_rank_population_latest_snapshot_wins():
    """A later flow_snapshot replaces the per-vrank family outright —
    including DROPPING ghost vranks when the rank count shrinks."""
    rec = StepRecorder(host="h", pid=1)
    rec.record("flow_snapshot", steps=1, n_ranks=4, moved_rows_total=0,
               imbalance=2.0, population=[8, 0, 0, 0], top_pairs=[])
    rec.record("flow_snapshot", steps=2, n_ranks=2, moved_rows_total=3,
               imbalance=1.0, population=[4, 4], top_pairs=[])
    reg = from_journal(rec)
    fam = reg.get("grid_rank_population")
    assert len(fam.children()) == 2
    assert fam.labels(vrank="0").value == 4
    assert fam.labels(vrank="1").value == 4
    # a null population leaf (accumulator never fed one) is skipped,
    # leaving the previous snapshot's family intact
    rec.record("flow_snapshot", steps=3, n_ranks=2, moved_rows_total=3,
               imbalance=1.0, population=None, top_pairs=[])
    reg2 = from_journal(rec)
    assert len(reg2.get("grid_rank_population").children()) == 2


def test_label_value_escaping_round_trips():
    reg = MetricsRegistry()
    c = reg.counter("odd", "escape check", labelnames=("reason",))
    raw = 'a"b\\c\nd'
    c.labels(reason=raw).inc()
    text = reg.render_openmetrics()
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    _, samples = _parse_openmetrics(text)
    (key,) = samples["odd"]["odd_total"]
    assert dict(key)["reason"] == 'a\\"b\\\\c\\nd'  # still escaped on wire


# ------------------------------------------- multi-host merge property


KINDS = ("migrate_step", "step_time", "alert", "flow_snapshot",
         "capacity_grow")


def test_merge_equals_sum_property(rng, tmp_path):
    shards = []
    for i in range(5):
        rec = StepRecorder(host=f"host{i:02d}", pid=1000 + i)
        for s in range(int(rng.integers(0, 40))):
            kind = KINDS[int(rng.integers(0, len(KINDS)))]
            rec.record(kind, step=s, v=int(rng.integers(0, 9)))
        # wall-clock wobble, including backward steps the merge must
        # repair to monotone
        for j, e in enumerate(rec._ring):
            rec._ring[j] = e._replace(
                time=e.time + float(rng.normal(0.0, 0.5))
            )
        shards.append(rec)
    merged = merge_journals(shards)
    assert isinstance(merged, MergedJournal)
    expected = {}
    for rec in shards:
        for k, n in rec.counts().items():
            expected[k] = expected.get(k, 0) + n
    assert merged.counts() == expected
    assert len(merged) == sum(len(r.events()) for r in shards)
    per = merged.per_shard_counts()
    for rec in shards:
        assert per[(rec.host, rec.pid)] == rec.counts()
    # merged order: aligned time non-decreasing, intra-shard seq order
    # preserved exactly
    times = [e["t_aligned"] for e in merged.events()]
    assert times == sorted(times)
    for rec in shards:
        seqs = [e["seq"] for e in merged.events()
                if e["host"] == rec.host]
        assert seqs == sorted(seqs)
    # the same merge through JSONL shard files (the pod artifact path)
    paths = []
    for rec in shards:
        p = tmp_path / f"{rec.host}.{rec.pid}.jsonl"
        rec.to_jsonl(str(p))
        paths.append(str(p))
    refile = merge_journals(paths, align="start")
    assert refile.counts() == expected
    t0 = [e["t_aligned"] for e in refile.events()]
    assert t0 == sorted(t0) and (not t0 or t0[0] == 0.0)


def test_pod_steps_sum_and_concat():
    recs = []
    for i, (sent, pop) in enumerate(((5, 40), (7, 24))):
        rec = StepRecorder(host=f"h{i}", pid=i + 1)
        for s in range(3):
            rec.record("migrate_step", step=s, sent=sent, received=sent,
                       backlog=i, dropped_recv=0, population=pop,
                       sent_per_rank=[sent, 0], received_per_rank=[0, sent],
                       population_per_rank=[pop // 2, pop // 2])
        recs.append(rec)
    merged = merge_journals(recs)
    pod = merged.to_recorder(pod_steps=True)
    assert pod.host == "pod" and pod.counts() == {"migrate_step": 3}
    for e in pod.events("migrate_step"):
        assert e.data["sent"] == 12 and e.data["population"] == 64
        # per-rank vectors concatenate in shard order
        assert e.data["population_per_rank"] == [20, 20, 12, 12]
    stats = merged.pod_stats()
    assert stats.population.shape == (3, 4)
    assert int(stats.sent.sum()) == 3 * 12


# --------------------------------------------------- live HTTP scrape


def test_metrics_serve_scrapes_over_http(tmp_path):
    paths = []
    for i in range(2):
        rec = StepRecorder(host=f"h{i}", pid=i + 1)
        for s in range(4):
            rec.record("migrate_step", step=s, sent=3 - i, received=3 - i,
                       backlog=0, dropped_recv=0, population=64)
        p = tmp_path / f"shard{i}.jsonl"
        rec.to_jsonl(str(p))
        paths.append(str(p))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, SERVE, "--journal", paths[0], "--journal",
         paths[1], "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO_ROOT, env=env,
    )
    watchdog = threading.Timer(120, proc.kill)
    watchdog.start()
    try:
        line = proc.stdout.readline()   # "serving http://host:port/..."
        m = re.search(r"http://([\d.]+):(\d+)/metrics", line)
        assert m, (line, proc.poll(), proc.stderr.read() if proc.poll()
                   is not None else "")
        base = f"http://{m.group(1)}:{m.group(2)}"
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.status == 200
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text"
            )
            text = r.read().decode("utf-8")
        _, samples = _parse_openmetrics(text)
        # two 4-step shards pod-merge into 4 pod steps; row counters sum
        key = (("kind", "migrate_step"),)
        assert samples["grid_journal_events"][
            "grid_journal_events_total"][key] == 4
        dkey = (("direction", "sent"),)
        assert samples["grid_migrate_rows"][
            "grid_migrate_rows_total"][dkey] == 4 * (3 + 2)
        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            assert r.status == 200
            verdict = json.loads(r.read().decode("utf-8"))
        assert verdict["status"] in ("OK", "WARN")
        # scraping twice re-snapshots, not accumulates
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.read().decode("utf-8").splitlines()[-1] == "# EOF"
    finally:
        watchdog.cancel()
        proc.terminate()
        proc.wait(timeout=10)


def test_journal_snapshotter_caches_unchanged_shards(tmp_path):
    """Scrape-storm contract (ISSUE 17): an unchanged shard set must not
    be re-parsed — the snapshotter caches the merged recorder keyed on
    every shard's (path, mtime, size) and invalidates on any growth."""
    spec = importlib.util.spec_from_file_location("_serve_mod", SERVE)
    serve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve)

    rec = StepRecorder(host="h", pid=1)
    rec.record("migrate_step", step=0, sent=1, received=1, backlog=0,
               dropped_recv=0, population=8)
    p = tmp_path / "shard.jsonl"
    rec.to_jsonl(str(p))
    snapshot, shutdown = serve.journal_snapshotter([str(p)], "wall")
    a = snapshot()
    assert a.counts() == {"migrate_step": 1}
    assert snapshot() is a          # quiescent journal: cache hit
    # the shard growing (size changes) invalidates on the next scrape
    rec.record("migrate_step", step=1, sent=1, received=1, backlog=0,
               dropped_recv=0, population=8)
    rec.to_jsonl(str(p))
    b = snapshot()
    assert b is not a
    assert b.counts() == {"migrate_step": 2}
    shutdown()


def test_incidents_endpoint_and_healthz_503(tmp_path):
    """The ISSUE 17 HTTP surface: a journal whose health verdict ALERTs
    must 503 on /healthz, and --incident-dir serves the flight-recorder
    bundle listing on /incidents (a 404 names all three endpoints)."""
    from mpi_grid_redistribute_tpu.telemetry import incident as incident_lib

    rec = StepRecorder(host="h", pid=1)
    for s in range(8):
        rec.record("migrate_step", step=s, sent=1, received=1,
                   backlog=100 * (s + 1), dropped_recv=0, population=64)
    bundles = tmp_path / "incidents"
    fr = incident_lib.FlightRecorder(rec, str(bundles), clock=lambda: 123.0)
    assert fr.capture(
        rule="backlog_growth", reason="monotone backlog", trigger="alert"
    ) is not None
    shard = tmp_path / "shard.jsonl"
    rec.to_jsonl(str(shard))

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, SERVE, "--journal", str(shard),
         "--incident-dir", str(bundles), "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO_ROOT, env=env,
    )
    watchdog = threading.Timer(120, proc.kill)
    watchdog.start()
    try:
        line = proc.stdout.readline()
        m = re.search(r"http://([\d.]+):(\d+)/metrics", line)
        assert m, (line, proc.poll(), proc.stderr.read() if proc.poll()
                   is not None else "")
        base = f"http://{m.group(1)}:{m.group(2)}"
        with urllib.request.urlopen(base + "/incidents", timeout=30) as r:
            assert r.status == 200
            doc = json.loads(r.read().decode("utf-8"))
        assert [e["id"] for e in doc["incidents"]] == [
            "incident-0001-backlog_growth"
        ]
        entry = doc["incidents"][0]
        assert entry["rule"] == "backlog_growth"
        assert entry["captured_at"] == 123.0
        # the monotone backlog ALERTs: the probe sees 503, not 200
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz", timeout=30)
        assert ei.value.code == 503
        verdict = json.loads(ei.value.read().decode("utf-8"))
        assert verdict["status"] == "ALERT"
        # /metrics still renders well-formed OpenMetrics alongside
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            assert r.read().decode("utf-8").splitlines()[-1] == "# EOF"
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope", timeout=30)
        assert ei.value.code == 404
        assert b"/incidents" in ei.value.read()
    finally:
        watchdog.cancel()
        proc.terminate()
        proc.wait(timeout=10)


def test_healthz_evaluate_is_read_only():
    rec = StepRecorder(host="h", pid=1)
    for s in range(8):
        rec.record("migrate_step", step=s, sent=1, received=1,
                   backlog=100 * (s + 1), dropped_recv=0, population=64)
    mon = HealthMonitor(rec)
    before = (dict(rec.counts()), rec.total_recorded)
    verdict = mon.evaluate(record=False)
    assert verdict["status"] == "ALERT"       # backlog grows monotonically
    assert (dict(rec.counts()), rec.total_recorded) == before
    # the recording evaluate() journals the same finding afterwards —
    # the read-only pass must not have consumed its novelty
    mon.evaluate()
    assert rec.counts().get("alert", 0) >= 1


# ------------------------------------------------- purity + schema gate


def test_scrape_path_loads_without_jax():
    """metrics.py/aggregate.py — the ISSUE 17 capture path (context.py,
    incident.py) and the ISSUE 18 history plane (store.py, query.py) —
    must be importable with jax absent from sys.modules — the runtime
    half of the G007 contract (a scrape, an incident capture or a store
    drain can never stall on device work it cannot even reach)."""
    code = (
        "import importlib.util, os, sys, types\n"
        f"tel = {TELEMETRY!r}\n"
        "pkg = types.ModuleType('scrape_pkg')\n"
        "pkg.__path__ = [tel]\n"
        "sys.modules['scrape_pkg'] = pkg\n"
        "for name in ('context', 'recorder', 'metrics', 'aggregate',\n"
        "             'incident', 'store', 'query'):\n"
        "    spec = importlib.util.spec_from_file_location(\n"
        "        'scrape_pkg.' + name, os.path.join(tel, name + '.py'))\n"
        "    mod = importlib.util.module_from_spec(spec)\n"
        "    sys.modules[spec.name] = mod\n"
        "    spec.loader.exec_module(mod)\n"
        "assert 'jax' not in sys.modules, 'scrape path pulled in jax'\n"
        "print('pure')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO_ROOT, timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert out.stdout.strip() == "pure"
    # static half: no jax import statement in the module sources
    for name in ("metrics.py", "aggregate.py", "context.py", "incident.py",
                 "store.py", "query.py"):
        with open(os.path.join(TELEMETRY, name), encoding="utf-8") as fh:
            src = fh.read()
        assert re.search(r"#\s*gridlint:\s*scrape-path", src), name
        assert not re.search(r"^\s*(?:import|from)\s+jax\b", src,
                             re.MULTILINE), f"{name} imports jax"


def _recorded_kinds():
    """Every literal event kind passed to .record()/.record_at() across
    the package (AST scan — grep would catch strings in comments)."""
    kinds = set()
    for dirpath, _, names in os.walk(PACKAGE):
        for fname in names:
            if not fname.endswith(".py"):
                continue
            path = os.path.join(dirpath, fname)
            with open(path, encoding="utf-8") as fh:
                tree = ast.parse(fh.read(), filename=path)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("record", "record_at")
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                kinds.add(node.args[0].value)
    return kinds


def test_schema_drift_gate():
    """SCHEMA.md and the code must agree on the event-kind set in BOTH
    directions: an undocumented kind and a documented-but-dead kind are
    equally schema drift."""
    with open(os.path.join(TELEMETRY, "SCHEMA.md"), encoding="utf-8") as fh:
        schema = fh.read()
    documented = set()
    for line in schema.splitlines():
        if line.startswith("### "):
            documented.update(re.findall(r"`([a-z_]+)`", line))
    recorded = _recorded_kinds()
    assert recorded, "AST scan found no journaled kinds — scan broken?"
    undocumented = recorded - documented
    dead = documented - recorded
    assert not undocumented, (
        f"journaled kinds missing from SCHEMA.md: {sorted(undocumented)}"
    )
    assert not dead, (
        f"SCHEMA.md documents kinds nothing records: {sorted(dead)}"
    )


# ------------------------------------------------ noise-aware classifier


def _bench_history():
    caps = []
    for i in range(1, 6):
        with open(os.path.join(REPO_ROOT, f"BENCH_r{i:02d}.json")) as fh:
            caps.append(json.load(fh))
    return caps


def test_classify_delta_boundaries():
    assert classify_delta(0.0, 0.10) == "OK"
    assert classify_delta(-0.3, 0.10) == "OK"
    assert classify_delta(0.05, 0.10) == "WOBBLE"
    assert classify_delta(0.15, 0.10, threshold=0.10) == "WARN"
    assert classify_delta(0.25, 0.10, threshold=0.10) == "REGRESSION"
    floor, defaulted = noise_floor(None, None)
    assert floor == pytest.approx(1.25 * 0.08) and defaulted
    floor, defaulted = noise_floor(0.16, 0.04)
    assert floor == pytest.approx(0.20) and not defaulted


def test_r04_to_r05_wobble_passes_the_gate():
    """The one measured wobble in committed history: r05's headline is
    7.9-8.6% below r04 on byte-identical exchange work. The noise-aware
    gate must classify it WOBBLE and pass; the legacy binary gate is the
    behavior this replaces."""
    caps = _bench_history()
    ok, lines, labels = classify_capture(caps[-1], caps[:-1])
    assert ok, "\n".join(lines)
    assert labels["value"] == "WOBBLE", (labels, lines)
    assert set(labels.values()) <= {"OK", "WOBBLE"}, lines


def test_synthetic_2x_slowdown_is_regression():
    caps = _bench_history()
    metrics = regress.extract_metrics(caps[-1])
    worse = {
        k: (v / 2 if regress.GUARDED_METRICS[k] == "higher" else v * 2)
        for k, v in metrics.items()
    }
    ok, lines, labels = classify_capture({"parsed": worse}, caps)
    assert not ok, "\n".join(lines)
    assert labels["value"] == "REGRESSION", (labels, lines)


def test_progprofile_hash_drift_notes():
    """A capture taken under a different progcheck wire-model hash than
    the best capture gets a correlation note (the delta may be the
    intentional J004-gated change); same hash or missing hashes stay
    silent."""
    caps = _bench_history()
    metrics = regress.extract_metrics(caps[-1])
    # synthetic best that wins the per-metric pick over all committed
    # captures, so ITS hash is the one the note compares against
    best = {
        k: (v * 2 if regress.GUARDED_METRICS[k] == "higher" else v / 2)
        for k, v in metrics.items()
    }
    best["progprofile_hash"] = "aaaa000011112222"

    def run(cur_hash):
        cur = dict(metrics)
        if cur_hash is not None:
            cur["progprofile_hash"] = cur_hash
        _, lines, _ = classify_capture(
            {"parsed": cur}, caps + [{"parsed": best}]
        )
        return [ln for ln in lines if "wire model changed" in ln]

    drift = run("bbbb333344445555")
    assert len(drift) == 1, drift
    assert "aaaa000011112222" in drift[0]
    assert "bbbb333344445555" in drift[0]
    assert "J004" in drift[0]
    assert run("aaaa000011112222") == []  # same hash: no note
    assert run(None) == []  # current predates the embed: no note


def test_bench_check_cli_passes_on_committed_history():
    """Satellite wiring: `make bench-check` runs the classifier and a
    WOBBLE-grade delta (the committed r04→r05 history) must exit 0."""
    out = subprocess.run(
        [sys.executable, os.path.join("scripts", "bench_check.py")],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert "bench-check ok" in out.stdout
    assert "WOBBLE" in out.stdout


# ------------------------------------------------- steady-state overhead


def test_recorder_plus_metrics_overhead_under_2pct(rng, _devices):
    """Acceptance: journaling + health + a full metrics scrape add <= 2%
    to the config1-style steady-state step (min-of-k protocol; the
    scrape is a host-side fold over the ring, so it must be noise
    against ms-scale device steps)."""
    import time

    import jax

    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
    from mpi_grid_redistribute_tpu.telemetry import (
        FlowAccumulator,
        record_flow_snapshot,
        record_migrate_steps,
    )

    grid = ProcessGrid((2, 2, 2))
    n_local = 2048
    n = grid.nranks * n_local
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=Domain(0.0, 1.0, periodic=True), grid=grid, dt=0.02,
        capacity=n_local // 4, n_local=n_local,
    )
    # 128 steps per sample: the observe path under test (per-step
    # journaling + the scrape over the journal) scales WITH the loop, so
    # the overhead ratio is steps-invariant — but the host's absolute
    # scheduler wobble is not, and at 32 steps it dominated a 2% gate
    # (paired deltas spread +-15%); the longer loop buys signal, not a
    # different measurement
    steps = 128
    loop = nbody.make_migrate_loop(cfg, mesh, steps)
    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.2 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    alive = np.ones((n,), bool)
    jax.block_until_ready(loop(pos, vel, alive))  # compile

    def sample(observe):
        rec = StepRecorder()
        mon = HealthMonitor(rec)
        t0 = time.perf_counter()
        out = loop(pos, vel, alive)
        jax.block_until_ready(out)
        stats_host = jax.tree.map(np.asarray, out[3])
        if observe:
            record_migrate_steps(rec, stats_host, rank_totals=True)
            acc = FlowAccumulator()
            acc.update(stats_host)
            record_flow_snapshot(rec, acc)
            mon.note_step_time((time.perf_counter() - t0) / steps)
            mon.evaluate()
            # the scrape itself: journal -> registry -> OpenMetrics text
            text = from_journal(rec).render_openmetrics()
            assert text.rstrip().endswith("# EOF")
        return time.perf_counter() - t0

    # median of paired base/observed deltas with GC held off, for the
    # same reason as test_flow's overhead gate: the in-suite loop
    # wobbles by several ms, so pairs share the slow drift and the
    # median rejects scheduler spikes a min-of-k difference cannot
    import gc

    def batch_median():
        deltas = []
        gc.collect()
        gc.disable()
        try:
            for k in range(9):
                # alternate which leg runs first: the two legs of a pair
                # share the slow drift, but the SECOND leg systematically
                # pays any residual warm-up/degradation trend —
                # alternating puts that bias on each leg equally often,
                # so the median of the signed deltas cancels it instead
                # of billing it to the observe path
                if k % 2:
                    o = sample(True)
                    b = sample(False)
                else:
                    b = sample(False)
                    o = sample(True)
                deltas.append((o - b) / b)
        finally:
            gc.enable()
        return float(np.median(deltas)), deltas

    overhead, deltas = batch_median()
    if overhead > 0.02:
        # a real regression reproduces; a scheduler-noise excursion does
        # not — confirm before failing (keeps the gate's false-failure
        # rate at p^2 without loosening the 2% acceptance itself)
        overhead2, deltas2 = batch_median()
        if overhead2 < overhead:
            overhead, deltas = overhead2, deltas2
    assert overhead <= 0.02, (
        f"recorder+metrics overhead {overhead:.1%} > 2% (median of "
        f"{len(deltas)} paired samples, {steps}-step loop, best of two "
        f"batches; deltas {[f'{d:.1%}' for d in deltas]})"
    )
