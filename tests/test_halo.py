import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.parallel import halo as halo_lib
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
from mpi_grid_redistribute_tpu import GridRedistribute
from mpi_grid_redistribute_tpu.oracle import brute_force_ghosts


def _sorted_rows(a):
    a = np.asarray(a)
    return a[np.lexsort(a.T[::-1])]


@pytest.mark.parametrize(
    "grid_shape,periodic",
    [((2, 2, 2), True), ((2, 2, 2), False), ((4, 2, 1), True)],
)
def test_halo_matches_brute_force(rng, grid_shape, periodic):
    domain = Domain(0.0, 1.0, periodic=periodic)
    grid = ProcessGrid(grid_shape)
    R = grid.nranks
    n_local = 64
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    # move particles onto their owners first
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=3 * n_local)
    res = rd.redistribute(pos)
    count = np.asarray(res.count)
    oc = res.positions.shape[0] // R
    w = 0.08
    mesh = mesh_lib.make_mesh(grid)
    hx = halo_lib.build_halo_exchange(
        mesh, domain, grid, w, pass_capacity=256, ghost_capacity=1024
    )
    hres = hx(res.positions, res.count)
    assert int(np.asarray(hres.overflow).sum()) == 0
    gcount = np.asarray(hres.ghost_count)
    gpos = np.asarray(hres.ghost_positions)

    shards = [
        np.asarray(res.positions)[r * oc : r * oc + count[r]] for r in range(R)
    ]
    expected = brute_force_ghosts(domain, grid, shards, w)
    for r in range(R):
        got = gpos[r * 1024 : r * 1024 + gcount[r]]
        exp = expected[r]
        assert gcount[r] == len(exp), f"rank {r}: {gcount[r]} vs {len(exp)}"
        np.testing.assert_allclose(
            _sorted_rows(got), _sorted_rows(exp), atol=1e-5
        )


def test_halo_fields_ride_along(rng):
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 32
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=2 * n_local)
    res = rd.redistribute(pos, np.arange(R * n_local, dtype=np.int32))
    mesh = mesh_lib.make_mesh(grid)
    hx = halo_lib.build_halo_exchange(
        mesh, domain, grid, 0.1, pass_capacity=128, ghost_capacity=512,
        n_fields=1,
    )
    hres = hx(res.positions, res.count, res.fields[0])
    gcount = np.asarray(hres.ghost_count)
    ids = np.asarray(hres.ghost_fields[0])
    gpos = np.asarray(hres.ghost_positions)
    # every ghost id refers to a real particle whose (unshifted) position
    # matches the ghost position modulo the domain extent
    oc = res.positions.shape[0] // R
    id2pos = {}
    cnt = np.asarray(res.count)
    for r in range(R):
        for i in range(cnt[r]):
            id2pos[int(np.asarray(res.fields[0])[r * oc + i])] = np.asarray(
                res.positions
            )[r * oc + i]
    for r in range(R):
        for k in range(gcount[r]):
            gid = int(ids[r * 512 + k])
            q = gpos[r * 512 + k]
            p = id2pos[gid]
            np.testing.assert_allclose(q % 1.0, p % 1.0, atol=1e-5)


def test_halo_width_validation():
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    with pytest.raises(ValueError):
        halo_lib.shard_halo_fn(domain, grid, 0.6, 8, 8)  # > cell width 0.5
    with pytest.raises(ValueError):
        halo_lib.shard_halo_fn(domain, grid, -0.1, 8, 8)


def test_halo_overflow_counted(rng):
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 64
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=2 * n_local)
    res = rd.redistribute(pos)
    mesh = mesh_lib.make_mesh(grid)
    hx = halo_lib.build_halo_exchange(
        mesh, domain, grid, 0.25, pass_capacity=4, ghost_capacity=8
    )
    hres = hx(res.positions, res.count)
    assert int(np.asarray(hres.overflow).sum()) > 0
    assert (np.asarray(hres.ghost_count) <= 8).all()


def test_default_capacities_uniform_headroom():
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    pc, gc = halo_lib.default_capacities(domain, grid, 0.05, 1000)
    # f = w/cell_w = 0.1 per direction; ghosts ~ (1.2^3 - 1)*1000 = 728
    assert 728 * 2 <= gc <= 728 * 2 + 8
    assert pc >= 2 * 100  # last-axis pass ~ 100 * 1.2^2 rows, 2x headroom
    with pytest.raises(ValueError):
        halo_lib.default_capacities(domain, grid, 0.05, 0)


def test_halo_auto_capacities_no_overflow(rng):
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 128
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=3 * n_local)
    res = rd.redistribute(pos)
    mesh = mesh_lib.make_mesh(grid)
    hx = halo_lib.build_halo_exchange(mesh, domain, grid, 0.08)
    hres = hx(res.positions, res.count)
    assert int(np.asarray(hres.overflow).sum()) == 0
    assert int(np.asarray(hres.ghost_count).sum()) > 0


@pytest.mark.parametrize(
    "grid_shape,periodic",
    [((2, 2, 2), True), ((2, 2, 2), False), ((4, 2, 1), True)],
)
def test_vrank_halo_matches_brute_force(rng, grid_shape, periodic):
    """The single-device vrank twin reproduces the brute-force ghost sets."""
    domain = Domain(0.0, 1.0, periodic=periodic)
    grid = ProcessGrid(grid_shape)
    R = grid.nranks
    n_local = 64
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=3 * n_local)
    res = rd.redistribute(pos)
    count = np.asarray(res.count)
    oc = res.positions.shape[0] // R
    w = 0.08
    G = 1024
    hv = halo_lib.build_halo_vranks(domain, grid, w, 256, G)
    gpos, gcount, overflow = hv(
        np.asarray(res.positions).reshape(R, oc, 3), count
    )
    gpos, gcount = np.asarray(gpos), np.asarray(gcount)
    assert int(np.asarray(overflow).sum()) == 0

    shards = [
        np.asarray(res.positions)[r * oc : r * oc + count[r]]
        for r in range(R)
    ]
    expected = brute_force_ghosts(domain, grid, shards, w)
    for r in range(R):
        got = gpos[r, : gcount[r]]
        exp = expected[r]
        assert gcount[r] == len(exp), f"rank {r}: {gcount[r]} vs {len(exp)}"
        np.testing.assert_allclose(
            _sorted_rows(got), _sorted_rows(exp), atol=1e-5
        )


def test_planar_halo_matches_rowmajor_bitlevel(rng):
    """Round-4 planar halo: same ghost set, same ORDER, bit-identical
    values as the row-major vrank engine — including a bitcast int32 id
    field riding the planar fused rows."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 2048
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=2 * n_local)
    ids = np.arange(R * n_local, dtype=np.int32)
    res = rd.redistribute(pos, ids)
    oc = res.positions.shape[0] // R
    count = np.asarray(res.count)
    w, H, G = 0.1, 2048, 4096
    # row-major engine with the id field riding along
    hv = halo_lib.build_halo_vranks(domain, grid, w, H, G)
    rpos, rcount, *rfields_over = hv(
        np.asarray(res.positions).reshape(R, oc, 3), count,
        np.asarray(res.fields[0]).reshape(R, oc),
    )
    rids, rover = rfields_over
    # planar engine: fused [V, K=4, n] = 3 pos rows + 1 bitcast id row
    fused = np.concatenate(
        [
            np.asarray(res.positions).reshape(R, oc, 3).transpose(0, 2, 1),
            np.asarray(res.fields[0])
            .reshape(R, 1, oc)
            .view(np.float32),
        ],
        axis=1,
    )
    hp = halo_lib.build_halo_planar_vranks(domain, grid, w, H, G)
    gplanar, pcount, pover = hp(fused, count)
    np.testing.assert_array_equal(np.asarray(pcount), np.asarray(rcount))
    np.testing.assert_array_equal(np.asarray(pover), np.asarray(rover))
    gplanar = np.asarray(gplanar)
    for r in range(R):
        g = int(np.asarray(rcount)[r])
        # positions: planar rows 0-2, bit-identical and SAME ORDER
        np.testing.assert_array_equal(
            gplanar[r, :3, :g].T.view(np.uint32),
            np.asarray(rpos)[r, :g].view(np.uint32),
        )
        # the id field: planar row 3 (bitcast) == row-major ghost field
        np.testing.assert_array_equal(
            gplanar[r, 3, :g].view(np.int32), np.asarray(rids)[r, :g]
        )
    # int32 input dtype round-trips too (transport is int32 either way)
    gp2, pc2, _ = hp(fused.view(np.int32), count)
    np.testing.assert_array_equal(
        np.asarray(gp2).view(np.uint32), gplanar.view(np.uint32)
    )


def test_planar_halo_shard_map_matches_vranks(rng):
    """The shard_map planar twin (ppermute wire) is bit-identical to the
    vmapped vrank planar engine."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 64
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=2 * n_local)
    res = rd.redistribute(pos)
    oc = res.positions.shape[0] // R
    count = np.asarray(res.count)
    w, H, G = 0.1, 128, 512
    fused_v = (
        np.asarray(res.positions).reshape(R, oc, 3).transpose(0, 2, 1)
    )  # [V, 3, n]
    hp = halo_lib.build_halo_planar_vranks(domain, grid, w, H, G)
    gv, cv, ov = hp(fused_v, count)
    mesh = mesh_lib.make_mesh(grid)
    hm = halo_lib.build_halo_planar(mesh, domain, grid, w, H, G)
    fused_g = np.ascontiguousarray(fused_v.transpose(1, 0, 2)).reshape(
        3, R * oc
    )
    gm, cm, om = hm(fused_g, count)
    np.testing.assert_array_equal(np.asarray(cm), np.asarray(cv))
    np.testing.assert_array_equal(np.asarray(om), np.asarray(ov))
    gm = np.asarray(gm).reshape(3, R, G).transpose(1, 0, 2)
    np.testing.assert_array_equal(
        gm.view(np.uint32), np.asarray(gv).view(np.uint32)
    )


def test_vrank_halo_matches_shard_map(rng):
    """Both engines produce identical ghost multisets (bit-level rows)."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 48
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=2 * n_local)
    res = rd.redistribute(pos)
    oc = res.positions.shape[0] // R
    w, H, G = 0.1, 128, 512
    mesh = mesh_lib.make_mesh(grid)
    hx = halo_lib.build_halo_exchange(
        mesh, domain, grid, w, pass_capacity=H, ghost_capacity=G
    )
    hres = hx(res.positions, res.count)
    hv = halo_lib.build_halo_vranks(domain, grid, w, H, G)
    vpos, vcount, voverflow = hv(
        np.asarray(res.positions).reshape(R, oc, 3), np.asarray(res.count)
    )
    gcount = np.asarray(hres.ghost_count)
    np.testing.assert_array_equal(gcount, np.asarray(vcount))
    np.testing.assert_array_equal(
        np.asarray(hres.overflow), np.asarray(voverflow)
    )
    spos = np.asarray(hres.ghost_positions).reshape(R, G, 3)
    for r in range(R):
        a = _sorted_rows(spos[r, : gcount[r]]).view(np.uint32)
        b = _sorted_rows(np.asarray(vpos)[r, : gcount[r]]).view(np.uint32)
        np.testing.assert_array_equal(a, b)



def _assert_planar_matches_rowmajor(res, count, rpos, rcount, rover,
                                    grid, domain, w, H, G):
    """Planar engine vs row-major reference on the same redistributed
    state: identical overflow counters, ghost counts, and per-rank ghost
    position bits (shared by the width and overflow parametrizations)."""
    R = grid.nranks
    oc = np.asarray(res.positions).shape[0] // R
    fused = np.ascontiguousarray(
        np.asarray(res.positions).reshape(R, oc, 3).transpose(0, 2, 1)
    )
    hp = halo_lib.build_halo_planar_vranks(domain, grid, w, H, G)
    gplanar, pcount, pover = hp(fused, count)
    np.testing.assert_array_equal(np.asarray(pcount), np.asarray(rcount))
    np.testing.assert_array_equal(np.asarray(pover), np.asarray(rover))
    gplanar = np.asarray(gplanar)
    for r in range(R):
        g = int(np.asarray(rcount)[r])
        np.testing.assert_array_equal(
            gplanar[r, :3, :g].T.view(np.uint32),
            np.asarray(rpos)[r, :g].view(np.uint32),
        )


@pytest.mark.parametrize("w", [0.2, 0.25, 0.3])
def test_planar_halo_band_widths_bitlevel(rng, w):
    """Both planar selection paths — the merged single-banded-sort axis
    (2w < cell_w: w=0.2) and the per-direction two-sort fallback
    (2w >= cell_w - ulp margin: w=0.25 exactly at the boundary, where
    f32 threshold rounding can OVERLAP the bands and a merged sort would
    drop one direction's copy — review round 4; and w=0.3) — stay
    bit-identical to the row-major vrank engine (and the static per-axis
    candidate window drops no ghosts)."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 512
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=2 * n_local)
    res = rd.redistribute(pos)
    oc = res.positions.shape[0] // R
    count = np.asarray(res.count)
    H, G = halo_lib.default_capacities(domain, grid, w, oc)
    hv = halo_lib.build_halo_vranks(domain, grid, w, H, G)
    rpos, rcount, rover = hv(
        np.asarray(res.positions).reshape(R, oc, 3), count
    )
    assert int(np.asarray(rover).sum()) == 0
    _assert_planar_matches_rowmajor(
        res, count, rpos, rcount, rover, grid, domain, w, H, G
    )


@pytest.mark.parametrize("w", [0.2, 0.3])
def test_planar_halo_overflow_parity_bitlevel(rng, w):
    """Under TIGHT capacities (overflowing passes and ghost buffer) the
    planar engine — merged banded-sort path (w=0.2) and two-sort
    fallback (w=0.3) — clips exactly like the row-major engine:
    identical overflow counters, ghost counts, and ghost bits."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 512
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=2 * n_local)
    res = rd.redistribute(pos)
    oc = res.positions.shape[0] // R
    count = np.asarray(res.count)
    H, G = 64, 160  # far below the shell population -> overflow
    hv = halo_lib.build_halo_vranks(domain, grid, w, H, G)
    rpos, rcount, rover = hv(
        np.asarray(res.positions).reshape(R, oc, 3), count
    )
    assert int(np.asarray(rover).sum()) > 0  # the regime under test
    _assert_planar_matches_rowmajor(
        res, count, rpos, rcount, rover, grid, domain, w, H, G
    )


# ---------------------------------------------------------------------------
# Public API surface: GridRedistribute.halo() (VERDICT round-4 item 4)
# ---------------------------------------------------------------------------


def _api_halo_setup(rng, grid_shape=(2, 2, 2), n_local=64, periodic=True):
    domain = Domain(0.0, 1.0, periodic=periodic)
    grid = ProcessGrid(grid_shape)
    R = grid.nranks
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=3 * n_local)
    res = rd.redistribute(pos)
    return domain, grid, rd, res


@pytest.mark.parametrize("engine", ["auto", "rowmajor"])
def test_api_halo_matches_brute_force(rng, engine):
    """rd.halo(positions, width=...) — one call from the package root,
    auto capacities, engine auto-select — reproduces the brute-force
    ghost sets."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 64
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=4.0,
                          out_capacity=3 * n_local, engine=engine)
    res = rd.redistribute(pos)
    count = np.asarray(res.count)
    oc = res.positions.shape[0] // R
    w = 0.08
    hres = rd.halo(res.positions, width=w, count=res.count)
    assert int(np.asarray(hres.overflow).sum()) == 0
    gcount = np.asarray(hres.ghost_count)
    gpos = np.asarray(hres.ghost_positions)
    G = gpos.shape[0] // R
    shards = [
        np.asarray(res.positions)[r * oc : r * oc + count[r]]
        for r in range(R)
    ]
    from mpi_grid_redistribute_tpu.oracle import brute_force_ghosts as bf
    expected = bf(domain, grid, shards, w)
    for r in range(R):
        got = gpos[r * G : r * G + gcount[r]]
        exp = expected[r]
        assert gcount[r] == len(exp), f"rank {r}: {gcount[r]} vs {len(exp)}"
        np.testing.assert_allclose(
            _sorted_rows(got), _sorted_rows(exp), atol=1e-5
        )


def test_api_halo_fields_and_engine_parity(rng):
    """Fields ride along through rd.halo, and the planar (auto) and
    row-major engines return identical ghost sets + counts."""
    domain, grid, rd, res = _api_halo_setup(rng)
    R = grid.nranks
    ids = np.arange(res.positions.shape[0], dtype=np.int32)
    h_auto = rd.halo(res.positions, ids, width=0.07, count=res.count)
    rd_rm = GridRedistribute(domain, grid, engine="rowmajor")
    h_rm = rd_rm.halo(res.positions, ids, width=0.07, count=res.count)
    assert np.array_equal(
        np.asarray(h_auto.ghost_count), np.asarray(h_rm.ghost_count)
    )
    ga, gb = np.asarray(h_auto.ghost_positions), np.asarray(h_rm.ghost_positions)
    ia, ib = np.asarray(h_auto.ghost_fields[0]), np.asarray(h_rm.ghost_fields[0])
    Ga, Gb = ga.shape[0] // R, gb.shape[0] // R
    cnt = np.asarray(h_auto.ghost_count)
    for r in range(R):
        rows_a = np.concatenate(
            [ga[r * Ga : r * Ga + cnt[r]], ia[r * Ga : r * Ga + cnt[r], None].astype(np.float32)],
            axis=1,
        )
        rows_b = np.concatenate(
            [gb[r * Gb : r * Gb + cnt[r]], ib[r * Gb : r * Gb + cnt[r], None].astype(np.float32)],
            axis=1,
        )
        np.testing.assert_array_equal(_sorted_rows(rows_a), _sorted_rows(rows_b))
    # each ghost id maps back to a source particle whose position matches
    # modulo the domain extent
    src_pos = np.asarray(res.positions)
    for r in range(R):
        gp = ga[r * Ga : r * Ga + cnt[r]]
        gi = ia[r * Ga : r * Ga + cnt[r]]
        d = np.abs(src_pos[gi] - gp)
        d = np.minimum(d, 1.0 - d)  # periodic extent 1.0
        assert d.max() < 1e-5


def test_api_halo_grow_on_overflow(rng):
    """Data overflowing the derived capacities is healed by growth under
    on_overflow='grow'; grown capacities stick per width.

    The derived budgets are sized from the PADDED per-shard rows (see
    default_capacities), so even headroom=1.0 is generous for clustered
    inputs — forcing real overflow needs headroom well below 1."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 256
    # cluster everything near a corner: shell population >> uniform
    pos = (rng.uniform(0, 1, size=(R * n_local, 3)) ** 4).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=8.0,
                          out_capacity=8 * n_local)
    res = rd.redistribute(pos)
    # establish that these inputs genuinely overflow the starved budgets
    # before claiming growth healed anything
    rd_probe = GridRedistribute(domain, grid, on_overflow="ignore")
    probe = rd_probe.halo(res.positions, width=0.12, count=res.count,
                          headroom=0.05)
    assert int(np.asarray(probe.overflow).sum()) > 0
    hres = rd.halo(res.positions, width=0.12, count=res.count,
                   headroom=0.05)
    assert int(np.asarray(hres.overflow).sum()) == 0
    assert rd._halo_caps  # growth stuck on the instance
    # the stuck capacities exceed the starved derived ones
    widths = halo_lib._as_per_axis(0.12, domain.ndim)
    dpc, dgc = halo_lib.default_capacities(
        domain, grid, widths, res.positions.shape[0] // R, 0.05
    )
    spc, sgc = rd._halo_caps[widths]
    assert spc >= dpc and sgc >= dgc and (spc, sgc) != (dpc, dgc)
    # 'raise' surfaces instead of healing
    rd2 = GridRedistribute(domain, grid, on_overflow="raise")
    with pytest.raises(RuntimeError, match="halo overflow"):
        rd2.halo(res.positions, width=0.12, count=res.count,
                 headroom=0.05)


def test_api_halo_grow_retries_with_grown_caps(rng):
    """Regression for the grow-then-retry restructure: every capacity
    pair the loop grows to is actually RUN (growth only happens when a
    retry follows), capacities increase monotonically, and the run that
    returns is the last attempted pair."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 256
    pos = (rng.uniform(0, 1, size=(R * n_local, 3)) ** 4).astype(np.float32)
    rd = GridRedistribute(domain, grid, capacity_factor=8.0,
                          out_capacity=8 * n_local)
    res = rd.redistribute(pos)
    attempts = []
    real_once = rd._halo_once

    def spy(positions, fields, count, widths, pc, gc):
        attempts.append((pc, gc))
        return real_once(positions, fields, count, widths, pc, gc)

    rd._halo_once = spy
    hres = rd.halo(res.positions, width=0.12, count=res.count,
                   headroom=0.05)
    assert int(np.asarray(hres.overflow).sum()) == 0
    assert len(attempts) >= 2  # starved start forced at least one retry
    for (pc0, gc0), (pc1, gc1) in zip(attempts, attempts[1:]):
        assert pc1 >= pc0 and gc1 >= gc0 and (pc1, gc1) != (pc0, gc0)
    # the capacities that stuck are the ones of the final successful run
    widths = halo_lib._as_per_axis(0.12, domain.ndim)
    assert rd._halo_caps[widths] == attempts[-1]


def test_api_halo_grow_nonconvergence_reports_run_caps(rng):
    """When growth never converges, the error names the capacities of
    the run that still overflowed — not untried next-round values."""
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 2, 2))
    R, n_local = 8, 64
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    rd = GridRedistribute(domain, grid)
    res = rd.redistribute(pos)
    attempts = []

    def always_overflow(positions, fields, count, widths, pc, gc):
        attempts.append((pc, gc))
        return halo_lib.HaloResult(
            positions, np.zeros(R, np.int32), (), np.ones(R, np.int32)
        )

    rd._halo_once = always_overflow
    with pytest.raises(RuntimeError, match="did not converge") as ei:
        rd.halo(res.positions, width=0.1, count=res.count)
    assert len(attempts) == 5  # max_attempts runs, all attempted
    last_pc, last_gc = attempts[-1]
    msg = str(ei.value)
    assert f"pass_capacity={last_pc}" in msg
    assert f"ghost_capacity={last_gc}" in msg


def test_api_halo_validation(rng):
    domain, grid, rd, res = _api_halo_setup(rng)
    with pytest.raises(ValueError, match="exceeds subdomain width"):
        rd.halo(res.positions, width=0.9, count=res.count)
    rdn = GridRedistribute(domain, grid, backend="numpy")
    with pytest.raises(ValueError, match="jax backend"):
        rdn.halo(np.asarray(res.positions), width=0.05, count=np.asarray(res.count))
    from mpi_grid_redistribute_tpu import GridEdges
    e = GridEdges.balanced_for(
        domain, grid, rng.uniform(0, 1, (4096, 3)).astype(np.float32)
    )
    rde = GridRedistribute(domain, grid, edges=e)
    with pytest.raises(ValueError, match="uniform cells"):
        rde.halo(res.positions, width=0.05, count=res.count)


def test_api_halo_zero_width(rng):
    """width=0 -> zero ghosts everywhere, no overflow."""
    domain, grid, rd, res = _api_halo_setup(rng)
    hres = rd.halo(res.positions, width=0.0, count=res.count)
    assert int(np.asarray(hres.ghost_count).sum()) == 0
    assert int(np.asarray(hres.overflow).sum()) == 0
