"""service/resident.py: chunked macro-stepping (ISSUE 10).

The chunk *scheduler* — boundary auto-split at snapshot/health cadences,
singleton chunks at fault-eligible steps, per-step journal folding, the
sleep-excluded SLO wall — is backend-independent, so the fault matrix
runs on the numpy oracle at tiny sizes and asserts the whole run is
invariant in ``cfg.chunk``: same final bytes, same fault step, same
journaled ``(step, dropped)`` stream. The jax resident path itself
(``lax.scan`` macro-step, device-resident carry) is exercised in-process
on the 8-virtual-device mesh — chunk-vs-eager particle-set identity,
misaligned snapshot cadence, and a jaxpr walk proving the traced macro
program carries no host callbacks (the dynamic backstop behind gridlint
rule G009). Service-shape speedups are gated by
``bench/config10_service.py`` (``make service-bench``), not here.
"""

import dataclasses
import os
import time

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.service import (
    CrashFault,
    DriverConfig,
    FallbackFloodFault,
    FaultPlan,
    JournalShardLossFault,
    RestartPolicy,
    ServiceDriver,
    StallError,
    StallFault,
    Supervisor,
    TornSnapshotFault,
)
from mpi_grid_redistribute_tpu.service import elastic, resident
from mpi_grid_redistribute_tpu.telemetry import StepRecorder
from mpi_grid_redistribute_tpu.utils import checkpoint

CHUNKS = (1, 7, 16)


def _cfg(tmp_path, **kw):
    base = dict(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=24,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
    )
    base.update(kw)
    return DriverConfig(**base)


def _jax_cfg(tmp_path, **kw):
    base = dict(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=12,
        seed=5,
        backend="jax",
        snapshot_every=0,
        snapshot_dir=None,
        watchdog_s=0.0,
    )
    base.update(kw)
    return DriverConfig(**base)


def _supervised(cfg, faults, max_restarts=5):
    rec = StepRecorder()

    def factory(grid_shape=None):
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return ServiceDriver(c, recorder=rec, faults=faults)

    sup = Supervisor(
        factory,
        policy=RestartPolicy(
            max_restarts=max_restarts, backoff_base_s=0.01,
            backoff_cap_s=0.02,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    return sup, rec


def _assert_bit_identical(a, b):
    for name, x, y in zip(("pos", "vel", "ids", "count"), a, b):
        assert x.tobytes() == y.tobytes(), f"{name} diverged"


def _latency_seq(rec):
    """The journaled per-step stream a chunked run must reproduce:
    step numbers and dropped counts (seconds are apportioned wall time,
    legitimately chunk-dependent)."""
    return [
        (e.data["step"], e.data["dropped"])
        for e in rec.events("step_latency")
    ]


# ------------------------------------- fault matrix, chunk-invariant


def _fault_for(kind, workdir):
    """Fresh injector + the per-kind config extras, mirroring
    tests/test_service.py's eager fault matrix."""
    extra = {}
    if kind == "crash":
        fault, restarts = CrashFault(9), 1
    elif kind == "stall":
        fault, restarts = StallFault(7, seconds=0.5), 1
        extra["watchdog_s"] = 0.2
    elif kind == "torn_snapshot":
        fault, restarts = TornSnapshotFault(snapshot_index=1), 1
    elif kind == "journal_loss":
        fault, restarts = JournalShardLossFault(6), 0
        extra["journal_dir"] = str(workdir / "journal")
    else:
        fault, restarts = FallbackFloodFault(start_step=1, steps=24), 0
    return fault, restarts, extra


@pytest.mark.parametrize("kind", [
    "crash", "stall", "torn_snapshot", "journal_loss", "fallback_flood",
])
def test_fault_matrix_is_chunk_invariant(tmp_path, kind):
    """Every injector fires at the same step for chunk in {1, 7, 16}
    (singleton chunks at fault-eligible steps) and the run ends
    bit-identical to the chunk=1 run — final state bytes AND the
    journaled (step, dropped) step_latency sequence."""
    results = {}
    for chunk in CHUNKS:
        workdir = tmp_path / f"chunk{chunk}"
        workdir.mkdir()
        fault, restarts, extra = _fault_for(kind, workdir)
        cfg = _cfg(workdir, chunk=chunk, **extra)
        sup, rec = _supervised(cfg, FaultPlan([fault]))
        verdict = sup.run()

        assert verdict.ok is True, (chunk, verdict)
        assert verdict.gave_up is False
        assert verdict.restarts == restarts, (chunk, verdict)
        assert verdict.step == cfg.steps
        fired = rec.events("fault_injected")
        assert len(fired) == 1
        results[chunk] = (
            sup.driver.state, fired[0].data["step"], _latency_seq(rec),
        )

    state1, fault_step1, seq1 = results[1]
    for chunk in CHUNKS[1:]:
        state, fault_step, seq = results[chunk]
        _assert_bit_identical(state, state1)
        assert fault_step == fault_step1, f"chunk={chunk}"
        assert seq == seq1, f"chunk={chunk}"


# ------------------------------------------- jax resident path, in-process


def test_jax_chunked_matches_eager(tmp_path):
    """chunk=5 on the resident lax.scan path vs chunk=1 on the eager
    per-step path, same seed/steps: identical particle set and an
    identical journaled (step, dropped) stream."""
    states, seqs = {}, {}
    for chunk in (1, 5):
        drv = ServiceDriver(_jax_cfg(tmp_path, chunk=chunk))
        drv.init_state()
        drv.run()
        drv.close()
        states[chunk] = drv.state
        seqs[chunk] = _latency_seq(drv.recorder)
    assert elastic.particle_set(*states[5]) == elastic.particle_set(
        *states[1]
    )
    assert states[5][3].tobytes() == states[1][3].tobytes()  # count
    assert seqs[5] == seqs[1]


def test_snapshot_cadence_survives_misaligned_chunk(tmp_path):
    """snapshot_every=6 with chunk=4 (6 % 4 != 0): chunks auto-split so
    snapshots land exactly at steps 6 and 12, from state bit-identical
    to the chunk=1 run's."""
    states = {}
    for chunk in (1, 4):
        snap_dir = tmp_path / f"snaps{chunk}"
        cfg = _jax_cfg(
            tmp_path, chunk=chunk, snapshot_every=6,
            snapshot_dir=str(snap_dir),
        )
        drv = ServiceDriver(cfg)
        drv.init_state()
        drv.run()
        drv.close()
        snaps = checkpoint.list_snapshots(cfg.snapshot_dir)
        steps = sorted(
            int(os.path.basename(p).split("_")[1]) for p in snaps
        )
        assert steps == [6, 12], f"chunk={chunk}"
        states[chunk] = drv.state
    assert elastic.particle_set(*states[4]) == elastic.particle_set(
        *states[1]
    )


# the jaxpr walk lives in the semantic analyzer now (progcheck's public
# API; rule J002 runs this same check over every resident-marked
# program in the registry)
from mpi_grid_redistribute_tpu.analysis.progcheck import (  # noqa: E402
    primitive_names,
)


def test_macro_step_jaxpr_has_no_host_callbacks(tmp_path):
    """The dynamic backstop behind gridlint G009: the traced chunk
    program must be pure device code — no callback/infeed/outfeed
    primitive anywhere in the scan body or its sub-jaxprs, so nothing
    can sync to the host between chunk boundaries."""
    import jax

    drv = ServiceDriver(_jax_cfg(tmp_path))
    drv.init_state()
    drv._ensure_built()
    pos, vel, ids, count = drv.state
    macro, _, _ = resident.make_chunk_fn(drv._rd, drv.cfg.dt, 4,
                                         pos, vel, ids)
    jaxpr = jax.make_jaxpr(macro)(pos, vel, ids, count)
    names = primitive_names(jaxpr.jaxpr)
    assert "scan" in names, "macro-step lost its lax.scan"
    hostile = [
        n for n in names
        if "callback" in n or "infeed" in n or "outfeed" in n
    ]
    assert not hostile, f"host syncs traced into the macro-step: {hostile}"
    drv.close()


# ----------------------------------------- step_sleep vs SLO wall


def test_step_sleep_excluded_from_step_latency(tmp_path):
    """Hand-math: 4 steps paced at step_sleep=0.1 must take >= 0.4s of
    wall clock, yet every journaled step_latency ``seconds`` (and hence
    the SLO histograms and the AmortizationGuard's step EMA fed from
    it) stays far below the 0.1s sleep — pacing is not latency."""
    cfg = _cfg(
        tmp_path, n_local=64, steps=4, snapshot_every=0,
        snapshot_dir=None, step_sleep=0.1,
    )
    drv = ServiceDriver(cfg)
    drv.init_state()
    t0 = time.perf_counter()
    drv.run()
    elapsed = time.perf_counter() - t0
    drv.close()
    evs = drv.recorder.events("step_latency")
    assert [e.data["step"] for e in evs] == [1, 2, 3, 4]
    assert elapsed >= 4 * 0.1  # the pacing itself still happened
    for e in evs:
        assert e.data["seconds"] < 0.05, (
            "step_sleep leaked into the journaled step wall"
        )


def test_step_sleep_still_counts_against_watchdog(tmp_path):
    """The other half of the contract: a sleep longer than watchdog_s
    IS a stall (a stuck pacing sleep must not hide from the watchdog),
    even though the journaled seconds — recorded before the raise —
    stay under the budget."""
    cfg = _cfg(
        tmp_path, n_local=64, steps=3, snapshot_every=0,
        snapshot_dir=None, step_sleep=0.1, watchdog_s=0.05,
    )
    drv = ServiceDriver(cfg)
    drv.init_state()
    with pytest.raises(StallError, match="watchdog"):
        drv.run()
    evs = drv.recorder.events("step_latency")
    assert len(evs) == 1 and evs[0].data["step"] == 1
    assert evs[0].data["seconds"] < cfg.watchdog_s


# ----------------------------------------- rebalance trigger rules


def _backlog_events(rec, backlogs):
    # monotone nonzero backlog growth across a window of migrate_step
    # events is exactly what trips health.backlog_growth (test_flow.py)
    for s, b in enumerate(backlogs):
        rec.record(
            "migrate_step", step=s, sent=10, received=10, backlog=b,
            dropped_recv=0, population=100,
        )


def test_backlog_growth_triggers_rebalance_and_journals_rule():
    cfg = DriverConfig(
        grid_shape=(2, 2, 2), n_local=256, steps=8, backend="numpy",
        snapshot_every=0, rebalance=True,
    )
    drv = ServiceDriver(cfg)
    drv.init_state()
    _backlog_events(drv.recorder, [0, 5, 9, 14, 20])
    drv._health_check()
    evs = [e.data for e in drv.recorder.events("rebalance")]
    assert len(evs) == 1, "backlog_growth ALERT never reached the planner"
    assert evs[0]["rule"] == "backlog_growth"


def test_rebalance_on_filters_trigger_rules():
    """With backlog_growth removed from rebalance_on, the same ALERT
    must NOT actuate — the trigger-rule set is policy, not advisory."""
    cfg = DriverConfig(
        grid_shape=(2, 2, 2), n_local=256, steps=8, backend="numpy",
        snapshot_every=0, rebalance=True,
        rebalance_on=("imbalance_ratio",),
    )
    drv = ServiceDriver(cfg)
    drv.init_state()
    _backlog_events(drv.recorder, [0, 5, 9, 14, 20])
    verdict = drv._health_check()
    assert any(
        f["rule"] == "backlog_growth" for f in verdict["findings"]
    )
    assert drv.recorder.events("rebalance") == []
