"""service/pipeline.py: software-pipelined macro-step (ISSUE 12).

The pipelined scan body reorders the SAME two kernels the sequential
body runs (land step k's exchange; drift+bin step k+1), so everything
observable must be preserved: the final particle SET and per-rank
counts (row order within a rank legitimately differs — resident-slot
layout compacted once at the chunk boundary), the journaled
``(step, dropped)`` stream, and the fault matrix's behavior at every
chunk length. The degrade contract is build-time and total: chunk < 2,
ragged receive capacity and the multi-device topology must hand back
the sequential builder's macro bit-exactly (including its
``ResidentLayoutError``), each journaled as an ``engine_resolved``
event. The overlap itself is a TRACE property, asserted on the jaxpr:
the steady-state cond's pipelined branch issues step k+1's binning
(``floor``) before step k's landing consumer (``scatter``); the
sequential branch does the opposite. Service-shape speedups are gated
by ``bench/config10_service.py`` (``make service-bench``), not here.
"""

import dataclasses

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.analysis import progcheck, rules_jaxpr
from mpi_grid_redistribute_tpu.service import (
    CrashFault,
    DriverConfig,
    FallbackFloodFault,
    FaultPlan,
    JournalShardLossFault,
    RestartPolicy,
    ServiceDriver,
    StallFault,
    Supervisor,
    TornSnapshotFault,
)
from mpi_grid_redistribute_tpu.service import elastic, pipeline, resident
from mpi_grid_redistribute_tpu.telemetry import StepRecorder

# chunk=1 rides the matrix as the must-degrade case (build-time
# delegation to the sequential builder); 2 is the smallest armed
# steady state (one in-flight exchange); 7 does not divide the
# horizon; 16 crosses every snapshot/fault split boundary.
CHUNKS = (1, 2, 7, 16)

# 16 ranks > the 8 forced host devices -> the vmapped vranks topology,
# the one the two-phase schedule arms on (conftest.py forces
# xla_force_host_platform_device_count=8; an 8-rank grid would resolve
# sharded and degrade).
_GRID = (2, 2, 4)


def _cfg(tmp_path, **kw):
    base = dict(
        grid_shape=_GRID,
        n_local=64,
        steps=24,
        seed=3,
        backend="jax",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
        watchdog_s=0.0,
    )
    base.update(kw)
    return DriverConfig(**base)


def _supervised(cfg, faults, max_restarts=5):
    rec = StepRecorder()

    def factory(grid_shape=None):
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return ServiceDriver(c, recorder=rec, faults=faults)

    sup = Supervisor(
        factory,
        policy=RestartPolicy(
            max_restarts=max_restarts, backoff_base_s=0.01,
            backoff_cap_s=0.02,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    return sup, rec


def _latency_seq(rec):
    return [
        (e.data["step"], e.data["dropped"])
        for e in rec.events("step_latency")
    ]


def _pipeline_reasons(rec):
    return [
        e.data["reason"]
        for e in rec.events("engine_resolved")
        if str(e.data.get("reason", "")).startswith("pipeline:")
    ]


def _fault_for(kind, workdir):
    """Fresh injector + per-kind config extras (test_resident.py's
    matrix, on the jax backend)."""
    extra = {}
    if kind == "crash":
        fault, restarts = CrashFault(9), 1
    elif kind == "stall":
        # jax compile steps journal up to ~0.7s of wall on the forced
        # 8-device CPU mesh, so the watchdog budget sits well above
        # that and the stall well above the budget
        fault, restarts = StallFault(7, seconds=3.0), 1
        extra["watchdog_s"] = 2.0
    elif kind == "torn_snapshot":
        fault, restarts = TornSnapshotFault(snapshot_index=1), 1
    elif kind == "journal_loss":
        fault, restarts = JournalShardLossFault(6), 0
        extra["journal_dir"] = str(workdir / "journal")
    else:
        fault, restarts = FallbackFloodFault(start_step=1, steps=24), 0
    return fault, restarts, extra


def _supervised_run(workdir, kind, chunk, pipelined):
    fault, restarts, extra = _fault_for(kind, workdir)
    cfg = _cfg(workdir, chunk=chunk, pipeline=pipelined, **extra)
    sup, rec = _supervised(cfg, FaultPlan([fault]))
    verdict = sup.run()
    assert verdict.ok is True, (kind, chunk, pipelined, verdict)
    assert verdict.gave_up is False
    assert verdict.restarts == restarts, (kind, chunk, pipelined, verdict)
    assert verdict.step == cfg.steps
    fired = rec.events("fault_injected")
    assert len(fired) == 1
    return (
        elastic.particle_set(*sup.driver.state),
        np.asarray(sup.driver.state[3]).tobytes(),
        fired[0].data["step"],
        _latency_seq(rec),
        _pipeline_reasons(rec),
    )


# ------------------------------ fault matrix, pipelined == sequential


@pytest.mark.parametrize("kind", [
    "crash", "stall", "torn_snapshot", "journal_loss", "fallback_flood",
])
def test_fault_matrix_pipelined_matches_sequential(tmp_path, kind):
    """Every injector fires at the same step with the pipelined body at
    chunk in {1, 2, 7, 16} as with the sequential chunk=1 reference,
    ending with the identical particle set, per-rank counts and
    journaled (step, dropped) stream. chunk=1 doubles as the
    must-degrade leg: its run must journal the chunk<2 degrade reason
    and never arm."""
    ref_dir = tmp_path / "seq"
    ref_dir.mkdir()
    ref_set, ref_counts, ref_fault, ref_seq, _ = _supervised_run(
        ref_dir, kind, 1, False
    )
    for chunk in CHUNKS:
        workdir = tmp_path / f"pipe{chunk}"
        workdir.mkdir()
        pset, counts, fault_step, seq, reasons = _supervised_run(
            workdir, kind, chunk, True
        )
        assert pset == ref_set, (kind, chunk)
        assert counts == ref_counts, (kind, chunk)
        assert fault_step == ref_fault, (kind, chunk)
        assert seq == ref_seq, (kind, chunk)
        if chunk == 1:
            # the driver goes eager at chunk=1; any chunk the scheduler
            # does dispatch resident must have degraded, never armed
            assert not any("armed" in r for r in reasons), reasons
        elif kind != "fallback_flood":
            # fallback_flood marks the WHOLE horizon fault-eligible, so
            # the scheduler splits every chunk to a singleton and runs
            # eager — no resident dispatch, hence no resolution to arm
            assert any(
                r.startswith("pipeline: armed") for r in reasons
            ), (kind, chunk, reasons)


# --------------------------------- direct macro identity (no driver)


def _template_state(rd, n_local, seed=11):
    """Random positions/velocities with 25% free slots per rank: enough
    headroom that every mover is granted — the macro-level identity
    contract covers clean (no-drop, no-backlog) trajectories; dirty
    chunks are the driver's discard + eager-rerun territory (the fault
    matrix above exercises that path end to end)."""
    import jax.numpy as jnp

    R = rd.nranks
    shape = np.asarray(rd.grid.shape, np.float32)
    rng = np.random.default_rng(seed)
    pos = np.empty((R * n_local, 3), np.float32)
    for coords in np.ndindex(*rd.grid.shape):
        r = rd.grid.rank_of_cell(coords)
        pos[r * n_local : (r + 1) * n_local] = (
            np.asarray(coords, np.float32)
            + rng.random((n_local, 3), dtype=np.float32)
        ) / shape
    vel = jnp.asarray(
        (rng.random((R * n_local, 3), dtype=np.float32) - 0.5) * 0.2
    )
    ids = jnp.arange(R * n_local, dtype=jnp.int32)
    count = jnp.full((R,), 3 * n_local // 4, jnp.int32)
    return jnp.asarray(pos), vel, ids, count


def _mk_rd(**kw):
    from mpi_grid_redistribute_tpu import api
    from mpi_grid_redistribute_tpu.domain import ProcessGrid

    base = dict(
        grid=ProcessGrid(_GRID),
        lo=(0.0,) * 3,
        hi=(1.0,) * 3,
        periodic=(True,) * 3,
        engine="auto",
    )
    base.update(kw)
    return api.GridRedistribute(**base)


def test_pipelined_macro_matches_sequential_stats():
    """One chunk=7 macro-step pair on identical inputs: same particle
    set, same counts, same per-step count trajectory, same send_counts
    tables, zero drops on both, and every step's stats.pipeline flag
    set (clean flow: the runtime cond always arms)."""
    rd = _mk_rd()
    pos, vel, ids, count = _template_state(rd, 64)
    seq_macro, _, _ = resident.make_chunk_fn(rd, 0.05, 7, pos, vel, ids)
    pipe_macro, _, _ = pipeline.make_pipelined_chunk_fn(
        rd, 0.05, 7, pos, vel, ids
    )
    assert getattr(pipe_macro.__wrapped__, "_progcheck_pipeline", False)

    (s_pos, s_vel, s_ids, s_count), s_ys = seq_macro(pos, vel, ids, count)
    (p_pos, p_vel, p_ids, p_count), p_ys = pipe_macro(pos, vel, ids, count)

    assert elastic.particle_set(
        np.asarray(p_pos), np.asarray(p_vel),
        np.asarray(p_ids), np.asarray(p_count),
    ) == elastic.particle_set(
        np.asarray(s_pos), np.asarray(s_vel),
        np.asarray(s_ids), np.asarray(s_count),
    )
    assert np.array_equal(np.asarray(p_count), np.asarray(s_count))
    assert np.array_equal(
        np.asarray(p_ys["count"]), np.asarray(s_ys["count"])
    )
    assert np.array_equal(
        np.asarray(p_ys["stats"].send_counts),
        np.asarray(s_ys["stats"].send_counts),
    )
    for leaf in ("dropped_send", "dropped_recv"):
        assert int(np.asarray(getattr(p_ys["stats"], leaf)).sum()) == 0
        assert int(np.asarray(getattr(s_ys["stats"], leaf)).sum()) == 0
    flags = np.asarray(p_ys["stats"].pipeline)
    assert flags.shape[0] == 7 and bool(flags.all())
    assert s_ys["stats"].pipeline is None


# ------------------------------------------- build-time degradation


def test_chunk1_degrades_to_sequential_builder():
    rd = _mk_rd()
    pos, vel, ids, _count = _template_state(rd, 32)
    macro, cap, out_cap = pipeline.make_pipelined_chunk_fn(
        rd, 0.05, 1, pos, vel, ids
    )
    assert getattr(macro.__wrapped__, "_progcheck_resident", False)
    assert not getattr(macro.__wrapped__, "_progcheck_pipeline", False)
    seq_macro, seq_cap, seq_out = resident.make_chunk_fn(
        rd, 0.05, 1, pos, vel, ids
    )
    assert (cap, out_cap) == (seq_cap, seq_out)
    assert "pipeline: chunk < 2 — sequential body" in [
        e.data["reason"] for e in rd.telemetry.events("engine_resolved")
    ]


def test_ragged_capacity_degrades_with_sequential_error():
    """out_capacity != n_local: the degrade resolution journals the
    ragged reason, then the sequential builder it delegated to raises
    its own ResidentLayoutError — bit-exact sequential behavior."""
    rd = _mk_rd(out_capacity=128)
    pos, vel, ids, _count = _template_state(rd, 64)
    with pytest.raises(resident.ResidentLayoutError):
        pipeline.make_pipelined_chunk_fn(rd, 0.05, 4, pos, vel, ids)
    assert "pipeline: ragged receive capacity — sequential body" in [
        e.data["reason"] for e in rd.telemetry.events("engine_resolved")
    ]


def test_multidevice_topology_degrades():
    """An 8-rank grid on the 8 forced host devices resolves the sharded
    mesh path (rd._vranks False) — no single-device completion, so the
    build degrades to the sequential macro."""
    from mpi_grid_redistribute_tpu.domain import ProcessGrid
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
    import jax

    grid = ProcessGrid((2, 2, 2))
    mesh = mesh_lib.make_mesh(grid, jax.devices()[: grid.nranks])
    rd = _mk_rd(grid=grid, mesh=mesh)
    pos, vel, ids, _count = _template_state(rd, 32)
    macro, _, _ = pipeline.make_pipelined_chunk_fn(
        rd, 0.05, 4, pos, vel, ids
    )
    assert not getattr(macro.__wrapped__, "_progcheck_pipeline", False)
    assert "pipeline: multi-device topology — sequential body" in [
        e.data["reason"] for e in rd.telemetry.events("engine_resolved")
    ]


# --------------------------------------------- the overlap, in jaxpr


def test_steady_state_bins_next_step_before_landing():
    """The tentpole's trace property: the scan body's dispatch cond has
    exactly one branch that bins step k+1 (floor) BEFORE step k's
    landing scatter, and a sequential branch that lands first; both
    land with exactly ONE scatter (the free-stack update is fused into
    the landing kernel — no second pass over landing rows) and no
    dynamic_update_slice."""
    import jax

    rd = _mk_rd()
    pos, vel, ids, count = _template_state(rd, 32)
    macro, _, _ = pipeline.make_pipelined_chunk_fn(
        rd, 0.05, 4, pos, vel, ids
    )
    closed = jax.make_jaxpr(macro)(pos, vel, ids, count)
    conds = progcheck.dispatch_conds(
        closed, rules_jaxpr.floor_before_scatter
    )
    assert len(conds) == 1, (
        "expected exactly one pipelined/sequential dispatch cond"
    )
    _eqn, seq_branch, pipe_branch = conds[0]
    for branch in (seq_branch, pipe_branch):
        names = progcheck.primitive_names(branch)
        assert names.count("scatter") == 1, names.count("scatter")
        assert "dynamic_update_slice" not in names
    pipe_names = progcheck.primitive_names(pipe_branch)
    seq_names = progcheck.primitive_names(seq_branch)
    assert pipe_names.index("floor") < pipe_names.index("scatter")
    assert seq_names.index("scatter") < seq_names.index("floor")
    # and the registered program is the same shape end to end: J003
    # green on this exact trace
    spec = progcheck.default_programs()["pipelined_macro_step"]
    assert rules_jaxpr.check_j003(closed, spec) == []
    assert rules_jaxpr.check_j002(closed, spec) == []
