"""progcheck: the semantic jaxpr analyzer (analysis/progcheck.py).

Per-rule coverage: one minimal VIOLATING fixture program and one CLEAN
twin for each of J001-J004, the registry completeness check (J000), the
public walk API the other jaxpr tests import, and the repo-wide gate —
every registered program traces clean under J001-J004 against the
committed profile baseline, mirroring test_gridlint's package gate.

Fixture programs are spiked single-purpose shard_map bodies on a flat
8-device ('x',) mesh: small enough to read, real enough that the traced
jaxpr carries genuine collective primitives.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mpi_grid_redistribute_tpu.compat import shard_map
from mpi_grid_redistribute_tpu.analysis import rules_jaxpr
from mpi_grid_redistribute_tpu.analysis.baseline import (
    load_progprofile_baseline,
    progprofile_baseline_path,
    progprofile_hash,
    write_progprofile_baseline,
)
from mpi_grid_redistribute_tpu.analysis.progcheck import (
    PROGRAMS,
    ProgFinding,
    ProgramSpec,
    aval_bytes,
    default_programs,
    dispatch_conds,
    has_primitive,
    main as progcheck_main,
    primitive_names,
    primitive_set,
    registry_coverage,
    trace_program,
    walk_eqns,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

AXES = ("x",)


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), AXES)


def _spec(name, fn, args, **kw):
    return ProgramSpec(name=name, build=lambda: (fn, args), **kw)


def _trace(fn, *args):
    return jax.make_jaxpr(fn)(*args)


# --------------------------------------------------------- walk API


def test_walk_eqns_recurses_into_scan_and_cond(_devices):
    def f(x):
        def body(c, _):
            c = lax.cond(c[0] > 0, lambda v: v * 2, lambda v: v + 1, c)
            return c, c.sum()

        return lax.scan(body, x, None, length=3)

    closed = _trace(f, jnp.ones((4,), jnp.float32))
    names = primitive_names(closed)
    assert isinstance(names, list)
    assert "scan" in names and "cond" in names
    assert primitive_set(closed) == set(names)
    # the walk accepts closed and open jaxprs alike
    assert primitive_set(closed.jaxpr) == set(names)
    assert sum(1 for _ in walk_eqns(closed)) == len(names)


def test_dispatch_conds_finds_disagreeing_branches(_devices):
    def f(x):
        return lax.cond(
            x[0] > 0,
            lambda v: jnp.sort(v),
            lambda v: v + 1.0,
            x,
        )

    conds = dispatch_conds(
        _trace(f, jnp.ones((8,), jnp.float32)),
        lambda b: has_primitive(b, "sort"),
    )
    assert len(conds) == 1
    _eqn, fast, flagged = conds[0]
    assert not has_primitive(fast, "sort")
    assert has_primitive(flagged, "sort")

    def g(x):  # both branches sort: NOT a dispatch site
        return lax.cond(
            x[0] > 0, lambda v: jnp.sort(v), lambda v: -jnp.sort(v), x
        )

    assert dispatch_conds(
        _trace(g, jnp.ones((8,), jnp.float32)),
        lambda b: has_primitive(b, "sort"),
    ) == []


def test_aval_bytes(_devices):
    closed = _trace(lambda x: x + 1, jnp.zeros((4, 8), jnp.float32))
    assert aval_bytes(closed.jaxpr.invars[0].aval) == 4 * 8 * 4


# ------------------------------------------------ J001: cond schedules


def _mismatched_cond_program(replicated_pred):
    """cond whose branches issue DIFFERENT collective schedules: one
    psum, the other nothing. With a shard-local predicate that is the
    J001 deadlock; guarded by a pmin-agreed scalar it is exactly the
    repo's one-scalar-cond fallback discipline."""
    mesh = _mesh()

    def body(v):
        if replicated_pred:
            ok = lax.pmin((v[0, 0] > 0).astype(jnp.int32), AXES)
            pred = ok == 1
        else:
            pred = v[0, 0] > 0  # each device decides alone
        return lax.cond(
            pred,
            lambda u: lax.psum(u, AXES),
            lambda u: u * 2.0,
            v,
        )

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (jnp.zeros((8, 4), jnp.float32),)


def test_j001_fires_on_mismatched_schedules_local_pred(_devices):
    fn, args = _mismatched_cond_program(replicated_pred=False)
    spec = _spec("spiked_j001", fn, args)
    findings = rules_jaxpr.check_j001(trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J001"]
    assert "mismatched collective schedules" in findings[0].message
    assert "psum" in findings[0].message


def test_j001_clean_with_pmin_agreed_pred(_devices):
    fn, args = _mismatched_cond_program(replicated_pred=True)
    spec = _spec("clean_j001", fn, args)
    assert rules_jaxpr.check_j001(trace_program(spec), spec) == []


def test_j001_clean_when_schedules_match(_devices):
    mesh = _mesh()

    def body(v):
        return lax.cond(  # same collective signature in both branches
            v[0, 0] > 0,
            lambda u: lax.psum(u, AXES),
            lambda u: lax.psum(u * 2.0, AXES),
            v,
        )

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    spec = _spec("matched_j001", f, (jnp.zeros((8, 4), jnp.float32),))
    assert rules_jaxpr.check_j001(trace_program(spec), spec) == []


def test_j001_sees_through_scan_carry(_devices):
    """The replication pass must propagate through a scan carry: a
    pmin-agreed guard computed once and carried into a scanned cond is
    still replicated."""
    mesh = _mesh()

    def body(v):
        ok = lax.pmin((v[0, 0] > 0).astype(jnp.int32), AXES)

        def step(carry, _):
            g, u = carry
            u = lax.cond(
                g == 1,
                lambda w: lax.psum(w, AXES),
                lambda w: w * 2.0,
                u,
            )
            return (g, u), None

        (_, out), _ = lax.scan(step, (ok, v), None, length=2)
        return out

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    spec = _spec("scanned_j001", f, (jnp.zeros((8, 4), jnp.float32),))
    assert rules_jaxpr.check_j001(trace_program(spec), spec) == []


# --------------------------------------------------- J002: residency


def _resident_program(spiked):
    mesh = _mesh()

    def body(v):
        if spiked:
            jax.debug.print("peek {}", v[0, 0])  # host callback
        return lax.psum(v, AXES)

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (jnp.zeros((8, 4), jnp.float32),)


def test_j002_fires_on_debug_print_in_resident_program(_devices):
    fn, args = _resident_program(spiked=True)
    spec = _spec("spiked_j002", fn, args, resident=True)
    findings = rules_jaxpr.check_j002(trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J002"]
    assert "callback" in findings[0].message


def test_j002_clean_without_host_syncs(_devices):
    fn, args = _resident_program(spiked=False)
    spec = _spec("clean_j002", fn, args, resident=True)
    assert rules_jaxpr.check_j002(trace_program(spec), spec) == []


def test_j002_ignores_non_resident_programs(_devices):
    fn, args = _resident_program(spiked=True)
    spec = _spec("nonresident", fn, args, resident=False)
    assert rules_jaxpr.check_j002(trace_program(spec), spec) == []


# ------------------------------------------- J003: fast-path contract


def _pred(v):
    return lax.pmin((v[0, 0] > 0).astype(jnp.int32), AXES) == 1


def _migrate_program(fast_sorts=False, fat_gather=False):
    """Sort-dispatch cond in migrate shape: dense branch sorts, fast
    branch must not. Spiking a sort into the fast branch erases the
    branch disagreement — exactly how a real regression would look."""
    mesh = _mesh()

    def body(v):
        def fast(u):
            if fast_sorts:
                u = jnp.sort(u, axis=0)
            if fat_gather:
                # resident-scale permutation: gathers every row
                u = u[jnp.argsort(u[:, 0]).astype(jnp.int32)[::-1]]
            else:
                u = u.at[:2].set(jnp.take(u, jnp.arange(2), axis=0) + 1)
            return u

        def dense(u):
            return jnp.sort(u, axis=0)

        return lax.cond(_pred(v), fast, dense, v)

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (jnp.zeros((64, 4), jnp.float32),)


def test_j003_migrate_clean(_devices):
    fn, args = _migrate_program()
    spec = _spec(
        "clean_migrate", fn, args, fastpath="migrate", resident_rows=8
    )
    assert rules_jaxpr.check_j003(trace_program(spec), spec) == []


def test_j003_fires_on_spiked_sort_in_fast_branch(_devices):
    fn, args = _migrate_program(fast_sorts=True)
    spec = _spec(
        "spiked_sort", fn, args, fastpath="migrate", resident_rows=8
    )
    findings = rules_jaxpr.check_j003(trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J003"]
    assert "fast path lost" in findings[0].message


def test_j003_fires_on_resident_scale_gather(_devices):
    fn, args = _migrate_program(fat_gather=True)
    spec = _spec(
        "spiked_gather", fn, args, fastpath="migrate", resident_rows=8
    )
    findings = rules_jaxpr.check_j003(trace_program(spec), spec)
    assert findings and all(f.rule == "J003" for f in findings)
    assert any("resident" in f.message for f in findings)


def _wire_program(narrow_cols, wide_cols):
    """Width-dispatch cond in sparse shape: both branches all_to_all,
    at different pool widths."""
    mesh = _mesh()

    def body(v):
        def use(cols):
            def branch(u):
                # per-shard pool [8 destinations, cols]; all_to_all
                # splits the destination axis across the 8 shards
                t = lax.all_to_all(
                    u[:, : 8 * cols].reshape(8, cols), "x", 0, 0
                )
                return jnp.zeros_like(u).at[:, : 8 * cols].set(
                    t.reshape(1, 8 * cols)
                )

            return branch

        return lax.cond(_pred(v), use(narrow_cols), use(wide_cols), v)

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (jnp.zeros((8, 256), jnp.float32),)


def test_j003_sparse_wire_clean(_devices):
    # narrow * cap == wide * B with cap=16, B=4 -> wide = 4 * narrow
    fn, args = _wire_program(narrow_cols=4, wide_cols=16)
    spec = _spec(
        "clean_wire", fn, args, fastpath="sparse_wire",
        capacity=16, mover_cap=4,
    )
    assert rules_jaxpr.check_j003(trace_program(spec), spec) == []


def test_j003_fires_on_broken_pool_width_ratio(_devices):
    fn, args = _wire_program(narrow_cols=8, wide_cols=16)
    spec = _spec(
        "spiked_wire", fn, args, fastpath="sparse_wire",
        capacity=16, mover_cap=4,
    )
    findings = rules_jaxpr.check_j003(trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J003"]
    assert "B/cap contract" in findings[0].message


def _neighbor_program(fast_permutes):
    mesh = _mesh()
    perm = [(i, (i + 1) % 8) for i in range(8)]

    def body(v):
        def fast(u):
            if fast_permutes:
                return lax.ppermute(u, "x", perm)
            return u * 2.0

        def dense(u):
            return lax.all_to_all(
                u.reshape(8, -1), "x", 0, 0
            ).reshape(u.shape)

        return lax.cond(_pred(v), fast, dense, v)

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (jnp.zeros((8, 64), jnp.float32),)


def test_j003_neighbor_clean(_devices):
    fn, args = _neighbor_program(fast_permutes=True)
    spec = _spec("clean_neighbor", fn, args, fastpath="neighbor_wire")
    assert rules_jaxpr.check_j003(trace_program(spec), spec) == []


def test_j003_fires_when_fast_branch_loses_ppermute(_devices):
    fn, args = _neighbor_program(fast_permutes=False)
    spec = _spec("spiked_neighbor", fn, args, fastpath="neighbor_wire")
    findings = rules_jaxpr.check_j003(trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J003"]
    assert "ppermute" in findings[0].message


def test_j003_unknown_fastpath_kind_is_loud(_devices):
    fn, args = _neighbor_program(fast_permutes=True)
    spec = _spec("bad_kind", fn, args, fastpath="nope")
    with pytest.raises(ValueError, match="unknown fastpath"):
        rules_jaxpr.check_j003(trace_program(spec), spec)


# --------------------------------- J004: static wire/footprint drift


def _psum_program(width):
    mesh = _mesh()

    def f(x):
        return shard_map(
            lambda v: lax.psum(v, AXES),
            mesh=mesh, in_specs=P("x"), out_specs=P("x"),
        )(x)

    return f, (jnp.zeros((8, width), jnp.float32),)


def test_profile_counts_collective_bytes_and_scan_trips(_devices):
    fn, args = _psum_program(16)
    prof = rules_jaxpr.program_profile(trace_program(_spec("p", fn, args)))
    # one psum over the full f32[8(/8 shards), 16] operand per shard
    assert prof["collective_bytes"] == {"psum": 1 * 16 * 4}
    assert prof["collective_count"] == 1
    assert prof["collective_bytes_total"] == 64
    assert prof["peak_live_bytes"] >= 8 * 16 * 4

    mesh = _mesh()

    def scanned_f(x):
        def body(v):
            def step(c, _):
                return lax.psum(c, AXES), None

            out, _ = lax.scan(step, v, None, length=5)
            return out

        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    prof5 = rules_jaxpr.program_profile(
        trace_program(_spec("p5", scanned_f, args))
    )
    # scan trip count multiplies the wire: 5 trips x 64 bytes
    assert prof5["collective_bytes_total"] == 5 * 64
    assert prof5["collective_count"] == 5


def test_profile_bills_cond_at_max_bytes_branch(_devices):
    fn, args = _wire_program(narrow_cols=4, wide_cols=16)
    prof = rules_jaxpr.program_profile(trace_program(_spec("c", fn, args)))
    # the cond bills its max-bytes branch: the wide f32[8, 16] pool
    # (512 B), never the narrow f32[8, 4] one (128 B)
    assert prof["collective_bytes"] == {"all_to_all": 8 * 16 * 4, "pmin": 4}


def test_j004_width_perturbation_fails_drift_gate(_devices):
    fn16, a16 = _psum_program(16)
    fn32, a32 = _psum_program(32)
    base = rules_jaxpr.program_profile(trace_program(_spec("w", fn16, a16)))
    wide = rules_jaxpr.program_profile(trace_program(_spec("w", fn32, a32)))

    assert rules_jaxpr.compare_profiles({"w": base}, {"w": base}) == []
    findings = rules_jaxpr.compare_profiles({"w": wide}, {"w": base})
    assert findings and all(f.rule == "J004" for f in findings)
    assert any("collective_bytes_total drifted" in f.message for f in findings)
    assert any("psum" in f.message for f in findings)
    # --update-baseline is the escape hatch: regate against the new
    # profile and the drift is gone
    assert rules_jaxpr.compare_profiles({"w": wide}, {"w": wide}) == []


def test_j004_missing_and_stale_baseline_entries(_devices):
    fn, args = _psum_program(16)
    prof = rules_jaxpr.program_profile(trace_program(_spec("m", fn, args)))
    missing = rules_jaxpr.compare_profiles({"m": prof}, {})
    assert [f.rule for f in missing] == ["J004"]
    assert "no committed profile baseline" in missing[0].message

    stale = rules_jaxpr.compare_profiles(
        {}, {"gone": prof}, check_stale=True
    )
    assert [f.rule for f in stale] == ["J004"]
    assert "stale baseline entry" in stale[0].message
    # a --programs subset run must not read missing names as stale
    assert rules_jaxpr.compare_profiles(
        {}, {"gone": prof}, check_stale=True, partial=True
    ) == []


def test_progprofile_baseline_roundtrip(tmp_path):
    path = str(tmp_path / "prof.json")
    assert load_progprofile_baseline(path) is None
    assert progprofile_hash(path) is None
    profiles = {"a": {"collective_bytes_total": 3}}
    write_progprofile_baseline(path, profiles)
    assert load_progprofile_baseline(path) == profiles
    h = progprofile_hash(path)
    assert isinstance(h, str) and len(h) == 16
    write_progprofile_baseline(path, {"a": {"collective_bytes_total": 4}})
    assert progprofile_hash(path) != h
    (tmp_path / "bad.json").write_text('{"not": "profiles"}')
    with pytest.raises(SystemExit, match="malformed"):
        load_progprofile_baseline(str(tmp_path / "bad.json"))


# ------------------------------------------ J000: registry coverage


def test_registry_is_complete(_devices):
    assert registry_coverage(default_programs()) == []


def test_registry_coverage_catches_missing_engine(_devices):
    programs = {
        n: s
        for n, s in default_programs().items()
        if s.engine != "sparse"
    }
    findings = registry_coverage(programs)
    assert findings and all(f.rule == "J000" for f in findings)
    assert any("'sparse'" in f.message for f in findings)


def test_registry_coverage_catches_missing_resident_tag(_devices):
    programs = {
        n: s
        for n, s in default_programs().items()
        if "resident" not in s.tags
    }
    findings = registry_coverage(programs)
    assert any(
        f.rule == "J000" and "'resident'" in f.message for f in findings
    )


def test_register_program_rejects_duplicates(_devices):
    default_programs()
    name = next(iter(PROGRAMS))
    from mpi_grid_redistribute_tpu.analysis.progcheck import (
        register_program,
    )

    with pytest.raises(ValueError, match="already registered"):
        register_program(PROGRAMS[name])


def test_resident_program_carries_marker(_devices):
    spec = default_programs()["resident_macro_step"]
    assert spec.resident
    fn, _args = spec.build()  # asserts the _progcheck_resident marker
    assert getattr(fn.__wrapped__, "_progcheck_resident", False)


# ------------------------------------------------------ the repo gate


def test_repo_programs_trace_clean_and_match_baseline(_devices, capsys):
    """The tier-1 gate, mirroring test_gridlint's package gate: every
    registered program traces clean under J000-J004 against the
    committed profile baseline."""
    rc = progcheck_main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out


def test_cli_exit_codes_and_json(_devices, capsys, tmp_path):
    assert progcheck_main(["--rules", "J999"]) == 2
    capsys.readouterr()
    assert progcheck_main(["--programs", "nope"]) == 2
    capsys.readouterr()
    assert progcheck_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    assert all(r in listed for r in ("J000", "J001", "J004"))
    assert progcheck_main(["--list-programs"]) == 0
    assert "resident_macro_step" in capsys.readouterr().out

    bl = str(tmp_path / "prof.json")
    rc = progcheck_main(
        [
            "--programs", "canonical_planar_sharded",
            "--baseline", bl,
            "--update-baseline",
        ]
    )
    capsys.readouterr()
    assert rc == 0
    rc = progcheck_main(
        [
            "--programs", "canonical_planar_sharded",
            "--baseline", bl,
            "--format", "json",
        ]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert "canonical_planar_sharded" in out["profiles"]


def test_cli_sarif_and_github_formats(_devices, capsys, tmp_path):
    # an empty baseline file means every program is a J004 finding —
    # a cheap way to exercise the failure formats on one program
    bl = str(tmp_path / "empty.json")
    with open(bl, "w") as fh:
        json.dump({"profiles": {}}, fh)
    rc = progcheck_main(
        [
            "--programs", "canonical_planar_sharded",
            "--baseline", bl,
            "--format", "sarif",
        ]
    )
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1
    results = sarif["runs"][0]["results"]
    assert results and results[0]["ruleId"] == "J004"
    assert "canonical_planar_sharded" in results[0]["message"]["text"]
    rule_ids = {r["id"] for r in sarif["runs"][0]["tool"]["driver"]["rules"]}
    assert {"J000", "J004"} <= rule_ids

    rc = progcheck_main(
        [
            "--programs", "canonical_planar_sharded",
            "--baseline", bl,
            "--format", "github",
        ]
    )
    lines = capsys.readouterr().out.strip().splitlines()
    assert rc == 1
    assert lines and all(l.startswith("::warning ") for l in lines)
    assert any("J004" in l for l in lines)


def test_cli_script_entry_point():
    """scripts/progcheck.py runs standalone (it forces the 8-device
    virtual mesh itself) and exits 0 on the committed baseline."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the wrapper must set the mesh itself
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "progcheck.py"),
            "--check",
        ],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_finding_render_and_dict():
    f = ProgFinding("J001", "prog", "msg")
    assert f.render() == "<prog>: J001: msg"
    d = f.to_dict()
    assert d["rule"] == "J001" and d["program"] == "prog"


def test_check_baseline_clean_on_committed_file(capsys):
    """--check-baseline hygiene mode: every name in the committed
    profiles AND wire_attribution sections is a registered program.
    Pure name check — nothing is traced, so no _devices needed."""
    rc = progcheck_main(["--check-baseline"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "0 stale baseline entr" in out


def test_check_baseline_flags_unregistered_programs(capsys, tmp_path):
    from mpi_grid_redistribute_tpu.analysis.baseline import (
        write_wire_baseline,
    )

    path = str(tmp_path / "prof.json")
    write_progprofile_baseline(
        path,
        {
            "canonical_planar_sharded": {"collective_bytes_total": 1},
            "ghost_profiled": {"collective_bytes_total": 2},
        },
    )
    write_wire_baseline(
        path,
        {
            "ghost_profiled": {"per_axis": {}, "total_bytes": 0},
            "ghost_wired": {"per_axis": {}, "total_bytes": 0},
        },
    )
    rc = progcheck_main(["--check-baseline", "--baseline", path])
    out = capsys.readouterr().out
    assert rc == 1
    assert "2 stale baseline entr" in out
    # each stale name reports WHICH sections still carry it
    assert "ghost_profiled [profiles, wire_attribution]" in out
    assert "ghost_wired [wire_attribution]" in out
    # the registered program is NOT flagged
    assert "canonical_planar_sharded" not in out
