"""Test fixtures: force an 8-device virtual CPU mesh (SURVEY.md §4).

Only one physical TPU chip is visible in this environment, so all
multi-device mesh logic is exercised on XLA's virtual host devices. The
sitecustomize hook force-registers the experimental ``axon`` TPU platform at
interpreter start, but backend selection is lazy — flipping
``jax_platforms`` here (before any computation) wins.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual CPU devices, got {devs}"
    return devs


@pytest.fixture
def rng():
    return np.random.default_rng(1234)
