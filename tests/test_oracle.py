import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu import oracle

DOMAIN = Domain(0.0, 1.0)
GRID = ProcessGrid((2, 2, 2))


def _shards(rng, n_per=500, R=8):
    return [rng.uniform(0, 1, size=(n_per, 3)).astype(np.float32) for _ in range(R)]


def test_oracle_conservation_and_ownership(rng):
    shards = _shards(rng)
    ids = [np.arange(i * 500, (i + 1) * 500, dtype=np.int64) for i in range(8)]
    recv_pos, recv_fields, counts = oracle.redistribute_oracle(
        DOMAIN, GRID, shards, [(i,) for i in ids]
    )
    assert sum(len(p) for p in recv_pos) == 8 * 500
    assert counts.sum() == 8 * 500
    oracle.assert_ownership(DOMAIN, GRID, recv_pos)
    # ids carried through the same permutation: global id set preserved
    all_ids = np.concatenate([f[0] for f in recv_fields])
    np.testing.assert_array_equal(np.sort(all_ids), np.arange(8 * 500))


def test_oracle_alltoallv_receive_order(rng):
    # Receive buffers must be source-major and stable within source.
    shards = _shards(rng, n_per=200)
    src_id = [np.full((200,), s, dtype=np.int32) for s in range(8)]
    row_id = [np.arange(200, dtype=np.int32) for _ in range(8)]
    recv_pos, recv_fields, _ = oracle.redistribute_oracle(
        DOMAIN, GRID, shards, [(s, r) for s, r in zip(src_id, row_id)]
    )
    for d in range(8):
        srcs, rows = recv_fields[d]
        assert (np.diff(srcs) >= 0).all(), "not source-major"
        for s in np.unique(srcs):
            rs = rows[srcs == s]
            assert (np.diff(rs) > 0).all(), "not stable within source"


def test_oracle_idempotent(rng):
    shards = _shards(rng)
    recv1, _, _ = oracle.redistribute_oracle(DOMAIN, GRID, shards)
    recv2, _, _ = oracle.redistribute_oracle(DOMAIN, GRID, recv1)
    for a, b in zip(recv1, recv2):
        np.testing.assert_array_equal(a, b)


def test_oracle_padded_matches_unpadded(rng):
    R, n_local = 8, 300
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    counts = np.full((R,), n_local, dtype=np.int32)
    pos_out, counts_out, _, stats = oracle.redistribute_oracle_padded(
        DOMAIN, GRID, pos, counts, [], capacity=n_local, out_capacity=2 * n_local
    )
    shards = [pos[r * n_local : (r + 1) * n_local] for r in range(R)]
    recv_pos, _, cmat = oracle.redistribute_oracle(DOMAIN, GRID, shards)
    assert stats["dropped_send"].sum() == 0
    assert stats["dropped_recv"].sum() == 0
    np.testing.assert_array_equal(stats["send_counts"], cmat)
    for r in range(R):
        got = pos_out[r * 2 * n_local : r * 2 * n_local + counts_out[r]]
        np.testing.assert_array_equal(got, recv_pos[r])


def test_oracle_padded_capacity_drop_semantics():
    # 2 ranks in x; everything on rank 0 destined to rank 1, capacity 2.
    dom = Domain(0.0, 1.0)
    grid = ProcessGrid((2, 1, 1))
    n_local = 4
    pos = np.zeros((8, 3), dtype=np.float32)
    pos[:4, 0] = [0.9, 0.8, 0.7, 0.6]  # rank 0's rows, all owned by rank 1
    pos[4:, 0] = 0.9                   # rank 1 keeps its own
    pos_out, counts_out, _, stats = oracle.redistribute_oracle_padded(
        dom, grid, pos, np.array([4, 4]), [], capacity=2, out_capacity=8
    )
    assert stats["dropped_send"][0] == 2
    assert stats["dropped_send"][1] == 0  # self-owned rows are never clipped
    assert counts_out[0] == 0
    assert counts_out[1] == 2 + 4
    # first `capacity` rows in stable order survive, source-major
    np.testing.assert_allclose(pos_out[8:10, 0], [0.9, 0.8])
