"""The runnable example (SURVEY.md §3.5, C10) works end-to-end."""

import os
import subprocess
import sys


def test_drift_demo_runs():
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, os.path.join(root, "examples", "drift_demo.py"),
         "--n", "4096", "--steps", "3"],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "every particle is inside its owner's subdomain" in out.stdout
    assert "no particles lost" in out.stdout
