"""ops/pallas_scatter: interpret-mode equivalence with XLA's row scatter.

The Mosaic path needs real TPU hardware; interpret mode validates the
kernel logic (chunking, alignment padding, drop sentinels, block
boundaries) on the CPU test mesh.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from mpi_grid_redistribute_tpu.ops import pallas_scatter as ps


@pytest.mark.parametrize(
    "n_rows,p",
    [
        (ps.BLOCK * 2, 1000),  # sparse
        (ps.BLOCK * 4, 3 * ps.RMAX + 17),  # multiple chunks, odd count
        (ps.BLOCK, 1),  # single arrival
    ],
)
def test_matches_xla_scatter(rng, n_rows, p):
    k = 7
    flat = jnp.asarray(rng.random((n_rows, k)).astype(np.float32))
    # include out-of-range targets: must be dropped
    targets = jnp.asarray(
        rng.choice(n_rows + 99, size=p, replace=False).astype(np.int32)
    )
    rows = jnp.asarray(rng.random((p, k)).astype(np.float32))
    got = np.asarray(ps.scatter_rows(flat, targets, rows, interpret=True))
    want = np.asarray(flat.at[targets].set(rows, mode="drop"))
    np.testing.assert_array_equal(got, want)


def test_clustered_targets_one_block(rng):
    # all arrivals inside one block: exercises the multi-chunk loop
    k = 7
    n_rows = ps.BLOCK * 2
    p = 2 * ps.RMAX
    flat = jnp.asarray(rng.random((n_rows, k)).astype(np.float32))
    targets = jnp.asarray(
        rng.choice(ps.BLOCK, size=p, replace=False).astype(np.int32)
    )
    rows = jnp.asarray(rng.random((p, k)).astype(np.float32))
    got = np.asarray(ps.scatter_rows(flat, targets, rows, interpret=True))
    want = np.asarray(flat.at[targets].set(rows, mode="drop"))
    np.testing.assert_array_equal(got, want)


def test_fallback_on_unaligned_rows(rng):
    k = 7
    n_rows = ps.BLOCK + 8  # not BLOCK-aligned -> XLA fallback
    flat = jnp.asarray(rng.random((n_rows, k)).astype(np.float32))
    targets = jnp.asarray(np.array([3, 9], np.int32))
    rows = jnp.asarray(rng.random((2, k)).astype(np.float32))
    got = np.asarray(ps.scatter_rows(flat, targets, rows))
    want = np.asarray(flat.at[targets].set(rows, mode="drop"))
    np.testing.assert_array_equal(got, want)
