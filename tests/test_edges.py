"""Non-uniform subdomain boundaries (GridEdges — SURVEY.md C1/C2's
"np.digitize / searchsorted on edges" digitize variant).

The compare-sum digitize is shared verbatim (``xp=``) between the NumPy
oracle and the jax engines, so backend bit-compatibility holds by
construction; these tests pin the semantics against an independent
``np.digitize`` reference and drive the whole public API with edges.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu import GridRedistribute, oracle
from mpi_grid_redistribute_tpu.domain import Domain, GridEdges, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning


@pytest.fixture
def rng():
    return np.random.default_rng(42)


def test_edges_validation():
    d = Domain(0.0, 1.0, periodic=True)
    g = ProcessGrid((2, 2, 2))
    GridEdges([(0.0, 0.25, 1.0)] * 3).validate_against(d, g)
    with pytest.raises(ValueError, match="strictly increasing"):
        GridEdges([(0.0, 0.5, 0.5)] * 3)
    with pytest.raises(ValueError, match="need >= 2"):
        GridEdges([(0.0,), (0.0, 1.0), (0.0, 1.0)])
    with pytest.raises(ValueError, match="shape\\+1"):
        GridEdges([(0.0, 0.2, 0.4, 1.0)] * 3).validate_against(d, g)
    with pytest.raises(ValueError, match="span"):
        GridEdges([(0.1, 0.5, 1.0)] * 3).validate_against(d, g)
    with pytest.raises(ValueError, match="ndim"):
        GridEdges([(0.0, 0.5, 1.0)] * 2).validate_against(d, g)


def test_cell_of_position_matches_digitize(rng):
    d = Domain(0.0, 1.0, ndim=2)
    g = ProcessGrid((4, 3))
    e = GridEdges([(0.0, 0.1, 0.2, 0.7, 1.0), (0.0, 0.55, 0.9, 1.0)])
    e.validate_against(d, g)
    pos = rng.random((5000, 2)).astype(np.float32)
    # include exact boundary hits and out-of-box values
    pos[:8, 0] = [0.0, 0.1, 0.2, 0.7, 1.0, -0.5, 1.5, 0.69999]
    got_np = binning.cell_of_position(pos, d, g, xp=np, edges=e)
    got_jx = np.asarray(
        binning.cell_of_position(jnp.asarray(pos), d, g, edges=e)
    )
    assert np.array_equal(got_np, got_jx)
    for a, ax_edges in enumerate(e.edges):
        ref = np.clip(
            np.digitize(pos[:, a], np.asarray(ax_edges[1:-1], np.float32)),
            0,
            g.shape[a] - 1,
        )
        assert np.array_equal(got_np[:, a], ref), a


def test_planar_cell_twin_matches(rng):
    d = Domain(0.0, 1.0, periodic=True)
    g = ProcessGrid((3, 2, 2))
    e = GridEdges(
        [
            (0.0, 0.2, 0.8, 1.0),
            (0.0, 0.6, 1.0),
            (0.0, 0.35, 1.0),
        ]
    )
    e.validate_against(d, g)
    pos = rng.random((4, 3, 257)).astype(np.float32)
    planar = np.asarray(
        binning.rank_of_position_planar(jnp.asarray(pos), d, g, edges=e)
    )
    rows = binning.rank_of_position(
        pos.transpose(0, 2, 1).reshape(-1, 3), d, g, xp=np, edges=e
    ).reshape(4, 257)
    assert np.array_equal(planar, rows)


@pytest.mark.parametrize("engine", ["planar", "rowmajor"])
def test_api_edges_backend_bit_equality(rng, engine, _devices):
    d = Domain(0.0, 1.0, periodic=True)
    g = (2, 2, 2)
    e = GridEdges([(0.0, 0.7, 1.0), (0.0, 0.12, 1.0), (0.0, 0.5, 1.0)])
    n_local = 256
    total = 8 * n_local
    pos = rng.random((total, 3)).astype(np.float32)
    ids = np.arange(total, dtype=np.int32)
    out_cap = 4 * n_local
    kw = dict(capacity_factor=16.0, out_capacity=out_cap, edges=e,
              engine=engine)
    r_jax = GridRedistribute(d, g, **kw).redistribute(pos, ids)
    r_np = GridRedistribute(d, g, backend="numpy", **kw).redistribute(
        pos, ids
    )
    assert np.asarray(r_jax.positions).tobytes() == np.asarray(
        r_np.positions
    ).tobytes()
    assert np.asarray(r_jax.count).tobytes() == np.asarray(
        r_np.count
    ).tobytes()
    for a, b in zip(r_jax.fields, r_np.fields):
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
    # conservation + non-uniform ownership
    cnt = np.asarray(r_jax.count)
    assert cnt.sum() == total
    shards = [
        np.asarray(r_jax.positions)[r * out_cap : r * out_cap + cnt[r]]
        for r in range(8)
    ]
    oracle.assert_ownership(d, ProcessGrid(g), shards, edges=e)
    # the hot corner cell (0.7, 0.12, 0.5 lower splits) must own the
    # plurality — sanity that the edges actually moved ownership
    grid = ProcessGrid(g)
    widths = [
        (0.7, 0.3), (0.12, 0.88), (0.5, 0.5),
    ]
    vol = np.array(
        [
            widths[0][i] * widths[1][j] * widths[2][k]
            for i in range(2)
            for j in range(2)
            for k in range(2)
        ]
    )
    frac = cnt / cnt.sum()
    assert np.allclose(frac, vol, atol=0.05)


def test_balanced_for_equalizes_load(rng):
    d = Domain(0.0, 1.0, periodic=True)
    g = ProcessGrid((4, 4, 1))
    # clustered sample: uniform cells would be ~7x imbalanced
    pos = (rng.lognormal(-1.0, 1.0, size=(200_000, 3)) % 1.0).astype(
        np.float32
    )
    e = GridEdges.balanced_for(d, g, pos)
    e.validate_against(d, g)
    ranks = binning.rank_of_position(pos, d, g, xp=np, edges=e)
    counts = np.bincount(ranks, minlength=g.nranks)
    bal = counts.max() / counts.mean()
    ranks_u = binning.rank_of_position(pos, d, g, xp=np)
    counts_u = np.bincount(ranks_u, minlength=g.nranks)
    unbal = counts_u.max() / counts_u.mean()
    # per-axis quantiles cannot perfectly balance a product grid on
    # correlated data, but must beat uniform cells decisively
    assert unbal > 2.0  # the workload is genuinely imbalanced
    assert bal < 0.5 * unbal
    assert bal < 1.8


def test_subdomain_of_rank_edges():
    d = Domain(0.0, 1.0, periodic=True)
    g = ProcessGrid((2, 1, 2))
    e = GridEdges([(0.0, 0.7, 1.0), (0.0, 1.0), (0.0, 0.25, 1.0)])
    e.validate_against(d, g)
    lo, hi = e.subdomain_of_rank(g.rank_of_cell((1, 0, 0)), g)
    assert lo == (0.7, 0.0, 0.0) and hi == (1.0, 1.0, 0.25)


def test_edges_nan_rejected():
    with pytest.raises(ValueError, match="NaN"):
        GridEdges([(0.0, float("nan"), 0.5, 1.0)])


def test_balanced_for_wraps_drifted_sample(rng):
    d = Domain(0.0, 1.0, periodic=True)
    g = ProcessGrid((4, 1, 1))
    base = rng.random((50_000, 3)).astype(np.float32)
    drifted = base + np.float32(1.0)  # every row past hi — legal input
    e = GridEdges.balanced_for(d, g, drifted)
    e.validate_against(d, g)
    ranks = binning.rank_of_position(base, d, g, xp=np, edges=e)
    counts = np.bincount(ranks, minlength=g.nranks)
    assert counts.max() / counts.mean() < 1.1


def test_balanced_for_clips_nonperiodic_sample(rng):
    d = Domain(0.0, 1.0, periodic=False)
    g = ProcessGrid((4, 1, 1))
    drifted = rng.random((50_000, 3)).astype(np.float32)
    # a third of the rows drift past hi on a clamped axis — legal input
    # (the engine clamps them into the last cell); without the sample
    # clip these quantiles landed above hi and raised "too degenerate"
    past = rng.random(50_000) < 0.34
    drifted[past, 0] += np.float32(1.0)
    e = GridEdges.balanced_for(d, g, drifted)  # must not raise
    e.validate_against(d, g)
    # a fully-clamped axis (point mass at hi) still yields VALID edges —
    # balance is impossible, so the near-empty slabs are best-effort,
    # matching what mid-domain point masses already got
    allpast = drifted.copy()
    allpast[:, 0] = 1.5
    e2 = GridEdges.balanced_for(d, g, allpast)
    e2.validate_against(d, g)
    ranks = binning.rank_of_position(
        np.clip(allpast, 0.0, 1.0), d, g, xp=np, edges=e2
    )
    assert (ranks == g.rank_of_cell((3, 0, 0))).all()


def test_api_coerces_raw_edges_and_balanced_for_validates_shape(rng):
    d = Domain(0.0, 1.0, periodic=True)
    rd = GridRedistribute(
        d, (2, 2, 2), backend="numpy",
        edges=[(0.0, 0.5, 1.0)] * 3,  # raw sequence, like grid=(2,2,2)
    )
    assert isinstance(rd.edges, GridEdges)
    with pytest.raises(ValueError, match=r"\[N, 3\]"):
        GridEdges.balanced_for(
            d, ProcessGrid((2, 2, 2)), rng.random((100, 2))
        )


def test_balanced_for_roundtrip_containment(rng):
    """Round-trip: bin with the balanced edges, then check every row
    actually lies inside its assigned rank's edge-slab subdomain (after
    the engine's periodic wrap) — the containment half of the
    edges<->binning contract."""
    d = Domain(0.0, 1.0, periodic=True)
    g = ProcessGrid((3, 2, 2))
    pos = (rng.random((20_000, 3)) ** 2).astype(np.float32)  # skewed
    e = GridEdges.balanced_for(d, g, pos)
    wrapped = np.remainder(pos, np.float32(1.0))
    ranks = binning.rank_of_position(pos, d, g, xp=np, edges=e)
    for r in range(g.nranks):
        rows = wrapped[ranks == r]
        assert rows.size, f"rank {r} got no rows from a balanced map"
        lo, hi = e.subdomain_of_rank(r, g)
        for a in range(3):
            assert (rows[:, a] >= np.float32(lo[a])).all()
            # the last slab owns its closing edge (clip semantics)
            if hi[a] < d.hi[a]:
                assert (rows[:, a] < np.float32(hi[a])).all()
            else:
                assert (rows[:, a] <= np.float32(hi[a])).all()


def test_balanced_for_rank_of_cell_consistency(rng):
    """The other half of the round-trip: a probe at each edge-cell's
    center must bin to exactly ``grid.rank_of_cell(cell)`` — the edge
    slabs and the Cartesian rank map name the same owners."""
    d = Domain(0.0, 1.0, periodic=True)
    g = ProcessGrid((2, 3, 2))
    e = GridEdges.balanced_for(
        d, g, (rng.random((30_000, 3)) ** 1.5).astype(np.float32)
    )
    cells = np.stack(
        np.meshgrid(*[np.arange(s) for s in g.shape], indexing="ij"),
        axis=-1,
    ).reshape(-1, 3)
    centers = np.empty((len(cells), 3), np.float32)
    for a in range(3):
        ax = np.asarray(e.edges[a], np.float64)
        mid = (ax[:-1] + ax[1:]) / 2.0
        centers[:, a] = mid[cells[:, a]]
    got = binning.rank_of_position(centers, d, g, xp=np, edges=e)
    want = np.asarray([g.rank_of_cell(tuple(c)) for c in cells], np.int32)
    assert np.array_equal(got, want)


def test_uniform_axes_detection():
    lin = tuple(float(v) for v in np.linspace(0.0, 1.0, 9))
    quant = (0.0, 0.1, 0.3, 0.35, 0.5, 0.62, 0.8, 0.9, 1.0)
    e = GridEdges((lin, quant))
    assert e.uniform_axes == (True, False)
    # two-edge axes (one cell) are trivially uniform
    assert GridEdges(((0.0, 1.0),)).uniform_axes == (True,)


def test_uniform_fast_path_matches_digitize(rng):
    """The floor-multiply fast path on exactly-linspace axes must be
    bit-identical to the compare-sum digitize it replaces, on both
    backends, including boundary hits."""
    d = Domain(0.0, 1.0, ndim=2, periodic=True)
    g = ProcessGrid((4, 4))
    lin = tuple(float(v) for v in np.linspace(0.0, 1.0, 5))
    uni = GridEdges((lin, lin))
    assert uni.uniform_axes == (True, True)
    # same VALUES but hand-typed: a non-linspace tuple of the same
    # floats must still take SOME correct path
    pos = rng.random((8192, 2)).astype(np.float32)
    pos[:6, 0] = [0.0, 0.25, 0.5, 0.75, 1.0, 0.249999]
    got_fast = binning.cell_of_position(pos, d, g, xp=np, edges=uni)
    got_jx = np.asarray(
        binning.cell_of_position(jnp.asarray(pos), d, g, edges=uni)
    )
    assert np.array_equal(got_fast, got_jx)
    # reference: force the digitize path by hiding uniform_axes
    ref = np.stack(
        [
            np.clip(
                np.digitize(pos[:, a], np.asarray(lin[1:-1], np.float32)),
                0, 3,
            )
            for a in range(2)
        ],
        axis=-1,
    ).astype(np.int32)
    assert np.array_equal(got_fast, ref)
