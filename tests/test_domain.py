import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid


def test_domain_scalar_broadcast():
    d = Domain(0.0, 1.0)
    assert d.ndim == 3
    assert d.lo == (0.0, 0.0, 0.0)
    assert d.hi == (1.0, 1.0, 1.0)
    assert d.periodic == (False, False, False)


def test_domain_validation():
    with pytest.raises(ValueError):
        Domain((0, 0), (1, -1))
    with pytest.raises(ValueError):
        Domain((0, 0, 0), (1, 1, 1), periodic=(True,))


def test_rank_cell_roundtrip():
    g = ProcessGrid((2, 3, 4))
    assert g.nranks == 24
    seen = set()
    for r in range(g.nranks):
        cell = g.cell_of_rank(r)
        assert g.rank_of_cell(cell) == r
        seen.add(cell)
    assert len(seen) == 24
    # row-major: last axis fastest
    assert g.rank_of_cell((0, 0, 1)) == 1
    assert g.rank_of_cell((0, 1, 0)) == 4
    assert g.rank_of_cell((1, 0, 0)) == 12


def test_slab_grid_with_unit_axis():
    g = ProcessGrid((4, 2, 1))
    assert g.nranks == 8
    assert g.cell_of_rank(7) == (3, 1, 0)


def test_subdomain_bounds():
    d = Domain((0.0, 0.0, 0.0), (8.0, 4.0, 2.0))
    g = ProcessGrid((4, 2, 1))
    lo, hi = g.subdomain_of_rank(0, d)
    assert lo == (0.0, 0.0, 0.0) and hi == (2.0, 2.0, 2.0)
    lo, hi = g.subdomain_of_rank(7, d)
    assert lo == (6.0, 2.0, 0.0) and hi == (8.0, 4.0, 2.0)


def test_neighbor_rank_periodic_and_edge():
    g = ProcessGrid((2, 2, 2))
    assert g.neighbor_rank(0, axis=0, step=1, periodic=False) == 4
    assert g.neighbor_rank(4, axis=0, step=1, periodic=False) == -1
    assert g.neighbor_rank(4, axis=0, step=1, periodic=True) == 0
    assert g.neighbor_rank(0, axis=2, step=-1, periodic=True) == 1


def test_grid_domain_ndim_mismatch():
    with pytest.raises(ValueError):
        ProcessGrid((2, 2)).validate_against(Domain(0.0, 1.0))


def test_make_hybrid_mesh_single_slice(_devices):
    """All-ones dcn_shape: bandwidth-aware single-slice mesh."""
    from mpi_grid_redistribute_tpu.domain import ProcessGrid
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    grid = ProcessGrid((2, 2, 2))
    mesh = mesh_lib.make_hybrid_mesh(grid)
    assert tuple(mesh.devices.shape) == (2, 2, 2)
    mesh_lib.validate_mesh_for_grid(mesh, grid)
    import pytest

    with pytest.raises(ValueError, match="divisible"):
        mesh_lib.make_hybrid_mesh(grid, dcn_shape=(3, 1, 1))
