"""utils/: checkpoint round-trips, stats summaries, scan timing."""

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.utils import checkpoint, profiling, stats


def test_checkpoint_roundtrip(tmp_path, rng):
    R, n_local = 4, 16
    arrays = {
        "pos": rng.random((R * n_local, 3)).astype(np.float32),
        "ids": np.arange(R * n_local, dtype=np.int64),
        "count": np.full((R,), n_local, dtype=np.int32),
    }
    checkpoint.save(str(tmp_path / "ck"), arrays, R, step=7,
                    extra={"dt": 0.05})
    back, manifest = checkpoint.load(str(tmp_path / "ck"))
    assert manifest["step"] == 7
    assert manifest["extra"]["dt"] == 0.05
    for k in arrays:
        np.testing.assert_array_equal(back[k], arrays[k])


def test_checkpoint_partial_ranks(tmp_path, rng):
    R, n_local = 4, 8
    pos = rng.random((R * n_local, 3)).astype(np.float32)
    checkpoint.save(str(tmp_path / "ck"), {"pos": pos}, R)
    back, _ = checkpoint.load(str(tmp_path / "ck"), ranks=[2, 0])
    np.testing.assert_array_equal(
        back["pos"],
        np.concatenate([pos[2 * n_local : 3 * n_local], pos[:n_local]]),
    )


def test_checkpoint_per_shard_is_by_name_not_shape(tmp_path, rng):
    # A genuine global 1-D array with exactly nranks rows (n_local=1) must
    # shard normally; only names listed in per_shard are per-shard scalars.
    R = 4
    arrays = {
        "pos": rng.random((R, 3)).astype(np.float32),  # n_local = 1
        "ids": np.arange(R, dtype=np.int64),  # global, happens to be [R]
        "count": np.ones((R,), dtype=np.int32),
    }
    checkpoint.save(str(tmp_path / "ck"), arrays, R)
    back, manifest = checkpoint.load(str(tmp_path / "ck"))
    assert manifest["per_shard"] == ["count"]
    assert manifest["rows_per_shard"] == 1
    for k in arrays:
        np.testing.assert_array_equal(back[k], arrays[k])
    # wrong-shaped per-shard array is an error, not silently sharded
    with pytest.raises(ValueError, match="per-shard"):
        checkpoint.save(
            str(tmp_path / "ck2"),
            {"pos": arrays["pos"], "count": np.ones((R, 2), np.int32)},
            R,
        )


def test_checkpoint_rejects_ragged(tmp_path, rng):
    with pytest.raises(ValueError, match="divide"):
        checkpoint.save(
            str(tmp_path / "ck"),
            {"pos": np.zeros((10, 3), np.float32)}, 4,
        )


def _save_small(path, rng, R=4, n_local=8, step=0):
    arrays = {
        "pos": rng.random((R * n_local, 3)).astype(np.float32),
        "count": np.full((R,), n_local, dtype=np.int32),
    }
    checkpoint.save(str(path), arrays, R, step=step)
    return arrays


def test_checkpoint_truncated_shard_names_the_shard(tmp_path, rng):
    _save_small(tmp_path / "ck", rng)
    shard = tmp_path / "ck" / "shard_00002.npz"
    raw = shard.read_bytes()
    shard.write_bytes(raw[: len(raw) // 2])  # torn write
    with pytest.raises(checkpoint.CheckpointCorruptError) as ei:
        checkpoint.load(str(tmp_path / "ck"))
    assert ei.value.shard == "shard_00002.npz"


def test_checkpoint_bitflip_fails_checksum(tmp_path, rng):
    _save_small(tmp_path / "ck", rng)
    shard = tmp_path / "ck" / "shard_00001.npz"
    raw = bytearray(shard.read_bytes())
    raw[len(raw) // 2] ^= 0xFF  # single flipped byte, zip may still open
    shard.write_bytes(bytes(raw))
    with pytest.raises(checkpoint.CheckpointCorruptError, match="sha256"):
        checkpoint.load(str(tmp_path / "ck"))


def test_checkpoint_broken_manifest(tmp_path, rng):
    _save_small(tmp_path / "ck", rng)
    (tmp_path / "ck" / "manifest.json").write_text("{not json")
    with pytest.raises(checkpoint.CheckpointCorruptError) as ei:
        checkpoint.load(str(tmp_path / "ck"))
    assert ei.value.shard == "manifest.json"


def test_load_latest_skips_corrupt_newest(tmp_path, rng):
    root = tmp_path / "snaps"
    good = _save_small(root / "step_00000004", rng, step=4)
    _save_small(root / "step_00000008", rng, step=8)
    # tear the newest snapshot's first shard: restore must fall back to
    # step 4 and report exactly one skipped snapshot
    bad = root / "step_00000008" / "shard_00000.npz"
    bad.write_bytes(bad.read_bytes()[:16])
    latest = checkpoint.load_latest(str(root))
    assert latest is not None
    assert latest.manifest["step"] == 4
    assert latest.skipped == 1
    np.testing.assert_array_equal(latest.arrays["pos"], good["pos"])


def test_load_latest_none_when_all_invalid(tmp_path, rng):
    root = tmp_path / "snaps"
    _save_small(root / "step_00000002", rng, step=2)
    (root / "step_00000002" / "manifest.json").unlink()
    assert checkpoint.load_latest(str(root)) is None
    assert checkpoint.load_latest(str(tmp_path / "missing")) is None


def test_list_snapshots_excludes_staging_dirs(tmp_path, rng):
    root = tmp_path / "snaps"
    _save_small(root / "step_00000002", rng, step=2)
    _save_small(root / "step_00000006", rng, step=6)
    # leftovers from a crashed mid-write and a retired rename
    (root / "step_00000009.tmp-123").mkdir()
    (root / "step_00000004.old-123").mkdir()
    snaps = checkpoint.list_snapshots(str(root))
    assert [s.rsplit("/", 1)[-1] for s in snaps] == [
        "step_00000006", "step_00000002",
    ]


def test_checkpoint_elastic_restore(tmp_path, rng):
    # the same global state saved at R, 2R, and R/2 shards must all load
    # back to identical global rows — resume on a different device count
    R, n_local = 4, 16
    pos = rng.random((R * n_local, 3)).astype(np.float32)
    vel = rng.random((R * n_local, 3)).astype(np.float32)
    for nranks in (R, 2 * R, R // 2):
        d = tmp_path / f"ck_{nranks}"
        checkpoint.save(
            str(d),
            {"pos": pos, "vel": vel,
             "count": np.full((nranks,), R * n_local // nranks, np.int32)},
            nranks,
        )
        back, manifest = checkpoint.load(str(d))
        assert manifest["nranks"] == nranks
        np.testing.assert_array_equal(back["pos"], pos)
        np.testing.assert_array_equal(back["vel"], vel)


def test_summarize_migrate_and_loss_check():
    from mpi_grid_redistribute_tpu.parallel.migrate import MigrateStats

    S, R = 3, 8
    st = MigrateStats(
        sent=np.full((S, R), 10, np.int32),
        received=np.full((S, R), 10, np.int32),
        population=np.full((S, R), 1000, np.int32),
        backlog=np.zeros((S, R), np.int32),
        dropped_recv=np.zeros((S, R), np.int32),
    )
    s = stats.summarize_migrate(st)
    assert s["sent_per_step"] == 80.0
    assert abs(s["migration_fraction"] - 0.01) < 1e-9
    assert s["population_imbalance"] == 1.0
    stats.check_no_loss(st)  # no raise
    bad = st._replace(dropped_recv=np.ones((S, R), np.int32))
    with pytest.raises(RuntimeError, match="dropped_recv"):
        stats.check_no_loss(bad)


def test_summarize_redistribute():
    from mpi_grid_redistribute_tpu.parallel.exchange import RedistributeStats

    R = 4
    send = np.zeros((1, R, R), np.int32)
    send[0, 0, 1] = 5
    send[0] += np.eye(R, dtype=np.int32) * 10  # self rows
    st = RedistributeStats(
        send_counts=send,
        recv_counts=np.transpose(send, (0, 2, 1)),
        dropped_send=np.zeros((R,), np.int32),
        dropped_recv=np.zeros((R,), np.int32),
        needed_capacity=np.full((R,), 5, np.int32),
    )
    s = stats.summarize_redistribute(st)
    assert s["moved_rows"] == 5.0
    assert s["dropped_send"] == 0


def test_scan_time_per_step_smoke(_devices):
    import jax
    import jax.numpy as jnp

    def make_loop(S):
        @jax.jit
        def loop(x):
            def body(c, _):
                return c * 1.0000001 + 1e-9, None
            out, _ = jax.lax.scan(body, x, None, length=S)
            return out
        return loop

    per, overhead, out = profiling.scan_time_per_step(
        make_loop, (jnp.ones((1024,)),), s1=2, s2=16, reps=1
    )
    assert per >= 0.0 or abs(per) < 1e-3  # tiny op: just don't blow up
    assert np.isfinite(overhead)
    assert out.shape == (1024,)  # long loop's output is returned


def test_exchange_bytes_per_step():
    from mpi_grid_redistribute_tpu.parallel.migrate import MigrateStats

    st = MigrateStats(
        sent=np.full((2, 8), 100, np.int32),
        received=np.full((2, 8), 100, np.int32),
        population=np.full((2, 8), 1000, np.int32),
        backlog=np.zeros((2, 8), np.int32),
        dropped_recv=np.zeros((2, 8), np.int32),
    )
    assert profiling.exchange_bytes_per_step(st, 28) == 800 * 28


def test_exchange_bw_util():
    # hbm domain: fraction of the 819 GB/s v5e HBM roof
    util = profiling.exchange_bw_util(819e9 / 2, "hbm")
    assert abs(util - 0.5) < 1e-12
    # ici domain: per-chip aggregate vs 4 summed 45 GB/s links
    peak = profiling.exchange_peak_bytes_per_sec("ici")
    assert peak == 4 * 45e9
    util = profiling.exchange_bw_util(8 * peak * 0.25, "ici", n_chips=8)
    assert abs(util - 0.25) < 1e-12
    with pytest.raises(ValueError):
        profiling.exchange_peak_bytes_per_sec("dcn")


def test_detect_stall():
    from mpi_grid_redistribute_tpu.parallel.migrate import MigrateStats

    def mk(backlogs):
        S = len(backlogs)
        z = np.zeros((S, 4), np.int32)
        b = np.zeros((S, 4), np.int32)
        b[:, 0] = backlogs
        return MigrateStats(sent=z, received=z, population=z, backlog=b,
                            dropped_recv=z)

    # constant nonzero backlog over the window -> stall (and never drains)
    r = stats.detect_stall(mk([0, 0, 3, 3, 3, 3]), window=4)
    assert r["stalled"] == 1.0 and r["backlog_final"] == 3
    assert r["never_drains"] == 1.0
    # draining backlog -> no stall
    r = stats.detect_stall(mk([5, 4, 3, 2, 1, 0]), window=4)
    assert r["stalled"] == 0.0 and r["never_drains"] == 0.0
    # zero backlog -> no stall
    r = stats.detect_stall(mk([0] * 6), window=4)
    assert r["stalled"] == 0.0 and r["never_drains"] == 0.0
    # too-short history -> not flagged
    r = stats.detect_stall(mk([7, 7]), window=4)
    assert r["stalled"] == 0.0 and r["never_drains"] == 0.0
    # OSCILLATING livelock (round-3 verdict weak item 4): backlog
    # alternates 5<->6 and never drains — 'stalled' (constant) misses it
    # by design, 'never_drains' catches it
    r = stats.detect_stall(mk([0, 5, 6, 5, 6, 5]), window=4)
    assert r["stalled"] == 0.0
    assert r["never_drains"] == 1.0
    assert r["backlog_min"] == 5 and r["backlog_max"] == 6


def test_rescue_disabled_above_128_ranks_warns():
    """round-3 verdict weak item 5: the flat engine silently disabled
    cycle rescue above 128 ranks; callers must get a runtime signal that
    the liveness guarantee changed."""
    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.parallel import migrate

    dom = Domain(0.0, 1.0, periodic=True)
    with pytest.warns(UserWarning, match="cycle_rescue disabled"):
        migrate.shard_migrate_fused_fn(dom, ProcessGrid((144, 1, 1)), 8)
    # explicit opt-out stays silent
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        migrate.shard_migrate_fused_fn(
            dom, ProcessGrid((144, 1, 1)), 8, cycle_rescue=False
        )
        # and small grids with rescue on stay silent too
        migrate.shard_migrate_fused_fn(dom, ProcessGrid((2, 2, 2)), 8)


def test_checkpoint_mid_drift_resume_bitlevel(tmp_path, rng, _devices):
    """Save the drift loop's planar state mid-run, reload, continue — the
    resumed run carries the SAME per-shard particle multiset, bit-level,
    as the uninterrupted one (slot ORDER may differ: resume rebuilds the
    free-slot stacks from the alive mask, and the migrate engine's
    contract is multiset equality, not slot order — migrate.py module
    docs; checkpoint is lossless npz, SURVEY.md §5.4)."""
    import jax
    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    grid = ProcessGrid((2, 2, 2))
    R = grid.nranks
    n_local = 128
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=Domain(0.0, 1.0, periodic=True), grid=grid, dt=0.02,
        capacity=32, n_local=n_local,
    )
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    vel = ((rng.random((R * n_local, 3)) - 0.5) * 0.1).astype(np.float32)
    alive = rng.random(R * n_local) > 0.1

    loop6 = nbody.make_migrate_loop(cfg, mesh, 6)
    p6, v6, a6, _ = jax.tree.map(np.asarray, loop6(pos, vel, alive))

    loop3 = nbody.make_migrate_loop(cfg, mesh, 3)
    p3, v3, a3, _ = jax.tree.map(np.asarray, loop3(pos, vel, alive))
    checkpoint.save(
        str(tmp_path / "mid"),
        {"pos": p3.reshape(R, -1), "vel": v3.reshape(R, -1),
         "alive": a3.reshape(R, -1)},
        R, step=3,
    )
    back, manifest = checkpoint.load(str(tmp_path / "mid"))
    assert manifest["step"] == 3
    pr, vr, ar, _ = jax.tree.map(
        np.asarray,
        loop3(back["pos"].reshape(-1), back["vel"].reshape(-1),
              back["alive"].reshape(-1).astype(bool)),
    )
    def shard_rows(p, v, a, r):
        # planar flat [3*R*n] -> this shard's LIVE [rows, 6] uint32
        pm = nbody.planar_to_rows(p, 3, R).reshape(R, n_local, 3)
        vm = nbody.planar_to_rows(v, 3, R).reshape(R, n_local, 3)
        am = a.reshape(R, n_local)
        rows = np.concatenate([pm[r], vm[r]], axis=1).view(np.uint32)
        rows = rows[am[r]]
        return rows[np.lexsort(rows.T[::-1])]

    for r in range(R):
        np.testing.assert_array_equal(
            shard_rows(pr, vr, ar, r), shard_rows(p6, v6, a6, r)
        )
