"""Closed-loop adaptive rebalancing (ISSUE 9): planner, amortization
guard, one-shot actuation, and the driver's ALERT -> plan -> guard ->
apply wiring.

The guard is exercised BOTH WAYS under scripted gauges (fires when the
projected saving clears the measured cost; declines below the
improvement floor / horizon; cooldown blocks back-to-back remaps), and
the full service loop is proven bit-identical: a rebalance only moves
ownership, never particles (``elastic.particle_set``).
"""

import numpy as np
import pytest

from mpi_grid_redistribute_tpu import GridRedistribute
from mpi_grid_redistribute_tpu.domain import Domain, GridEdges, ProcessGrid
from mpi_grid_redistribute_tpu.service import elastic
from mpi_grid_redistribute_tpu.service.driver import (
    DriverConfig,
    ServiceDriver,
)
from mpi_grid_redistribute_tpu.telemetry.rebalance import (
    AmortizationGuard,
    RebalancePlan,
    RebalancePlanner,
)


@pytest.fixture
def rng():
    return np.random.default_rng(9)


DOM = Domain(0.0, 1.0, periodic=True)
GRID = ProcessGrid((2, 2, 2))
R = GRID.nranks


def _skewed_state(rng, n_local=256, hot_frac=0.9):
    """Padded global layout with ~hot_frac of all live rows crammed into
    one octant (rank 0's subdomain) — a stale decomposition."""
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    hot = rng.random(R * n_local) < hot_frac
    pos[hot] = (pos[hot] * 0.5).astype(np.float32)  # into [0, 0.5)^3
    count = np.full(R, n_local // 2, np.int32)
    return pos, count


# ---------------------------------------------------------------- planner


def test_planner_occupancy_hand_math():
    # 4 live rows, hand-placed: three in fine cell (0,0,0), one in the
    # last fine cell — factor-1 planning (fine grid == rank grid)
    p = RebalancePlanner(DOM, GRID, cells_per_rank_axis=1)
    pos = np.zeros((R * 2, 3), np.float32)
    pos[0] = [0.1, 0.1, 0.1]
    pos[1] = [0.2, 0.2, 0.2]
    pos[2] = [0.3, 0.3, 0.3]  # rank 1's first live row
    pos[3] = [0.9, 0.9, 0.9]
    count = np.zeros(R, np.int32)
    count[0] = 2
    count[1] = 2
    loads = p.occupancy(pos, count=count)
    assert loads.sum() == 4
    assert loads[0] == 3 and loads[-1] == 1
    assert (loads[1:-1] == 0).all()


def test_planner_plan_lowers_projected_imbalance(rng):
    pos, count = _skewed_state(rng)
    p = RebalancePlanner(DOM, GRID, cells_per_rank_axis=4)
    plan = p.plan(pos, count=count)
    assert isinstance(plan, RebalancePlan)
    # the measured counts are uniform (old = 1.0 is the COUNT gauge) but
    # the LPT projection must be near-balanced over the skewed occupancy
    assert plan.projected_imbalance < 1.1
    assert plan.n_cells == 8 ** 3
    assert 0 < plan.occupied_cells <= plan.n_cells
    e = plan.edges
    assert isinstance(e, GridEdges)
    assert e.assignment is not None and len(e.assignment) == plan.n_cells
    assert e.uniform_axes == (True, True, True)
    e.validate_against(DOM, GRID)
    # the projection is realized: re-bin the live rows under the plan
    from mpi_grid_redistribute_tpu.ops import binning

    live = p._live_rows(pos, count)
    ranks = binning.rank_of_position(live, DOM, GRID, xp=np, edges=e)
    c = np.bincount(ranks, minlength=R).astype(np.float64)
    assert c.max() / c.mean() == pytest.approx(plan.projected_imbalance)


def test_planner_no_live_rows_returns_none():
    p = RebalancePlanner(DOM, GRID)
    pos = np.zeros((R * 8, 3), np.float32)
    assert p.plan(pos, count=np.zeros(R, np.int32)) is None


def test_planner_validation():
    with pytest.raises(ValueError, match="cells_per_rank_axis"):
        RebalancePlanner(DOM, GRID, cells_per_rank_axis=0)
    p = RebalancePlanner(DOM, GRID)
    with pytest.raises(ValueError, match=r"\[R\*n_local"):
        p.occupancy(np.zeros((R * 4 + 1, 3), np.float32))


# ------------------------------------------------------------------ guard


def test_guard_fires_when_saving_clears_cost():
    g = AmortizationGuard(horizon_steps=100, cooldown_steps=10)
    # scripted gauges: 10 ms steps, 2.0x -> 1.0x. Seeded cost is
    # 8 x 10 ms = 80 ms; saving 5 ms/step x 100 steps = 500 ms >> 80.
    d = g.consider(
        step=50, step_seconds=0.010,
        old_imbalance=2.0, projected_imbalance=1.0,
    )
    assert d.apply
    assert d.projected_saving_s == pytest.approx(0.005)
    assert d.cost_s == pytest.approx(0.080)


def test_guard_declines_below_improvement_floor():
    g = AmortizationGuard(min_improvement=0.05)
    d = g.consider(
        step=50, step_seconds=0.010,
        old_imbalance=1.04, projected_imbalance=1.02,
    )
    assert not d.apply
    assert "below the" in d.reason and "floor" in d.reason


def test_guard_declines_when_horizon_saving_under_cost():
    # 1 improvement but a 4-step horizon: 4 x 5 ms = 20 ms < 80 ms seed
    g = AmortizationGuard(horizon_steps=4)
    d = g.consider(
        step=50, step_seconds=0.010,
        old_imbalance=2.0, projected_imbalance=1.0,
    )
    assert not d.apply
    assert "does not clear" in d.reason
    assert d.projected_saving_s == pytest.approx(0.005)


def test_guard_cooldown_blocks_back_to_back():
    g = AmortizationGuard(horizon_steps=100, cooldown_steps=16)
    gauges = dict(
        step_seconds=0.010, old_imbalance=3.0, projected_imbalance=1.0
    )
    assert g.consider(step=10, **gauges).apply
    g.note_applied(10, cost_seconds=0.030)
    d = g.consider(step=20, **gauges)
    assert not d.apply and "cooldown" in d.reason
    # cooldown elapsed: fires again, now against the MEASURED cost
    d2 = g.consider(step=26, **gauges)
    assert d2.apply
    assert d2.cost_s == pytest.approx(0.030)


def test_guard_measured_cost_ema():
    g = AmortizationGuard(cost_alpha=0.5)
    g.note_applied(0, 0.040)
    g.note_applied(100, 0.020)
    assert g.cost_ema_s == pytest.approx(0.030)
    assert g.applies == 2


def test_guard_zero_imbalance_and_validation():
    g = AmortizationGuard()
    d = g.consider(
        step=0, step_seconds=0.01,
        old_imbalance=0.0, projected_imbalance=1.0,
    )
    assert not d.apply and "no measured imbalance" in d.reason
    with pytest.raises(ValueError):
        AmortizationGuard(horizon_steps=0)
    with pytest.raises(ValueError):
        AmortizationGuard(min_improvement=1.0)
    with pytest.raises(ValueError):
        AmortizationGuard(cost_alpha=0.0)


# -------------------------------------------------------------- actuation


def test_apply_assignment_is_a_pure_permutation(rng):
    pos, count = _skewed_state(rng, n_local=128)
    n_local = 128
    vel = rng.random((R * n_local, 3), dtype=np.float32)
    ids = np.arange(R * n_local, dtype=np.int32)
    rd = GridRedistribute(
        DOM, GRID, backend="numpy", capacity=n_local, on_overflow="grow"
    )
    before = rd.redistribute(pos, vel, ids, count=count)
    pset_before = elastic.particle_set(
        np.asarray(before.positions),
        np.asarray(before.fields[0]),
        np.asarray(before.fields[1], np.int32),
        np.asarray(before.count, np.int32),
    )
    plan = RebalancePlanner(DOM, GRID, cells_per_rank_axis=4).plan(
        np.asarray(before.positions),
        count=np.asarray(before.count, np.int32),
    )
    res = rd.apply_assignment(
        plan.edges,
        np.asarray(before.positions),
        np.asarray(before.fields[0]),
        np.asarray(before.fields[1], np.int32),
        count=np.asarray(before.count, np.int32),
    )
    pset_after = elastic.particle_set(
        np.asarray(res.positions),
        np.asarray(res.fields[0]),
        np.asarray(res.fields[1], np.int32),
        np.asarray(res.count, np.int32),
    )
    assert pset_after == pset_before  # ownership moved, particles didn't
    # the new edges stick: subsequent redistributes route by them
    assert rd.edges is plan.edges
    new_counts = np.asarray(res.count, np.float64)
    assert new_counts.max() / new_counts.mean() <= 1.1


# ------------------------------------------------------------ closed loop


def _drift_driver(rebalance, n_local=512, steps=48):
    cfg = DriverConfig(
        grid_shape=(2, 2, 2),
        n_local=n_local,
        fill=0.5,
        steps=steps,
        backend="numpy",
        health_every=4,
        rebalance=rebalance,
        rebalance_threshold=1.5,
        rebalance_cells=4,
        rebalance_cooldown=8,
        rebalance_horizon=512,
    )
    drv = ServiceDriver(cfg)
    drv.init_state()
    pos, vel, ids, count = drv.state
    sink = np.asarray([0.25, 0.25, 0.25], np.float32)
    vel = ((sink[None, :] - pos) / np.float32(2 * steps)).astype(np.float32)
    drv.state = (pos, vel, ids, count)
    drv.run()
    drv.close()
    return drv


def test_closed_loop_alert_to_applied_rebalance():
    drv = _drift_driver(True)
    alerts = [
        e for e in drv.recorder.events("alert")
        if e.data.get("rule") == "imbalance_ratio"
    ]
    assert alerts, "drift bias never fired the imbalance_ratio ALERT"
    applied = [
        e.data for e in drv.recorder.events("rebalance")
        if e.data.get("applied")
    ]
    assert applied, "ALERT never became an applied rebalance"
    for e in applied:
        assert e["realized_imbalance"] <= 1.1
        assert e["rows_moved"] > 0
        assert e["cost_s"] > 0
        assert "trigger" in e and "reason" in e
    dropped = sum(
        int(e.data.get("dropped", 0))
        for e in drv.recorder.events("step_latency")
    )
    assert dropped == 0


def test_closed_loop_particle_set_bit_identical():
    base = _drift_driver(False)
    reb = _drift_driver(True)
    assert any(
        e.data.get("applied") for e in reb.recorder.events("rebalance")
    )
    assert elastic.particle_set(*reb.state) == elastic.particle_set(
        *base.state
    )


def test_closed_loop_decline_journaled(monkeypatch):
    """Force the guard to decline (impossible improvement floor just
    under 1) and check the decline is journaled applied=false with the
    gauges — the loop is auditable even when it does nothing."""
    drv = ServiceDriver(
        DriverConfig(
            grid_shape=(2, 2, 2),
            n_local=256,
            fill=0.5,
            steps=32,
            backend="numpy",
            health_every=4,
            rebalance=True,
            rebalance_threshold=1.2,
            rebalance_min_improvement=0.999,
        )
    )
    drv.init_state()
    pos, vel, ids, count = drv.state
    sink = np.asarray([0.25, 0.25, 0.25], np.float32)
    vel = ((sink[None, :] - pos) / np.float32(64)).astype(np.float32)
    drv.state = (pos, vel, ids, count)
    drv.run()
    drv.close()
    events = [e.data for e in drv.recorder.events("rebalance")]
    assert events, "no rebalance consideration was journaled"
    assert all(not e["applied"] for e in events)
    declined = [e for e in events if "old_imbalance" in e]
    assert declined, "declines lost their gauges"
    for e in declined:
        assert "below the" in e["reason"]
        assert e["projected_imbalance"] <= e["old_imbalance"]
