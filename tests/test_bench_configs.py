"""Smoke-run the five BASELINE config drivers at tiny sizes (SURVEY.md §6)."""

import os

import numpy as np

import pytest

os.environ.setdefault("BENCH_SCALE", "0.01")


def test_config1_oracle():
    import gc
    import warnings

    from mpi_grid_redistribute_tpu.bench import config1_oracle

    # RuntimeWarnings as errors: the driver must resolve its deferred
    # overflow windows itself (flush/with), not warn from __del__
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        out = config1_oracle.run(n_total=1 << 12, reps=1)
        gc.collect()  # trigger any leftover GridRedistribute.__del__ now
    assert out["bit_equal_vs_oracle"] is True
    assert out["value"] > 0
    # the merged telemetry surface rides the bench JSON
    rep = out["api_report"]
    assert rep["kind"] == "redistribute"
    assert rep["bw_util"] is not None and rep["bw_util"] > 0
    assert rep["unresolved_windows"] is False


def test_config7_stress():
    from mpi_grid_redistribute_tpu.bench import config7_stress

    out = config7_stress.run(n_total=1 << 12, reps=1)
    # full-reshuffle regime: destinations are uniform, so ~(R-1)/R of
    # rows change owner every step — far above any drift config
    assert out["migration_fraction"] > 0.5
    assert out["bw_util"] > 0
    assert out["exchange_bytes_per_step"] > 0
    assert out["timing_spread"] >= 0
    assert out["exchange_domain"] == "hbm"


def test_config2_clustered():
    from mpi_grid_redistribute_tpu.bench import config2_clustered

    out = config2_clustered.run(n_local=256, max_rounds=64)
    assert out["dropped_recv"] == 0
    assert out["placement_dropped_recv"] == 0
    assert out["ownership_imbalance"] >= 1.0
    # tiny CPU smoke: scan differencing can be noise-dominated, so only
    # presence/finiteness of the steady-state fields is asserted here
    for k in ("pps_imbalanced", "pps_uniform_ref", "imbalanced_over_uniform"):
        assert np.isfinite(out[k])


def test_config3_slab():
    from mpi_grid_redistribute_tpu.bench import config3_slab

    out = config3_slab.run(n_local=512)
    assert out["value"] > 0
    assert out["chips"] == 1  # 64 slabs as vranks on 8 CPU devices? no: 64>8


def test_config4_drift():
    from mpi_grid_redistribute_tpu.bench import config4_drift

    out = config4_drift.run(n_local=1 << 12, steps=16)
    assert out["value"] > 0
    assert out["chips"] == 8  # 2x2x2 fits the 8 virtual CPU devices


def test_config4_rebalance_smoke_gate():
    # the `make rebalance-smoke` gate at a CI-sized leg: ALERT ->
    # applied rebalance -> post-imbalance <= 1.1x, zero drops, and the
    # particle set bit-identical to the no-rebalance twin. The
    # steady-state ms/step win is regress-guarded at bench scale, not
    # asserted at this size.
    from mpi_grid_redistribute_tpu.bench import config4_drift

    out = config4_drift.run_rebalance(n_local=512, steps=48)
    assert out["alerts"] >= 1
    assert out["rebalances_applied"] >= 1
    assert out["post_rebalance_imbalance"] <= 1.1
    assert out["dropped"] == 0
    assert out["bit_identical"]


def test_config5_deposit():
    from mpi_grid_redistribute_tpu.bench import config5_deposit

    out = config5_deposit.run(n_local=1 << 10, mesh_cells=16)
    assert out["value"] > 0


def test_config8_soak(monkeypatch):
    from mpi_grid_redistribute_tpu.bench import config8_soak

    monkeypatch.setenv("BENCH_SOAK_EVERY", "4")  # short cadence, short run
    monkeypatch.setenv("BENCH_SOAK_STEPS", "12")  # short crash/elastic legs
    out = config8_soak.run(n_local=512, reps=2)
    assert out["metric"] == "soak_pps"
    assert out["value"] > 0
    assert out["snapshots_written"] >= 1
    assert np.isfinite(out["snapshot_overhead"])
    # the crash leg: exactly one supervised restart, and the resumed
    # trajectory byte-equal to the uninterrupted run (the tier-1 half of
    # the `make soak` gate; the 2% overhead budget is gated at real
    # scale by `make soak` / bench-check, not at this smoke size)
    assert out["restarts"] == 1
    assert out["bit_identical_resume"] is True
    # the elastic leg: crash + half the devices lost -> shrink-restore,
    # journaled reshard, and the id-sorted particle set preserved
    assert out["elastic_restarts"] == 1
    assert out["resharded"] == 1
    assert out["elastic_grid"] != out["grid"]
    assert out["elastic_set_identical"] is True
    # the gate helper agrees with a green capture when overhead passes
    ok = dict(out, snapshot_overhead=0.0)
    assert config8_soak._soak_gate(ok) == []
    bad = dict(out, bit_identical_resume=False)
    assert config8_soak._soak_gate(bad) != []
    bad2 = dict(ok, elastic_set_identical=False)
    assert config8_soak._soak_gate(bad2) != []
