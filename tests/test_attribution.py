"""Roofline observatory tests (ISSUE 14).

Covers the three layers of the attribution stack:

* ``telemetry/roofline.py`` — the pure hand-math (``predict``,
  ``extract_cost``, ``cross_check``) against synthetic cost dicts with
  exact expected values, plus one real compile through
  ``roofline_report`` so the journaling/discrepancy path is exercised
  end to end on the CPU backend.
* ``telemetry/profiler.py`` — the gating contract: disabled sessions
  journal nothing and never import jax; armed sessions journal
  ``profile_session``; a broken profiler degrades to ``armed=False``
  with the error string instead of taking the caller down.
* ``scripts/attribution.py`` — the committed-snapshot drift gate:
  clean at HEAD, findings on a perturbed snapshot/rendered table, and
  the section-merged baseline round-trip in ``analysis/baseline.py``.

Satellite surfaces ride along: the ``grid_roofline_achieved_fraction``
gauge / ``grid_profile_sessions`` counter in ``metrics.from_journal``,
and the Perfetto phase-lane ``annotations`` merge in ``traceview``.
"""

import copy
import importlib.util
import json
import os

import pytest

from mpi_grid_redistribute_tpu.analysis.baseline import (
    attribution_hash,
    load_attribution_baseline,
    write_attribution_baseline,
)
from mpi_grid_redistribute_tpu.telemetry import metrics, traceview
from mpi_grid_redistribute_tpu.telemetry.phases import PhaseTiming
from mpi_grid_redistribute_tpu.telemetry.profiler import (
    PROFILE_DIR_ENV,
    ProfilerSession,
)
from mpi_grid_redistribute_tpu.telemetry.recorder import StepRecorder
from mpi_grid_redistribute_tpu.telemetry.roofline import (
    BOUND_COLLECTIVE,
    BOUND_COMPUTE,
    BOUND_MEMORY,
    BOUND_UNKNOWN,
    cross_check,
    extract_cost,
    format_roofline_table,
    predict,
    roofline_report,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_attribution():
    spec = importlib.util.spec_from_file_location(
        "attribution_cli",
        os.path.join(REPO_ROOT, "scripts", "attribution.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


attribution = _load_attribution()


# ------------------------------------------------------ roofline math


def test_extract_cost_container_variants():
    # jax returns a 1-list of dicts on some versions, a bare dict on
    # others; 'bytes accessed' (with a space) is XLA's key
    assert extract_cost([{"flops": 3.0, "bytes accessed": 7.0}]) == {
        "flops": 3.0,
        "bytes_accessed": 7.0,
    }
    assert extract_cost({"flops": 3.0}) == {
        "flops": 3.0,
        "bytes_accessed": 0.0,
    }
    assert extract_cost(None) is None
    assert extract_cost([]) is None
    assert extract_cost("not a cost table") is None


def test_predict_hand_math_compute_bound():
    row = predict(
        {"flops": 2e9, "bytes_accessed": 1e6},
        collective_bytes=2048,
        peak_flops_per_sec=1e12,
        peak_bytes_per_sec=1e9,
        collective_peak_bytes_per_sec=1e9,
    )
    assert row["t_compute_s"] == pytest.approx(2e-3)
    assert row["t_memory_s"] == pytest.approx(1e-3)
    assert row["t_collective_s"] == pytest.approx(2.048e-6)
    assert row["t_predicted_s"] == pytest.approx(2e-3)
    assert row["bound_by"] == BOUND_COMPUTE


def test_predict_hand_math_memory_and_collective_bound():
    mem = predict(
        {"flops": 1e6, "bytes_accessed": 8e9},
        collective_bytes=0,
        peak_flops_per_sec=1e12,
        peak_bytes_per_sec=1e9,
        collective_peak_bytes_per_sec=1e9,
    )
    assert mem["bound_by"] == BOUND_MEMORY
    assert mem["t_predicted_s"] == pytest.approx(8.0)
    coll = predict(
        {"flops": 1e6, "bytes_accessed": 1e3},
        collective_bytes=5_000_000_000,
        peak_flops_per_sec=1e12,
        peak_bytes_per_sec=1e9,
        collective_peak_bytes_per_sec=1e9,
    )
    assert coll["bound_by"] == BOUND_COLLECTIVE
    assert coll["t_predicted_s"] == pytest.approx(5.0)


def test_predict_zero_cost_ties_break_compute_and_none_is_unknown():
    zero = predict({"flops": 0.0, "bytes_accessed": 0.0})
    assert zero["bound_by"] == BOUND_COMPUTE
    assert zero["t_predicted_s"] == 0.0
    unk = predict(None, collective_bytes=4096)
    assert unk["bound_by"] == BOUND_UNKNOWN
    assert unk["flops"] is None
    assert unk["t_predicted_s"] == unk["t_collective_s"] > 0


def test_cross_check_verdicts():
    prof = {"collective_bytes_total": 1000}
    wire = {"per_domain": {"ici": 600}}
    ok = cross_check({"flops": 1.0, "bytes_accessed": 4000.0}, prof, wire)
    assert not ok["discrepancy"]
    assert ok["bytes_ratio"] == pytest.approx(4.0)
    assert ok["static_ici_bytes"] == 600

    low = cross_check({"flops": 1.0, "bytes_accessed": 999.0}, prof, wire)
    assert low["discrepancy"]
    assert "below the static collective total" in low["discrepancy_reason"]

    nocost = cross_check(None, prof, wire)
    assert nocost["discrepancy"]
    assert "no cost model" in nocost["discrepancy_reason"]

    nobase = cross_check({"flops": 1.0, "bytes_accessed": 1.0}, None, None)
    assert nobase["discrepancy"]
    assert "J004 baseline" in nobase["discrepancy_reason"]


class _FakeSpec:
    """A minimal ProgramSpec stand-in: build() -> (fn, example_args)."""

    def build(self):
        import jax.numpy as jnp

        return (lambda x: x * 2.0 + 1.0), (jnp.ones((8,), jnp.float32),)


def test_roofline_report_compiles_journals_and_flags_unbaselined():
    rec = StepRecorder()
    report = roofline_report(
        programs={"fake_prog": _FakeSpec()},
        measured_s={"fake_prog": 1e-3},
        recorder=rec,
    )
    row = report["fake_prog"]
    # a program outside the J004 baseline is a journaled discrepancy,
    # never a silent drop
    assert row["discrepancy"]
    assert "J004" in row["discrepancy_reason"]
    assert row["measured_s"] == 1e-3
    events = rec.events("roofline")
    assert len(events) == 1
    assert events[0].data["program"] == "fake_prog"
    assert events[0].data["phase"] == "total"
    assert events[0].data["discrepancy"] is True
    # the table renderer accepts the same rows
    table = format_roofline_table(report)
    assert "fake_prog" in table and "DISCREPANT" in table


# -------------------------------------------------- profiler sessions


def test_profiler_session_disabled_is_a_true_noop(monkeypatch):
    monkeypatch.delenv(PROFILE_DIR_ENV, raising=False)
    rec = StepRecorder()
    with ProfilerSession(None, recorder=rec) as s:
        assert not s.enabled
    assert rec.events("profile_session") == []


def test_profiler_session_env_knob_arms_it(tmp_path, monkeypatch):
    calls = []
    import jax

    monkeypatch.setattr(
        jax.profiler, "start_trace", lambda d: calls.append(("start", d))
    )
    monkeypatch.setattr(
        jax.profiler, "stop_trace", lambda: calls.append(("stop",))
    )
    monkeypatch.setenv(PROFILE_DIR_ENV, str(tmp_path))
    rec = StepRecorder()
    with ProfilerSession(recorder=rec, label="knob") as s:
        assert s.enabled and s.armed
    assert calls == [("start", str(tmp_path)), ("stop",)]
    (ev,) = rec.events("profile_session")
    assert ev.data["trace_dir"] == str(tmp_path)
    assert ev.data["label"] == "knob"
    assert ev.data["armed"] is True
    assert ev.data["error"] is None
    assert ev.data["duration_s"] >= 0.0


def test_profiler_session_broken_profiler_degrades(tmp_path, monkeypatch):
    import jax

    def _boom(d):
        raise RuntimeError("profiler says no")

    monkeypatch.setattr(jax.profiler, "start_trace", _boom)
    rec = StepRecorder()
    with ProfilerSession(str(tmp_path), recorder=rec):
        pass  # must not raise
    (ev,) = rec.events("profile_session")
    assert ev.data["armed"] is False
    assert "RuntimeError" in ev.data["error"]


# ----------------------------------------------- metrics + traceview


def test_metrics_roofline_gauge_and_profile_counter():
    rec = StepRecorder()
    rec.record(
        "roofline",
        program="p1",
        phase="total",
        achieved_fraction=0.25,
        discrepancy=False,
    )
    rec.record(
        "profile_session",
        trace_dir="/tmp/x",
        label="s",
        duration_s=0.1,
        armed=True,
        error=None,
    )
    text = metrics.from_journal(rec).render_openmetrics()
    assert (
        'grid_roofline_achieved_fraction{program="p1",phase="total"} 0.25'
        in text
    )
    assert "grid_profile_sessions_total 1" in text


def test_metrics_roofline_gauge_clears_without_measurement():
    # rows without achieved_fraction (no measurement) must not leave a
    # stale gauge behind
    rec = StepRecorder()
    rec.record(
        "roofline", program="p1", phase="total", achieved_fraction=None
    )
    text = metrics.from_journal(rec).render_openmetrics()
    assert 'grid_roofline_achieved_fraction{' not in text


def test_traceview_annotations_merge_without_overwrite():
    rows = [
        PhaseTiming("1", 0.001, 0.001, None, None),
        PhaseTiming("2", 0.003, 0.002, None, None),
    ]
    ann = {"1": {"flops": 5.0, "bound_by": "memory", "delta_s": 999.0}}
    doc = traceview.to_chrome_trace(phase_timings=rows, annotations=ann)
    lane = [
        e
        for e in doc["traceEvents"]
        if e.get("pid") == 1 and e.get("ph") == "X"
    ]
    assert len(lane) == 2
    by_name = {e["name"]: e["args"] for e in lane}
    assert by_name["1"]["flops"] == 5.0
    assert by_name["1"]["bound_by"] == "memory"
    # measured columns win over annotation keys of the same name
    assert by_name["1"]["delta_s"] == pytest.approx(0.001)
    assert "flops" not in by_name["2"]
    json.dumps(doc)  # stays serializable


# ------------------------------------- attribution snapshot + gate


def test_attribution_baseline_round_trip_section_merge(tmp_path):
    path = str(tmp_path / "attr.json")
    write_attribution_baseline(path, phase_tables={"migrate": {"x": 1}})
    write_attribution_baseline(path, roofline={"prog": {"flops": 2.0}})
    doc = load_attribution_baseline(path)
    # the second write merged its section without clobbering the first
    assert doc["phase_tables"] == {"migrate": {"x": 1}}
    assert doc["roofline"] == {"prog": {"flops": 2.0}}
    h = attribution_hash(path)
    assert isinstance(h, str) and len(h) == 16
    assert attribution_hash(path) == h


def test_render_table_deterministic_hand_math():
    table = {
        "grid": "2,2,2",
        "phases": [1, 2],
        "shapes": {
            "4096": {
                "rows": [
                    {"phase": 1, "cumulative_s": 0.0011, "delta_s": 0.0011},
                    {"phase": 2, "cumulative_s": 0.0031, "delta_s": 0.0020},
                ]
            }
        },
    }
    md = attribution.render_table("migrate", table)
    assert md == attribution.render_table("migrate", table)
    lines = md.splitlines()
    assert lines[0] == "| phase (cumulative) | 8×4k ms | delta |"
    assert lines[2] == "| 1 drift + wrap + bin | 1.10 | (first) |"
    # the last row is the full step: bold ms, signed delta
    assert "**3.10**" in lines[3] and "+2.00" in lines[3]


def test_render_markdown_replaces_marker_regions():
    doc = {
        "phase_tables": {
            "migrate": {
                "grid": "2,2,2",
                "phases": [1],
                "shapes": {
                    "4096": {
                        "rows": [
                            {
                                "phase": 1,
                                "cumulative_s": 0.001,
                                "delta_s": 0.001,
                            }
                        ]
                    }
                },
            },
            "pipeline": {
                "grid": "2,2,2",
                "phases": ["a"],
                "shapes": {
                    "4096": {
                        "rows": [
                            {
                                "phase": "a",
                                "cumulative_s": 0.002,
                                "delta_s": 0.002,
                            }
                        ]
                    }
                },
            },
        }
    }
    text = (
        "intro\n<!-- attribution:migrate:begin -->\nSTALE\n"
        "<!-- attribution:migrate:end -->\nmiddle\n"
        "<!-- attribution:pipeline:begin -->\nSTALE\n"
        "<!-- attribution:pipeline:end -->\ntail\n"
    )
    out = attribution.render_markdown(doc, text)
    assert "STALE" not in out
    assert "intro" in out and "middle" in out and "tail" in out
    # idempotent: rendering rendered text changes nothing
    assert attribution.render_markdown(doc, out) == out
    with pytest.raises(SystemExit):
        attribution.render_markdown(doc, "no markers here")


def test_attribution_check_clean_at_head():
    # the committed snapshot + rendered BENCH_CONFIGS.md tables must be
    # current: the same gate `make check` runs
    assert attribution.check_findings() == []


def test_attribution_check_fails_on_perturbed_snapshot(monkeypatch):
    head = load_attribution_baseline()
    assert head is not None

    perturbed = copy.deepcopy(head)
    perturbed["phase_tables"]["migrate"]["phases"] = [1, 2, 3]
    monkeypatch.setattr(
        attribution, "load_attribution_baseline", lambda: perturbed
    )
    rules = {f.rule for f in attribution.check_findings()}
    assert "A001" in rules

    # dropping a roofline row breaks registry coverage (A003)
    perturbed2 = copy.deepcopy(head)
    name, _ = sorted(perturbed2["roofline"].items())[0]
    del perturbed2["roofline"][name]
    perturbed2["roofline"]["not_a_registered_program"] = {}
    monkeypatch.setattr(
        attribution, "load_attribution_baseline", lambda: perturbed2
    )
    msgs = [f for f in attribution.check_findings() if f.rule == "A003"]
    assert any(name in f.message for f in msgs)
    assert any("not_a_registered_program" in f.message for f in msgs)

    # restoring the real loader ("--update-baseline" undone) is clean
    monkeypatch.undo()
    assert attribution.check_findings() == []


def test_attribution_check_fails_on_stale_rendered_table(
    tmp_path, monkeypatch
):
    # same snapshot, stale markdown: the A002 leg alone must fire
    with open(attribution.BENCH_MD, "r", encoding="utf-8") as fh:
        text = fh.read()
    stale = str(tmp_path / "BENCH_CONFIGS.md")
    parts = attribution._split_markers(text, "migrate")
    assert parts is not None
    before, _, after = parts
    with open(stale, "w", encoding="utf-8") as fh:
        fh.write(before + "\n| doctored | table |\n" + after)
    monkeypatch.setattr(attribution, "BENCH_MD", stale)
    findings = attribution.check_findings()
    assert {f.rule for f in findings} == {"A002"}
    assert any("migrate" in f.message for f in findings)
