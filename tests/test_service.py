"""service/: driver snapshot/restore, supervisor, fault matrix (ISSUE 6).

Everything runs the numpy backend at tiny sizes — the recovery logic
under test is backend-independent, and the CPU oracle keeps the whole
fault matrix inside the tier-1 budget. The jax path is covered by the
config8 soak bench and ``scripts/pod_smoke.py --kill-restore``.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.service import (
    CrashFault,
    DeviceLossFault,
    DriverConfig,
    ElasticRestoreError,
    FallbackFloodFault,
    FaultPlan,
    InjectedCrash,
    JournalShardLossFault,
    LatencySpikeFault,
    RestartPolicy,
    ServiceDriver,
    StallFault,
    Supervisor,
    TornSnapshotFault,
)
from mpi_grid_redistribute_tpu.service import elastic
from mpi_grid_redistribute_tpu.telemetry import StepRecorder
from mpi_grid_redistribute_tpu.telemetry import health
from mpi_grid_redistribute_tpu.utils import checkpoint


def _cfg(tmp_path, **kw):
    base = dict(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=24,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
    )
    base.update(kw)
    return DriverConfig(**base)


def _reference_state(cfg):
    """The uninterrupted trajectory: same config, snapshots/journal off
    (neither may influence the state for restarts to be bit-exact)."""
    ref = ServiceDriver(
        dataclasses.replace(
            cfg, snapshot_every=0, snapshot_dir=None, journal_dir=None,
            watchdog_s=0.0,
        )
    )
    ref.init_state()
    state = ref.run()
    ref.close()
    return state


def _assert_bit_identical(a, b):
    for name, x, y in zip(("pos", "vel", "ids", "count"), a, b):
        assert x.tobytes() == y.tobytes(), f"{name} diverged"


# ------------------------------------------------------- driver basics


def test_driver_config_validation(tmp_path):
    with pytest.raises(ValueError, match="snapshot_dir"):
        ServiceDriver(_cfg(tmp_path, snapshot_dir=None))
    with pytest.raises(ValueError, match="keep_snapshots"):
        ServiceDriver(_cfg(tmp_path, keep_snapshots=1))


def test_snapshot_restore_bit_identical(tmp_path):
    cfg = _cfg(tmp_path, keep_snapshots=2)
    drv = ServiceDriver(cfg)
    drv.init_state()
    drv.run(max_steps=10)  # past two snapshot points (steps 4 and 8)
    drv.close()

    # pruning: only keep_snapshots newest survive on disk
    snaps = checkpoint.list_snapshots(cfg.snapshot_dir)
    assert len(snaps) == 2

    resumed = ServiceDriver(cfg)
    assert resumed.restore_latest() is True
    assert resumed.step == 8
    ev = resumed.recorder.last("restore")
    assert ev.data["what"] == "state" and ev.data["step"] == 8
    assert ev.data["snapshots_skipped"] == 0
    resumed.run()  # 8 -> 24 entirely from the restored snapshot
    resumed.close()
    _assert_bit_identical(resumed.state, _reference_state(cfg))


def test_restore_latest_without_snapshots(tmp_path):
    drv = ServiceDriver(_cfg(tmp_path, snapshot_every=0, snapshot_dir=None))
    assert drv.restore_latest() is False
    drv2 = ServiceDriver(_cfg(tmp_path))  # dir configured but empty
    assert drv2.restore_latest() is False


# ------------------------------------------------------- fault matrix


def _supervised(tmp_path, cfg, faults, max_restarts=5, **policy_kw):
    rec = StepRecorder()

    def factory(grid_shape=None):
        # the supervisor's shrink policy restarts onto a smaller grid by
        # passing grid_shape; a plain restart keeps the configured one
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return ServiceDriver(c, recorder=rec, faults=faults)

    sup = Supervisor(
        factory,
        policy=RestartPolicy(
            max_restarts=max_restarts, backoff_base_s=0.01,
            backoff_cap_s=0.02, **policy_kw,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    return sup, rec


@pytest.mark.parametrize("kind", [
    "crash", "stall", "torn_snapshot", "journal_loss", "fallback_flood",
])
def test_fault_matrix(tmp_path, kind):
    extra = {}
    if kind == "crash":
        fault, restarts = CrashFault(9), 1
    elif kind == "stall":
        fault, restarts = StallFault(7, seconds=0.5), 1
        extra["watchdog_s"] = 0.2
    elif kind == "torn_snapshot":
        fault, restarts = TornSnapshotFault(snapshot_index=1), 1
    elif kind == "journal_loss":
        fault, restarts = JournalShardLossFault(6), 0
        extra["journal_dir"] = str(tmp_path / "journal")
    else:
        fault, restarts = FallbackFloodFault(start_step=1, steps=24), 0

    cfg = _cfg(tmp_path, **extra)
    sup, rec = _supervised(tmp_path, cfg, FaultPlan([fault]))
    verdict = sup.run()

    # every fault mode ends in a healthy, completed service
    assert verdict.ok is True, verdict
    assert verdict.gave_up is False
    assert verdict.restarts == restarts
    assert verdict.step == cfg.steps
    counts = rec.counts()
    assert counts.get("fault_injected") == 1
    assert counts.get("restart", 0) == restarts

    if kind in ("crash", "stall", "torn_snapshot"):
        # restarted from a snapshot: a journaled restore, then a resumed
        # trajectory byte-equal to the uninterrupted run
        restores = [
            e for e in rec.events("restore")
            if e.data.get("what") == "state"
        ]
        assert len(restores) == 1
        _assert_bit_identical(sup.driver.state, _reference_state(cfg))
        if kind == "torn_snapshot":
            # the corrupted newest snapshot was skipped, not loaded
            assert restores[0].data["snapshots_skipped"] >= 1
            assert restores[0].data["step"] == 4
    if kind == "stall":
        assert "StallError" in rec.last("restart").data["reason"]
    if kind == "journal_loss":
        # loss detected and healed: shard re-exported with the retained
        # window, restore(what=journal) journaled, file back on disk
        heals = [
            e for e in rec.events("restore")
            if e.data.get("what") == "journal"
        ]
        assert len(heals) == 1
        assert os.path.exists(sup.driver.journal_path)
        _assert_bit_identical(sup.driver.state, _reference_state(cfg))
    if kind == "fallback_flood":
        # graceful degrade: exactly one engine -> planar transition,
        # pinned for the rest of the run (never flaps back)
        degrades = rec.events("degrade")
        assert len(degrades) == 1
        assert degrades[0].data["to"] == "planar"
        assert sup.driver.degraded is True
        assert sup.driver.engine == "planar"
        assert verdict.health == "WARN"  # rule still firing, not ALERT


def test_crash_loop_trips_circuit_breaker(tmp_path):
    cfg = _cfg(tmp_path, steps=12)
    sup, rec = _supervised(
        tmp_path, cfg, FaultPlan([CrashFault(None)]), max_restarts=3
    )
    verdict = sup.run()
    assert verdict.ok is False
    assert verdict.gave_up is True
    assert verdict.restarts == 3
    assert "circuit breaker" in verdict.reason
    actions = [e.data["action"] for e in rec.events("restart")]
    assert actions == ["restart"] * 3 + ["give_up"]
    # backoff grows (bounded exponential; jitter keeps it monotone here)
    backoffs = [
        e.data["backoff_s"] for e in rec.events("restart")
        if e.data["action"] == "restart"
    ]
    assert all(b > 0 for b in backoffs)


def test_healthz_alert_forces_restart(tmp_path):
    # a clean exit with a red /healthz is a failure: the supervisor must
    # restart, and a deterministic alert must end at the breaker
    always_red = health.HealthRule(
        "always_red", health.ALERT, lambda rec: "synthetic alert"
    )
    cfg = _cfg(tmp_path, steps=6, snapshot_every=0, snapshot_dir=None)
    rec = StepRecorder()
    sup = Supervisor(
        lambda: ServiceDriver(
            cfg, recorder=rec,
            monitor=health.HealthMonitor(rec, rules=[always_red]),
        ),
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.01),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    verdict = sup.run()
    assert verdict.ok is False and verdict.gave_up is True
    assert verdict.health == "ALERT"
    assert "healthz 503" in verdict.reason
    restart = [
        e for e in rec.events("restart") if e.data["action"] == "restart"
    ]
    assert all("healthz 503" in e.data["reason"] for e in restart)


# ------------------------------------------- elastic restore (ISSUE 8)


def test_device_loss_shrink_restore_preserves_particle_set(tmp_path):
    # crash at step 9, and every restore after the crash sees only 4 of
    # the 8 devices: the driver must shrink-to-fit (2,2,2)->(1,2,2),
    # re-shard the snapshot, and finish with the SAME global particles
    cfg = _cfg(tmp_path)
    plan = FaultPlan([CrashFault(9), DeviceLossFault(4)])
    sup, rec = _supervised(tmp_path, cfg, plan)
    verdict = sup.run()

    assert verdict.ok is True, verdict
    assert verdict.restarts == 1
    assert verdict.step == cfg.steps
    assert tuple(sup.driver.cfg.grid_shape) == (1, 2, 2)
    # capacity preserved: half the vranks, double the padded rows
    assert sup.driver.cfg.n_local == 512
    assert rec.counts().get("fault_injected") == 2

    (ev,) = rec.events("reshard")
    assert ev.data["old_grid"] == [2, 2, 2]
    assert ev.data["old_shards"] == 8
    assert ev.data["old_rows_per_shard"] == 256
    assert ev.data["new_grid"] == [1, 2, 2]
    assert ev.data["new_rows_per_shard"] == 512
    assert ev.data["step"] == 8  # resharded the step-8 snapshot
    assert 0 < ev.data["moved"] <= ev.data["rows"]

    # mesh shapes differ, so compare the id-sorted global particle SET
    # (and total row conservation), not the padded per-vrank layout
    ref = _reference_state(cfg)
    assert int(sup.driver.state[3].sum()) == int(ref[3].sum())
    assert elastic.particle_set(*sup.driver.state) == \
        elastic.particle_set(*ref)


def test_restore_latest_onto_explicit_grid(tmp_path):
    cfg = _cfg(tmp_path)
    drv = ServiceDriver(cfg)
    drv.init_state()
    drv.run(max_steps=8)
    drv.close()

    res = ServiceDriver(_cfg(tmp_path))
    assert res.restore_latest(grid_shape=(1, 2, 2)) is True
    assert res.step == 8
    assert tuple(res.cfg.grid_shape) == (1, 2, 2)
    assert res.cfg.n_local == 512
    ev = res.recorder.last("reshard")
    assert ev.data["new_grid"] == [1, 2, 2]
    # live rows conserved through the reshard
    assert int(res.state[3].sum()) == int(drv.state[3].sum())
    res.run()  # 8 -> 24 on the smaller mesh
    res.close()
    assert elastic.particle_set(*res.state) == \
        elastic.particle_set(*_reference_state(cfg))


def test_elastic_restore_disabled_raises_naming_both_shapes(tmp_path):
    cfg = _cfg(tmp_path)
    drv = ServiceDriver(cfg)
    drv.init_state()
    drv.run(max_steps=4)
    drv.close()

    # the same layout restores fine with auto_reshard off
    same = ServiceDriver(_cfg(tmp_path, auto_reshard=False))
    assert same.restore_latest() is True

    # a different layout must fail FAST with both shapes in the message
    strict = ServiceDriver(
        _cfg(tmp_path, grid_shape=(1, 2, 2), n_local=512,
             auto_reshard=False)
    )
    with pytest.raises(ElasticRestoreError) as ei:
        strict.restore_latest()
    msg = str(ei.value)
    assert "(2, 2, 2)" in msg and "(1, 2, 2)" in msg
    assert "auto_reshard is disabled" in msg


def test_slo_breach_restarts_then_shrinks(tmp_path):
    # a latency-spike flood breaches the p99 SLO at the step-4 health
    # check -> restart; the leftover spikes breach again -> second
    # consecutive breach trips the shrink policy -> restart onto
    # shrink_shape((2,2,2)) with an elastic re-shard; the spike budget is
    # then spent, so the third attempt completes clean with no operator
    # input anywhere
    cfg = _cfg(
        tmp_path, steps=32, slo_latency_p99_s=0.25, slo_window=4,
    )
    plan = FaultPlan([LatencySpikeFault(2, seconds=1.0, spikes=6)])
    sup, rec = _supervised(tmp_path, cfg, plan, shrink_after=2)
    verdict = sup.run()

    assert verdict.ok is True, verdict
    assert verdict.restarts == 2
    assert tuple(sup.driver.cfg.grid_shape) == (1, 2, 2)
    actions = [e.data["action"] for e in rec.events("restart")]
    assert actions == ["restart", "shrink", "restart"]
    reasons = [
        e.data["reason"] for e in rec.events("restart")
        if e.data["action"] == "restart"
    ]
    assert all("SLOBreachError" in r for r in reasons)
    assert all("slo_latency_p99" in r for r in reasons)
    (shrink,) = [
        e for e in rec.events("restart") if e.data["action"] == "shrink"
    ]
    assert shrink.data["old_grid"] == [2, 2, 2]
    assert shrink.data["new_grid"] == [1, 2, 2]
    assert len(rec.events("reshard")) == 1
    assert rec.counts().get("fault_injected") == 1


# ------------------------------------------------- breaker boundaries


class _FailFirstN:
    """Scripted injector: crash the first ``n`` runs (at step 1), then
    let every later run succeed — exact failure counts for boundary
    tests, where CrashFault(None) can only fail forever."""

    kind = "fail_first_n"

    def __init__(self, n):
        self.left = int(n)

    def before_step(self, driver):
        if self.left > 0 and driver.step == 1:
            self.left -= 1
            raise InjectedCrash("scripted failure")


def _ticking_clock(spacing):
    """Deterministic clock: each restart loop reads the same instant
    twice (breaker check + window append), instants ``spacing`` apart."""

    def gen():
        t = 0.0
        while True:
            yield t
            yield t
            t += spacing

    it = gen()
    return lambda: next(it)


def _boundary_sup(tmp_path, n_failures, policy, clock):
    cfg = _cfg(tmp_path, steps=4, snapshot_every=0, snapshot_dir=None)
    rec = StepRecorder()
    plan = FaultPlan([_FailFirstN(n_failures)])
    sup = Supervisor(
        lambda: ServiceDriver(cfg, recorder=rec, faults=plan),
        policy=policy,
        recorder=rec,
        sleep_fn=lambda s: None,
        clock=clock,
    )
    return sup, rec


def test_breaker_count_boundary(tmp_path):
    # all failures at one instant (static clock): exactly max_restarts
    # failures must NOT trip the breaker (the max_restarts-th restart is
    # still granted), one more must
    policy = RestartPolicy(
        max_restarts=3, backoff_base_s=0.01, backoff_cap_s=0.02
    )
    sup, rec = _boundary_sup(tmp_path, 3, policy, lambda: 0.0)
    verdict = sup.run()
    assert verdict.ok is True and verdict.gave_up is False
    assert verdict.restarts == 3

    sup, rec = _boundary_sup(tmp_path, 4, policy, lambda: 0.0)
    verdict = sup.run()
    assert verdict.ok is False and verdict.gave_up is True
    assert verdict.restarts == 3
    actions = [e.data["action"] for e in rec.events("restart")]
    assert actions == ["restart"] * 3 + ["give_up"]


def test_breaker_window_boundary_is_inclusive(tmp_path):
    # failures spaced EXACTLY window_s apart: the inclusive window keeps
    # at most one prior restart in view, so max_restarts=2 never trips
    # even through 5 straight failures
    policy = RestartPolicy(
        max_restarts=2, window_s=10.0, backoff_base_s=0.01,
        backoff_cap_s=0.02,
    )
    sup, rec = _boundary_sup(tmp_path, 5, policy, _ticking_clock(10.0))
    verdict = sup.run()
    assert verdict.ok is True and verdict.gave_up is False
    assert verdict.restarts == 5

    # the same failures clustered INSIDE the window (spacing < window_s)
    # trip the breaker at the count boundary
    sup, rec = _boundary_sup(tmp_path, 5, policy, _ticking_clock(5.0))
    verdict = sup.run()
    assert verdict.ok is False and verdict.gave_up is True
    assert verdict.restarts == 2
    actions = [e.data["action"] for e in rec.events("restart")]
    assert actions == ["restart"] * 2 + ["give_up"]


def test_backoff_jitter_deterministic_under_seed(tmp_path):
    def backoffs(seed):
        policy = RestartPolicy(
            max_restarts=5, backoff_base_s=0.01, backoff_cap_s=1.0,
            seed=seed,
        )
        sup, rec = _boundary_sup(tmp_path, 3, policy, lambda: 0.0)
        assert sup.run().ok is True
        return [
            e.data["backoff_s"] for e in rec.events("restart")
            if e.data["action"] == "restart"
        ]

    a = backoffs(7)
    assert len(a) == 3
    # the jitter stream is seeded: same seed -> identical journaled
    # schedule; different seed -> different jitter
    assert backoffs(7) == a
    assert backoffs(8) != a
    # bounded exponential under jitter in [1, 1+jitter): each attempt's
    # floor (base*2^k) clears the previous attempt's ceiling
    assert a == sorted(a) and all(x > 0 for x in a)


# ------------------------------------------------- plan and health rule


def test_seeded_fault_plan_is_deterministic():
    a = FaultPlan.seeded(7, 30)
    b = FaultPlan.seeded(7, 30)
    assert len(a.faults) == 5
    sig = lambda plan: [
        (type(f).__name__, getattr(f, "step", getattr(f, "start_step", None)))
        for f in plan.faults
    ]
    assert sig(a) == sig(b)
    assert sig(FaultPlan.seeded(8, 30)) != sig(a)
    with pytest.raises(ValueError, match="steps"):
        FaultPlan.seeded(0, 1)


def test_snapshot_staleness_rule():
    rec = StepRecorder()
    mon = health.HealthMonitor(rec, rules=[health.snapshot_staleness()])
    # quiet: no snapshot yet, then cadence unknown (cold EMA), then fresh
    assert mon.evaluate(record=False)["status"] == "OK"
    rec.record("snapshot", step=4, cadence_s=0.0)
    assert mon.evaluate(record=False)["status"] == "OK"
    rec.record("snapshot", step=8, cadence_s=60.0)
    assert mon.evaluate(record=False)["status"] == "OK"
    # a snapshot event far older than 2x its own cadence: writer is dead
    rec.record_at("snapshot", time.time() - 10.0, step=12, cadence_s=1.0)
    verdict = mon.evaluate(record=False)
    assert verdict["status"] == "WARN"
    (finding,) = verdict["findings"]
    assert finding["rule"] == "snapshot_staleness"
    assert "stalled or dead" in finding["reason"]


# ------------------------------------------------------------------ CLI


def _service_cmd(*args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    cmd = [
        sys.executable, "-m", "mpi_grid_redistribute_tpu.service",
        "--backend", "numpy", "--grid", "2,2,2", "--n-local", "128",
    ] + list(args)
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=180
    )


def test_cli_breaker_exit_code():
    r = _service_cmd(
        "--steps", "8", "--supervise", "--inject-crash", "-1",
        "--max-restarts", "2", "--backoff-base", "0.01",
        "--backoff-cap", "0.02",
    )
    assert r.returncode == 3, r.stderr
    verdict = json.loads(r.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is False
    assert verdict["gave_up"] is True
    assert verdict["restarts"] == 2


def test_cli_hard_crash_then_resume_bit_identical(tmp_path):
    snaps = str(tmp_path / "snaps")
    common = ["--steps", "10", "--seed", "5", "--snapshot-every", "3"]
    # run 1: os._exit(13) at step 7, after committed snapshots at 3 and 6
    r = _service_cmd(
        *common, "--snapshot-dir", snaps, "--sync-snapshots",
        "--inject-crash", "7", "--hard-crash",
    )
    assert r.returncode == 13, r.stderr
    # run 2: resumes from the newest committed snapshot, finishes
    out = tmp_path / "resumed.npz"
    r = _service_cmd(
        *common, "--snapshot-dir", snaps, "--final-out", str(out),
    )
    assert r.returncode == 0, r.stderr
    # reference: uninterrupted run in a fresh snapshot dir
    ref_out = tmp_path / "ref.npz"
    r = _service_cmd(
        *common, "--snapshot-dir", str(tmp_path / "ref_snaps"),
        "--final-out", str(ref_out),
    )
    assert r.returncode == 0, r.stderr
    got, ref = np.load(out), np.load(ref_out)
    assert int(got["step"]) == int(ref["step"]) == 10
    for k in ("pos", "vel", "count"):
        assert got[k].tobytes() == ref[k].tobytes(), k
