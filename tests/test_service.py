"""service/: driver snapshot/restore, supervisor, fault matrix (ISSUE 6).

Everything runs the numpy backend at tiny sizes — the recovery logic
under test is backend-independent, and the CPU oracle keeps the whole
fault matrix inside the tier-1 budget. The jax path is covered by the
config8 soak bench and ``scripts/pod_smoke.py --kill-restore``.
"""

import dataclasses
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.service import (
    CrashFault,
    DriverConfig,
    FallbackFloodFault,
    FaultPlan,
    JournalShardLossFault,
    RestartPolicy,
    ServiceDriver,
    StallFault,
    Supervisor,
    TornSnapshotFault,
)
from mpi_grid_redistribute_tpu.telemetry import StepRecorder
from mpi_grid_redistribute_tpu.telemetry import health
from mpi_grid_redistribute_tpu.utils import checkpoint


def _cfg(tmp_path, **kw):
    base = dict(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=24,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
    )
    base.update(kw)
    return DriverConfig(**base)


def _reference_state(cfg):
    """The uninterrupted trajectory: same config, snapshots/journal off
    (neither may influence the state for restarts to be bit-exact)."""
    ref = ServiceDriver(
        dataclasses.replace(
            cfg, snapshot_every=0, snapshot_dir=None, journal_dir=None,
            watchdog_s=0.0,
        )
    )
    ref.init_state()
    state = ref.run()
    ref.close()
    return state


def _assert_bit_identical(a, b):
    for name, x, y in zip(("pos", "vel", "count"), a, b):
        assert x.tobytes() == y.tobytes(), f"{name} diverged"


# ------------------------------------------------------- driver basics


def test_driver_config_validation(tmp_path):
    with pytest.raises(ValueError, match="snapshot_dir"):
        ServiceDriver(_cfg(tmp_path, snapshot_dir=None))
    with pytest.raises(ValueError, match="keep_snapshots"):
        ServiceDriver(_cfg(tmp_path, keep_snapshots=1))


def test_snapshot_restore_bit_identical(tmp_path):
    cfg = _cfg(tmp_path, keep_snapshots=2)
    drv = ServiceDriver(cfg)
    drv.init_state()
    drv.run(max_steps=10)  # past two snapshot points (steps 4 and 8)
    drv.close()

    # pruning: only keep_snapshots newest survive on disk
    snaps = checkpoint.list_snapshots(cfg.snapshot_dir)
    assert len(snaps) == 2

    resumed = ServiceDriver(cfg)
    assert resumed.restore_latest() is True
    assert resumed.step == 8
    ev = resumed.recorder.last("restore")
    assert ev.data["what"] == "state" and ev.data["step"] == 8
    assert ev.data["snapshots_skipped"] == 0
    resumed.run()  # 8 -> 24 entirely from the restored snapshot
    resumed.close()
    _assert_bit_identical(resumed.state, _reference_state(cfg))


def test_restore_latest_without_snapshots(tmp_path):
    drv = ServiceDriver(_cfg(tmp_path, snapshot_every=0, snapshot_dir=None))
    assert drv.restore_latest() is False
    drv2 = ServiceDriver(_cfg(tmp_path))  # dir configured but empty
    assert drv2.restore_latest() is False


# ------------------------------------------------------- fault matrix


def _supervised(tmp_path, cfg, faults, max_restarts=5):
    rec = StepRecorder()
    sup = Supervisor(
        lambda: ServiceDriver(cfg, recorder=rec, faults=faults),
        policy=RestartPolicy(
            max_restarts=max_restarts, backoff_base_s=0.01,
            backoff_cap_s=0.02,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    return sup, rec


@pytest.mark.parametrize("kind", [
    "crash", "stall", "torn_snapshot", "journal_loss", "fallback_flood",
])
def test_fault_matrix(tmp_path, kind):
    extra = {}
    if kind == "crash":
        fault, restarts = CrashFault(9), 1
    elif kind == "stall":
        fault, restarts = StallFault(7, seconds=0.5), 1
        extra["watchdog_s"] = 0.2
    elif kind == "torn_snapshot":
        fault, restarts = TornSnapshotFault(snapshot_index=1), 1
    elif kind == "journal_loss":
        fault, restarts = JournalShardLossFault(6), 0
        extra["journal_dir"] = str(tmp_path / "journal")
    else:
        fault, restarts = FallbackFloodFault(start_step=1, steps=24), 0

    cfg = _cfg(tmp_path, **extra)
    sup, rec = _supervised(tmp_path, cfg, FaultPlan([fault]))
    verdict = sup.run()

    # every fault mode ends in a healthy, completed service
    assert verdict.ok is True, verdict
    assert verdict.gave_up is False
    assert verdict.restarts == restarts
    assert verdict.step == cfg.steps
    counts = rec.counts()
    assert counts.get("fault_injected") == 1
    assert counts.get("restart", 0) == restarts

    if kind in ("crash", "stall", "torn_snapshot"):
        # restarted from a snapshot: a journaled restore, then a resumed
        # trajectory byte-equal to the uninterrupted run
        restores = [
            e for e in rec.events("restore")
            if e.data.get("what") == "state"
        ]
        assert len(restores) == 1
        _assert_bit_identical(sup.driver.state, _reference_state(cfg))
        if kind == "torn_snapshot":
            # the corrupted newest snapshot was skipped, not loaded
            assert restores[0].data["snapshots_skipped"] >= 1
            assert restores[0].data["step"] == 4
    if kind == "stall":
        assert "StallError" in rec.last("restart").data["reason"]
    if kind == "journal_loss":
        # loss detected and healed: shard re-exported with the retained
        # window, restore(what=journal) journaled, file back on disk
        heals = [
            e for e in rec.events("restore")
            if e.data.get("what") == "journal"
        ]
        assert len(heals) == 1
        assert os.path.exists(sup.driver.journal_path)
        _assert_bit_identical(sup.driver.state, _reference_state(cfg))
    if kind == "fallback_flood":
        # graceful degrade: exactly one engine -> planar transition,
        # pinned for the rest of the run (never flaps back)
        degrades = rec.events("degrade")
        assert len(degrades) == 1
        assert degrades[0].data["to"] == "planar"
        assert sup.driver.degraded is True
        assert sup.driver.engine == "planar"
        assert verdict.health == "WARN"  # rule still firing, not ALERT


def test_crash_loop_trips_circuit_breaker(tmp_path):
    cfg = _cfg(tmp_path, steps=12)
    sup, rec = _supervised(
        tmp_path, cfg, FaultPlan([CrashFault(None)]), max_restarts=3
    )
    verdict = sup.run()
    assert verdict.ok is False
    assert verdict.gave_up is True
    assert verdict.restarts == 3
    assert "circuit breaker" in verdict.reason
    actions = [e.data["action"] for e in rec.events("restart")]
    assert actions == ["restart"] * 3 + ["give_up"]
    # backoff grows (bounded exponential; jitter keeps it monotone here)
    backoffs = [
        e.data["backoff_s"] for e in rec.events("restart")
        if e.data["action"] == "restart"
    ]
    assert all(b > 0 for b in backoffs)


def test_healthz_alert_forces_restart(tmp_path):
    # a clean exit with a red /healthz is a failure: the supervisor must
    # restart, and a deterministic alert must end at the breaker
    always_red = health.HealthRule(
        "always_red", health.ALERT, lambda rec: "synthetic alert"
    )
    cfg = _cfg(tmp_path, steps=6, snapshot_every=0, snapshot_dir=None)
    rec = StepRecorder()
    sup = Supervisor(
        lambda: ServiceDriver(
            cfg, recorder=rec,
            monitor=health.HealthMonitor(rec, rules=[always_red]),
        ),
        policy=RestartPolicy(max_restarts=2, backoff_base_s=0.01),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    verdict = sup.run()
    assert verdict.ok is False and verdict.gave_up is True
    assert verdict.health == "ALERT"
    assert "healthz 503" in verdict.reason
    restart = [
        e for e in rec.events("restart") if e.data["action"] == "restart"
    ]
    assert all("healthz 503" in e.data["reason"] for e in restart)


# ------------------------------------------------- plan and health rule


def test_seeded_fault_plan_is_deterministic():
    a = FaultPlan.seeded(7, 30)
    b = FaultPlan.seeded(7, 30)
    assert len(a.faults) == 5
    sig = lambda plan: [
        (type(f).__name__, getattr(f, "step", getattr(f, "start_step", None)))
        for f in plan.faults
    ]
    assert sig(a) == sig(b)
    assert sig(FaultPlan.seeded(8, 30)) != sig(a)
    with pytest.raises(ValueError, match="steps"):
        FaultPlan.seeded(0, 1)


def test_snapshot_staleness_rule():
    rec = StepRecorder()
    mon = health.HealthMonitor(rec, rules=[health.snapshot_staleness()])
    # quiet: no snapshot yet, then cadence unknown (cold EMA), then fresh
    assert mon.evaluate(record=False)["status"] == "OK"
    rec.record("snapshot", step=4, cadence_s=0.0)
    assert mon.evaluate(record=False)["status"] == "OK"
    rec.record("snapshot", step=8, cadence_s=60.0)
    assert mon.evaluate(record=False)["status"] == "OK"
    # a snapshot event far older than 2x its own cadence: writer is dead
    rec.record_at("snapshot", time.time() - 10.0, step=12, cadence_s=1.0)
    verdict = mon.evaluate(record=False)
    assert verdict["status"] == "WARN"
    (finding,) = verdict["findings"]
    assert finding["rule"] == "snapshot_staleness"
    assert "stalled or dead" in finding["reason"]


# ------------------------------------------------------------------ CLI


def _service_cmd(*args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    cmd = [
        sys.executable, "-m", "mpi_grid_redistribute_tpu.service",
        "--backend", "numpy", "--grid", "2,2,2", "--n-local", "128",
    ] + list(args)
    return subprocess.run(
        cmd, env=env, capture_output=True, text=True, timeout=180
    )


def test_cli_breaker_exit_code():
    r = _service_cmd(
        "--steps", "8", "--supervise", "--inject-crash", "-1",
        "--max-restarts", "2", "--backoff-base", "0.01",
        "--backoff-cap", "0.02",
    )
    assert r.returncode == 3, r.stderr
    verdict = json.loads(r.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is False
    assert verdict["gave_up"] is True
    assert verdict["restarts"] == 2


def test_cli_hard_crash_then_resume_bit_identical(tmp_path):
    snaps = str(tmp_path / "snaps")
    common = ["--steps", "10", "--seed", "5", "--snapshot-every", "3"]
    # run 1: os._exit(13) at step 7, after committed snapshots at 3 and 6
    r = _service_cmd(
        *common, "--snapshot-dir", snaps, "--sync-snapshots",
        "--inject-crash", "7", "--hard-crash",
    )
    assert r.returncode == 13, r.stderr
    # run 2: resumes from the newest committed snapshot, finishes
    out = tmp_path / "resumed.npz"
    r = _service_cmd(
        *common, "--snapshot-dir", snaps, "--final-out", str(out),
    )
    assert r.returncode == 0, r.stderr
    # reference: uninterrupted run in a fresh snapshot dir
    ref_out = tmp_path / "ref.npz"
    r = _service_cmd(
        *common, "--snapshot-dir", str(tmp_path / "ref_snaps"),
        "--final-out", str(ref_out),
    )
    assert r.returncode == 0, r.stderr
    got, ref = np.load(out), np.load(ref_out)
    assert int(got["step"]) == int(ref["step"]) == 10
    for k in ("pos", "vel", "count"):
        assert got[k].tobytes() == ref[k].tobytes(), k
