"""Fused drift+wrap+bin kernel (ops/pallas_driftbin.py) vs the exact
XLA chain the nbody loop + Dev==1 vrank engine execute — bit level,
interpret mode on CPU, including hostile inputs (out-of-domain, huge,
negative, dead rows)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import pallas_driftbin


def _mk_state(r, K, V, n, scale=1.0):
    m = V * n
    pos = (r.random((3, m), dtype=np.float32) * 2 - 0.5) * scale
    vel = (r.random((3, m), dtype=np.float32) - 0.5).astype(np.float32)
    alive = (r.random((m,)) < 0.9).astype(np.int32)
    flat = np.concatenate(
        [pos.view(np.int32), vel.view(np.int32), alive[None, :]], axis=0
    )
    assert flat.shape[0] == K
    return flat


@pytest.mark.parametrize("grid_shape", [(2, 2, 2), (4, 2, 1)])
@pytest.mark.parametrize("scale", [1.0, 50.0])
def test_driftbin_kernel_matches_xla_twin(rng, _devices, grid_shape, scale):
    K, V, n = 7, int(np.prod(grid_shape)), 2048
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid(grid_shape)
    r = np.random.default_rng(hash((grid_shape, scale)) % 2**32)
    flat = _mk_state(r, K, V, n, scale=scale)
    # the twin must run UNDER JIT: LLVM contracts the drift mul+add
    # into an fma both in the jitted twin and in the jitted interpret
    # kernel (bit-identical); on TPU neither contracts (measured) —
    # see the kernel's FMA note
    f_x, k_x = jax.jit(
        lambda f: pallas_driftbin.drift_wrap_bin_xla(
            f, 0.05, domain, grid, V, V
        )
    )(jnp.asarray(flat))
    f_p, k_p = pallas_driftbin.drift_wrap_bin(
        jnp.asarray(flat), 0.05, domain, grid, V, V,
        interpret=True, w=1024,
    )
    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_x))
    np.testing.assert_array_equal(np.asarray(k_p), np.asarray(k_x))


def test_driftbin_mixed_periodic_and_open(rng, _devices):
    K, V, n = 7, 4, 1024
    domain = Domain(
        (0.0, -2.0, 1.0), (1.0, 2.0, 3.0), periodic=(True, False, True)
    )
    grid = ProcessGrid((2, 2, 1))
    r = np.random.default_rng(5)
    flat = _mk_state(r, K, V, n, scale=3.0)
    f_x, k_x = jax.jit(
        lambda f: pallas_driftbin.drift_wrap_bin_xla(
            f, 0.1, domain, grid, V, V
        )
    )(jnp.asarray(flat))
    f_p, k_p = pallas_driftbin.drift_wrap_bin(
        jnp.asarray(flat), 0.1, domain, grid, V, V,
        interpret=True, w=1024,
    )
    np.testing.assert_array_equal(np.asarray(f_p), np.asarray(f_x))
    np.testing.assert_array_equal(np.asarray(k_p), np.asarray(k_x))


def test_driftbin_fallback_contract(rng, _devices):
    """Non-pow2 periodic extent and indivisible n fall back to the XLA
    twin (same object semantics, no kernel)."""
    K, V, n = 7, 2, 1000  # n has no candidate width divisor
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid((2, 1, 1))
    r = np.random.default_rng(9)
    flat = _mk_state(r, K, V, n)
    f_a, k_a = pallas_driftbin.drift_wrap_bin(
        jnp.asarray(flat), 0.05, domain, grid, V, V, interpret=True
    )
    f_x, k_x = pallas_driftbin.drift_wrap_bin_xla(
        jnp.asarray(flat), 0.05, domain, grid, V, V
    )
    np.testing.assert_array_equal(np.asarray(f_a), np.asarray(f_x))
    np.testing.assert_array_equal(np.asarray(k_a), np.asarray(k_x))
    # non-pow2 extent: supports() must refuse
    dom2 = Domain(0.0, 3.0, periodic=True)
    assert not pallas_driftbin.supports(dom2, 2, 2048, K)
    assert pallas_driftbin.supports(domain, 2, 2048, K)
