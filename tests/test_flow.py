"""Grid observatory (telemetry/flow.py, health.py, traceview.py).

Three layers, each tested against hand math or the engines themselves:

* flow — the [R, R] matrix's row sums must equal ``sent`` and column
  sums ``received`` EXACTLY on every engine path (sends are
  receiver-granted, so both sides agree by construction), and its
  capture must add zero host callbacks to the scanned step (jaxpr
  assertion).
* health — declarative rules over journal events; synthetic event
  sequences drive each rule and the alert/callback/dedup contract.
* traceview — output must be valid Chrome-trace JSON (every event
  carries ``ph``/``pid``, non-metadata events carry ``ts``).
"""

import json

import numpy as np
import pytest

import jax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.models import nbody
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
from mpi_grid_redistribute_tpu.parallel.migrate import MigrateStats
from mpi_grid_redistribute_tpu.telemetry import (
    FlowAccumulator,
    HealthMonitor,
    StepRecorder,
    default_rules,
    flow_matrix_of,
    record_flow_snapshot,
    record_migrate_steps,
    to_chrome_trace,
    write_trace,
)
from mpi_grid_redistribute_tpu.telemetry import flow as flow_lib
from mpi_grid_redistribute_tpu.telemetry import health as health_lib

DOMAIN = Domain(0.0, 1.0, periodic=True)


# ------------------------------------------------------------ hand math


def _stats2(flow_steps, population):
    """Build a 2-rank step-stacked MigrateStats from hand flow matrices."""
    f = np.asarray(flow_steps, np.int32)  # [S, 2, 2]
    return MigrateStats(
        sent=f.sum(axis=2),
        received=f.sum(axis=1),
        population=np.asarray(population, np.int32),
        backlog=np.zeros_like(f.sum(axis=2)),
        dropped_recv=np.zeros_like(f.sum(axis=2)),
        flow=f,
    )


def test_flow_accumulator_hand_math():
    # step 1: rank0 sends 3 to rank1; step 2: 1 back, 5 forward
    stats = _stats2(
        [[[0, 3], [0, 0]], [[0, 5], [1, 0]]],
        [[7, 3], [4, 6]],
    )
    acc = FlowAccumulator(ema_alpha=0.5)
    acc.update(stats)
    np.testing.assert_array_equal(
        acc.cumulative, np.asarray([[0, 8], [1, 0]])
    )
    # EMA seeded with step 1, then 0.5-blended with step 2
    np.testing.assert_allclose(
        acc.ema, np.asarray([[0.0, 4.0], [0.5, 0.0]])
    )
    assert acc.steps == 2
    # imbalance from the LAST step's population: max/mean of [4, 6]
    assert acc.imbalance == pytest.approx(6.0 / 5.0)
    # hot pairs: cumulative, descending, deterministic
    assert acc.top_pairs(k=5) == [(0, 1, 8), (1, 0, 1)]
    snap = acc.snapshot(k=1)
    assert snap["moved_rows_total"] == 9
    assert snap["n_ranks"] == 2
    assert snap["top_pairs"] == [[0, 1, 8]]
    json.dumps(snap)  # journal-able


def test_imbalance_gauge_empty_and_partial_population():
    """Hand math for the zero/partial-population edges: an ALL-empty
    system is perfectly balanced (1.0, not the old 0.0 never-fed
    sentinel), and a SOME-ranks-empty population still reads max/mean —
    the empty ranks push the ratio UP, they don't reset it."""
    acc = FlowAccumulator()
    assert acc.imbalance == 0.0  # never fed: the 0.0 sentinel stands
    acc.update(np.zeros((2, 2), np.int64), population=[0, 0])
    assert acc.imbalance == 1.0  # all-empty = balanced
    assert acc.snapshot()["population"] == [0, 0]
    # partial: [0, 6] -> mean 3, max 6 -> 2.0 (NOT 1.0, NOT 0.0)
    acc.update(np.zeros((2, 2), np.int64), population=[0, 6])
    assert acc.imbalance == pytest.approx(2.0)
    assert acc.snapshot()["population"] == [0, 6]
    # [S, R] population: only the LAST step's gauge sticks
    acc.update(
        np.zeros((2, 2, 2), np.int64), population=[[9, 1], [4, 4]]
    )
    assert acc.imbalance == pytest.approx(1.0)
    assert acc.snapshot()["population"] == [4, 4]
    with pytest.raises(ValueError, match="non-negative"):
        acc.update(np.zeros((2, 2), np.int64), population=[3, -1])


def test_snapshot_population_none_until_fed():
    acc = FlowAccumulator()
    acc.update(np.asarray([[0, 2], [1, 0]], np.int64))  # raw matrix,
    # no population gauge rides along
    snap = acc.snapshot()
    assert snap["population"] is None
    assert snap["imbalance"] == 0.0
    json.dumps(snap)


def test_top_pairs_ordering_diag_and_zeros():
    m = np.asarray([[9, 4, 0], [4, 9, 2], [0, 0, 9]])
    # diagonal excluded by default; tie (0,1) vs (1,0) breaks toward the
    # lower (src, dst); zero links never reported even when k allows
    assert flow_lib.top_pairs(m, k=10) == [
        (0, 1, 4), (1, 0, 4), (1, 2, 2)
    ]
    assert flow_lib.top_pairs(m, k=1, include_diag=True) == [(0, 0, 9)]
    with pytest.raises(ValueError):
        flow_lib.top_pairs(np.zeros((2, 3)))


def test_flow_matrix_of_validation():
    stats = _stats2([[[0, 1], [2, 0]]], [[3, 3]])
    m = flow_matrix_of(stats)
    assert m.shape == (1, 2, 2) and m.dtype == np.int64
    # hand-built fixture without the flow leaf is a named error
    with pytest.raises(ValueError, match="flow is None"):
        flow_matrix_of(stats._replace(flow=None))
    with pytest.raises(TypeError):
        flow_matrix_of(object())
    acc = FlowAccumulator(n_ranks=4)
    with pytest.raises(ValueError, match="built for 4 ranks"):
        acc.update(stats)


def test_link_report_per_link_bw():
    m = np.asarray([[0.0, 100.0], [25.0, 0.0]])
    rep = flow_lib.link_report(m, row_bytes=28, step_seconds=1e-3)
    assert rep["domain"] == "ici"
    top = rep["links"][0]
    assert (top["src"], top["dst"]) == (0, 1)
    assert top["bytes_per_step"] == pytest.approx(2800.0)
    assert top["bytes_per_sec"] == pytest.approx(2.8e6)
    assert top["bw_util"] == pytest.approx(
        2.8e6 / rep["link_roof_bytes_per_sec"]
    )
    # without step_seconds the rate fields stay None, never guessed
    rep2 = flow_lib.link_report(m, row_bytes=28)
    assert rep2["links"][0]["bw_util"] is None


# ------------------------------------- engine exactness (CPU mesh, 8 dev)


def _run_loop(grid_shape, vgrid, n_steps, rng):
    grid = ProcessGrid(grid_shape)
    R = grid.nranks
    n_local = 64
    n = R * n_local
    mesh = mesh_lib.make_mesh(grid)
    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.6 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    alive = rng.random(n) > 0.125
    cfg = nbody.DriftConfig(
        domain=DOMAIN, grid=grid, dt=0.07, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, n_steps, vgrid=vgrid)
    _, _, _, stats = jax.tree.map(np.asarray, loop(pos, vel, alive))
    return stats


@pytest.mark.parametrize("grid_shape", [(2, 2, 2), (4, 2, 1)])
def test_flow_row_col_sums_exact_multidevice(grid_shape, rng, _devices):
    """8-device shard_map path: flow rows == sent, columns == received,
    bit-exact, every step."""
    stats = _run_loop(grid_shape, None, 5, rng)
    m = flow_matrix_of(stats)
    np.testing.assert_array_equal(m.sum(axis=2), np.asarray(stats.sent))
    np.testing.assert_array_equal(
        m.sum(axis=1), np.asarray(stats.received)
    )
    # movers only: the diagonal is structurally zero on the migrate path
    assert np.einsum("sii->s", m).sum() == 0


def test_flow_row_col_sums_exact_vranks(rng, _devices):
    """Vranks twin (2 devices x 8 vranks each): same exactness through
    the remote-overlay flow rows (local ``allowed`` table + remote
    granted-send rows stitched at the device's vrank offset)."""
    vgrid = ProcessGrid((2, 2, 2))
    dev_grid = ProcessGrid((2, 1, 1))
    mesh = mesh_lib.make_mesh(dev_grid)
    n_local = 64
    R_total = mesh.size * vgrid.nranks  # 16 global vranks
    n = R_total * n_local
    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.6 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    alive = rng.random(n) > 0.125
    cfg = nbody.DriftConfig(
        domain=DOMAIN, grid=dev_grid, dt=0.07, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, 4, vgrid=vgrid)
    stats = jax.tree.map(np.asarray, loop(pos, vel, alive))[3]
    m = flow_matrix_of(stats)
    assert m.shape == (4, R_total, R_total)
    np.testing.assert_array_equal(m.sum(axis=2), np.asarray(stats.sent))
    np.testing.assert_array_equal(
        m.sum(axis=1), np.asarray(stats.received)
    )


_HOST_SYNC_PRIMS = (
    "callback", "infeed", "outfeed", "host", "debug_print",
)


def _sub_jaxprs(params):
    """Yield every Jaxpr nested in an eqn's params (scan/cond/shard_map
    bodies), whatever container they ride in."""
    stack = list(params.values())
    while stack:
        x = stack.pop()
        if isinstance(x, (list, tuple)):
            stack.extend(x)
        elif hasattr(x, "jaxpr"):  # ClosedJaxpr
            yield x.jaxpr
        elif hasattr(x, "eqns"):  # raw Jaxpr
            yield x


def _assert_no_host_prims(jaxpr, seen):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        seen.add(name)
        assert not any(tok in name for tok in _HOST_SYNC_PRIMS), (
            f"host-syncing primitive {name!r} inside the scanned step — "
            "flow capture must stay pure device work"
        )
        for sub in _sub_jaxprs(eqn.params):
            _assert_no_host_prims(sub, seen)


def test_flow_capture_adds_no_host_sync(rng, _devices):
    """Jit-trace assertion: the whole scanned migrate loop — flow leaf
    included — lowers to pure device ops (no callbacks/infeed/outfeed)."""
    grid = ProcessGrid((2, 2, 2))
    n_local = 32
    n = grid.nranks * n_local
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=DOMAIN, grid=grid, dt=0.07, capacity=n_local,
        n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, 3)
    # pre-convert to the planar flat layout: under make_jaxpr the inputs
    # are tracers, so the loop's numpy-side auto-conversion cannot run
    jaxpr = jax.make_jaxpr(loop)(
        nbody.rows_to_planar(np.zeros((n, 3), np.float32), mesh.size),
        nbody.rows_to_planar(np.zeros((n, 3), np.float32), mesh.size),
        np.ones((n,), bool),
    )
    seen = set()
    _assert_no_host_prims(jaxpr.jaxpr, seen)
    assert "scan" in seen  # we really walked the step loop


# --------------------------------------------------------------- health


def _backlog_events(rec, backlogs):
    for s, b in enumerate(backlogs):
        rec.record(
            "migrate_step", step=s, sent=10, received=10, backlog=b,
            dropped_recv=0, population=100,
        )


def test_backlog_growth_alert_and_callback():
    rec = StepRecorder()
    fired = []
    mon = HealthMonitor(rec, on_alert=fired.append)
    _backlog_events(rec, [0, 5, 9, 14, 20])
    verdict = mon.evaluate()
    assert verdict["status"] == health_lib.ALERT
    assert [f["rule"] for f in verdict["findings"]] == ["backlog_growth"]
    assert "5 -> 20" in verdict["findings"][0]["reason"]
    # callback fired once, and the alert landed in the same ring
    assert len(fired) == 1 and fired[0].rule == "backlog_growth"
    alerts = rec.events("alert")
    assert len(alerts) == 1
    assert alerts[0].data["rule"] == "backlog_growth"
    # dedup: re-evaluating the same evidence must not re-fire
    verdict2 = mon.evaluate()
    assert verdict2["status"] == health_lib.ALERT  # still alerting...
    assert len(fired) == 1 and len(rec.events("alert")) == 1  # ...once
    # new evidence re-arms the rule
    _backlog_events(rec, [22, 25, 29, 31])
    mon.evaluate()
    assert len(fired) == 2


def test_backlog_growth_requires_monotone_and_nonzero():
    rec = StepRecorder()
    mon = HealthMonitor(rec)
    # dips mid-window: healthy retry behavior, no alert
    _backlog_events(rec, [0, 5, 3, 6, 4])
    assert mon.evaluate()["status"] == health_lib.OK
    # drains to zero at the end: no alert either
    rec2 = StepRecorder()
    _backlog_events(rec2, [1, 2, 3, 0])
    assert HealthMonitor(rec2).evaluate()["status"] == health_lib.OK


def test_dropped_rows_and_imbalance_rules():
    rec = StepRecorder()
    rec.record(
        "migrate_step", step=0, sent=5, received=4, backlog=0,
        dropped_recv=1, population=10,
    )
    v = HealthMonitor(rec).evaluate()
    assert v["status"] == health_lib.ALERT
    assert any(f["rule"] == "dropped_rows" for f in v["findings"])

    rec2 = StepRecorder()
    acc = FlowAccumulator()
    # max/mean = 90/30 = 3.0x > the 2.0x threshold
    acc.update(
        np.zeros((4, 4), np.int64),
        population=np.asarray([90, 10, 10, 10]),
    )
    record_flow_snapshot(rec2, acc)
    v2 = HealthMonitor(rec2).evaluate()
    assert v2["status"] == health_lib.WARN
    assert any(f["rule"] == "imbalance_ratio" for f in v2["findings"])


def test_step_time_spike_rule():
    rec = StepRecorder()
    mon = HealthMonitor(rec)
    for _ in range(6):
        mon.note_step_time(0.010)
    assert mon.evaluate()["status"] == health_lib.OK
    mon.note_step_time(0.200)  # 20x the EMA
    v = mon.evaluate()
    assert v["status"] == health_lib.WARN
    assert any(f["rule"] == "step_time_spike" for f in v["findings"])


def test_default_rules_cover_issue_list():
    names = {r.name for r in default_rules()}
    assert names >= {
        "backlog_growth", "dropped_rows", "capacity_grow_frequency",
        "imbalance_ratio", "step_time_spike",
    }


# ------------------------------------------------------------- traceview


def _valid_chrome_trace(trace):
    assert isinstance(trace["traceEvents"], list)
    for e in trace["traceEvents"]:
        assert "ph" in e and "pid" in e, e
        if e["ph"] != "M":  # metadata events carry no timestamp
            assert isinstance(e["ts"], (int, float)), e
        if e["ph"] == "X":
            assert e["dur"] >= 0
    json.loads(json.dumps(trace))  # serializable round trip


def test_chrome_trace_schema(tmp_path):
    from mpi_grid_redistribute_tpu.telemetry.phases import PhaseTiming

    rec = StepRecorder()
    rec.record("capacity_grow", old=64, new=128)
    _backlog_events(rec, [0, 3, 7, 9])  # monotone window -> alert event
    mon = HealthMonitor(rec)
    assert mon.evaluate()["status"] == health_lib.ALERT
    acc = FlowAccumulator()
    acc.update(np.asarray([[0, 2], [1, 0]]))
    record_flow_snapshot(rec, acc)
    timings = [
        PhaseTiming("bin", 0.010, 0.010, 1024, 0.001),
        PhaseTiming("sort", 0.030, 0.020, None, None),
    ]
    trace = to_chrome_trace(rec, phase_timings=timings, step_seconds=2e-3)
    _valid_chrome_trace(trace)
    evs = trace["traceEvents"]
    by_ph = {}
    for e in evs:
        by_ph.setdefault(e["ph"], []).append(e)
    # instants cover every journal kind, alerts included
    kinds = {e["name"] for e in by_ph["i"]}
    assert kinds >= {"capacity_grow", "migrate_step", "alert",
                     "flow_snapshot"}
    # duration lane laid end to end in microseconds
    spans = by_ph["X"]
    assert [s["name"] for s in spans] == ["bin", "sort"]
    assert spans[0]["ts"] == 0 and spans[0]["dur"] == pytest.approx(1e4)
    assert spans[1]["ts"] == pytest.approx(1e4)
    assert spans[0]["args"]["x_roofline"] == pytest.approx(10.0)
    # counter track uses the measured synthetic step time
    counters = [e for e in by_ph["C"] if e["name"] == "backlog"]
    assert [c["ts"] for c in counters] == [0.0, 2e3, 4e3, 6e3]
    assert [c["args"]["backlog"] for c in counters] == [0, 3, 7, 9]
    # file round trip
    path = tmp_path / "trace.json"
    n = write_trace(str(path), rec, phase_timings=timings)
    reloaded = json.loads(path.read_text())
    assert len(reloaded["traceEvents"]) == n
    _valid_chrome_trace(reloaded)


def test_trace_export_cli(tmp_path):
    import subprocess
    import sys as _sys

    rec = StepRecorder()
    _backlog_events(rec, [0, 1])
    jsonl = tmp_path / "journal.jsonl"
    rec.to_jsonl(str(jsonl))
    out = tmp_path / "trace.json"
    proc = subprocess.run(
        [_sys.executable, "scripts/trace_export.py",
         "--journal", str(jsonl), "--out", str(out)],
        capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    _valid_chrome_trace(json.loads(out.read_text()))


# ------------------------------------------------------- public API + bench


def test_rd_flow_health_perfetto(tmp_path, rng, _devices):
    from mpi_grid_redistribute_tpu import GridRedistribute

    pos = rng.random((1024, 3), dtype=np.float32)
    with GridRedistribute(lo=0.0, hi=1.0, grid=(2, 2, 2),
                          capacity_factor=4.0) as rd:
        with pytest.raises(RuntimeError):
            rd.flow()
        res = rd.redistribute(pos)
        fl = rd.flow(k=3)
        m = np.asarray(fl["matrix"])
        send = np.asarray(res.stats.send_counts)
        np.testing.assert_array_equal(m, send.astype(np.int64))
        assert fl["imbalance"] >= 1.0
        assert len(fl["hot_links"]) <= 3
        # flow() journaled a snapshot; health sees a balanced exchange
        assert rd.telemetry.counts().get("flow_snapshot") == 1
        assert rd.health()["status"] == "OK"
        path = tmp_path / "api_trace.json"
        n = rd.to_perfetto(str(path))
        assert n > 0
        _valid_chrome_trace(json.loads(path.read_text()))


def test_config4_emits_health_and_flow(monkeypatch):
    from mpi_grid_redistribute_tpu.bench import config4_drift

    monkeypatch.setenv("BENCH_SCALE", "0.004")
    out = config4_drift.run(steps=16)
    assert out["health"]["status"] == "OK"
    assert out["flow"]["n_ranks"] == 8
    assert out["report"]["links"]["links"], "per-link section missing"
    json.dumps(out)


def test_record_migrate_steps_validates_and_rank_totals():
    good = _stats2([[[0, 3], [1, 0]]], [[5, 5]])
    rec = StepRecorder()
    record_migrate_steps(rec, good, rank_totals=True)
    ev = rec.last("migrate_step")
    assert ev.data["sent_per_rank"] == [3, 1]
    assert ev.data["received_per_rank"] == [1, 3]
    assert ev.data["population_per_rank"] == [5, 5]
    bad = good._replace(backlog=np.zeros((1, 3), np.int32))
    with pytest.raises(ValueError, match="shape-congruent"):
        record_migrate_steps(StepRecorder(), bad)


# ------------------------------------------- steady-state overhead budget


def test_recorder_monitor_overhead_under_2pct(rng, _devices):
    """Acceptance: journaling + health evaluation add <= 2% to the
    config1-style steady-state step (min-of-k protocol; the observatory
    is host-side bookkeeping outside the compiled loop, so its cost must
    be noise against ms-scale device steps)."""
    import time

    grid = ProcessGrid((2, 2, 2))
    n_local = 2048
    n = grid.nranks * n_local
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=DOMAIN, grid=grid, dt=0.02, capacity=n_local // 4,
        n_local=n_local,
    )
    steps = 32  # amortize the one stats read-back per loop boundary
    loop = nbody.make_migrate_loop(cfg, mesh, steps)
    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.2 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    alive = np.ones((n,), bool)
    jax.block_until_ready(loop(pos, vel, alive))  # compile

    def sample(observe):
        rec = StepRecorder()
        mon = HealthMonitor(rec)
        t0 = time.perf_counter()
        out = loop(pos, vel, alive)
        jax.block_until_ready(out)
        # every bench driver already reads the stats pytree to the host
        # for its report — that fetch is the shared baseline, not
        # observatory overhead
        stats_host = jax.tree.map(np.asarray, out[3])
        if observe:
            record_migrate_steps(rec, stats_host, rank_totals=True)
            acc = FlowAccumulator()
            acc.update(stats_host)
            record_flow_snapshot(rec, acc)
            mon.note_step_time((time.perf_counter() - t0) / steps)
            mon.evaluate()
        return time.perf_counter() - t0

    # noise protocol: inside a full-suite run the loop itself wobbles
    # by several ms (allocator/scheduler state left by hundreds of
    # prior tests) — far above the sub-ms observe path under test, so
    # a min-of-k difference is noise-dominated. Each observed sample
    # is paired with an immediately preceding base sample (the pair
    # shares the slow drift) and the MEDIAN pair delta rejects the
    # occasional scheduler spike. GC is held off so a collection over
    # the suite's accumulated heap is not billed to the observe path.
    import gc

    def batch_median():
        deltas = []
        gc.collect()
        gc.disable()
        try:
            for k in range(9):
                # alternate which leg runs first: the two legs of a pair
                # share the slow drift, but the SECOND leg systematically
                # pays any residual warm-up/degradation trend —
                # alternating puts that bias on each leg equally often,
                # so the median of the signed deltas cancels it instead
                # of billing it to the observe path
                if k % 2:
                    o = sample(True)
                    b = sample(False)
                else:
                    b = sample(False)
                    o = sample(True)
                deltas.append((o - b) / b)
        finally:
            gc.enable()
        return float(np.median(deltas)), deltas

    overhead, deltas = batch_median()
    if overhead > 0.02:
        # a real regression reproduces; a scheduler-noise excursion does
        # not — confirm before failing (keeps the gate's false-failure
        # rate at p^2 without loosening the 2% acceptance itself)
        overhead2, deltas2 = batch_median()
        if overhead2 < overhead:
            overhead, deltas = overhead2, deltas2
    assert overhead <= 0.02, (
        f"observatory overhead {overhead:.1%} > 2% (median of "
        f"{len(deltas)} paired samples, {steps}-step loop, best of two "
        f"batches; deltas {[f'{d:.1%}' for d in deltas]})"
    )
