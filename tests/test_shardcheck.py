"""shardcheck: the replication abstract interpreter (analysis/shardcheck.py).

Per-rule coverage mirroring test_progcheck: one minimal VIOLATING
fixture program and one CLEAN twin for each of S001-S004, the lattice
edge cases the interpreter must get right (while_loop carry fixpoint,
nested pjit-inside-cond, ppermute full-rotation vs identity vs partial
perms), the wire-attribution hand-math and its drift gate, the shared
suppression/measurement baseline machinery, and the repo-wide gate —
every registered program runs clean under S001-S004 against the
committed wire_attribution baseline.

Fixture programs are spiked single-purpose shard_map bodies on a flat
8-device ('x',) mesh or a (4, 2) ('x', 'y') mesh — small enough to
read, real enough that the traced jaxpr carries genuine collectives.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mpi_grid_redistribute_tpu.compat import shard_map
from mpi_grid_redistribute_tpu.analysis import rules_jaxpr, rules_shard
from mpi_grid_redistribute_tpu.analysis import shardcheck as sc
from mpi_grid_redistribute_tpu.analysis.baseline import (
    load_baseline,
    load_progprofile_baseline,
    load_wire_baseline,
    split_baselined,
    write_baseline,
    write_progprofile_baseline,
    write_wire_baseline,
)
from mpi_grid_redistribute_tpu.analysis.progcheck import ProgramSpec
from mpi_grid_redistribute_tpu.analysis.sarif import merge_sarif, to_sarif
from mpi_grid_redistribute_tpu.analysis.shardcheck import (
    S_RULE_IDS,
    ShardFinding,
    analyze,
    main as shardcheck_main,
    run_shardcheck,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

AXES = ("x",)
AXES2 = ("x", "y")


def _mesh():
    return Mesh(np.array(jax.devices()[:8]), AXES)


def _mesh2(names=AXES2):
    return Mesh(np.array(jax.devices()[:8]).reshape(4, 2), names)


def _spec(name, fn=None, args=(), **kw):
    return ProgramSpec(name=name, build=lambda: (fn, args), **kw)


def _trace(fn, *args):
    return jax.make_jaxpr(fn)(*args)


def _x84():
    return jnp.zeros((8, 4), jnp.float32)


# ----------------------------------------------------- lattice basics


def test_replicated_in_spec_stays_replicated(_devices):
    """A P() in_spec is a broadcast: the body sees the same value on
    every rank, and emitting it back through P() is clean."""
    mesh = _mesh()

    def f(s):
        return shard_map(
            lambda v: v * 2.0, mesh=mesh, in_specs=P(), out_specs=P()
        )(s)

    report = analyze(_trace(f, jnp.float32(3.0)))
    assert report.escapes == []
    assert report.out_vary == [frozenset()]


def test_partitioned_input_varies_and_psum_clears(_devices):
    mesh = _mesh()

    def f(x):
        return shard_map(
            lambda v: lax.psum(jnp.sum(v), AXES),
            mesh=mesh, in_specs=P("x"), out_specs=P(),
        )(x)

    report = analyze(_trace(f, _x84()))
    assert report.escapes == []  # psum makes the P() out legitimate
    # and the full reduction of a varying operand is NOT redundant
    assert report.reductions == []


def test_axis_index_varies_on_its_axis(_devices):
    mesh = _mesh()

    def f(x):
        return shard_map(
            lambda v: v + lax.axis_index("x").astype(jnp.float32),
            mesh=mesh, in_specs=P(), out_specs=P("x"),
        )(x)

    report = analyze(_trace(f, jnp.zeros((8,), jnp.float32)))
    # varying over exactly 'x', and the P('x') out_spec absorbs it
    assert report.escapes == []


# -------------------------------------- S001: declared-replicated outs


def test_s001_fires_on_varying_replicated_out(_devices):
    mesh = _mesh()

    def f(x):
        return shard_map(
            lambda v: jnp.sum(v),  # shard-local sum, no reduction
            mesh=mesh, in_specs=P("x"), out_specs=P(),
        )(x)

    spec = _spec("spiked_s001", f, (_x84(),))
    report = analyze(sc.trace_program(spec))
    findings = rules_shard.check_s001(report, spec)
    assert [f.rule for f in findings] == ["S001"]
    assert "declared fully replicated" in findings[0].message
    assert "'x'" in findings[0].message


def test_s001_clean_with_reduction_before_boundary(_devices):
    mesh = _mesh()

    def f(x):
        return shard_map(
            lambda v: lax.pmin(jnp.min(v), AXES),
            mesh=mesh, in_specs=P("x"), out_specs=P(),
        )(x)

    spec = _spec("clean_s001", f, (_x84(),))
    assert rules_shard.check_s001(analyze(sc.trace_program(spec)), spec) == []


# ------------------------------------------ S002: redundant collectives


def test_s002_fires_on_redundant_psum(_devices):
    """The spiked fixture the ISSUE demands: a psum of a psum — the
    second reduction pays wire for a value every rank already holds."""
    mesh = _mesh()

    def f(x):
        def body(v):
            t = lax.psum(jnp.sum(v), AXES)
            return lax.psum(t, AXES)  # redundant: t is replicated

        return shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P())(x)

    spec = _spec("spiked_s002", f, (_x84(),))
    findings = rules_shard.check_s002(analyze(sc.trace_program(spec)), spec)
    assert [f.rule for f in findings] == ["S002"]
    assert "redundant psum" in findings[0].message
    assert "['x']" in findings[0].message


def test_s002_fires_on_pmin_of_replicated_guard(_devices):
    mesh = _mesh()

    def f(x):
        def body(v):
            ok = lax.pmin(jnp.min(v), AXES)
            return lax.pmin(ok, AXES)  # double-agreed guard

        return shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P())(x)

    spec = _spec("spiked_s002_pmin", f, (_x84(),))
    findings = rules_shard.check_s002(analyze(sc.trace_program(spec)), spec)
    assert [f.rule for f in findings] == ["S002"]
    assert "redundant pmin" in findings[0].message


def test_s002_clean_single_reduction_and_partial_axes(_devices):
    mesh = _mesh2()

    def f(x):
        def body(v):
            t = lax.psum(jnp.sum(v), ("x",))  # clears x, still varies y
            return lax.psum(t, ("y",))  # reduces the VARYING axis: fine

        return shard_map(
            body, mesh=mesh, in_specs=P("x", "y"), out_specs=P()
        )(x)

    spec = _spec("clean_s002", f, (_x84(),))
    assert rules_shard.check_s002(analyze(sc.trace_program(spec)), spec) == []


def test_s002_grouped_reduction_never_clears(_devices):
    mesh = _mesh()

    def f(x):
        def body(v):
            t = jnp.sum(v)  # shard-local: varies on x
            # grouped psum: replicated only WITHIN each group, so 'x'
            # must not clear — if it did, the full pmax that follows
            # would be flagged redundant by S002
            g = lax.psum(
                t, AXES, axis_index_groups=[[0, 1, 2, 3], [4, 5, 6, 7]]
            )
            return lax.pmax(g, AXES)

        return shard_map(body, mesh=mesh, in_specs=P("x"), out_specs=P())(x)

    spec = _spec("grouped_s002", f, (_x84(),))
    report = analyze(sc.trace_program(spec))
    assert rules_shard.check_s002(report, spec) == []
    assert rules_shard.check_s001(report, spec) == []  # pmax re-agrees


# ------------------------------------------- S003: varying-value escape


def test_s003_fires_on_partially_reduced_output(_devices):
    mesh = _mesh2()

    def f(x):
        return shard_map(
            lambda v: v * 1.0,
            mesh=mesh, in_specs=P("x", "y"), out_specs=P("x"),
        )(x)

    spec = _spec("spiked_s003", f, (_x84(),))
    findings = rules_shard.check_s003(analyze(sc.trace_program(spec)), spec)
    assert [f.rule for f in findings] == ["S003"]
    assert "program output" in findings[0].message
    assert "'y'" in findings[0].message  # varies on y, only x declared


def test_s003_fires_on_scan_ys_leaf(_devices):
    mesh = _mesh2()

    def f(x):
        sm = shard_map(
            lambda v: v * 1.0,
            mesh=mesh, in_specs=P("x", "y"), out_specs=P("x"),
        )

        def step(c, _):
            return c, sm(c)

        _c, ys = lax.scan(step, x, None, length=3)
        return ys

    spec = _spec("spiked_s003_ys", f, (_x84(),))
    report = analyze(sc.trace_program(spec))
    kinds = {e.kind for e in report.escapes}
    assert "scan_ys" in kinds  # the stacked ys leaf itself
    findings = rules_shard.check_s003(report, spec)
    assert findings and all(f.rule == "S003" for f in findings)
    assert any("scan ys leaf" in f.message for f in findings)


def test_s003_clean_when_out_specs_cover_all_axes(_devices):
    mesh = _mesh2()

    def f(x):
        return shard_map(
            lambda v: v * 1.0,
            mesh=mesh, in_specs=P("x", "y"), out_specs=P("x", "y"),
        )(x)

    spec = _spec("clean_s003", f, (_x84(),))
    assert rules_shard.check_s003(analyze(sc.trace_program(spec)), spec) == []


# ------------------------------------------------- lattice edge cases


def _while_cond_program(replicated_guard):
    """A pmin-agreed (or shard-local) guard carried through a
    while_loop into a mismatched-schedule cond: the carry fixpoint must
    preserve (or propagate) its vary-set."""
    mesh = _mesh()

    def body(v):
        if replicated_guard:
            g0 = lax.pmin((v[0, 0] > 0).astype(jnp.int32), AXES)
        else:
            g0 = (v[0, 0] > 0).astype(jnp.int32)

        def cond_f(carry):
            _g, _u, i = carry
            return i < 3

        def step(carry):
            g, u, i = carry
            u = lax.cond(
                g == 1,
                lambda w: lax.psum(w, AXES),
                lambda w: w * 2.0,
                u,
            )
            return (g, u, i + 1)

        _g, u, _i = lax.while_loop(cond_f, step, (g0, v, 0))
        return u

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (_x84(),)


def test_while_loop_fixpoint_preserves_replicated_guard(_devices):
    fn, args = _while_cond_program(replicated_guard=True)
    spec = _spec("while_clean", fn, args)
    assert rules_jaxpr.check_j001(sc.trace_program(spec), spec) == []


def test_while_loop_fixpoint_propagates_varying_guard(_devices):
    fn, args = _while_cond_program(replicated_guard=False)
    spec = _spec("while_spiked", fn, args)
    findings = rules_jaxpr.check_j001(sc.trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J001"]


def _pjit_in_cond_program(replicated_pred):
    """The dispatch collective hidden inside a jitted helper inside a
    cond branch: the signature walk and the lattice must both see
    through the nested pjit."""
    mesh = _mesh()

    def body(v):
        if replicated_pred:
            guard = lax.pmin((v[0, 0] > 0).astype(jnp.int32), AXES)
            pred = jax.jit(lambda t: t + 0)(guard) == 1  # pjit identity
        else:
            pred = v[0, 0] > 0
        return lax.cond(
            pred,
            lambda u: jax.jit(lambda w: lax.psum(w, AXES))(u),
            lambda u: u * 2.0,
            v,
        )

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (_x84(),)


def test_nested_pjit_inside_cond_clean_with_agreed_pred(_devices):
    fn, args = _pjit_in_cond_program(replicated_pred=True)
    spec = _spec("pjit_clean", fn, args)
    assert rules_jaxpr.check_j001(sc.trace_program(spec), spec) == []


def test_nested_pjit_inside_cond_fires_with_local_pred(_devices):
    fn, args = _pjit_in_cond_program(replicated_pred=False)
    spec = _spec("pjit_spiked", fn, args)
    findings = rules_jaxpr.check_j001(sc.trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J001"]
    assert "psum" in findings[0].message  # the signature saw through pjit


def _ppermute_pred_program(perm):
    """A pmin-agreed guard pushed through a ppermute, then used as a
    mismatched-cond predicate: a FULL permutation keeps it replicated
    (J001 clean), a partial one taints it (J001 fires)."""
    mesh = _mesh()

    def body(v):
        ok = lax.pmin((v[0, 0] > 0).astype(jnp.int32), AXES)
        okp = lax.ppermute(ok, "x", perm)
        return lax.cond(
            okp == 1,
            lambda u: lax.psum(u, AXES),
            lambda u: u * 2.0,
            v,
        )

    def f(x):
        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    return f, (_x84(),)


def test_ppermute_full_rotation_preserves_replication(_devices):
    fn, args = _ppermute_pred_program([(i, (i + 1) % 8) for i in range(8)])
    spec = _spec("rotation", fn, args)
    assert rules_jaxpr.check_j001(sc.trace_program(spec), spec) == []


def test_ppermute_identity_perm_preserves_replication(_devices):
    fn, args = _ppermute_pred_program([(i, i) for i in range(8)])
    spec = _spec("identity", fn, args)
    assert rules_jaxpr.check_j001(sc.trace_program(spec), spec) == []


def test_ppermute_partial_perm_taints(_devices):
    # rank 7's slot receives nothing (zero-filled): rank-dependent
    fn, args = _ppermute_pred_program([(i, i + 1) for i in range(7)])
    spec = _spec("partial", fn, args)
    findings = rules_jaxpr.check_j001(sc.trace_program(spec), spec)
    assert [f.rule for f in findings] == ["J001"]


# --------------------------------- S004: per-axis wire attribution


def test_wire_profile_bills_the_crossed_axis(_devices):
    mesh = _mesh2()

    def f(x):
        return shard_map(
            lambda v: lax.psum(v, ("x",)),
            mesh=mesh, in_specs=P("x", "y"), out_specs=P(None, "y"),
        )(x)

    w = rules_shard.wire_profile(_trace(f, jnp.zeros((8, 8), jnp.float32)))
    # the f32[2, 4] shard is 32 bytes, billed to 'x' only
    assert w == {
        "per_axis": {"x": 32},
        "per_domain": {"dcn": 0, "ici": 32},
        "total_bytes": 32,
    }


def test_wire_profile_two_axis_collective_bills_both(_devices):
    mesh = _mesh2()

    def f(x):
        return shard_map(
            lambda v: lax.psum(v, AXES2),
            mesh=mesh, in_specs=P("x", "y"), out_specs=P(),
        )(x)

    w = rules_shard.wire_profile(_trace(f, jnp.zeros((8, 8), jnp.float32)))
    # per_axis is the axis-crossing view (full bytes on each axis);
    # per_domain bills the collective ONCE, so it sums to J004's total
    assert w["per_axis"] == {"x": 32, "y": 32}
    assert w["per_domain"] == {"dcn": 0, "ici": 32}
    assert w["total_bytes"] == 32


def test_wire_profile_dcn_axis_rolls_up_to_dcn(_devices):
    mesh = _mesh2(names=("dcn", "x"))

    def f(x):
        def body(v):
            a = lax.psum(v, ("dcn",))  # crosses the pod boundary
            return lax.psum(a, ("x",))  # stays on ICI

        return shard_map(
            body, mesh=mesh, in_specs=P("dcn", "x"), out_specs=P()
        )(x)

    w = rules_shard.wire_profile(_trace(f, jnp.zeros((8, 8), jnp.float32)))
    # mesh (4, 2): the f32[2, 4] shard is 32 bytes per collective
    assert w["per_axis"] == {"dcn": 32, "x": 32}
    assert w["per_domain"] == {"dcn": 32, "ici": 32}
    assert w["total_bytes"] == 64
    assert rules_shard.axis_domain("dcn") == rules_shard.DCN_DOMAIN
    assert rules_shard.axis_domain("z") == rules_shard.ICI_DOMAIN


def test_wire_profile_scan_multiplies_and_cond_bills_max(_devices):
    mesh = _mesh()

    def scanned(x):
        def body(v):
            def step(c, _):
                return lax.psum(c, AXES), None

            out, _ = lax.scan(step, v, None, length=5)
            return out

        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    w = rules_shard.wire_profile(_trace(scanned, _x84()))
    assert w["per_axis"] == {"x": 5 * 16}  # f32[1, 4] shard x 5 trips

    def conded(x):
        def body(v):
            return lax.cond(
                v[0, 0] > 0,
                lambda u: lax.psum(jnp.concatenate([u, u], 1), AXES)[:, :4],
                lambda u: lax.psum(u, AXES),
                v,
            )

        return shard_map(
            body, mesh=mesh, in_specs=P("x"), out_specs=P("x")
        )(x)

    w = rules_shard.wire_profile(_trace(conded, _x84()))
    assert w["per_axis"] == {"x": 32}  # the wide f32[1, 8] branch only


def test_compare_wire_drift_missing_and_stale():
    base = {
        "p": {
            "per_axis": {"x": 32},
            "per_domain": {"dcn": 0, "ici": 32},
            "total_bytes": 32,
        }
    }
    pert = {
        "p": {
            "per_axis": {"x": 64},
            "per_domain": {"dcn": 0, "ici": 64},
            "total_bytes": 64,
        }
    }
    assert rules_shard.compare_wire(base, base) == []
    findings = rules_shard.compare_wire(pert, base)
    assert findings and all(f.rule == "S004" for f in findings)
    assert any("total wire bytes drifted" in f.message for f in findings)
    assert any("axis 'x' drifted" in f.message for f in findings)
    assert rules_shard.compare_wire(pert, pert) == []

    missing = rules_shard.compare_wire(base, None)
    assert [f.rule for f in missing] == ["S004"]
    assert "no committed wire-attribution baseline" in missing[0].message

    stale = rules_shard.compare_wire({}, base, check_stale=True)
    assert [f.rule for f in stale] == ["S004"]
    assert "stale wire-attribution baseline entry" in stale[0].message
    # a --programs subset run must not read missing names as stale
    assert rules_shard.compare_wire({}, base, check_stale=True, partial=True) == []


def test_s004_perturbed_width_fails_check_until_update(
    _devices, capsys, tmp_path
):
    """The acceptance gate: a perturbed collective width fails --check
    against the committed wire table until --update-baseline refreshes
    it — exercised through the real CLI on a real registry program."""
    bl = str(tmp_path / "prof.json")
    prog = "canonical_planar_sharded"
    assert shardcheck_main(
        ["--programs", prog, "--baseline", bl, "--update-baseline"]
    ) == 0
    capsys.readouterr()
    assert shardcheck_main(
        ["--programs", prog, "--baseline", bl, "--check"]
    ) == 0
    capsys.readouterr()

    with open(bl) as fh:
        doc = json.load(fh)
    entry = doc["wire_attribution"]["programs"][prog]
    entry["per_axis"]["x"] += 4  # a collective got 4 bytes wider
    entry["total_bytes"] += 4
    with open(bl, "w") as fh:
        json.dump(doc, fh)

    rc = shardcheck_main(["--programs", prog, "--baseline", bl, "--check"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "S004" in out and "drifted" in out

    assert shardcheck_main(
        ["--programs", prog, "--baseline", bl, "--update-baseline"]
    ) == 0
    capsys.readouterr()
    assert shardcheck_main(
        ["--programs", prog, "--baseline", bl, "--check"]
    ) == 0


# ---------------------------------------------- baseline file plumbing


def test_profile_and_wire_sections_coexist(tmp_path):
    """progcheck's profiles section and shardcheck's wire_attribution
    section share one file: refreshing either must preserve the other."""
    path = str(tmp_path / "prof.json")
    profiles = {"a": {"collective_bytes_total": 3}}
    wires = {
        "a": {
            "per_axis": {"x": 8},
            "per_domain": {"dcn": 0, "ici": 8},
            "total_bytes": 8,
        }
    }
    assert load_wire_baseline(path) is None
    write_progprofile_baseline(path, profiles)
    assert load_wire_baseline(path) is None  # section not written yet
    write_wire_baseline(path, wires)
    assert load_progprofile_baseline(path) == profiles
    assert load_wire_baseline(path) == wires

    # refresh profiles: the wire section survives
    profiles2 = {"b": {"collective_bytes_total": 5}}
    write_progprofile_baseline(path, profiles2)
    assert load_progprofile_baseline(path) == profiles2
    assert load_wire_baseline(path) == wires

    # refresh wires: the profiles survive
    wires2 = {"b": wires["a"]}
    write_wire_baseline(path, wires2)
    assert load_progprofile_baseline(path) == profiles2
    assert load_wire_baseline(path) == wires2

    bad = tmp_path / "bad.json"
    bad.write_text('{"wire_attribution": "nope"}')
    with pytest.raises(SystemExit, match="malformed"):
        load_wire_baseline(str(bad))


def test_suppression_baseline_roundtrip(tmp_path):
    """ShardFindings ride the gridlint suppression machinery verbatim:
    the program name is the symbol, matching is message-exact."""
    path = str(tmp_path / "supp.json")
    known = ShardFinding("S002", "progA", "redundant but deliberate")
    write_baseline(path, [known], justification="journal entry")
    keys = load_baseline(path)
    assert known.baseline_key() in keys
    fresh = ShardFinding("S002", "progB", "a new one")
    new, old = split_baselined([known, fresh], keys)
    assert [f.program for f in new] == ["progB"]
    assert [f.program for f in old] == ["progA"]


def test_shard_finding_surface():
    f = ShardFinding("S001", "prog", "msg")
    assert f.render() == "<prog>: S001: msg"
    assert f.symbol == "prog"
    assert f.baseline_key() == ("S001", f.path, "prog", "msg")
    d = f.to_dict()
    assert d["rule"] == "S001" and d["program"] == "prog"


def test_merge_sarif_concatenates_runs():
    a = to_sarif([ShardFinding("S001", "p", "m")], "shardcheck", {})
    b = to_sarif([], "gridlint", {"G001": "doc"})
    merged = merge_sarif([a, b])
    assert merged["version"] == a["version"]
    assert [r["tool"]["driver"]["name"] for r in merged["runs"]] == [
        "shardcheck",
        "gridlint",
    ]


# ------------------------------------------------------ the repo gate


def test_rule_docs_cover_all_rules():
    assert set(rules_shard.RULE_DOCS) == set(S_RULE_IDS)


def test_repo_programs_shardcheck_clean(_devices, capsys):
    """The tier-1 gate, mirroring the gridlint/progcheck repo gates:
    every registered program runs clean under S001-S004 against the
    committed wire_attribution baseline and suppression file."""
    rc = shardcheck_main(["--check"])
    out = capsys.readouterr().out
    assert rc == 0, out


def test_repo_programs_have_shard_reports(_devices):
    """The interpreter annotates every program: the sharded canonical
    engines must show real inferred vary-sets (not a silent no-op)."""
    findings, wires = run_shardcheck(
        rules=["S001", "S002", "S003", "S004"],
    )
    assert findings == []
    assert set(wires) == set(sc.default_programs())
    w = wires["canonical_planar_sharded"]
    assert w["total_bytes"] > 0
    assert set(w["per_axis"]) == {"x", "y", "z"}
    assert w["per_domain"]["dcn"] == 0  # single-pod meshes today


def test_cli_exit_codes_lists_and_json(_devices, capsys, tmp_path):
    assert shardcheck_main(["--rules", "S999"]) == 2
    capsys.readouterr()
    assert shardcheck_main(["--programs", "nope"]) == 2
    capsys.readouterr()
    assert shardcheck_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    assert all(r in listed for r in S_RULE_IDS)
    assert shardcheck_main(["--list-programs"]) == 0
    assert "resident_macro_step" in capsys.readouterr().out

    bl = str(tmp_path / "prof.json")
    prog = "canonical_planar_vranks"
    assert shardcheck_main(
        ["--programs", prog, "--baseline", bl, "--update-baseline"]
    ) == 0
    capsys.readouterr()
    rc = shardcheck_main(
        ["--programs", prog, "--baseline", bl, "--format", "json"]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert prog in out["wire_attribution"]


def test_cli_sarif_format_and_stale_suppression(_devices, capsys, tmp_path):
    bl = str(tmp_path / "prof.json")
    supp = str(tmp_path / "supp.json")
    prog = "canonical_planar_vranks"
    assert shardcheck_main(
        ["--programs", prog, "--baseline", bl, "--update-baseline"]
    ) == 0
    capsys.readouterr()

    # an unbaselined program renders through the shared SARIF formatter
    empty = str(tmp_path / "empty.json")
    with open(empty, "w") as fh:
        json.dump({"wire_attribution": {"programs": {}}}, fh)
    rc = shardcheck_main(
        ["--programs", prog, "--baseline", empty, "--format", "sarif"]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 1
    results = doc["runs"][0]["results"]
    assert results and results[0]["ruleId"] == "S004"
    assert doc["runs"][0]["tool"]["driver"]["name"] == "shardcheck"

    # a suppression entry matching nothing is stale under --check
    write_baseline(supp, [ShardFinding("S002", "ghost", "long gone")])
    rc = shardcheck_main(
        [
            "--programs", prog,
            "--baseline", bl,
            "--suppressions", supp,
            "--check",
        ]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "stale suppression entry" in out


def test_cli_script_entry_point():
    """scripts/shardcheck.py runs standalone (it forces the 8-device
    virtual mesh itself) and exits 0 on the committed baseline."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the wrapper must set the mesh itself
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "shardcheck.py"),
            "--check",
        ],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def _check_all_registry():
    """Load scripts/check_all.py's ANALYZERS registry — the single
    source of truth for the family list, so this test stops needing an
    N -> N+1 edit every time a family lands."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "_check_all", os.path.join(REPO_ROOT, "scripts", "check_all.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.ANALYZERS


def test_check_all_umbrella_merges_every_registered_tool(tmp_path):
    """scripts/check_all.py: every analyzer in its ANALYZERS registry,
    clean at HEAD, one SARIF run per family merged into the requested
    file — and every registered baseline actually committed."""
    analyzers = _check_all_registry()
    expected = [a.name for a in analyzers]
    assert len(expected) >= 6 and "kernelcheck" in expected
    for a in analyzers:
        assert os.path.exists(os.path.join(REPO_ROOT, a.baseline)), (
            f"{a.name}: registered baseline {a.baseline} is not committed"
        )
    out_path = str(tmp_path / "merged.sarif")
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "check_all.py"),
            "--sarif-out", out_path,
        ],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(out_path) as fh:
        merged = json.load(fh)
    names = [r["tool"]["driver"]["name"] for r in merged["runs"]]
    assert names == expected
    assert all(r["results"] == [] for r in merged["runs"])
    # per-analyzer wall-time must stay visible (lint-growth telemetry)
    for name in expected:
        assert any(
            line.startswith(f"check: {name} clean") and line.endswith("s)")
            for line in proc.stdout.splitlines()
        ), proc.stdout
