"""State-health observatory (ISSUE 20): in-graph invariant probes.

Four contracts pinned here:

* **Hand-math semantics** — the in-graph summary
  (``ops/statehealth.py``) and its numpy mirror
  (``telemetry.probes.summarize_host``) agree bit-for-bit on every
  counter against fixtures with a known corruption layout: NaN rows
  count in ``nan_pos`` only (IEEE comparisons are false both ways),
  ±Inf position rows count in BOTH ``nan_pos`` and ``oob``, dead
  (padding) rows never count whatever garbage they hold, and the
  conservation residual is exact int32 arithmetic.
* **Off tier is bit-identical zero-cost** — ``make_chunk_fn`` with
  ``probes=ProbeConfig("off")`` emits the EXACT unprobed program
  (jaxpr equality for chunk in {1, 7, 16}), and a counters-probed
  driver run reproduces the unprobed run's particle set and count
  bytes — observing the state never perturbs it.
* **Probes stay in-graph** — a jaxpr walk over the armed macro-step
  (both tiers) finds the ``lax.scan`` and no callback/infeed/outfeed
  primitive: the summary rides the scan ys, it never syncs to the
  host mid-chunk (the dynamic backstop behind progcheck J002 for the
  probe-armed registry program).
* **End-to-end recovery** — an injected :class:`StateCorruptionFault`
  produces a nonzero ``nan_pos`` ``state_health`` event, the
  ``nan_detected`` rule ALERTs naming the step, the boundary gate
  restarts the driver BEFORE the corruption is snapshotted, and the
  supervised run finishes bit-identical to an unfaulted reference.

Plus the documentation drift test SCHEMA.md and ``health.py`` both
name: ``test_default_rules_match_schema_table`` asserts the "Health
rule table" and ``default_rules()`` agree on name, order and severity.
"""

import dataclasses
import re
from pathlib import Path

import numpy as np
import pytest

import mpi_grid_redistribute_tpu.telemetry.health as health
from mpi_grid_redistribute_tpu.service import (
    DriverConfig,
    FaultPlan,
    RestartPolicy,
    ServiceDriver,
    StateCorruptionFault,
    Supervisor,
)
from mpi_grid_redistribute_tpu.service import elastic, resident
from mpi_grid_redistribute_tpu.telemetry import StepRecorder
from mpi_grid_redistribute_tpu.telemetry.probes import (
    ProbeConfig,
    record_probe_steps,
    summarize_host,
)

CHUNKS = (1, 7, 16)


def _cfg(tmp_path, **kw):
    base = dict(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=24,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
    )
    base.update(kw)
    return DriverConfig(**base)


def _jax_cfg(tmp_path, **kw):
    base = dict(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=12,
        seed=5,
        backend="jax",
        snapshot_every=0,
        snapshot_dir=None,
        watchdog_s=0.0,
    )
    base.update(kw)
    return DriverConfig(**base)


def _supervised(cfg, faults, max_restarts=5):
    rec = StepRecorder()

    def factory(grid_shape=None):
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return ServiceDriver(c, recorder=rec, faults=faults)

    sup = Supervisor(
        factory,
        policy=RestartPolicy(
            max_restarts=max_restarts, backoff_base_s=0.01,
            backoff_cap_s=0.02,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    return sup, rec


def _reference_state(cfg):
    """The uninterrupted trajectory: same config, snapshots/journal off
    (neither may influence the state for restarts to be bit-exact)."""
    ref = ServiceDriver(
        dataclasses.replace(
            cfg, snapshot_every=0, snapshot_dir=None, journal_dir=None,
            watchdog_s=0.0,
        )
    )
    ref.init_state()
    state = ref.run()
    ref.close()
    return state


def _assert_bit_identical(a, b):
    for name, x, y in zip(("pos", "vel", "ids", "count"), a, b):
        assert x.tobytes() == y.tobytes(), f"{name} diverged"


# ------------------------------------------------- hand-math fixtures


def _corrupt_fixture():
    """2 shards x cap 4, ndim 3, count [3, 2]: one clean row, one NaN
    position (nan_pos only), one +Inf position (nan_pos AND oob), one
    finite out-of-bounds row, one NaN velocity — and three dead rows
    stuffed with the worst garbage available."""
    pos = np.array(
        [
            [0.1, 0.2, 0.3],        # live, clean
            [np.nan, 0.5, 0.5],     # live: nan_pos, NOT oob
            [np.inf, 0.5, 0.5],     # live: nan_pos AND oob
            [np.nan, np.inf, -5.0], # dead garbage — must not count
            [1.5, 0.5, 0.5],        # live: oob only
            [0.9, 0.0, 0.25],       # live, clean pos (vel is NaN)
            [2.5, np.nan, 0.5],     # dead garbage
            [0.5, 0.5, 0.5],        # dead (clean-looking) garbage
        ],
        dtype=np.float32,
    )
    vel = np.tile(
        np.array([0.5, -0.25, 1.0], dtype=np.float32), (8, 1)
    )
    vel[3] = [np.inf, 0.0, 0.0]     # dead
    vel[5] = [np.nan, 0.0, 0.0]     # live: nan_vel
    vel[6] = np.nan                 # dead
    count = np.array([3, 2], dtype=np.int32)
    expect = {
        "live": 5, "nan_pos": 2, "nan_vel": 1, "oob": 2, "residual": 0,
    }
    return pos, vel, count, expect


def _clean_fixture():
    """2 shards x cap 2, ndim 2, count [2, 1], dyadic values — the
    moments are exact in float32, so even pos_min/pos_max/vel_m2 admit
    equality assertions."""
    pos = np.array(
        [[0.25, 0.5], [0.75, 0.125], [0.5, 0.875], [9.0, -9.0]],
        dtype=np.float32,
    )
    vel = np.array(
        [[1.0, 2.0], [-2.0, 0.0], [0.5, 0.5], [100.0, 100.0]],
        dtype=np.float32,
    )
    count = np.array([2, 1], dtype=np.int32)
    expect = {
        "live": 3, "nan_pos": 0, "nan_vel": 0, "oob": 0, "residual": 0,
        "pos_min": [0.25, 0.125], "pos_max": [0.75, 0.875],
        "vel_m2": 9.5,
    }
    return pos, vel, count, expect


def _summarize_graph(pos, vel, count, initial, dropped, tier):
    import jax.numpy as jnp

    from mpi_grid_redistribute_tpu.ops import statehealth

    out = statehealth.summarize(
        jnp.asarray(pos), jnp.asarray(vel), jnp.asarray(count),
        jnp.int32(initial), jnp.int32(dropped), 0.0, 1.0, tier,
    )
    return {k: np.asarray(v) for k, v in out.items()}


COUNTERS = ("live", "nan_pos", "nan_vel", "oob", "residual")


def test_counters_hand_math_corrupt_fixture():
    pos, vel, count, expect = _corrupt_fixture()
    # initial 8, 3 rows legitimately dropped since -> live 5, residual 0
    got = _summarize_graph(pos, vel, count, 8, 3, "counters")
    for k in COUNTERS:
        assert int(got[k]) == expect[k], k
    host = summarize_host(pos, vel, count, 8, 3, ProbeConfig("counters"))
    assert {k: int(v) for k, v in host.items()} == expect


def test_residual_is_exact_and_signed():
    pos, vel, count, _ = _corrupt_fixture()
    # 5 live + 2 dropped - 8 initial = -1: a row vanished unaccounted
    for fn in (
        lambda: _summarize_graph(pos, vel, count, 8, 2, "counters"),
        lambda: summarize_host(
            pos, vel, count, 8, 2, ProbeConfig("counters")
        ),
    ):
        assert int(fn()["residual"]) == -1
    # 5 live + 4 dropped - 8 initial = +1: a row appeared from nowhere
    assert int(
        _summarize_graph(pos, vel, count, 8, 4, "counters")["residual"]
    ) == 1


def test_moments_hand_math_clean_fixture():
    pos, vel, count, expect = _clean_fixture()
    for payload in (
        _summarize_graph(pos, vel, count, 3, 0, "moments"),
        summarize_host(pos, vel, count, 3, 0, ProbeConfig("moments")),
    ):
        for k in COUNTERS:
            assert int(payload[k]) == expect[k], k
        assert [float(x) for x in payload["pos_min"]] == expect["pos_min"]
        assert [float(x) for x in payload["pos_max"]] == expect["pos_max"]
        assert float(payload["vel_m2"]) == expect["vel_m2"]


def test_graph_matches_host_mirror_fuzz():
    """Seeded fuzz: random prefix-valid layouts with NaN/Inf/OOB salted
    into live AND dead rows. Counters must match the numpy mirror
    exactly; moments only float-close (f32 reduction order differs)."""
    rng = np.random.default_rng(20)
    for trial in range(12):
        nranks, cap, ndim = 4, 16, 3
        n = nranks * cap
        pos = rng.uniform(0.0, 1.0, (n, ndim)).astype(np.float32)
        vel = rng.normal(0.0, 1.0, (n, ndim)).astype(np.float32)
        for arr, vals in (
            (pos, (np.nan, np.inf, -np.inf, 1.5, -0.5)),
            (vel, (np.nan, np.inf, -np.inf)),
        ):
            k = rng.integers(0, 12)
            rows = rng.integers(0, n, k)
            cols = rng.integers(0, ndim, k)
            arr[rows, cols] = rng.choice(vals, k)
        count = rng.integers(0, cap + 1, nranks).astype(np.int32)
        initial = int(count.sum()) + int(rng.integers(-3, 4))
        dropped = int(rng.integers(0, 5))
        tier = ("counters", "moments")[trial % 2]
        graph = _summarize_graph(pos, vel, count, initial, dropped, tier)
        host = summarize_host(
            pos, vel, count, initial, dropped, ProbeConfig(tier)
        )
        for k in COUNTERS:
            assert int(graph[k]) == int(host[k]), (trial, k)
        if tier == "moments":
            for k in ("pos_min", "pos_max", "vel_m2"):
                np.testing.assert_allclose(
                    np.asarray(graph[k], dtype=np.float64),
                    np.asarray(host[k], dtype=np.float64),
                    rtol=1e-5, equal_nan=True, err_msg=f"{trial}:{k}",
                )


def test_probe_config_validation():
    assert ProbeConfig().tier == "off"
    assert not ProbeConfig().armed
    assert ProbeConfig("counters").armed
    assert not ProbeConfig("counters").moments
    assert ProbeConfig("moments").moments
    with pytest.raises(ValueError, match="unknown probe tier"):
        ProbeConfig("verbose")
    with pytest.raises(ValueError, match="lo < hi"):
        ProbeConfig("counters", lo=1.0, hi=1.0)


def test_record_probe_steps_event_stream():
    rec = StepRecorder()
    probe = {
        "live": np.array([10, 9, 9]),
        "nan_pos": np.array([0, 2, 0]),
        "nan_vel": np.array([0, 0, 1]),
        "oob": np.array([0, 0, 3]),
        "residual": np.array([0, -1, 0]),
    }
    assert record_probe_steps(rec, 5, probe) == 3
    ev = rec.events("state_health")
    assert [e.data["step"] for e in ev] == [5, 6, 7]
    assert [e.data["nan_pos"] for e in ev] == [0, 2, 0]
    assert [e.data["residual"] for e in ev] == [0, -1, 0]
    assert all("pos_min" not in e.data for e in ev)  # counters tier
    # moments tier adds the vector keys, per step
    probe["pos_min"] = np.zeros((3, 3), np.float32)
    probe["pos_max"] = np.ones((3, 3), np.float32)
    probe["vel_m2"] = np.array([1.0, 2.0, 3.0], np.float32)
    rec2 = StepRecorder()
    record_probe_steps(rec2, 1, probe)
    e = rec2.events("state_health")[-1]
    assert e.data["pos_max"] == [1.0, 1.0, 1.0]
    assert e.data["vel_m2"] == 3.0


# -------------------------------------- off tier: bit-identical program


def test_off_tier_emits_identical_jaxpr(tmp_path):
    """probes=None and probes=ProbeConfig("off") must trace to the SAME
    program, for every chunk length — the default tier is zero-cost by
    construction, not merely cheap."""
    import jax

    drv = ServiceDriver(_jax_cfg(tmp_path))
    drv.init_state()
    drv._ensure_built()
    pos, vel, ids, count = drv.state
    for chunk in CHUNKS:
        jaxprs = []
        for probes in (None, ProbeConfig("off")):
            macro, _, _ = resident.make_chunk_fn(
                drv._rd, drv.cfg.dt, chunk, pos, vel, ids, probes=probes
            )
            jaxprs.append(str(jax.make_jaxpr(macro)(pos, vel, ids, count)))
        assert jaxprs[0] == jaxprs[1], f"chunk={chunk}"
    drv.close()


def test_probed_run_reproduces_unprobed_trajectory(tmp_path):
    """Counters-probed resident run vs unprobed, same seed: identical
    particle set and count bytes — the probe observes, never perturbs.
    The probed run must also journal one clean state_health per step."""
    states = {}
    recs = {}
    for probes in ("off", "counters"):
        drv = ServiceDriver(_jax_cfg(tmp_path, chunk=5, probes=probes))
        drv.init_state()
        drv.run()
        drv.close()
        states[probes] = drv.state
        recs[probes] = drv.recorder
    assert elastic.particle_set(*states["counters"]) == (
        elastic.particle_set(*states["off"])
    )
    assert states["counters"][3].tobytes() == states["off"][3].tobytes()
    assert recs["off"].events("state_health") == []
    ev = recs["counters"].events("state_health")
    assert [e.data["step"] for e in ev] == list(range(1, 13))
    for e in ev:
        assert e.data["nan_pos"] == 0 and e.data["nan_vel"] == 0
        assert e.data["oob"] == 0 and e.data["residual"] == 0


@pytest.mark.parametrize("tier", ["counters", "moments"])
def test_armed_macro_jaxpr_stays_on_device(tmp_path, tier):
    """The probe-armed macro-step is still pure device code: the scan
    survives and no callback/infeed/outfeed primitive appears anywhere
    in the traced program (progcheck J002's dynamic backstop for the
    probe-armed registry entry)."""
    import jax

    from mpi_grid_redistribute_tpu.analysis.progcheck import (
        primitive_names,
    )

    drv = ServiceDriver(_jax_cfg(tmp_path))
    drv.init_state()
    drv._ensure_built()
    pos, vel, ids, count = drv.state
    macro, _, _ = resident.make_chunk_fn(
        drv._rd, drv.cfg.dt, 4, pos, vel, ids, probes=ProbeConfig(tier)
    )
    jaxpr = jax.make_jaxpr(macro)(pos, vel, ids, count)
    names = primitive_names(jaxpr.jaxpr)
    assert "scan" in names, "armed macro-step lost its lax.scan"
    hostile = [
        n for n in names
        if "callback" in n or "infeed" in n or "outfeed" in n
    ]
    assert not hostile, f"host syncs traced into the probed macro: {hostile}"
    drv.close()


# -------------------------------- corruption fault -> alert -> recovery


def test_state_corruption_detected_and_recovered(tmp_path):
    """The observatory's end-to-end leg of the fault matrix: an
    injected NaN burst is seen by the probes (state_health with the
    exact corrupted row count), paged by nan_detected (ALERT naming the
    step), rolled back by the supervisor (restore from a PRE-corruption
    snapshot), and the recovered run finishes bit-identical to an
    unfaulted reference — the injector fires once, so a second burst
    would mean the restore resurrected corrupt state."""
    cfg = _cfg(tmp_path, probes="counters", chunk=4)
    sup, rec = _supervised(cfg, FaultPlan([StateCorruptionFault(6, rows=5)]))
    verdict = sup.run()

    assert verdict.ok is True and verdict.gave_up is False
    assert verdict.restarts == 1
    assert verdict.step == cfg.steps

    fired = rec.events("fault_injected")
    assert len(fired) == 1
    assert fired[0].data["fault"] == "state_corruption"
    corrupt_step = fired[0].data["step"] + 1  # corrupts the NEXT step

    bursts = [
        e for e in rec.events("state_health") if e.data["nan_pos"] > 0
    ]
    assert bursts, "probes never saw the injected NaN burst"
    assert bursts[0].data["step"] == corrupt_step
    assert bursts[0].data["nan_pos"] == 5  # exactly the corrupted rows

    alerts = [
        e for e in rec.events("alert") if e.data["rule"] == "nan_detected"
    ]
    assert alerts, "nan_detected never paged"
    assert f"step {corrupt_step}" in alerts[0].data["reason"]

    restores = [
        e for e in rec.events("restore") if e.data.get("what") == "state"
    ]
    assert restores, "supervisor never restored state"
    assert restores[-1].data["step"] < corrupt_step, (
        "restored from a snapshot taken AFTER the corruption"
    )

    _assert_bit_identical(sup.driver.state, _reference_state(cfg))


def test_state_corruption_fault_validates_rows():
    with pytest.raises(ValueError, match="rows must be >= 1"):
        StateCorruptionFault(3, rows=0)


def test_state_rules_respect_restore_freshness_cut():
    """Corruption evidence older than the newest state restore is
    rolled-back history, not a standing finding — without the cut a
    recovered service would page on its own journal forever. A journal
    restore (what != "state") must NOT cut: it rolls back no state."""
    rec = StepRecorder()
    rec.record(
        "state_health", step=6, live=10, nan_pos=5, nan_vel=0, oob=0,
        residual=0,
    )
    mon = health.HealthMonitor(rec, rules=[health.nan_detected()])
    assert mon.evaluate(record=False)["status"] == health.ALERT
    rec.record("restore", what="journal", path="x")
    assert mon.evaluate(record=False)["status"] == health.ALERT
    rec.record("restore", what="state", step=4, path="y")
    assert mon.evaluate(record=False)["status"] == health.OK


# --------------------------------------- documentation drift backstop


def test_default_rules_match_schema_table():
    """SCHEMA.md's "Health rule table" is the authoritative contract
    for ``default_rules()`` — name, evaluation order and severity. A
    rule added to either side must land in the other in the same
    commit; this test is named by both."""
    schema = (
        Path(health.__file__).parent / "SCHEMA.md"
    ).read_text()
    section = schema.split("## Health rule table")[1]
    rows = []
    for line in section.splitlines():
        m = re.match(r"\|\s*`([a-z_]+)`\s*\|\s*(alert|warn)\s*\|", line)
        if m:
            rows.append((m.group(1), m.group(2)))
        elif rows and not line.startswith("|"):
            break  # contiguous table ended
    assert rows, "health rule table not found in SCHEMA.md"
    code = [
        (r.name, r.severity.lower()) for r in health.default_rules()
    ]
    assert rows == code, (
        "SCHEMA.md health rule table and health.default_rules() drifted"
    )
