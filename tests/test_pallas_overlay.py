"""Planar one-hot overlay scatter (ops/pallas_overlay.py) vs the XLA
column scatter, bit level — including NaN-bit payloads (bitcast int
fields), drop sentinels, and empty updates. Interpret mode on CPU."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu.ops import pallas_overlay


def _ref(flat, targets, cols):
    return np.asarray(
        jnp.asarray(flat).at[:, jnp.asarray(targets)].set(
            jnp.asarray(cols), mode="drop"
        )
    )


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("dtype", [np.float32, np.int32])
@pytest.mark.parametrize("encoding", ["quarter", "half", "int8"])
def test_overlay_matches_xla_scatter_bits(rng, seed, encoding, dtype,
                                          _devices):
    # every encoding must be bit-exact: int8 ((byte-128) s8 planes,
    # s8xs8->s32 matmul) is the SHIPPED default; quarter (byte planes,
    # DEFAULT matmul) and half (uint16 planes, HIGHEST) stay selectable.
    # Both dtypes matter: production migrate hands the kernel int32
    # bit-pattern transport, tests historically only drove f32.
    r = np.random.default_rng(seed)
    k, m, p = 7, 4 * 256, 37
    w, rmax = 256, 128
    targets = r.choice(m, size=p, replace=False).astype(np.int32)
    if dtype is np.int32:
        # the migrate engines' transport: raw int32 words, cols matching
        flat = r.integers(
            -(2**31), 2**31 - 1, size=(k, m), dtype=np.int32
        )
        cols = r.integers(
            -(2**31), 2**31 - 1, size=(k, p), dtype=np.int32
        )
    else:
        flat = r.standard_normal((k, m)).astype(np.float32)
        cols = r.standard_normal((k, p)).astype(np.float32)
        # bitcast int32 payloads (NaN-looking bit patterns) in one row
        cols[3] = r.integers(
            -(2**31), 2**31 - 1, size=p, dtype=np.int32
        ).view(np.float32)
        flat[3] = r.integers(
            -(2**31), 2**31 - 1, size=m, dtype=np.int32
        ).view(np.float32)
    out = pallas_overlay.overlay_scatter_planar(
        jnp.asarray(flat), jnp.asarray(targets), jnp.asarray(cols),
        interpret=True, w=w, rmax=rmax, encoding=encoding,
    )
    want = _ref(flat, targets, cols)
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint32), want.view(np.uint32)
    )


def test_overlay_rejects_unknown_encoding(rng, _devices):
    k, m, p = 7, 256, 8
    r = np.random.default_rng(0)
    flat = r.standard_normal((k, m)).astype(np.float32)
    targets = np.arange(p, dtype=np.int32)
    cols = r.standard_normal((k, p)).astype(np.float32)
    with pytest.raises(ValueError, match="encoding"):
        pallas_overlay.overlay_scatter_planar(
            jnp.asarray(flat), jnp.asarray(targets), jnp.asarray(cols),
            interpret=True, encoding="byte",
        )


def test_overlay_drop_sentinel_and_empty(rng, _devices):
    r = np.random.default_rng(7)
    k, m = 7, 2 * 256
    w, rmax = 256, 128
    flat = r.standard_normal((k, m)).astype(np.float32)
    # all targets out of range -> pure pass-through
    targets = np.full((16,), m, np.int32)
    cols = r.standard_normal((k, 16)).astype(np.float32)
    out = pallas_overlay.overlay_scatter_planar(
        jnp.asarray(flat), jnp.asarray(targets), jnp.asarray(cols),
        interpret=True, w=w, rmax=rmax,
    )
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint32), flat.view(np.uint32)
    )
    # mixed: some valid, some sentinel, negatives dropped too
    targets = np.array([0, 5, m, m + 3, -1, 511], np.int32)
    cols = r.standard_normal((k, 6)).astype(np.float32)
    out = pallas_overlay.overlay_scatter_planar(
        jnp.asarray(flat), jnp.asarray(targets), jnp.asarray(cols),
        interpret=True, w=w, rmax=rmax,
    )
    want = _ref(flat, np.array([0, 5, 511], np.int32), cols[:, [0, 1, 5]])
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint32), want.view(np.uint32)
    )


def test_overlay_dense_updates_multichunk(rng, _devices):
    """More updates than one rmax chunk per block; every column updated."""
    r = np.random.default_rng(3)
    k, m = 5, 2 * 256
    w, rmax = 256, 128
    flat = r.standard_normal((k, m)).astype(np.float32)
    targets = r.permutation(m).astype(np.int32)  # all columns, shuffled
    cols = r.standard_normal((k, m)).astype(np.float32)
    out = pallas_overlay.overlay_scatter_planar(
        jnp.asarray(flat), jnp.asarray(targets), jnp.asarray(cols),
        interpret=True, w=w, rmax=rmax,
    )
    want = _ref(flat, targets, cols)
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint32), want.view(np.uint32)
    )


def test_overlay_debug_unique_check(rng, _devices):
    """debug_unique raises on duplicate in-range targets (the silent-
    corruption case the round-3 advisor flagged) and passes clean calls
    — duplicate SENTINELS (dropped entries) stay legal."""
    r = np.random.default_rng(11)
    k, m = 7, 2 * 256
    w, rmax = 256, 128
    flat = r.standard_normal((k, m)).astype(np.float32)
    cols = r.standard_normal((k, 4)).astype(np.float32)
    dup_targets = np.array([3, 17, 17, 200], np.int32)
    with pytest.raises(ValueError, match="duplicate in-range"):
        pallas_overlay.overlay_scatter_planar(
            jnp.asarray(flat), jnp.asarray(dup_targets), jnp.asarray(cols),
            interpret=True, w=w, rmax=rmax, debug_unique=True,
        )
    # unique in-range + repeated drop sentinels: fine, and bit-correct
    ok_targets = np.array([3, 17, m, m], np.int32)
    out = pallas_overlay.overlay_scatter_planar(
        jnp.asarray(flat), jnp.asarray(ok_targets), jnp.asarray(cols),
        interpret=True, w=w, rmax=rmax, debug_unique=True,
    )
    want = _ref(flat, np.array([3, 17], np.int32), cols[:, :2])
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint32), want.view(np.uint32)
    )
    # fallback-triggering shape (m not a multiple of w): the check must
    # STILL fire — uniqueness is a property of the targets, not shapes
    flat_odd = r.standard_normal((k, 100)).astype(np.float32)
    with pytest.raises(ValueError, match="duplicate in-range"):
        pallas_overlay.overlay_scatter_planar(
            jnp.asarray(flat_odd), jnp.asarray(dup_targets),
            jnp.asarray(cols), interpret=True, w=w, rmax=rmax,
            debug_unique=True,
        )
    # traced path: the check rides jax.debug.callback
    f = jax.jit(
        lambda fl, t, c: pallas_overlay.overlay_scatter_planar(
            fl, t, c, interpret=True, w=w, rmax=rmax, debug_unique=True
        )
    )
    with pytest.raises(Exception, match="duplicate in-range"):
        jax.block_until_ready(
            f(jnp.asarray(flat), jnp.asarray(dup_targets), jnp.asarray(cols))
        )


def test_overlay_fallback_on_contract_violation(rng, _devices):
    r = np.random.default_rng(4)
    # m not a multiple of w -> falls back to XLA scatter (still correct)
    k, m = 7, 100
    flat = r.standard_normal((k, m)).astype(np.float32)
    targets = np.array([3, 50], np.int32)
    cols = r.standard_normal((k, 2)).astype(np.float32)
    out = pallas_overlay.overlay_scatter_planar(
        jnp.asarray(flat), jnp.asarray(targets), jnp.asarray(cols),
        interpret=True, w=256, rmax=128,
    )
    np.testing.assert_array_equal(
        np.asarray(out).view(np.uint32), _ref(flat, targets, cols).view(np.uint32)
    )
