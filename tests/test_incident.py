"""Incident observatory (ISSUE 17): step context, flight recorder, burn rate.

Everything here is host-only — the causal step context
(``telemetry/context.py``), the flight recorder
(``telemetry/incident.py``), the burn-rate SLO rules and the Perfetto
flow arrows all live on the journal side of the device boundary, so the
tests run on plain recorders plus the numpy service backend. The no-jax
import contract of context.py/incident.py is asserted separately in
``tests/test_metrics.py`` (scrape-path purity).
"""

import dataclasses
import importlib.util
import json
import os
import threading

import pytest

from mpi_grid_redistribute_tpu.telemetry import StepRecorder
from mpi_grid_redistribute_tpu.telemetry import context as context_lib
from mpi_grid_redistribute_tpu.telemetry import health
from mpi_grid_redistribute_tpu.telemetry import incident as incident_lib
from mpi_grid_redistribute_tpu.telemetry import traceview
from mpi_grid_redistribute_tpu.telemetry.context import StepContext
from mpi_grid_redistribute_tpu.telemetry.health import (
    ALERT,
    Finding,
    HealthMonitor,
    HealthRule,
    WARN,
)
from mpi_grid_redistribute_tpu.telemetry.incident import FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- context


def test_context_envelope_and_immutability():
    ctx = StepContext(trace="t1", step=3, call=2, attempt=1, origin="main")
    assert ctx.envelope() == {
        "trace": "t1",
        "ctx_step": 3,
        "ctx_call": 2,
        "ctx_attempt": 1,
        "ctx_origin": "main",
    }
    # None fields are omitted so steady-state envelopes stay small
    sparse = StepContext(trace="t2", origin="x")
    assert sparse.envelope() == {"trace": "t2", "ctx_origin": "x"}
    with pytest.raises(AttributeError, match="immutable"):
        ctx.step = 4
    assert "t1" in repr(ctx)
    # a fresh context invents a trace id; explicit origin=None derives
    # from the current thread name
    auto = StepContext()
    assert isinstance(auto.trace, str) and len(auto.trace) == 12
    assert auto.origin == threading.current_thread().name


def test_context_child_inherits_and_clears():
    root = StepContext(trace="run", step=5, attempt=0, origin="driver")
    kid = root.child(step=6)
    assert kid.trace == "run" and kid.step == 6
    assert kid.attempt == 0 and kid.origin == "driver"
    # explicit None clears; unpassed inherits
    cleared = root.child(step=None, origin="snapshot-writer")
    assert cleared.step is None and cleared.origin == "snapshot-writer"
    assert cleared.trace == "run"


def test_context_scoped_nesting_and_restore():
    assert context_lib.current() is None
    with context_lib.scoped(step=1) as outer:
        assert context_lib.current() is outer
        with context_lib.scoped(step=2) as inner:
            assert inner.trace == outer.trace
            assert context_lib.current_trace() == outer.trace
            assert context_lib.current().step == 2
        assert context_lib.current() is outer
    assert context_lib.current() is None
    # exception-safe restore
    with pytest.raises(RuntimeError):
        with context_lib.use(StepContext(trace="boom")):
            raise RuntimeError("x")
    assert context_lib.current() is None


def test_context_is_thread_local():
    seen = {}

    def probe():
        seen["ctx"] = context_lib.current()

    with context_lib.use(StepContext(trace="main-only")):
        t = threading.Thread(target=probe)
        t.start()
        t.join()
    # thread-locals never cross the spawn: handoff is explicit child()
    assert seen["ctx"] is None


def test_recorder_merges_context_payload_wins():
    rec = StepRecorder()
    rec.record("migrate_step", step=0, sent=1)  # no context active
    with context_lib.use(StepContext(trace="abc", step=5, origin="loop")):
        rec.record("migrate_step", step=9, sent=2)
        # payload keys win: a replayed event's original attribution is
        # never restamped by whatever context the replayer runs under
        rec.record_at("alert", 50.0, rule="r", trace="original")
    bare, tagged, replayed = rec.events()
    assert "trace" not in bare.data
    assert tagged.data["trace"] == "abc"
    assert tagged.data["ctx_step"] == 5 and tagged.data["step"] == 9
    assert tagged.data["ctx_origin"] == "loop"
    assert replayed.data["trace"] == "original"


# ---------------------------------------------------- callback isolation


def test_callback_error_isolated():
    rec = StepRecorder()
    rule = HealthRule("boom", ALERT, lambda r: "it broke")
    delivered = []

    def bad_sink(finding):
        raise ValueError("sink down")

    mon = HealthMonitor(rec, rules=[rule], on_alert=bad_sink)
    mon.add_callback(delivered.append)
    verdict = mon.evaluate()
    # the broken sink neither masks the ALERT nor starves later sinks
    assert verdict["status"] == ALERT
    assert delivered and delivered[0].rule == "boom"
    err = rec.last("callback_error")
    assert err.data["rule"] == "boom"
    assert "bad_sink" in err.data["callback"]
    assert err.data["error"].startswith("ValueError: sink down")


# ------------------------------------------------------ burn-rate rules


def _latency_journal(seconds_list):
    rec = StepRecorder()
    for i, s in enumerate(seconds_list):
        rec.record("step_latency", step=i, seconds=float(s), dropped=0)
    return rec


def test_burn_rate_fast_window_fires():
    rule = health.burn_rate_latency(0.25, fast_window=16, slow_window=64)
    assert rule.severity == ALERT and rule.name == "burn_rate_latency"
    # total breach: every step in the fast window blows the threshold
    reason = rule.fn(_latency_journal([1.0] * 16))
    assert reason is not None and "fast window" in reason
    # healthy window: no budget burned
    assert rule.fn(_latency_journal([0.001] * 64)) is None
    # cold journal: neither window is full yet, not a breach
    assert rule.fn(_latency_journal([1.0] * 10)) is None


def test_burn_rate_slow_window_catches_sustained_burn():
    rule = health.burn_rate_latency(0.25, fast_window=16, slow_window=64)
    # 3 bad steps early in the slow window, clean fast window: the
    # point-in-time p99 over the last 16 forgives this, the slow burn
    # (3/64 / 1% budget = 4.7x >= 2x) does not
    seconds = [1.0] * 3 + [0.001] * 61
    reason = rule.fn(_latency_journal(seconds))
    assert reason is not None and "slow window" in reason


def test_burn_rate_dropped_and_validation():
    rule = health.burn_rate_dropped(fast_window=4, slow_window=8)
    rec = StepRecorder()
    for i in range(4):
        rec.record("step_latency", step=i, seconds=0.001, dropped=10)
    assert "fast window" in rule.fn(rec)
    with pytest.raises(ValueError, match="objective"):
        health.burn_rate_latency(0.25, objective=1.5)
    with pytest.raises(ValueError, match="slow_window"):
        health.burn_rate_latency(0.25, fast_window=8, slow_window=8)
    with pytest.raises(ValueError, match="threshold"):
        health.burn_rate_dropped(threshold=-1)
    with pytest.raises(ValueError, match="burn factors"):
        health.burn_rate_latency(0.25, fast_burn=0.0)


# ------------------------------------------------------ flight recorder


def _seeded_journal(rec):
    """A small deterministic journal recorded under a fixed context."""
    with context_lib.use(
        StepContext(trace="fixed-trace", step=7, attempt=0, origin="test")
    ):
        rec.record_at("migrate_step", 100.0, step=0, sent=4, received=4,
                      backlog=0, dropped_recv=0, population=64)
        rec.record_at("flow_snapshot", 100.5, steps=1, n_ranks=2,
                      moved_rows_total=4, imbalance=1.0)
        rec.record_at("alert", 101.0, rule="backlog_growth",
                      severity="ALERT", reason="backlog grew")


def test_capture_writes_consistent_bundle(tmp_path):
    rec = StepRecorder()
    _seeded_journal(rec)
    fr = FlightRecorder(rec, str(tmp_path), clock=lambda: 111.0)
    out = fr.capture(rule="backlog_growth", reason="backlog grew")
    assert os.path.basename(out) == "incident-0001-backlog_growth"

    index = json.load(open(os.path.join(out, "index.json")))
    assert index["schema"] == 1
    assert index["rule"] == "backlog_growth"
    assert index["trigger"] == "alert"
    assert index["captured_at"] == 111.0
    # the triggering step context rode the alert event's envelope into
    # the manifest — the join key back into the frozen journal
    assert index["context"]["trace"] == "fixed-trace"
    assert index["context"]["ctx_step"] == 7
    assert index["events_retained"] == 3
    assert index["files"] == sorted(
        ["journal.jsonl", "counts.json", "metrics.prom", "health.json",
         "flow.json", "env.json"]
    )
    for name in index["files"]:
        assert os.path.isfile(os.path.join(out, name)), name
    # the frozen window predates the incident event (a bundle never
    # contains its own capture), but the live journal carries it
    lines = open(os.path.join(out, "journal.jsonl")).read().splitlines()
    assert len(lines) == 3
    ev = rec.last("incident")
    assert ev.data["id"] == "incident-0001-backlog_growth"
    assert ev.data["rule"] == "backlog_growth" and ev.data["events"] == 3
    assert ev.time == 111.0
    health_doc = json.load(open(os.path.join(out, "health.json")))
    assert health_doc["trigger"]["rule"] == "backlog_growth"
    assert health_doc["recent_alerts"][0]["rule"] == "backlog_growth"
    flow_doc = json.load(open(os.path.join(out, "flow.json")))
    assert flow_doc["imbalance"] == 1.0


def test_capture_debounce_and_prune(tmp_path):
    rec = StepRecorder()
    _seeded_journal(rec)
    now = [0.0]
    fr = FlightRecorder(
        rec, str(tmp_path), debounce_s=60.0, keep=2, clock=lambda: now[0]
    )
    first = fr.capture(rule="r1", reason="x")
    assert first is not None
    # same rule inside the window: suppressed, no second bundle
    now[0] = 30.0
    assert fr.capture(rule="r1", reason="x") is None
    # a different rule has its own debounce clock
    assert fr.capture(rule="r2", reason="y") is not None
    # past the window the same rule captures again; keep=2 prunes the
    # oldest bundle so the incident dir stays bounded
    now[0] = 120.0
    assert fr.capture(rule="r1", reason="x") is not None
    ids = [e["id"] for e in incident_lib.list_bundles(tmp_path)]
    assert len(ids) == 2
    assert "incident-0003-r1" in ids


def test_on_finding_alert_only(tmp_path):
    rec = StepRecorder()
    _seeded_journal(rec)
    fr = FlightRecorder(rec, str(tmp_path), clock=lambda: 1.0)
    assert fr.on_finding(Finding("r", WARN, "advisory")) is None
    assert incident_lib.list_bundles(tmp_path) == []
    out = fr.on_finding(Finding("r", ALERT, "page"))
    assert out is not None


def test_scan_faults_cursor_and_event_context(tmp_path):
    rec = StepRecorder()
    with context_lib.use(StepContext(trace="ft", step=2, origin="loop")):
        rec.record("fault_injected", fault="latency_spike", step=2)
    fr = FlightRecorder(rec, str(tmp_path), clock=lambda: 5.0)
    made = fr.scan_faults()
    assert len(made) == 1
    index = json.load(open(os.path.join(made[0], "index.json")))
    assert index["rule"] == "fault_latency_spike"
    assert index["trigger"] == "fault"
    # context comes from the fault event itself, not the scanner thread
    assert index["context"] == {
        "trace": "ft", "ctx_step": 2, "ctx_origin": "loop",
    }
    # the cursor advanced: an unchanged journal yields nothing new
    assert fr.scan_faults() == []
    rec.record("fault_injected", fault="crash", step=9)
    assert len(fr.scan_faults()) == 1


def test_capture_regression_labels(tmp_path):
    rec = StepRecorder()
    _seeded_journal(rec)
    fr = FlightRecorder(rec, str(tmp_path), clock=lambda: 9.0)
    made = fr.capture_regression(
        lines=["config1_pps REGRESSION -12% vs best", "other fine"],
        labels={"config1_pps": "REGRESSION", "service_pps": "WOBBLE"},
    )
    assert len(made) == 1
    index = json.load(open(os.path.join(made[0], "index.json")))
    assert index["rule"] == "regression_config1_pps"
    assert index["trigger"] == "regression"
    assert "config1_pps" in index["reason"]


def test_install_idempotent_across_monitor_restarts(tmp_path):
    rec = StepRecorder()
    mon1 = HealthMonitor(rec, rules=[])
    fr = incident_lib.install(mon1, rec, tmp_path)
    assert incident_lib.install(mon1, rec, tmp_path) is fr
    assert sum(
        1 for cb in mon1.callbacks
        if getattr(cb, "__self__", None) is fr
    ) == 1
    # a supervisor restart builds a fresh monitor around the SAME
    # journal: the flight recorder (debounce clocks, bundle counter)
    # carries over instead of re-capturing every standing alert
    mon2 = HealthMonitor(rec, rules=[])
    assert incident_lib.install(mon2, rec, tmp_path) is fr
    assert any(getattr(cb, "__self__", None) is fr for cb in mon2.callbacks)
    # a different bundle root is a different recorder instance
    other = incident_lib.install(mon2, rec, tmp_path / "other")
    assert other is not fr


def test_bundles_byte_stable_across_seeded_runs(tmp_path):
    def run(out_dir):
        rec = StepRecorder()
        _seeded_journal(rec)
        fr = FlightRecorder(rec, str(out_dir), clock=lambda: 111.0)
        return fr.capture(rule="backlog_growth", reason="backlog grew")

    a = run(tmp_path / "a")
    b = run(tmp_path / "b")
    assert os.path.basename(a) == os.path.basename(b)
    names = sorted(os.listdir(a))
    assert names == sorted(os.listdir(b))
    for name in names:
        wa = open(os.path.join(a, name), "rb").read()
        wb = open(os.path.join(b, name), "rb").read()
        assert wa == wb, f"{name} differs between seeded runs"


def test_list_and_load_bundles(tmp_path):
    assert incident_lib.list_bundles(tmp_path / "missing") == []
    rec = StepRecorder()
    _seeded_journal(rec)
    now = [1.0]
    fr = FlightRecorder(
        rec, str(tmp_path), debounce_s=0.0, clock=lambda: now[0]
    )
    fr.capture(rule="r1", reason="x")
    now[0] = 2.0
    fr.capture(rule="r2", reason="y")
    # a corrupt bundle during an incident is itself a finding — it shows
    # up as an error entry rather than being hidden
    bad = tmp_path / "incident-9999-bad"
    bad.mkdir()
    (bad / "index.json").write_text("{not json")
    entries = incident_lib.list_bundles(tmp_path)
    assert [e.get("id") for e in entries] == [
        "incident-9999-bad", "incident-0001-r1", "incident-0002-r2",
    ]
    assert "error" in entries[0]
    loaded = incident_lib.load_bundle(tmp_path, "incident-0001-r1")
    assert loaded["dir"] == str(tmp_path / "incident-0001-r1")
    assert "journal.jsonl" in loaded["files_present"]
    with pytest.raises(OSError):
        incident_lib.load_bundle(tmp_path, "incident-0000-nope")


# ------------------------------------------- perfetto causal flow arrows


def test_flow_arrows_pair_same_trace_cause_to_effect():
    rec = StepRecorder()
    with context_lib.use(StepContext(trace="t1", step=1, origin="loop")):
        rec.record_at("migrate_step", 100.0, step=0, sent=1, population=8,
                      backlog=0)
        rec.record_at("alert", 101.0, rule="r", severity="ALERT", reason="x")
        rec.record_at("callback_error", 101.5, rule="r", callback="cb",
                      error="ValueError: down")
        rec.record_at("alert", 102.0, rule="r2", severity="ALERT", reason="y")
    doc = traceview.to_chrome_trace(rec)
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "causal"]
    starts = {e["id"]: e for e in flows if e["ph"] == "s"}
    ends = {e["id"]: e for e in flows if e["ph"] == "f"}
    # every arrow is an id-paired s/f couple, finish at or after start
    assert set(starts) == set(ends) and len(starts) == 2
    for fid, s in starts.items():
        f = ends[fid]
        assert f["ts"] >= s["ts"]
        assert s["name"] == f["name"] and s["name"].startswith("cause:")
        assert f.get("bp") == "e"
    # neither the first alert nor the callback_error may act as a flow
    # source: both arrows point at the workload event (ts=0 relative)
    assert {s["ts"] for s in starts.values()} == {0.0}
    # events without a trace draw no arrows
    rec2 = StepRecorder()
    rec2.record("migrate_step", step=0, sent=1)
    rec2.record("alert", rule="r", severity="ALERT", reason="x")
    doc2 = traceview.to_chrome_trace(rec2)
    assert [e for e in doc2["traceEvents"] if e.get("cat") == "causal"] == []


def test_counter_track_uses_real_wall_times():
    rec = StepRecorder()
    # step_time events anchor the counter axis with honest wall times
    rec.record_at("step_time", 100.0, seconds=0.01)
    rec.record_at("step_time", 101.0, seconds=0.01)
    rec.record_at("step_time", 102.5, seconds=0.01)
    for s in range(3):
        rec.record_at("migrate_step", 103.0, step=s, population=10 + s,
                      backlog=0, sent=1)
    doc = traceview.to_chrome_trace(rec)
    counters = [
        e for e in doc["traceEvents"]
        if e["ph"] == "C" and e["name"] == "population"
    ]
    assert [e["ts"] for e in counters] == [0.0, 1.0e6, 2.5e6]
    # without timings the axis degrades to synthetic step spacing
    rec2 = StepRecorder()
    for s in range(3):
        rec2.record_at("migrate_step", 50.0, step=s, population=1, backlog=0,
                       sent=0)
    doc2 = traceview.to_chrome_trace(rec2, step_seconds=2e-3)
    counters2 = [
        e for e in doc2["traceEvents"]
        if e["ph"] == "C" and e["name"] == "population"
    ]
    assert [e["ts"] for e in counters2] == [0.0, 2000.0, 4000.0]


# --------------------------------------------- supervised integration


def test_supervised_slo_breach_freezes_bundles(tmp_path):
    """The demo contract as a tier-1 test: a fault-injected supervised
    run leaves alert- AND fault-triggered bundles, every index carries
    the triggering step context's trace id, and the per-rule debounce
    holds across restarts (one bundle per ALERT rule)."""
    from mpi_grid_redistribute_tpu.service import (
        DriverConfig,
        FaultPlan,
        LatencySpikeFault,
        RestartPolicy,
        ServiceDriver,
        Supervisor,
    )

    bundles = tmp_path / "incidents"
    cfg = DriverConfig(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=32,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
        slo_latency_p99_s=0.25,
        slo_window=4,
        incident_dir=str(bundles),
    )
    rec = StepRecorder()
    faults = FaultPlan([LatencySpikeFault(2, seconds=1.0, spikes=6)])

    def factory(grid_shape=None):
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return ServiceDriver(c, recorder=rec, faults=faults)

    sup = Supervisor(
        factory,
        policy=RestartPolicy(
            max_restarts=5, backoff_base_s=0.01, backoff_cap_s=0.02,
            shrink_after=2,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    verdict = sup.run()
    assert verdict.ok is True, verdict

    entries = incident_lib.list_bundles(bundles)
    assert entries, "no incident bundles frozen"
    assert all("error" not in e for e in entries)
    triggers = {e["trigger"] for e in entries}
    assert {"alert", "fault"} <= triggers
    # one supervised run = one trace, threaded through every bundle
    traces = {e["context"].get("trace") for e in entries}
    assert len(traces) == 1 and None not in traces
    # every ALERT rule maps to exactly one debounced bundle — a standing
    # alert re-confirmed at every health boundary (and across restarts,
    # which rebuild the monitor around the same journal) must not spam
    alert_rules = {
        e.data["rule"] for e in rec.events("alert")
        if e.data.get("severity") == ALERT
    }
    bundle_rules = [e["rule"] for e in entries if e["trigger"] == "alert"]
    assert sorted(bundle_rules) == sorted(set(bundle_rules))
    assert set(bundle_rules) <= alert_rules
    # journaled incident events mirror the on-disk bundles one-to-one
    journaled = [e.data["id"] for e in rec.events("incident")]
    assert sorted(journaled) == sorted(e["id"] for e in entries)


# ----------------------------------------------------------------- CLI


def _load_cli():
    path = os.path.join(REPO, "scripts", "incident.py")
    spec = importlib.util.spec_from_file_location("_incident_cli", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_incident_cli_list_show_export(tmp_path, capsys):
    rec = StepRecorder()
    _seeded_journal(rec)
    fr = FlightRecorder(rec, str(tmp_path), clock=lambda: 7.0)
    fr.capture(rule="backlog_growth", reason="backlog grew")
    cli = _load_cli()

    assert cli.main(["list", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "incident-0001-backlog_growth" in out
    assert "trigger=alert" in out and "trace=fixed-trace" in out

    assert cli.main(["list", str(tmp_path), "--json"]) == 0
    entries = json.loads(capsys.readouterr().out)
    assert entries[0]["id"] == "incident-0001-backlog_growth"

    assert cli.main(["show", str(tmp_path), "incident-0001-backlog_growth"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["rule"] == "backlog_growth"
    assert "journal.jsonl" in doc["files_present"]
    with pytest.raises(SystemExit):
        cli.main(["show", str(tmp_path), "incident-0000-nope"])

    trace_out = tmp_path / "incident_trace.json"
    assert cli.main([
        "export", str(tmp_path), "incident-0001-backlog_growth",
        "--out", str(trace_out),
    ]) == 0
    assert "perfetto" in capsys.readouterr().out
    doc = json.load(open(trace_out))
    phases = {e.get("ph") for e in doc["traceEvents"]}
    # the frozen window carried its context, so the exported trace draws
    # the causal arrow from the workload step to the alert
    assert {"s", "f"} <= phases
    assert cli.main(["list", str(tmp_path / "empty")]) == 0
    assert "no bundles" in capsys.readouterr().out
