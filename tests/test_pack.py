import jax
import jax.numpy as jnp
import numpy as np

from mpi_grid_redistribute_tpu.ops import pack


def test_pack_by_destination_layout():
    dest = jnp.array([1, 0, 1, 2, 0, 3], dtype=jnp.int32)  # R=3 sentinel 3
    counts = jnp.array([2, 2, 1], dtype=jnp.int32)
    vals = jnp.arange(6, dtype=jnp.float32) * 10
    out = pack.pack_by_destination(dest, counts, (vals,), capacity=3)[0]
    # dest 0: rows 1,4 ; dest 1: rows 0,2 ; dest 2: row 3; rest zero-masked
    np.testing.assert_array_equal(
        np.asarray(out),
        [[10, 40, 0], [0, 20, 0], [30, 0, 0]],
    )


def test_pack_capacity_clip_keeps_stable_prefix():
    # dest 0 overflows capacity; dest 1's segment must still be located by
    # the FULL count of dest 0 (offset 3), not the clipped one.
    dest = jnp.array([0, 0, 1, 0, 1], dtype=jnp.int32)
    counts = jnp.array([3, 2], dtype=jnp.int32)  # full, unclipped
    vals = jnp.array([5.0, 6.0, 7.0, 8.0, 9.0])
    out = pack.pack_by_destination(dest, counts, (vals,), capacity=2)[0]
    np.testing.assert_array_equal(np.asarray(out), [[5.0, 6.0], [7.0, 9.0]])


def test_pack_multifield_shares_permutation(rng):
    n, R, C = 257, 4, 128
    dest = jnp.asarray(rng.integers(0, R, size=n).astype(np.int32))
    counts = jnp.asarray(
        np.bincount(np.asarray(dest), minlength=R).astype(np.int32)
    )
    a = jnp.asarray(rng.normal(size=(n, 3)).astype(np.float32))
    b = jnp.asarray(np.arange(n, dtype=np.int64))
    pa, pb = pack.pack_by_destination(dest, counts, (a, b), C)
    pa, pb, dest_np = np.asarray(pa), np.asarray(pb), np.asarray(dest)
    for r in range(R):
        rows = np.flatnonzero(dest_np == r)[: C]
        np.testing.assert_array_equal(pb[r, : len(rows)], rows)
        np.testing.assert_array_equal(pa[r, : len(rows)], np.asarray(a)[rows])
        assert (pb[r, len(rows):] == 0).all()


def test_compact_received_order_and_drop():
    # R=2, C=3: rank layout with ragged valid counts
    recv = jnp.asarray(
        np.array(
            [[[1.0], [2.0], [99.0]], [[3.0], [4.0], [5.0]]], dtype=np.float32
        )
    )
    recv_counts = jnp.array([2, 3], dtype=jnp.int32)
    out, n, dropped = pack.compact_received((recv,), recv_counts, out_capacity=4)
    assert int(n) == 4 and int(dropped) == 1
    np.testing.assert_array_equal(
        np.asarray(out[0]).ravel(), [1.0, 2.0, 3.0, 4.0]
    )
    out2, n2, d2 = pack.compact_received((recv,), recv_counts, out_capacity=8)
    assert int(n2) == 5 and int(d2) == 0
    np.testing.assert_array_equal(
        np.asarray(out2[0]).ravel(), [1.0, 2.0, 3.0, 4.0, 5.0, 0.0, 0.0, 0.0]
    )


def test_pack_jit_static_shapes():
    f = jax.jit(
        lambda d, c, v: pack.pack_by_destination(d, c, (v,), capacity=4)
    )
    dest = jnp.array([0, 1, 1, 2], dtype=jnp.int32)
    counts = jnp.array([1, 2, 1], dtype=jnp.int32)
    out = f(dest, counts, jnp.ones((4, 2)))[0]
    assert out.shape == (3, 4, 2)
