import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import deposit as deposit_lib
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
from mpi_grid_redistribute_tpu import GridRedistribute

DOMAIN = Domain(0.0, 1.0, periodic=True)
GRID = ProcessGrid((2, 2, 2))
MESH_SHAPE = (8, 8, 8)


def cic_numpy(pos, mass, mesh_shape, domain):
    """Global periodic CIC oracle."""
    M = np.asarray(mesh_shape)
    lo = np.asarray(domain.lo, dtype=np.float64)
    ext = np.asarray(domain.extent, dtype=np.float64)
    rel = (pos.astype(np.float64) - lo) / ext * M
    i0 = np.floor(rel).astype(np.int64)
    frac = rel - i0
    rho = np.zeros(mesh_shape, dtype=np.float64)
    for corner in itertools.product((0, 1), repeat=3):
        off = np.asarray(corner)
        w = np.prod(np.where(off == 1, frac, 1.0 - frac), axis=1)
        idx = (i0 + off) % M
        np.add.at(rho, (idx[:, 0], idx[:, 1], idx[:, 2]), mass * w)
    return rho


def _deposit_inputs(rng, n_local=200):
    R = GRID.nranks
    pos = rng.uniform(0, 1, size=(R * n_local, 3)).astype(np.float32)
    mass = rng.uniform(0.5, 2.0, size=(R * n_local,)).astype(np.float32)
    return pos, mass


def test_deposit_matches_numpy_oracle(rng):
    pos, mass = _deposit_inputs(rng)
    # deposit requires particles on their owner shard first
    rd = GridRedistribute(DOMAIN, GRID, capacity_factor=3.0, out_capacity=800)
    res = rd.redistribute(pos, mass)
    mesh = mesh_lib.make_mesh(GRID)
    dep = deposit_lib.build_deposit(mesh, DOMAIN, GRID, MESH_SHAPE)
    rho = np.asarray(dep(res.positions, res.fields[0], res.count))
    assert rho.shape == MESH_SHAPE
    expected = cic_numpy(pos, mass, MESH_SHAPE, DOMAIN)
    np.testing.assert_allclose(rho, expected, rtol=2e-4, atol=1e-4)
    np.testing.assert_allclose(rho.sum(), mass.sum(), rtol=1e-5)


def test_deposit_single_particle_weights():
    # one particle at a known fractional position on rank 0
    pos = np.zeros((8, 3), dtype=np.float32)
    pos[0] = [0.15625, 0.03125, 0.0625]  # rel = (1.25, 0.25, 0.5) on 8^3
    mass = np.zeros((8,), dtype=np.float32)
    mass[0] = 2.0
    count = np.array([1, 0, 0, 0, 0, 0, 0, 0], dtype=np.int32)
    mesh = mesh_lib.make_mesh(GRID)
    dep = deposit_lib.build_deposit(mesh, DOMAIN, GRID, MESH_SHAPE)
    rho = np.asarray(dep(pos, mass, count))
    expected = cic_numpy(pos[:1], mass[:1], MESH_SHAPE, DOMAIN)
    np.testing.assert_allclose(rho, expected, rtol=1e-5, atol=1e-6)
    assert rho[1, 0, 0] == pytest.approx(2.0 * 0.75 * 0.75 * 0.5)


def test_deposit_ghost_fold_across_faces(rng):
    # particles hugging the upper faces spill into neighbor shards (and wrap)
    R = GRID.nranks
    pos = np.full((R * 50, 3), 0.999, dtype=np.float32)
    mass = np.ones((R * 50,), dtype=np.float32)
    rd = GridRedistribute(DOMAIN, GRID, capacity_factor=8.0, out_capacity=R * 50)
    res = rd.redistribute(pos, mass)
    mesh = mesh_lib.make_mesh(GRID)
    dep = deposit_lib.build_deposit(mesh, DOMAIN, GRID, MESH_SHAPE)
    rho = np.asarray(dep(res.positions, res.fields[0], res.count))
    expected = cic_numpy(pos, mass, MESH_SHAPE, DOMAIN)
    np.testing.assert_allclose(rho, expected, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(rho.sum(), mass.sum(), rtol=1e-5)


def cic_numpy_clamped(pos, mass, mesh_shape, domain):
    """Global CIC oracle for non-periodic axes: cells+1 node planes, no
    wrap, boundary particles clamp into the last cell (frac -> 1)."""
    M = np.asarray(mesh_shape)
    per = np.asarray(domain.periodic)
    lo = np.asarray(domain.lo, dtype=np.float64)
    ext = np.asarray(domain.extent, dtype=np.float64)
    rel = (pos.astype(np.float64) - lo) / ext * M
    i0 = np.clip(np.floor(rel).astype(np.int64), 0, M - 1)
    frac = np.clip(rel - i0, 0.0, 1.0)
    nodes = tuple(m if p else m + 1 for m, p in zip(mesh_shape, per))
    rho = np.zeros(nodes, dtype=np.float64)
    for corner in itertools.product((0, 1), repeat=3):
        off = np.asarray(corner)
        w = np.prod(np.where(off == 1, frac, 1.0 - frac), axis=1)
        idx = np.where(per, (i0 + off) % M, i0 + off)
        np.add.at(rho, (idx[:, 0], idx[:, 1], idx[:, 2]), mass * w)
    return rho


@pytest.mark.parametrize(
    "periodic", [False, (True, False, True)], ids=["open", "mixed"]
)
def test_deposit_nonperiodic_matches_oracle(rng, periodic):
    # round-1 verdict item 8: non-periodic CIC — one extra clamp-edge node
    # plane per open axis, assembled dense + replicated; boundary mass at
    # the upper faces lands on the last plane instead of wrapping.
    dom = Domain(0.0, 1.0, periodic=periodic)
    pos, mass = _deposit_inputs(rng, n_local=300)
    pos[:40] = 0.999999  # exercise the upper boundary planes
    pos[40:80, 0] = 0.0
    rd = GridRedistribute(dom, GRID, capacity_factor=4.0, out_capacity=1200)
    res = rd.redistribute(pos, mass)
    mesh = mesh_lib.make_mesh(GRID)
    dep = deposit_lib.build_deposit(mesh, dom, GRID, MESH_SHAPE)
    rho = np.asarray(dep(res.positions, res.fields[0], res.count))
    assert rho.shape == deposit_lib.global_node_shape(dom, MESH_SHAPE)
    expected = cic_numpy_clamped(pos, mass, MESH_SHAPE, dom)
    np.testing.assert_allclose(rho, expected, rtol=2e-4, atol=1e-4)
    np.testing.assert_allclose(rho.sum(), mass.sum(), rtol=1e-5)


def test_deposit_nonperiodic_migrate_step(rng):
    # the masked (migrate-path) deposit also supports open domains
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.ops import binning

    dom = Domain(0.0, 1.0, periodic=False)
    R = GRID.nranks
    n_local = 64
    cfg = nbody.DriftConfig(
        domain=dom, grid=GRID, dt=0.0, capacity=16, n_local=n_local,
        deposit_shape=(4, 4, 4),
    )
    mesh = mesh_lib.make_mesh(GRID)
    step = nbody.make_migrate_step(cfg, mesh)
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    dest = binning.rank_of_position(pos, dom, GRID, xp=np)
    alive = dest == np.repeat(np.arange(R), n_local)
    vel = np.zeros_like(pos)
    out = jax.tree.map(np.asarray, step(pos, vel, alive))
    rho = out[-1]
    assert rho.shape == (5, 5, 5)
    np.testing.assert_allclose(rho.sum(), alive.sum(), rtol=1e-5)


def test_deposit_rejects_indivisible_mesh():
    with pytest.raises(ValueError):
        deposit_lib.shard_deposit_fn(DOMAIN, GRID, (9, 8, 8))


def test_masked_deposit_ignores_garbage_holes(rng, _devices):
    """Dead slots may hold NaN/Inf bytes (migration holes); the masked
    deposit must still produce a finite, mass-conserving mesh."""
    import jax
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    grid = ProcessGrid((2, 2, 2))
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 32
    n = R * n_local
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.0, capacity=4, n_local=n_local,
        deposit_shape=(4, 4, 4),
    )
    step = nbody.make_migrate_step(cfg, mesh)

    pos = rng.random((n, 3), dtype=np.float32)
    from mpi_grid_redistribute_tpu.ops import binning
    dest = binning.rank_of_position(pos, domain, grid, xp=np)
    alive = dest == np.repeat(np.arange(R), n_local)
    pos[~alive] = np.nan  # garbage holes
    vel = np.zeros((n, 3), dtype=np.float32)

    out = jax.tree.map(np.asarray, step(pos, vel, alive))
    rho = out[-1]
    assert np.isfinite(rho).all()
    assert np.isclose(rho.sum(), alive.sum(), rtol=1e-4)


def test_scan_deposit_matches_segment(rng, _devices):
    """The scatter-free 'scan' deposit agrees with segment_sum tightly
    (double-float prefixes), including NaN holes and ghost fold."""
    import jax
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import deposit as dep

    N = 50000
    M = (8, 8, 8)
    pos = rng.random((N, 3)).astype(np.float32)
    mass = rng.random(N).astype(np.float32)
    valid = rng.random(N) > 0.1
    pos[~valid] = np.nan
    lo = jnp.zeros(3)
    inv_h = jnp.full(3, 8.0)
    a = np.asarray(
        dep.cic_deposit_local(
            jnp.asarray(pos), jnp.asarray(mass), jnp.asarray(valid), lo,
            inv_h, M,
        )
    )
    b = np.asarray(
        dep.cic_deposit_local_sorted(
            jnp.asarray(pos), jnp.asarray(mass), jnp.asarray(valid), lo,
            inv_h, M,
        )
    )
    assert np.isfinite(b).all()
    np.testing.assert_allclose(b.sum(), a.sum(), rtol=1e-5)
    np.testing.assert_allclose(b, a, atol=a.max() * 1e-6)


def test_scan_deposit_accuracy_vs_float64_oracle(rng, _devices):
    """Round-1 verdict item 5: the fast path's per-cell error vs a float64
    oracle is <=1e-5 relative, at scale, on clustered data.

    The f64 oracle sums the *same f32 per-particle weights* in float64, so
    the comparison isolates summation error (the thing the double-float
    prefix scheme fixes) from the shared f32 frac quantization. Strict
    per-cell relative error is checked for every cell above 1e-6 of the
    peak (below that, the ~eps^2 * channel-total double-float floor
    dominates any fixed-precision prefix scheme)."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import deposit as dep

    N = 1_000_000
    M = (16, 16, 16)
    pos = (rng.lognormal(-1.5, 0.5, size=(N, 3)) % 1.0).astype(np.float32)
    mass = rng.uniform(0.5, 2.0, N).astype(np.float32)
    valid = rng.random(N) > 0.05
    lo = jnp.zeros(3)
    inv_h = jnp.full(3, float(M[0]))
    got = np.asarray(
        dep.cic_deposit_local_sorted(
            jnp.asarray(pos), jnp.asarray(mass), jnp.asarray(valid), lo,
            inv_h, M,
        )
    )
    # float64 oracle over the f32 weight pipeline
    posv, massv = pos[valid], mass[valid]
    rel32 = posv * np.asarray(M, np.float32)
    i0 = np.clip(np.floor(rel32).astype(np.int64), 0, np.asarray(M) - 1)
    frac = np.clip(rel32 - i0.astype(np.float32), 0, 1).astype(np.float32)
    rho = np.zeros(tuple(m + 1 for m in M))
    for corner in itertools.product((0, 1), repeat=3):
        off = np.asarray(corner)
        w = np.prod(
            np.where(off == 1, frac, np.float32(1) - frac), axis=1
        ).astype(np.float32)
        wf = (massv * w).astype(np.float32)
        idx = i0 + off
        np.add.at(rho, (idx[:, 0], idx[:, 1], idx[:, 2]), wf.astype(np.float64))

    diff = np.abs(got - rho)
    floor = rho.max() * 1e-6
    cells = rho > floor
    max_rel = (diff[cells] / rho[cells]).max()
    assert max_rel <= 1e-5, f"max per-cell relative error {max_rel:.2e}"
    assert diff.max() <= rho.max() * 1e-5  # normalized max error, all cells
    np.testing.assert_allclose(got.sum(), rho.sum(), rtol=1e-6)


def test_planar_deposit_matches_rowmajor(rng, _devices):
    """Round-4 planar deposit: component-major [D, V*n] input, no [n, D]
    buffer anywhere — per-cell values are BIT-IDENTICAL to the row-major
    scan deposit (both cores sort by (key, iota) with two compare keys,
    pinning the within-cell summation order)."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.domain import ProcessGrid
    from mpi_grid_redistribute_tpu.ops import deposit as dep

    V, n = 8, 40000
    vblock = (8, 8, 8)
    pos = rng.random((V, n, 3)).astype(np.float32)
    mass = rng.random((V, n)).astype(np.float32)
    valid = rng.random((V, n)) > 0.1
    # per-vrank origins on a 2x2x2 subgrid of a [0,1) domain
    vg = ProcessGrid((2, 2, 2))
    lo = np.asarray(
        [np.asarray(vg.cell_of_rank(v)) * 0.5 for v in range(V)],
        np.float32,
    )
    pos_abs = lo[:, None, :] + pos * 0.5
    inv_h = jnp.full(3, 16.0)  # vblock 8 over width 0.5
    a = np.asarray(
        dep.cic_deposit_vranks_sorted(
            jnp.asarray(pos_abs), jnp.asarray(mass), jnp.asarray(valid),
            jnp.asarray(lo), inv_h, vblock,
        )
    )
    pos_rows = jnp.asarray(
        np.ascontiguousarray(pos_abs.transpose(2, 0, 1)).reshape(3, V * n)
    )
    b = np.asarray(
        dep.cic_deposit_vranks_planar(
            pos_rows, jnp.asarray(mass.reshape(-1)),
            jnp.asarray(valid.reshape(-1)), jnp.asarray(lo), inv_h,
            vblock,
        )
    )
    np.testing.assert_array_equal(b.view(np.uint32), a.view(np.uint32))


def test_device_planar_deposit_matches_local_sorted(rng, _devices):
    """Late-round-4 DEVICE-keyed planar deposit: keys by device-local
    global cell (no per-vrank assembly) — bit-identical to the row-major
    single-block scan deposit on the same inputs (same (key, iota) sort
    contract), and mass-conserving."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import deposit as dep

    n = 120000
    dev_block = (16, 16, 16)
    pos = rng.random((n, 3)).astype(np.float32)
    mass = rng.random(n).astype(np.float32)
    valid = rng.random(n) > 0.1
    lo = jnp.zeros(3)
    inv_h = jnp.full(3, 16.0)
    a = np.asarray(
        dep.cic_deposit_local_sorted(
            jnp.asarray(pos), jnp.asarray(mass), jnp.asarray(valid),
            lo, inv_h, dev_block,
        )
    )
    pos_rows = jnp.asarray(np.ascontiguousarray(pos.T))
    b = np.asarray(
        dep.cic_deposit_device_planar(
            pos_rows, jnp.asarray(mass), jnp.asarray(valid),
            lo, inv_h, dev_block,
        )
    )
    np.testing.assert_array_equal(b.view(np.uint32), a.view(np.uint32))
    np.testing.assert_allclose(b.sum(), mass[valid].sum(), rtol=1e-5)
    # the channel-grouped form (the >16M-row memory bound) is bit-identical
    key = jnp.zeros(n, jnp.int32)
    strides = dep._row_major_strides(dev_block)
    rel = jnp.where(jnp.asarray(valid)[None, :],
                    jnp.asarray(pos_rows) * 16.0, 0.0)
    for d in range(3):
        i0 = jnp.clip(
            jnp.floor(rel[d]).astype(jnp.int32), 0, dev_block[d] - 1
        )
        key = key + i0 * jnp.int32(strides[d])
    key = jnp.where(jnp.asarray(valid), key, jnp.int32(16 ** 3))
    mass_z = jnp.where(jnp.asarray(valid), jnp.asarray(mass), 0.0)
    c = np.asarray(dep._sorted_per_segment_planar(
        key, rel, mass_z, 16 ** 3, dev_block, 256, channel_group=2,
    ))
    d = np.asarray(dep._sorted_per_segment_planar(
        key, rel, mass_z, 16 ** 3, dev_block, 256, channel_group=None,
    ))
    np.testing.assert_array_equal(c.view(np.uint32), d.view(np.uint32))


def test_device_planar_deposit_sharded_oracle(rng, _devices):
    """Device-keyed planar deposit through shard_map on a 2x2x2 mesh:
    matches the global NumPy CIC oracle and conserves mass."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from mpi_grid_redistribute_tpu.compat import shard_map
    from mpi_grid_redistribute_tpu.ops import deposit as dep
    from mpi_grid_redistribute_tpu.bench import common

    dom = Domain(0.0, 1.0, periodic=True)
    dev_grid = ProcessGrid((2, 2, 2))
    mesh = mesh_lib.make_mesh(dev_grid)
    n = 4096
    fn = dep.shard_deposit_device_planar_fn(dom, dev_grid, MESH_SHAPE)
    spec = P(dev_grid.axis_names)
    wrapped = jax.jit(
        shard_map(
            fn, mesh=mesh,
            in_specs=(P(None, dev_grid.axis_names), spec, spec),
            out_specs=dep.deposit_out_spec(dom, dev_grid),
        )
    )
    pos, _, _ = common.uniform_state((2, 2, 2), n, 1.0, rng)
    pos_rows = np.ascontiguousarray(
        pos.reshape(8, n, 3).transpose(2, 0, 1)
    ).reshape(3, 8 * n)
    mass = np.ones(8 * n, np.float32)
    valid = np.ones(8 * n, bool)
    rho = np.asarray(wrapped(pos_rows, mass, valid))
    np.testing.assert_allclose(rho.sum(), 8 * n, rtol=1e-6)
    expected = cic_numpy(
        pos_rows.T.astype(np.float32), mass, MESH_SHAPE, dom
    )
    np.testing.assert_allclose(rho, expected, rtol=2e-4, atol=1e-4)


def test_planar_deposit_conserves_and_places(rng, _devices):
    """Mass conservation + correct block placement for the planar deposit
    through the shard-level wrapper (fold_ghosts path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from mpi_grid_redistribute_tpu.compat import shard_map
    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.ops import deposit as dep
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    dom = Domain(0.0, 1.0, periodic=True)
    dev_grid = ProcessGrid((2, 2, 2))
    vgrid = ProcessGrid((1, 1, 1))
    mesh = mesh_lib.make_mesh(dev_grid)
    n = 4096
    fn = dep.shard_deposit_vranks_planar_fn(dom, dev_grid, vgrid, (16, 16, 16))
    spec = P(dev_grid.axis_names)
    wrapped = jax.jit(
        shard_map(
            fn, mesh=mesh, in_specs=(P(None, dev_grid.axis_names), spec, spec),
            out_specs=dep.deposit_out_spec(dom, dev_grid),
        )
    )
    from mpi_grid_redistribute_tpu.bench import common
    pos, _, _ = common.uniform_state((2, 2, 2), n, 1.0, rng)
    pos_rows = np.ascontiguousarray(
        pos.reshape(8, n, 3).transpose(2, 0, 1)
    ).reshape(3, 8 * n)
    mass = np.ones(8 * n, np.float32)
    valid = np.ones(8 * n, bool)
    rho = np.asarray(wrapped(pos_rows, mass, valid))
    np.testing.assert_allclose(rho.sum(), 8 * n, rtol=1e-6)


def test_drift_loop_scan_deposit_method(rng, _devices):
    """deposit_method='scan' plumbs through BOTH the fused config-5 step
    and make_drift_loop (incl. deposit_each_step, the benchmark path)."""
    import jax
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    grid = ProcessGrid((2, 2, 2))
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 64
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.01, capacity=16, n_local=n_local,
        deposit_shape=(8, 8, 8), deposit_method="scan",
    )
    step = nbody.make_drift_step(cfg, mesh)
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    vel = np.zeros((R * n_local, 3), np.float32)
    count = np.full((R,), n_local, np.int32)
    out = jax.tree.map(np.asarray, step(pos, vel, count))
    loop = nbody.make_drift_loop(cfg, mesh, 3, deposit_each_step=True)
    lout = jax.tree.map(np.asarray, loop(pos, vel, count))
    np.testing.assert_allclose(
        lout[-1].sum(), lout[2].sum(), rtol=1e-4
    )
    rho = out[-1]
    # scattered initial placement overflows out_capacity on some shards;
    # the drops are surfaced, and deposited mass must match survivors
    survivors = out[2].sum()
    dropped = out[3].dropped_recv.sum()
    assert survivors + dropped == R * n_local
    np.testing.assert_allclose(rho.sum(), survivors, rtol=1e-4)


def test_migrate_loop_deposit_each_step(rng, _devices):
    """deposit_each_step on the migrate loop (config-5 fused workload):
    every scanned step deposits; the carried mesh equals a standalone
    deposit of the final state and conserves mass."""
    import jax
    from mpi_grid_redistribute_tpu.models import nbody

    grid = ProcessGrid((2, 2, 2))
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 64
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=0.01, capacity=16, n_local=n_local,
        deposit_shape=(8, 8, 8),
    )
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    vel = (rng.random((R * n_local, 3), dtype=np.float32) - 0.5).astype(
        np.float32
    ) * 0.01
    alive = rng.random(R * n_local) > 0.2
    loop = nbody.make_migrate_loop(cfg, mesh, 3, deposit_each_step=True)
    p, v, a, st, rho = jax.tree.map(np.asarray, loop(pos, vel, alive))
    p = nbody.planar_to_rows(p, 3, mesh.size)
    survivors = int(a.sum())
    np.testing.assert_allclose(rho.sum(), survivors, rtol=1e-4)
    # equals a standalone deposit of the final state
    dep = nbody.build_deposit_masked(cfg, mesh)
    rho2 = np.asarray(dep(p, np.ones(p.shape[0], np.float32), a))
    np.testing.assert_allclose(rho, rho2, rtol=1e-5, atol=1e-5)

    # vrank variant of the same fused workload
    dev_grid = ProcessGrid((2, 1, 1))
    vgrid = ProcessGrid((1, 2, 2))
    vmesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:2])
    vcfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.01, capacity=16,
        n_local=n_local, deposit_shape=(8, 8, 8),
    )
    vloop = nbody.make_migrate_loop(
        vcfg, vmesh, 3, vgrid=vgrid, deposit_each_step=True
    )
    pv, vv, av, stv, rhov = jax.tree.map(np.asarray, vloop(pos, vel, alive))
    np.testing.assert_allclose(rhov.sum(), av.sum(), rtol=1e-4)

    # non-periodic variant: the dense-assembled rho ends in a psum
    # (axis-invariant), and the scan carry must match (regression:
    # a varying init failed lax.scan's carry-type check)
    for per in (False, (True, True, False)):
        odom = Domain(0.0, 1.0, periodic=per)
        ocfg = nbody.DriftConfig(
            domain=odom, grid=grid, dt=0.0, capacity=16, n_local=n_local,
            deposit_shape=(8, 8, 8),
        )
        oloop = nbody.make_migrate_loop(ocfg, mesh, 2,
                                        deposit_each_step=True)
        oo = jax.tree.map(np.asarray, oloop(pos, vel, alive))
        rho_o = oo[-1]
        assert rho_o.shape == deposit_lib.global_node_shape(odom, (8, 8, 8))
        np.testing.assert_allclose(rho_o.sum(), oo[2].sum(), rtol=1e-4)


def test_vrank_deposit_matches_flat(rng, _devices):
    """Deposit through the vrank migrate loop equals the same particles
    deposited on the equivalent flat grid."""
    import jax
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
    from mpi_grid_redistribute_tpu.ops import binning

    dev_grid = ProcessGrid((2, 1, 1))
    vgrid = ProcessGrid((2, 2, 1))
    full = ProcessGrid((4, 2, 1))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 128
    R = 8
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:2])
    dshape = (8, 8, 8)

    # particles legally placed per slab (device-major slabs of the full grid)
    from tests.test_migrate import _slab_full_ranks

    _, slab_rank = _slab_full_ranks(dev_grid, vgrid)
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    dest = binning.rank_of_position(pos, domain, full, xp=np)
    alive = dest == np.repeat(slab_rank, n_local)
    vel = np.zeros_like(pos)

    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.0, capacity=8, n_local=n_local,
        deposit_shape=dshape,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, 1, vgrid=vgrid)
    out = jax.tree.map(np.asarray, loop(pos, vel, alive))
    rho = out[-1]
    assert rho.shape == dshape
    np.testing.assert_allclose(rho.sum(), alive.sum(), rtol=1e-5)

    expected = cic_numpy(pos[alive], np.ones(alive.sum(), np.float32),
                         dshape, domain)
    np.testing.assert_allclose(rho, expected, rtol=1e-4, atol=1e-4)


def test_pallas_dfscan_bit_identical_to_xla():
    """The VMEM double-float prefix kernel must reproduce _df_cumsum
    bit-for-bit — the scan deposit's accuracy contract rides on the
    exact TwoSum sequence."""
    import numpy as np
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import deposit, pallas_dfscan

    r = np.random.default_rng(11)
    for rows, tile in [(7, 256), (300, 128), (1025, 64)]:
        x = (r.random((rows, tile), dtype=np.float32) - 0.5) * np.exp(
            r.normal(0, 8, size=(rows, tile))
        ).astype(np.float32)
        hi_ref, lo_ref = deposit._df_cumsum(jnp.asarray(x), axis=1)
        hi_k, lo_k = pallas_dfscan.tile_df_cumsum_rows(
            jnp.asarray(x), interpret=True
        )
        assert np.array_equal(
            np.asarray(hi_ref).view(np.uint32),
            np.asarray(hi_k).view(np.uint32),
        ), (rows, tile)
        assert np.array_equal(
            np.asarray(lo_ref).view(np.uint32),
            np.asarray(lo_k).view(np.uint32),
        ), (rows, tile)


def test_segdep_kernel_matches_xla_fallback(rng):
    """The Pallas segmented-sum deposit kernel (interpret mode) matches
    the XLA segment_sum fallback on the same sorted stream — across
    sentinels, empty cells, multi-chunk spans, and block boundaries."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import pallas_segdep as sd

    for n, density, vblock in [(10_000, 1.0, (8, 8, 8)),
                               (9_000, 0.05, (16, 16, 16)),
                               (4096, 0.0, (8, 8, 8)),
                               (100, 1.0, (8, 8, 8)),
                               (5_000, 0.01, (16, 16, 16))]:
        n_cells = int(np.prod(vblock))
        if density:
            # density < 1 clusters all keys into a FRACTION of the cell
            # range, so blocks span many empty canvas chunks — the
            # kernel's flush-forward gap handling is actually exercised
            hot = max(1, int(n_cells * density))
            cells = rng.choice(n_cells, size=hot, replace=False)
            key = cells[rng.integers(0, hot, size=n)].astype(np.int32)
            valid = rng.random(n) < 0.9
        else:
            key = np.zeros(n, np.int32)
            valid = np.zeros(n, bool)
        key = np.sort(np.where(valid, key, n_cells)).astype(np.int32)
        rel = (rng.random((3, n)) * vblock[0]).astype(np.float32)
        mass = rng.random(n).astype(np.float32)
        a = np.asarray(
            sd._segsum_tpu(
                jnp.asarray(key), jnp.asarray(rel), jnp.asarray(mass),
                n_cells, vblock, 3, interpret=True,
            )
        )
        b = np.asarray(
            sd._segsum_xla(
                jnp.asarray(key), jnp.asarray(rel), jnp.asarray(mass),
                n_cells, vblock, 3,
            )
        )
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)
        # unit-mass (mass=None) drops the operand and multiplies by 1
        au = np.asarray(
            sd._segsum_tpu(
                jnp.asarray(key), jnp.asarray(rel), None,
                n_cells, vblock, 3, interpret=True,
            )
        )
        bu = np.asarray(
            sd._segsum_xla(
                jnp.asarray(key), jnp.asarray(rel), None,
                n_cells, vblock, 3,
            )
        )
        np.testing.assert_allclose(au, bu, rtol=1e-6, atol=1e-6)


def test_mxu_deposit_accuracy_and_conservation(rng, _devices):
    """cic_deposit_device_mxu vs the float64 oracle (same tolerance the
    scan engine is held to) + exact-class conservation; and the fused
    migrate loop runs end-to-end with deposit_method='mxu'."""
    import jax
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import deposit as dep
    from mpi_grid_redistribute_tpu.models import nbody

    n = 120_000
    dev_block = (16, 16, 16)
    pos = rng.random((n, 3)).astype(np.float32)
    mass = rng.random(n).astype(np.float32)
    valid = rng.random(n) > 0.1
    pos_rows = jnp.asarray(np.ascontiguousarray(pos.T))
    rho = np.asarray(
        dep.cic_deposit_device_mxu(
            pos_rows, jnp.asarray(mass), jnp.asarray(valid),
            jnp.zeros(3), jnp.full(3, 16.0), dev_block,
        )
    )
    np.testing.assert_allclose(rho.sum(), mass[valid].sum(), rtol=1e-5)
    # f64 oracle per-cell (ghost mesh, no fold)
    rel = pos.astype(np.float64) * 16.0
    i0 = np.clip(np.floor(rel).astype(np.int64), 0, 15)
    frac = rel - i0
    want = np.zeros((17, 17, 17))
    import itertools as it
    for corner in it.product((0, 1), repeat=3):
        off = np.asarray(corner)
        w = np.prod(np.where(off == 1, frac, 1.0 - frac), axis=1)
        idx = i0 + off
        np.add.at(
            want, (idx[:, 0], idx[:, 1], idx[:, 2]),
            np.where(valid, mass.astype(np.float64) * w, 0.0),
        )
    np.testing.assert_allclose(rho, want, rtol=2e-5, atol=2e-5)

    # fused loop end-to-end (CPU: exercises the XLA fallback path)
    grid = ProcessGrid((2, 2, 2))
    mesh = mesh_lib.make_mesh(grid)
    n_local = 64
    cfg = nbody.DriftConfig(
        domain=Domain(0.0, 1.0, periodic=True), grid=grid, dt=0.01,
        capacity=16, n_local=n_local, deposit_shape=(8, 8, 8),
        deposit_method="mxu",
    )
    R = grid.nranks
    pos2 = rng.random((R * n_local, 3), dtype=np.float32)
    vel2 = (rng.random((R * n_local, 3), dtype=np.float32) - 0.5) * 0.01
    alive = rng.random(R * n_local) > 0.2
    loop = nbody.make_migrate_loop(cfg, mesh, 3, deposit_each_step=True)
    out = jax.tree.map(np.asarray, loop(pos2, vel2.astype(np.float32), alive))
    rho2 = out[-1]
    np.testing.assert_allclose(rho2.sum(), out[2].sum(), rtol=1e-4)


def test_segdep_kernel_slab_stream(rng):
    """Concatenated per-slab sorts are a legal kernel stream (the
    CHUNK-MONOTONE contract): vrank-major keys sorted per slab leave
    sentinel runs MID-stream — including T-blocks that START with
    sentinels — and the min-key block starts must still match the XLA
    fallback."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import pallas_segdep as sd

    V, vblock = 4, (8, 8, 8)
    C = int(np.prod(vblock))
    n_cells = V * C
    # slab 0 is 1.5 T-blocks long and 97% invalid, so block 1 STARTS
    # inside slab 0's sentinel tail (k2[0,0] == sentinel while the block
    # holds valid slab-1 keys: the exact case k2[0,0]-based starts skip)
    slab_sizes = [6144, 3000, 4096, 500]
    valid_frac = [0.03, 0.8, 0.5, 1.0]
    keys = []
    for v, (sn, vf) in enumerate(zip(slab_sizes, valid_frac)):
        valid = rng.random(sn) < vf
        k = np.where(
            valid, v * C + rng.integers(0, C, size=sn), n_cells
        )
        keys.append(np.sort(k.astype(np.int32)))
    key = np.concatenate(keys)
    m = key.shape[0]
    rel = (rng.random((3, m)) * vblock[0]).astype(np.float32)
    mass = rng.random(m).astype(np.float32)
    for mz in (jnp.asarray(mass), None):
        a = np.asarray(
            sd._segsum_tpu(
                jnp.asarray(key), jnp.asarray(rel), mz,
                n_cells, vblock, 3, interpret=True,
            )
        )
        b = np.asarray(
            sd._segsum_xla(
                jnp.asarray(key), jnp.asarray(rel), mz,
                n_cells, vblock, 3,
            )
        )
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_slab_mxu_deposit_matches_flat_engine(rng):
    """cic_deposit_vranks_mxu (slab-keyed, per-slab sorts, vrank-major
    canvas remap) against the flat device-keyed engine AND the float64
    oracle, on slab-consistent data (each slab's rows inside its vrank's
    region — the post-redistribute invariant)."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import deposit as dep

    vgrid_shape = (2, 2, 1)
    V = int(np.prod(vgrid_shape))
    dev_block = (16, 16, 16)
    vblock = tuple(b // v for b, v in zip(dev_block, vgrid_shape))
    n = 30_000
    pos = np.empty((V * n, 3), np.float32)
    vcells = list(itertools.product(*[range(g) for g in vgrid_shape]))
    for v, vc in enumerate(vcells):
        lo = np.asarray(vc) / np.asarray(vgrid_shape)
        wid = 1.0 / np.asarray(vgrid_shape)
        pos[v * n : (v + 1) * n] = (
            lo + rng.random((n, 3)) * wid
        ).astype(np.float32)
    mass = rng.uniform(0.5, 2.0, size=(V * n,)).astype(np.float32)
    valid = rng.random(V * n) > 0.1
    pos_rows = jnp.asarray(np.ascontiguousarray(pos.T))
    lo_all = jnp.asarray(
        np.asarray(vcells, np.float32) / np.asarray(vgrid_shape, np.float32)
    )
    rho_slab = np.asarray(
        dep.cic_deposit_vranks_mxu(
            pos_rows, jnp.asarray(mass), jnp.asarray(valid),
            lo_all, jnp.full(3, 16.0), vblock, vgrid_shape,
        )
    )
    rho_flat = np.asarray(
        dep.cic_deposit_device_mxu(
            pos_rows, jnp.asarray(mass), jnp.asarray(valid),
            jnp.zeros(3), jnp.full(3, 16.0), dev_block,
        )
    )
    # block-local vs device-relative rel arithmetic differ by ~1 ulp
    np.testing.assert_allclose(rho_slab, rho_flat, rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(
        rho_slab.sum(), mass[valid].sum(), rtol=1e-5
    )
    # f64 oracle (ghost mesh, no fold)
    rel = pos.astype(np.float64) * 16.0
    i0 = np.clip(np.floor(rel).astype(np.int64), 0, 15)
    frac = rel - i0
    want = np.zeros((17, 17, 17))
    for corner in itertools.product((0, 1), repeat=3):
        off = np.asarray(corner)
        w = np.prod(np.where(off == 1, frac, 1.0 - frac), axis=1)
        idx = i0 + off
        np.add.at(
            want, (idx[:, 0], idx[:, 1], idx[:, 2]),
            np.where(valid, mass.astype(np.float64) * w, 0.0),
        )
    np.testing.assert_allclose(rho_slab, want, rtol=2e-5, atol=2e-5)

    # unit mass (mass=None) drops the sort operand on the slab path too
    rho_unit = np.asarray(
        dep.cic_deposit_vranks_mxu(
            pos_rows, None, jnp.asarray(valid),
            lo_all, jnp.full(3, 16.0), vblock, vgrid_shape,
        )
    )
    np.testing.assert_allclose(rho_unit.sum(), valid.sum(), rtol=1e-5)


def test_fused_loop_slab_mxu_deposit(rng, _devices):
    """The fused vrank loop with deposit_method='mxu' routes the
    slab-keyed engine (canonical block vranks) and conserves mass; its
    density matches the double-float scan engine at f32 tolerance."""
    import jax
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((2, 2, 2))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 256
    R = vgrid.nranks
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    vel = (rng.random((R * n_local, 3), dtype=np.float32) - 0.5) * 0.02
    alive = rng.random(R * n_local) > 0.2
    rhos = {}
    for method in ("mxu", "scan"):
        cfg = nbody.DriftConfig(
            domain=domain, grid=dev_grid, dt=0.01, capacity=64,
            n_local=n_local, deposit_shape=(8, 8, 8),
            deposit_method=method,
        )
        loop = nbody.make_migrate_loop(
            cfg, mesh, 3, vgrid=vgrid, deposit_each_step=True
        )
        out = jax.tree.map(np.asarray, loop(pos, vel, alive))
        rhos[method] = out[-1]
        np.testing.assert_allclose(
            out[-1].sum(), out[2].sum(), rtol=1e-4
        )
    np.testing.assert_allclose(
        rhos["mxu"], rhos["scan"], rtol=2e-4, atol=2e-4
    )


def test_slab_mxu_residence_guard_falls_back(rng, _devices):
    """Random (mis-slabbed) starts leave backlogged rows on the wrong
    slab for several steps; the slab engine's residence guard must
    lax.cond-route those steps to the position-keyed flat engine instead
    of silently clamping them into wrong cells (caught by the round-4
    verify drive: 35% of cells off before the guard)."""
    import jax
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    dev_grid = ProcessGrid((2, 1, 1))
    vgrid = ProcessGrid((2, 2, 1))
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = 256
    R = dev_grid.nranks * vgrid.nranks
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:2])
    # deliberately scattered start + tight capacity: rows stay
    # mis-slabbed (backlogged) across the 3 deposited steps
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    vel = (rng.random((R * n_local, 3), dtype=np.float32) - 0.5) * 0.02
    alive = rng.random(R * n_local) > 0.2
    rhos = {}
    for method in ("mxu", "scan"):
        cfg = nbody.DriftConfig(
            domain=domain, grid=dev_grid, dt=0.01, capacity=48,
            n_local=n_local, deposit_shape=(8, 8, 8),
            deposit_method=method,
        )
        loop = nbody.make_migrate_loop(
            cfg, mesh, 3, vgrid=vgrid, deposit_each_step=True
        )
        out = jax.tree.map(np.asarray, loop(pos, vel, alive))
        rhos[method] = out[-1]
        np.testing.assert_allclose(out[-1].sum(), out[2].sum(), rtol=1e-4)
    np.testing.assert_allclose(
        rhos["mxu"], rhos["scan"], rtol=2e-4, atol=2e-4
    )


def test_slab_mxu_fast_path_engages(rng, _devices, monkeypatch):
    """On slab-resident data the builder must take the SLAB branch (and
    the flat branch on mis-slabbed data) — without this, a regression in
    the lo_all/guard logic would silently route every step to the flat
    engine and erase the slab-sort win with zero CI signal (review
    round 4). Each branch is poisoned in turn to observe which one the
    result follows."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from mpi_grid_redistribute_tpu.compat import shard_map
    from mpi_grid_redistribute_tpu.ops import deposit as dep
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    dom = Domain(0.0, 1.0, periodic=True)
    dev_grid = ProcessGrid((2, 2, 2))
    vgrid = ProcessGrid((2, 1, 1))
    mesh = mesh_lib.make_mesh(dev_grid)
    V, n = vgrid.nranks, 1500
    full = ProcessGrid(
        tuple(d * v for d, v in zip(dev_grid.shape, vgrid.shape))
    )

    def run():
        fn = dep.shard_deposit_device_mxu_fn(
            dom, dev_grid, (8, 8, 8), vgrid=vgrid
        )
        spec = P(dev_grid.axis_names)
        wrapped = jax.jit(shard_map(
            fn, mesh=mesh,
            in_specs=(P(None, dev_grid.axis_names), spec, spec),
            out_specs=dep.deposit_out_spec(dom, dev_grid),
        ))
        return np.asarray(wrapped(pos_rows, mass, valid))

    def slab_positions(legal):
        pos = np.empty((dev_grid.nranks * V * n, 3), np.float32)
        i = 0
        for d in range(dev_grid.nranks):
            dc = dev_grid.cell_of_rank(d)
            for v in range(V):
                vc = vgrid.cell_of_rank(v)
                cell = np.asarray([
                    dc[a] * vgrid.shape[a] + vc[a] for a in range(3)
                ])
                if not legal:
                    cell = (cell + 1) % np.asarray(full.shape)
                lo = cell / np.asarray(full.shape)
                pos[i : i + n] = (
                    lo + rng.random((n, 3)) / np.asarray(full.shape)
                ).astype(np.float32)
                i += n
        return pos

    orig_flat = dep.cic_deposit_device_mxu
    orig_slab = dep._slab_deposit_from_keys

    for legal in (True, False):
        pos = slab_positions(legal)
        mass = rng.uniform(0.5, 2.0, size=(pos.shape[0],)).astype(np.float32)
        valid = rng.random(pos.shape[0]) > 0.1
        pos_rows = np.ascontiguousarray(
            pos.reshape(dev_grid.nranks, V * n, 3).transpose(2, 0, 1)
        ).reshape(3, -1)

        monkeypatch.setattr(dep, "cic_deposit_device_mxu", orig_flat)
        monkeypatch.setattr(dep, "_slab_deposit_from_keys", orig_slab)
        base = run()
        monkeypatch.setattr(
            dep, "cic_deposit_device_mxu",
            lambda *a, **k: orig_flat(*a, **k) + 1000.0,
        )
        flat_poisoned = run()
        monkeypatch.setattr(dep, "cic_deposit_device_mxu", orig_flat)
        monkeypatch.setattr(
            dep, "_slab_deposit_from_keys",
            lambda *a, **k: orig_slab(*a, **k) + 1000.0,
        )
        slab_poisoned = run()
        if legal:
            # slab branch taken: poisoning flat changes nothing,
            # poisoning slab shows up
            np.testing.assert_array_equal(base, flat_poisoned)
            assert np.abs(slab_poisoned - base).max() > 100.0
        else:
            np.testing.assert_array_equal(base, slab_poisoned)
            assert np.abs(flat_poisoned - base).max() > 100.0
