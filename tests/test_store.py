"""Telemetry history plane (telemetry/store.py, query.py) — ISSUE 18 gates.

Six contracts, each tested against hand math, a real corruption, or a
real HTTP exchange:

* exactness — the headline claim: ``metrics.from_journal`` over a
  drained+compacted (and retention-trimmed) store equals the live
  recorder's all-time counts after ring eviction, byte for byte, and
  the manifest's conservation ledger (``counts == retired + segments
  + active + missed``) holds at every stage;
* durability — rotation closes immutable sha256-checksummed segments,
  ``verify()`` catches a single flipped byte, manifest publishes are
  staged-rename atomic (no ``.tmp-`` droppings), and a restarted
  writer resumes from the drain watermark with zero duplicates;
* compaction — non-step events survive verbatim while per-step runs
  collapse into ``store_window`` sketches whose merged quantiles equal
  the live ``Histogram``'s (identical ``STEP_TIME_EDGES`` buckets);
* query plane — filters/group-bys/windowed aggregations against hand
  fixtures, the cursor total order (exact resume, evicted-cursor
  fallback, unknown-shard replay), and the flat-string grammar's
  error surface (unknown param, bad int → ``QueryError``);
* service — ``GET /query``/``GET /events`` over a real store via a
  subprocess ``metrics_serve --store``, cursor-walked to exhaustion;
  the in-process concurrency gate (parallel ``/metrics`` + ``/query``
  + ``/events`` against a LIVE recorder under an armed
  ``ThreadAccessTracer`` — zero unlocked accesses); and the driver
  integration (boundary drains, supervised-restart no-duplication);
* overhead — boundary drains add <= 2% to the config1-style
  steady-state step (the same paired-delta median protocol as the
  recorder+metrics gate in test_metrics.py).

CLI smokes for ``grid_top --once``, ``history`` and ``storecheck``
ride along so ``make check``'s new surfaces stay exercised in tier-1.
"""

import dataclasses
import http.server
import importlib.util
import json
import os
import re
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.telemetry import (
    StepRecorder,
    ThreadAccessTracer,
    from_journal,
    record_chunk_steps,
)
from mpi_grid_redistribute_tpu.telemetry import metrics as metrics_lib
from mpi_grid_redistribute_tpu.telemetry import query as query_lib
from mpi_grid_redistribute_tpu.telemetry import store as store_lib
from mpi_grid_redistribute_tpu.telemetry.query import (
    QueryError,
    events_page,
    filter_rows,
    group_rows,
    run_query,
    window_aggregate,
)
from mpi_grid_redistribute_tpu.telemetry.store import (
    JournalStore,
    StoreCorruptError,
    StoreReader,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SERVE = os.path.join(REPO_ROOT, "scripts", "metrics_serve.py")


def _journal_counter(reader):
    """The scrape-side counts: ``grid_journal_events_total`` per kind
    from ``from_journal`` over the store."""
    reg = from_journal(reader)
    fam = reg.get("grid_journal_events")
    out = {}
    for values, child in fam.children():
        out[values[0]] = int(child._value)
    return out


def _conservation(man):
    """retired + closed segments + active + missed, per kind."""
    total = dict(man["retired"]["counts"])
    for seg in man["segments"]:
        for k, v in seg["counts"].items():
            total[k] = total.get(k, 0) + v
    if man["active"]:
        for k, v in man["active"]["counts"].items():
            total[k] = total.get(k, 0) + v
    for k, v in man["missed"].items():
        total[k] = total.get(k, 0) + v
    return total


def _drive(root, chunks=16, per_chunk=40, capacity=96, **store_kw):
    """A wrapping-ring run drained at every chunk boundary: enough
    volume to force eviction, rotation and (with the right knobs)
    compaction + retention."""
    kw = dict(
        segment_events=120,
        segment_bytes=1 << 20,
        retain_bytes=1 << 30,
        compact_after=1,
        compact_window=16,
    )
    kw.update(store_kw)
    rec = StepRecorder(capacity=capacity, host="h0", pid=7)
    store = JournalStore(str(root), **kw)
    for c in range(chunks):
        record_chunk_steps(
            rec, c * per_chunk, 0.002 * (1 + (c % 3)), [c % 2] * per_chunk
        )
        if c % 4 == 0:
            rec.record(
                "alert", rule="imbalance_ratio", severity="WARN",
                value=1.0 + c, step=c * per_chunk,
            )
        if c % 7 == 0:
            rec.record(
                "flow_snapshot", imbalance_ratio=1.0 + 0.1 * c,
                total_rows=64, step=c * per_chunk,
            )
        store.drain(rec)
    return rec, store


# ====================================================== exactness


def test_counts_exact_after_eviction_and_compaction(tmp_path):
    """The ISSUE 18 headline: after the ring evicted hundreds of events
    and old raw segments were compacted to sketches, the store's counts
    — manifest-side AND through a full ``from_journal`` scrape — equal
    the live recorder's all-time counts exactly."""
    rec, store = _drive(tmp_path / "store")
    assert rec.evicted > 0, "ring never wrapped — test is vacuous"
    man = store.manifest
    assert any(s["kind"] == "summary" for s in man["segments"]), (
        "nothing compacted — test is vacuous"
    )
    reader = store.reader()
    assert reader.counts() == rec.counts()
    assert _journal_counter(reader) == rec.counts()
    assert _conservation(man) == rec.counts()
    # the live scrape agrees with the store scrape, counter for counter
    assert _journal_counter(reader) == _journal_counter(rec)


def test_counts_exact_after_retention(tmp_path):
    """Retention deletes the oldest segments but folds their per-kind
    counts into the ``retired`` ledger — all-time counts survive the
    disk bound, and closed-segment bytes respect it."""
    bound = 26 << 10
    rec, store = _drive(tmp_path / "store", chunks=20, retain_bytes=bound)
    man = store.manifest
    assert man["retired"]["segments"] >= 1, "nothing retired — vacuous"
    closed = sum(s["bytes"] for s in man["segments"])
    assert closed <= bound
    reader = store.reader()
    assert reader.counts() == rec.counts()
    assert _journal_counter(reader) == rec.counts()
    assert _conservation(man) == rec.counts()
    # retired detail is gone from events() but not from the ledger
    assert man["retired"]["counts"].get("step_latency", 0) > 0


def test_missed_ledger_accounts_for_between_drain_eviction(tmp_path):
    """Events the ring evicts BETWEEN drains are unrecoverable; the
    manifest must say so (``missed``) instead of silently shorting the
    conservation sum."""
    rec = StepRecorder(capacity=8, host="h0", pid=1)
    store = JournalStore(str(tmp_path / "s"), segment_events=1000)
    store.drain(rec)
    # 50 events through an 8-slot ring with no drain in between: most
    # are gone before the next drain can see them
    for i in range(50):
        rec.record("step_time", step=i, seconds=0.001)
    store.drain(rec)
    man = store.manifest
    assert man["missed"].get("step_time", 0) > 0
    assert _conservation(man) == rec.counts()
    assert store.reader().counts() == rec.counts()


# ===================================================== durability


def test_rotation_checksums_and_verify_detects_corruption(tmp_path):
    rec, store = _drive(tmp_path / "store", compact_after=10**6)
    man = store.manifest
    raws = [s for s in man["segments"] if s["kind"] == "raw"]
    assert len(raws) >= 2, "rotation never closed a segment — vacuous"
    # staged-rename publish leaves no droppings behind
    assert not [
        n for n in os.listdir(tmp_path / "store") if ".tmp-" in n
    ]
    reader = StoreReader(str(tmp_path / "store"))
    reader.verify()  # every sha256 matches
    # flip one byte of a closed segment: verify must name the member
    victim = os.path.join(str(tmp_path / "store"), raws[0]["name"])
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(victim, "wb") as f:
        f.write(blob)
    with pytest.raises(StoreCorruptError) as ei:
        StoreReader(str(tmp_path / "store")).verify()
    assert raws[0]["name"] in str(ei.value)


def test_restart_resumes_watermark_no_duplicates(tmp_path):
    """A supervisor restart re-opens the same root: the new writer must
    resume from ``drained_seq``, persisting nothing twice and nothing
    already covered — the exactly-once contract."""
    rec = StepRecorder(capacity=256, host="h0", pid=1)
    store = JournalStore(str(tmp_path / "s"), segment_events=10**6)
    record_chunk_steps(rec, 0, 0.001, [0] * 10)
    store.drain(rec)
    before = len(store.reader().events())

    # "restart": a fresh JournalStore over the same root + recorder
    store2 = JournalStore(str(tmp_path / "s"), segment_events=10**6)
    persisted = store2.drain(rec)
    # the drain journals itself, so exactly the one store_drain row is
    # new — none of the 10 steps re-persist
    assert persisted == 1
    record_chunk_steps(rec, 10, 0.001, [0] * 5)
    store2.drain(rec)
    rows = store2.reader().events()
    seqs = [r["seq"] for r in rows]
    assert len(seqs) == len(set(seqs)), "duplicate seq after restart"
    assert len([r for r in rows if r["kind"] == "step_latency"]) == 15
    assert len(rows) > before
    assert store2.reader().counts() == rec.counts()


def test_drain_rejects_new_recorder_incarnation(tmp_path):
    """A FRESH recorder (seq space restarted) draining into an existing
    store would have every event silently skipped by the watermark and
    then booked as missed. All-time counts are monotone for the real
    writer, so the regression is detectable — drain must refuse loudly
    rather than lose data."""
    rec = StepRecorder(capacity=64, host="h0", pid=1)
    store = JournalStore(str(tmp_path / "s"), segment_events=10**6)
    record_chunk_steps(rec, 0, 0.001, [0] * 20)
    store.drain(rec)

    fresh = StepRecorder(capacity=64, host="h0", pid=1)
    record_chunk_steps(fresh, 0, 0.001, [0] * 5)
    store2 = JournalStore(str(tmp_path / "s"), segment_events=10**6)
    with pytest.raises(ValueError, match="regressed|incarnation"):
        store2.drain(fresh)
    # nothing was persisted or mis-booked by the refused drain
    man = store2.reader().manifest
    assert man["missed"] == {}
    assert man["counts"]["step_latency"] == 20
    # a recorder rebuilt from the store resumes cleanly
    rebuilt = store2.reader().to_recorder()
    n = store2.drain(rebuilt)
    assert n == 1  # just its own store_drain row
    assert store2.reader().counts() == rebuilt.counts()


def test_store_drain_journals_itself(tmp_path):
    rec = StepRecorder(capacity=64, host="h0", pid=1)
    store = JournalStore(str(tmp_path / "s"))
    rec.record("step_time", step=0, seconds=0.001)
    store.drain(rec)
    store.drain(rec)
    rows = store.reader().events("store_drain")
    assert len(rows) == 2
    assert rows[0]["after_seq"] == 0
    assert rows[1]["after_seq"] > 0
    for r in rows:
        assert r["segment"].startswith("seg_")
    assert store.reader().counts()["store_drain"] == 2


def test_close_flushes_and_helpers(tmp_path):
    rec = StepRecorder(capacity=64, host="h0", pid=1)
    root = tmp_path / "runs" / "a" / "store"
    store = JournalStore(str(root))
    rec.record("step_time", step=0, seconds=0.001)
    store.close(rec)  # final drain + rotate: nothing left active
    man = StoreReader(str(root)).manifest
    assert man["active"] is None
    assert store_lib.is_store(str(root))
    assert not store_lib.is_store(str(tmp_path))
    assert store_lib.list_stores(str(tmp_path)) == [str(root)]
    store_lib.wipe(str(root))
    assert not os.path.exists(root)


# ===================================================== compaction


def test_compaction_preserves_non_step_and_quantiles(tmp_path):
    """Every non-step event survives compaction verbatim; the per-step
    stream collapses to ``store_window`` sketches whose merged quantile
    equals the live ``Histogram``'s — same edges, same answer."""
    rec, store = _drive(tmp_path / "store")
    reader = store.reader()
    man = store.manifest
    windows = reader.events("store_window")
    assert windows, "no summary rows — vacuous"
    # alerts recorded inside compacted segments are still there, with
    # their payloads intact
    live_alerts = [e.data for e in rec.events("alert")]
    stored_alerts = reader.events("alert")
    assert len(stored_alerts) == rec.counts()["alert"]
    for row in stored_alerts:
        assert row["rule"] == "imbalance_ratio"
        assert row["severity"] == "WARN"
    # the ring only retains the tail; the store has the full history
    assert len(stored_alerts) >= len(live_alerts)

    # quantile exactness: live histogram over every recorded latency
    live = metrics_lib.Histogram((), metrics_lib.STEP_TIME_EDGES)
    for c in range(16):
        for _ in range(40):
            live.observe(0.002 * (1 + (c % 3)))
    merged = reader.latency_histogram()
    assert merged._bucket_counts == live._bucket_counts
    assert merged.count == live.count
    for q in (0.5, 0.9, 0.99):
        assert merged.quantile(q) == live.quantile(q)
    # window rows carry the exact per-kind counts of their span
    total = {}
    for w in windows:
        for k, v in w["counts"].items():
            total[k] = total.get(k, 0) + v
    summary_counts = {}
    for seg in man["segments"]:
        if seg["kind"] == "summary":
            for k, v in seg["counts"].items():
                if k in store_lib.COMPACT_KINDS:
                    summary_counts[k] = summary_counts.get(k, 0) + v
    assert total == summary_counts


def test_to_recorder_pins_alltime_counts(tmp_path):
    rec, store = _drive(tmp_path / "store")
    replay = store.reader().to_recorder()
    assert replay.counts() == rec.counts()
    # the replayed ring serves the retained tail for health rules
    assert replay.events("step_latency")


# ==================================================== query plane


def _rows(spec):
    """Hand-built envelope rows: (kind, host, pid, seq, time, extra)."""
    out = []
    for kind, host, pid, seq, t, extra in spec:
        row = {"kind": kind, "host": host, "pid": pid, "seq": seq,
               "time": t}
        row.update(extra)
        out.append(row)
    return out


def test_query_filters_and_groups():
    rows = _rows([
        ("step_latency", "a", 1, 1, 10.0, {"step": 5, "seconds": 0.1}),
        ("step_latency", "a", 1, 2, 11.0, {"step": 6, "seconds": 0.2}),
        ("alert", "a", 1, 3, 12.0, {"rule": "x", "ctx_trace": "t1",
                                    "ctx_step": 6}),
        ("migrate_step", "b", 2, 1, 13.0,
         {"step": 7, "sent_per_rank": [3, 0], "received_per_rank": [0, 3]}),
    ])
    assert [r["seq"] for r in filter_rows(rows, kind="alert")] == [3]
    assert len(filter_rows(rows, kind="step_latency,alert")) == 3
    # step bounds match payload step AND ctx_step envelopes
    got = filter_rows(rows, step_min=6, step_max=6)
    assert sorted(r["kind"] for r in got) == ["alert", "step_latency"]
    assert [r["host"] for r in filter_rows(rows, host="b")] == ["b"]
    assert filter_rows(rows, trace="t1")[0]["kind"] == "alert"
    assert filter_rows(rows, ctx={"trace": "t1"})[0]["seq"] == 3
    assert filter_rows(rows, since=12.5)[0]["kind"] == "migrate_step"
    assert filter_rows(rows, until=10.0)[0]["seq"] == 1

    groups = group_rows(rows, "kind")
    assert sorted(groups) == ["alert", "migrate_step", "step_latency"]
    # vrank explodes per-rank vectors into scalar slices
    by_rank = group_rows(rows, "vrank")
    assert sorted(by_rank) == ["0", "1"]
    assert by_rank["0"][0]["sent"] == 3
    assert by_rank["1"][0]["received"] == 3
    with pytest.raises(QueryError):
        group_rows(rows, "nope")


def test_query_window_aggregate_ops():
    rows = _rows([
        ("step_latency", "a", 1, i, float(i), {"step": i,
                                               "seconds": 0.001 * (i + 1)})
        for i in range(10)
    ])
    series = window_aggregate(rows, op="count", window_s=5.0)
    assert [w["n"] for w in series] == [5, 5]
    assert [w["value"] for w in series] == [5.0, 5.0]
    rate = window_aggregate(rows, op="rate", window_s=5.0)
    assert rate[0]["value"] == pytest.approx(1.0)
    mean = window_aggregate(rows, op="mean", window_s=5.0)
    assert mean[0]["value"] == pytest.approx(0.003)
    # hand-checkable EMA: window means are 0.003 and 0.008
    ema = window_aggregate(rows, op="ema", window_s=5.0, ema_alpha=0.5)
    assert ema[0]["value"] == pytest.approx(0.003)
    assert ema[1]["value"] == pytest.approx(0.5 * 0.008 + 0.5 * 0.003)
    # quantiles answer with the Histogram's bucketed upper bound
    h = metrics_lib.Histogram((), metrics_lib.STEP_TIME_EDGES)
    for i in range(10):
        h.observe(0.001 * (i + 1))
    p99 = window_aggregate(rows, op="p99", window_s=100.0)
    assert p99[0]["value"] == h.quantile(0.99)
    with pytest.raises(QueryError):
        window_aggregate(rows, op="p12")
    with pytest.raises(QueryError):
        window_aggregate(rows, op="count", window_s=0.0)


def test_query_quantile_merges_store_sketches(tmp_path):
    """A query spanning raw + compacted history answers the same p99 as
    the all-raw run — sketches are the histogram, not an estimate."""
    rec, store = _drive(tmp_path / "store")
    reader = store.reader()
    reply = run_query(reader, {"agg": "p99", "window_s": "1e9",
                               "kind": "step_latency,store_window"})
    (window,) = reply["series"]
    assert window["value"] == reader.latency_histogram().quantile(0.99)
    assert window["n"] == 16 * 40


def test_query_grammar_errors_and_limit():
    rec = StepRecorder(capacity=32, host="h", pid=1)
    for i in range(8):
        rec.record("step_time", step=i, seconds=0.001)
    with pytest.raises(QueryError, match="unknown query parameter"):
        run_query(rec, {"bogus": "1"})
    with pytest.raises(QueryError, match="bad integer"):
        run_query(rec, {"step_min": "abc"})
    with pytest.raises(QueryError, match="bad number"):
        run_query(rec, {"since": "abc"})
    with pytest.raises(QueryError, match="limit"):
        run_query(rec, {"limit": "0"})
    reply = run_query(rec, {"kind": "step_time", "limit": "3"})
    assert reply["matched"] == 8
    # newest kept under the cap
    assert [r["step"] for r in reply["events"]] == [5, 6, 7]
    by = run_query(rec, {"by": "kind"})
    assert by["groups"] == {"step_time": 8}


def test_query_cursor_semantics():
    rows = _rows([
        ("a", "h", 1, i, float(i), {}) for i in range(1, 7)
    ])
    page = events_page(rows, cursor=None, limit=4)
    assert [r["seq"] for r in page["events"]] == [1, 2, 3, 4]
    assert page["cursor"] == "h:1:4"
    assert page["remaining"] == 2
    page2 = events_page(rows, cursor=page["cursor"], limit=4)
    assert [r["seq"] for r in page2["events"]] == [5, 6]
    assert page2["remaining"] == 0
    # exhausted: the reply echoes the input cursor, never regresses
    page3 = events_page(rows, cursor=page2["cursor"], limit=4)
    assert page3["events"] == [] and page3["cursor"] == page2["cursor"]
    # evicted cursor: rows 1-3 compacted away, resume at seq 4 (no
    # duplicates, no skips of retained rows)
    page4 = events_page(rows[3:], cursor="h:1:2", limit=10)
    assert [r["seq"] for r in page4["events"]] == [4, 5, 6]
    # unknown shard replays everything
    page5 = events_page(rows, cursor="other:9:3", limit=10)
    assert len(page5["events"]) == 6
    with pytest.raises(QueryError, match="bad cursor"):
        events_page(rows, cursor="nocolons")
    with pytest.raises(QueryError, match="limit"):
        events_page(rows, cursor=None, limit=0)


def test_rows_of_sources_agree(tmp_path):
    """One query plane, every source: live recorder, JSONL shard file
    and store reader rows agree on the shared span."""
    rec = StepRecorder(capacity=256, host="h0", pid=1)
    for i in range(6):
        rec.record("step_time", step=i, seconds=0.001)
    store = JournalStore(str(tmp_path / "s"))
    store.drain(rec)
    # shard written after the drain: all three sources cover the same
    # span, store_drain event included
    shard = tmp_path / "shard.jsonl"
    rec.to_jsonl(str(shard))

    live = query_lib.rows_of(rec)
    file_rows = query_lib.rows_of(str(shard))
    stored = query_lib.rows_of(store.reader())
    key = lambda r: (r["seq"], r["kind"])  # noqa: E731
    live_keys = [key(r) for r in live]
    assert "store_drain" in {k[1] for k in live_keys}
    assert [key(r) for r in file_rows] == live_keys
    assert [key(r) for r in stored] == live_keys


# ======================================================== service


def _spawn_serve(args):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, SERVE] + args + ["--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        cwd=REPO_ROOT, env=env,
    )
    watchdog = threading.Timer(120, proc.kill)
    watchdog.start()
    line = proc.stdout.readline()
    m = re.search(r"http://([\d.]+):(\d+)/metrics", line)
    assert m, (line, proc.poll(),
               proc.stderr.read() if proc.poll() is not None else "")
    return proc, watchdog, f"http://{m.group(1)}:{m.group(2)}"


def _get_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        assert r.status == 200
        return json.loads(r.read().decode("utf-8"))


def test_http_query_and_events_over_store(tmp_path):
    """The served history plane: a compacted store behind
    ``metrics_serve --store`` answers /query aggregations and a full
    /events cursor walk; the grammar's 400 surface round-trips."""
    rec, store = _drive(tmp_path / "store")
    store.close(rec)
    proc, watchdog, base = _spawn_serve(["--store", str(tmp_path / "store")])
    try:
        by = _get_json(base + "/query?by=kind")
        assert by["groups"]["alert"] == rec.counts()["alert"]
        assert "store_window" in by["groups"]
        p99 = _get_json(
            base + "/query?agg=p99&window_s=1e9"
            "&kind=step_latency,store_window"
        )
        (window,) = p99["series"]
        assert window["value"] == store.reader().latency_histogram(
        ).quantile(0.99)
        # /metrics over the same store scrapes the exact all-time counts
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            text = r.read().decode("utf-8")
        line = [
            ln for ln in text.splitlines()
            if ln.startswith("grid_journal_events_total")
            and 'kind="step_latency"' in ln
        ]
        assert line and float(line[0].rsplit(" ", 1)[1]) == float(
            rec.counts()["step_latency"]
        )
        # cursor walk to exhaustion: every retained row exactly once
        seen, cursor = [], ""
        while True:
            page = _get_json(
                base + f"/events?limit=100&cursor={cursor}"
            )
            seen.extend(page["events"])
            cursor = page["cursor"]
            if page["remaining"] == 0 and not page["events"]:
                break
        keys = [(r["host"], r["pid"], r["seq"]) for r in seen]
        assert len(keys) == len(set(keys)), "cursor walk duplicated rows"
        assert len(seen) == len(store.reader().events())
        # a bad parameter is a 400 with the offending name, not a 500
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/query?bogus=1", timeout=30)
        assert ei.value.code == 400
        assert b"bogus" in ei.value.read()
    finally:
        watchdog.cancel()
        proc.terminate()
        proc.wait(timeout=10)


def test_metrics_serve_concurrency_tracer_clean():
    """The ISSUE 18 concurrency satellite: parallel /metrics + /query +
    /events (cursor-resumed) against a LIVE recorder being written by a
    step thread, with the runtime thread sanitizer armed — every ring
    access must go through the lock (zero violations)."""
    spec = importlib.util.spec_from_file_location("_serve_mod", SERVE)
    serve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(serve)

    rec = StepRecorder(capacity=512, host="h0", pid=1)
    handler = serve.make_handler(lambda: rec)
    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    server.daemon_threads = True
    srv_thread = threading.Thread(target=server.serve_forever, daemon=True)
    srv_thread.start()
    base = f"http://127.0.0.1:{server.server_address[1]}"
    errors = []

    def writer():
        for i in range(300):
            rec.record("step_time", step=i, seconds=0.001)

    def scraper():
        try:
            for _ in range(10):
                with urllib.request.urlopen(
                    base + "/metrics", timeout=30
                ) as r:
                    assert r.read().decode().rstrip().endswith("# EOF")
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append(e)

    def querier():
        try:
            for _ in range(10):
                doc = _get_json(base + "/query?agg=count&window_s=60")
                assert "series" in doc
        except Exception as e:  # pragma: no cover
            errors.append(e)

    def streamer():
        try:
            cursor, got = "", 0
            for _ in range(10):
                page = _get_json(
                    base + f"/events?limit=64&cursor={cursor}"
                )
                got += len(page["events"])
                cursor = page["cursor"]
            assert got > 0
        except Exception as e:  # pragma: no cover
            errors.append(e)

    try:
        with ThreadAccessTracer(rec) as tracer:
            threads = [threading.Thread(target=writer, daemon=True)]
            threads += [
                threading.Thread(target=fn, daemon=True)
                for fn in (scraper, scraper, querier, streamer)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60)
            assert not errors, errors
            tracer.assert_clean()
            assert tracer.violations() == []
            assert len(tracer.by_thread()) >= 3, (
                "concurrency never happened — test is vacuous"
            )
    finally:
        server.shutdown()
        server.server_close()
    assert rec.counts()["step_time"] == 300


def test_driver_drains_store_at_boundaries(tmp_path):
    """Service integration: a driver with ``store_dir`` set leaves a
    complete, verified store behind — every step's latency row
    persisted despite the ring, counts byte-equal the live journal."""
    from mpi_grid_redistribute_tpu.service import DriverConfig, ServiceDriver

    cfg = DriverConfig(
        grid_shape=(2, 2, 2),
        n_local=128,
        steps=24,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
        store_dir=str(tmp_path / "store"),
        store_segment_events=64,
    )
    rec = StepRecorder(capacity=64, host="h0", pid=1)
    driver = ServiceDriver(cfg, recorder=rec)
    driver.run()
    driver.close()
    reader = StoreReader(str(tmp_path / "store"), verify=True)
    assert reader.counts() == rec.counts()
    latencies = reader.events("step_latency")
    assert len(latencies) == 24, "boundary drains missed steps"
    # driver steps are 1-based (step is incremented before the boundary)
    assert sorted(r["step"] for r in latencies) == list(range(1, 25))
    assert reader.counts()["store_drain"] >= 24 // 4


def test_supervised_restart_store_no_duplicates(tmp_path):
    """The watermark across real restarts: a crash-injected supervised
    run re-opens the same store root; no (host, pid, seq) persists
    twice and the final counts still match the shared journal."""
    from mpi_grid_redistribute_tpu.service import (
        CrashFault,
        DriverConfig,
        FaultPlan,
        RestartPolicy,
        ServiceDriver,
        Supervisor,
    )

    cfg = DriverConfig(
        grid_shape=(2, 2, 2),
        n_local=128,
        steps=24,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
        store_dir=str(tmp_path / "store"),
    )
    rec = StepRecorder(capacity=4096, host="h0", pid=1)
    faults = FaultPlan([CrashFault(10)])

    def factory(grid_shape=None):
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return ServiceDriver(c, recorder=rec, faults=faults)

    sup = Supervisor(
        factory,
        policy=RestartPolicy(
            max_restarts=3, backoff_base_s=0.01, backoff_cap_s=0.02,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    verdict = sup.run()
    assert verdict.ok is True, verdict
    assert rec.counts().get("restart", 0) >= 1, "no restart?"
    reader = StoreReader(str(tmp_path / "store"), verify=True)
    rows = reader.events()
    keys = [(r["host"], r["pid"], r["seq"]) for r in rows]
    assert len(keys) == len(set(keys)), "restart duplicated rows"
    assert reader.counts() == rec.counts()


# ======================================================= overhead


def test_drain_overhead_under_2pct(rng, _devices, tmp_path):
    """Acceptance: boundary drains (journal -> fsync'd segment +
    manifest publish) add <= 2% to the config1-style steady state —
    the same paired-delta median protocol as the recorder+metrics gate
    (test_metrics.py), with the drain as the only difference between
    the legs."""
    import gc
    import time

    import jax

    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
    from mpi_grid_redistribute_tpu.telemetry import record_migrate_steps

    grid = ProcessGrid((2, 2, 2))
    n_local = 2048
    n = grid.nranks * n_local
    mesh = mesh_lib.make_mesh(grid)
    cfg = nbody.DriftConfig(
        domain=Domain(0.0, 1.0, periodic=True), grid=grid, dt=0.02,
        capacity=n_local // 4, n_local=n_local,
    )
    # 128 steps per sample for the same reason as the metrics gate: the
    # drain path scales with the journal window, so the ratio is
    # steps-invariant, but the host's scheduler wobble needs the longer
    # loop to stay under a 2% signal
    steps = 128
    loop = nbody.make_migrate_loop(cfg, mesh, steps)
    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.2 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    alive = np.ones((n,), bool)
    jax.block_until_ready(loop(pos, vel, alive))  # compile

    store = JournalStore(
        str(tmp_path / "store"), segment_events=4096,
        retain_bytes=8 << 20, compact_after=2,
    )
    base_rec = StepRecorder()
    obs_rec = StepRecorder()

    def sample(observe):
        rec = obs_rec if observe else base_rec
        t0 = time.perf_counter()
        out = loop(pos, vel, alive)
        jax.block_until_ready(out)
        stats_host = jax.tree.map(np.asarray, out[3])
        # both legs journal (that cost is the metrics gate's budget);
        # only the observed leg drains to disk
        record_migrate_steps(rec, stats_host, rank_totals=True)
        if observe:
            store.drain(rec)
        return time.perf_counter() - t0

    def batch_median():
        deltas = []
        gc.collect()
        gc.disable()
        try:
            for k in range(9):
                if k % 2:
                    o = sample(True)
                    b = sample(False)
                else:
                    b = sample(False)
                    o = sample(True)
                deltas.append((o - b) / b)
        finally:
            gc.enable()
        return float(np.median(deltas)), deltas

    overhead, deltas = batch_median()
    if overhead > 0.02:
        # confirm before failing, exactly like the metrics gate: a real
        # regression reproduces, a scheduler excursion does not
        overhead2, deltas2 = batch_median()
        if overhead2 < overhead:
            overhead, deltas = overhead2, deltas2
    assert overhead <= 0.02, (
        f"store drain overhead {overhead:.1%} > 2% (median of "
        f"{len(deltas)} paired samples, {steps}-step loop, best of two "
        f"batches; deltas {[f'{d:.1%}' for d in deltas]})"
    )
    # the drained store is real, not a no-op: every sample persisted
    assert store.reader().counts().get("migrate_step", 0) > 0


# ===================================================== CLI smokes


def _run_cli(args, **kw):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable] + args, capture_output=True, text=True,
        cwd=REPO_ROOT, env=env, timeout=300, **kw,
    )


def test_storecheck_cli_clean_and_real_store(tmp_path):
    out = _run_cli([os.path.join("scripts", "storecheck.py"), "--check"])
    assert out.returncode == 0, out.stdout + out.stderr
    # point it at a real store root built here
    rec, store = _drive(tmp_path / "store")
    store.close(rec)
    out = _run_cli(
        [os.path.join("scripts", "storecheck.py"), str(tmp_path / "store")]
    )
    assert out.returncode == 0, out.stdout + out.stderr


def test_grid_top_once_renders_store(tmp_path):
    rec, store = _drive(tmp_path / "store")
    store.close(rec)
    out = _run_cli([
        os.path.join("scripts", "grid_top.py"),
        "--store", str(tmp_path / "store"), "--once",
    ])
    assert out.returncode == 0, out.stdout + out.stderr
    assert "steps" in out.stdout
    assert "p99" in out.stdout
    # an unreadable store is exit 1, not a stack trace
    bad = _run_cli([
        os.path.join("scripts", "grid_top.py"),
        "--store", str(tmp_path / "nope"), "--once",
    ])
    assert bad.returncode == 1
    assert "Traceback" not in bad.stderr


def test_history_cli_indexes_runs(tmp_path):
    rec, store = _drive(tmp_path / "runs" / "r1" / "store")
    store.close(rec)
    out = _run_cli([
        os.path.join("scripts", "history.py"), "--json",
        "--stores", str(tmp_path / "runs"),
    ])
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    # the committed BENCH_r*.json history indexes alongside the store
    assert len(doc["benches"]) >= 5
    (entry,) = doc["stores"]
    assert entry["events_total"] == sum(rec.counts().values())
    assert entry["steps"] == rec.counts()["step_latency"]
