"""gridlint (mpi_grid_redistribute_tpu.analysis) — rule fixtures + repo gate.

Each rule gets at least one fixture that must FIRE and one that must
stay QUIET; the final test runs the real package through the linter
against the committed baseline and requires zero non-baselined
findings — the tier-1 gate the CLI (`make lint`) also enforces.

Pure AST work: nothing here imports jax or executes fixture code.
"""

import json
import os
import subprocess
import sys
import textwrap

from mpi_grid_redistribute_tpu.analysis.baseline import (
    default_baseline_path,
    load_baseline,
    split_baselined,
    write_baseline,
)
from mpi_grid_redistribute_tpu.analysis.cli import main as cli_main
from mpi_grid_redistribute_tpu.analysis.core import RULE_IDS, run_gridlint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO_ROOT, "mpi_grid_redistribute_tpu")


def lint(tmp_path, files, rules=None):
    """Write ``files`` (name -> source) under tmp_path and lint them."""
    for name, src in files.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return run_gridlint([str(tmp_path)], root=str(tmp_path), rules=rules)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- G001


_G001_PREAMBLE = """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import Mesh
    from mpi_grid_redistribute_tpu.compat import shard_map

    mesh = Mesh(jax.devices(), axis_names=("shards",))
"""


def test_g001_fires_on_data_dependent_collective(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": _G001_PREAMBLE
            + """
    def body(x, count):
        if count > 0:
            x = lax.psum(x, axis_name="shards")
        return x

    fn = shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
    """,
        },
    )
    assert rules_of(findings) == ["G001"], findings
    assert "data-dependent" in findings[0].message


def test_g001_quiet_on_unconditional_collective(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": _G001_PREAMBLE
            + """
    def body(x, count):
        # trace-time host branch on config is fine
        if x.ndim == 2:
            x = x + 1
        return lax.psum(x, axis_name="shards")

    fn = shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
    """,
        },
    )
    assert findings == [], findings


def test_g001_fires_inside_cond_branch_and_try(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": _G001_PREAMBLE
            + """
    def body(x, flag):
        def hot(y):
            return lax.psum(y, axis_name="shards")

        def cold(y):
            return y

        try:
            z = lax.ppermute(x, "shards", [(0, 1)])
        except ValueError:
            z = x
        return lax.cond(flag, hot, cold, z)

    fn = shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
    """,
        },
    )
    msgs = "\n".join(f.message for f in findings)
    assert "branch function" in msgs
    assert "try block" in msgs


def test_g001_fires_on_undeclared_axis_name(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": _G001_PREAMBLE
            + """
    def body(x):
        return lax.psum(x, axis_name="shrads")  # typo'd axis

    fn = shard_map(body, mesh=mesh, in_specs=None, out_specs=None)
    """,
        },
    )
    assert rules_of(findings) == ["G001"], findings
    assert "shrads" in findings[0].message


# ---------------------------------------------------------------- G002


def test_g002_fires_on_host_syncs_in_jitted_code(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax
    import numpy as np

    @jax.jit
    def step(x):
        n = int(x)            # host sync
        y = np.asarray(x)     # device->host copy
        return x.item() + n + y.sum()
    """,
        },
    )
    assert rules_of(findings) == ["G002"]
    assert len(findings) == 3, findings


def test_g002_quiet_on_static_annotated_params_and_host_fns(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax
    import numpy as np

    @jax.jit
    def step(x, n_steps: int, scale: float):
        # int()/float() on annotated config params is trace-time math
        return x * float(scale) * int(n_steps)

    def host_only(x):
        # not jit-reachable: host syncs are fine here
        return float(np.asarray(x).sum())
    """,
        },
    )
    assert findings == [], findings


def test_g002_reaches_through_builders_and_helpers(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax

    def helper(x):
        return x.item()  # reached transitively from the jit root

    def build():
        def call(x):
            return helper(x)

        return jax.jit(call)
    """,
        },
    )
    assert rules_of(findings) == ["G002"]
    assert findings[0].symbol == "helper"


# ---------------------------------------------------------------- G003


def test_g003_fires_on_dynamic_shapes(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def pick(x):
        idx = jnp.nonzero(x > 0)          # unsized
        hits = jnp.where(x > 1)           # 1-arg nonzero form
        return x[x > 0], idx, hits        # boolean-mask indexing
    """,
        },
    )
    assert rules_of(findings) == ["G003"]
    assert len(findings) == 3, findings


def test_g003_quiet_on_sized_and_select_forms(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def pick(x, cap: int):
        idx = jnp.nonzero(x > 0, size=cap, fill_value=0)
        sel = jnp.where(x > 1, x, 0)
        return idx, sel
    """,
        },
    )
    assert findings == [], findings


# ---------------------------------------------------------------- G004


def test_g004_fires_on_unguarded_fuse(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    from pack import fuse_fields

    def ship(positions, fields):
        return fuse_fields(positions, fields)
    """,
            "pack.py": """
    def fuse_fields(positions, fields):
        return positions
    """,
        },
    )
    assert rules_of(findings) == ["G004"], findings


def test_g004_quiet_when_guard_in_callee_or_caller(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    def fuse_fields(positions, fields):
        # self-guarding fuse (migrate.fuse_fields shape)
        if positions.dtype.itemsize != 4:
            raise TypeError("planar path needs 32-bit rows")
        return positions

    def specs_of(a):
        if a.dtype.itemsize != 4:
            return None
        return a.shape

    def build(specs):
        def call(positions, fields):
            return fuse_fields(positions, fields)

        return call

    def entry(positions, fields):
        # one-frame-up guard: entry consults the itemsize helper
        specs = specs_of(positions)
        if specs is None:
            return positions
        return build(specs)(positions, fields)
    """,
        },
    )
    assert findings == [], findings


# ---------------------------------------------------------------- G005


def test_g005_fires_on_defaulted_pallas_call(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    from jax.experimental import pallas as pl

    def launch(kernel, x):
        return pl.pallas_call(kernel, out_shape=x)(x)
    """,
        },
    )
    msgs = "\n".join(f.message for f in findings)
    assert rules_of(findings) == ["G005"]
    assert "grid" in msgs and "in_specs" in msgs


def test_g005_fires_on_unbounded_program_id_kernel(tmp_path):
    findings = lint(
        tmp_path,
        {
            "pallas_fix.py": """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def _kernel(in_ref, out_ref):
        b = pl.program_id(0)
        out_ref[b] = in_ref[b] + 1  # no bound: last padded block escapes

    def launch(x, grid, in_specs, out_specs):
        return pl.pallas_call(
            _kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=x,
        )(x)
    """,
        },
    )
    assert rules_of(findings) == ["G005"], findings
    assert "program_id" in findings[0].message


def test_g005_quiet_on_bounded_partial_wrapped_kernel(tmp_path):
    findings = lint(
        tmp_path,
        {
            "pallas_fix.py": """
    import functools
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def _kernel(in_ref, out_ref, *, n):
        b = pl.program_id(0)
        i = jnp.minimum(b, n - 1)
        out_ref[i] = in_ref[i] + 1

    def launch(x, n, grid, in_specs, out_specs):
        kernel = functools.partial(_kernel, n=n)
        return pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=x,
        )(x)
    """,
        },
    )
    assert findings == [], findings


def test_g005_quiet_on_scratch_shapes_kernel(tmp_path):
    """scratch_shapes (VMEM accumulators + DMA semaphores) are extra
    positional refs AFTER the in/out refs — the declared-specs and
    bounded-program_id checks must not trip over them."""
    findings = lint(
        tmp_path,
        {
            "pallas_fix.py": """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _kernel(in_ref, out_ref, acc_ref, sem):
        b = pl.program_id(0)
        nb = pl.num_programs(0)
        i = jnp.minimum(b, nb - 1)
        acc_ref[:] = in_ref[:] * 2.0
        out_ref[:] = acc_ref[:] + i

    def launch(x, grid, in_specs, out_specs):
        return pl.pallas_call(
            _kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=x,
            scratch_shapes=[
                pltpu.VMEM((8, 128), jnp.float32),
                pltpu.SemaphoreType.DMA((2,)),
            ],
        )(x)
    """,
        },
    )
    assert findings == [], findings


def test_g005_quiet_on_grid_dim_zero_literal(tmp_path):
    """A zero-extent grid dim is lexically a fully-declared launch —
    G005 has nothing to say. Whether running ZERO instances leaves the
    output uncovered is a semantic question: kernelcheck's K002
    coverage rule owns it (see test_kernelcheck.py's twin)."""
    findings = lint(
        tmp_path,
        {
            "pallas_fix.py": """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _kernel(in_ref, out_ref):
        out_ref[:] = in_ref[:]

    def launch(x, nblk):
        return pl.pallas_call(
            _kernel,
            grid=(nblk, 0),
            in_specs=[pl.BlockSpec((8, 128), lambda i, j: (i, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((8, 128), lambda i, j: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=x,
        )(x)
    """,
        },
    )
    assert findings == [], findings


def test_g005_quiet_on_semantically_out_of_bounds_index_map(tmp_path):
    """The AST/semantic split, spiked from the gridlint side: this
    launch is lexically impeccable (grid, specs, no raw program_id in
    the kernel body) yet its index map addresses one block PAST the
    end. G005 must stay quiet — kernelcheck K001 proves the bounds
    violation on the captured site (the disjoint twin lives in
    test_kernelcheck.py::test_k001_and_g005_are_disjoint)."""
    findings = lint(
        tmp_path,
        {
            "pallas_fix.py": """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    def _kernel(in_ref, out_ref):
        out_ref[:] = in_ref[:] + 1.0

    def launch(x):
        return pl.pallas_call(
            _kernel,
            grid=(4,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i + 1, 0),
                                   memory_space=pltpu.VMEM)],
            out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=x,
        )(x)
    """,
        },
    )
    assert findings == [], findings


# ---------------------------------------------------------------- G006


def test_g006_fires_on_sort_and_arange_take_in_marked_fn(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax.numpy as jnp
    from jax import lax

    # gridlint: fastpath-engine
    def fast_branch(flat, block, n):
        order = lax.sort(block, dimension=-1)
        cols = jnp.take(flat, jnp.arange(n), axis=1)
        return order, cols
    """,
        },
        rules=["G006"],
    )
    assert rules_of(findings) == ["G006"], findings
    assert len(findings) == 2
    assert any("sort" in f.message for f in findings)
    assert any("arange/iota" in f.message for f in findings)


def test_g006_quiet_on_plan_indexed_gather_and_unmarked_fn(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax.numpy as jnp
    from jax import lax

    # gridlint: fastpath-engine
    def fast_branch(flat, plan, window):
        # plan-shaped gather: indices come in as a value, no iota
        cols = jnp.take(flat, plan.reshape(-1), axis=1)
        win = lax.dynamic_slice(window, (0,), (8,))
        return cols, win

    def dense_engine(dest, n):
        # unmarked: the dense engine may sort residents freely
        order = jnp.argsort(dest)
        return jnp.take(dest, jnp.arange(n))
    """,
        },
        rules=["G006"],
    )
    assert findings == [], findings


def test_g006_sees_nested_defs_in_marked_fn(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax.numpy as jnp

    # gridlint: fastpath-engine
    def fast_branch(block):
        def inner(row):
            return jnp.sort(row)
        return inner(block)
    """,
        },
        rules=["G006"],
    )
    assert rules_of(findings) == ["G006"], findings


def test_g006_fires_on_subscript_iota_in_marked_fn(tmp_path):
    # the exchange wire builders' idiom (ISSUE 7): a dense permutation
    # spelled as advanced indexing — x[:, arange(n)] — must fire; the
    # plan-shaped subscript and the unmarked dense engine stay quiet
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax.numpy as jnp
    from jax import lax

    # gridlint: fastpath-engine
    def wire(pool, plan, n):
        dense = pool[:, jnp.arange(n)]
        narrow = pool[:, plan]
        return dense, narrow

    def dense_wire(pool, n):
        return pool[:, jnp.arange(n)]
    """,
        },
        rules=["G006"],
    )
    assert rules_of(findings) == ["G006"], findings
    assert len(findings) == 1
    assert "subscript" in findings[0].message
    assert findings[0].symbol == "wire"


def test_g006_exchange_wire_builders_are_marked_and_clean():
    # the real count-driven wire builders carry the marker (the contract
    # is opted into, not implied) and lint clean — the static half of
    # the wire-cost contract; the jaxpr walks in
    # tests/test_exchange_sparse.py hold the dynamic half
    from mpi_grid_redistribute_tpu.analysis.rules_fastpath import (
        _MARKER_RE,
    )

    path = os.path.join(PACKAGE, "parallel", "exchange.py")
    with open(path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    marked = {
        lines[i + 1].split("(")[0].replace("def ", "").strip()
        for i, ln in enumerate(lines)
        if _MARKER_RE.search(ln) and i + 1 < len(lines)
    }
    assert {"_sparse_wire", "_neighbor_wire"} <= marked, marked
    findings = run_gridlint([path], root=REPO_ROOT, rules=["G006"])
    assert findings == [], findings


# ---------------------------------------------------------------- G007


def test_g007_fires_on_jax_import_and_sync_in_marked_module(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    # gridlint: scrape-path
    import jax
    from jax import numpy as jnp

    def scrape(x):
        return x.block_until_ready()
    """,
        },
        rules=["G007"],
    )
    assert rules_of(findings) == ["G007"], findings
    assert len(findings) == 3, findings  # two imports + one sync


def test_g007_quiet_without_marker_and_on_clean_marked_module(tmp_path):
    findings = lint(
        tmp_path,
        {
            # jax everywhere, but no scrape-path marker: out of scope
            "unmarked.py": """
    import jax

    def f(x):
        return jax.device_get(x)
    """,
            # marked, but host-only: json/math folds are the contract
            "marked.py": """
    # gridlint: scrape-path
    import json
    import math

    def fold(rows):
        return {"n": len(rows), "log": math.log2(max(1, len(rows)))}
    """,
        },
        rules=["G007"],
    )
    assert findings == [], findings


def test_g007_metrics_plane_is_marked_and_clean():
    # the real modules carry the marker (the contract is opted into, not
    # implied) and lint clean — the static half of the scrape-path
    # purity gate (tests/test_metrics.py holds the source-scan half)
    from mpi_grid_redistribute_tpu.analysis.rules_scrape import _MARKER_RE

    tel = os.path.join(PACKAGE, "telemetry")
    # the ISSUE 18 history plane (store.py, query.py) joins the original
    # metrics plane under the same opt-in purity contract
    for name in ("metrics.py", "aggregate.py", "store.py", "query.py"):
        with open(os.path.join(tel, name), encoding="utf-8") as fh:
            src = fh.read()
        assert _MARKER_RE.search(src), f"{name} lost its scrape-path marker"
    findings = run_gridlint([tel], root=REPO_ROOT, rules=["G007"])
    assert findings == [], findings


# ---------------------------------------------------------------- G008


def test_g008_fires_on_bare_except_and_swallowed_handler(tmp_path):
    findings = lint(
        tmp_path,
        {
            "svc.py": """
    # gridlint: service-path

    def step(run):
        try:
            run()
        except:
            pass

    def probe(run):
        try:
            run()
        except ValueError:
            ...
    """,
        },
        rules=["G008"],
    )
    assert rules_of(findings) == ["G008"], findings
    assert len(findings) == 2, findings  # one bare except + one swallow
    msgs = sorted(f.message for f in findings)
    assert "bare `except:`" in msgs[0], msgs
    assert "swallowed exception" in msgs[1], msgs


def test_g008_quiet_without_marker_and_on_real_handling(tmp_path):
    findings = lint(
        tmp_path,
        {
            # swallows everywhere, but unmarked: out of scope
            "unmarked.py": """
    def best_effort(run):
        try:
            run()
        except Exception:
            pass
    """,
            # marked, but every handler does real work: journals the
            # failure, converts it to a verdict, or narrows + re-raises
            "svc.py": """
    # gridlint: service-path

    def supervised(run, recorder):
        try:
            run()
        except Exception as e:
            recorder.record("restart", reason=str(e))

    def teardown(close):
        try:
            close()
        except OSError as e:
            return f"teardown failed: {e}"
        return None

    def narrow(run):
        try:
            run()
        except RuntimeError:
            if not harmless():
                raise

    def harmless():
        return True
    """,
        },
        rules=["G008"],
    )
    assert findings == [], findings


def test_g008_service_subsystem_is_marked_and_clean():
    # the real service modules carry the marker (the supervisor must see
    # every fault) and lint clean — the static half of the never-mask-a-
    # fault gate (tests/test_service.py's fault matrix is the dynamic
    # half)
    from mpi_grid_redistribute_tpu.analysis.rules_service import _MARKER_RE

    svc = os.path.join(PACKAGE, "service")
    marked = [
        os.path.join(svc, name)
        for name in ("driver.py", "supervisor.py", "faults.py", "elastic.py")
    ]
    # the rebalance actuation runs inside the driver's health boundary —
    # a swallowed fault there silently turns the closed loop off
    marked.append(os.path.join(PACKAGE, "telemetry", "rebalance.py"))
    for path in marked:
        with open(path, encoding="utf-8") as fh:
            src = fh.read()
        assert _MARKER_RE.search(src), (
            f"{os.path.basename(path)} lost its service-path marker"
        )
    findings = run_gridlint(
        [svc, os.path.join(PACKAGE, "telemetry", "rebalance.py")],
        root=REPO_ROOT, rules=["G008"],
    )
    assert findings == [], findings


# ---------------------------------------------------------------- G009


def test_g009_fires_on_host_syncs_in_marked_fn(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import numpy as np

    # gridlint: resident-path
    def macro(pos, vel, count):
        host = np.asarray(count)
        pos.block_until_ready()
        total = float(count.sum())
        return host, total
    """,
        },
        rules=["G009"],
    )
    assert rules_of(findings) == ["G009"], findings
    assert len(findings) == 3
    assert any("np.asarray" in f.message for f in findings)
    assert any("block_until_ready" in f.message for f in findings)
    assert any("float()" in f.message for f in findings)


def test_g009_scans_nested_scan_body_and_spares_device_ops(tmp_path):
    # the scan body is a nested def — lexically inside the marked
    # function, so it IS scanned; jnp.asarray and float literals are
    # device-safe and must not fire
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import numpy as np
    import jax.numpy as jnp
    from jax import lax

    # gridlint: resident-path
    def macro(pos, count):
        def body(carry, _):
            p, c = carry
            p = p + jnp.asarray(1.0, p.dtype) * float(0.5)
            c = int(3) + np.asarray(c)
            return (p, c), c
        return lax.scan(body, (pos, count), None, length=4)
    """,
        },
        rules=["G009"],
    )
    assert rules_of(findings) == ["G009"], findings
    assert len(findings) == 1
    assert "np.asarray" in findings[0].message


def test_g009_unmarked_fn_and_boundary_code_are_free(tmp_path):
    # host syncs OUTSIDE marked functions are the chunk-boundary
    # contract working as designed — no findings
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import numpy as np

    def retire_chunk(ys):
        dropped = np.asarray(ys["dropped"])
        return float(dropped.sum())

    # gridlint: resident-path
    def macro(pos, count):
        return pos, count
    """,
        },
        rules=["G009"],
    )
    assert findings == [], findings


def test_g009_repo_gate_resident_engine_is_marked_and_clean():
    # the chunk engine must carry the resident-path marker (the static
    # half of the no-per-step-host-sync gate; tests/test_resident.py's
    # jaxpr walk is the dynamic half) and lint clean
    from mpi_grid_redistribute_tpu.analysis.rules_resident import (
        _MARKER_RE,
    )

    path = os.path.join(PACKAGE, "service", "resident.py")
    with open(path, encoding="utf-8") as fh:
        lines = fh.read().splitlines()
    marked = {
        lines[i + 1].split("(")[0].replace("def ", "").strip()
        for i, ln in enumerate(lines)
        if _MARKER_RE.search(ln) and i + 1 < len(lines)
    }
    assert "macro" in marked, marked
    findings = run_gridlint([path], root=REPO_ROOT, rules=["G009"])
    assert findings == [], findings


# ------------------------------------------------------------------ G010


def test_g010_fires_on_marked_fn_without_span(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    # gridlint: fastpath-engine
    def hot_no_span(x):
        return x + 1

    # gridlint: resident-path
    def macro_no_span(pos, count):
        return pos, count
    """,
        },
        rules=["G010"],
    )
    assert rules_of(findings) == ["G010"], findings
    assert len(findings) == 2
    assert {f.symbol for f in findings} == {"hot_no_span", "macro_no_span"}
    assert all("named_scope" in f.message for f in findings)


def test_g010_quiet_with_span_even_in_nested_body(tmp_path):
    # a span anywhere lexically inside the marked function counts —
    # including inside a scan-body nested def; unmarked functions are
    # never G010's business, and host-side span() does NOT satisfy it
    # (it times host code, the profiler never sees it)
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax
    from jax import lax
    from mpi_grid_redistribute_tpu.telemetry.phases import (
        span, traced_span,
    )

    # gridlint: fastpath-engine
    def hot_direct(x):
        with jax.named_scope("hot"):
            return x + 1

    # gridlint: resident-path
    def macro_nested(pos, count):
        def body(carry, _):
            with traced_span("svc:drift"):
                return carry, None
        return lax.scan(body, (pos, count), None, length=4)

    def unmarked_cold(x):
        return x - 1

    # gridlint: resident-path
    def macro_host_span_only(pos):
        with span("host-timer"):
            return pos
    """,
        },
        rules=["G010"],
    )
    assert rules_of(findings) == ["G010"], findings
    assert findings[0].symbol == "macro_host_span_only"


def test_g010_repo_gate_marked_hot_paths_all_carry_spans():
    # every fastpath-engine/resident-path-marked function in the
    # package names at least one profiler scope — the knockout and
    # ProfilerSession attribution surface has no blind spots
    findings = run_gridlint([PACKAGE], root=REPO_ROOT, rules=["G010"])
    assert findings == [], findings


# ------------------------------------------------- suppressions, baseline


def test_inline_and_file_suppressions(tmp_path):
    files = {
        "mod.py": """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def pick(x):
        return jnp.nonzero(x > 0)  # gridlint: disable=G003
    """,
        "legacy.py": """
    # gridlint: disable-file=G003
    import jax
    import jax.numpy as jnp

    @jax.jit
    def old(x):
        return jnp.nonzero(x < 0)
    """,
    }
    assert lint(tmp_path, files) == []
    # same fixtures without the pragmas do fire
    stripped = {
        k: v.replace("# gridlint: disable=G003", "").replace(
            "# gridlint: disable-file=G003", ""
        )
        for k, v in files.items()
    }
    assert rules_of(lint(tmp_path, stripped)) == ["G003"]


def test_baseline_roundtrip_and_staleness(tmp_path):
    findings = lint(
        tmp_path,
        {
            "mod.py": """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def pick(x):
        return jnp.nonzero(x > 0)
    """,
        },
    )
    assert len(findings) == 1
    bl_path = str(tmp_path / "baseline.json")
    write_baseline(bl_path, findings, justification="fixture")
    baseline = load_baseline(bl_path)
    new, old = split_baselined(findings, baseline)
    assert new == [] and len(old) == 1
    # entries carry the justification
    payload = json.loads(open(bl_path).read())
    assert payload["findings"][0]["justification"] == "fixture"
    # a key nothing matches is stale
    stale_keys = baseline - {f.baseline_key() for f in old}
    assert stale_keys == set()


def test_cli_exit_codes_and_json(tmp_path, capsys):
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def pick(x):
                return jnp.nonzero(x > 0)
            """
        )
    )
    rc = cli_main(
        [
            str(tmp_path / "mod.py"),
            "--root",
            str(tmp_path),
            "--no-baseline",
            "--format",
            "json",
        ]
    )
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"] for f in out["findings"]] == ["G003"]
    # --write-baseline then a clean --check round-trip
    bl = str(tmp_path / "bl.json")
    assert (
        cli_main(
            [
                str(tmp_path / "mod.py"),
                "--root",
                str(tmp_path),
                "--baseline",
                bl,
                "--write-baseline",
            ]
        )
        == 0
    )
    capsys.readouterr()
    assert (
        cli_main(
            [
                str(tmp_path / "mod.py"),
                "--root",
                str(tmp_path),
                "--baseline",
                bl,
                "--check",
            ]
        )
        == 0
    )
    capsys.readouterr()
    assert cli_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    assert all(rid in listed for rid in RULE_IDS)


def _violating_tree(tmp_path):
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def pick(x):
                return jnp.nonzero(x > 0)
            """
        )
    )
    return [
        str(tmp_path / "mod.py"), "--root", str(tmp_path), "--no-baseline"
    ]


def test_cli_sarif_format(tmp_path, capsys):
    rc = cli_main(_violating_tree(tmp_path) + ["--format", "sarif"])
    sarif = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "gridlint"
    results = run["results"]
    assert [r["ruleId"] for r in results] == ["G003"]
    loc = results[0]["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "mod.py"
    assert loc["region"]["startLine"] >= 1
    assert loc["region"]["startColumn"] >= 1  # SARIF columns are 1-based
    # the rule catalog rides along for code-scanning display
    assert any(
        r["id"] == "G003" for r in run["tool"]["driver"]["rules"]
    )


def test_cli_github_format(tmp_path, capsys):
    rc = cli_main(_violating_tree(tmp_path) + ["--format", "github"])
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 1
    assert len(out) == 1
    line = out[0]
    assert line.startswith("::warning file=mod.py,line=")
    assert "title=G003" in line and "::" in line[2:]
    # a clean tree emits no annotation lines and exits 0
    clean = tmp_path / "clean"
    clean.mkdir()
    (clean / "ok.py").write_text("x = 1\n")
    rc = cli_main(
        [str(clean / "ok.py"), "--root", str(clean), "--no-baseline",
         "--format", "github"]
    )
    assert rc == 0
    assert capsys.readouterr().out.strip() == ""


def test_cli_check_baseline_hygiene(tmp_path, capsys):
    """--check-baseline reports ONLY staleness: exit 1 + a named stale
    entry once the violation is fixed, exit 0 while the baseline still
    matches — and it must NOT gate new findings (that's --check's job)."""
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def pick(x):
                return jnp.nonzero(x > 0)
            """
        )
    )
    bl = str(tmp_path / "bl.json")
    args = [str(tmp_path / "mod.py"), "--root", str(tmp_path),
            "--baseline", bl]
    assert cli_main(args + ["--write-baseline"]) == 0
    capsys.readouterr()
    # baseline still matches: hygiene passes
    assert cli_main(args + ["--check-baseline"]) == 0
    assert "0 stale" in capsys.readouterr().out
    # fix the violation; the suppression is now stale -> exit 1, and the
    # report names the entry so it can be deleted
    (tmp_path / "mod.py").write_text("x = 1\n")
    rc = cli_main(args + ["--check-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "stale baseline entry" in out and "G003" in out
    assert "1 stale" in out
    # a NEW finding alone does not trip hygiene mode: fresh violating
    # file, empty-but-present baseline dir via --no-baseline is gated
    # elsewhere; here use a matching baseline plus an extra violation
    (tmp_path / "mod.py").write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def pick(x):
                return jnp.nonzero(x > 0)
            """
        )
    )
    (tmp_path / "mod2.py").write_text(
        textwrap.dedent(
            """
            import jax
            import jax.numpy as jnp

            @jax.jit
            def pick2(x):
                return jnp.unique(x)
            """
        )
    )
    rc = cli_main(
        [str(tmp_path / "mod.py"), str(tmp_path / "mod2.py"),
         "--root", str(tmp_path), "--baseline", bl, "--check-baseline"]
    )
    out = capsys.readouterr().out
    assert rc == 0, out  # mod2's new finding is not this mode's business
    assert "0 stale" in out


# ------------------------------------------------------- the repo gate


def test_package_is_gridlint_clean_against_baseline():
    """The tier-1 gate: zero non-baselined findings over the package."""
    findings = run_gridlint([PACKAGE], root=REPO_ROOT)
    baseline = load_baseline(default_baseline_path())
    new, _ = split_baselined(findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)


def test_baseline_has_no_stale_entries():
    findings = run_gridlint([PACKAGE], root=REPO_ROOT)
    baseline = load_baseline(default_baseline_path())
    _, old = split_baselined(findings, baseline)
    stale = baseline - {f.baseline_key() for f in old}
    assert stale == set(), stale


def test_cli_script_entry_point():
    """scripts/gridlint.py is runnable and exits 0 on the package."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "scripts", "gridlint.py"),
         "mpi_grid_redistribute_tpu/", "--check"],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
