"""racecheck: host-thread shared-state analyzer (T001-T005, ISSUE 15).

Mirrors tests/test_gridlint.py's shape: every rule gets a minimal
fixture pair — one that FIRES and a twin with the blessed idiom that
stays QUIET — written under tmp_path and scanned with the real
analyzer, plus CLI/exit-code coverage and the repo-wide gate (the tree
at HEAD must be clean modulo the justified committed baseline).

The second half exercises the runtime twin, ``telemetry/tsan.py``:
``ThreadAccessTracer`` must stay silent across the supervised fault
matrix and the SLO-breach scenario (the recorder lock actually guards
every journal mutation), and must deterministically flag a recorder
whose write path bypasses the lock — the regression the static T-rules
can only approximate.
"""

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

from mpi_grid_redistribute_tpu.analysis.baseline import (
    racecheck_baseline_path,
)
from mpi_grid_redistribute_tpu.analysis.racecheck import (
    T_RULE_IDS,
    build_model,
    main as race_main,
    run_racecheck,
)
from mpi_grid_redistribute_tpu.telemetry import (
    StepRecorder,
    ThreadAccessTracer,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def check(tmp_path, files, rules=None):
    """Write ``files`` (name -> source) under tmp_path and scan them."""
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_racecheck([str(tmp_path)], root=str(tmp_path), rules=rules)


def rules_of(findings):
    return [f.rule for f in findings]


# ------------------------------------------------------------ T001


_T001_FIRE = """
    import threading

    counter = 0

    def w1():
        global counter
        counter = counter + 1

    def w2():
        global counter
        counter = counter - 1

    def main():
        t1 = threading.Thread(target=w1, daemon=True)
        t2 = threading.Thread(target=w2, daemon=True)
        t1.start()
        t2.start()
        t1.join()
        t2.join()
"""


def test_t001_unguarded_global_write_fires(tmp_path):
    fs = check(tmp_path, {"mod.py": _T001_FIRE}, rules=["T001"])
    assert rules_of(fs) == ["T001"]
    assert "counter" in fs[0].message
    assert "no common lock" in fs[0].message


def test_t001_common_lock_is_quiet(tmp_path):
    quiet = _T001_FIRE.replace(
        "global counter\n        counter = counter + 1",
        "global counter\n        with lock:\n            "
        "counter = counter + 1",
    ).replace(
        "global counter\n        counter = counter - 1",
        "global counter\n        with lock:\n            "
        "counter = counter - 1",
    ).replace(
        "counter = 0", "counter = 0\n    lock = threading.Lock()"
    )
    assert check(tmp_path, {"mod.py": quiet}, rules=["T001"]) == []


def test_t001_class_field_from_two_threads(tmp_path):
    src = """
        import threading

        class Tally:
            def __init__(self):
                self.total = 0
                self.seen = []

            def bump(self):
                self.total = self.total + 1
                self.seen.append(self.total)

        box = Tally()

        def w1():
            box.bump()

        def w2():
            box.bump()

        def main():
            a = threading.Thread(target=w1, daemon=True)
            b = threading.Thread(target=w2, daemon=True)
            a.start()
            b.start()
            a.join()
            b.join()
    """
    fs = check(tmp_path, {"mod.py": src}, rules=["T001"])
    syms = {f.symbol for f in fs}
    assert any("total" in s for s in syms)
    # .append on a self.field is a WRITE through the mutator table
    assert any("seen" in s for s in syms)


def test_t001_handler_pool_alone_counts_as_cross_thread(tmp_path):
    # a pool root (http.server handler) races against itself: a write
    # inside its closure fires even with no second Thread anywhere
    src = """
        import http.server

        total = 0

        class H(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                global total
                total = total + 1
    """
    fs = check(tmp_path, {"srv.py": src}, rules=["T001"])
    assert rules_of(fs) == ["T001"]
    assert "total" in fs[0].message


def test_t001_reads_only_never_fire(tmp_path):
    # cross-thread READS of a config-style global are fine: T001 needs
    # at least one non-init write
    src = """
        import threading

        limit = 7

        def w1():
            return limit + 1

        def w2():
            return limit + 2

        def main():
            a = threading.Thread(target=w1, daemon=True)
            b = threading.Thread(target=w2, daemon=True)
            a.start()
            b.start()
            a.join()
            b.join()
    """
    assert check(tmp_path, {"mod.py": src}, rules=["T001"]) == []


def test_t001_caller_held_lock_guards_helper(tmp_path):
    # the recorder.py idiom: the public method takes the lock, the
    # private helper mutates. One level of caller-guard inference must
    # keep the helper's writes guarded.
    src = """
        import threading

        class Rec:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self._n = self._n + 1

        r = Rec()

        def w1():
            r.bump()

        def w2():
            r.bump()

        def main():
            a = threading.Thread(target=w1, daemon=True)
            b = threading.Thread(target=w2, daemon=True)
            a.start()
            b.start()
            a.join()
            b.join()
    """
    assert check(tmp_path, {"mod.py": src}, rules=["T001"]) == []


# ------------------------------------------------------------ T002


_T002_FIRE = """
    import threading

    a = threading.Lock()
    b = threading.Lock()

    def f1():
        with a:
            with b:
                pass

    def f2():
        with b:
            with a:
                pass
"""


def test_t002_lock_order_cycle_fires(tmp_path):
    fs = check(tmp_path, {"mod.py": _T002_FIRE}, rules=["T002"])
    assert rules_of(fs) == ["T002"]
    assert "cycle" in fs[0].message


def test_t002_consistent_order_is_quiet(tmp_path):
    quiet = _T002_FIRE.replace(
        "with b:\n            with a:", "with a:\n            with b:"
    )
    assert check(tmp_path, {"mod.py": quiet}, rules=["T002"]) == []


# ------------------------------------------------------------ T003


def test_t003_sleep_under_lock_fires(tmp_path):
    src = """
        import threading
        import time

        lk = threading.Lock()

        def f():
            with lk:
                time.sleep(0.5)
    """
    fs = check(tmp_path, {"mod.py": src}, rules=["T003"])
    assert rules_of(fs) == ["T003"]
    assert "while holding lock" in fs[0].message


def test_t003_interprocedural_one_level(tmp_path):
    # the blocking call hides one call deep; f holds the lock
    src = """
        import threading
        import time

        lk = threading.Lock()

        def helper():
            time.sleep(0.5)

        def f():
            with lk:
                helper()
    """
    fs = check(tmp_path, {"mod.py": src}, rules=["T003"])
    assert rules_of(fs) == ["T003"]
    assert "helper" in fs[0].message


def test_t003_copy_then_io_outside_lock_is_quiet(tmp_path):
    # the blessed to_jsonl shape: snapshot under the lock, I/O outside
    src = """
        import threading
        import time

        lk = threading.Lock()
        ring = []

        def f():
            with lk:
                snap = list(ring)
            time.sleep(0.5)
            return snap
    """
    assert check(tmp_path, {"mod.py": src}, rules=["T003"]) == []


def test_t003_str_join_is_not_blocking(tmp_path):
    src = """
        import threading

        lk = threading.Lock()

        def f(parts):
            with lk:
                return ",".join(parts)
    """
    assert check(tmp_path, {"mod.py": src}, rules=["T003"]) == []


# ------------------------------------------------------------ T004


_T004_FIRE = """
    # gridlint: service-path
    import threading

    def work():
        pass

    def main():
        t = threading.Thread(target=work)
        t.start()
"""


def test_t004_undisciplined_thread_in_service_module(tmp_path):
    fs = check(tmp_path, {"svc.py": _T004_FIRE}, rules=["T004"])
    assert rules_of(fs) == ["T004"]
    assert "service path" in fs[0].message


def test_t004_daemon_and_joined_is_quiet(tmp_path):
    quiet = _T004_FIRE.replace(
        "t = threading.Thread(target=work)",
        "t = threading.Thread(target=work, daemon=True)",
    ).replace("t.start()", "t.start()\n        t.join()")
    assert check(tmp_path, {"svc.py": quiet}, rules=["T004"]) == []


def test_t004_unmarked_module_is_exempt(tmp_path):
    unmarked = _T004_FIRE.replace(
        "    # gridlint: service-path\n", ""
    )
    assert check(tmp_path, {"svc.py": unmarked}, rules=["T004"]) == []


# ------------------------------------------------------------ T005


_T005_FIRE = """
    import threading

    class StepRecorder:
        def record(self, kind, **data):
            pass

    rec = StepRecorder()

    def worker():
        rec.record("step")

    def main():
        t = threading.Thread(target=worker, daemon=True)
        t.start()
        t.join()
"""


def test_t005_unmarked_writer_thread_fires(tmp_path):
    fs = check(tmp_path, {"mod.py": _T005_FIRE}, rules=["T005"])
    assert rules_of(fs) == ["T005"]
    assert "recorder-writer" in fs[0].message


def test_t005_marked_writer_is_quiet(tmp_path):
    quiet = _T005_FIRE.replace(
        "def worker():",
        "def worker():  # racecheck: recorder-writer",
    )
    assert check(tmp_path, {"mod.py": quiet}, rules=["T005"]) == []


def test_t005_fresh_local_recorder_is_exempt(tmp_path):
    # a thread that builds its OWN recorder is single-writer by
    # construction — no marker needed
    src = """
        import threading

        class StepRecorder:
            def record(self, kind, **data):
                pass

        def worker():
            mine = StepRecorder()
            mine.record("step")

        def main():
            t = threading.Thread(target=worker, daemon=True)
            t.start()
            t.join()
    """
    assert check(tmp_path, {"mod.py": src}, rules=["T005"]) == []


# ------------------------------------------------- suppression/model


def test_same_line_suppression(tmp_path):
    src = """
        import threading
        import time

        lk = threading.Lock()

        def f():
            with lk:
                time.sleep(0.5)  # racecheck: disable=T003
    """
    assert check(tmp_path, {"mod.py": src}, rules=["T003"]) == []


def test_file_level_suppression(tmp_path):
    src = "# racecheck: disable-file=T002\n" + textwrap.dedent(
        _T002_FIRE
    )
    (tmp_path / "mod.py").write_text(src)
    assert (
        run_racecheck(
            [str(tmp_path)], root=str(tmp_path), rules=["T002"]
        )
        == []
    )


def test_gridlint_markers_do_not_suppress_racecheck(tmp_path):
    # racecheck has its OWN marker namespace: a gridlint disable on the
    # same line must not silence a T-rule
    src = """
        import threading
        import time

        lk = threading.Lock()

        def f():
            with lk:
                time.sleep(0.5)  # gridlint: disable=T003
    """
    fs = check(tmp_path, {"mod.py": src}, rules=["T003"])
    assert rules_of(fs) == ["T003"]


def test_rule_subset_filters(tmp_path):
    both = textwrap.dedent(_T002_FIRE) + textwrap.dedent(
        """
        import time

        def g():
            with a:
                time.sleep(0.5)
        """
    )
    (tmp_path / "mod.py").write_text(both)
    only = run_racecheck(
        [str(tmp_path)], root=str(tmp_path), rules=["T002"]
    )
    assert set(rules_of(only)) == {"T002"}
    every = run_racecheck([str(tmp_path)], root=str(tmp_path))
    assert {"T002", "T003"} <= set(rules_of(every))


def test_model_topology_facts(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent(_T001_FIRE))
    model = build_model([str(tmp_path)], root=str(tmp_path))
    labels = sorted(model.root_by_label)
    assert len(labels) == 2
    for label in labels:
        r = model.root_by_label[label]
        assert r.daemon is True
        assert r.joined is True
        assert model.reach[label]  # closure reaches the target


# ----------------------------------------------------------- CLI


def _write_fixture(tmp_path, src):
    (tmp_path / "mod.py").write_text(textwrap.dedent(src))


def test_cli_clean_exit_0(tmp_path, capsys):
    _write_fixture(tmp_path, "x = 1\n")
    rc = race_main(
        [str(tmp_path), "--root", str(tmp_path), "--no-baseline"]
    )
    assert rc == 0
    assert "0 finding(s)" in capsys.readouterr().out


def test_cli_findings_exit_1_and_json(tmp_path, capsys):
    _write_fixture(tmp_path, _T002_FIRE)
    rc = race_main(
        [
            str(tmp_path),
            "--root",
            str(tmp_path),
            "--no-baseline",
            "--format=json",
        ]
    )
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in doc["findings"]] == ["T002"]


def test_cli_sarif_shape(tmp_path, capsys):
    _write_fixture(tmp_path, _T002_FIRE)
    rc = race_main(
        [
            str(tmp_path),
            "--root",
            str(tmp_path),
            "--no-baseline",
            "--format=sarif",
        ]
    )
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    (run,) = doc["runs"]
    assert run["tool"]["driver"]["name"] == "racecheck"
    assert {r["ruleId"] for r in run["results"]} == {"T002"}


def test_cli_unknown_rule_exit_2(tmp_path, capsys):
    _write_fixture(tmp_path, "x = 1\n")
    rc = race_main(
        [str(tmp_path), "--root", str(tmp_path), "--rules", "T999"]
    )
    assert rc == 2
    assert "unknown rule" in capsys.readouterr().err


def test_cli_list_rules(capsys):
    assert race_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in T_RULE_IDS:
        assert rid in out


def test_cli_list_threads(tmp_path, capsys):
    _write_fixture(tmp_path, _T001_FIRE)
    rc = race_main(
        [str(tmp_path), "--root", str(tmp_path), "--list-threads"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "thread roots:" in out
    assert "daemon=True" in out
    assert "cross-thread fields:" in out
    assert "UNGUARDED" in out


def test_cli_write_baseline_roundtrip(tmp_path, capsys):
    _write_fixture(tmp_path, _T002_FIRE)
    bl = tmp_path / "bl.json"
    rc = race_main(
        [
            str(tmp_path),
            "--root",
            str(tmp_path),
            "--write-baseline",
            "--baseline",
            str(bl),
        ]
    )
    assert rc == 0
    assert json.loads(bl.read_text())["findings"]
    capsys.readouterr()
    rc = race_main(
        [
            str(tmp_path),
            "--root",
            str(tmp_path),
            "--check",
            "--baseline",
            str(bl),
        ]
    )
    assert rc == 0
    assert "baselined" in capsys.readouterr().out


def test_cli_stale_baseline_fails_check(tmp_path, capsys):
    _write_fixture(tmp_path, "x = 1\n")
    bl = tmp_path / "bl.json"
    bl.write_text(
        json.dumps(
            {
                "comment": "test",
                "findings": [
                    {
                        "rule": "T001",
                        "path": "gone.py",
                        "symbol": "gone.x",
                        "message": "never matches",
                        "justification": "stale on purpose",
                    }
                ],
            }
        )
    )
    rc = race_main(
        [
            str(tmp_path),
            "--root",
            str(tmp_path),
            "--check",
            "--baseline",
            str(bl),
        ]
    )
    assert rc == 1
    assert "stale" in capsys.readouterr().out


# ------------------------------------------------- repo-wide gate


def test_repo_is_racecheck_clean():
    # the committed tree must carry zero unjustified findings: the CI
    # entry point itself (subprocess, like make racecheck runs it)
    proc = subprocess.run(
        [sys.executable, "scripts/racecheck.py", "--check"],
        cwd=REPO,
        capture_output=True,
        text=True,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_committed_baseline_entries_are_justified():
    data = json.loads(
        open(racecheck_baseline_path(), encoding="utf-8").read()
    )
    assert data["findings"], "baseline exists but is empty?"
    for entry in data["findings"]:
        assert entry.get("justification", "").strip(), entry
        assert entry["rule"] in T_RULE_IDS


# =================================================================
# runtime twin: telemetry/tsan.py
# =================================================================


def test_tsan_clean_concurrent_run():
    rec = StepRecorder(capacity=256)

    def writer():
        for i in range(200):
            rec.record("step_time", step=i, seconds=0.001)

    with ThreadAccessTracer(rec) as tracer:
        t = threading.Thread(target=writer, daemon=True)
        t.start()
        # concurrent scrape path: snapshot reads under the lock
        for _ in range(50):
            rec.counts()
            rec.events("step_time")
        t.join()
        tracer.assert_clean()
        assert tracer.violations() == []
        assert len(tracer.by_thread()) >= 2
        assert tracer.accesses

    # arm/disarm journaled per SCHEMA.md `thread_audit`
    audits = rec.events("thread_audit")
    assert [e.data["action"] for e in audits] == ["arm", "disarm"]
    assert audits[1].data["violations"] == 0
    assert audits[1].data["accesses"] > 0
    assert audits[1].data["threads"] >= 2
    # the traced run still counted every record()
    assert rec.counts()["step_time"] == 200


def test_tsan_detects_lockless_mutation():
    rec = StepRecorder(capacity=8)
    with ThreadAccessTracer(rec) as tracer:
        rec.record("ok")  # locked: clean
        # bypass the lock the way a regressed recorder would
        rec._counts["x"] = rec._counts.get("x", 0) + 1
        bad = tracer.violations()
        assert len(bad) == 2  # the lockless read + the lockless write
        assert {v.op for v in bad} == {"read", "write"}
        assert all(v.field == "_counts" for v in bad)
        with pytest.raises(AssertionError, match="unguarded"):
            tracer.assert_clean()


def test_tsan_attributes_violation_to_thread():
    rec = StepRecorder(capacity=8)

    def rogue():
        rec._ring.append(None)  # no lock held

    with ThreadAccessTracer(rec) as tracer:
        t = threading.Thread(
            target=rogue, name="rogue-writer", daemon=True
        )
        t.start()
        t.join()
        (v,) = tracer.violations()
        assert v.thread_name == "rogue-writer"
        assert v.field == "_ring"
        assert v.op == "write"


def test_tsan_catches_unlocked_record_subclass():
    # the exact regression T005/T001 exist to prevent: a record() that
    # skips the lock. The static rules see idioms; the tracer sees the
    # actual interleaving surface — it must flag this deterministically,
    # single-threaded, no lucky timing required.
    class UnlockedRecorder(StepRecorder):
        def record(self, kind, **data):
            self._record_locked(kind, None, data)  # no lock!

    rec = UnlockedRecorder(capacity=8)
    with ThreadAccessTracer(rec) as tracer:
        rec.record("step_time", seconds=0.001)
        assert tracer.violations()
        fields = {v.field for v in tracer.violations()}
        assert "_counts" in fields and "_ring" in fields
        with pytest.raises(AssertionError):
            tracer.assert_clean()


def test_tsan_disarm_restores_recorder():
    rec = StepRecorder(capacity=16)
    orig_lock = rec._lock
    with ThreadAccessTracer(rec):
        rec.record("a")
        rec.record("b")
        assert rec._lock is not orig_lock  # traced while armed
    assert rec._lock is orig_lock
    assert type(rec._counts) is dict
    assert type(rec._ring).__name__ == "deque"
    # journal state survives the copy-back
    assert rec.counts()["a"] == 1
    assert [e.kind for e in rec.events()][:2] == [
        "thread_audit",
        "a",
    ]


# ------------------------- tsan-instrumented service scenarios


service = pytest.importorskip(
    "mpi_grid_redistribute_tpu.service",
    reason="service plane unavailable",
)


def _cfg(tmp_path, **kw):
    base = dict(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=24,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=str(tmp_path / "snaps"),
    )
    base.update(kw)
    return service.DriverConfig(**base)


def _supervised(tmp_path, cfg, faults, max_restarts=5, **policy_kw):
    import dataclasses

    rec = StepRecorder()

    def factory(grid_shape=None):
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return service.ServiceDriver(c, recorder=rec, faults=faults)

    sup = service.Supervisor(
        factory,
        policy=service.RestartPolicy(
            max_restarts=max_restarts,
            backoff_base_s=0.01,
            backoff_cap_s=0.02,
            **policy_kw,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    return sup, rec


@pytest.mark.parametrize("kind", [
    "crash", "stall", "torn_snapshot", "journal_loss",
    "fallback_flood",
])
def test_tsan_fault_matrix_lock_discipline(tmp_path, kind):
    # the whole fault matrix re-run with the sanitizer armed: every
    # journal access from the step loop, the async snapshot writer and
    # the health scrape must hold the recorder lock
    extra = {}
    if kind == "crash":
        fault = service.CrashFault(9)
    elif kind == "stall":
        fault = service.StallFault(7, seconds=0.5)
        extra["watchdog_s"] = 0.2
    elif kind == "torn_snapshot":
        fault = service.TornSnapshotFault(snapshot_index=1)
    elif kind == "journal_loss":
        fault = service.JournalShardLossFault(6)
        extra["journal_dir"] = str(tmp_path / "journal")
    else:
        fault = service.FallbackFloodFault(start_step=1, steps=24)

    cfg = _cfg(tmp_path, **extra)
    sup, rec = _supervised(
        tmp_path, cfg, service.FaultPlan([fault])
    )
    with ThreadAccessTracer(rec) as tracer:
        verdict = sup.run()
        tracer.assert_clean()
        assert tracer.accesses

    assert verdict.ok is True, verdict
    audits = rec.events("thread_audit")
    assert [e.data["action"] for e in audits] == ["arm", "disarm"]
    assert audits[-1].data["violations"] == 0


def test_tsan_slo_breach_supervisor_clean(tmp_path):
    # the busiest host-thread scenario in the suite (restart -> shrink
    # -> elastic re-shard, snapshot writer live throughout): still zero
    # unguarded journal accesses
    cfg = _cfg(
        tmp_path, steps=32, slo_latency_p99_s=0.25, slo_window=4,
    )
    plan = service.FaultPlan(
        [service.LatencySpikeFault(2, seconds=1.0, spikes=6)]
    )
    sup, rec = _supervised(tmp_path, cfg, plan, shrink_after=2)
    with ThreadAccessTracer(rec) as tracer:
        verdict = sup.run()
        tracer.assert_clean()

    assert verdict.ok is True, verdict
    assert verdict.restarts == 2
    assert tuple(sup.driver.cfg.grid_shape) == (1, 2, 2)


def test_supervisor_give_up_leaks_no_nondaemon_threads(tmp_path):
    # T004's runtime counterpart: even when the supervisor gives up
    # mid-run, no non-daemon helper thread may outlive it
    before = {
        t for t in threading.enumerate() if not t.daemon and t.is_alive()
    }
    cfg = _cfg(tmp_path, steps=12)
    sup, rec = _supervised(
        tmp_path,
        cfg,
        service.FaultPlan([service.CrashFault(None)]),
        max_restarts=2,
    )
    verdict = sup.run()
    assert verdict.gave_up is True
    for t in threading.enumerate():
        if t in before or not t.is_alive():
            continue
        assert t.daemon, f"non-daemon thread leaked: {t.name}"
