"""Cross-check the built-in NumPy rank-simulation oracle against REAL
mpi4py collectives (SURVEY.md §4). mpi4py is not installed in the build
environment, so this module skips there; on a machine with MPI, run e.g.:

    mpirun -n 8 python -m pytest tests/test_oracle_mpi4py.py -q

Each rank redistributes its shard with ``comm.Alltoall`` +
``comm.Alltoallv`` and compares byte-for-byte with what
``oracle.redistribute_oracle`` predicts for its rank — proving the
simulated ``Alltoallv`` receive-ordering semantics (source-major, stable
within source) match the real MPI library.
"""

import numpy as np
import pytest

mpi4py = pytest.importorskip("mpi4py")
from mpi4py import MPI  # noqa: E402

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu import oracle


def test_oracle_matches_real_alltoallv():
    comm = MPI.COMM_WORLD
    R = comm.Get_size()
    rank = comm.Get_rank()
    grid_shape = {1: (1, 1, 1), 2: (2, 1, 1), 4: (2, 2, 1), 8: (2, 2, 2)}.get(R)
    if grid_shape is None:
        pytest.skip(f"no grid mapping for {R} ranks")
    grid = ProcessGrid(grid_shape)
    domain = Domain(0.0, 1.0, periodic=True)

    n_local = 1000
    rng = np.random.default_rng(1234 + rank)
    pos = rng.random((n_local, 3), dtype=np.float32)

    # --- real MPI path ---
    dest = binning.rank_of_position(pos, domain, grid, xp=np)
    order = np.argsort(dest, kind="stable")
    send_buf = np.ascontiguousarray(pos[order])
    send_counts = np.bincount(dest, minlength=R).astype(np.int64)
    recv_counts = np.empty(R, dtype=np.int64)
    comm.Alltoall(send_counts, recv_counts)
    recv_buf = np.empty((int(recv_counts.sum()), 3), dtype=np.float32)
    comm.Alltoallv(
        [send_buf, send_counts * 3, MPI.FLOAT],
        [recv_buf, recv_counts * 3, MPI.FLOAT],
    )

    # --- simulated oracle (every rank simulates all shards) ---
    all_pos = comm.allgather(pos)
    want_pos, _, _ = oracle.redistribute_oracle(domain, grid, all_pos)
    assert recv_buf.tobytes() == want_pos[rank].tobytes()
