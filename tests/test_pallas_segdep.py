"""Segmented CIC deposit kernel (ops/pallas_segdep.py) vs the XLA
segment_sum fallback — interpret mode on CPU.

The two engines share :func:`_corner_weights`, so per-particle channel
VALUES are identical bits; only the per-cell SUMMATION order differs
(MXU chunk accumulation vs scatter-add). Bit-identity across engines
is therefore asserted on DYADIC data: ``rel`` drawn from multiples of
1/4 makes every corner weight a multiple of 1/16, and with ~a dozen
rows per cell the partial sums stay exactly representable in f32 —
any order sums to the same bits. Generic float data gets an allclose
gate against a float64 oracle instead (that tolerance, not bit
equality, is the cross-engine contract for arbitrary reals)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu.ops import pallas_segdep


def _dyadic_case(seed, n, n_cells, d, sentinel_tail):
    r = np.random.default_rng(seed)
    keys = np.sort(
        r.integers(0, n_cells, size=n - sentinel_tail)
    ).astype(np.int32)
    keys = np.concatenate(
        [keys, np.full((sentinel_tail,), n_cells, np.int32)]
    )
    # multiples of 1/4 in [0, 8): corner weights become multiples of
    # 1/16, so every per-cell sum is exact in f32 (order-independent)
    rel = (r.integers(0, 32, size=(d, n)) * 0.25).astype(np.float32)
    return jnp.asarray(keys), jnp.asarray(rel)


def _xla_twin(keys, rel, mass, n_cells, vblock, d):
    return np.asarray(
        jax.jit(
            lambda k, rl: pallas_segdep._segsum_xla(
                k, rl, mass, n_cells, vblock, d
            )
        )(keys, rel)
    )


@pytest.mark.parametrize(
    "n,n_cells,d,vblock",
    [
        (2048, 256, 2, (8, 8)),  # single T-block
        (6000, 512, 2, (8, 8)),  # grid (2,): chunk boundary mid-stream
        (3000, 200, 3, (4, 4, 4)),  # 3-D: 8 channels, odd cell count
    ],
)
def test_segdep_matches_xla_twin_bits_on_dyadic_data(
    rng, _devices, n, n_cells, d, vblock
):
    keys, rel = _dyadic_case(hash((n, n_cells, d)) % 2**32, n, n_cells,
                             d, sentinel_tail=n // 20)
    got = np.asarray(
        pallas_segdep.segsum_sorted(
            keys, rel, None, n_cells, vblock, interpret=True
        )
    )
    want = _xla_twin(keys, rel, None, n_cells, vblock, d)
    assert got.shape == (2**d, n_cells)
    np.testing.assert_array_equal(
        got.view(np.uint32), want.view(np.uint32)
    )


def test_segdep_all_sentinel_stream(rng, _devices):
    """A fully-invalid stream (every key = the n_cells sentinel) must
    deposit exactly zero everywhere in both engines."""
    n, n_cells, d, vblock = 1024, 128, 2, (8, 8)
    keys = jnp.full((n,), n_cells, jnp.int32)
    r = np.random.default_rng(3)
    rel = jnp.asarray(
        (r.integers(0, 32, size=(d, n)) * 0.25).astype(np.float32)
    )
    got = np.asarray(
        pallas_segdep.segsum_sorted(
            keys, rel, None, n_cells, vblock, interpret=True
        )
    )
    np.testing.assert_array_equal(got, np.zeros((4, n_cells), np.float32))


def test_segdep_generic_floats_match_f64_oracle(rng, _devices):
    """Arbitrary reals: both engines must sit within f32 summation
    noise of the float64 scatter-add oracle (bit equality is NOT the
    contract here — summation order differs by design)."""
    n, n_cells, d, vblock = 4096, 256, 2, (8, 8)
    r = np.random.default_rng(9)
    keys = np.sort(r.integers(0, n_cells, size=n)).astype(np.int32)
    rel = (r.random((d, n)) * np.array(vblock)[:, None]).astype(
        np.float32
    )
    got = np.asarray(
        pallas_segdep.segsum_sorted(
            jnp.asarray(keys), jnp.asarray(rel), None, n_cells, vblock,
            interpret=True,
        )
    )
    w64 = np.asarray(
        pallas_segdep._corner_weights(
            [jnp.asarray(rel[dd]) for dd in range(d)], None, vblock
        ),
        np.float64,
    )
    oracle = np.zeros((2**d, n_cells), np.float64)
    for ch in range(2**d):
        np.add.at(oracle[ch], keys, w64[ch])
    np.testing.assert_allclose(got, oracle, rtol=2e-5, atol=2e-5)
