"""Telemetry package: recorder, report math, phase attribution, regress
gate. All CPU-runnable (tier 1); device work uses the 8 virtual CPU
devices from conftest.py."""

import json

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.parallel.exchange import RedistributeStats
from mpi_grid_redistribute_tpu.parallel.migrate import MigrateStats
from mpi_grid_redistribute_tpu.telemetry import (
    StepRecorder,
    attribute_phases,
    check_capture,
    exchange_report,
    extract_metrics,
    format_phase_table,
    min_of_k,
    record_migrate_steps,
    row_bytes_of,
)
from mpi_grid_redistribute_tpu.utils import profiling


# ---------------------------------------------------------------- recorder


def test_recorder_ring_eviction_and_counts():
    rec = StepRecorder(capacity=4)
    for i in range(10):
        rec.record("tick", i=i)
    assert len(rec) == 4
    assert rec.total_recorded == 10
    assert rec.evicted == 6
    # all-time counts survive eviction
    assert rec.counts() == {"tick": 10}
    # retained window is the newest events, oldest first
    assert [e.data["i"] for e in rec.events("tick")] == [6, 7, 8, 9]
    assert rec.last("tick").data["i"] == 9
    rec.clear()
    assert len(rec) == 0 and rec.counts() == {}


def test_recorder_disabled_still_counts():
    rec = StepRecorder(capacity=8, enabled=False)
    rec.record("tick")
    rec.record("tock")
    assert len(rec) == 0
    assert rec.counts() == {"tick": 1, "tock": 1}


def test_recorder_jsonl_roundtrip(tmp_path):
    rec = StepRecorder()
    rec.record("capacity_grow", old=8, new=16)
    rec.record("redistribute", call=0)
    path = tmp_path / "events.jsonl"
    assert rec.to_jsonl(str(path)) == 2
    lines = path.read_text().splitlines()
    assert len(lines) == 2
    first = json.loads(lines[0])
    assert first["kind"] == "capacity_grow"
    assert first["old"] == 8 and first["new"] == 16
    assert json.loads(lines[1])["seq"] > first["seq"]


def test_record_migrate_steps_bridges_stacked_stats():
    S, R = 3, 4
    stats = MigrateStats(
        sent=np.full((S, R), 2, np.int32),
        received=np.full((S, R), 2, np.int32),
        population=np.full((S, R), 100, np.int32),
        backlog=np.zeros((S, R), np.int32),
        dropped_recv=np.zeros((S, R), np.int32),
    )
    rec = StepRecorder()
    assert record_migrate_steps(rec, stats) == S
    evs = rec.events("migrate_step")
    assert [e.data["step"] for e in evs] == [0, 1, 2]
    assert all(e.data["sent"] == 2 * R for e in evs)
    # trailing window
    rec2 = StepRecorder()
    assert record_migrate_steps(rec2, stats, max_steps=1) == 1
    assert rec2.last("migrate_step").data["step"] == S - 1


# -------------------------------------------------- recorder from real API


def test_recorder_events_from_real_grow_path():
    from mpi_grid_redistribute_tpu import GridRedistribute

    rng = np.random.default_rng(3)
    pos = rng.random((512, 3), dtype=np.float32)
    with GridRedistribute(
        lo=0.0, hi=1.0, grid=(2, 2, 2), capacity=2, on_overflow="grow"
    ) as rd:
        res = rd.redistribute(pos)
        assert int(np.asarray(res.count).sum()) == 512
        counts = rd.telemetry.counts()
        # a per-pair capacity of 2 cannot carry ~512/8 rows/pair: the
        # retry loop must have grown and journaled it
        assert counts.get("capacity_grow", 0) >= 1
        assert counts.get("redistribute", 0) >= 1
        grow = rd.telemetry.last("capacity_grow")
        assert grow.data["new"] > grow.data["old"]
        assert grow.data["needed"] > 2

        rep = rd.report()
        assert rep["kind"] == "redistribute"
        assert rep["exchange_bytes_per_step"] > 0
        assert rep["bw_util"] is None  # no step_seconds supplied
        rep2 = rd.report(step_seconds=1e-3)
        assert rep2["bw_util"] > 0
        assert rep2["events"]["capacity_grow"] == counts["capacity_grow"]
        assert rep2["unresolved_windows"] is False


def test_report_before_any_call_raises():
    from mpi_grid_redistribute_tpu import GridRedistribute

    rd = GridRedistribute(lo=0.0, hi=1.0, grid=(2, 2, 2))
    with pytest.raises(RuntimeError):
        rd.report()


# ------------------------------------------------------------- report math


def test_row_bytes_of():
    import jax

    pos = np.zeros((10, 3), np.float32)
    ids = np.zeros((10,), np.int32)
    vel = np.zeros((10, 3), np.float32)
    assert row_bytes_of(pos) == 12
    assert row_bytes_of(pos, vel, ids) == 28
    structs = [
        jax.ShapeDtypeStruct((10, 3), np.float32),
        jax.ShapeDtypeStruct((10,), np.int32),
    ]
    assert row_bytes_of(*structs) == 16


def _stats_2rank():
    # rank 0 sends 3 (keeps) + 1 (moves); rank 1 sends 2 (moves) + 4
    send = np.array([[3, 1], [2, 4]], np.int32)
    return RedistributeStats(
        send_counts=send,
        recv_counts=send.T,
        dropped_send=np.zeros((2,), np.int32),
        dropped_recv=np.zeros((2,), np.int32),
        needed_capacity=np.full((2,), 4, np.int32),
    )


def test_exchange_report_hand_math_hbm():
    stats = _stats_2rank()
    row_bytes = 28
    rep = exchange_report(stats, row_bytes, step_seconds=0.01, domain="hbm")
    # total = 10 rows, moved (off-diagonal) = 3 rows
    assert rep["exchange_bytes_per_step"] == 10 * row_bytes
    assert rep["moved_bytes_per_step"] == 3 * row_bytes
    # HBM domain: ALL rows cross HBM (gather + scatter)
    expected_bps = 10 * row_bytes / 0.01
    assert rep["exchange_bytes_per_sec"] == pytest.approx(expected_bps)
    assert rep["bw_util"] == pytest.approx(
        expected_bps / profiling.HBM_PEAK_BYTES_PER_SEC
    )
    assert rep["kind"] == "redistribute"
    assert rep["stats"]["dropped_send"] == 0
    json.dumps(rep)  # the whole surface must be JSON-serializable


def test_exchange_report_hand_math_ici():
    stats = _stats_2rank()
    row_bytes = 28
    rep = exchange_report(
        stats, row_bytes, step_seconds=0.01, domain="ici", n_chips=2
    )
    # ICI wire carries only the moved rows, and the roof is per chip
    expected_bps = 3 * row_bytes / 0.01
    assert rep["exchange_bytes_per_sec"] == pytest.approx(expected_bps)
    roof = (
        profiling.ICI_LINK_BYTES_PER_SEC * profiling.ICI_LINKS_PER_CHIP
    )
    assert rep["bw_util"] == pytest.approx(expected_bps / 2 / roof)


def test_exchange_report_without_step_seconds():
    rep = exchange_report(_stats_2rank(), 28)
    assert rep["exchange_bytes_per_sec"] is None
    assert rep["bw_util"] is None
    assert rep["exchange_bytes_per_step"] == 280


def test_exchange_report_migrate_stats():
    S, R = 2, 4
    stats = MigrateStats(
        sent=np.full((S, R), 5, np.int32),
        received=np.full((S, R), 5, np.int32),
        population=np.full((S, R), 50, np.int32),
        backlog=np.zeros((S, R), np.int32),
        dropped_recv=np.zeros((S, R), np.int32),
    )
    rep = exchange_report(stats, 28, step_seconds=0.001)
    assert rep["kind"] == "migrate"
    # MigrateStats.sent counts movers exclusively: total == moved
    assert rep["exchange_bytes_per_step"] == 5 * R * 28
    assert rep["moved_bytes_per_step"] == rep["exchange_bytes_per_step"]


# ------------------------------------------------------- phase attribution


def test_attribute_phases_orders_and_rooflines():
    import jax
    import jax.numpy as jnp
    from jax import lax

    # phase tokens = number of extra multiply passes; cumulative time
    # must be returned per phase with deltas and roofline columns filled
    def loop_builder(phase, S):
        @jax.jit
        def loop(x):
            def body(c, _):
                for _i in range(phase):
                    c = c * 1.000001 + 1e-9
                return c, ()

            c, _ = lax.scan(body, x, None, length=S)
            return c

        return loop

    x = jnp.ones((64, 64), jnp.float32)
    pb = {1: 1000, 2: 2000}
    rows = attribute_phases(
        loop_builder, (x,), [1, 2], s1=2, s2=6, reps=1, phase_bytes=pb
    )
    assert [r.phase for r in rows] == [1, 2]
    assert rows[0].delta_s == rows[0].cumulative_s
    assert rows[1].delta_s == pytest.approx(
        rows[1].cumulative_s - rows[0].cumulative_s
    )
    assert rows[0].logical_bytes == 1000
    assert rows[0].roofline_s == pytest.approx(
        1000 / profiling.HBM_PEAK_BYTES_PER_SEC
    )
    table = format_phase_table(rows)
    assert table.splitlines()[0].startswith("| phase (cumulative)")
    assert len(table.splitlines()) == 2 + len(rows)
    assert "(first)" in table.splitlines()[2]


# ----------------------------------------------------------------- regress


def _capture(value=100.0, ms=10.0, xbps=1e8, wrap=False):
    line = {
        "metric": "particles_per_sec_per_chip",
        "value": value,
        "ms_per_step": ms,
        "exchange_bytes_per_sec": xbps,
    }
    if wrap:
        return {"n": 1, "cmd": "python bench.py", "rc": 0, "parsed": line}
    return line


def test_min_of_k_protocol():
    it = iter([3.0, 1.0, 2.0])
    d = min_of_k(lambda: next(it), k=3)
    assert d["min"] == 1.0 and d["max"] == 3.0
    assert d["spread"] == pytest.approx(2.0)
    assert d["k"] == 3 and len(d["values"]) == 3
    with pytest.raises(ValueError):
        min_of_k(lambda: 1.0, k=0)


def test_extract_metrics_handles_wrappers():
    assert extract_metrics(_capture())["value"] == 100.0
    assert extract_metrics(_capture(wrap=True))["ms_per_step"] == 10.0
    assert extract_metrics({"parsed": None}) is None
    assert extract_metrics({"tail": "crashed"}) is None


def test_check_capture_accepts_within_threshold():
    ok, lines = check_capture(
        _capture(value=95.0), [_capture(value=100.0), _capture(value=90.0)]
    )
    assert ok, lines
    assert any(ln.startswith("warn") for ln in lines)


def test_check_capture_rejects_regressions():
    # 20% throughput drop vs best
    ok, lines = check_capture(_capture(value=80.0), [_capture(value=100.0)])
    assert not ok
    assert any(ln.startswith("FAIL") and "value" in ln for ln in lines)
    # times regress UPWARD
    ok, lines = check_capture(_capture(ms=12.5), [_capture(ms=10.0)])
    assert not ok
    assert any("ms_per_step" in ln and ln.startswith("FAIL") for ln in lines)


def test_check_capture_compares_against_best_not_latest():
    # history drifted down; the gate must still hold the line at the best
    history = [_capture(value=100.0), _capture(value=92.0, wrap=True)]
    ok, _ = check_capture(_capture(value=88.0), history)
    assert not ok  # 12% below the 100.0 best, despite being ~4% below latest


def test_check_capture_skips_missing_metrics():
    cur = {"value": 100.0, "metric": "x"}  # no ms_per_step in current
    ok, lines = check_capture(cur, [_capture()])
    assert ok
    assert any(ln.startswith("skip") and "ms_per_step" in ln for ln in lines)


def test_regress_cli_on_fixture_files(tmp_path):
    from mpi_grid_redistribute_tpu.telemetry import regress

    good = tmp_path / "BENCH_r01.json"
    good.write_text(json.dumps(_capture(value=100.0, wrap=True)))
    bad = tmp_path / "current_bad.json"
    bad.write_text(json.dumps(_capture(value=70.0)))
    okc = tmp_path / "current_ok.json"
    okc.write_text(json.dumps(_capture(value=99.0)))

    hist = str(tmp_path / "BENCH_r*.json")
    assert regress.main(["--current", str(okc), "--history", hist]) == 0
    assert regress.main(["--current", str(bad), "--history", hist]) == 1
    assert regress.main(["--history", str(tmp_path / "nope*.json")]) == 2


def test_regress_cli_self_test_on_committed_history():
    # the acceptance gate: the repo's own committed history must pass
    from mpi_grid_redistribute_tpu.telemetry import regress

    assert regress.main([]) == 0
