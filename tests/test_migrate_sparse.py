"""Mover-sparse migrate fast path (ISSUE 4): bit-identity vs the planar
engine, routing guard behavior, jaxpr cost contract, telemetry.

The sparse engine is an *engine*, not a semantic: under the residence
guard it must reproduce the planar engine's output bit-for-bit (row sets
AND slot order AND stats counters — same grants, same vacated slots,
same stack), fall back to the dense step when the guard trips, and its
cond fast branch must contain no resident-scale op (no sort, no full-
array gather) — asserted structurally on the jaxpr, since a silent cost
regression would pass every correctness test.
"""

import numpy as np
import pytest

import jax

from mpi_grid_redistribute_tpu import api
from mpi_grid_redistribute_tpu import telemetry
from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.models import nbody
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.parallel import exchange
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

MESHES = [
    ((1, 1, 1), (2, 2, 2)),
    ((2, 2, 1), (1, 2, 2)),
    ((2, 1, 1), (2, 2, 1)),
]


def _drift_inputs(dev_shape, v_shape, n_local, rng, hole_frac=0.125):
    """Legal start state: live rows on the slab owning their position."""
    dev_grid = ProcessGrid(dev_shape)
    vgrid = ProcessGrid(v_shape)
    full = ProcessGrid(
        tuple(d * v for d, v in zip(dev_shape, v_shape))
    )
    n = full.nranks * n_local
    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.6 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    alive = rng.random(n) > hole_frac
    domain = Domain(0.0, 1.0, periodic=True)
    dest = binning.rank_of_position(pos, domain, full, xp=np)
    # device-major slab rank per slot (same construction as test_migrate)
    slab = []
    for d in range(dev_grid.nranks):
        dc = dev_grid.cell_of_rank(d)
        for v in range(vgrid.nranks):
            vc = vgrid.cell_of_rank(v)
            cell = tuple(
                dc[a] * v_shape[a] + vc[a] for a in range(len(dc))
            )
            slab.append(full.rank_of_cell(cell))
    slot_slab = np.repeat(np.asarray(slab), n_local)
    alive &= dest == slot_slab
    return domain, dev_grid, vgrid, pos, vel, alive


def _run(domain, dev_grid, vgrid, pos, vel, alive, *, engine,
         mover_cap=None, n_local, steps=5, dt=0.07):
    mesh = mesh_lib.make_mesh(dev_grid)
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=dt, capacity=n_local,
        n_local=n_local, engine=engine, mover_cap=mover_cap,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, steps, vgrid=vgrid)
    return jax.tree.map(np.asarray, loop(pos, vel, alive))


def _assert_bitexact(a, b):
    """pos/vel/alive/stats tuples equal to the BIT, slot order included."""
    pa, va, aa, sa = a
    pb, vb, ab, sb = b
    assert np.array_equal(pa.view(np.uint32), pb.view(np.uint32))
    assert np.array_equal(va.view(np.uint32), vb.view(np.uint32))
    assert np.array_equal(aa, ab)
    for name in ("sent", "received", "population", "backlog",
                 "dropped_recv", "flow"):
        assert np.array_equal(
            np.asarray(getattr(sa, name)), np.asarray(getattr(sb, name))
        ), name


@pytest.mark.parametrize("dev_shape,v_shape", MESHES)
def test_sparse_matches_planar_bitexact(dev_shape, v_shape, rng, _devices):
    n_local = 64
    domain, dev_grid, vgrid, pos, vel, alive = _drift_inputs(
        dev_shape, v_shape, n_local, rng
    )
    ref = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="planar", n_local=n_local)
    got = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="auto", n_local=n_local)
    _assert_bitexact(ref, got)
    assert ref[3].fast_path is None  # planar build carries no sparse path
    if dev_grid.nranks == 1:
        # single-device vranks: auto routes sparse, leaf is [S, V]
        fp = np.asarray(got[3].fast_path)
        assert fp.shape == (5, vgrid.nranks)
    else:
        # multi-device: auto resolves to planar, no sparse path at all
        assert got[3].fast_path is None


def test_sparse_zero_movers_takes_fast_path_every_step(rng, _devices):
    n_local = 64
    domain, dev_grid, vgrid, pos, vel, alive = _drift_inputs(
        (1, 1, 1), (2, 2, 2), n_local, rng
    )
    # dt=0: nothing ever leaves its slab — the degenerate sparse case
    ref = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="planar", n_local=n_local, dt=0.0)
    got = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="sparse", n_local=n_local, dt=0.0)
    _assert_bitexact(ref, got)
    assert np.asarray(got[3].sent).sum() == 0
    assert np.asarray(got[3].fast_path).all()


def test_sparse_full_swap_falls_back_bitexact(rng, _devices):
    """config7-stress shape: ~100% movers per step. The per-chunk
    candidate cap structurally cannot hold that, so every step must take
    the dense fallback — and stay bit-identical doing it."""
    n_local = 64
    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((2, 1, 1))
    n = 2 * n_local
    domain = Domain(0.0, 1.0, periodic=True)
    pos = rng.random((n, 3), dtype=np.float32)
    pos[:n_local, 0] = 0.75  # vrank 0's rows all in vrank 1's half
    pos[n_local:, 0] = 0.25
    vel = np.zeros((n, 3), dtype=np.float32)
    alive = np.ones(n, dtype=bool)
    ref = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="planar", n_local=n_local, steps=1, dt=0.0)
    got = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="sparse", mover_cap=8, n_local=n_local,
               steps=1, dt=0.0)
    _assert_bitexact(ref, got)
    assert np.asarray(got[3].sent).sum() == n  # everyone still moved
    assert not np.asarray(got[3].fast_path).any()


def test_static_infeasibility_runs_dense_with_zero_leaf(
    rng, _devices, monkeypatch
):
    """MPI_GRID_SELECT=flat disables the two-level selection the sparse
    engine is built from: the build must quietly run dense and keep the
    stats pytree uniform (fast_path present, all zeros) so stacked loops
    don't change structure with the env."""
    monkeypatch.setenv("MPI_GRID_SELECT", "flat")
    n_local = 64
    domain, dev_grid, vgrid, pos, vel, alive = _drift_inputs(
        (1, 1, 1), (2, 2, 2), n_local, rng
    )
    got = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="sparse", n_local=n_local)
    fp = np.asarray(got[3].fast_path)
    assert fp.shape == (5, vgrid.nranks)
    assert not fp.any()


def test_mover_capacity_growth_recovers_fast_path(rng, _devices):
    """Measured-need growth: an undersized mover_cap falls back (never
    errors), MoverCapacity folds the observed peak and ratchets, and the
    rebuilt loop routes sparse again — the same grow-on-measurement
    lifecycle the canonical engine runs on capacity."""
    n_local = 64
    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((2, 1, 1))
    n = 2 * n_local
    domain = Domain(0.0, 1.0, periodic=True)
    pos = rng.random((n, 3), dtype=np.float32)
    pos[:, 0] = pos[:, 0] * 0.5 + 0.5 * (np.arange(n) >= n_local)
    # exactly 6 movers: six vrank-0 rows sitting in vrank 1's half
    pos[:6, 0] = 0.75
    vel = np.zeros((n, 3), dtype=np.float32)
    alive = np.ones(n, dtype=bool)
    alive[n_local : n_local + 16] = False  # room to receive

    rec = telemetry.StepRecorder()
    mc = api.MoverCapacity(1, recorder=rec)
    out = _run(domain, dev_grid, vgrid, pos, vel, alive,
               engine="sparse", mover_cap=mc.value, n_local=n_local,
               steps=1, dt=0.0)
    assert not np.asarray(out[3].fast_path).any()  # undersized: fallback
    assert np.asarray(out[3].sent).sum() == 6  # dense still moved them
    grew = mc.update(out[3])
    assert grew and mc.value == 8  # next pow2 over the measured peak
    assert rec.counts().get("mover_cap_grow") == 1

    out2 = _run(domain, dev_grid, vgrid, pos, vel, alive,
                engine="sparse", mover_cap=mc.value, n_local=n_local,
                steps=1, dt=0.0)
    assert np.asarray(out2[3].fast_path).all()
    assert np.asarray(out2[3].sent).sum() == 6
    assert not mc.update(out2[3])  # converged: never shrinks, no thrash
    assert mc.value == 8


# ------------------------------------------------- jaxpr cost contract


# the jaxpr walk lives in the semantic analyzer now (progcheck's public
# API; rule J003 runs this same check over every registered program)
from mpi_grid_redistribute_tpu.analysis.progcheck import (  # noqa: E402
    dispatch_conds,
    has_primitive,
    walk_eqns,
)


def test_fast_branch_jaxpr_has_no_resident_scale_ops(rng, _devices):
    n_local = 64
    domain, dev_grid, vgrid, pos, vel, alive = _drift_inputs(
        (1, 1, 1), (2, 2, 2), n_local, rng
    )
    mesh = mesh_lib.make_mesh(dev_grid)
    mover_cap = 16
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.07, capacity=n_local,
        n_local=n_local, engine="sparse", mover_cap=mover_cap,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, 3, vgrid=vgrid)
    # trace with planar-flat (1-D) payloads: the loop host-packs numpy
    # rows but passes device/tracer arrays through untouched
    pos_p = nbody.rows_to_planar(pos, mesh.size)
    vel_p = nbody.rows_to_planar(vel, mesh.size)
    jaxpr = jax.make_jaxpr(loop)(pos_p, vel_p, alive).jaxpr

    # no host round-trips anywhere in the compiled step
    assert not any(
        "callback" in e.primitive.name for e in walk_eqns(jaxpr)
    )

    # the engine-dispatch cond is the one whose branches DISAGREE about
    # sorting: dense sorts residents, the fast branch must not sort at
    # all (the selection sorts live outside the cond, in the shared
    # prefix). Inner conds — two_level's flat fallback, the vacated-plan
    # guard — sort on both sides or on neither.
    dispatch = dispatch_conds(
        jaxpr, lambda b: has_primitive(b, "sort")
    )
    assert dispatch, "engine-dispatch cond not found in jaxpr"

    resident_elems = pos.shape[0]  # V * n rows
    for _, fast, _dense in dispatch:
        for e in walk_eqns(fast):
            assert e.primitive.name != "sort"
            if e.primitive.name == "gather":
                # every gather in the fast branch reads a mover-scale
                # block, never a resident-scale permutation
                out_rows = max(
                    int(np.prod(v.aval.shape[1:])) if v.aval.shape else 1
                    for v in e.outvars
                )
                assert out_rows < resident_elems, (
                    f"fast-branch gather produces {out_rows} rows "
                    f">= resident count {resident_elems}"
                )


# ------------------------------------------------------------ telemetry


def _sparse_stats(rng, _devices, steps=5):
    n_local = 64
    domain, dev_grid, vgrid, pos, vel, alive = _drift_inputs(
        (1, 1, 1), (2, 2, 2), n_local, rng
    )
    return _run(domain, dev_grid, vgrid, pos, vel, alive,
                engine="auto", n_local=n_local, steps=steps)[3]


def test_record_fast_path_and_report_hit_rate(rng, _devices):
    stats = _sparse_stats(rng, _devices)
    rec = telemetry.StepRecorder()
    n_ev = telemetry.record_fast_path_steps(rec, stats, mover_cap=1024)
    assert n_ev == 5 and rec.counts()["fast_path"] == 5
    ev = rec.events("fast_path")
    assert all(e.data["mover_cap"] == 1024 for e in ev)
    assert all(e.data["movers"] >= e.data["movers_max_rank"] for e in ev)
    hit = telemetry.fast_path_hit_rate(rec)
    assert hit == 1.0  # the drift workload is mover-sparse by design

    rep = telemetry.exchange_report(stats, 28)
    assert rep["fast_path_steps"] == 5
    assert rep["fast_path_hit_rate"] == 1.0

    # dense-only stats: no hit-rate key in the report, loud error from
    # the journal bridge (a silent 0% would misread as always-fallback)
    dense = stats._replace(fast_path=None)
    assert "fast_path_hit_rate" not in telemetry.exchange_report(dense, 28)
    with pytest.raises(ValueError, match="fast_path is None"):
        telemetry.record_fast_path_steps(rec, dense)


def test_fast_path_fallback_health_rule(rng, _devices):
    rec = telemetry.StepRecorder()
    mon = telemetry.HealthMonitor(rec)
    rule_names = {r.name for r in mon.rules}
    assert "fast_path_fallback" in rule_names  # stock rule set

    # under a full window: silent (a cold journal is not evidence)
    for s in range(8):
        rec.record("fast_path", step=s, taken=0, movers=50)
    assert mon.evaluate()["status"] == telemetry.health.OK

    for s in range(8, 16):
        rec.record("fast_path", step=s, taken=0, movers=50)
    verdict = mon.evaluate()
    assert verdict["status"] == "WARN"
    assert any(
        f["rule"] == "fast_path_fallback" for f in verdict["findings"]
    )

    # mostly-taken window: healthy
    rec2 = telemetry.StepRecorder()
    for s in range(16):
        rec2.record("fast_path", step=s, taken=int(s % 8 != 0), movers=3)
    assert telemetry.HealthMonitor(rec2).evaluate()["status"] == "OK"


# ------------------------------------------------------ engine dispatch


def test_resolve_engine_matrix():
    r = exchange.resolve_engine
    # migrate-loop (non-canonical) routing
    assert r("auto", vranks=True, n_devices=1) == "sparse"
    assert r("sparse", vranks=True, n_devices=1) == "sparse"
    assert r("auto", vranks=True, n_devices=8) == "planar"
    assert r("auto", vranks=False, n_devices=1) == "planar"
    assert r("planar", vranks=True, n_devices=1) == "planar"
    with pytest.raises(ValueError, match="canonical-exchange"):
        r("rowmajor", vranks=True, n_devices=1)
    with pytest.raises(ValueError, match="canonical-exchange"):
        r("neighbor", vranks=True, n_devices=1)
    # canonical-exchange routing (ISSUE 7): auto picks the count-driven
    # sparse wire on multi-device meshes, planar on one device (no wire
    # to shrink), rowmajor when the payload can't ride planar transport;
    # sparse/neighbor are honored as asked — the dense pool is reachable
    # only via explicit planar or the in-graph overflow fallback
    assert r("sparse", canonical=True) == "sparse"
    assert r("neighbor", canonical=True) == "neighbor"
    assert r("auto", canonical=True, planar_ok=True, n_devices=8) == "sparse"
    assert r("auto", canonical=True, planar_ok=True, n_devices=1) == "planar"
    assert r("auto", canonical=True, planar_ok=False) == "rowmajor"
    assert r("rowmajor", canonical=True) == "rowmajor"
    with pytest.raises(ValueError, match="engine must be one of"):
        r("warp", vranks=True, n_devices=1)


def test_mover_capacity_validation_and_clamp():
    with pytest.raises(ValueError, match=">= 1"):
        api.MoverCapacity(0)
    mc = api.MoverCapacity(5, max_cap=16)
    assert mc.value == 8  # pow2 bucketing, same as Redistributer
    stats = type("S", (), {})()
    stats.sent = np.asarray([100, 0])
    stats.backlog = np.asarray([3, 0])
    assert mc.update(stats) and mc.value == 16  # clamped at max_cap
    assert not mc.update(stats)  # at the clamp: no further growth
