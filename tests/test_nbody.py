import dataclasses

import jax
import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.models import nbody
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
from mpi_grid_redistribute_tpu import oracle

DOMAIN = Domain(0.0, 1.0, periodic=True)
GRID = ProcessGrid((2, 2, 2))


N_LOCAL = 200  # padded slots per shard
N_FILL = 150   # valid particles per shard; headroom absorbs imbalance


def _state(rng):
    R = GRID.nranks
    pos = rng.uniform(0, 1, size=(R * N_LOCAL, 3)).astype(np.float32)
    vel = rng.normal(scale=0.3, size=(R * N_LOCAL, 3)).astype(np.float32)
    # unique x-velocities let us match particles after redistribution
    vel[:, 0] = np.linspace(-0.5, 0.5, R * N_LOCAL, dtype=np.float32)
    count = np.full((R,), N_FILL, dtype=np.int32)
    return pos, vel, count


def _gather_valid(arrs, count, n_local):
    R = len(count)
    rows = [
        np.concatenate([np.asarray(a)[r * n_local : r * n_local + count[r]]
                        for r in range(R)])
        for a in arrs
    ]
    return rows


def _cfg(n_local, deposit_shape=None, capacity=None):
    return nbody.DriftConfig(
        domain=DOMAIN,
        grid=GRID,
        dt=0.05,
        capacity=capacity or n_local,
        n_local=n_local,
        deposit_shape=deposit_shape,
    )


def test_drift_step_moves_and_redistributes(rng):
    pos, vel, count = _state(rng)
    mesh = mesh_lib.make_mesh(GRID)
    step = nbody.make_drift_step(_cfg(N_LOCAL), mesh)
    p1, v1, c1, stats = step(pos, vel, count)
    c1 = np.asarray(c1)
    assert c1.sum() == count.sum()
    assert int(np.asarray(stats.dropped_send).sum()) == 0
    assert int(np.asarray(stats.dropped_recv).sum()) == 0
    # ownership after the step
    shards = [
        np.asarray(p1)[r * N_LOCAL : r * N_LOCAL + c1[r]] for r in range(8)
    ]
    oracle.assert_ownership(DOMAIN, GRID, shards)
    # each surviving particle moved by vel*dt (mod 1), matched via unique vx
    P0, V0 = _gather_valid([pos, vel], count, N_LOCAL)
    P1, V1 = _gather_valid([p1, v1], c1, N_LOCAL)
    o0, o1 = np.argsort(V0[:, 0]), np.argsort(V1[:, 0])
    np.testing.assert_array_equal(V0[o0], V1[o1])
    expect = (P0[o0] + V0[o0] * np.float32(0.05)) % 1.0
    np.testing.assert_allclose(P1[o1], expect, atol=1e-6)


def test_drift_loop_scan_matches_stepwise(rng):
    pos, vel, count = _state(rng)
    mesh = mesh_lib.make_mesh(GRID)
    cfg = _cfg(N_LOCAL)
    step = nbody.make_drift_step(cfg, mesh)
    loop = nbody.make_drift_loop(cfg, mesh, n_steps=4)
    p_l, v_l, c_l, stats = loop(pos, vel, count)
    p_s, v_s, c_s = pos, vel, count
    for _ in range(4):
        p_s, v_s, c_s, _st = step(p_s, v_s, c_s)
    np.testing.assert_array_equal(np.asarray(c_l), np.asarray(c_s))
    np.testing.assert_array_equal(np.asarray(p_l), np.asarray(p_s))
    np.testing.assert_array_equal(np.asarray(v_l), np.asarray(v_s))
    assert np.asarray(stats.send_counts).shape[0] == 4  # stacked per step
    assert int(np.asarray(c_l).sum()) == count.sum()


def test_drift_loop_with_deposit(rng):
    from tests.test_deposit import cic_numpy

    pos, vel, count = _state(rng)
    mesh = mesh_lib.make_mesh(GRID)
    cfg = _cfg(N_LOCAL, deposit_shape=(8, 8, 8))
    loop = nbody.make_drift_loop(cfg, mesh, n_steps=2)
    p, v, c, stats, rho = loop(pos, vel, count)
    rho = np.asarray(rho)
    assert rho.shape == (8, 8, 8)
    np.testing.assert_allclose(rho.sum(), count.sum(), rtol=1e-5)
    # density equals a fresh CIC of the final particle state
    c = np.asarray(c)
    P, = _gather_valid([p], c, N_LOCAL)
    expected = cic_numpy(P, np.ones(len(P)), (8, 8, 8), DOMAIN)
    np.testing.assert_allclose(rho, expected, rtol=2e-4, atol=1e-4)
