"""Hierarchical two-level exchange (ISSUE 19): bit-identity vs the
planar oracle across pod decompositions, routing + degradation reasons,
cross-stage wire structure, and the S004 DCN-ratio gate.

The two-level engine is an *engine*, not semantics: intra-pod rows ride
the 3x3x3 neighbor ``ppermute`` schedule, boundary-crossing rows ride
one condensed per-destination-pod block over a staged DCN hop plus an
intra-pod fanout — and the result must be byte-identical to the dense
planar exchange on every decomposition. What makes it worth having is
structural (the DCN domain carries mover-count-driven bytes, never the
dense fan-out), so that is asserted structurally on the jaxpr.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu import api, telemetry
from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.parallel import exchange
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib


def _inputs(shape, n_local, drift, rng, K=7):
    """Shard-local particles plus a gaussian drift ([R, K, n] layout)."""
    grid = ProcessGrid(shape=shape)
    R = grid.nranks
    pos = np.empty((R, 3, n_local), np.float32)
    for r in range(R):
        cell = grid.cell_of_rank(r)
        for a in range(3):
            w = 1.0 / shape[a]
            pos[r, a] = (cell[a] + rng.random(n_local)) * w
    pos = pos + rng.normal(0, drift, size=pos.shape).astype(np.float32)
    pos = np.mod(pos, 1.0).astype(np.float32)
    other = rng.standard_normal((R, K - 3, n_local)).astype(np.float32)
    fused = np.concatenate([pos, other], axis=1)
    count = rng.integers(
        n_local // 2, n_local + 1, size=R
    ).astype(np.int32)
    return grid, fused, count


# (grid shape, dcn split) — both sharded cases split the 8-rank grid
# into pods, including the non-cubic (1, 2, 2) and (2, 1, 1) pod shapes
SHARDED_CASES = [
    ((2, 2, 2), (2, 1, 1)),  # 2 pods of (1, 2, 2)
    ((2, 2, 2), (1, 2, 2)),  # 4 pods of (2, 1, 1)
]


@pytest.mark.parametrize(
    "shape,dcn", SHARDED_CASES, ids=["2pods-122", "4pods-211"]
)
def test_hierarchical_matches_planar_bitexact_sharded(
    shape, dcn, rng, _devices
):
    grid, fused, count = _inputs(shape, 120, 0.01, rng)
    R = grid.nranks
    domain = Domain(lo=(0.0,) * 3, hi=(1.0,) * 3, periodic=(True,) * 3)
    hier = mesh_lib.HierarchicalMesh(grid, dcn)
    cap, out_cap, B, B2 = 60, 300, 16, 16
    K = fused.shape[1]
    fused_g = jnp.asarray(
        np.transpose(fused, (1, 0, 2)).reshape(K, R * 120)
    )
    count_g = jnp.asarray(count)
    mesh = mesh_lib.make_mesh(grid, jax.devices()[:R])
    ref = exchange.build_redistribute_planar(
        mesh, domain, grid, cap, out_cap, 3
    )
    out_p, cnt_p, st_p = ref(fused_g, count_g)
    emesh = hier.build_mesh(list(jax.devices()[:R]))
    f = exchange.shard_redistribute_hierarchical_sharded(
        emesh, domain, grid, hier, cap, out_cap, B, B2, 3
    )
    out_h, cnt_h, st_h = jax.jit(f)(fused_g, count_g)
    assert np.asarray(out_h).tobytes() == np.asarray(out_p).tobytes()
    assert np.array_equal(np.asarray(cnt_h), np.asarray(cnt_p))
    for name in ("send_counts", "recv_counts", "dropped_send",
                 "dropped_recv", "needed_capacity"):
        assert np.array_equal(
            np.asarray(getattr(st_h, name)),
            np.asarray(getattr(st_p, name)),
        ), name
    assert not np.asarray(st_h.fallback).any()
    assert int(np.asarray(st_h.needed_cross).max()) <= B2

    # vrank twin on the same decomposition: byte-equal to the planar
    # vrank twin AND to the sharded global result
    fused_v = jnp.asarray(fused)
    ref_v = exchange.build_redistribute_planar_vranks(
        domain, grid, cap, out_cap, 3
    )
    out_pv, cnt_pv, _ = ref_v(fused_v, count_g)
    fv = jax.jit(
        exchange.vrank_redistribute_hierarchical_fn(
            domain, grid, hier, cap, out_cap, B, B2, 3
        )
    )
    out_hv, cnt_hv, _ = fv(fused_v, count_g)
    assert np.asarray(out_hv).tobytes() == np.asarray(out_pv).tobytes()
    assert np.array_equal(np.asarray(cnt_hv), np.asarray(cnt_pv))
    out_g = np.transpose(np.asarray(out_hv), (1, 0, 2)).reshape(
        K, R * out_cap
    )
    assert out_g.tobytes() == np.asarray(out_p).tobytes()


@pytest.mark.parametrize(
    "shape,dcn",
    [((2, 2, 4), (1, 1, 2)), ((3, 3, 3), (3, 1, 1))],
    ids=["16vr-cubic-pod", "27vr-133-pod"],
)
def test_hierarchical_matches_planar_bitexact_vranks(shape, dcn, rng):
    # more ranks than devices: the single-device vrank build, including
    # a cubic (2, 2, 2) pod and the 27-rank non-pow2 grid
    grid, fused, count = _inputs(shape, 48, 0.01, rng)
    domain = Domain(lo=(0.0,) * 3, hi=(1.0,) * 3, periodic=(True,) * 3)
    hier = mesh_lib.HierarchicalMesh(grid, dcn)
    cap, out_cap, B, B2 = 32, 128, 8, 8
    fused_v = jnp.asarray(fused)
    count_g = jnp.asarray(count)
    ref_v = exchange.build_redistribute_planar_vranks(
        domain, grid, cap, out_cap, 3
    )
    out_p, cnt_p, _ = ref_v(fused_v, count_g)
    fv = jax.jit(
        exchange.vrank_redistribute_hierarchical_fn(
            domain, grid, hier, cap, out_cap, B, B2, 3
        )
    )
    out_h, cnt_h, st = fv(fused_v, count_g)
    assert np.asarray(out_h).tobytes() == np.asarray(out_p).tobytes()
    assert np.array_equal(np.asarray(cnt_h), np.asarray(cnt_p))
    assert not np.asarray(st.dropped_send).any()


# ------------------------------------------------------- wire structure

from mpi_grid_redistribute_tpu.analysis.progcheck import (  # noqa: E402
    walk_eqns,
)
from mpi_grid_redistribute_tpu.analysis.shardcheck import (  # noqa: E402
    COLLECTIVE_PRIMS,
    collective_axes,
)


def test_cross_pod_stage_has_no_dense_all_to_all(_devices):
    """Every collective crossing a ``dcn_*`` axis is either a counts
    exchange (all_to_all at counts scale) or the staged condensed-block
    ``ppermute`` hop — never a payload-width all_to_all: the dense
    fan-out must stay inside the pod."""
    grid = ProcessGrid((2, 2, 2))
    hier = mesh_lib.HierarchicalMesh(grid, (2, 1, 1))
    domain = Domain(lo=(0.0,) * 3, hi=(1.0,) * 3, periodic=(True,) * 3)
    R, cap, B, B2, K = 8, 64, 8, 8, 7
    emesh = hier.build_mesh(list(jax.devices()[:R]))
    f = exchange.shard_redistribute_hierarchical_sharded(
        emesh, domain, grid, hier, cap, 256, B, B2, 3
    )
    jaxpr = jax.make_jaxpr(f)(
        jnp.zeros((K, R * cap), jnp.float32),
        jnp.zeros((R,), jnp.int32),
    ).jaxpr
    dcn_ppermutes = 0
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name not in COLLECTIVE_PRIMS:
            continue
        axes = collective_axes(eqn)
        if not any(a.startswith("dcn_") for a in axes):
            continue
        width = max(
            int(np.prod(v.aval.shape)) for v in eqn.invars
        )
        if eqn.primitive.name == "ppermute":
            # the staged hop ships the condensed per-destination-pod
            # block: (P-1) blocks of B2 columns, K rows per shard
            assert width <= K * (hier.n_pods - 1) * B2, (
                f"DCN ppermute wider than the condensed block: {width}"
            )
            dcn_ppermutes += 1
        else:
            # counts-scale only ([P, L] exchanges, scalar reductions) —
            # the dense pool is R * cap * K wide and must never cross
            # DCN; in particular no payload all_to_all
            assert width <= R * R, (
                f"payload {eqn.primitive.name} crosses DCN: "
                f"{width} elements"
            )
    assert dcn_ppermutes > 0, "staged DCN hop not found in the jaxpr"


# ---------------------------------------------------------- API routing


def _mk_rows(grid, n_local, drift, rng):
    R = grid.nranks
    pos = np.empty((R * n_local, 3), np.float32)
    for r in range(R):
        cell = grid.cell_of_rank(r)
        for a in range(3):
            w = 1.0 / grid.shape[a]
            pos[r * n_local:(r + 1) * n_local, a] = (
                cell[a] + rng.random(n_local)
            ) * w
    pos = np.mod(pos + rng.normal(0, drift, pos.shape), 1.0).astype(
        np.float32
    )
    return pos, np.arange(R * n_local, dtype=np.int32)


def _rd(shape, engine, **kw):
    return api.GridRedistribute(
        grid=shape, lo=(0.0,) * 3, hi=(1.0,) * 3,
        periodic=(True,) * 3, engine=engine, **kw
    )


def _valid_rows(res, R):
    """Per-rank valid row prefixes (robust to out_capacity deltas)."""
    cnt = np.asarray(res.count)
    pos = np.asarray(res.positions)
    out_cap = pos.shape[0] // R
    return [
        pos[r * out_cap: r * out_cap + int(cnt[r])] for r in range(R)
    ]


def test_api_hierarchical_bitexact_and_reports_domains(rng, _devices):
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.02, rng)
    rd_h = _rd((2, 2, 2), "hierarchical", dcn_shape=(2, 1, 1),
               capacity=96, out_capacity=256)
    rd_p = _rd((2, 2, 2), "planar", capacity=96, out_capacity=256)
    res_h = rd_h.redistribute(pos, ids)
    res_p = rd_p.redistribute(pos, ids)
    assert np.asarray(res_h.positions).tobytes() == np.asarray(
        res_p.positions
    ).tobytes()
    assert np.array_equal(
        np.asarray(res_h.count), np.asarray(res_p.count)
    )
    ev = [e for e in rd_h.telemetry.events()
          if e.kind == "engine_resolved"]
    assert ev[0].data["resolved"] == "hierarchical"
    assert ev[0].data["reason"] == "explicit hierarchical two-level wire"
    rep = rd_h.report()
    assert rep["engine"] == "hierarchical"
    assert rep["dcn_bytes_per_step"] > 0
    assert rep["ici_bytes_per_step"] > 0
    # the whole point: the DCN domain carries a sliver of the schedule
    assert rep["dcn_bytes_per_step"] < rep["ici_bytes_per_step"]
    assert (
        rep["wire_bytes_per_step"]
        == rep["dcn_bytes_per_step"] + rep["ici_bytes_per_step"]
    )
    assert rep["wire_bytes_per_step"] < rep["dense_wire_bytes_per_step"]
    # runtime link reports stay consistent with the planar oracle's
    flow_h = rd_h.flow()
    flow_p = rd_p.flow()
    assert np.array_equal(
        np.asarray(flow_h["matrix"]), np.asarray(flow_p["matrix"])
    )


def test_api_auto_routes_hierarchical_on_multipod(rng, _devices):
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.02, rng)
    rd_a = _rd((2, 2, 2), "auto", dcn_shape=(1, 2, 2))
    rd_p = _rd((2, 2, 2), "planar")
    res_a = rd_a.redistribute(pos, ids)
    res_p = rd_p.redistribute(pos, ids)
    for a, b in zip(_valid_rows(res_a, 8), _valid_rows(res_p, 8)):
        assert a.tobytes() == b.tobytes()
    ev = [e for e in rd_a.telemetry.events()
          if e.kind == "engine_resolved"]
    assert ev[0].data["resolved"] == "hierarchical"
    assert ev[0].data["reason"] == (
        "auto: multi-pod mesh -> hierarchical two-level wire"
    )


@pytest.mark.parametrize("dcn", [None, (1, 1, 1)], ids=["none", "ones"])
def test_api_hierarchical_flat_mesh_degrades_to_sparse(
    dcn, rng, _devices
):
    # a flat mesh (no dcn domains) must degrade to the count-driven
    # sparse engine with the journaled reason — never error
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.02, rng)
    kw = {} if dcn is None else {"dcn_shape": dcn}
    rd = _rd((2, 2, 2), "hierarchical", **kw)
    rd_p = _rd((2, 2, 2), "planar")
    res = rd.redistribute(pos, ids)
    res_p = rd_p.redistribute(pos, ids)
    for a, b in zip(_valid_rows(res, 8), _valid_rows(res_p, 8)):
        assert a.tobytes() == b.tobytes()
    ev = [e for e in rd.telemetry.events()
          if e.kind == "engine_resolved"]
    assert ev[0].data["resolved"] == "sparse"
    assert ev[0].data["reason"] == (
        "hierarchical -> sparse: flat mesh (no dcn domains)"
    )
    assert rd.report()["engine"] == "sparse"


def test_api_hierarchical_vranks_bitexact(rng, _devices):
    # 16 ranks > 8 devices: the vmapped vrank build of the two-level
    # engine, explicit opt-in, bit-identical to planar
    grid = ProcessGrid((2, 2, 4))
    pos, ids = _mk_rows(grid, 40, 0.01, rng)
    rd_h = _rd((2, 2, 4), "hierarchical", dcn_shape=(1, 1, 2),
               capacity=40, out_capacity=120)
    rd_p = _rd((2, 2, 4), "planar", capacity=40, out_capacity=120)
    res_h = rd_h.redistribute(pos, ids)
    res_p = rd_p.redistribute(pos, ids)
    assert np.asarray(res_h.positions).tobytes() == np.asarray(
        res_p.positions
    ).tobytes()
    assert rd_h.report()["engine"] == "hierarchical"


def test_api_cross_cap_ratchets_from_measured_need(rng, _devices):
    # cross_cap=1 + real cross-pod movers: the staged block clips, the
    # retry loop ratchets the cap from stats.needed_cross (journaled as
    # cross_cap_grow) and the healed result matches the planar oracle
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.05, rng)
    rd = _rd((2, 2, 2), "hierarchical", dcn_shape=(2, 1, 1),
             cross_cap=1, capacity=96)
    rd_p = _rd((2, 2, 2), "planar", capacity=96)
    res = rd.redistribute(pos, ids)
    res_p = rd_p.redistribute(pos, ids)
    for a, b in zip(_valid_rows(res, 8), _valid_rows(res_p, 8)):
        assert a.tobytes() == b.tobytes()
    assert rd._cross_cap > 1
    grow = [e for e in rd.telemetry.events()
            if e.kind == "cross_cap_grow"]
    assert grow and grow[-1].data["new"] == rd._cross_cap
    assert grow[-1].data["peak_cross"] >= grow[-1].data["old"]


def test_resolve_two_phase_degrades_on_multipod():
    rec = telemetry.StepRecorder()
    two = exchange.resolve_two_phase(
        "auto", chunk=4, planar_ok=True, ragged=False, vranks=True,
        n_devices=1, n_pods=2, recorder=rec,
    )
    assert not two.armed
    ev = [e for e in rec.events() if e.kind == "engine_resolved"]
    assert ev[0].data["resolved"] == "sequential"
    assert ev[0].data["reason"] == (
        "pipeline: hierarchical multi-pod topology — sequential body"
    )


# --------------------------------------------------- S004 DCN-ratio gate


def test_check_dcn_ratio_gate():
    from mpi_grid_redistribute_tpu.analysis import rules_shard

    def wires(hier_dcn, flat_dcn):
        return {
            "canonical_hierarchical_sharded": {
                "per_domain": {"dcn": hier_dcn, "ici": 100},
            },
            "canonical_sparse_pods": {
                "per_domain": {"dcn": flat_dcn, "ici": 0},
            },
        }

    # within the gate: silent
    assert rules_shard.check_dcn_ratio(wires(15, 100)) == []
    # over the gate: one S004 finding naming both programs' bytes
    out = rules_shard.check_dcn_ratio(wires(16, 100))
    assert len(out) == 1 and out[0].rule == "S004"
    assert "16" in out[0].message and "15%" in out[0].message
    # vacuous denominator: loud, not silent
    out = rules_shard.check_dcn_ratio(wires(0, 0))
    assert len(out) == 1 and "vacuous" in out[0].message
    # --programs subset without either side: skipped
    assert rules_shard.check_dcn_ratio({"other": {}}) == []


def test_committed_baseline_holds_the_dcn_ratio():
    """The acceptance criterion itself, against the committed baseline:
    hierarchical DCN bytes <= 15% of the flat sparse engine's cross-pod
    bytes, as gated by ``make shardcheck``."""
    from mpi_grid_redistribute_tpu.analysis import rules_shard
    from mpi_grid_redistribute_tpu.analysis.baseline import (
        load_wire_baseline,
        progprofile_baseline_path,
    )

    wires = load_wire_baseline(progprofile_baseline_path())
    assert "canonical_hierarchical_sharded" in wires
    assert "canonical_sparse_pods" in wires
    assert rules_shard.check_dcn_ratio(wires) == []
    hier = wires["canonical_hierarchical_sharded"]["per_domain"]["dcn"]
    flat = wires["canonical_sparse_pods"]["per_domain"]["dcn"]
    assert 0 < hier <= 0.15 * flat
