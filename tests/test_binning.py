import jax.numpy as jnp
import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning

DOMAIN = Domain((0.0, 0.0, 0.0), (1.0, 2.0, 4.0))
GRID = ProcessGrid((2, 2, 2))


def test_cell_of_position_jax_numpy_agree(rng):
    pos = rng.uniform(0, 1, size=(5000, 3)).astype(np.float32) * np.array(
        [1.0, 2.0, 4.0], dtype=np.float32
    )
    c_np = binning.cell_of_position(pos, DOMAIN, GRID, xp=np)
    c_jx = binning.cell_of_position(jnp.asarray(pos), DOMAIN, GRID)
    np.testing.assert_array_equal(c_np, np.asarray(c_jx))


def test_edges_clamp_into_grid():
    pos = np.array(
        [
            [0.0, 0.0, 0.0],
            [1.0, 2.0, 4.0],       # exactly hi -> last cell
            [-0.1, 2.5, 4.0001],   # outside, non-periodic -> clamped
        ],
        dtype=np.float32,
    )
    c = binning.cell_of_position(pos, DOMAIN, GRID, xp=np)
    assert c.min() >= 0 and (c < np.array(GRID.shape)).all()
    np.testing.assert_array_equal(c[1], [1, 1, 1])
    np.testing.assert_array_equal(c[2], [0, 1, 1])


def test_periodic_wrap():
    dom = Domain((0.0, 0.0, 0.0), (1.0, 1.0, 1.0), periodic=True)
    pos = np.array([[1.25, -0.25, 3.5]], dtype=np.float32)
    w = binning.wrap_periodic(pos, dom, xp=np)
    np.testing.assert_allclose(w, [[0.25, 0.75, 0.5]], atol=1e-6)
    # mixed: only axis 0 periodic
    dom2 = Domain((0.0, 0.0, 0.0), (1.0, 1.0, 1.0), periodic=(True, False, False))
    w2 = binning.wrap_periodic(pos, dom2, xp=np)
    np.testing.assert_allclose(w2, [[0.25, -0.25, 3.5]], atol=1e-6)


def test_periodic_wrap_tiny_negative_float32():
    dom = Domain(0.0, 1.0, periodic=True)
    pos = np.full((1, 3), -1e-9, dtype=np.float32)
    w = binning.wrap_periodic(pos, dom, xp=np)
    assert (w < 1.0).all() and (w >= 0.0).all()
    c = binning.cell_of_position(w, dom, ProcessGrid((2, 2, 2)), xp=np)
    assert (c >= 0).all() and (c <= 1).all()


def test_rank_of_position_rowmajor():
    pos = np.array([[0.9, 1.9, 3.9]], dtype=np.float32)  # cell (1,1,1)
    r = binning.rank_of_position(pos, DOMAIN, GRID, xp=np)
    assert r[0] == 7


def test_dest_histogram_matches_numpy(rng):
    R = GRID.nranks
    dest = rng.integers(0, R + 1, size=1000).astype(np.int32)  # incl sentinel
    h_jx = binning.dest_histogram(jnp.asarray(dest), R)
    h_np = binning.dest_histogram_np(dest, R)
    np.testing.assert_array_equal(np.asarray(h_jx), h_np)
    assert h_np.sum() == (dest < R).sum()


def test_dest_histogram_valid_mask():
    dest = np.array([0, 0, 1, 1, 1], dtype=np.int32)
    valid = np.array([True, False, True, True, False])
    h = binning.dest_histogram(jnp.asarray(dest), 2, valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(h), [1, 2])


def test_remainder_fast_bit_equal_pow2():
    """The reciprocal-multiply fast path is bit-identical to remainder for
    power-of-two extents (the exactness condition it gates on)."""
    from mpi_grid_redistribute_tpu.ops import binning
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    q = (rng.standard_normal(200_000) * 4).astype(np.float32)
    for ext in (1.0, 0.5, 2.0, 0.25):
        a = np.asarray(binning.remainder_fast(jnp.asarray(q), ext))
        b = np.asarray(jnp.remainder(jnp.asarray(q), jnp.float32(ext)))
        np.testing.assert_array_equal(a.view(np.uint32), b.view(np.uint32))
        # numpy twin too (oracle bit-compat)
        an = binning.remainder_fast(q, ext, xp=np)
        bn = np.remainder(q, np.float32(ext))
        np.testing.assert_array_equal(
            an.view(np.uint32), bn.view(np.uint32)
        )
    # non-pow2 falls back to remainder exactly
    a = np.asarray(binning.remainder_fast(jnp.asarray(q), 0.3))
    b = np.asarray(jnp.remainder(jnp.asarray(q), jnp.float32(0.3)))
    np.testing.assert_array_equal(a.view(np.uint32), b.view(np.uint32))


def test_wrap_periodic_pow2_path_matches_oracle():
    """wrap_periodic's vectorized pow2 fast path == numpy remainder path
    bit-for-bit (both backends share this function; drift loops depend on
    the bit-compat)."""
    from mpi_grid_redistribute_tpu.domain import Domain
    from mpi_grid_redistribute_tpu.ops import binning
    import jax.numpy as jnp

    rng = np.random.default_rng(1)
    pos = (rng.standard_normal((50_000, 3)) * 3).astype(np.float32)
    dom = Domain((0.0, -1.0, 0.5), (1.0, 1.0, 4.5), periodic=True)
    # extents (1.0, 2.0, 4.0): all pow2 -> fast path
    a = np.asarray(binning.wrap_periodic(jnp.asarray(pos), dom))
    b = binning.wrap_periodic(pos, dom, xp=np)
    np.testing.assert_array_equal(a.view(np.uint32), b.view(np.uint32))
    lo = np.asarray(dom.lo); hi = np.asarray(dom.hi)
    assert (a >= lo).all() and (a < hi).all()
    # non-pow2 extent: falls back, still matched between backends
    dom2 = Domain(0.0, 0.3, periodic=True)
    a2 = np.asarray(binning.wrap_periodic(jnp.asarray(pos), dom2))
    b2 = binning.wrap_periodic(pos, dom2, xp=np)
    np.testing.assert_array_equal(a2.view(np.uint32), b2.view(np.uint32))


def test_remainder_fast_extreme_inputs_match_numpy_twin():
    """Tiny (denormal-product) and huge (inf-product) inputs: the jnp and
    np twins of the fast path stay bit-equal and in [0, ext) after the
    callers' fold (the TPU-FTZ divergence is closed by the r<0 fold —
    reviewed round 3; CPU cannot reproduce FTZ, so this pins the
    algebraic invariant and twin equality, and the on-chip bit-equality
    is covered by config1's oracle check)."""
    from mpi_grid_redistribute_tpu.ops import binning
    import jax.numpy as jnp

    q = np.array(
        [-1e-36, 1e-36, -3.2e38, 3.2e38, -0.5, 0.0, 1023.9], np.float32
    )
    for ext in (1024.0, 0.25, 1.0):
        a = np.asarray(binning.remainder_fast(jnp.asarray(q), ext))
        b = binning.remainder_fast(q, ext, xp=np)
        np.testing.assert_array_equal(a.view(np.uint32), b.view(np.uint32))
        # the fast path is total: result GUARANTEED in [0, ext)
        assert np.isfinite(a).all()
        assert (a >= 0).all() and (a < ext).all()


def test_wrap_periodic_mixed_nonpow2_nonperiodic_axis():
    """A non-pow2 extent on a NON-periodic axis must not disable the fast
    path or corrupt the passthrough (reviewed round 3)."""
    from mpi_grid_redistribute_tpu.domain import Domain
    from mpi_grid_redistribute_tpu.ops import binning
    import jax.numpy as jnp

    rng = np.random.default_rng(2)
    pos = (rng.standard_normal((10_000, 3)) * 2).astype(np.float32)
    dom = Domain((0.0, 0.0, 0.0), (1.0, 0.3, 2.0),
                 periodic=(True, False, True))
    a = np.asarray(binning.wrap_periodic(jnp.asarray(pos), dom))
    b = binning.wrap_periodic(pos, dom, xp=np)
    np.testing.assert_array_equal(a.view(np.uint32), b.view(np.uint32))
    # non-periodic axis passes through untouched
    np.testing.assert_array_equal(a[:, 1], pos[:, 1])
    # periodic axes wrapped into range
    assert (a[:, 0] >= 0).all() and (a[:, 0] < 1.0).all()
    assert (a[:, 2] >= 0).all() and (a[:, 2] < 2.0).all()


def test_bounds_dense_matches_searchsorted():
    """The scatter-free dense searchsorted (two single-operand sorts) is
    exact-int identical to jnp.searchsorted across segment shapes: empty
    segments, duplicate runs, sentinel tails, strided edges, empty keys."""
    import jax.numpy as jnp
    from mpi_grid_redistribute_tpu.ops import binning

    rng = np.random.default_rng(5)
    cases = []
    for n, s in [(10_000, 257), (4096, 1), (5000, 4096), (1, 7), (513, 16)]:
        keys = np.sort(rng.integers(0, s, size=n)).astype(np.int32)
        cases.append((keys, s, 1, s))
    # sentinel tail (invalid rows keyed past every edge)
    keys = np.sort(
        np.concatenate([rng.integers(0, 100, 900), np.full(100, 100)])
    ).astype(np.int32)
    cases.append((keys, 101, 1, 100))
    # all-sentinel
    cases.append((np.full(64, 50, np.int32), 51, 1, 50))
    # strided edges (the pallas starts pattern)
    keys = np.sort(rng.integers(0, 8192, size=20_000)).astype(np.int32)
    cases.append((keys, 8192 // 512 + 1, 512, 8192))
    for keys, n_edges, stride, key_bound in cases:
        got = np.asarray(
            binning.bounds_dense(
                jnp.asarray(keys), n_edges, stride=stride,
                key_bound=key_bound,
            )
        )
        want = np.searchsorted(
            keys, np.arange(n_edges, dtype=np.int64) * stride, side="left"
        ).astype(np.int32)
        np.testing.assert_array_equal(got, want)
    # int32-overflow guard falls back to jnp.searchsorted, still exact
    keys = np.sort(rng.integers(0, 2**30, size=1000)).astype(np.int32)
    got = np.asarray(
        binning.bounds_dense(
            jnp.asarray(keys), 100, stride=2**24, key_bound=2**30
        )
    )
    want = np.searchsorted(
        keys, np.arange(100, dtype=np.int64) * 2**24, side="left"
    ).astype(np.int32)
    np.testing.assert_array_equal(got, want)


def _select_ref(dest, n_dest):
    import jax

    return jax.vmap(
        lambda k: binning.sorted_dest_counts(k, n_dest)
    )(jnp.asarray(dest))


@pytest.mark.parametrize(
    "n,chunk,cap,frac",
    [
        (8192, 512, 64, 0.02),   # fast path, several chunks
        (8192, 512, 64, 0.5),    # guard violated -> cond fallback
        (5000, 512, 64, 0.02),   # n not a chunk multiple (padding)
        (300, 512, 64, 0.1),     # n < chunk (single padded chunk)
        (4096, 512, 8, 0.05),    # tight cap: fallback on unlucky chunks
    ],
)
def test_sorted_dest_counts_batched_matches_flat(rng, n, chunk, cap, frac):
    V, R = 5, 23
    dest = np.full((V, n), R, np.int32)
    m = rng.random((V, n)) < frac
    dest[m] = rng.integers(0, R, size=int(m.sum()), dtype=np.int32)
    o2, c2, b2 = binning.sorted_dest_counts_batched(
        jnp.asarray(dest), R, chunk=chunk, cap=cap
    )
    o1, c1, b1 = _select_ref(dest, R)
    np.testing.assert_array_equal(np.asarray(c1), np.asarray(c2))
    np.testing.assert_array_equal(np.asarray(b1), np.asarray(b2))
    # the consumed contract: the leaver prefix is bit-identical
    nl = np.asarray(c1).sum(axis=1)
    for v in range(V):
        np.testing.assert_array_equal(
            np.asarray(o1)[v, : nl[v]], np.asarray(o2)[v, : nl[v]]
        )


def test_sorted_dest_counts_batched_static_fallbacks(rng, monkeypatch):
    V, n, R = 3, 1024, 7
    dest = np.full((V, n), R, np.int32)
    dest[:, ::97] = 3
    want = [np.asarray(a) for a in _select_ref(dest, R)]
    # env escape hatch forces the flat engine (A/B hook)
    monkeypatch.setenv("MPI_GRID_SELECT", "flat")
    got = binning.sorted_dest_counts_batched(jnp.asarray(dest), R)
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, np.asarray(g))
    monkeypatch.delenv("MPI_GRID_SELECT")
    # non-power-of-two chunk: static flat fallback, full equality
    got = binning.sorted_dest_counts_batched(
        jnp.asarray(dest), R, chunk=500, cap=50
    )
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, np.asarray(g))
