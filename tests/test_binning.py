import jax.numpy as jnp
import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning

DOMAIN = Domain((0.0, 0.0, 0.0), (1.0, 2.0, 4.0))
GRID = ProcessGrid((2, 2, 2))


def test_cell_of_position_jax_numpy_agree(rng):
    pos = rng.uniform(0, 1, size=(5000, 3)).astype(np.float32) * np.array(
        [1.0, 2.0, 4.0], dtype=np.float32
    )
    c_np = binning.cell_of_position(pos, DOMAIN, GRID, xp=np)
    c_jx = binning.cell_of_position(jnp.asarray(pos), DOMAIN, GRID)
    np.testing.assert_array_equal(c_np, np.asarray(c_jx))


def test_edges_clamp_into_grid():
    pos = np.array(
        [
            [0.0, 0.0, 0.0],
            [1.0, 2.0, 4.0],       # exactly hi -> last cell
            [-0.1, 2.5, 4.0001],   # outside, non-periodic -> clamped
        ],
        dtype=np.float32,
    )
    c = binning.cell_of_position(pos, DOMAIN, GRID, xp=np)
    assert c.min() >= 0 and (c < np.array(GRID.shape)).all()
    np.testing.assert_array_equal(c[1], [1, 1, 1])
    np.testing.assert_array_equal(c[2], [0, 1, 1])


def test_periodic_wrap():
    dom = Domain((0.0, 0.0, 0.0), (1.0, 1.0, 1.0), periodic=True)
    pos = np.array([[1.25, -0.25, 3.5]], dtype=np.float32)
    w = binning.wrap_periodic(pos, dom, xp=np)
    np.testing.assert_allclose(w, [[0.25, 0.75, 0.5]], atol=1e-6)
    # mixed: only axis 0 periodic
    dom2 = Domain((0.0, 0.0, 0.0), (1.0, 1.0, 1.0), periodic=(True, False, False))
    w2 = binning.wrap_periodic(pos, dom2, xp=np)
    np.testing.assert_allclose(w2, [[0.25, -0.25, 3.5]], atol=1e-6)


def test_periodic_wrap_tiny_negative_float32():
    dom = Domain(0.0, 1.0, periodic=True)
    pos = np.full((1, 3), -1e-9, dtype=np.float32)
    w = binning.wrap_periodic(pos, dom, xp=np)
    assert (w < 1.0).all() and (w >= 0.0).all()
    c = binning.cell_of_position(w, dom, ProcessGrid((2, 2, 2)), xp=np)
    assert (c >= 0).all() and (c <= 1).all()


def test_rank_of_position_rowmajor():
    pos = np.array([[0.9, 1.9, 3.9]], dtype=np.float32)  # cell (1,1,1)
    r = binning.rank_of_position(pos, DOMAIN, GRID, xp=np)
    assert r[0] == 7


def test_dest_histogram_matches_numpy(rng):
    R = GRID.nranks
    dest = rng.integers(0, R + 1, size=1000).astype(np.int32)  # incl sentinel
    h_jx = binning.dest_histogram(jnp.asarray(dest), R)
    h_np = binning.dest_histogram_np(dest, R)
    np.testing.assert_array_equal(np.asarray(h_jx), h_np)
    assert h_np.sum() == (dest < R).sum()


def test_dest_histogram_valid_mask():
    dest = np.array([0, 0, 1, 1, 1], dtype=np.int32)
    valid = np.array([True, False, True, True, False])
    h = binning.dest_histogram(jnp.asarray(dest), 2, valid=jnp.asarray(valid))
    np.testing.assert_array_equal(np.asarray(h), [1, 2])
