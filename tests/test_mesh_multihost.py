"""Multi-host surface (VERDICT round-1 item 10): construction-level tests
for make_hybrid_mesh and initialize_distributed on the virtual CPU mesh.

Real DCN/multi-slice hardware is not reachable here; these tests pin down
what can be pinned: hybrid meshes build, validate, and run the exchange on
8 virtual devices, and the distributed bring-up passthrough initializes a
single-process "cluster" in a subprocess.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib


def test_hybrid_mesh_all_ones_reduces_to_plain(_devices):
    grid = ProcessGrid((2, 2, 2))
    mesh = mesh_lib.make_hybrid_mesh(grid)
    mesh_lib.validate_mesh_for_grid(mesh, grid)
    assert tuple(mesh.devices.shape) == (2, 2, 2)


def test_hybrid_mesh_dcn_split(_devices):
    # dcn_shape=(2,1,1): axis x spans 2 "slices" of 4 devices each. On the
    # virtual CPU platform every device reports the same process/slice, so
    # mesh_utils may either build the hybrid layout or reject it — both
    # are valid constructions to pin; what must hold is: a returned mesh
    # has the right shape and axis names and passes validation.
    grid = ProcessGrid((2, 2, 2))
    try:
        mesh = mesh_lib.make_hybrid_mesh(grid, dcn_shape=(2, 1, 1))
    except (ValueError, AssertionError) as e:
        pytest.skip(f"hybrid layout rejected on virtual devices: {e}")
    mesh_lib.validate_mesh_for_grid(mesh, grid)
    assert tuple(mesh.devices.shape) == (2, 2, 2)


def test_hybrid_mesh_rejects_indivisible():
    grid = ProcessGrid((2, 2, 2))
    with pytest.raises(ValueError, match="not divisible"):
        mesh_lib.make_hybrid_mesh(grid, dcn_shape=(3, 1, 1))
    with pytest.raises(ValueError, match="axes"):
        mesh_lib.make_hybrid_mesh(grid, dcn_shape=(2, 1))


def test_exchange_runs_on_hybrid_mesh(rng, _devices):
    from mpi_grid_redistribute_tpu import GridRedistribute

    grid = ProcessGrid((2, 2, 2))
    mesh = mesh_lib.make_hybrid_mesh(grid)
    rd = GridRedistribute(
        Domain(0.0, 1.0), (2, 2, 2), mesh=mesh, capacity_factor=3.0
    )
    pos = rng.random((8 * 64, 3)).astype(np.float32)
    res = rd.redistribute(pos)
    assert int(np.asarray(res.count).sum()) == 8 * 64


def test_initialize_distributed_single_process():
    # jax.distributed.initialize mutates global state; exercise it in a
    # subprocess so the test session's backend stays untouched.
    code = (
        "import os;"
        "os.environ['XLA_FLAGS']=os.environ.get('XLA_FLAGS','')"
        "+' --xla_force_host_platform_device_count=8';"
        "import jax; jax.config.update('jax_platforms', 'cpu');"
        "from mpi_grid_redistribute_tpu.parallel import mesh as m;"
        "m.initialize_distributed(coordinator_address='localhost:12399',"
        "num_processes=1, process_id=0);"
        "assert jax.process_count() == 1;"
        "from mpi_grid_redistribute_tpu.domain import ProcessGrid;"
        "mesh = m.make_mesh(ProcessGrid((2, 2, 2)));"
        "print('distributed-init-ok', len(mesh.devices.ravel()))"
    )
    env = dict(os.environ)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "distributed-init-ok 8" in out.stdout


# ----------------------------------------------- elastic shrink (ISSUE 8)


def test_shrink_shape_halves_largest_axis():
    assert mesh_lib.shrink_shape((2, 2, 2)) == (1, 2, 2)  # tie: lowest axis
    assert mesh_lib.shrink_shape((1, 2, 2)) == (1, 1, 2)
    assert mesh_lib.shrink_shape((1, 1, 2)) == (1, 1, 1)
    assert mesh_lib.shrink_shape((2, 4, 2)) == (2, 2, 2)
    assert mesh_lib.shrink_shape((1, 8)) == (1, 4)
    # the floor: an all-ones grid cannot shrink and is returned unchanged
    assert mesh_lib.shrink_shape((1, 1, 1)) == (1, 1, 1)


def test_shrink_to_fit_walks_the_shrink_ladder():
    assert mesh_lib.shrink_to_fit((2, 2, 2), 8) == (2, 2, 2)  # already fits
    assert mesh_lib.shrink_to_fit((2, 2, 2), 4) == (1, 2, 2)
    assert mesh_lib.shrink_to_fit((2, 2, 2), 3) == (1, 1, 2)
    assert mesh_lib.shrink_to_fit((2, 2, 2), 1) == (1, 1, 1)
    assert mesh_lib.shrink_to_fit((4, 4), 5) == (2, 2)
    with pytest.raises(ValueError, match="cannot fit"):
        mesh_lib.shrink_to_fit((2, 2, 2), 0)


# ----------------------------------------------- HierarchicalMesh (ISSUE 19)


def test_make_hybrid_mesh_dcn_shape_defaults_to_none():
    # the published signature: dcn_shape is optional and None means
    # "flat" — callers must not need to spell out the all-ones tuple
    import inspect

    sig = inspect.signature(mesh_lib.make_hybrid_mesh)
    param = sig.parameters["dcn_shape"]
    assert param.default is None


@pytest.mark.parametrize(
    "dcn,msg",
    [
        ((2, 1), "must have 3 axes"),
        ((0, 1, 1), ">= 1"),
        ((3, 1, 1), "not divisible"),
    ],
    ids=["rank-mismatch", "nonpositive", "indivisible"],
)
def test_hierarchical_mesh_validates_dcn_shape(dcn, msg):
    grid = ProcessGrid((2, 2, 2))
    with pytest.raises(ValueError, match=msg):
        mesh_lib.HierarchicalMesh(grid, dcn)


def test_hierarchical_mesh_all_ones_is_flat():
    grid = ProcessGrid((2, 2, 2))
    hm = mesh_lib.HierarchicalMesh(grid, (1, 1, 1))
    assert hm.n_pods == 1
    assert hm.pod_size == grid.nranks
    assert hm.dcn_axes == ()
    assert hm.axis_names == grid.axis_names
    assert hm.local_grid.shape == grid.shape
    assert np.array_equal(hm.pod_of, np.zeros(8, np.int32))
    assert np.array_equal(hm.local_of, np.arange(8, dtype=np.int32))


def test_hierarchical_mesh_tables_2pods():
    grid = ProcessGrid((2, 2, 2))
    hm = mesh_lib.HierarchicalMesh(grid, (2, 1, 1))
    assert hm.n_pods == 2
    assert hm.pod_size == 4
    assert hm.ici_shape == (1, 2, 2)
    # interleaved expansion: the split axis becomes (dcn_x, x)
    assert hm.axis_names == ("dcn_x", "x", "y", "z")
    assert hm.axis_sizes == (2, 1, 2, 2)
    assert hm.dcn_axes == ("dcn_x",)
    assert hm.ici_axes == grid.axis_names
    # row-major flat index over the expanded axes IS the grid rank —
    # the bit-identity invariant the whole engine rests on
    ranks = np.arange(grid.nranks).reshape(grid.shape)
    assert np.array_equal(
        ranks.reshape(hm.axis_sizes).reshape(-1),
        np.arange(grid.nranks),
    )
    # pod/local tables are mutually consistent with the rank table
    for r in range(grid.nranks):
        assert hm.rank_table[hm.pod_of[r], hm.local_of[r]] == r
    # each pod's ranks are strictly ascending (deterministic routing)
    assert (np.diff(hm.rank_table, axis=1) > 0).all()
    # periodicity only survives on axes a pod spans fully
    assert hm.local_periodic((True, True, True)) == (False, True, True)
    assert hm.local_periodic((False, True, False)) == (
        False, True, False
    )


def test_hierarchical_mesh_build_mesh_expanded_axes(_devices):
    import jax

    grid = ProcessGrid((2, 2, 2))
    hm = mesh_lib.HierarchicalMesh(grid, (2, 1, 1))
    emesh = hm.build_mesh(list(jax.devices()[:8]))
    assert emesh.axis_names == ("dcn_x", "x", "y", "z")
    assert tuple(emesh.devices.shape) == (2, 1, 2, 2)
    with pytest.raises(ValueError, match="needs 8 devices"):
        hm.build_mesh(list(jax.devices()[:4]))
