"""Count-driven canonical exchange (ISSUE 7): bit-identity vs the
planar engine, wire-schedule structure, API dispatch and telemetry.

The sparse/neighbor engines are *engines*, not semantics: with any
``mover_cap`` they must reproduce the dense planar exchange's output
bit-for-bit (payload bytes AND counts AND stats prefix) — via the
``[K, R*B]`` count-driven pool when every shard's movers fit, via the
one-``lax.cond`` dense fallback when any shard overflows. What makes
them worth having is structural, so it is asserted structurally: the
neighbor fast branch is a ``ppermute`` shift schedule with NO dense
``all_to_all``, and the sparse dispatch cond's branches disagree on
pool width — invisible to correctness suites, the worst kind of
regression (see analysis/rules_fastpath.py).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu import api
from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.parallel import exchange
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

# (shape, periodic, mover_cap, n_local, cap, out_cap, drift)
CASES = [
    ((2, 2, 2), (True, True, True), 16, 120, 60, 300, 0.01),
    ((2, 2, 2), (True, True, True), 8, 120, 60, 300, 0.0),  # zero movers
    ((4, 2, 1), (False, False, False), 16, 100, 64, 300, 0.008),
    # tiny block + full reshuffle: every shard MUST take the fallback
    ((2, 2, 2), (True, True, True), 2, 120, 100, 400, 0.45),
]
IDS = ["g222-drift", "g222-zero", "g421-nonperiodic", "g222-reshuffle"]


def _inputs(shape, n_local, drift, rng, K=7):
    """Shard-local particles plus a gaussian drift: a realistic mover
    fraction, [R, K, n] vrank layout."""
    grid = ProcessGrid(shape=shape)
    R = grid.nranks
    pos = np.empty((R, 3, n_local), np.float32)
    for r in range(R):
        cell = grid.cell_of_rank(r)
        for a in range(3):
            w = 1.0 / shape[a]
            pos[r, a] = (cell[a] + rng.random(n_local)) * w
    pos = pos + rng.normal(0, drift, size=pos.shape).astype(np.float32)
    pos = np.mod(pos, 1.0).astype(np.float32)
    other = rng.standard_normal((R, K - 3, n_local)).astype(np.float32)
    fused = np.concatenate([pos, other], axis=1)
    count = rng.integers(
        n_local // 2, n_local + 1, size=R
    ).astype(np.int32)
    return grid, fused, count


@pytest.mark.parametrize("engine", ["sparse", "neighbor"])
@pytest.mark.parametrize(
    "shape,periodic,B,n_local,cap,out_cap,drift", CASES, ids=IDS
)
def test_count_driven_matches_planar_bitexact(
    shape, periodic, B, n_local, cap, out_cap, drift, engine, rng,
    _devices,
):
    grid, fused, count = _inputs(shape, n_local, drift, rng)
    R = grid.nranks
    domain = Domain(lo=(0.0,) * 3, hi=(1.0,) * 3, periodic=periodic)
    mesh = mesh_lib.make_mesh(grid, jax.devices()[:R])
    K = fused.shape[1]
    fused_g = jnp.asarray(
        np.transpose(fused, (1, 0, 2)).reshape(K, R * n_local)
    )
    count_g = jnp.asarray(count)

    ref = exchange.build_redistribute_planar(
        mesh, domain, grid, cap, out_cap, 3
    )
    out_p, cnt_p, st_p = ref(fused_g, count_g)
    f = exchange.build_redistribute_count_driven(
        mesh, domain, grid, cap, out_cap, B, 3, engine=engine
    )
    out_s, cnt_s, st_s = f(fused_g, count_g)
    assert np.asarray(out_s).tobytes() == np.asarray(out_p).tobytes()
    assert np.array_equal(np.asarray(cnt_s), np.asarray(cnt_p))
    # the 5-leaf stats prefix matches the dense engine's exactly
    for name in ("send_counts", "recv_counts", "dropped_send",
                 "dropped_recv", "needed_capacity"):
        assert np.array_equal(
            np.asarray(getattr(st_s, name)),
            np.asarray(getattr(st_p, name)),
        ), name
    fb = np.asarray(st_s.fallback)
    if drift == 0.45:
        assert fb.all(), "full reshuffle past mover_cap must fall back"
    elif drift == 0.0:
        assert not fb.any(), "zero movers must stay on the fast branch"

    # vrank twin: same engine, [R, K, n] single-device layout — equal to
    # the planar vrank twin AND to the sharded global result
    fused_v = jnp.asarray(fused)
    ref_v = exchange.build_redistribute_planar_vranks(
        domain, grid, cap, out_cap, 3
    )
    out_pv, cnt_pv, _ = ref_v(fused_v, count_g)
    fv = exchange.build_redistribute_count_driven_vranks(
        domain, grid, cap, out_cap, B, 3, engine=engine
    )
    out_sv, cnt_sv, _ = fv(fused_v, count_g)
    assert np.asarray(out_sv).tobytes() == np.asarray(out_pv).tobytes()
    assert np.array_equal(np.asarray(cnt_sv), np.asarray(cnt_pv))
    out_g = np.transpose(np.asarray(out_sv), (1, 0, 2)).reshape(
        K, R * out_cap
    )
    assert out_g.tobytes() == np.asarray(out_p).tobytes()


# ------------------------------------------------------- wire structure


# the jaxpr walk lives in the semantic analyzer now (progcheck's public
# API; rule J003 runs these same checks over every registered program)
from mpi_grid_redistribute_tpu.analysis.progcheck import (  # noqa: E402
    dispatch_conds,
    has_primitive,
    primitive_set,
    walk_eqns,
)


def test_neighbor_schedule_is_ppermute_no_dense_all_to_all(_devices):
    grid = ProcessGrid(shape=(2, 2, 2))
    domain = Domain(lo=(0.0,) * 3, hi=(1.0,) * 3, periodic=(True,) * 3)
    mesh = mesh_lib.make_mesh(grid, jax.devices()[:8])
    f = exchange.shard_redistribute_count_driven_sharded(
        mesh, domain, grid, 64, 256, 8, 3, engine="neighbor"
    )
    jaxpr = jax.make_jaxpr(f)(
        jnp.zeros((7, 8 * 64), jnp.float32),
        jnp.zeros((8,), jnp.int32),
    ).jaxpr
    conds = dispatch_conds(
        jaxpr, lambda b: has_primitive(b, "all_to_all")
    )
    assert conds, "neighbor dispatch cond not found"
    for _eqn, fast, dense in conds:
        fast_prims = primitive_set(fast)
        # the fast branch is the ppermute shift schedule — never the
        # dense pool exchange
        assert "ppermute" in fast_prims
        assert "all_to_all" not in fast_prims
        assert "ppermute" not in primitive_set(dense)


def test_sparse_dispatch_cond_separates_pool_widths(_devices):
    grid = ProcessGrid(shape=(2, 2, 2))
    domain = Domain(lo=(0.0,) * 3, hi=(1.0,) * 3, periodic=(True,) * 3)
    mesh = mesh_lib.make_mesh(grid, jax.devices()[:8])
    cap, B = 64, 8
    f = exchange.shard_redistribute_count_driven_sharded(
        mesh, domain, grid, cap, 256, B, 3, engine="sparse"
    )
    jaxpr = jax.make_jaxpr(f)(
        jnp.zeros((7, 8 * 64), jnp.float32),
        jnp.zeros((8,), jnp.int32),
    ).jaxpr
    # both branches exchange (sparse still rides all_to_all — at B, not
    # cap, columns per destination), so find the dispatch cond by the
    # branches' all_to_all operand widths instead
    widths = []
    for eqn in walk_eqns(jaxpr):
        if eqn.primitive.name != "cond":
            continue
        per_branch = []
        for b in eqn.params["branches"]:
            w = [
                int(np.prod(e.invars[0].aval.shape))
                for e in walk_eqns(b.jaxpr)
                if e.primitive.name == "all_to_all"
            ]
            per_branch.append(max(w) if w else 0)
        if len(set(per_branch)) == 2 and min(per_branch) > 0:
            widths.append(sorted(per_branch))
    assert widths, "sparse dispatch cond not found"
    for narrow, wide in widths:
        # the sparse pool is B/cap of the dense pool, per payload row
        assert narrow * cap == wide * B


# ---------------------------------------------------------- API dispatch


def _mk_rows(grid, n_local, drift, rng):
    """[N, 3] shard-local row positions + int32 ids (API layout)."""
    R = grid.nranks
    pos = np.empty((R * n_local, 3), np.float32)
    for r in range(R):
        cell = grid.cell_of_rank(r)
        for a in range(3):
            w = 1.0 / grid.shape[a]
            pos[r * n_local:(r + 1) * n_local, a] = (
                cell[a] + rng.random(n_local)
            ) * w
    pos = np.mod(pos + rng.normal(0, drift, pos.shape), 1.0).astype(
        np.float32
    )
    return pos, np.arange(R * n_local, dtype=np.int32)


def _rd(shape, engine, **kw):
    return api.GridRedistribute(
        grid=shape, lo=(0.0,) * 3, hi=(1.0,) * 3,
        periodic=(True,) * 3, engine=engine, **kw
    )


def test_api_auto_routes_sparse_and_journals_once(rng, _devices):
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.02, rng)
    rd_a = _rd((2, 2, 2), "auto")
    rd_p = _rd((2, 2, 2), "planar")
    res_a = rd_a.redistribute(pos, ids)
    res_p = rd_p.redistribute(pos, ids)
    assert np.asarray(res_a.positions).tobytes() == np.asarray(
        res_p.positions
    ).tobytes()
    assert np.array_equal(
        np.asarray(res_a.count), np.asarray(res_p.count)
    )
    ev = [e for e in rd_a.telemetry.events()
          if e.kind == "engine_resolved"]
    assert [e.data["resolved"] for e in ev] == ["sparse"]
    assert ev[0].data["requested"] == "auto"
    # second call, same routing inputs: journaled once, not per call
    rd_a.redistribute(pos, ids)
    assert len([e for e in rd_a.telemetry.events()
                if e.kind == "engine_resolved"]) == 1
    # the redistribute event carries the scheduled wire bytes
    ev_rd = [e for e in rd_a.telemetry.events()
             if e.kind == "redistribute"]
    assert ev_rd[-1].data["engine"] == "sparse"
    assert ev_rd[-1].data["wire_bytes"] > 0
    rep = rd_a.report()
    assert rep["engine"] == "sparse"
    assert rep["fallback_steps"] == 0
    assert (
        rep["wire_bytes_per_step"] < rep["dense_wire_bytes_per_step"]
    )
    # ... and feeds the OpenMetrics counter family
    assert "grid_exchange_wire_bytes_total" in rd_a.metrics(render=True)


def test_api_neighbor_bitexact(rng, _devices):
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.02, rng)
    res_n = _rd((2, 2, 2), "neighbor").redistribute(pos, ids)
    res_p = _rd((2, 2, 2), "planar").redistribute(pos, ids)
    assert np.asarray(res_n.positions).tobytes() == np.asarray(
        res_p.positions
    ).tobytes()


def test_api_vranks_auto_planar_explicit_sparse(rng, _devices):
    # 27 ranks > 8 devices: single-device vrank build. auto keeps the
    # dense planar engine (no wire to shrink on one device); explicit
    # sparse opts into the count-driven vrank engine, bit-identically.
    grid = ProcessGrid((3, 3, 3))
    pos, ids = _mk_rows(grid, 40, 0.01, rng)
    rd_a = _rd((3, 3, 3), "auto", capacity=16)
    rd_s = _rd((3, 3, 3), "sparse", capacity=16)
    res_a = rd_a.redistribute(pos, ids)
    res_s = rd_s.redistribute(pos, ids)
    assert np.asarray(res_s.positions).tobytes() == np.asarray(
        res_a.positions
    ).tobytes()
    assert rd_a.report()["engine"] == "planar"
    assert rd_s.report()["engine"] == "sparse"


def test_api_fallback_surfaced_and_billed_dense(rng, _devices):
    # mover_cap=1 + a 45%-drift reshuffle: the in-graph dense fallback
    # IS the result under on_overflow='ignore' (no lossy branch exists —
    # out_capacity is sized up), surfaced in the report and billed at
    # dense width in the wire model
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.45, rng)
    rd_f = _rd((2, 2, 2), "sparse", mover_cap=1, capacity=96,
               out_capacity=256, on_overflow="ignore")
    rd_p = _rd((2, 2, 2), "planar", capacity=96, out_capacity=256,
               on_overflow="ignore")
    res_f = rd_f.redistribute(pos, ids)
    res_p = rd_p.redistribute(pos, ids)
    assert np.asarray(res_f.positions).tobytes() == np.asarray(
        res_p.positions
    ).tobytes()
    rep = rd_f.report()
    assert rep["fallback_steps"] == 1
    assert (
        rep["wire_bytes_per_step"] == rep["dense_wire_bytes_per_step"]
    )


def test_api_mover_cap_ratchets_from_measured_need(rng, _devices):
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 96, 0.05, rng)
    rd = _rd((2, 2, 2), "sparse", mover_cap=1, capacity=96,
             out_capacity=256)
    rd.redistribute(pos, ids)
    assert rd._mover_cap > 1
    grow = [e for e in rd.telemetry.events()
            if e.kind == "mover_cap_grow"]
    assert grow and grow[-1].data["new"] == rd._mover_cap


def test_api_explicit_count_driven_needs_planar_payload(rng, _devices):
    grid = ProcessGrid((2, 2, 2))
    pos, ids = _mk_rows(grid, 32, 0.0, rng)
    rd = _rd((2, 2, 2), "sparse")
    with pytest.raises(TypeError, match="32-bit"):
        rd.redistribute(pos.astype(np.float64), ids)


def test_resolve_engine_journals_degradation():
    from mpi_grid_redistribute_tpu import telemetry

    rec = telemetry.StepRecorder()
    out = exchange.resolve_engine(
        "auto", canonical=True, planar_ok=False, recorder=rec
    )
    assert out == "rowmajor"
    ev = rec.events("engine_resolved")
    assert len(ev) == 1
    assert ev[0].data["requested"] == "auto"
    assert ev[0].data["resolved"] == "rowmajor"
    assert "planar-eligible" in ev[0].data["reason"]
    assert ev[0].data["canonical"] is True
