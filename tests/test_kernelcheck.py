"""kernelcheck (analysis/kernelcheck.py + rules_kernel.py) — spiked
fixtures per K-rule, the capture machinery over the real registry, the
CLI/baseline contract, and the repo gate.

Spiked kernels are REAL ``pallas_call`` launches captured through the
same ``jax.eval_shape`` patch the production registry uses, so the
fixtures exercise the whole pipeline, not hand-mocked sites; rule
corner cases that don't need capture use hand-built PallasSites."""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mpi_grid_redistribute_tpu.analysis import kernelcheck as kc
from mpi_grid_redistribute_tpu.analysis import rules_kernel as rk
from mpi_grid_redistribute_tpu.analysis.baseline import (
    write_kernelcheck_baseline,
)
from mpi_grid_redistribute_tpu.analysis.core import run_gridlint
from mpi_grid_redistribute_tpu.analysis.kernelcheck import (
    BlockRef,
    KernelCase,
    KernelFinding,
    KernelSpec,
    PallasSite,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _copy_kernel(in_ref, out_ref):
    out_ref[:] = in_ref[:]


def _plus_one_kernel(in_ref, out_ref):
    out_ref[:] = in_ref[:] + 1.0


def _mk_spec(
    name,
    *,
    in_map,
    out_map,
    grid=(4,),
    shape=(32, 128),
    block=(8, 128),
    scatter=False,
    kernel=_copy_kernel,
    reference=None,
    aliases=None,
):
    """A runnable single-operand spiked kernel spec."""
    x = jnp.asarray(
        np.arange(int(np.prod(shape)), dtype=np.float32).reshape(shape)
    )

    def run(a, interpret):
        return pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(block, in_map, memory_space=pltpu.VMEM)
            ],
            out_specs=pl.BlockSpec(block, out_map,
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct(shape, jnp.float32),
            input_output_aliases=dict(aliases or {}),
            interpret=interpret,
        )(a)

    def build():
        return KernelCase(args=x, run=run, reference=reference)

    return KernelSpec(name, build, scatter=scatter)


def _ref(role, index, shape, dtype="float32", block=None, imap=None,
         space="vmem"):
    return BlockRef(
        role=role,
        index=index,
        memory_space=space,
        array_shape=tuple(shape),
        dtype=dtype,
        block_shape=tuple(block) if block else None,
        index_map=imap,
    )


def _site(grid, ins=(), outs=(), scratch=(), aliases=None,
          vmem_limit=None):
    return PallasSite(
        kernel="spiked",
        fn_name="k",
        path="tests/test_kernelcheck.py",
        line=1,
        grid=tuple(grid),
        ins=list(ins),
        outs=list(outs),
        scratch=list(scratch),
        aliases=dict(aliases or {}),
        vmem_limit_bytes=vmem_limit,
    )


_SPEC = KernelSpec("spiked", lambda: None)
_SCATTER_SPEC = KernelSpec("spiked", lambda: None, scatter=True)


def _run(spec, rules):
    findings, footprints, _ = kc.run_kernelcheck(
        {spec.name: spec}, rules=rules
    )
    return findings, footprints


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ------------------------------------------------------------- surface


def test_rule_docs_cover_all_ids():
    assert set(kc.K_RULE_IDS) == set(rk.RULE_DOCS)


# ---------------------------------------------------------------- K001


def test_k001_fires_on_out_of_bounds_index_map(_devices):
    spec = _mk_spec(
        "oob", in_map=lambda i: (i + 1, 0), out_map=lambda i: (i, 0)
    )
    findings, _ = _run(spec, ["K001"])
    assert rules_of(findings) == ["K001"], findings
    msg = findings[0].message
    assert "in[0]" in msg and "[1, 4]" in msg and "g0" in msg
    # the capture points at the REAL launch site (this file)
    assert findings[0].path == "tests/test_kernelcheck.py"


def test_k001_quiet_on_clean_twin(_devices):
    spec = _mk_spec(
        "clean", in_map=lambda i: (i, 0), out_map=lambda i: (i, 0)
    )
    findings, _ = _run(spec, ["K001", "K002", "K004"])
    assert findings == [], findings


def test_k001_fires_on_negative_index(_devices):
    spec = _mk_spec(
        "neg", in_map=lambda i: (i - 1, 0), out_map=lambda i: (i, 0)
    )
    findings, _ = _run(spec, ["K001"])
    assert rules_of(findings) == ["K001"], findings
    assert "[-1, 2]" in findings[0].message


def test_k001_non_affine_map_still_checked_exactly():
    # enumeration is ground truth: an affine fit of i*i misses, the
    # exhaustive sweep still proves the bound violation at i=3
    ref = _ref("in", 0, (32, 128), block=(8, 128),
               imap=lambda i: (i * i, 0))
    findings = rk.check_k001(_site((4,), ins=[ref]), _SPEC)
    assert rules_of(findings) == ["K001"], findings
    assert "[0, 9]" in findings[0].message


def test_k001_and_g005_are_disjoint(tmp_path, _devices):
    """The AST/semantic split, spiked from the kernelcheck side: the
    SAME out-of-bounds launch is lexically impeccable (G005 quiet) yet
    semantically broken (K001 fires); a lexically-defaulted launch
    (G005 fires) is semantically fine whole-array (K001/K002 quiet)."""
    # twin A: lexically clean, semantically out of bounds
    src = tmp_path / "pallas_fix.py"
    src.write_text(textwrap.dedent("""
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        def _kernel(in_ref, out_ref):
            out_ref[:] = in_ref[:] + 1.0

        def launch(x):
            return pl.pallas_call(
                _kernel,
                grid=(4,),
                in_specs=[pl.BlockSpec((8, 128), lambda i: (i + 1, 0),
                                       memory_space=pltpu.VMEM)],
                out_specs=pl.BlockSpec((8, 128), lambda i: (i, 0),
                                       memory_space=pltpu.VMEM),
                out_shape=x,
            )(x)
    """))
    g_findings = run_gridlint([str(tmp_path)], root=str(tmp_path))
    assert g_findings == [], g_findings  # G005 cannot see the bounds
    spec = _mk_spec(
        "twin_a", in_map=lambda i: (i + 1, 0), out_map=lambda i: (i, 0)
    )
    k_findings, _ = _run(spec, ["K001"])
    assert rules_of(k_findings) == ["K001"]

    # twin B: lexically defaulted (G005's concern), semantically fine
    src.write_text(textwrap.dedent("""
        from jax.experimental import pallas as pl

        def launch(kernel, x):
            return pl.pallas_call(kernel, out_shape=x)(x)
    """))
    g_findings = run_gridlint([str(tmp_path)], root=str(tmp_path))
    assert rules_of(g_findings) == ["G005"], g_findings

    x = jnp.asarray(np.arange(128, dtype=np.float32).reshape(1, 128))

    def run_b(a, interpret):
        return pl.pallas_call(
            _copy_kernel,
            out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
            interpret=interpret,
        )(a)

    spec_b = KernelSpec(
        "twin_b", lambda: KernelCase(args=x, run=run_b, reference=None)
    )
    k_findings, _ = _run(spec_b, ["K001", "K002"])
    assert k_findings == [], k_findings


# ---------------------------------------------------------------- K002


def test_k002_fires_on_scatter_write_overlap(_devices):
    spec = _mk_spec(
        "overlap",
        in_map=lambda i: (0, 0),
        out_map=lambda i: (0, 0),
        grid=(2,),
        shape=(16, 128),
        block=(8, 128),
        scatter=True,
    )
    findings, _ = _run(spec, ["K002"])
    assert rules_of(findings) == ["K002"], findings
    msgs = "\n".join(f.message for f in findings)
    assert "write overlap" in msgs and "coverage gap" in msgs


def test_k002_fires_on_coverage_gap(_devices):
    spec = _mk_spec(
        "gap",
        in_map=lambda i: (i, 0),
        out_map=lambda i: (0, 0),
        grid=(4,),
    )
    findings, _ = _run(spec, ["K002"])
    assert any(
        "coverage gap" in f.message and "3 of 4" in f.message
        for f in findings
    ), findings


def test_k002_consecutive_revisit_is_legal(_devices):
    # the driftbin shape: the same out block accumulated across the
    # fast (last) grid axis — consecutive in execution order, legal
    spec = _mk_spec(
        "revisit_ok",
        in_map=lambda i, j: (i, 0),
        out_map=lambda i, j: (i, 0),
        grid=(2, 2),
        shape=(16, 128),
        block=(8, 128),
    )
    findings, _ = _run(spec, ["K002"])
    assert findings == [], findings


def test_k002_fires_on_non_consecutive_revisit(_devices):
    # transposed: the same block revisited on the SLOW axis — the
    # pipeline flushes it in between, later steps clobber
    spec = _mk_spec(
        "revisit_bad",
        in_map=lambda i, j: (j, 0),
        out_map=lambda i, j: (j, 0),
        grid=(2, 2),
        shape=(16, 128),
        block=(8, 128),
    )
    findings, _ = _run(spec, ["K002"])
    assert rules_of(findings) == ["K002"], findings
    assert "NON-consecutive" in findings[0].message


def test_k002_alias_exempts_coverage(_devices):
    spec = _mk_spec(
        "aliased",
        in_map=lambda i: (0, 0),
        out_map=lambda i: (0, 0),
        grid=(2,),
        shape=(16, 128),
        block=(8, 128),
        aliases={0: 0},
    )
    findings, _ = _run(spec, ["K002"])
    assert findings == [], findings


def test_k002_grid_dim_zero_means_uncovered_output():
    """The semantic twin of test_gridlint's grid-dim-0 fixture: zero
    grid steps run, so a non-aliased blocked output is never written."""
    imap = lambda i, j: (i, 0)  # noqa: E731
    out = _ref("out", 0, (32, 128), block=(8, 128), imap=imap)
    findings = rk.check_k002(_site((0, 4), outs=[out]), _SPEC)
    assert rules_of(findings) == ["K002"], findings
    assert "4 of 4 block(s) never written" in findings[0].message


# ---------------------------------------------------------------- K003


def test_k003_fires_on_vmem_overflow():
    imap = lambda i: (i, 0)  # noqa: E731
    big_in = _ref("in", 0, (4096, 2048), block=(1024, 2048), imap=imap)
    big_out = _ref("out", 0, (4096, 2048), block=(1024, 2048), imap=imap)
    site = _site((4,), ins=[big_in], outs=[big_out])
    findings = rk.check_k003_budget("spiked", [site])
    assert rules_of(findings) == ["K003"], findings
    assert "default ~16 MiB/core" in findings[0].message
    # a declared (deliberate) budget clears the same footprint
    site_ok = _site(
        (4,), ins=[big_in], outs=[big_out], vmem_limit=64 * 2**20
    )
    assert rk.check_k003_budget("spiked", [site_ok]) == []


def test_k003_footprint_model_pads_and_double_buffers():
    varying = lambda i: (i, 0)  # noqa: E731
    const = lambda i: (0, 0)  # noqa: E731
    site = _site(
        (4,),
        ins=[_ref("in", 0, (32, 100), block=(8, 100), imap=varying)],
        outs=[_ref("out", 0, (32, 100), block=(8, 100), imap=const)],
        scratch=[
            _ref("scratch", 0, (7, 100)),
            _ref("scratch", 1, (2,), dtype="dma_sem", space="semaphore"),
            _ref("scratch", 2, (4,), dtype="int32", space="smem"),
        ],
    )
    rec = rk.site_footprint(site)
    lane_padded = 8 * 128 * 4  # (8, 100) f32 -> (8, 128)
    assert rec["block_bytes"] == 2 * lane_padded + 1 * lane_padded
    assert rec["scratch_bytes"] == 8 * 128 * 4  # (7,100) -> (8,128)
    assert rec["smem_bytes"] == 16  # semaphores free, SMEM separate
    assert rec["vmem_bytes"] == rec["block_bytes"] + rec["scratch_bytes"]


def test_k003_compare_footprints_missing_drift_stale():
    fp = {"path": "p", "grid": [2], "block_bytes": 10,
          "scratch_bytes": 0, "smem_bytes": 0, "vmem_bytes": 10,
          "budget_bytes": 100}
    cur = {"k1": {"peak_vmem_bytes": 10, "sites": [dict(fp)]}}
    # missing baseline entry
    findings = rk.compare_footprints(cur, {})
    assert ["K003"] == rules_of(findings)
    assert "no committed footprint baseline" in findings[0].message
    # exact match: clean
    base = json.loads(json.dumps(cur))
    assert rk.compare_footprints(cur, base) == []
    # numeric drift
    base["k1"]["sites"][0]["vmem_bytes"] = 11
    findings = rk.compare_footprints(cur, base)
    assert any("vmem_bytes drifted" in f.message for f in findings)
    # stale entry only under --check over the full registry
    base = json.loads(json.dumps(cur))
    base["ghost"] = {"peak_vmem_bytes": 1, "sites": []}
    assert rk.compare_footprints(cur, base) == []
    findings = rk.compare_footprints(cur, base, check_stale=True)
    assert any("stale footprint baseline" in f.message for f in findings)
    assert rk.compare_footprints(
        cur, base, check_stale=True, partial=True
    ) == []


# ---------------------------------------------------------------- K004


def test_k004_fires_on_illegal_lane_split():
    imap = lambda i: (0, i)  # noqa: E731
    ref = _ref("in", 0, (8, 400), block=(8, 100), imap=imap)
    findings = rk.check_k004(_site((4,), ins=[ref]), _SPEC)
    assert rules_of(findings) == ["K004"], findings
    assert "lane" in findings[0].message and "128" in findings[0].message


def test_k004_fires_on_illegal_sublane_split():
    imap = lambda i: (i, 0)  # noqa: E731
    ref = _ref("in", 0, (9, 128), block=(3, 128), imap=imap)
    findings = rk.check_k004(_site((3,), ins=[ref]), _SPEC)
    assert rules_of(findings) == ["K004"], findings
    assert "sublane tile 8" in findings[0].message


def test_k004_full_dim_blocks_are_legal_padding():
    # driftbin's (7, w) blocks: 7 is the FULL sublane extent — the
    # compiler pads, K003 charges it, K004 stays quiet
    imap = lambda i: (0, i)  # noqa: E731
    ref = _ref("in", 0, (7, 4096), block=(7, 1024), imap=imap)
    assert rk.check_k004(_site((4,), ins=[ref]), _SPEC) == []


def test_k004_fires_on_8_byte_dtype():
    ref = _ref("scratch", 0, (8, 128), dtype="float64")
    findings = rk.check_k004(_site((1,), scratch=[ref]), _SPEC)
    assert rules_of(findings) == ["K004"], findings
    assert "no legal TPU VMEM tiling" in findings[0].message


# ---------------------------------------------------------------- K005


def test_k005_fires_on_missing_reference(_devices):
    spec = _mk_spec(
        "noref", in_map=lambda i: (i, 0), out_map=lambda i: (i, 0)
    )
    findings, _ = _run(spec, ["K005"])
    assert rules_of(findings) == ["K005"], findings
    assert "no registered jnp/XLA reference" in findings[0].message


def test_k005_fires_on_bit_mismatch(_devices):
    spec = _mk_spec(
        "mismatch",
        in_map=lambda i: (i, 0),
        out_map=lambda i: (i, 0),
        kernel=_plus_one_kernel,
        reference=lambda a: a,  # wrong twin: identity
    )
    findings, _ = _run(spec, ["K005"])
    assert rules_of(findings) == ["K005"], findings
    assert "not bit-identical" in findings[0].message
    assert "4096 of 4096" in findings[0].message


def test_k005_quiet_on_bit_identical_reference(_devices):
    spec = _mk_spec(
        "exact",
        in_map=lambda i: (i, 0),
        out_map=lambda i: (i, 0),
        kernel=_plus_one_kernel,
        reference=lambda a: a + 1.0,
    )
    findings, _ = _run(spec, ["K005"])
    assert findings == [], findings


# -------------------------------------------------------- suppressions


def test_suppression_line_and_file_level(tmp_path):
    src = tmp_path / "m.py"
    src.write_text(
        "x = 1  # kernelcheck: disable=K001\n"
        "# kernelcheck: disable-file=K004\n"
    )
    f1 = KernelFinding("K001", "k", "m", path=str(src), line=1)
    f2 = KernelFinding("K004", "k", "m", path=str(src), line=2)
    f3 = KernelFinding("K002", "k", "m", path=str(src), line=1)
    kept, n_suppressed = kc._apply_suppressions([f1, f2, f3])
    assert n_suppressed == 2
    assert [f.rule for f in kept] == ["K002"]
    # a gridlint pragma must NOT silence K-rules (own namespace)
    src.write_text("y = 1  # gridlint: disable=K001\n")
    kept, n_suppressed = kc._apply_suppressions(
        [KernelFinding("K001", "k", "m", path=str(src), line=1)]
    )
    assert n_suppressed == 0 and len(kept) == 1


# ------------------------------------------------- registry + capture


def test_registry_capture_driftbin_site(_devices):
    kernels = kc.default_kernels()
    case, sites = kc.capture_kernel(kernels["driftbin_v8_n2048"])
    assert len(sites) == 1
    s = sites[0]
    assert s.path == "mpi_grid_redistribute_tpu/ops/pallas_driftbin.py"
    assert s.grid == (2, 8)
    assert s.aliases == {0: 0}
    assert [r.blocked for r in s.outs] == [True, True]
    assert s.ins[0].block_shape == (7, 1024)


def test_registry_capture_scatter_records_compiler_params(_devices):
    kernels = kc.default_kernels()
    case, sites = kc.capture_kernel(kernels["scatter_rows_16384x7"])
    assert len(sites) == 1
    s = sites[0]
    assert s.vmem_limit_bytes == 100 * 1024 * 1024
    assert any(r.memory_space == "semaphore" for r in s.scratch)
    assert any(r.memory_space == "smem" for r in s.ins)


def test_registry_static_rules_clean(_devices):
    findings, footprints, _ = kc.run_kernelcheck(
        kc.default_kernels(), rules=["K000", "K001", "K002", "K004"]
    )
    assert findings == [], findings
    assert footprints == {}  # K003 not selected -> no table


def test_k000_fires_on_fallback_taking_case(_devices):
    from mpi_grid_redistribute_tpu.ops import pallas_dfscan

    # rows below any block and a non-kernel path: 1000 is fine, but an
    # entry point that never reaches pallas_call must be flagged — use
    # a run() that skips the kernel entirely
    def run(a, interpret):
        return a * 2.0

    spec = KernelSpec(
        "fallback",
        lambda: KernelCase(
            args=jnp.ones((4, 4), jnp.float32), run=run, reference=None
        ),
    )
    findings, _ = _run(spec, ["K000"])
    assert rules_of(findings) == ["K000"], findings
    assert "no pallas_call captured" in findings[0].message
    del pallas_dfscan


def test_k000_fires_on_broken_build(_devices):
    def bad_build():
        raise RuntimeError("no such shape")

    spec = KernelSpec("broken", bad_build)
    findings, _ = _run(spec, ["K001"])  # K000 build failures always fire
    assert rules_of(findings) == ["K000"], findings
    assert "failed to build/trace" in findings[0].message


# ------------------------------------------------------ CLI + baseline


def test_cli_list_rules_and_usage_errors(capsys):
    assert kc.main(["--list-rules"]) == 0
    assert "K003" in capsys.readouterr().out
    assert kc.main(["--rules", "K999"]) == 2
    assert kc.main(["--kernels", "nope"]) == 2


def test_cli_baseline_roundtrip_and_drift(tmp_path, capsys, _devices):
    bp = str(tmp_path / "kb.json")
    rc = kc.main(
        ["--kernels", "dfscan_300x256", "--update-baseline",
         "--baseline", bp]
    )
    assert rc == 0
    capsys.readouterr()
    rc = kc.main(
        ["--kernels", "dfscan_300x256", "--rules", "K003",
         "--baseline", bp]
    )
    assert rc == 0, capsys.readouterr().out
    capsys.readouterr()
    with open(bp) as fh:
        doc = json.load(fh)
    doc["footprints"]["dfscan_300x256"]["peak_vmem_bytes"] += 4096
    with open(bp, "w") as fh:
        json.dump(doc, fh)
    rc = kc.main(
        ["--kernels", "dfscan_300x256", "--rules", "K003",
         "--baseline", bp]
    )
    out = capsys.readouterr().out
    assert rc == 1
    assert "drifted" in out


def test_cli_check_baseline_mode(tmp_path, capsys):
    missing = str(tmp_path / "none.json")
    assert kc.main(["--check-baseline", "--baseline", missing]) == 1
    assert "no footprint baseline" in capsys.readouterr().out
    bp = str(tmp_path / "kb.json")
    rows = {
        name: {"peak_vmem_bytes": 1, "sites": []}
        for name in kc.default_kernels()
    }
    rows["ghost_kernel"] = {"peak_vmem_bytes": 1, "sites": []}
    write_kernelcheck_baseline(bp, rows)
    assert kc.main(["--check-baseline", "--baseline", bp]) == 1
    assert "ghost_kernel" in capsys.readouterr().out
    del rows["ghost_kernel"]
    write_kernelcheck_baseline(bp, rows)
    assert kc.main(["--check-baseline", "--baseline", bp]) == 0


def test_cli_json_and_sarif_formats(capsys, _devices):
    rc = kc.main(
        ["--kernels", "dfscan_300x256", "--rules", "K001,K002,K004",
         "--format", "json"]
    )
    data = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert data["findings"] == []
    assert data["kernels"] == ["dfscan_300x256"]
    rc = kc.main(
        ["--kernels", "dfscan_300x256", "--rules", "K001",
         "--format", "sarif"]
    )
    doc = json.loads(capsys.readouterr().out)
    assert rc == 0
    run0 = doc["runs"][0]
    assert run0["tool"]["driver"]["name"] == "kernelcheck"
    assert {r["id"] for r in run0["tool"]["driver"]["rules"]} == set(
        kc.K_RULE_IDS
    )


def test_repo_gate_check_exits_zero(_devices):
    """The committed registry + baseline must be clean at HEAD — the
    same gate `make kernelcheck` and check_all.py enforce (includes
    the K005 interpret execution of every shipped kernel)."""
    assert kc.main(["--check"]) == 0


def test_cli_script_entry_point():
    """scripts/kernelcheck.py runs standalone (it pins the CPU
    platform itself)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)  # the wrapper must pin cpu itself
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "kernelcheck.py"),
            "--list-kernels",
        ],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=300,
        env=env,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "driftbin_v8_n2048" in proc.stdout
