"""C++ host runtime (native/) vs the NumPy reference — bit-level equality
and fallback behavior (SURVEY.md §2 native components)."""

import numpy as np
import pytest

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.utils import native


pytestmark = pytest.mark.skipif(
    not native.build(),  # explicit opt-in build (advisor: no implicit g++)
    reason="native library not built (no g++?)",
)


@pytest.mark.parametrize(
    "dom,gshape",
    [
        (Domain(0.0, 1.0, periodic=True), (4, 4, 4)),
        (
            Domain((-1.0, 0.0, 2.5), (1.0, 0.3, 7.1),
                   periodic=(True, False, True)),
            (3, 5, 2),
        ),
        (Domain(0.0, 1.0, ndim=2, periodic=False), (8, 8)),
    ],
)
def test_bin_bit_identical(dom, gshape, rng):
    grid = ProcessGrid(gshape)
    pos = (rng.standard_normal((100000, dom.ndim)) * 2).astype(np.float32)
    pos[:10] = 0.0
    pos[10:20] = 1.0
    pos[20:30] = -1e-8
    want = binning.rank_of_position(pos, dom, grid, xp=np)
    got = native.bin_positions(pos, dom, grid)
    np.testing.assert_array_equal(want, got)


def test_count_sort_matches_stable_argsort(rng):
    dest = rng.integers(0, 9, size=50000).astype(np.int32)  # 8 + sentinel
    counts, order = native.count_sort(dest, 8)
    np.testing.assert_array_equal(
        counts, np.bincount(dest, minlength=9)[:8]
    )
    np.testing.assert_array_equal(order, np.argsort(dest, kind="stable"))


def test_gather_rows(rng):
    src = rng.random((1000, 5)).astype(np.float32)
    order = rng.permutation(1000).astype(np.int64)[:300]
    np.testing.assert_array_equal(native.gather_rows(src, order), src[order])
    ids = rng.integers(0, 1 << 40, size=1000)  # int64 rows
    np.testing.assert_array_equal(native.gather_rows(ids, order), ids[order])


def test_oracle_uses_native_and_matches_jax(rng, _devices):
    """End-to-end: the native-accelerated oracle still bit-matches JAX."""
    import mpi_grid_redistribute_tpu as gr

    n_local = 256
    pos = rng.random((8 * n_local, 3), dtype=np.float32)
    kw = dict(grid=(2, 2, 2), lo=0.0, hi=1.0, periodic=True,
              capacity_factor=8.0)
    res = gr.GridRedistribute(backend="jax", **kw).redistribute(pos)
    res_np = gr.GridRedistribute(backend="numpy", **kw).redistribute(pos)
    assert np.asarray(res.positions).tobytes() == res_np.positions.tobytes()
    assert np.asarray(res.count).tobytes() == res_np.count.tobytes()
