"""Within-tile double-float prefix-sum kernel (ops/pallas_dfscan.py)
vs the XLA Hillis-Steele loop it replaces (deposit._df_cumsum) — bit
level, interpret mode on CPU. The kernel runs the IDENTICAL
_two_sum/_df_add float sequence in the same order (adds/subs only, so
no fma contraction can split the paths), hence both hi and lo planes
must match exactly, including the row-padding slice."""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu.ops import deposit, pallas_dfscan


def _xla_twin(x):
    hi, lo = jax.jit(functools.partial(deposit._df_cumsum, axis=1))(x)
    return np.asarray(hi), np.asarray(lo)


@pytest.mark.parametrize(
    "rows,tile",
    [
        (100, 256),  # single partial block (padded to 256)
        (256, 128),  # exactly one block, smaller tile
        (300, 512),  # grid (2,): block boundary + padding tail
    ],
)
def test_dfscan_matches_xla_twin_bits(rng, _devices, rows, tile):
    r = np.random.default_rng(hash((rows, tile)) % 2**32)
    x = jnp.asarray(r.standard_normal((rows, tile)).astype(np.float32))
    hi_p, lo_p = pallas_dfscan.tile_df_cumsum_rows(x, interpret=True)
    hi_x, lo_x = _xla_twin(x)
    np.testing.assert_array_equal(
        np.asarray(hi_p).view(np.uint32), hi_x.view(np.uint32)
    )
    np.testing.assert_array_equal(
        np.asarray(lo_p).view(np.uint32), lo_x.view(np.uint32)
    )


def test_dfscan_hostile_magnitudes(rng, _devices):
    """Catastrophic-cancellation bait: mixed huge/tiny magnitudes and
    signs is exactly where the compensated lo plane earns its keep —
    and where any reassociation between the two paths would show."""
    r = np.random.default_rng(77)
    rows, tile = 64, 256
    mags = r.choice([1e-30, 1e-8, 1.0, 1e8, 1e30], size=(rows, tile))
    x = (r.standard_normal((rows, tile)) * mags).astype(np.float32)
    x[3, :8] = 0.0  # exact zeros mid-stream
    xj = jnp.asarray(x)
    hi_p, lo_p = pallas_dfscan.tile_df_cumsum_rows(xj, interpret=True)
    hi_x, lo_x = _xla_twin(xj)
    np.testing.assert_array_equal(
        np.asarray(hi_p).view(np.uint32), hi_x.view(np.uint32)
    )
    np.testing.assert_array_equal(
        np.asarray(lo_p).view(np.uint32), lo_x.view(np.uint32)
    )


def test_dfscan_prefix_is_inclusive(rng, _devices):
    """Sanity anchor independent of the twin: the last prefix equals a
    float64 row sum to double-float accuracy."""
    r = np.random.default_rng(5)
    rows, tile = 32, 256
    x = r.standard_normal((rows, tile)).astype(np.float32)
    hi, lo = pallas_dfscan.tile_df_cumsum_rows(
        jnp.asarray(x), interpret=True
    )
    total = np.asarray(hi[:, -1], np.float64) + np.asarray(
        lo[:, -1], np.float64
    )
    np.testing.assert_allclose(
        total, x.astype(np.float64).sum(axis=1), rtol=1e-12, atol=1e-10
    )
