"""Headline benchmark: particles redistributed per second per chip.

Prints ONE JSON line:
  {"metric": "particles_per_sec_per_chip", "value": N, "unit": "particles/s",
   "vs_baseline": N}

North star (BASELINE.json / BASELINE.md): >=10x particles/sec vs 8-rank CPU
MPI on the redistribute pipeline. mpi4py is not installed here (SURVEY.md
§4), so the baseline denominator is the pure-NumPy 8-rank oracle — the same
digitize -> pack -> Alltoallv-semantics exchange the MPI path runs, minus
the wire (favorable to the baseline: zero comm cost). ``vs_baseline`` is
(our aggregate particles/sec) / (8-rank CPU aggregate particles/sec); >=10
means the north star is met.

Workload: the periodic drift loop (SURVEY.md §3.3, the steady-state
redistribution workload) over a 2x2x2 Cartesian grid of subdomains with
particles genuinely crossing subdomain boundaries every step. On one chip
the 8 subdomains run as virtual ranks (vmapped slabs + on-device exchange);
with >=8 devices they run one per device with the all_to_all on the wire.
Timing uses scan-compiled loops of two lengths and differences them, which
cancels compile, dispatch and transfer overhead (the remote-tunnel TPU here
has ~100 ms fixed round-trip latency that would otherwise swamp the signal).

Env overrides: BENCH_N_LOCAL (particles per subdomain), BENCH_MIGRATION
(target per-step migration fraction, default 0.02 — a
generous rate for drift steps, which move particles well under a cell width), BENCH_S1/BENCH_S2
(loop lengths), BENCH_BASELINE_N (CPU-oracle total particles; defaults to
the device run's total so numerator and denominator price the same
population), BENCH_GRID (comma grid shape, default "2,2,2" — "4,4,4" with
the default n_local is the BASELINE north-star 64M-particle workload, run
as 64 vranks on one chip when fewer devices exist), BENCH_STRESS (0
disables the full-reshuffle stress capture appended under "stress").
"""

from __future__ import annotations

import json
import math
import os
import sys
import time

import numpy as np

GRID = tuple(
    int(x) for x in os.environ.get("BENCH_GRID", "2,2,2").split(",")
)
R = math.prod(GRID)


def _stderr(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


FILL = 0.9  # fraction of slots occupied; holes give arrival headroom


def _initial_state(n_local: int, migration: float, rng):
    """Shared slab placement (bench.common) + velocities sized so
    ~``migration`` of live rows cross a subdomain face per step (dt=1)."""
    from mpi_grid_redistribute_tpu.bench import common

    v_scale, _, _ = common.drift_sizing(GRID, n_local, FILL, migration)
    return common.uniform_state(GRID, n_local, FILL, rng, vel_scale=v_scale)


def time_device_pipeline(n_local: int, migration: float, s1: int, s2: int):
    import jax
    import jax.numpy as jnp

    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    devs = jax.devices()
    domain = Domain(0.0, 1.0, periodic=True)
    if len(devs) >= R:
        dev_grid, vgrid, n_chips = ProcessGrid(GRID), None, R
        mesh = mesh_lib.make_mesh(dev_grid, devices=devs[:R])
    else:
        dev_grid, vgrid, n_chips = (
            ProcessGrid((1, 1, 1)),
            ProcessGrid(GRID),
            1,
        )
        mesh = mesh_lib.make_mesh(dev_grid, devices=devs[:1])

    # capacity per (source, dest) pair: migrants spread over the distinct
    # face neighbors, modest headroom (spikes backlog harmlessly and retry
    # next step); budget bounds the compact on-device routing
    # (bench.common.drift_sizing is the shared sizing policy)
    from mpi_grid_redistribute_tpu.bench import common as bcommon

    _, cap, budget = bcommon.drift_sizing(GRID, n_local, FILL, migration)
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=1.0, capacity=cap,
        n_local=n_local, local_budget=budget,
    )

    rng = np.random.default_rng(0)
    pos, vel, alive = _initial_state(n_local, migration, rng)
    # transfer FLAT: any [N, 3] array crossing a program boundary (even an
    # eager reshape) materializes the tiled T(8,128) layout — 42.7x
    # padding, 32 GB at 64M particles; the migrate loop takes flat input
    pos, vel, alive = (
        jax.device_put(jnp.asarray(nbody.rows_to_planar(pos, mesh.size))),
        jax.device_put(jnp.asarray(nbody.rows_to_planar(vel, mesh.size))),
        jax.device_put(jnp.asarray(alive)),
    )

    from mpi_grid_redistribute_tpu.utils import profiling

    t0 = time.perf_counter()
    # min-of-k protocol (telemetry.regress): k independent long-loop runs
    # give per-step samples; min is the estimate, spread the noise floor
    detail, long_out = profiling.scan_time_per_step_samples(
        lambda S: nbody.make_migrate_loop(cfg, mesh, S, vgrid=vgrid),
        (pos, vel, alive),
        s1=s1,
        s2=s2,
        reps=int(os.environ.get("BENCH_REPS", 4)),
    )
    per_step = detail["min"]
    c1 = time.perf_counter() - t0  # includes both compiles
    stats = long_out[3]
    sent = np.asarray(stats.sent).sum(axis=1)
    backlog = np.asarray(stats.backlog).sum()
    dropped = np.asarray(stats.dropped_recv).sum()
    total = int(FILL * n_local) * R
    # Exchange bandwidth (the second half of the BASELINE metric): bytes
    # of migrant payload crossing the exchange per step. K fused f32
    # columns per row (pos 3 + vel 3 + alive 1). On one chip the vrank
    # exchange is HBM-side (routing gathers/scatters, no wire); with >=8
    # devices the same rows ride the ICI all_to_all.
    row_bytes = 4 * (2 * 3 + 1)
    xbytes = profiling.exchange_bytes_per_step(stats, row_bytes)
    xdomain = "ici" if n_chips > 1 else "hbm"
    _stderr(
        f"device: {n_chips} chip(s), grid {GRID}"
        + (f" as vranks {vgrid.shape}" if vgrid else "")
        + f", n/slab={n_local}, cap/pair={cap}, first compile {c1:.0f}s"
    )
    _stderr(
        f"  per-step {per_step*1e3:.2f} ms (spread "
        f"{detail['spread']*100:.1f}% over k={detail['k']}); "
        f"migration/step "
        f"{sent.mean()/total:.3%} (backlog {backlog}, dropped {dropped}); "
        f"exchange {xbytes/1e6:.2f} MB/step ({xdomain})"
    )
    if dropped:
        _stderr("  WARNING: arrivals dropped — raise slab headroom")
    # BENCH_JOURNAL_DIR=dir: journal the already-fetched stats and write
    # this process's shard for pod-wide aggregation (ISSUE 5) — zero
    # extra device reads, stats/per_step are host values at this point
    if os.environ.get("BENCH_JOURNAL_DIR"):
        from mpi_grid_redistribute_tpu import telemetry

        rec = telemetry.StepRecorder()
        telemetry.record_migrate_steps(rec, stats, rank_totals=True)
        if stats.fast_path is not None:
            telemetry.record_fast_path_steps(rec, stats)
        acc = telemetry.FlowAccumulator()
        acc.update(stats)
        telemetry.record_flow_snapshot(rec, acc)
        telemetry.HealthMonitor(rec).note_step_time(per_step)
        bcommon.write_journal_shard(rec, "bench_headline")
    return total / per_step, n_chips, xbytes, xdomain, per_step, detail


def time_cpu_oracle(n_total: int, migration: float, n_steps: int = 5,
                    native_ok: bool = False):
    """8-rank CPU oracle drift loop — the CPU-MPI stand-in.

    ``native_ok=False`` (the baseline) runs the reference-equivalent
    pipeline: NumPy digitize + stable argsort + buffer copies, i.e. what
    the mpi4py utility does minus the wire. ``native_ok=True`` uses this
    repo's own C++ host runtime — a STRONGER comparator than the
    reference, reported alongside for honesty."""
    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu import oracle

    grid = ProcessGrid(GRID)
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = n_total // R
    cap = n_local
    rng = np.random.default_rng(0)
    pos, vel, _ = _initial_state(n_local, migration, rng)
    # same FILL as the device run: keep only the live prefix per slab
    n_live = int(FILL * n_local)
    keep = np.tile(np.arange(n_local) < n_live, R)
    pos, vel = pos[keep], vel[keep]
    n_local = n_live
    count = np.full((R,), n_local, dtype=np.int32)

    def one_step(pos, vel, count):
        pos = (pos + vel * np.float32(1.0)) % np.float32(1.0)
        pos, count, (vel,), _stats = oracle.redistribute_oracle_padded(
            domain, grid, pos, count, [vel], cap, n_local,
            native_ok=native_ok,
        )
        return pos, vel, count

    pos, vel, count = one_step(pos, vel, count)  # warm
    t0 = time.perf_counter()
    for _ in range(n_steps):
        pos, vel, count = one_step(pos, vel, count)
    dt = (time.perf_counter() - t0) / n_steps
    return (R * n_local) / dt


def main() -> None:
    import jax

    from mpi_grid_redistribute_tpu.analysis import baseline as baseline_lib
    from mpi_grid_redistribute_tpu.telemetry import regress
    from mpi_grid_redistribute_tpu.utils import profiling

    on_tpu = jax.devices()[0].platform not in ("cpu",)
    n_local = int(
        os.environ.get("BENCH_N_LOCAL", 2**20 if on_tpu else 2**14)
    )
    migration = float(os.environ.get("BENCH_MIGRATION", 0.02))
    s1 = int(os.environ.get("BENCH_S1", 8))
    s2 = int(os.environ.get("BENCH_S2", 72))
    # default the CPU comparator to the DEVICE run's population, so
    # vs_baseline divides throughputs over the same workload (the old
    # fixed 2**21 silently compared different populations whenever
    # BENCH_N_LOCAL changed)
    baseline_n = int(os.environ.get("BENCH_BASELINE_N", R * n_local))

    pps, n_chips, xbytes, xdomain, per_step, detail = time_device_pipeline(
        n_local, migration, s1, s2
    )
    pps_per_chip = pps / n_chips
    _stderr(f"device pipeline: {pps:.3e} particles/s aggregate")

    cpu_pps = time_cpu_oracle(baseline_n, migration, native_ok=False)
    _stderr(
        f"8-rank CPU baseline (reference-equivalent numpy): "
        f"{cpu_pps:.3e} particles/s"
    )
    from mpi_grid_redistribute_tpu.utils import native

    native.build()  # explicit opt-in; falls back to NumPy with a log line
    cpu_native_pps = time_cpu_oracle(baseline_n, migration, native_ok=True)
    _stderr(
        f"8-rank CPU with our C++ host runtime"
        f"{'' if native.available() else ' (FALLBACK: numpy)'}: "
        f"{cpu_native_pps:.3e} particles/s"
    )

    # full-reshuffle stress capture (bench/config7_stress.py): what
    # utilization the exchange reaches when ~every row moves every step —
    # the drift loop above is compute-bound at 2% migration, so its
    # bw_util says nothing about the exchange's own roof-side headroom
    stress = None
    if os.environ.get("BENCH_STRESS", "1") != "0":
        from mpi_grid_redistribute_tpu.bench import config7_stress

        stress = config7_stress.run()

    # service soak capture (bench/config8_soak.py): sustained throughput
    # through the full service loop with the checkpoint cadence ON, plus
    # the crash/restore leg — guards soak_pps and keeps the <= 2%
    # snapshot-overhead budget honest across PRs
    soak = None
    if os.environ.get("BENCH_SOAK", "1") != "0":
        from mpi_grid_redistribute_tpu.bench import config8_soak

        soak = config8_soak.run()

    # closed-loop adaptive-rebalance capture (bench/config4_drift
    # .run_rebalance): twin drift-bias runs with the loop on/off —
    # guards rebalance_drift_ms (LOWER) so the one-shot remap keeps
    # paying for itself across PRs; CPU-only (numpy backend), so the
    # capture is deterministic modulo host timing noise
    rebalance = None
    if os.environ.get("BENCH_REBALANCE", "1") != "0":
        from mpi_grid_redistribute_tpu.bench import config4_drift

        rebalance = config4_drift.run_rebalance()

    # resident chunked-stepping capture (bench/config10_service.py):
    # service-mode pps with lax.scan macro-steps vs the eager per-step
    # loop — guards service_pps so the chunk path keeps paying for the
    # host syncs it removed, and pipeline_pps (the software-pipelined
    # scan body at the same chunk) so the overlapped schedule keeps its
    # edge over the sequential body; runs in its own subprocess so the
    # vrank topology is measured even under the 8-device forcing above
    service = None
    if os.environ.get("BENCH_SERVICE", "1") != "0":
        from mpi_grid_redistribute_tpu.bench import config10_service

        service = config10_service.run()

    # hierarchical two-level wire capture (bench/config4_drift
    # .hierarchical_wire_capture, ISSUE 19): the same ~2% drift workload
    # through the two-level engine on a virtual 2x(1,2,2)-pod split —
    # the per-domain schedule split lands top-level so regress.py's
    # auto-armed LOWER gates (exchange_dcn_bytes_per_step /
    # exchange_ici_bytes_per_step) read it from this capture too
    hier = None
    if os.environ.get("BENCH_HIER", "1") != "0":
        from mpi_grid_redistribute_tpu.bench import config4_drift

        hier = config4_drift.hierarchical_wire_capture(
            (2, 2, 2), (2, 1, 1), migration
        )

    print(
        json.dumps(
            {
                "metric": "particles_per_sec_per_chip",
                "value": round(pps_per_chip, 2),
                "unit": "particles/s",
                "vs_baseline": round(pps / cpu_pps, 3),
                "vs_our_native_cpu": round(pps / cpu_native_pps, 3),
                # comparator provenance: the population both CPU rates
                # timed, and the rates themselves, so vs_* is reproducible
                # from the capture alone
                "baseline_n": baseline_n,
                "cpu_pps": round(cpu_pps, 2),
                "cpu_native_pps": round(cpu_native_pps, 2),
                "ms_per_step": round(per_step * 1e3, 3),
                # min-of-k noise floor: (max-min)/min over k long-loop
                # runs (telemetry.regress protocol) — a capture whose
                # spread rivals the 10% regression threshold is suspect
                "timing_spread": round(detail["spread"], 4),
                "timing_k": detail["k"],
                # BASELINE metric's second half: exchange bandwidth. On a
                # single chip the vrank exchange never leaves HBM
                # (exchange_domain = "hbm"); on >=8 chips the same rows
                # ride the ICI all_to_all (= "ici").
                "exchange_bytes_per_step": round(xbytes, 1),
                "exchange_bytes_per_sec": round(xbytes / per_step, 1),
                "exchange_domain": xdomain,
                # Utilization = bytes/s vs the domain's peak (HBM 819 GB/s
                # on one chip; 4x45 GB/s summed ICI links per chip on >=8).
                # Low by design at the default 2% migration rate: the
                # exchange moves only migrant payload, so the step is
                # compute-bound (see knockout roofline, BENCH_CONFIGS.md).
                "exchange_bw_util": round(
                    profiling.exchange_bw_util(
                        xbytes / per_step, xdomain, n_chips
                    ),
                    6,
                ),
                "stress": stress,
                "soak": soak,
                "rebalance": rebalance,
                "service": service,
                "hier": hier,
                "exchange_dcn_bytes_per_step": (
                    hier.get("dcn_bytes_per_step") if hier else None
                ),
                "exchange_ici_bytes_per_step": (
                    hier.get("ici_bytes_per_step") if hier else None
                ),
                # environment fingerprint (telemetry.regress): the
                # classifier flags cross-capture deltas whose machine
                # changed out from under them
                "env": regress.env_fingerprint(),
                # progcheck static wire-model hash (analysis.baseline):
                # lets bench_check tell a perf delta that coincides with
                # an intentional wire/footprint change from one that
                # doesn't (see classify_capture's drift note)
                "progprofile_hash": baseline_lib.progprofile_hash(),
                # attribution snapshot hash (ISSUE 14): same idea for
                # the committed phase-table/roofline snapshot — a perf
                # delta that lands with a refreshed attribution is a
                # re-measured pipeline, not silent drift
                "attribution_hash": baseline_lib.attribution_hash(),
            }
        )
    )


if __name__ == "__main__":
    main()
