"""Headline benchmark: particles redistributed per second per chip.

Prints ONE JSON line:
  {"metric": "particles_per_sec_per_chip", "value": N, "unit": "particles/s",
   "vs_baseline": N}

North star (BASELINE.json / BASELINE.md): >=10x particles/sec vs 8-rank CPU
MPI on the redistribute pipeline. mpi4py is not installed here (SURVEY.md §4),
so the baseline denominator is the pure-NumPy 8-rank oracle — the same
digitize -> histogram -> argsort pack -> Alltoallv-semantics exchange the MPI
path runs, minus the wire (favorable to the baseline: zero comm cost).
``vs_baseline`` is therefore (our aggregate particles/sec) / (8-rank CPU
aggregate particles/sec); >=10 means the north star is met.

Shape of the timed run: the fused periodic drift step (drift + wrap + bin +
pack + all_to_all + compact — SURVEY.md §3.3, the steady-state workload) on
a 2x2x2 mesh when >=8 devices are visible, else on the single available chip.

Env overrides: BENCH_N_LOCAL (particles per chip), BENCH_STEPS (timed steps),
BENCH_BASELINE_N (CPU-oracle particle count).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _stderr(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def time_device_pipeline(devs, n_local_per_chip: int, n_steps: int):
    import jax

    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

    if len(devs) >= 8:
        shape = (2, 2, 2)
    else:
        shape = (1, 1, 1)
    grid = ProcessGrid(shape)
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    mesh = mesh_lib.make_mesh(grid, devices=devs[:R])
    cfg = nbody.DriftConfig(
        domain=domain,
        grid=grid,
        dt=0.01,
        capacity=max(1, n_local_per_chip // max(1, R)),
        n_local=n_local_per_chip,
    )
    step = nbody.make_drift_step(cfg, mesh)

    rng = np.random.default_rng(0)
    n = R * n_local_per_chip
    pos = rng.random((n, 3), dtype=np.float32)
    vel = (0.2 * (rng.random((n, 3), dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    count = np.full((R,), n_local_per_chip, dtype=np.int32)

    t0 = time.perf_counter()
    out = step(pos, vel, count)
    jax.block_until_ready(out)
    _stderr(f"compile+first step: {time.perf_counter() - t0:.1f}s")
    pos_d, vel_d, count_d = out[0], out[1], out[2]

    t0 = time.perf_counter()
    for _ in range(n_steps):
        pos_d, vel_d, count_d, _stats = step(pos_d, vel_d, count_d)
    jax.block_until_ready((pos_d, vel_d, count_d))
    dt = (time.perf_counter() - t0) / n_steps
    total_particles = R * n_local_per_chip
    return total_particles / dt, R, dt


def time_cpu_oracle(n_total: int, n_steps: int):
    """8-rank pure-NumPy oracle: the CPU-MPI stand-in (no wire cost)."""
    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu import oracle

    grid = ProcessGrid((2, 2, 2))
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    n_local = n_total // R
    cap = max(1, n_local // R)
    rng = np.random.default_rng(0)
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    vel = 0.2 * (rng.random((R * n_local, 3), dtype=np.float32) - 0.5)
    count = np.full((R,), n_local, dtype=np.int32)
    dt_drift = np.float32(0.01)

    def one_step(pos, vel, count):
        pos = (pos + vel * dt_drift) % np.float32(1.0)
        pos, count, (vel,), _stats = oracle.redistribute_oracle_padded(
            domain, grid, pos, count, [vel], cap, n_local
        )
        return pos, vel, count

    pos, vel, count = one_step(pos, vel, count)  # warm caches
    t0 = time.perf_counter()
    for _ in range(n_steps):
        pos, vel, count = one_step(pos, vel, count)
    dt = (time.perf_counter() - t0) / n_steps
    return (R * n_local) / dt


def main() -> None:
    import jax

    devs = jax.devices()
    platform = devs[0].platform
    on_tpu = platform not in ("cpu",)
    n_local = int(
        os.environ.get("BENCH_N_LOCAL", 2**22 if on_tpu else 2**16)
    )
    n_steps = int(os.environ.get("BENCH_STEPS", 10))
    baseline_n = int(os.environ.get("BENCH_BASELINE_N", 2**21))

    _stderr(
        f"devices: {len(devs)} x {platform}; n_local/chip={n_local}, "
        f"steps={n_steps}"
    )
    pps, n_chips, step_dt = time_device_pipeline(devs, n_local, n_steps)
    pps_per_chip = pps / n_chips
    _stderr(
        f"device pipeline: {pps:.3e} particles/s aggregate on {n_chips} "
        f"chip(s) ({step_dt*1e3:.2f} ms/step)"
    )

    cpu_pps = time_cpu_oracle(baseline_n, max(2, n_steps // 3))
    _stderr(f"8-rank CPU oracle baseline: {cpu_pps:.3e} particles/s")

    print(
        json.dumps(
            {
                "metric": "particles_per_sec_per_chip",
                "value": round(pps_per_chip, 2),
                "unit": "particles/s",
                "vs_baseline": round(pps / cpu_pps, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
