#!/usr/bin/env python
"""Run index: every store run + bench capture, one trajectory view.

The repo accumulates two kinds of durable run evidence: committed
``BENCH_r*.json`` captures (the regression-guard history ``bench_check``
compares against) and ``telemetry.store`` journal-store roots (what a
service driver started with ``--store-dir`` leaves behind). This script
indexes both into one run-index, renders the perf trajectory across
bench revisions, and feeds the whole indexed history into
``regress.classify_capture`` so a fresh capture is judged against
*every* usable run, not just whichever files a caller remembered to
pass.

Modes:

  # human view: trajectory table + sparkline + indexed store runs
  python scripts/history.py

  # machine view: the full index as JSON (tooling / grid_top feeds)
  python scripts/history.py --json

  # regression gate with cross-run context: classify one capture
  # against the indexed history (exit 1 on REGRESSION)
  python scripts/history.py --check capture.json

``--bench GLOB`` and ``--stores DIR`` override where captures and
store roots are discovered (defaults: ``BENCH_r*.json`` next to the
repo root, no store scan unless ``--stores`` is given).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_REPO = os.path.dirname(os.path.abspath(os.path.dirname(__file__)))
_SPARK = "▁▂▃▄▅▆▇█"


def _load(path):
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def index_benches(patterns):
    """Index bench captures: one entry per readable ``BENCH_r*.json``
    (revision number parsed from the filename, guarded metrics via
    ``regress.extract_metrics``), ordered by revision."""
    from mpi_grid_redistribute_tpu.telemetry import regress

    entries = []
    for pattern in patterns:
        for path in sorted(glob.glob(pattern)):
            try:
                doc = _load(path)
            except (OSError, ValueError) as e:
                entries.append(
                    {"path": path, "error": str(e), "metrics": None}
                )
                continue
            m = re.search(r"r(\d+)", os.path.basename(path))
            parsed = doc.get("parsed", doc) if isinstance(doc, dict) else {}
            entries.append(
                {
                    "path": path,
                    "rev": int(m.group(1)) if m else None,
                    "metrics": regress.extract_metrics(doc),
                    "spread": regress._spread_of(doc),
                    "platform": (
                        (regress._env_of(doc) or {}).get("platform")
                    ),
                    "config": parsed.get("config")
                    if isinstance(parsed, dict)
                    else None,
                    "doc": doc,
                }
            )
    entries.sort(key=lambda e: (e.get("rev") is None, e.get("rev"), e["path"]))
    return entries


def index_stores(root):
    """Index journal-store runs under ``root``: writer, span, exact
    event totals and the merged-store p99 per run, newest first."""
    from mpi_grid_redistribute_tpu.telemetry import store as store_lib

    entries = []
    for store_root in store_lib.list_stores(root):
        try:
            reader = store_lib.StoreReader(store_root)
        except store_lib.StoreCorruptError as e:
            entries.append({"root": store_root, "error": str(e)})
            continue
        man = reader.manifest
        counts = reader.counts()
        h = reader.latency_histogram()
        entries.append(
            {
                "root": store_root,
                "writer": man.get("writer"),
                "created": man.get("created"),
                "updated": man.get("updated"),
                "events_total": sum(counts.values()),
                "steps": counts.get("step_latency", 0),
                "p99_s": h.quantile(0.99) if h.count else None,
                "segments": len(man.get("segments", [])),
                "retired": man.get("retired", {}).get("segments", 0),
                "bytes": sum(s["bytes"] for s in man.get("segments", []))
                + (man.get("active") or {}).get("bytes", 0),
            }
        )
    return entries


def build_index(bench_patterns, stores_root=None):
    benches = index_benches(bench_patterns)
    index = {
        "benches": [
            {k: v for k, v in e.items() if k != "doc"} for e in benches
        ],
        "stores": index_stores(stores_root) if stores_root else [],
    }
    return index, benches


def sparkline(values):
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        else:
            out.append(_SPARK[int((v - lo) / span * (len(_SPARK) - 1))])
    return "".join(out)


def render_trajectory(benches, stores):
    """Human view: the headline metric across revisions plus each
    indexed store run."""
    lines = ["run history"]
    usable = [b for b in benches if b.get("metrics")]
    if usable:
        values = [b["metrics"].get("value") for b in usable]
        lines.append(
            "  bench trajectory (value = particles/sec/chip)   "
            + sparkline(values)
        )
        best = max(v for v in values if v is not None)
        for b in usable:
            v = b["metrics"].get("value")
            ms = b["metrics"].get("ms_per_step")
            rel = f"{v / best * 100:5.1f}% of best" if v else ""
            lines.append(
                f"    r{b['rev']:02d}  value={v:.4g}"
                + (f"  ms_per_step={ms:.4g}" if ms else "")
                + (f"  [{b['platform']}]" if b.get("platform") else "")
                + f"  {rel}"
            )
    else:
        lines.append("  (no usable bench captures)")
    bad = [b for b in benches if b.get("error")]
    for b in bad:
        lines.append(f"    unreadable: {b['path']}: {b['error']}")
    if stores:
        lines.append("  store runs (newest first)")
        for s in stores:
            if s.get("error"):
                lines.append(f"    corrupt: {s['root']}: {s['error']}")
                continue
            writer = s.get("writer") or {}
            p99 = s.get("p99_s")
            lines.append(
                f"    {s['root']}  steps={s['steps']}"
                f"  events={s['events_total']}"
                + (f"  p99={p99:.4g}s" if p99 is not None else "")
                + f"  segs={s['segments']}(+{s['retired']})"
                + (
                    f"  writer={writer.get('host')}:{writer.get('pid')}"
                    if writer
                    else ""
                )
            )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Index bench captures + journal-store runs; render "
        "the perf trajectory or gate a capture against it."
    )
    p.add_argument(
        "--bench",
        action="append",
        default=[],
        metavar="GLOB",
        help="bench capture glob (default: BENCH_r*.json at the repo "
        "root; repeatable)",
    )
    p.add_argument(
        "--stores",
        metavar="DIR",
        help="directory to scan for journal-store roots (each child "
        "with a MANIFEST.json is one run)",
    )
    p.add_argument("--json", action="store_true",
                   help="print the run-index as JSON and exit")
    p.add_argument(
        "--check",
        metavar="CAPTURE",
        help="classify CAPTURE (a bench JSON line or BENCH wrapper) "
        "against the indexed history via regress.classify_capture; "
        "exit 1 on REGRESSION",
    )
    p.add_argument("--threshold", type=float, default=0.10,
                   help="regression threshold for --check")
    args = p.parse_args(argv)

    patterns = args.bench or [os.path.join(_REPO, "BENCH_r*.json")]
    index, benches = build_index(patterns, args.stores)

    if args.check:
        from mpi_grid_redistribute_tpu.telemetry import regress

        try:
            current = _load(args.check)
        except (OSError, ValueError) as e:
            print(f"history: cannot read capture: {e}", file=sys.stderr)
            return 1
        history = [b["doc"] for b in benches if b.get("metrics")]
        ok, lines, _labels = regress.classify_capture(
            current, history, threshold=args.threshold
        )
        print(f"history: capture vs {len(history)} indexed runs")
        for ln in lines:
            print("  " + ln)
        return 0 if ok else 1

    if args.json:
        json.dump(index, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
        return 0

    sys.stdout.write(render_trajectory(benches, index["stores"]))
    return 0


if __name__ == "__main__":
    sys.exit(main())
