"""Probe: does Pallas lower on this platform, and how fast is a
row-scatter kernel vs XLA's scatter?

Kernel: out[targets[j]] = rows[j] for presorted targets; the output
streams through VMEM in row blocks and each block overlays its arrivals
(a contiguous range of the sorted targets, located by precomputed
per-block starts) with VMEM row stores.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def make_scatter(n_rows, k, p, block, interpret=False):
    """out[t] = rows[j] for t = targets[j], targets sorted ascending,
    out-of-range (>= n_rows) sentinels at the tail."""
    assert n_rows % block == 0
    nblocks = n_rows // block

    def kernel(starts_ref, targets_ref, rows_ref, in_ref, out_ref):
        b = pl.program_id(0)
        out_ref[:] = in_ref[:]
        start = starts_ref[b]
        end = starts_ref[b + 1]
        base = b * block

        def row_body(j, _):
            t = targets_ref[j, 0] - base
            out_ref[pl.ds(t, 1), :] = rows_ref[pl.ds(j, 1), :]
            return _

        jax.lax.fori_loop(start, end, row_body, None)

    def fn(flat, starts, targets, rows):
        return pl.pallas_call(
            kernel,
            grid=(nblocks,),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),  # starts [nb+1]
                pl.BlockSpec(memory_space=pltpu.VMEM),  # targets [p, 1]
                pl.BlockSpec(memory_space=pltpu.VMEM),  # rows [p, k]
                pl.BlockSpec((block, k), lambda b: (b, 0),
                             memory_space=pltpu.VMEM),  # flat block
            ],
            out_specs=pl.BlockSpec((block, k), lambda b: (b, 0),
                                   memory_space=pltpu.VMEM),
            out_shape=jax.ShapeDtypeStruct((n_rows, k), jnp.float32),
            interpret=interpret,
        )(starts, targets[:, None], rows, flat)

    return fn


def main():
    interpret = os.environ.get("PALLAS_INTERPRET", "") == "1"
    n_rows = 8 * (1 << 20)
    k = 7
    p = 196608
    block = 8192
    rng = np.random.default_rng(0)
    flat = jnp.asarray(rng.random((n_rows, k), dtype=np.float32))
    targets = rng.choice(n_rows, size=p, replace=False).astype(np.int32)
    rows = rng.random((p, k), dtype=np.float32)

    ts = np.sort(targets)
    order = np.argsort(targets, kind="stable")
    rows_sorted = jnp.asarray(rows[order])
    starts = np.searchsorted(
        ts, np.arange(0, n_rows + block, block)
    ).astype(np.int32)
    ts_j = jnp.asarray(ts)
    starts_j = jnp.asarray(starts)

    fn = jax.jit(make_scatter(n_rows, k, p, block, interpret=interpret))

    out = fn(flat, starts_j, ts_j, rows_sorted)
    out_np = np.asarray(out)
    want = np.asarray(flat).copy()
    want[ts] = np.asarray(rows_sorted)
    print("correct:", np.array_equal(out_np, want))

    from mpi_grid_redistribute_tpu.utils import profiling

    def make_loop(S):
        @jax.jit
        def loop(flat, starts, targets, rows):
            def body(f, _):
                return fn(f, starts, targets, rows), ()
            f, _ = lax.scan(body, flat, None, length=S)
            return f
        return loop

    per, _, _ = profiling.scan_time_per_step(
        make_loop, (flat, starts_j, ts_j, rows_sorted), s1=4, s2=24
    )
    print(f"pallas scatter: {per*1e3:.2f} ms for {p} rows into "
          f"[{n_rows},{k}]")

    def make_xla_loop(S):
        @jax.jit
        def loop(flat, targets, rows):
            def body(f, _):
                return f.at[targets].set(rows, mode="drop"), ()
            f, _ = lax.scan(body, flat, None, length=S)
            return f
        return loop

    per_x, _, _ = profiling.scan_time_per_step(
        make_xla_loop, (flat, ts_j, rows_sorted), s1=4, s2=24
    )
    print(f"xla scatter:    {per_x*1e3:.2f} ms")


if __name__ == "__main__":
    main()
