"""Time ops.pallas_scatter vs XLA's row scatter on the current device."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.ops import pallas_scatter as ps
from mpi_grid_redistribute_tpu.utils import profiling


def main():
    n_rows = int(os.environ.get("N_ROWS", 8 * (1 << 20)))
    p = int(os.environ.get("P", 196608))
    k = 7
    rng = np.random.default_rng(0)
    flat = jnp.asarray(rng.random((n_rows, k), dtype=np.float32))
    targets = jnp.asarray(
        rng.choice(n_rows, size=p, replace=False).astype(np.int32)
    )
    rows = jnp.asarray(rng.random((p, k), dtype=np.float32))

    out = ps.scatter_rows(flat, targets, rows)
    want = flat.at[targets].set(rows, mode="drop")
    print("correct:", bool(jnp.array_equal(out, want)))

    for name, impl in (
        ("pallas", lambda f, t, r: ps.scatter_rows(f, t, r)),
        ("xla", lambda f, t, r: f.at[t].set(r, mode="drop")),
    ):
        def make_loop(S, impl=impl):
            @jax.jit
            def loop(flat, targets, rows):
                def body(f, _):
                    return impl(f, targets, rows), ()
                f, _ = lax.scan(body, flat, None, length=S)
                return f
            return loop

        per, _, _ = profiling.scan_time_per_step(
            make_loop, (flat, targets, rows), s1=4, s2=24
        )
        print(f"{name}: {per*1e3:.2f} ms for {p} rows into [{n_rows},{k}]")


if __name__ == "__main__":
    main()
