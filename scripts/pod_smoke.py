"""Pod-readiness smoke test: first thing to run on a REAL multi-chip slice.

RISK NOTE (round-2 verdict, missing item 6): in the build environment only
ONE physical TPU chip is reachable, so ``lax.all_to_all`` / ``ppermute``
have NEVER executed on real ICI here — every multi-device proof ran on
XLA's virtual CPU mesh (tests/conftest.py, ``dryrun_multichip``) or as the
single-device vrank transpose twin (bit-identical semantics, HBM-side).
SURVEY.md §7.6 named "all_to_all lowers and runs on >= 2 real chips" the
first smoke test on real hardware; THIS script is that test. On a v5e-8 /
v5e-16 / pod slice:

    python scripts/pod_smoke.py

It will, over all visible real devices:
  1. build the near-cubic Cartesian mesh;
  2. run the canonical shard_map redistribute (counts + payload
     ``lax.all_to_all`` on the wire) and assert conservation + ownership;
  2b. run the PUBLIC API (``GridRedistribute.redistribute()``, which
     routes the round-4 planar shard_map engine) with an int32 id field
     and assert bit-exact id conservation — this exercises the int32
     transport (the denormal-flush fix) on real ICI;
  3. run S steps of the migrate drift loop (receiver-granted all_to_all)
     and assert conservation, zero drops, and no stall;
  4. run one auto-sized halo exchange (``ppermute``) and assert zero
     overflow, then the PLANAR halo twin and assert identical ghost
     counts;
  5. print per-step wall timings (scan-differenced) for the migrate loop
     so the first real-ICI numbers land next to the single-chip ones in
     BENCH_CONFIGS.md.

With one device it degrades to the single-rank grid and says so — still a
useful sanity check that the script itself runs.

``--kill-restore`` runs a different, standalone leg (ISSUE 6 acceptance):
SIGKILL the service driver mid-run after >= 2 committed snapshots, resume
it from the latest valid snapshot in a fresh process, and byte-compare
the final state against an uninterrupted run of the same config — the
kill-anywhere/restore-bit-identical contract of `service/driver.py` on
real subprocesses (CPU mesh; the TPU smoke above is untouched).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main(journal_dir: str = None) -> None:
    # honor a forced virtual CPU mesh (same trick as __graft_entry__ /
    # tests/conftest.py): the baked sitecustomize pins the axon TPU
    # platform, hiding --xla_force_host_platform_device_count devices
    if "xla_force_host_platform_device_count" in os.environ.get(
        "XLA_FLAGS", ""
    ) and os.environ.get("JAX_PLATFORMS") == "cpu":
        import jax

        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass
    import jax
    import jax.numpy as jnp

    from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.ops import binning
    from mpi_grid_redistribute_tpu import oracle
    from mpi_grid_redistribute_tpu.parallel import (
        exchange, halo as halo_lib, mesh as mesh_lib,
    )
    from mpi_grid_redistribute_tpu.utils import profiling, stats as stats_lib

    devs = jax.devices()
    R = len(devs)
    print(f"devices: {R} x {devs[0].platform}", flush=True)
    if R == 1:
        print(
            "WARNING: single device — the collectives below compile away; "
            "this run only sanity-checks the script itself. Run on a "
            ">= 2-chip slice for the real smoke.",
            flush=True,
        )
    shape = mesh_lib.near_cubic_shape(R, 3)
    grid = ProcessGrid(shape)
    domain = Domain(0.0, 1.0, periodic=True)
    mesh = mesh_lib.make_mesh(grid, devices=devs[:R])
    print(f"mesh: {shape}", flush=True)

    n_local = 1 << 16
    rng = np.random.default_rng(0)
    n = R * n_local
    pos = rng.random((n, 3), dtype=np.float32)
    count = np.full((R,), n_local, np.int32)

    # --- 1/2: canonical all_to_all exchange on the wire ---------------
    cap = int(n_local * 1.5 / R) + 64
    out_cap = 2 * n_local
    xfn = exchange.build_redistribute(
        mesh, domain, grid, cap, out_cap, n_fields=0
    )
    pos_out, count_out, st = xfn(jnp.asarray(pos), jnp.asarray(count))
    jax.block_until_ready(pos_out)
    kept = int(np.asarray(count_out).sum())
    dropped = int(np.asarray(st.dropped_send).sum()) + int(
        np.asarray(st.dropped_recv).sum()
    )
    assert kept + dropped == n, (kept, dropped, n)
    assert dropped == 0, f"dropped {dropped}: raise cap/out_cap"
    shards = [
        np.asarray(pos_out)[r * out_cap : r * out_cap + np.asarray(count_out)[r]]
        for r in range(R)
    ]
    oracle.assert_ownership(domain, grid, shards)
    print(
        f"canonical all_to_all: OK ({kept} rows conserved, ownership "
        f"verified)", flush=True,
    )

    # --- 2b: the public API -> planar shard_map engine, with a bitcast
    # int32 id payload (the round-4 denormal-flush regression on the
    # actual wire: ids < 2^23 are denormal f32 patterns) --------------
    from mpi_grid_redistribute_tpu import GridRedistribute

    ids = np.arange(n, dtype=np.int32)
    rd = GridRedistribute(
        domain, grid, mesh=mesh, capacity=cap, out_capacity=out_cap,
        on_overflow="ignore",
    )
    res = rd.redistribute(pos, ids, count=count)
    jax.block_until_ready(res.positions)
    assert int(np.asarray(res.stats.dropped_send).sum()) == 0
    assert int(np.asarray(res.stats.dropped_recv).sum()) == 0
    cnt_api = np.asarray(res.count)
    got_ids = np.concatenate(
        [
            np.asarray(res.fields[0])[r * out_cap : r * out_cap + cnt_api[r]]
            for r in range(R)
        ]
    )
    assert np.array_equal(np.sort(got_ids), ids), (
        "planar API path corrupted int32 ids on the wire"
    )
    # and byte-identical routing vs the raw row-major engine above
    assert np.array_equal(cnt_api, np.asarray(count_out))
    print(
        "public API (planar engine): OK (int32 ids bit-exact across "
        "the wire)", flush=True,
    )

    # --- 3: migrate drift loop over ICI -------------------------------
    fill, migration, S = 0.9, 0.02, 16
    from mpi_grid_redistribute_tpu.bench import common as bcommon

    v_scale, mcap, budget = bcommon.drift_sizing(
        shape, n_local, fill, migration
    )
    p0, v0, alive = bcommon.uniform_state(
        shape, n_local, fill, rng, vel_scale=v_scale
    )
    cfg = nbody.DriftConfig(
        domain=domain, grid=grid, dt=1.0, capacity=mcap,
        n_local=n_local, local_budget=budget,
    )
    per_step, _, long_out = profiling.scan_time_per_step(
        lambda S_: nbody.make_migrate_loop(cfg, mesh, S_),
        (
            jnp.asarray(nbody.rows_to_planar(p0, mesh.size)),
            jnp.asarray(nbody.rows_to_planar(v0, mesh.size)),
            jnp.asarray(alive),
        ),
        s1=4, s2=S,
    )
    mstats = jax.tree.map(np.asarray, long_out[3])
    stats_lib.check_no_loss(mstats)
    stall = stats_lib.detect_stall(mstats)
    assert not stall["stalled"], stall
    total = int(fill * n_local) * R
    assert int(np.asarray(long_out[2]).sum()) == total
    print(
        f"migrate loop: OK ({per_step*1e3:.2f} ms/step, "
        f"{total/per_step/R/1e6:.1f}M pps/chip, backlog "
        f"{stall['backlog_final']})", flush=True,
    )

    # --- 3b: multi-host journal sharding + pod-wide aggregation --------
    # On a real pod every process journals its own shard; here each rank
    # of the mesh plays one "host" (its slice of the [S, R] stats) and
    # the merge must reconstruct the pod totals exactly — the
    # merge-equals-sum contract of telemetry/aggregate.py. Shards only
    # hit disk with --journal-dir; the aggregation check always runs.
    from mpi_grid_redistribute_tpu import telemetry

    shards = []
    for r in range(R):
        rec = telemetry.StepRecorder(host=f"host{r:02d}", pid=1000 + r)
        for s in range(mstats.sent.shape[0]):
            rec.record(
                "migrate_step",
                step=s,
                sent=int(mstats.sent[s, r]),
                received=int(mstats.received[s, r]),
                backlog=int(mstats.backlog[s, r]),
                dropped_recv=int(mstats.dropped_recv[s, r]),
                population=int(mstats.population[s, r]),
            )
        shards.append(rec)
    if journal_dir:
        os.makedirs(journal_dir, exist_ok=True)
        paths = []
        for rec in shards:
            path = os.path.join(
                journal_dir, f"pod_smoke.{rec.host}.{rec.pid}.jsonl"
            )
            rec.to_jsonl(path)
            paths.append(path)
        merged = telemetry.merge_journals(paths)
    else:
        merged = telemetry.merge_journals(shards)
    # aggregate counters == sum of per-shard counters
    want = {"migrate_step": R * int(mstats.sent.shape[0])}
    assert merged.counts() == want, (merged.counts(), want)
    assert merged.counts() == {
        k: sum(c.get(k, 0) for c in merged.per_shard_counts().values())
        for k in merged.counts()
    }
    # pod-wide per-step sums == direct sums over the stats pytree
    pod_rec = merged.to_recorder(pod_steps=True)
    pod_sent = sum(
        e.data["sent"] for e in pod_rec.events("migrate_step")
    )
    assert pod_sent == int(mstats.sent.sum()), (
        pod_sent, int(mstats.sent.sum())
    )
    pstats = merged.pod_stats()
    assert int(pstats.population.sum()) == int(mstats.population.sum())
    # the scrapable projection agrees with the recorder's exact counts
    reg = telemetry.from_journal(merged)
    fam = reg.get("grid_journal_events")
    scraped = {
        labels[0]: child.value for labels, child in fam.children()
    }
    assert scraped == merged.counts(), (scraped, merged.counts())
    print(
        f"journal aggregation: OK ({R} shards, "
        f"{len(merged)} events merged"
        + (f", shards in {journal_dir}" if journal_dir else "")
        + ")", flush=True,
    )

    # --- 4: halo exchange (ppermute) -----------------------------------
    hw = 0.25 * min(grid.cell_widths(domain))
    hx = halo_lib.build_halo_exchange(mesh, domain, grid, hw)
    hres = hx(pos_out, count_out)
    jax.block_until_ready(hres.ghost_positions)
    assert int(np.asarray(hres.overflow).sum()) == 0
    g = int(np.asarray(hres.ghost_count).sum())
    assert (g > 0) or (R == 1 and not any(
        s > 1 for s in shape
    )), "no ghosts on a decomposed mesh"
    print(f"halo exchange: OK ({g} ghosts, zero overflow)", flush=True)

    # --- 4b: the PLANAR halo twin (the shipped fast engine) ------------
    pc, gc = halo_lib.default_capacities(domain, grid, hw, out_cap)
    hp = halo_lib.build_halo_planar(mesh, domain, grid, hw, pc, gc)
    fused_g = jnp.transpose(
        jnp.asarray(pos_out).reshape(R, out_cap, 3), (2, 0, 1)
    ).reshape(3, R * out_cap)
    ghost_p, gcount_p, over_p = hp(fused_g, count_out)
    jax.block_until_ready(ghost_p)
    assert int(np.asarray(over_p).sum()) == 0
    assert np.array_equal(
        np.asarray(gcount_p), np.asarray(hres.ghost_count)
    ), "planar halo ghost counts differ from the row-major engine"
    print(
        f"planar halo: OK ({int(np.asarray(gcount_p).sum())} ghosts, "
        f"counts identical to the row-major engine)", flush=True,
    )

    # --- 5: fused deposit, MXU kernel vs double-float scan engine ------
    # (the late-round-4 throughput engine: ops/pallas_segdep.py; first
    # real-ICI run must prove the SHIPPED engines, so run both and
    # cross-check)
    rhos = {}
    for method in ("mxu", "scan"):
        dcfg = nbody.DriftConfig(
            domain=domain, grid=grid, dt=1.0, capacity=mcap,
            n_local=n_local, local_budget=budget,
            deposit_shape=(32,) * domain.ndim, deposit_method=method,
        )
        dloop = nbody.make_migrate_loop(
            dcfg, mesh, 2, deposit_each_step=True
        )
        dout = jax.tree.map(
            np.asarray,
            dloop(
                jnp.asarray(nbody.rows_to_planar(p0, mesh.size)),
                jnp.asarray(nbody.rows_to_planar(v0, mesh.size)),
                jnp.asarray(alive),
            ),
        )
        rho = dout[-1]
        live = dout[2].sum()
        assert abs(rho.sum() - live) / live < 1e-4, (
            method, rho.sum(), live,
        )
        rhos[method] = rho
    np.testing.assert_allclose(
        rhos["mxu"], rhos["scan"], rtol=2e-5, atol=2e-5,
        err_msg="MXU deposit kernel disagrees with the scan engine",
    )
    print(
        "fused deposit (mxu + scan engines): OK (mass conserved, "
        "engines agree)", flush=True,
    )

    # --- 5b: vrank (slab-keyed) deposit on top of the real mesh -------
    # (the production config-5 engine when devices are oversubscribed:
    # per-slab sorts + chunk-monotone segdep stream + residence guard —
    # deposit.cic_deposit_vranks_mxu; same particles, same physics, so
    # the density must agree with the flat engines above)
    vgrid = ProcessGrid((2, 1, 1))
    V = vgrid.nranks
    if n_local % V == 0 and all(
        (32 // s) % v == 0 for s, v in zip(shape, vgrid.shape)
    ):
        # slab-LEGAL start: each (device, vrank) slab's rows inside its
        # own full-grid region (reusing the flat p0 would start ~half of
        # every device's rows on the wrong SLAB — a migration burst the
        # 2%-sized capacities are not meant for)
        n_slab = n_local // V
        vshape = tuple(d * v for d, v in zip(shape, vgrid.shape))
        pv = np.empty((R * n_local, 3), np.float32)
        i = 0
        for d in range(R):
            dc = grid.cell_of_rank(d)
            for v in range(V):
                vc = vgrid.cell_of_rank(v)
                cell = np.asarray([
                    dc[a] * vgrid.shape[a] + vc[a] for a in range(3)
                ])
                lo = cell / np.asarray(vshape)
                pv[i : i + n_slab] = (
                    lo + rng.random((n_slab, 3)) / np.asarray(vshape)
                ).astype(np.float32)
                i += n_slab
        vscale2, mcap2, budget2 = bcommon.drift_sizing(
            vshape, n_slab, fill, migration
        )
        vv = ((rng.random((R * n_local, 3)) - 0.5) * 2 * vscale2).astype(
            np.float32
        )
        valive = rng.random(R * n_local) < fill
        vrhos = {}
        for method in ("mxu", "scan"):
            vcfg = nbody.DriftConfig(
                domain=domain, grid=grid, dt=1.0, capacity=mcap2,
                n_local=n_slab, local_budget=budget2,
                deposit_shape=(32,) * domain.ndim,
                deposit_method=method,
            )
            vdloop = nbody.make_migrate_loop(
                vcfg, mesh, 2, vgrid=vgrid, deposit_each_step=True
            )
            vdout = jax.tree.map(
                np.asarray,
                vdloop(
                    jnp.asarray(nbody.rows_to_planar(pv, mesh.size)),
                    jnp.asarray(nbody.rows_to_planar(vv, mesh.size)),
                    jnp.asarray(valive),
                ),
            )
            stats_lib.check_no_loss(jax.tree.map(np.asarray, vdout[3]))
            vrho = vdout[-1]
            vlive = vdout[2].sum()
            assert abs(vrho.sum() - vlive) / vlive < 1e-4, (
                method, vrho.sum(), vlive,
            )
            vrhos[method] = vrho
        np.testing.assert_allclose(
            vrhos["mxu"], vrhos["scan"], rtol=2e-5, atol=2e-5,
            err_msg="slab-keyed vrank deposit disagrees with the scan "
            "engine",
        )
        print(
            f"slab-keyed vrank deposit (V={V}): OK (mass conserved, "
            "agrees with the scan engine)", flush=True,
        )
    else:
        print(
            f"slab-keyed vrank deposit: SKIPPED (mesh {shape} does not "
            f"divide for vgrid {vgrid.shape})", flush=True,
        )
    # non-uniform GridEdges through the public API on this mesh: the
    # planar shard_map exchange with quantile-balanced boundaries must
    # ride the real collective and stay bit-equal to the NumPy oracle
    from mpi_grid_redistribute_tpu import GridRedistribute, GridEdges

    rng_e = np.random.default_rng(11)
    n_e = grid.nranks * 4096
    epos = (rng_e.lognormal(-1.0, 1.0, size=(n_e, 3)) % 1.0).astype(
        np.float32
    )
    gedges = GridEdges.balanced_for(domain, grid, epos)
    kw = dict(capacity_factor=16.0, out_capacity=4 * 4096, edges=gedges)
    # context-manager form: resolve deferred overflow windows at exit
    # instead of warning from __del__ on these transient instances
    with GridRedistribute(domain, grid, mesh=mesh, **kw) as rd_e:
        res = rd_e.redistribute(epos)
    with GridRedistribute(domain, grid, backend="numpy", **kw) as rd_np_e:
        res_np = rd_np_e.redistribute(epos)
    assert (
        np.asarray(res.positions).tobytes()
        == np.asarray(res_np.positions).tobytes()
    ), "edges exchange != oracle bits on this mesh"
    assert int(np.asarray(res.count).sum()) == n_e
    print(
        f"non-uniform GridEdges exchange: OK (bit-equal to oracle, "
        f"{n_e} rows conserved)", flush=True,
    )
    print("POD SMOKE PASSED", flush=True)


def kill_restore(steps: int = 40, n_local: int = 2048,
                 snapshot_every: int = 4) -> None:
    """SIGKILL the service driver mid-run; prove bit-identical resume.

    Three subprocesses on the forced-CPU 8-device mesh: a victim run
    killed with SIGKILL once >= 2 snapshots have committed, a resume run
    restoring from the latest valid snapshot in the same directory, and
    an uninterrupted reference run — resume and reference must produce
    byte-identical final state (pos/vel/count) at the same step.
    """
    import json
    import shutil
    import signal
    import subprocess
    import tempfile
    import time

    # host-only in the parent: snapshot inspection needs numpy + json,
    # never jax — the children own the devices
    from mpi_grid_redistribute_tpu.utils import checkpoint

    root = tempfile.mkdtemp(prefix="pod_smoke_kr_")
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=8",
    )
    base = [
        sys.executable, "-m", "mpi_grid_redistribute_tpu.service",
        "--grid", "2,2,2", "--n-local", str(n_local),
        "--steps", str(steps), "--seed", "5",
        "--snapshot-every", str(snapshot_every),
    ]
    snaps = os.path.join(root, "snaps")
    try:
        # --- victim: paced so SIGKILL lands mid-run -------------------
        victim = subprocess.Popen(
            base + ["--snapshot-dir", snaps, "--step-sleep", "0.05"],
            env=env, stdout=subprocess.DEVNULL,
        )
        deadline = time.time() + 180
        while time.time() < deadline:
            if len(checkpoint.list_snapshots(snaps)) >= 2:
                break
            if victim.poll() is not None:
                break
            time.sleep(0.05)
        committed = len(checkpoint.list_snapshots(snaps))
        assert committed >= 2, (
            f"victim produced only {committed} snapshots before "
            f"{'exiting' if victim.poll() is not None else 'the deadline'}"
        )
        if victim.poll() is None:
            victim.send_signal(signal.SIGKILL)
            victim.wait(timeout=30)
            print(
                f"victim: SIGKILLed after {committed} committed "
                f"snapshots (exit {victim.returncode})", flush=True,
            )
        else:
            print(
                "victim: WARNING — finished before the kill landed; "
                "still exercising restore-from-snapshot", flush=True,
            )

        # --- resume: restore from the latest valid snapshot -----------
        latest = checkpoint.load_latest(snaps)
        assert latest is not None, "no valid snapshot survived the kill"
        resumed_out = os.path.join(root, "resumed.npz")
        subprocess.run(
            base + ["--snapshot-dir", snaps, "--final-out", resumed_out],
            env=env, check=True, stdout=subprocess.DEVNULL,
        )
        print(
            f"resume: restored step {latest.manifest['step']} "
            f"({latest.skipped} invalid snapshot(s) skipped), "
            f"ran to step {steps}", flush=True,
        )

        # --- reference: the same config, never interrupted ------------
        ref_out = os.path.join(root, "ref.npz")
        subprocess.run(
            base + [
                "--snapshot-dir", os.path.join(root, "ref_snaps"),
                "--final-out", ref_out,
            ],
            env=env, check=True, stdout=subprocess.DEVNULL,
        )

        with np.load(resumed_out) as res, np.load(ref_out) as ref:
            assert int(res["step"]) == int(ref["step"]) == steps
            for name in ("pos", "vel", "count"):
                assert res[name].tobytes() == ref[name].tobytes(), (
                    f"resumed {name} differs from the uninterrupted run"
                )
        print(
            f"kill-restore: OK (resumed trajectory bit-identical to the "
            f"uninterrupted run at step {steps})", flush=True,
        )
        print("KILL-RESTORE PASSED", flush=True)
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    import argparse

    _p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    _p.add_argument(
        "--journal-dir",
        default=os.environ.get("POD_SMOKE_JOURNAL_DIR"),
        help="write one JSONL journal shard per (virtual) host here; "
        "the pod-wide aggregation check runs either way",
    )
    _p.add_argument(
        "--kill-restore",
        action="store_true",
        help="run the standalone kill/restore leg (subprocess SIGKILL + "
        "bit-identical resume on the CPU mesh) instead of the TPU smoke",
    )
    _args = _p.parse_args()
    if _args.kill_restore:
        kill_restore()
    else:
        main(journal_dir=_args.journal_dir)
