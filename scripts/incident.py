#!/usr/bin/env python
"""Inspect flight-recorder incident bundles (`make incident-demo`).

Thin CLI over :mod:`mpi_grid_redistribute_tpu.telemetry.incident`. A
bundle directory is what the :class:`~...telemetry.incident
.FlightRecorder` froze when an ALERT / injected fault / bench
REGRESSION fired: the retained journal window, all-time counts, the
rendered OpenMetrics exposition, health findings, flow snapshot, env
fingerprint and the triggering step context, indexed by ``index.json``
(layout: README "Incident response"). Three subcommands:

* ``list DIR`` — one line per bundle (id, rule, trigger, capture time,
  triggering trace id), oldest first; ``--json`` prints the raw index
  entries instead.
* ``show DIR ID`` — a bundle's full ``index.json`` plus which files are
  actually present on disk.
* ``export DIR ID --out TRACE.json`` — re-hydrate the bundle's frozen
  journal window into a Perfetto/Chrome trace (flow arrows link the
  causing step to the alert/restart/incident it produced — open at
  https://ui.perfetto.dev).

Examples:

  python scripts/incident.py list /tmp/incidents
  python scripts/incident.py show /tmp/incidents incident-0001-slo_latency_p99_s
  python scripts/incident.py export /tmp/incidents \\
      incident-0001-slo_latency_p99_s --out incident.trace.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def cmd_list(args) -> int:
    from mpi_grid_redistribute_tpu.telemetry import incident as incident_lib

    entries = incident_lib.list_bundles(args.dir)
    if args.json:
        json.dump(entries, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    if not entries:
        print(f"no bundles under {args.dir}")
        return 0
    for e in entries:
        if "error" in e:
            print(f"{e.get('id', '?')}: UNREADABLE ({e['error']})")
            continue
        trace = (e.get("context") or {}).get("trace", "-")
        print(
            f"{e.get('id')}  rule={e.get('rule')}  "
            f"trigger={e.get('trigger')}  t={e.get('captured_at')}  "
            f"trace={trace}"
        )
    return 0


def cmd_show(args) -> int:
    from mpi_grid_redistribute_tpu.telemetry import incident as incident_lib

    try:
        index = incident_lib.load_bundle(args.dir, args.id)
    except (OSError, ValueError) as exc:
        raise SystemExit(f"{args.dir}/{args.id}: {exc}")
    json.dump(index, sys.stdout, indent=2, sort_keys=True)
    print()
    return 0


def cmd_export(args) -> int:
    from mpi_grid_redistribute_tpu import telemetry
    from mpi_grid_redistribute_tpu.telemetry import traceview

    journal = os.path.join(args.dir, args.id, "journal.jsonl")
    if not os.path.isfile(journal):
        raise SystemExit(f"{journal}: no frozen journal in this bundle")
    # the frozen window is a normal to_jsonl export: re-hydrate it
    # through the aggregation layer (single shard) so the exported trace
    # is exactly what a pod merge of the same lines would show
    merged = telemetry.merge_journals([journal])
    rec = merged.to_recorder()
    n_ev = traceview.write_trace(args.out, rec)
    print(
        f"wrote {args.out} ({n_ev} trace events) — open at "
        f"https://ui.perfetto.dev"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="List, inspect and export flight-recorder incident "
        "bundles (telemetry/incident.py)."
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_list = sub.add_parser("list", help="list bundles under a directory")
    p_list.add_argument("dir", help="incident bundle root")
    p_list.add_argument(
        "--json", action="store_true", help="print raw index entries"
    )
    p_list.set_defaults(fn=cmd_list)

    p_show = sub.add_parser("show", help="print one bundle's index")
    p_show.add_argument("dir", help="incident bundle root")
    p_show.add_argument("id", help="bundle id (see `list`)")
    p_show.set_defaults(fn=cmd_show)

    p_exp = sub.add_parser(
        "export", help="export a bundle's journal window to a Perfetto trace"
    )
    p_exp.add_argument("dir", help="incident bundle root")
    p_exp.add_argument("id", help="bundle id (see `list`)")
    p_exp.add_argument("--out", required=True, help="output trace JSON path")
    p_exp.set_defaults(fn=cmd_export)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
