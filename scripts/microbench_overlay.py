"""On-chip: planar one-hot overlay scatter vs XLA column scatter.

Shapes mirror the bench.py headline landing: [7, 8.4M] planar state,
~196k updates (the landing plan length at 2% migration, 8 vranks x 1M).
Both timed with the scan-differencing harness; bit-equality asserted
against the XLA scatter first (including NaN-bit payload rows).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.ops import pallas_overlay
from mpi_grid_redistribute_tpu.utils import profiling

K = 7
M = 8 * (1 << 20)  # 8.4M columns
P = 196_608  # landing-plan entries


def main():
    r = np.random.default_rng(0)
    flat = r.standard_normal((K, M)).astype(np.float32)
    flat[6] = r.integers(-(2**31), 2**31 - 1, size=M, dtype=np.int32).view(
        np.float32
    )
    targets = r.choice(M, size=P, replace=False).astype(np.int32)
    # ~7% drop sentinels like a real plan's padding tail
    targets[r.random(P) < 0.07] = M
    cols = r.standard_normal((K, P)).astype(np.float32)
    cols[6] = r.integers(-(2**31), 2**31 - 1, size=P, dtype=np.int32).view(
        np.float32
    )

    fd, td, cd = (
        jax.device_put(jnp.asarray(flat)),
        jax.device_put(jnp.asarray(targets)),
        jax.device_put(jnp.asarray(cols)),
    )

    out_k = pallas_overlay.overlay_scatter_planar(fd, td, cd)
    out_x = fd.at[:, td].set(cd, mode="drop")
    a = np.asarray(out_k).view(np.uint32)
    b = np.asarray(out_x).view(np.uint32)
    assert np.array_equal(a, b), (
        f"bit mismatch: {np.sum(a != b)} of {a.size}"
    )
    print("bit-equality vs XLA scatter: OK", flush=True)

    def time_impl(impl):
        def make_loop(S):
            @jax.jit
            def loop(f, t, c):
                def body(acc, _):
                    o = impl(f + acc * jnp.float32(1e-30), t, c)
                    return acc + o[0, 0], None
                out, _ = lax.scan(body, jnp.float32(0), None, length=S)
                return out
            return loop
        per, _, _ = profiling.scan_time_per_step(
            make_loop, (fd, td, cd), s1=2, s2=10
        )
        return per

    t_x = time_impl(
        lambda f, t, c: f.at[:, t].set(c, mode="drop")
    )
    print(f"XLA column scatter: {t_x*1e3:.2f} ms", flush=True)
    import functools
    for w in (512, 1024, 2048, 4096, 8192):
        t_k = time_impl(functools.partial(
            pallas_overlay.overlay_scatter_planar, w=w))
        print(f"overlay kernel W={w} (incl. sort+prep): {t_k*1e3:.2f} ms "
              f"({t_x/t_k:.1f}x)", flush=True)


if __name__ == "__main__":
    main()
