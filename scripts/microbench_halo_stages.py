"""Attribution probe for the PLANAR halo at the config-6 shape: which of
the per-pass stages — selection predicate, packed-order sort, column
gather, or the roll/append tail — dominates the 36.8 ns/ghost cost.

Truncated variants (cumulative, scan-differenced like
scripts/knockout_stages.py; zero recv is fed to later axes for truncated
variants, so deltas are directional — the full variant is the engine):

  A  predicate + counts per pass
  B  A + packed one-word order sort (pack._stable_order)
  C  B + K-row column gather + periodic wrap surgery (send built)
  D  full engine (roll + vmapped DUS appends) = halo.vrank_halo_planar_fn

Usage: python scripts/microbench_halo_stages.py [n_local]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops.pack import _stable_order, _take_rows
from mpi_grid_redistribute_tpu.parallel import halo as halo_lib
from mpi_grid_redistribute_tpu.bench import common
from mpi_grid_redistribute_tpu.utils import profiling

n_local = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 18
grid = ProcessGrid((2, 2, 2))
R = grid.nranks
domain = Domain(0.0, 1.0, periodic=True)
w_f = 0.1 * min(grid.cell_widths(domain))
pc, gc = halo_lib.default_capacities(domain, grid, w_f, n_local)
rng = np.random.default_rng(0)
pos, _, _ = common.uniform_state(grid.shape, n_local, 1.0, rng)
count = np.full((R,), n_local, np.int32)
fused0 = jnp.asarray(
    np.ascontiguousarray(
        pos.reshape(R, n_local, 3).transpose(0, 2, 1)
    ).view(np.int32)
)
count0 = jnp.asarray(count)


def truncated(fused, count, phase):
    """Copy of vrank_halo_planar_fn's loop cut after ``phase`` per pass."""
    widths, cell_w = halo_lib._validate_widths(domain, grid, w_f)
    H, G = pc, gc
    V = grid.nranks
    nd = 3
    fi = fused
    K, n = fi.shape[1], fi.shape[2]
    valid = jnp.arange(n, dtype=jnp.int32)[None, :] < count[:, None]
    ghost = jnp.zeros((V, K, G + H), jnp.int32)
    gcount = jnp.zeros((V,), jnp.int32)
    overflow = jnp.zeros((V,), jnp.int32)
    ranks = jnp.arange(V, dtype=jnp.int32)
    strides = grid.strides
    probe = jnp.int32(0)

    for a in range(nd):
        g = grid.shape[a]
        w = jnp.asarray(widths[a], jnp.float32)
        extent_a = jnp.asarray(domain.extent[a], jnp.float32)
        coord_idx = (ranks // strides[a]) % g
        lo_a = (
            jnp.asarray(domain.lo[a], jnp.float32)
            + coord_idx.astype(jnp.float32)
            * jnp.asarray(cell_w[a], jnp.float32)
        )
        hi_a = lo_a + jnp.asarray(cell_w[a], jnp.float32)
        cand = jnp.concatenate([fi, ghost[:, :, :G]], axis=2)
        cand_valid = jnp.concatenate(
            [
                valid,
                jnp.arange(G, dtype=jnp.int32)[None, :] < gcount[:, None],
            ],
            axis=1,
        )
        incoming = []
        for dirn in (1, -1):
            at_edge = coord_idx == (g - 1 if dirn == 1 else 0)

            def pass_one(c_v, cv_v, lo_v, hi_v, e_v):
                D_row = lax.bitcast_convert_type(c_v[a, :], jnp.float32)
                if dirn == 1:
                    mask = cv_v & (D_row >= hi_v - w)
                else:
                    mask = cv_v & (D_row < lo_v + w)
                cnt = jnp.sum(mask.astype(jnp.int32))
                send_cnt = jnp.minimum(cnt, H)
                if phase == 0:
                    return jnp.zeros((c_v.shape[0], H), jnp.int32), send_cnt
                order = _stable_order(jnp.logical_not(mask))
                if phase == 1:
                    return (
                        jnp.zeros((c_v.shape[0], H), jnp.int32)
                        .at[0, 0]
                        .set(order[0]),
                        send_cnt,
                    )
                take = _take_rows(order, H)
                slot_valid = jnp.arange(H, dtype=jnp.int32) < send_cnt
                send = jnp.where(
                    slot_valid[None, :], jnp.take(c_v, take, axis=1), 0
                )
                shift = jnp.where(
                    e_v & domain.periodic[a],
                    -jnp.asarray(dirn, jnp.float32) * extent_a,
                    jnp.asarray(0, jnp.float32),
                )
                row_a = lax.bitcast_convert_type(send[a, :], jnp.float32)
                row_a = jnp.where(slot_valid, row_a + shift, row_a)
                send = jnp.concatenate(
                    [
                        send[:a],
                        lax.bitcast_convert_type(row_a, jnp.int32)[None, :],
                        send[a + 1 :],
                    ],
                    axis=0,
                )
                return send, send_cnt

            send, send_cnt = jax.vmap(pass_one)(
                cand, cand_valid, lo_a, hi_a, at_edge
            )
            probe = probe + send[0, 0, 0] + send_cnt[0]
            if phase >= 3:
                recv = jnp.roll(
                    send.reshape(grid.shape + send.shape[1:]), dirn, axis=a
                ).reshape(send.shape)
                recv_cnt = jnp.roll(
                    send_cnt.reshape(grid.shape), dirn, axis=a
                ).reshape((V,))
                incoming.append((recv, recv_cnt))
        for recv, recv_cnt in incoming:
            ghost, gcount, overflow = jax.vmap(
                lambda gh_v, gc_v, ov_v, rc_v, rcnt_v: halo_lib._append_recv_cols(
                    gh_v, gc_v, ov_v, rc_v, rcnt_v, pc, gc
                )
            )(ghost, gcount, overflow, recv, recv_cnt)
    return probe + gcount[0] + ghost[0, 0, 0]


def make_loop(phase):
    def build(S):
        if phase == 4:
            fn = halo_lib.vrank_halo_planar_fn(domain, grid, w_f, pc, gc)

            @jax.jit
            def loop(fused, count):
                def body(carry, _):
                    f, c = carry
                    gh, gcnt, ov = fn(f, c)
                    f = f + (gh[0, 0, 0] + gcnt[0] + ov[0]).astype(
                        jnp.int32
                    ) * 0
                    return (f, c), gcnt[0]

                _, outs = lax.scan(body, (fused, count), None, length=S)
                return outs
        else:

            @jax.jit
            def loop(fused, count):
                def body(carry, _):
                    f, c = carry
                    p = truncated(f, c, phase)
                    f = f + p * 0
                    return (f, c), p

                _, outs = lax.scan(body, (fused, count), None, length=S)
                return outs

        return loop

    return build


print(f"V={R} n_local={n_local} pc={pc} gc={gc}")
for phase, name in [
    (0, "A predicate+counts"),
    (1, "B +packed sort"),
    (2, "C +gather+wrap"),
    (3, "D +roll+appends"),
    (4, "E full engine fn"),
]:
    t, _, _ = profiling.scan_time_per_step(
        make_loop(phase), (fused0, count0), s1=2, s2=8
    )
    print(f"{name:22s}: {t * 1e3:8.2f} ms")
