"""Per-stage device-time attribution for the headline migrate step.

Times each pipeline stage of the vrank migrate step in isolation at
bench-identical shapes (V vranks of n rows, K fused columns, per-pair
capacity C), using the same scan-length-differencing as bench.py so the
~100 ms tunnel round-trip cancels. Each stage's scan carries a data
dependency through the timed op so XLA cannot hoist or DCE it.

Usage:  python scripts/profile_stages.py [n_local] [capacity]

Output: a markdown table of ms/step per stage; paste into README (VERDICT
round-1 item 1: publish the stage table explaining where the step time
goes).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.utils import profiling

GRID = (2, 2, 2)
V = 8
R_TOTAL = 8
K = 7  # pos(3) + vel(3) + alive(1)
FILL = 0.9
MIGRATION = 0.02


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2**20
    import math

    distinct = sum(1 if g == 2 else 2 for g in GRID)
    C = (
        int(sys.argv[2])
        if len(sys.argv) > 2
        else max(64, math.ceil(FILL * n * MIGRATION / distinct * 1.3))
    )
    # compact on-device routing budget (bench.py's local_budget): the
    # gather/scatter plans are sized to M migrant rows per vrank, not to
    # the R*C padded collective layout
    M_budget = max(256, math.ceil(FILL * n * MIGRATION * 1.3))
    domain = Domain(0.0, 1.0, periodic=True)
    vgrid = ProcessGrid(GRID)
    dev_grid = ProcessGrid((1, 1, 1))

    rng = np.random.default_rng(0)
    fused = rng.random((V, n, K), dtype=np.float32)
    fused[:, :, -1] = (rng.random((V, n)) < FILL).astype(np.float32)
    fused = jax.device_put(jnp.asarray(fused))
    # a plausible dest_key distribution: mostly sentinel (stay), ~2% spread
    # over the 3 distinct neighbors
    key_np = np.full((V, n), R_TOTAL, np.int32)
    m = int(n * FILL * MIGRATION)
    for v in range(V):
        idx = rng.choice(n, size=m, replace=False)
        key_np[v, idx] = rng.choice([1, 2, 4], size=m)  # face neighbors of 0
    dest_key = jax.device_put(jnp.asarray(key_np))
    gather_idx = jax.device_put(
        jnp.asarray(
            rng.integers(0, n, size=(V, M_budget), dtype=np.int32)
        )
    )
    target = gather_idx
    rows = jax.device_put(
        jnp.asarray(
            rng.random((V, M_budget, K), dtype=np.float32)
        )
    )

    stages = {}

    def timed(name, make_loop, *args, s1=4, s2=24):
        per_step, _, _out = profiling.scan_time_per_step(
            make_loop, args, s1=s1, s2=s2
        )
        stages[name] = per_step * 1e3
        print(f"  {name:30s} {per_step*1e3:8.2f} ms", file=sys.stderr)

    # --- 1. elementwise: drift + wrap + bin -> dest key -----------------
    full_shape = tuple(d * v for d, v in zip(dev_grid.shape, vgrid.shape))
    full_grid = ProcessGrid(full_shape)

    def bin_one(f, v_id):
        cell = binning.cell_of_position(
            binning.wrap_periodic(f[:, :3], domain), domain, full_grid
        )
        vshape = jnp.asarray(vgrid.shape, jnp.int32)
        dest_v = binning.rank_of_cell(cell % vshape, vgrid)
        staying = dest_v == v_id
        alive = f[:, -1] > 0.5
        return jnp.where(
            alive & ~staying, dest_v, R_TOTAL
        ).astype(jnp.int32)

    def make_bin_loop(S):
        @jax.jit
        def loop(fused):
            def body(f, _):
                p = f[..., :3] + f[..., 3:6] * jnp.float32(1e-4)
                p = binning.wrap_periodic(p, domain)
                f = jnp.concatenate([p, f[..., 3:]], axis=-1)
                key = jax.vmap(bin_one)(f, jnp.arange(V, dtype=jnp.int32))
                # dependency: fold key stats back into carry
                # float-underflow dependency: tiny*sum underflows to 0
                # at runtime but cannot be constant-folded like `* 0`
                dep = key.sum(axis=1).astype(jnp.float32) * jnp.float32(1e-38)
                f = f.at[:, 0, 0].add(dep)
                return f, ()

            f, _ = lax.scan(body, fused, None, length=S)
            return f

        return loop

    timed("drift+wrap+bin (elementwise)", make_bin_loop, fused)

    # --- 2. stable key sort + counts ------------------------------------
    def make_sort_loop(S):
        @jax.jit
        def loop(key):
            def body(k, _):
                order, counts, bounds = jax.vmap(
                    lambda kk: binning.sorted_dest_counts(kk, R_TOTAL)
                )(k)
                dep = (
                    (order[:, :1] + counts[:, :1]).astype(jnp.float32)
                    * jnp.float32(1e-38)
                ).astype(jnp.int32)  # runtime 0, not foldable
                k = (k + dep).astype(jnp.int32)
                return k, ()

            k, _ = lax.scan(body, key, None, length=S)
            return k

        return loop

    timed("stable sort + searchsorted", make_sort_loop, dest_key)

    # --- 3. pack gather: [V, R*C] rows from [V, n, K] --------------------
    def make_gather_loop(S):
        @jax.jit
        def loop(fused, idx):
            def body(carry, _):
                f, i = carry
                send = jax.vmap(
                    lambda ff, ii: jnp.take(ff, ii, axis=0)
                )(f, i)
                dep = (send[:, :1, 0] * jnp.float32(1e-38)).astype(jnp.int32)
                i = (i + dep) % n
                return (f, i), ()

            (f, i), _ = lax.scan(body, (fused, idx), None, length=S)
            return f, i

        return loop

    timed(f"arrival gather ({V}x{M_budget} rows)", make_gather_loop, fused,
          gather_idx)

    # --- 4. landing scatter: flat [V*M] rows into [V*n, K] ---------------
    # FLAT, as the real step does it: the vmapped per-vrank form measures
    # ~2x slower than what XLA emits for the flat scatter (measured; see
    # scripts/knockout_stages.py for in-context attribution)
    def make_scatter_loop(S):
        @jax.jit
        def loop(fused, tgt, rows):
            def body(carry, _):
                f, t = carry
                flat = f.reshape(V * n, K)
                gt = (
                    jnp.arange(V, dtype=jnp.int32)[:, None] * n + t
                ).reshape(-1)
                flat = flat.at[gt].set(
                    rows.reshape(-1, K), mode="drop"
                )
                f = flat.reshape(V, n, K)
                dep = (f[:, :1, 0] * jnp.float32(1e-38)).astype(jnp.int32)
                t = (t + dep) % n
                return (f, t), ()

            (f, t), _ = lax.scan(body, (fused, tgt), None, length=S)
            return f, t

        return loop

    timed(f"landing scatter (flat {V}x{M_budget} rows)", make_scatter_loop,
          fused, target, rows)

    # --- 5. full migrate step (reference) --------------------------------
    from mpi_grid_redistribute_tpu.parallel import migrate, mesh as mesh_lib
    from mpi_grid_redistribute_tpu.models import nbody

    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=1e-4, capacity=C, n_local=n,
        local_budget=M_budget,
    )
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])
    pos = np.asarray(fused[0][:, :3]).copy()
    pos_all = rng.random((V * n, 3), dtype=np.float32)
    vel_all = rng.random((V * n, 3), dtype=np.float32) * 1e-4
    alive_all = rng.random((V * n,)) < FILL
    args = (
        jax.device_put(jnp.asarray(pos_all)),
        jax.device_put(jnp.asarray(vel_all)),
        jax.device_put(jnp.asarray(alive_all)),
    )
    timed(
        "FULL migrate step",
        lambda S: nbody.make_migrate_loop(cfg, mesh, S, vgrid=vgrid),
        *args,
    )

    print("\n| stage | ms/step |\n|---|---|")
    for name, ms in stages.items():
        print(f"| {name} | {ms:.2f} |")
    accounted = sum(v for k, v in stages.items() if "FULL" not in k)
    print(f"| (sum of stages) | {accounted:.2f} |")


if __name__ == "__main__":
    main()
