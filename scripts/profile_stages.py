"""Per-stage device-time attribution for the headline migrate step.

Times each pipeline stage of the PLANAR vrank migrate step in isolation at
bench-identical shapes (V vranks of n columns, K fused rows, on-device
budget M), using the same scan-length-differencing as bench.py so the
~100 ms tunnel round-trip cancels. Each stage's scan carries a data
dependency through the timed op so XLA cannot hoist or DCE it.

In-context attribution (the sum here can differ from the real step —
isolated microbenches measured 2x off for the vmapped scatter) lives in
scripts/knockout_stages.py; this script is the per-op sanity check.

Usage:  python scripts/profile_stages.py [n_local] [capacity]
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.utils import profiling

GRID = (2, 2, 2)
V = 8
R_TOTAL = 8
K = 7  # pos(3) + vel(3) + alive(1)
FILL = 0.9
MIGRATION = 0.02


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2**20
    import math

    distinct = sum(1 if g == 2 else 2 for g in GRID)
    C = (
        int(sys.argv[2])
        if len(sys.argv) > 2
        else max(64, math.ceil(FILL * n * MIGRATION / distinct * 1.3))
    )
    M_budget = max(256, math.ceil(FILL * n * MIGRATION * 1.3))
    domain = Domain(0.0, 1.0, periodic=True)
    vgrid = ProcessGrid(GRID)
    dev_grid = ProcessGrid((1, 1, 1))

    rng = np.random.default_rng(0)
    # planar fused state: [K, V*n], alive = last row
    fused = rng.random((K, V * n), dtype=np.float32)
    fused[-1, :] = (rng.random((V * n,)) < FILL).astype(np.float32)
    fused = jax.device_put(jnp.asarray(fused))
    key_np = np.full((V, n), R_TOTAL, np.int32)
    m = int(n * FILL * MIGRATION)
    for v in range(V):
        idx = rng.choice(n, size=m, replace=False)
        key_np[v, idx] = rng.choice([1, 2, 4], size=m)
    dest_key = jax.device_put(jnp.asarray(key_np))
    gather_idx = jax.device_put(
        jnp.asarray(rng.integers(0, n, size=(V, M_budget), dtype=np.int32))
    )
    cols = jax.device_put(
        jnp.asarray(rng.random((K, V * M_budget), dtype=np.float32))
    )

    stages = {}

    def timed(name, make_loop, *args, s1=4, s2=24):
        per_step, _, _out = profiling.scan_time_per_step(
            make_loop, args, s1=s1, s2=s2
        )
        stages[name] = per_step * 1e3
        print(f"  {name:34s} {per_step*1e3:8.2f} ms", file=sys.stderr)

    full_shape = tuple(d * v for d, v in zip(dev_grid.shape, vgrid.shape))
    full_grid = ProcessGrid(full_shape)

    # --- 1. elementwise: drift + wrap + bin -> dest key -----------------
    def make_bin_loop(S):
        @jax.jit
        def loop(fused):
            def body(f, _):
                p = f[:3, :] + f[3:6, :] * jnp.float32(1e-4)
                p = binning.wrap_periodic_planar(p, domain)
                f = jnp.concatenate([p, f[3:, :]], axis=0)
                alive = f[-1, :].reshape(V, n) > 0.5
                cell = binning.cell_of_position_planar(
                    f[:3, :], domain, full_grid
                )
                dv = jnp.zeros((V * n,), jnp.int32)
                for d in range(3):
                    dv = dv + (
                        cell[d] % vgrid.shape[d]
                    ) * vgrid.strides[d]
                dv = dv.reshape(V, n)
                staying = dv == jnp.arange(V, dtype=jnp.int32)[:, None]
                key = jnp.where(alive & ~staying, dv, R_TOTAL)
                dep = key.sum(axis=1).astype(jnp.float32).sum() * 1e-38
                f = f.at[0, 0].add(dep)
                return f, ()

            f, _ = lax.scan(body, fused, None, length=S)
            return f

        return loop

    timed("drift+wrap+bin (planar)", make_bin_loop, fused)

    # --- 2. stable key sort + counts ------------------------------------
    def make_sort_loop(S):
        @jax.jit
        def loop(key):
            def body(k, _):
                order, counts, bounds = jax.vmap(
                    lambda kk: binning.sorted_dest_counts(kk, R_TOTAL)
                )(k)
                dep = (
                    (order[:, :1] + counts[:, :1]).astype(jnp.float32)
                    * jnp.float32(1e-38)
                ).astype(jnp.int32)  # runtime 0, not foldable
                k = (k + dep).astype(jnp.int32)
                return k, ()

            k, _ = lax.scan(body, key, None, length=S)
            return k

        return loop

    timed("stable sort + searchsorted", make_sort_loop, dest_key)

    # --- 3. arrival gather: [K, V*M] columns from [K, V*n] ---------------
    def make_gather_loop(S):
        @jax.jit
        def loop(fused, idx):
            def body(carry, _):
                f, i = carry
                gi = (
                    jnp.arange(V, dtype=jnp.int32)[:, None] * n + i
                ).reshape(-1)
                send = jnp.take(f, gi, axis=1)
                dep = (send[0, :1] * jnp.float32(1e-38)).astype(jnp.int32)
                i = (i + dep[None, :]) % n
                return (f, i), ()

            (f, i), _ = lax.scan(body, (fused, idx), None, length=S)
            return f, i

        return loop

    timed(f"arrival gather ({V}x{M_budget} cols)", make_gather_loop, fused,
          gather_idx)

    # --- 4. landing scatter: [K, V*M] columns into [K, V*n] --------------
    def make_scatter_loop(S):
        @jax.jit
        def loop(fused, tgt, cols):
            def body(carry, _):
                f, t = carry
                gt = (
                    jnp.arange(V, dtype=jnp.int32)[:, None] * n + t
                ).reshape(-1)
                f = f.at[:, gt].set(cols, mode="drop")
                dep = (f[0, :1] * jnp.float32(1e-38)).astype(jnp.int32)
                t = (t + dep[None, :]) % n
                return (f, t), ()

            (f, t), _ = lax.scan(body, (fused, tgt), None, length=S)
            return f, t

        return loop

    timed(f"landing scatter ({V}x{M_budget} cols)", make_scatter_loop,
          fused, gather_idx, cols)

    # --- 5. full migrate step (reference) --------------------------------
    from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib
    from mpi_grid_redistribute_tpu.models import nbody

    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=1e-4, capacity=C, n_local=n,
        local_budget=M_budget,
    )
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])
    pos_all = rng.random((V * n, 3), dtype=np.float32)
    vel_all = rng.random((V * n, 3), dtype=np.float32) * 1e-4
    alive_all = rng.random((V * n,)) < FILL
    args = (
        jax.device_put(
            jnp.asarray(nbody.rows_to_planar(pos_all, mesh.size))
        ),
        jax.device_put(
            jnp.asarray(nbody.rows_to_planar(vel_all, mesh.size))
        ),
        jax.device_put(jnp.asarray(alive_all)),
    )
    timed(
        "FULL migrate step",
        lambda S: nbody.make_migrate_loop(cfg, mesh, S, vgrid=vgrid),
        *args,
    )

    print("\n| stage | ms/step |\n|---|---|")
    for name, ms in stages.items():
        print(f"| {name} | {ms:.2f} |")
    accounted = sum(v for k, v in stages.items() if "FULL" not in k)
    print(f"| (sum of stages) | {accounted:.2f} |")


if __name__ == "__main__":
    main()
