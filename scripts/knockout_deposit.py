"""Knockout profiling of the PLANAR scan deposit at the 64M north-star
shape (config 5's non-migrate cost): time the deposit truncated after each
phase, scan-length-differenced like scripts/knockout_stages.py.

The fused config-5 step at 64M measures 1931 ms while the migrate step
alone is ~261 ms — the deposit is ~1670 ms and has never had its own
attribution. Phases of ``ops.deposit.cic_deposit_vranks_planar``:

  1. key build: rel / i0 / flat segment key (elementwise)
  2. payload sort: (key, iota, rel0..2, mass) — 6 operands, V*n rows
  3. bounds: searchsorted of n_segments+1 edges (method="sort")
  4. channel prefixes: corner-weight rows + double-float tiled prefix
     (Pallas dfscan) + tile-total scan, per channel group
  5. boundary gathers + differencing -> per_cell [8, V*n_cells]
  6. placement: reshape + corner pads + vrank assembly + ghost fold

MAINTENANCE: phases are a DELIBERATE copy of the deposit core (same
reason as knockout_stages.py — a truncating profiler cannot share the
un-truncatable original). Phase 6 must match the standalone deposit cost
inferred from bench/config5_deposit.py minus the migrate step.

Usage: python scripts/knockout_deposit.py [n_per_vrank]
       KNOCKOUT_GRID=4,4,4 python scripts/knockout_deposit.py 1048576
"""

from __future__ import annotations

import itertools
import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu.ops import binning, deposit
from mpi_grid_redistribute_tpu.utils import profiling

GRID = tuple(
    int(x) for x in os.environ.get("KNOCKOUT_GRID", "4,4,4").split(",")
)
FILL = 0.9
MESH_CELLS = 128
HBM_PEAK = 819e9


def truncated_deposit(dev_block, V, n, phase, channel_group=2, tile=256):
    """Planar deposit cut after ``phase`` (copy of
    deposit.cic_deposit_device_planar's core, Dev=1: DEVICE-cell keys,
    corner placement by static pads + periodic self-fold — the late-
    round-4 engine; the per-vrank assembly it replaced measured +54 ms
    at 4.2M rows in this script's earlier form)."""
    D = 3
    n_cells = math.prod(dev_block)
    m = V * n
    strides = deposit._row_major_strides(dev_block)
    corners = list(itertools.product((0, 1), repeat=D))
    nch = len(corners)
    K = max(1, min(tile, m))
    n_pad = -(-m // K) * K
    inv_h = np.float32(MESH_CELLS / 1.0)

    def fn(state):
        pos_rows, mass, valid = state  # [3, m], [m], [m] bool

        def probe(*arrs):
            d = jnp.float32(0)
            for a in arrs:
                d = d + (
                    a.ravel()[0] == jnp.asarray(7, a.dtype)
                ).astype(jnp.float32)
            return (pos_rows.at[0, 0].add(d * 1e-12), mass, valid)

        # ---- 1: key build (elementwise, device-cell keys) -----------
        rel = []
        cell = jnp.zeros((m,), jnp.int32)
        for d in range(D):
            r = pos_rows[d] * inv_h  # dev_lo = 0 on the unit domain
            r = jnp.where(valid, r, 0.0)
            i0_d = jnp.clip(
                jnp.floor(r).astype(jnp.int32), 0, dev_block[d] - 1
            )
            cell = cell + i0_d * jnp.int32(strides[d])
            rel.append(r)
        key = jnp.where(valid, cell, n_cells).astype(jnp.int32)
        mass_z = jnp.where(valid, mass, 0.0)
        rel_rows = jnp.stack(rel, axis=0)
        if phase == 1:
            return probe(key, mass_z, rel_rows)

        # ---- 2: payload sort ----------------------------------------
        iota = jnp.arange(m, dtype=jnp.int32)
        operands = (key, iota) + tuple(
            rel_rows[d] for d in range(D)
        ) + (mass_z,)
        s = jax.lax.sort(operands, num_keys=2, is_stable=False)
        keys_sorted = s[0]
        rel_s = jnp.stack(s[2 : 2 + D], axis=0)
        mass_s = s[2 + D]
        if phase == 2:
            return probe(keys_sorted, rel_s, mass_s)

        i0_s = jnp.clip(
            jnp.floor(rel_s).astype(jnp.int32),
            0,
            jnp.asarray(dev_block, jnp.int32)[:, None] - 1,
        )
        frac = jnp.clip(rel_s - i0_s.astype(rel_s.dtype), 0.0, 1.0)

        # ---- 3: bounds (KNOCKOUT_BOUNDS=xla for the jnp rank-scatter
        # searchsorted the engine used before binning.bounds_dense) ----
        n_segments = n_cells
        if os.environ.get("KNOCKOUT_BOUNDS") == "xla":
            bounds = jnp.searchsorted(
                keys_sorted,
                jnp.arange(n_segments + 1, dtype=jnp.int32),
                side="left",
                method="sort",
            ).astype(jnp.int32)
        else:
            bounds = binning.bounds_dense(
                keys_sorted, n_segments + 1, key_bound=n_segments
            )
        if phase == 3:
            return probe(bounds, frac)

        t_idx = bounds // K
        has_local = (bounds % K > 0)[None, :]
        lb = jnp.clip(bounds - 1, 0, n_pad - 1)
        cg = max(1, min(channel_group, nch))

        def per_group(corner_list, upto):
            rows = []
            for corner in corner_list:
                w = None
                for d in range(D):
                    t = frac[d] if corner[d] == 1 else 1.0 - frac[d]
                    w = t if w is None else w * t
                rows.append(mass_s * w)
            wg = jnp.stack(rows, axis=0)
            gch = wg.shape[0]
            wt = jnp.pad(wg, ((0, 0), (0, n_pad - m))).reshape(
                gch, n_pad // K, K
            )
            lhi, llo = deposit._tile_prefix_planar(wt)
            thi, tlo = deposit._df_cumsum(
                lhi[:, :, -1], axis=1, x_lo=llo[:, :, -1]
            )
            if upto == 4:
                return (lhi, llo, thi, tlo)
            zg = jnp.zeros((gch, 1), wg.dtype)
            s_hi = jnp.concatenate([zg, thi], axis=1)
            s_lo = jnp.concatenate([zg, tlo], axis=1)
            l_pack = jnp.concatenate(
                [lhi.reshape(gch, n_pad), llo.reshape(gch, n_pad)],
                axis=0,
            )
            s_pack = jnp.concatenate([s_hi, s_lo], axis=0)
            l_at = jnp.where(
                has_local, jnp.take(l_pack, lb, axis=1), 0.0
            )
            s_at = jnp.take(s_pack, t_idx, axis=1)
            g_hi, g_lo = deposit._df_add(
                s_at[:gch], s_at[gch:], l_at[:gch], l_at[gch:]
            )
            return (g_hi[:, 1:] - g_hi[:, :-1]) + (
                g_lo[:, 1:] - g_lo[:, :-1]
            )

        # ---- 4: channel weight build + prefixes (no gathers) --------
        if phase == 4:
            outs = []
            for g0 in range(0, nch, cg):
                outs.extend(per_group(corners[g0 : g0 + cg], 4))
            return probe(*outs)

        # ---- 5: + boundary gathers + differencing -------------------
        per_cell = jnp.concatenate(
            [
                per_group(corners[g0 : g0 + cg], 5)
                for g0 in range(0, nch, cg)
            ],
            axis=0,
        )
        if phase == 5:
            return probe(per_cell)

        # ---- 6: placement (corner pads + periodic self-fold) --------
        per_cell = per_cell.reshape((nch,) + dev_block)
        ghost = tuple(b + 1 for b in dev_block)
        total = jnp.zeros(ghost, dtype=mass.dtype)
        for kk, corner in enumerate(corners):
            pad = [
                (c, gg - b - c)
                for c, gg, b in zip(corner, ghost, dev_block)
            ]
            total = total + jnp.pad(per_cell[kk], pad)
        total = _self_fold(total)
        return probe(total)

    return fn


def _self_fold(rho_ghost):
    """Dev=1 periodic self-fold of the +1 ghost faces (fold_ghosts with
    grid extent 1 on every axis — no collectives)."""
    for a in range(3):
        mm = rho_ghost.shape[a] - 1
        ghost = jax.lax.slice_in_dim(rho_ghost, mm, mm + 1, axis=a)
        body = jax.lax.slice_in_dim(rho_ghost, 0, mm, axis=a)
        first = jax.lax.slice_in_dim(body, 0, 1, axis=a) + ghost
        rest = jax.lax.slice_in_dim(body, 1, mm, axis=a)
        rho_ghost = jnp.concatenate([first, rest], axis=a)
    return rho_ghost


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 20
    V = math.prod(GRID)
    m = V * n
    dev_block = (MESH_CELLS,) * 3  # Dev = 1: the device owns the mesh
    rng = np.random.default_rng(0)
    pos = rng.random((3, m), np.float32)
    mass = np.ones((m,), np.float32)
    valid = rng.random(m) < FILL
    state = (
        jax.device_put(jnp.asarray(pos)),
        jax.device_put(jnp.asarray(mass)),
        jax.device_put(jnp.asarray(valid)),
    )
    print(
        f"grid {GRID} V={V} n={n} m={m} dev_block={dev_block} "
        f"segments={math.prod(dev_block)} "
        f"bounds={'xla' if os.environ.get('KNOCKOUT_BOUNDS') == 'xla' else 'dense'}"
    )
    prev = 0.0
    for phase in (1, 2, 3, 4, 5, 6):
        fn = truncated_deposit(dev_block, V, n, phase)

        def make_loop(S, fn=fn):
            @jax.jit
            def loop(*st):
                def body(c, _):
                    return fn(c), None

                out, _ = jax.lax.scan(body, st, None, length=S)
                return out

            return loop

        per_step, _, _ = profiling.scan_time_per_step(
            make_loop, state, s1=2, s2=6
        )
        ms = per_step * 1e3
        print(
            f"phase {phase}: {ms:8.2f} ms  (delta {ms - prev:+8.2f})",
            flush=True,
        )
        prev = ms


if __name__ == "__main__":
    main()
