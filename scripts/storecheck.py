#!/usr/bin/env python
"""Journal-store integrity checker (`make storecheck`).

Verifies the durable telemetry store's on-disk contract
(``telemetry/store.py``; format in telemetry/SCHEMA.md "Telemetry
history store"): segment checksums against the manifest, the exact
count-conservation ledger, segment ordering, rotation/retention bounds,
and compaction exactness.

With no argument it builds a demo store in a tempdir — a live
``StepRecorder`` with a deliberately tiny ring drained through
rotation, compaction AND retention, with enough events that the ring
wraps many times — then checks every invariant end to end, including
the headline one: ``metrics.from_journal`` over the drained+compacted
store reports all-time counts byte-equal to the live recorder's,
after eviction (the PR 5 exactness claim, verified from disk). With a
PATH it checks a real store's file-level invariants (ST01-ST03,
ST05-ST06).

Usage:
    python scripts/storecheck.py                    # demo store, report
    python scripts/storecheck.py --check [--format=sarif]
    python scripts/storecheck.py /path/to/store     # real store
    python scripts/storecheck.py --keep DIR         # keep the demo store

``--check`` gates the assertions for CI (``scripts/check_all.py``
registry row ``storecheck``): exit 0 clean, 1 findings, 2 usage error;
``--format=sarif`` emits the findings as one SARIF run. The committed
baseline (``analysis/storecheck_baseline.json``) records the
expected-clean contract.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import argparse  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

RULE_DOCS = {
    "ST01": "every closed segment's sha256 must match its manifest "
    "entry (torn/modified segments are corruption, not data)",
    "ST02": "count conservation: manifest all-time counts must equal "
    "retired + closed-segment + active + missed counts, per kind",
    "ST03": "closed segments must cover monotone, non-overlapping seq "
    "ranges, and the drain watermark must be their maximum",
    "ST04": "rotation bound: no closed segment may exceed the "
    "configured segment_events by more than one drain batch",
    "ST05": "retention bound: closed segments must fit the configured "
    "retain_bytes budget after every publish",
    "ST06": "compaction exactness: a summary segment's per-kind counts, "
    "window sketches, state-health corruption ledgers and verbatim "
    "non-step rows must reproduce its raw source exactly",
    "ST07": "end-to-end exactness: metrics.from_journal over the "
    "drained+compacted store must equal the live recorder's all-time "
    "counts after ring eviction, and its grid_state_* corruption "
    "totals must equal a direct walk of the retained segment files",
}

_SELF = "scripts/storecheck.py"


def _finding(rule, message):
    from mpi_grid_redistribute_tpu.analysis.core import Finding

    return Finding(rule=rule, path=_SELF, line=1, col=0, message=message)


def _check_segments(reader, root):
    """ST01 + ST03 over a reader's manifest."""
    from mpi_grid_redistribute_tpu.telemetry import store as store_lib

    findings = []
    try:
        reader.verify()
    except store_lib.StoreCorruptError as e:
        findings.append(_finding("ST01", str(e)))
    man = reader.manifest
    prev_max = None
    prev_name = None
    for seg in man["segments"]:
        lo, hi = seg.get("seq_min"), seg.get("seq_max")
        if lo is None or hi is None or lo > hi:
            findings.append(_finding(
                "ST03",
                f"{seg['name']} has a bad seq range [{lo}, {hi}]",
            ))
            continue
        if prev_max is not None and lo <= prev_max:
            findings.append(_finding(
                "ST03",
                f"{seg['name']} seq range [{lo}, {hi}] overlaps "
                f"{prev_name} (ends at {prev_max})",
            ))
        prev_max, prev_name = hi, seg["name"]
    tail = man.get("active") or (
        man["segments"][-1] if man["segments"] else None
    )
    if tail and tail.get("seq_max") is not None:
        if int(man["drained_seq"]) != int(tail["seq_max"]):
            findings.append(_finding(
                "ST03",
                f"drain watermark {man['drained_seq']} != newest "
                f"segment's seq_max {tail['seq_max']}",
            ))
    return findings


def _check_ledger(man):
    """ST02: exact count conservation across the whole store life."""
    findings = []
    total = {k: int(v) for k, v in man["retired"]["counts"].items()}

    def fold(counts):
        for k, v in counts.items():
            total[k] = total.get(k, 0) + int(v)

    for seg in man["segments"]:
        fold(seg["counts"])
    if man.get("active"):
        fold(man["active"]["counts"])
    fold(man.get("missed", {}))
    declared = {k: int(v) for k, v in man["counts"].items()}
    if total != declared:
        diff = {
            k: (total.get(k, 0), declared.get(k, 0))
            for k in set(total) | set(declared)
            if total.get(k, 0) != declared.get(k, 0)
        }
        findings.append(_finding(
            "ST02",
            f"count ledger broken (ledger vs manifest): {diff}",
        ))
    return findings


def _check_retention(man):
    """ST05 against the manifest's own recorded config."""
    budget = int(man.get("config", {}).get("retain_bytes", 0))
    if not budget:
        return []
    closed = sum(int(s["bytes"]) for s in man["segments"])
    if closed > budget:
        return [_finding(
            "ST05",
            f"closed segments hold {closed} bytes "
            f"(> retain_bytes {budget})",
        )]
    return []


def _check_compaction(reader, root):
    """ST06: re-derive every summary segment's ledger from its file."""
    from mpi_grid_redistribute_tpu.telemetry.store import COMPACT_KINDS

    findings = []
    for seg in reader.manifest["segments"]:
        if seg.get("kind") != "summary":
            continue
        path = os.path.join(root, seg["name"])
        windows = []
        verbatim = {}
        try:
            with open(path, encoding="utf-8") as f:
                for ln in f:
                    if not ln.strip():
                        continue
                    row = json.loads(ln)
                    if row.get("kind") == "store_window":
                        windows.append(row)
                    else:
                        k = row.get("kind")
                        verbatim[k] = verbatim.get(k, 0) + 1
        except (OSError, ValueError) as e:
            findings.append(_finding(
                "ST06", f"{seg['name']} unreadable: {e}"
            ))
            continue
        # re-derived per-kind counts: window ledgers + verbatim rows
        derived = dict(verbatim)
        sketched = 0
        for w in windows:
            for k, v in w.get("counts", {}).items():
                derived[k] = derived.get(k, 0) + int(v)
            sketched += int(w.get("latency", {}).get("count", 0))
        declared = {k: int(v) for k, v in seg["counts"].items()}
        if derived != declared:
            findings.append(_finding(
                "ST06",
                f"{seg['name']} counts diverge from its rows: "
                f"file {derived} vs manifest {declared}",
            ))
        if sum(declared.values()) != int(seg["events"]):
            findings.append(_finding(
                "ST06",
                f"{seg['name']} counts sum "
                f"{sum(declared.values())} != events {seg['events']}",
            ))
        # every step_latency the raw segment held must be in a sketch
        expect = declared.get("step_latency", 0)
        if sketched != expect:
            findings.append(_finding(
                "ST06",
                f"{seg['name']} latency sketches hold {sketched} "
                f"samples, source had {expect} step_latency events",
            ))
        bad_kind = [k for k in verbatim if k in COMPACT_KINDS]
        if bad_kind:
            findings.append(_finding(
                "ST06",
                f"{seg['name']} kept per-step kind(s) {bad_kind} "
                f"verbatim (should be windowed)",
            ))
        # a window that swallowed state_health rows must carry the
        # corruption ledger, or compaction silently forgot corruption
        for w in windows:
            n_state = int(w.get("counts", {}).get("state_health", 0))
            if n_state and "state" not in w:
                findings.append(_finding(
                    "ST06",
                    f"{seg['name']} window at seq {w.get('seq')} holds "
                    f"{n_state} state_health rows but no state ledger",
                ))
    return findings


def _state_totals_from_disk(reader, root):
    """Corrupt-row totals re-derived by walking every retained segment
    file directly: raw ``state_health`` rows plus the ``state`` ledgers
    of compacted windows. The independent ground truth ST07 holds
    ``metrics.from_journal`` (which folds the same two row shapes
    through a different code path) to."""
    totals = {"nan_pos": 0, "nan_vel": 0, "oob": 0}
    man = reader.manifest
    segs = list(man["segments"])
    if man.get("active"):
        segs.append(man["active"])
    for seg in segs:
        with open(os.path.join(root, seg["name"]), encoding="utf-8") as f:
            for ln in f:
                if not ln.strip():
                    continue
                row = json.loads(ln)
                if row.get("kind") == "state_health":
                    for k in totals:
                        totals[k] += int(row.get(k, 0))
                elif row.get("kind") == "store_window":
                    st = row.get("state")
                    if st:
                        for k in totals:
                            totals[k] += int(st.get(k, 0))
    return totals


def check_store(root, batch_bound=None):
    """File-level invariants on any store root. ``batch_bound`` (max
    events one drain can append — the ring capacity in the demo)
    enables the ST04 rotation bound."""
    from mpi_grid_redistribute_tpu.telemetry import store as store_lib

    try:
        reader = store_lib.StoreReader(root)
    except store_lib.StoreCorruptError as e:
        return [_finding("ST01", str(e))], None
    man = reader.manifest
    findings = []
    findings += _check_segments(reader, root)
    findings += _check_ledger(man)
    findings += _check_retention(man)
    findings += _check_compaction(reader, root)
    if batch_bound is not None:
        limit = int(man["config"]["segment_events"]) + int(batch_bound)
        for seg in man["segments"]:
            if int(seg["events"]) > limit:
                findings.append(_finding(
                    "ST04",
                    f"{seg['name']} holds {seg['events']} events "
                    f"(> segment_events + drain batch = {limit})",
                ))
    return findings, reader


def run_demo(out_dir, verbose=True):
    """Build a demo store through rotation/compaction/retention with a
    wrapping ring; returns (findings, reader)."""
    from mpi_grid_redistribute_tpu import telemetry
    from mpi_grid_redistribute_tpu.telemetry import (
        StepRecorder,
        record_chunk_steps,
    )
    from mpi_grid_redistribute_tpu.telemetry import store as store_lib

    root = os.path.join(out_dir, "store")
    capacity = 96
    rec = StepRecorder(capacity=capacity, host="demo", pid=1)
    st = store_lib.JournalStore(
        root,
        segment_events=120,
        segment_bytes=1 << 20,
        retain_bytes=26 << 10,
        compact_after=1,
        compact_window=16,
    )
    # 20 chunks x 45 step_latency events + a sprinkling of non-step
    # events: the 96-slot ring wraps ~9x, rotation closes ~8 segments,
    # compaction summarises all but the newest, retention retires the
    # oldest — every lifecycle path runs. Each chunk also journals a
    # few probed-run state_health rows (ISSUE 20) with two NaN/OOB
    # bursts late enough to survive retention, so the compacted
    # windows' corruption ledgers are exercised non-vacuously
    for chunk in range(20):
        record_chunk_steps(rec, chunk * 45, 0.002, [0] * 45)
        for i in range(3):
            rec.record(
                "state_health",
                step=chunk * 45 + 15 * i,
                live=360,
                nan_pos=4 if (chunk, i) == (16, 1) else 0,
                nan_vel=0,
                oob=2 if (chunk, i) == (18, 2) else 0,
                residual=0,
            )
        if chunk % 4 == 0:
            rec.record(
                "alert", rule="demo_rule", severity="warn",
                reason=f"chunk {chunk}",
            )
        if chunk % 7 == 0:
            rec.record("flow_snapshot", imbalance=1.0 + 0.01 * chunk)
        st.drain(rec)
    st.close(rec)

    findings, reader = check_store(root, batch_bound=capacity)
    if reader is None:
        return findings, None
    man = reader.manifest

    # the demo must actually exercise the machinery it claims to check
    if rec.evicted <= 0:
        findings.append(_finding(
            "ST07", "demo ring never wrapped; exactness check is vacuous"
        ))
    if man["retired"]["segments"] < 1:
        findings.append(_finding(
            "ST05", "demo retention never retired a segment"
        ))
    if not any(s["kind"] == "summary" for s in man["segments"]):
        findings.append(_finding(
            "ST06", "demo compaction never produced a summary segment"
        ))

    # ST07: the headline — counts from disk == live recorder counts
    live = rec.counts()
    stored = reader.counts()
    if stored != live:
        findings.append(_finding(
            "ST07",
            f"store counts != live recorder counts after eviction: "
            f"store {stored} vs live {live}",
        ))
    reg = telemetry.MetricsRegistry.from_journal(reader)
    fam = reg.get("grid_journal_events")  # rendered with _total suffix
    scraped = {}
    for values, child in fam.children():  # labelnames == ("kind",)
        scraped[values[0]] = int(child._value)
    if scraped != {k: int(v) for k, v in live.items()}:
        findings.append(_finding(
            "ST07",
            f"from_journal counters diverge from the live recorder: "
            f"scraped {scraped} vs live {live}",
        ))

    # ST07 state leg: the scrape's corruption totals (raw state_health
    # rows for the newest segments, compacted `state` ledgers for the
    # rest) must equal a direct walk of the retained segment files
    disk = _state_totals_from_disk(reader, root)
    if not (disk["nan_pos"] and disk["oob"]):
        findings.append(_finding(
            "ST06",
            f"demo corruption bursts did not survive to a retained "
            f"segment ({disk}); state-ledger exactness is vacuous",
        ))
    state_scraped = {"nan_pos": 0, "nan_vel": 0, "oob": 0}
    for values, child in reg.get("grid_state_nan").children():
        state_scraped[f"nan_{values[0]}"] = int(child._value)
    for values, child in reg.get("grid_state_oob").children():
        state_scraped["oob"] = int(child._value)
    if state_scraped != disk:
        findings.append(_finding(
            "ST07",
            f"grid_state_* corruption totals diverge from the segment "
            f"files: scraped {state_scraped} vs disk {disk}",
        ))

    if verbose:
        kinds = ", ".join(f"{k}={v}" for k, v in sorted(live.items()))
        print(
            f"demo: {rec.total_recorded} events ({kinds}), "
            f"ring evicted {rec.evicted}"
        )
        print(
            f"demo: store {len(man['segments'])} segments "
            f"(+{man['retired']['segments']} retired, "
            f"{sum(1 for s in man['segments'] if s['kind'] == 'summary')}"
            f" summaries), {man['drains']} drains, missed={man['missed']}"
        )
        h = reader.latency_histogram()
        print(
            f"demo: merged latency histogram n={h.count} "
            f"p99={h.quantile(0.99):.6g}s"
        )
        print(
            f"demo: state corruption totals from disk "
            f"nan_pos={disk['nan_pos']} nan_vel={disk['nan_vel']} "
            f"oob={disk['oob']} (raw rows + compacted ledgers)"
        )
    return findings, reader


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Journal-store integrity checker: demo-store "
        "lifecycle invariants or a real store's file-level contract."
    )
    p.add_argument(
        "path",
        nargs="?",
        default=None,
        help="existing store root to check (default: build and check "
        "a demo store)",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="CI gate mode: findings only, exit 1 when any fire",
    )
    p.add_argument(
        "--format",
        choices=("text", "sarif"),
        default="text",
        help="finding output format (sarif implies --check semantics)",
    )
    p.add_argument(
        "--keep",
        metavar="DIR",
        default=None,
        help="build the demo store in DIR and keep it (default: "
        "tempdir, removed on exit)",
    )
    args = p.parse_args(argv)

    if args.path is not None:
        findings, _ = check_store(args.path)
    else:
        out_dir = args.keep or tempfile.mkdtemp(prefix="storecheck_")
        try:
            findings, _ = run_demo(
                out_dir, verbose=args.format != "sarif"
            )
        finally:
            if args.keep is None:
                shutil.rmtree(out_dir, ignore_errors=True)

    if args.format == "sarif":
        from mpi_grid_redistribute_tpu.analysis.sarif import to_sarif

        json.dump(
            to_sarif(findings, "storecheck", RULE_DOCS),
            sys.stdout,
            indent=2,
        )
        print()
    else:
        for f in findings:
            print(f"{f.rule}: {f.message}")
        if not findings:
            print("storecheck: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
