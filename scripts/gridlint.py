#!/usr/bin/env python
"""Run gridlint, the repo's AST-based SPMD/JIT invariant checker.

Usage:
    python scripts/gridlint.py [paths...] [--format=json] [--check]
    python scripts/gridlint.py --list-rules

See mpi_grid_redistribute_tpu/analysis/__init__.py for the rule table
(G001-G007), suppression syntax, and baseline semantics. The analysis
itself is pure-stdlib ``ast`` work; nothing it scans is executed.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_grid_redistribute_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
