"""Microbench: corner-channel placement of the device-keyed scan deposit.

The deposit's final phase places 8 corner-channel meshes ``[8, M^3]`` onto
the +1-ghost device mesh ``[(M+1)^3]`` and (fully-periodic, Dev=1) folds
the ghost faces back. knockout_deposit measured this at +150 ms for
M=128 — ~500x its ~0.3 ms roofline — because every ``jnp.pad`` that adds
a LOW-side plane on the minor (lane) axis shifts the whole array by one
lane (unaligned relayout), and the naive form does 8 of them.

Variants:
  A. naive: 8x pad to [(M+1)^3] + add, then self-fold      (the engine's
     original form)
  B. grouped: sum the 4 channels sharing each minor-axis offset FIRST on
     [M+1, M+1, M] (high-axis pads only — aligned), then 2 minor-axis
     pads + add + self-fold
  C. rolls: fully-periodic Dev=1 skips the ghost entirely —
     ``total = sum_k roll(block_k, corner_k)`` on [M^3]
     (mathematically equal to fold(pads); different f32 add order)

Usage: python scripts/microbench_placement.py [M]
"""

from __future__ import annotations

import itertools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu.utils import profiling


def variant_a(per_cell, M):
    ghost = (M + 1,) * 3
    total = jnp.zeros(ghost, jnp.float32)
    for k, corner in enumerate(itertools.product((0, 1), repeat=3)):
        pad = [(c, 1 - c) for c in corner]
        total = total + jnp.pad(per_cell[k].reshape(M, M, M), pad)
    return _self_fold(total)


def variant_b(per_cell, M):
    blocks = [per_cell[k].reshape(M, M, M) for k in range(8)]
    groups = []
    for c2 in (0, 1):
        s = jnp.zeros((M + 1, M + 1, M), jnp.float32)
        for k, corner in enumerate(itertools.product((0, 1), repeat=3)):
            if corner[2] != c2:
                continue
            s = s + jnp.pad(
                blocks[k], [(corner[0], 1 - corner[0]),
                            (corner[1], 1 - corner[1]), (0, 0)]
            )
        groups.append(s)
    total = jnp.pad(groups[0], [(0, 0), (0, 0), (0, 1)]) + jnp.pad(
        groups[1], [(0, 0), (0, 0), (1, 0)]
    )
    return _self_fold(total)


def variant_c(per_cell, M):
    total = jnp.zeros((M, M, M), jnp.float32)
    for k, corner in enumerate(itertools.product((0, 1), repeat=3)):
        total = total + jnp.roll(
            per_cell[k].reshape(M, M, M), corner, axis=(0, 1, 2)
        )
    return total


def _self_fold(rho):
    for a in range(3):
        mm = rho.shape[a] - 1
        ghost = jax.lax.slice_in_dim(rho, mm, mm + 1, axis=a)
        body = jax.lax.slice_in_dim(rho, 0, mm, axis=a)
        first = jax.lax.slice_in_dim(body, 0, 1, axis=a) + ghost
        rho = jnp.concatenate(
            [first, jax.lax.slice_in_dim(body, 1, mm, axis=a)], axis=a
        )
    return rho


def main():
    M = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    rng = np.random.default_rng(0)
    per_cell = jax.device_put(
        jnp.asarray(rng.random((8, M * M * M), np.float32))
    )
    ref = None
    for name, fn in (("A naive-pads", variant_a),
                     ("B grouped-pads", variant_b),
                     ("C rolls", variant_c)):
        def make_loop(S, fn=fn):
            @jax.jit
            def loop(x):
                def body(c, _):
                    out = fn(c, M)
                    # fold a data dependency back into the carry
                    return c.at[0, 0].add(out[0, 0, 0] * 1e-20), None

                c, _ = jax.lax.scan(body, x, None, length=S)
                return c

            return loop

        per, _, _ = profiling.scan_time_per_step(
            make_loop, (per_cell,), s1=4, s2=16
        )
        out = np.asarray(jax.jit(fn, static_argnums=1)(per_cell, M))
        tot = out.sum()
        if ref is None:
            ref = tot
        print(
            f"{name}: {per*1e3:8.3f} ms   sum={tot:.6e} "
            f"(rel dev {abs(tot-ref)/abs(ref):.2e})",
            flush=True,
        )


if __name__ == "__main__":
    main()
