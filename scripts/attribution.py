#!/usr/bin/env python
"""Continuous attribution: knockout phase tables + XLA cost-model
rooflines + profiler sessions, one CLI (ISSUE 14).

The BENCH_CONFIGS.md CPU phase tables used to be hand-pasted knockout
output — which is how they went stale for three PRs. This tool makes
the committed snapshot (``telemetry/attribution_baseline.json``) the
single source: measurement writes the snapshot, the markdown tables are
RENDERED from it between ``<!-- attribution:* -->`` markers, and a
structural drift gate runs in ``make check`` so "table is stale" is a
CI failure, not a footnote.

Usage:
    python scripts/attribution.py                      # report view
    python scripts/attribution.py --update-baseline    # re-measure
    python scripts/attribution.py --render             # baseline -> md
    python scripts/attribution.py --check [--format=sarif|json|github]
    python scripts/attribution.py --update-baseline --profile DIR

Modes:
  * ``--update-baseline`` RE-MEASURES: runs the two knockout scripts
    (``knockout_stages.py`` — the migrate step; ``knockout_pipeline.py``
    — the two-phase pipelined engine) as subprocesses at both committed
    shapes, computes the per-program roofline report
    (``telemetry.roofline.roofline_report`` — compiles all registered
    programs and cross-checks XLA's cost model against the J004/S004
    static wire model, journaling every discrepancy), and section-merges
    both into the snapshot. Minutes of CPU; run it when an engine's
    phase structure or cost model changes.
  * ``--render`` is cheap and deterministic: regenerate the
    BENCH_CONFIGS.md tables from the committed snapshot.
  * ``--check`` NEVER re-measures (timings are host-dependent): it
    gates STRUCTURE — the snapshot exists, its phase names/counts match
    the live knockout definitions, its roofline section covers every
    progcheck-registered program, and the rendered markdown matches the
    snapshot byte-for-byte. Exit codes mirror gridlint: 0 clean,
    1 findings, 2 usage error.
  * ``--profile DIR`` wraps the in-process roofline compile pass in a
    ``telemetry.profiler.ProfilerSession`` (journaled, degrades to a
    no-op when profiling is unavailable).
"""

import os
import sys

# the sharded registry programs need the same forced 8-device virtual
# CPU mesh as tests/conftest.py — set BEFORE jax is imported (the
# scripts/progcheck.py idiom)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import argparse  # noqa: E402
import importlib.util  # noqa: E402
import json  # noqa: E402
import subprocess  # noqa: E402
import tempfile  # noqa: E402

from mpi_grid_redistribute_tpu.analysis.baseline import (  # noqa: E402
    attribution_baseline_path,
    load_attribution_baseline,
    write_attribution_baseline,
)
from mpi_grid_redistribute_tpu.analysis.core import Finding  # noqa: E402
from mpi_grid_redistribute_tpu.analysis.sarif import (  # noqa: E402
    github_annotations,
    to_sarif,
)

BENCH_MD = os.path.join(REPO, "BENCH_CONFIGS.md")
GRID = "2,2,2"
SHAPES = (4096, 65536)

# the migrate knockout's cumulative truncation points (knockout_stages
# KNOCKOUT_PHASES; diagnostics 0/41/42/71 are excluded from the
# committed table on purpose) and their table labels
STAGE_PHASES = (1, 2, 3, 4, 5, 6, 7, 8)
STAGE_LABELS = {
    1: "1 drift + wrap + bin",
    2: "2 stable key sort + counts",
    3: "3 local allocation fixpoint",
    4: "4 vacated-slot plan",
    5: "5 arrival gather",
    6: "6 landing plan",
    7: "7 landing (overlay)",
    8: "8 free-stack update (**full step**)",
}

ENGINES = ("migrate", "pipeline")
SCRIPTS = {
    "migrate": "knockout_stages.py",
    "pipeline": "knockout_pipeline.py",
}

RULE_DOCS = {
    "A001": "committed attribution snapshot must exist and its phase "
    "names/counts must match the live knockout definitions",
    "A002": "BENCH_CONFIGS.md rendered CPU phase tables must match the "
    "committed snapshot (run scripts/attribution.py --render)",
    "A003": "the snapshot's roofline section must cover every "
    "progcheck-registered program",
}

_BASELINE_REL = os.path.relpath(attribution_baseline_path(), REPO)


def _pipeline_phases():
    """The pipelined knockout's phase names, from the script itself so
    this gate cannot drift from what the measurement actually cuts."""
    spec = importlib.util.spec_from_file_location(
        "_knockout_pipeline",
        os.path.join(REPO, "scripts", "knockout_pipeline.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return list(mod.PHASES)


def _live_phases(engine):
    if engine == "migrate":
        return list(STAGE_PHASES)
    return _pipeline_phases()


# ---------------------------------------------------------------------
# measurement (--update-baseline)
# ---------------------------------------------------------------------


def _run_knockout(engine, n_local):
    """One knockout subprocess -> its JSON phase rows."""
    script = os.path.join(REPO, "scripts", SCRIPTS[engine])
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "rows.json")
        env = dict(os.environ)
        env["KNOCKOUT_JSON"] = out
        env["KNOCKOUT_GRID"] = GRID
        env["JAX_PLATFORMS"] = "cpu"
        if engine == "migrate":
            env["KNOCKOUT_PHASES"] = ",".join(
                str(p) for p in STAGE_PHASES
            )
        print(
            f"attribution: measuring {engine} @ n_local={n_local} "
            f"(grid {GRID}) ...",
            file=sys.stderr,
            flush=True,
        )
        proc = subprocess.run(
            [sys.executable, script, str(n_local)],
            cwd=REPO,
            env=env,
            stdout=sys.stderr,
            stderr=sys.stderr,
        )
        if proc.returncode != 0:
            raise SystemExit(
                f"attribution: {SCRIPTS[engine]} n_local={n_local} "
                f"failed (exit {proc.returncode})"
            )
        with open(out, "r", encoding="utf-8") as fh:
            return json.load(fh)


def _measure_phase_tables():
    tables = {}
    for engine in ENGINES:
        shapes = {}
        for n in SHAPES:
            shapes[str(n)] = {"rows": _run_knockout(engine, n)}
        tables[engine] = {
            "grid": GRID,
            "phases": _live_phases(engine),
            "shapes": shapes,
        }
    return tables


def _measure_roofline(profile_dir=None):
    from mpi_grid_redistribute_tpu.telemetry.profiler import (
        ProfilerSession,
    )
    from mpi_grid_redistribute_tpu.telemetry.recorder import StepRecorder
    from mpi_grid_redistribute_tpu.telemetry.roofline import (
        roofline_report,
    )

    rec = StepRecorder()
    print(
        "attribution: compiling registered programs for the cost "
        "model ...",
        file=sys.stderr,
        flush=True,
    )
    with ProfilerSession(profile_dir, recorder=rec, label="roofline"):
        report = roofline_report(recorder=rec)
    n_disc = sum(1 for r in report.values() if r["discrepancy"])
    print(
        f"attribution: roofline over {len(report)} programs, "
        f"{n_disc} discrepancy(ies) journaled",
        file=sys.stderr,
    )
    return report


# ---------------------------------------------------------------------
# rendering (baseline -> BENCH_CONFIGS.md)
# ---------------------------------------------------------------------


def _shape_label(grid, n):
    v = 1
    for x in grid.split(","):
        v *= int(x)
    if n % 1024 == 0:
        return f"{v}×{n // 1024}k"
    return f"{v}×{n}"


def _fmt_ms(seconds, bold=False):
    s = f"{seconds * 1e3:.2f}"
    return f"**{s}**" if bold else s


def _fmt_delta(seconds, first):
    if first:
        return "(first)"
    ms = seconds * 1e3
    # unicode minus, matching the hand-written tables this replaces
    return f"+{ms:.2f}" if ms >= 0 else f"−{-ms:.2f}"


def _row_label(engine, phase, last):
    if engine == "migrate":
        return STAGE_LABELS.get(phase, str(phase))
    return f"{phase} (**full**)" if last else str(phase)


def render_table(engine, table):
    """Deterministic markdown for one engine's committed phase table."""
    grid = table["grid"]
    ns = sorted(int(k) for k in table["shapes"])
    header = "| phase (cumulative) |"
    rule = "|---|"
    for n in ns:
        header += f" {_shape_label(grid, n)} ms | delta |"
        rule += "---|---|"
    lines = [header, rule]
    phases = table["phases"]
    for i, phase in enumerate(phases):
        last = i == len(phases) - 1
        cells = [_row_label(engine, phase, last)]
        for n in ns:
            rows = table["shapes"][str(n)]["rows"]
            row = rows[i]
            cells.append(_fmt_ms(row["cumulative_s"], bold=last))
            cells.append(_fmt_delta(row["delta_s"], first=i == 0))
        lines.append("| " + " | ".join(cells) + " |")
    return "\n".join(lines)


def _marker(engine, which):
    return f"<!-- attribution:{engine}:{which} -->"


def _split_markers(text, engine):
    """(before, inside, after) of the engine's marker region, or None
    when the markers are absent/malformed."""
    begin, end = _marker(engine, "begin"), _marker(engine, "end")
    i = text.find(begin)
    j = text.find(end)
    if i < 0 or j < 0 or j <= i:
        return None
    i_end = i + len(begin)
    return text[:i_end], text[i_end:j], text[j:]


def render_markdown(doc, text):
    """BENCH_CONFIGS.md content with every marker region re-rendered
    from the snapshot ``doc``; raises SystemExit on missing markers."""
    tables = doc.get("phase_tables") or {}
    for engine in ENGINES:
        if engine not in tables:
            raise SystemExit(
                f"attribution: snapshot has no phase_tables[{engine!r}] "
                "— run --update-baseline first"
            )
        parts = _split_markers(text, engine)
        if parts is None:
            raise SystemExit(
                f"attribution: BENCH_CONFIGS.md is missing the "
                f"{_marker(engine, 'begin')} / "
                f"{_marker(engine, 'end')} markers"
            )
        before, _, after = parts
        text = (
            before + "\n" + render_table(engine, tables[engine]) + "\n"
            + after
        )
    return text


# ---------------------------------------------------------------------
# the drift gate (--check)
# ---------------------------------------------------------------------


def check_findings():
    """Structural findings against the committed snapshot. Never
    re-measures: timings are host-dependent, structure is not."""
    findings = []

    def fail(rule, path, msg):
        findings.append(Finding(rule, path, 1, 0, msg, "attribution"))

    doc = load_attribution_baseline()
    if doc is None:
        fail(
            "A001",
            _BASELINE_REL,
            "no committed attribution snapshot — run "
            "scripts/attribution.py --update-baseline",
        )
        return findings

    tables = doc.get("phase_tables") or {}
    for engine in ENGINES:
        table = tables.get(engine)
        if table is None:
            fail(
                "A001",
                _BASELINE_REL,
                f"snapshot has no phase_tables[{engine!r}] section — "
                "run --update-baseline",
            )
            continue
        live = _live_phases(engine)
        committed = table.get("phases")
        if committed != live:
            fail(
                "A001",
                _BASELINE_REL,
                f"phase_tables[{engine!r}].phases {committed!r} != the "
                f"live knockout definition {live!r} — the engine's "
                "phase structure changed; run --update-baseline",
            )
            continue
        for n, shape in sorted((table.get("shapes") or {}).items()):
            got = [r.get("phase") for r in shape.get("rows", [])]
            if got != live:
                fail(
                    "A001",
                    _BASELINE_REL,
                    f"phase_tables[{engine!r}] shape {n}: measured row "
                    f"phases {got!r} != the live knockout definition "
                    f"{live!r} — run --update-baseline",
                )

    # roofline coverage: every registered program, no strays. Program
    # REGISTRATION is jax-cheap (no tracing/compiling happens here).
    from mpi_grid_redistribute_tpu.analysis import progcheck

    want = sorted(progcheck.default_programs())
    have = sorted(doc.get("roofline") or {})
    for name in want:
        if name not in have:
            fail(
                "A003",
                _BASELINE_REL,
                f"registered program {name!r} missing from the "
                "roofline section — run --update-baseline",
            )
    for name in have:
        if name not in want:
            fail(
                "A003",
                _BASELINE_REL,
                f"roofline section names {name!r}, which is not a "
                "registered program — run --update-baseline",
            )

    # rendered-markdown drift: the committed tables must be exactly
    # what --render would produce from the committed snapshot
    if not findings:
        with open(BENCH_MD, "r", encoding="utf-8") as fh:
            text = fh.read()
        for engine in ENGINES:
            parts = _split_markers(text, engine)
            if parts is None:
                fail(
                    "A002",
                    "BENCH_CONFIGS.md",
                    f"missing {_marker(engine, 'begin')} markers for "
                    "the rendered phase table",
                )
                continue
            _, inside, _ = parts
            want_md = render_table(engine, tables[engine])
            if inside.strip("\n") != want_md:
                fail(
                    "A002",
                    "BENCH_CONFIGS.md",
                    f"the rendered {engine} phase table is stale vs "
                    "the committed snapshot — run "
                    "scripts/attribution.py --render",
                )
    return findings


# ---------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------


def _emit(findings, fmt):
    if fmt == "sarif":
        print(
            json.dumps(
                to_sarif(findings, "attribution", RULE_DOCS), indent=2
            )
        )
    elif fmt == "json":
        print(
            json.dumps(
                [
                    {
                        "rule": f.rule,
                        "path": f.path,
                        "message": f.message,
                    }
                    for f in findings
                ],
                indent=2,
            )
        )
    elif fmt == "github":
        for line in github_annotations(findings):
            print(line)
    else:
        for f in findings:
            print(f"{f.path}: {f.rule} {f.message}")
        if not findings:
            print("attribution: clean")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="attribution",
        description="knockout phase tables + cost-model rooflines: "
        "measure, render, and gate the committed attribution snapshot",
    )
    p.add_argument(
        "--update-baseline",
        action="store_true",
        help="re-measure (knockout subprocesses + roofline compile "
        "pass) and rewrite the committed snapshot",
    )
    p.add_argument(
        "--render",
        action="store_true",
        help="regenerate the BENCH_CONFIGS.md tables from the snapshot",
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="structural drift gate (never re-measures)",
    )
    p.add_argument(
        "--format",
        default="text",
        choices=("text", "json", "sarif", "github"),
        dest="fmt",
    )
    p.add_argument(
        "--profile",
        default=None,
        metavar="DIR",
        help="wrap the roofline compile pass in a ProfilerSession "
        "writing a jax.profiler trace into DIR",
    )
    args = p.parse_args(argv)

    if args.update_baseline:
        tables = _measure_phase_tables()
        roofline = {
            name: row
            for name, row in _measure_roofline(args.profile).items()
        }
        write_attribution_baseline(
            None, phase_tables=tables, roofline=roofline
        )
        print(
            f"attribution: wrote {_BASELINE_REL} "
            f"({len(tables)} phase tables, {len(roofline)} roofline "
            "rows)",
            file=sys.stderr,
        )

    if args.render:
        doc = load_attribution_baseline()
        if doc is None:
            print(
                "attribution: no snapshot to render — run "
                "--update-baseline first",
                file=sys.stderr,
            )
            return 2
        with open(BENCH_MD, "r", encoding="utf-8") as fh:
            text = fh.read()
        new = render_markdown(doc, text)
        if new != text:
            with open(BENCH_MD, "w", encoding="utf-8") as fh:
                fh.write(new)
            print(
                "attribution: re-rendered BENCH_CONFIGS.md phase "
                "tables",
                file=sys.stderr,
            )
        else:
            print(
                "attribution: BENCH_CONFIGS.md already current",
                file=sys.stderr,
            )

    if args.check:
        findings = check_findings()
        _emit(findings, args.fmt)
        return 1 if findings else 0

    if not (args.update_baseline or args.render):
        # report view: the committed snapshot, human-readable
        doc = load_attribution_baseline()
        if doc is None:
            print(
                "attribution: no committed snapshot — run "
                "--update-baseline",
                file=sys.stderr,
            )
            return 2
        from mpi_grid_redistribute_tpu.telemetry.roofline import (
            format_roofline_table,
        )

        for engine in ENGINES:
            table = (doc.get("phase_tables") or {}).get(engine)
            if table:
                print(f"## {engine} (grid {table['grid']})")
                print(render_table(engine, table))
                print()
        rl = doc.get("roofline") or {}
        if rl:
            print("## roofline (XLA cost model vs chip roofs)")
            print(format_roofline_table(rl))
    return 0


if __name__ == "__main__":
    sys.exit(main())
