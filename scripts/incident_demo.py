#!/usr/bin/env python
"""End-to-end incident-observatory smoke (`make incident-demo`).

Runs the whole ISSUE 17 loop in-process on the numpy backend, in
seconds: a supervised service run with an injected latency-spike flood
breaches the p99 SLO, the health pass fires the
:class:`~mpi_grid_redistribute_tpu.telemetry.incident.FlightRecorder`,
and the resulting bundles are verified end to end —

* at least one debounced bundle exists (fault- and alert-triggered);
* every ``index.json`` carries the triggering step context (``trace``
  join key from ``telemetry/context.py``);
* a standing rule re-confirmed across restarts stays debounced to ONE
  bundle;
* the frozen journal window exports to a Perfetto trace whose causal
  flow arrows (``ph="s"/"f"``) link the cause step to the alert.

Usage:
    python scripts/incident_demo.py                    # report view
    python scripts/incident_demo.py --check [--format=sarif]
    python scripts/incident_demo.py --keep DIR         # keep bundles

``--check`` gates the same assertions for CI (``scripts/check_all.py``
registry row ``incident-demo``): exit 0 clean, 1 findings, 2 usage
error; ``--format=sarif`` emits the findings as one SARIF run. The
committed baseline (``analysis/incident_demo_baseline.json``) records
the expected-clean contract.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import argparse  # noqa: E402
import dataclasses  # noqa: E402
import shutil  # noqa: E402
import tempfile  # noqa: E402

RULE_DOCS = {
    "I001": "a fault-injected supervised run must leave at least one "
    "incident bundle behind (alert- and fault-triggered)",
    "I002": "every bundle index must carry the triggering step context "
    "(trace join key)",
    "I003": "a standing alert re-confirmed across restarts must stay "
    "debounced to one bundle per rule",
    "I004": "a bundle's frozen journal must export to a Perfetto trace "
    "with causal flow arrows",
}

_SELF = "scripts/incident_demo.py"


def _finding(rule, message):
    from mpi_grid_redistribute_tpu.analysis.core import Finding

    return Finding(rule=rule, path=_SELF, line=1, col=0, message=message)


def run_demo(out_dir, verbose=True):
    """Drive the incident loop; returns (findings, bundle entries)."""
    from mpi_grid_redistribute_tpu.service import (
        DriverConfig,
        FaultPlan,
        LatencySpikeFault,
        RestartPolicy,
        ServiceDriver,
        Supervisor,
    )
    from mpi_grid_redistribute_tpu.telemetry import (
        StepRecorder,
        incident,
        merge_journals,
        traceview,
    )

    snaps = os.path.join(out_dir, "snaps")
    bundles = os.path.join(out_dir, "incidents")
    cfg = DriverConfig(
        grid_shape=(2, 2, 2),
        n_local=256,
        steps=32,
        seed=3,
        backend="numpy",
        snapshot_every=4,
        snapshot_dir=snaps,
        slo_latency_p99_s=0.25,
        slo_window=4,
        incident_dir=bundles,
    )
    rec = StepRecorder()
    plan = FaultPlan([LatencySpikeFault(2, seconds=1.0, spikes=6)])

    def factory(grid_shape=None):
        c = cfg
        if grid_shape is not None:
            c = dataclasses.replace(c, grid_shape=tuple(grid_shape))
        return ServiceDriver(c, recorder=rec, faults=plan)

    sup = Supervisor(
        factory,
        policy=RestartPolicy(
            max_restarts=5, backoff_base_s=0.01, backoff_cap_s=0.02,
            shrink_after=2,
        ),
        recorder=rec,
        sleep_fn=lambda s: None,
    )
    verdict = sup.run()
    if verbose:
        print(
            f"demo: supervised run done (ok={verdict.ok} "
            f"restarts={verdict.restarts} health={verdict.health})"
        )

    findings = []
    entries = incident.list_bundles(bundles)
    if verbose:
        for e in entries:
            print(
                f"demo: bundle {e.get('id')} rule={e.get('rule')} "
                f"trigger={e.get('trigger')} "
                f"trace={(e.get('context') or {}).get('trace')}"
            )
    if not entries:
        findings.append(_finding(
            "I001", "supervised fault run produced no incident bundles"
        ))
        return findings, entries
    triggers = {e.get("trigger") for e in entries}
    if not {"alert", "fault"} <= triggers:
        findings.append(_finding(
            "I001",
            f"expected both alert- and fault-triggered bundles, "
            f"got triggers {sorted(triggers)}",
        ))
    for e in entries:
        ctx = e.get("context") or {}
        if not ctx.get("trace"):
            findings.append(_finding(
                "I002",
                f"bundle {e.get('id')} index carries no trace id "
                f"(context={ctx})",
            ))
    rules = [e.get("rule") for e in entries]
    dupes = sorted({r for r in rules if rules.count(r) > 1})
    if dupes:
        findings.append(_finding(
            "I003",
            f"debounce failed: multiple bundles for rule(s) {dupes}",
        ))

    # export smoke: the alert-triggered bundle's frozen journal ->
    # Perfetto trace; the causal flow arrows must link cause -> alert
    target = next(
        (e for e in entries if e.get("trigger") == "alert"), entries[0]
    )
    journal = os.path.join(
        bundles, str(target.get("id")), "journal.jsonl"
    )
    trace_out = os.path.join(out_dir, "incident.trace.json")
    try:
        merged = merge_journals([journal])
        traceview.write_trace(trace_out, merged.to_recorder())
        with open(trace_out, "r", encoding="utf-8") as fh:
            events = json.load(fh)["traceEvents"]
        phases = {ev.get("ph") for ev in events}
        if not {"s", "f"} <= phases:
            findings.append(_finding(
                "I004",
                f"exported trace of {target.get('id')} has no causal "
                f"flow arrows (phases={sorted(phases)})",
            ))
        elif verbose:
            n_flow = sum(1 for ev in events if ev.get("ph") in ("s", "f"))
            print(
                f"demo: exported {trace_out} "
                f"({len(events)} events, {n_flow} flow endpoints)"
            )
    except Exception as exc:
        findings.append(_finding(
            "I004",
            f"bundle export failed: {type(exc).__name__}: {exc}",
        ))
    return findings, entries


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Fault-injected incident-observatory smoke: "
        "supervised run -> flight-recorder bundles -> Perfetto export."
    )
    p.add_argument(
        "--check",
        action="store_true",
        help="CI gate mode: findings only, exit 1 when any fire",
    )
    p.add_argument(
        "--format",
        choices=("text", "sarif"),
        default="text",
        help="finding output format (sarif implies --check semantics)",
    )
    p.add_argument(
        "--keep",
        metavar="DIR",
        default=None,
        help="run in DIR and keep the bundles (default: tempdir, "
        "removed on exit)",
    )
    args = p.parse_args(argv)

    out_dir = args.keep or tempfile.mkdtemp(prefix="incident_demo_")
    try:
        findings, _ = run_demo(out_dir, verbose=args.format != "sarif")
    finally:
        if args.keep is None:
            shutil.rmtree(out_dir, ignore_errors=True)

    if args.format == "sarif":
        from mpi_grid_redistribute_tpu.analysis.sarif import to_sarif

        json.dump(
            to_sarif(findings, "incident-demo", RULE_DOCS),
            sys.stdout,
            indent=2,
        )
        print()
    else:
        for f in findings:
            print(f"{f.rule}: {f.message}")
        if not findings:
            print("incident-demo: clean")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
