"""Microbench: [K, n] (transposed, lane-major) vs [n, K] (row-major) layouts.

Drives the round-3 redesign (VERDICT round-2 items 1-2): the migrate scan
carry must become ``[K, n]`` so no narrow-minor rank-2 buffer materializes
(T(8,128) tiling pads ``[n, 7]`` 18x at carry boundaries — 32 GB at 64M
rows).  The open question is what the pack gather and landing scatter cost
in that layout:

  1. column gather ``x[:, idx]``     on [8, n]  vs row gather    on [n, 8]
  2. column scatter ``x.at[:, t]``   on [8, n]  vs row scatter   on [n, 8]
  3. sorted-target column scatter (the write plan can be sorted cheaply)
  4. contiguous tail landing: dynamic_update_slice [8, P] into [8, n]
  5. 1-D scatter of P elements into [n] (alive-kill cost floor)
  6. transpose [n, 8] -> [8, n] at size (materialization cost)

Usage: python scripts/microbench_layout.py  (from /root/repo)
"""

from __future__ import annotations

import sys

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.utils import profiling

N = 2**23  # resident columns/rows
P = 2**18  # rows moved per step
K = 8


def timed(name, make_loop, args, s1=4, s2=16):
    per_step, _, _ = profiling.scan_time_per_step(make_loop, args, s1=s1, s2=s2)
    print(f"  {name:46s} {per_step*1e3:8.3f} ms  {per_step*1e9/P:7.1f} ns/row",
          file=sys.stderr, flush=True)
    return per_step * 1e3


def _idx(sorted_idx=False, n=N, p=P):
    rng = np.random.default_rng(0)
    idx = rng.integers(0, n, size=(p,), dtype=np.int32)
    if sorted_idx:
        idx = np.sort(idx)
    return jax.device_put(jnp.asarray(idx))


def _chain(i, dep):
    # thread a dependency through a float-underflow product so XLA cannot
    # constant-fold the loop body away (memory: int *0 folds)
    return (i + (dep * 1e-38).astype(jnp.int32)) % N


def bench_row_gather():
    rng = np.random.default_rng(1)
    arr = jax.device_put(jnp.asarray(rng.random((N, K), dtype=np.float32)))
    idx = _idx()

    def make_loop(S):
        @jax.jit
        def loop(arr, idx):
            def body(carry, _):
                a, i = carry
                out = jnp.take(a, i, axis=0)
                (a, i, out) = lax.optimization_barrier((a, i, out))
                i = _chain(i, out[0, 0])
                return (a, i), ()
            return lax.scan(body, (arr, idx), None, length=S)[0]
        return loop
    return make_loop, (arr, idx)


def bench_col_gather(sorted_idx=False):
    rng = np.random.default_rng(1)
    arr = jax.device_put(jnp.asarray(rng.random((K, N), dtype=np.float32)))
    idx = _idx(sorted_idx)

    def make_loop(S):
        @jax.jit
        def loop(arr, idx):
            def body(carry, _):
                a, i = carry
                out = jnp.take(a, i, axis=1)
                (a, i, out) = lax.optimization_barrier((a, i, out))
                i = _chain(i, out[0, 0])
                return (a, i), ()
            return lax.scan(body, (arr, idx), None, length=S)[0]
        return loop
    return make_loop, (arr, idx)


def bench_row_scatter(sorted_idx=False):
    rng = np.random.default_rng(2)
    arr = jax.device_put(jnp.asarray(rng.random((N, K), dtype=np.float32)))
    rows = jax.device_put(jnp.asarray(rng.random((P, K), dtype=np.float32)))
    idx = _idx(sorted_idx)

    def make_loop(S):
        @jax.jit
        def loop(arr, idx, rows):
            def body(carry, _):
                a, i = carry
                a = a.at[i].set(rows, mode="drop")
                (a, i) = lax.optimization_barrier((a, i))
                i = _chain(i, a[0, 0])
                return (a, i), ()
            return lax.scan(body, (arr, idx), None, length=S)[0]
        return loop
    return make_loop, (arr, idx, rows)


def bench_col_scatter(sorted_idx=False):
    rng = np.random.default_rng(2)
    arr = jax.device_put(jnp.asarray(rng.random((K, N), dtype=np.float32)))
    cols = jax.device_put(jnp.asarray(rng.random((K, P), dtype=np.float32)))
    idx = _idx(sorted_idx)

    def make_loop(S):
        @jax.jit
        def loop(arr, idx, cols):
            def body(carry, _):
                a, i = carry
                a = a.at[:, i].set(cols, mode="drop")
                (a, i) = lax.optimization_barrier((a, i))
                i = _chain(i, a[0, 0])
                return (a, i), ()
            return lax.scan(body, (arr, idx, cols)[:2], None, length=S)[0]
        return loop
    return make_loop, (arr, idx, cols)


def bench_tail_dus():
    rng = np.random.default_rng(3)
    arr = jax.device_put(jnp.asarray(rng.random((K, N), dtype=np.float32)))
    cols = jax.device_put(jnp.asarray(rng.random((K, P), dtype=np.float32)))

    def make_loop(S):
        @jax.jit
        def loop(arr, cols):
            def body(carry, _):
                a, off = carry
                a = lax.dynamic_update_slice(a, cols, (0, off))
                (a,) = lax.optimization_barrier((a,))
                off = (off + 1 + (a[0, 0] * 1e-38).astype(jnp.int32)) % (N - P)
                return (a, off), ()
            return lax.scan(body, (arr, jnp.int32(0)), None, length=S)[0]
        return loop
    return make_loop, (arr, cols)


def bench_scatter_1d(sorted_idx=False):
    rng = np.random.default_rng(4)
    arr = jax.device_put(jnp.asarray(rng.random((N,), dtype=np.float32)))
    vals = jax.device_put(jnp.asarray(rng.random((P,), dtype=np.float32)))
    idx = _idx(sorted_idx)

    def make_loop(S):
        @jax.jit
        def loop(arr, idx, vals):
            def body(carry, _):
                a, i = carry
                a = a.at[i].set(vals, mode="drop")
                (a, i) = lax.optimization_barrier((a, i))
                i = _chain(i, a[0])
                return (a, i), ()
            return lax.scan(body, (arr, idx, vals)[:2], None, length=S)[0]
        return loop
    return make_loop, (arr, idx, vals)


def bench_transpose():
    rng = np.random.default_rng(5)
    arr = jax.device_put(jnp.asarray(rng.random((N, K), dtype=np.float32)))

    def make_loop(S):
        @jax.jit
        def loop(arr):
            def body(a, _):
                t = a.T
                (t,) = lax.optimization_barrier((t,))
                a = t.T
                (a,) = lax.optimization_barrier((a,))
                return a, ()
            return lax.scan(body, arr, None, length=S)[0]
        return loop
    return make_loop, (arr,)


def main():
    print(f"n={N} ({N/1e6:.1f}M), P={P} ({P/1e3:.0f}k), K={K}",
          file=sys.stderr)
    ml, args = bench_row_gather()
    timed("row gather  [n,8] random", ml, args)
    ml, args = bench_col_gather()
    timed("col gather  [8,n] random", ml, args)
    ml, args = bench_col_gather(sorted_idx=True)
    timed("col gather  [8,n] SORTED", ml, args)
    ml, args = bench_row_scatter()
    timed("row scatter [n,8] random", ml, args)
    ml, args = bench_row_scatter(sorted_idx=True)
    timed("row scatter [n,8] SORTED", ml, args)
    ml, args = bench_col_scatter()
    timed("col scatter [8,n] random", ml, args)
    ml, args = bench_col_scatter(sorted_idx=True)
    timed("col scatter [8,n] SORTED", ml, args)
    ml, args = bench_tail_dus()
    timed("tail DUS    [8,P] into [8,n]", ml, args)
    ml, args = bench_scatter_1d()
    timed("1-D scatter [n] random", ml, args)
    ml, args = bench_scatter_1d(sorted_idx=True)
    timed("1-D scatter [n] SORTED", ml, args)
    ml, args = bench_transpose()
    timed("transpose   [n,8]<->[8,n] x2 (per pair)", ml, args)


if __name__ == "__main__":
    main()
