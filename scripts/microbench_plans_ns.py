"""On-chip: plan-phase formulations at the 64M north-star shape.

Round-4 knockout at 64 vranks: phase 4 (vacated plan) +56.1 ms, phase 6
(landing plan) +30.8 ms, phase 8 (stack update) +12.1 ms — all thousands
of x over their logical-byte rooflines. Candidate causes measured here:

  A. `_segment_of_auto` switches to vmapped searchsorted(method="sort")
     once cum has > 33 entries — exactly at V=64 (65-entry tables); the
     V=8 headline still used the vectorized comparison-count.
  B. vmapped per-vrank gathers `order[pos]` / `take_along_axis` vs ONE
     flat `jnp.take` with globally-indexed columns.

Usage: python scripts/microbench_plans_ns.py
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.parallel import migrate
from mpi_grid_redistribute_tpu.utils import profiling

V, n, M = 64, 1 << 20, 24_537


def timed(name, fn, *args):
    def make_loop(S):
        @jax.jit
        def loop(*a):
            def body(acc, _):
                return fn(*a[1:], acc), ()

            acc, _ = lax.scan(body, a[0], None, length=S)
            return acc

        return loop

    per, _, _ = profiling.scan_time_per_step(make_loop, args, s1=2, s2=10)
    print(f"  {name}: {per*1e3:8.2f} ms", flush=True)
    return per


def main():
    r = np.random.default_rng(0)
    # realistic inputs: per-vrank sorted-order permutations, allowed
    # counts summing to ~M*0.8, free stacks
    order = np.stack([r.permutation(n).astype(np.int32) for _ in range(V)])
    allowed = r.integers(0, 2 * M // V, size=(V, V)).astype(np.int32)
    loc_starts = np.cumsum(
        np.concatenate([np.zeros((V, 1), np.int32), allowed], axis=1)[:, :-1],
        axis=1,
    ).astype(np.int32)
    free_stack = np.stack(
        [r.permutation(n).astype(np.int32) for _ in range(V)]
    )
    n_free = r.integers(M, n // 2, size=V).astype(np.int32)
    n_sent = np.minimum(allowed.sum(1), M).astype(np.int32)
    n_in = np.minimum(allowed.sum(0), M).astype(np.int32)

    od = jax.device_put(jnp.asarray(order))
    ad = jax.device_put(jnp.asarray(allowed))
    ld = jax.device_put(jnp.asarray(loc_starts))
    fsd = jax.device_put(jnp.asarray(free_stack))
    nfd = jax.device_put(jnp.asarray(n_free))
    nsd = jax.device_put(jnp.asarray(n_sent))
    nid = jax.device_put(jnp.asarray(n_in))
    acc0 = jax.device_put(jnp.zeros((8, 128), jnp.int32))

    def dep(acc, *arrs):
        # consume the FULL array (sum reduction): a 1-element probe lets
        # XLA slice through gathers and DCE the work being measured
        for a in arrs:
            acc = acc.at[0, 0].add(jnp.sum(a.astype(jnp.int32)))
        return acc

    # ---- phase 4: vacated plan --------------------------------------
    def plan_current(ls, al, o, acc):
        vac, _ = jax.vmap(lambda ss, sc, oo: migrate._plan_rows(ss, sc, oo, M))(
            ls, al, o
        )
        return dep(acc, vac)

    def plan_segof(ls, al, o, acc):
        # comparison-count segment_of + flat take
        j = jnp.arange(M, dtype=jnp.int32)
        cum = jnp.concatenate(
            [jnp.zeros((V, 1), jnp.int32), jnp.cumsum(al, axis=1)], axis=1
        )
        seg = jnp.clip(
            jax.vmap(lambda c: migrate._segment_of(j, c))(cum), 0, V - 1
        )  # [V, M]
        pos = jnp.take_along_axis(ls, seg, axis=1) + (
            j[None, :] - jnp.take_along_axis(cum, seg, axis=1)
        )
        gidx = (
            jnp.arange(V, dtype=jnp.int32)[:, None] * n
            + jnp.clip(pos, 0, n - 1)
        )
        vac = jnp.take(o.reshape(-1), gidx.reshape(-1)).reshape(V, M)
        return dep(acc, vac)

    print("phase 4 (vacated plan):", flush=True)
    timed("current (_segment_of_auto + vmapped order[pos])", plan_current,
          acc0, ld, ad, od)
    timed("segof-compare + flat take", plan_segof, acc0, ld, ad, od)

    # ---- phase 6: landing plan --------------------------------------
    vac0 = jax.device_put(
        jnp.asarray(r.integers(0, n, size=(V, M)).astype(np.int32))
    )

    def land_current(vac, nin, nsent, nf, fs, acc):
        k_idx = jnp.arange(M, dtype=jnp.int32)

        def lp(vacv, ninv, nsentv, nfv):
            n_pop = jnp.clip(ninv - nsentv, 0, nfv)
            pop_idx = jnp.clip(nfv - 1 - (k_idx - nsentv), 0, n - 1)
            target = jnp.where(
                k_idx < jnp.minimum(ninv, nsentv),
                vacv,
                jnp.where(
                    (k_idx >= nsentv) & (k_idx < nsentv + n_pop),
                    jnp.zeros((), jnp.int32),
                    jnp.where(
                        (k_idx >= ninv) & (k_idx < nsentv), vacv, n
                    ),
                ),
            )
            return target, n_pop, pop_idx

        targets, n_pop, pop_idx = jax.vmap(lp)(vac, nin, nsent, nf)
        pops = jnp.take_along_axis(fs, pop_idx, axis=1)
        use_pop = (k_idx[None, :] >= nsent[:, None]) & (
            k_idx[None, :] < (nsent + n_pop)[:, None]
        )
        targets = jnp.where(use_pop, pops, targets)
        return dep(acc, targets)

    def land_flat(vac, nin, nsent, nf, fs, acc):
        k_idx = jnp.arange(M, dtype=jnp.int32)[None, :]
        n_pop = jnp.clip(nin - nsent, 0, nf)[:, None]
        pop_idx = jnp.clip(
            nf[:, None] - 1 - (k_idx - nsent[:, None]), 0, n - 1
        )
        gpop = jnp.arange(V, dtype=jnp.int32)[:, None] * n + pop_idx
        pops = jnp.take(fs.reshape(-1), gpop.reshape(-1)).reshape(V, M)
        nin_b, nsent_b = nin[:, None], nsent[:, None]
        target = jnp.where(
            k_idx < jnp.minimum(nin_b, nsent_b),
            vac,
            jnp.where(
                (k_idx >= nsent_b) & (k_idx < nsent_b + n_pop),
                pops,
                jnp.where(
                    (k_idx >= nin_b) & (k_idx < nsent_b), vac, n
                ),
            ),
        )
        return dep(acc, target)

    print("phase 6 (landing plan):", flush=True)
    timed("current (vmapped + take_along_axis)", land_current,
          acc0, vac0, nid, nsd, nfd, fsd)
    timed("broadcast + flat take", land_flat,
          acc0, vac0, nid, nsd, nfd, fsd)

    # ---- phase 8: stack update --------------------------------------
    npop0 = jax.device_put(
        jnp.asarray(r.integers(0, M // 2, size=V).astype(np.int32))
    )
    npush0 = jax.device_put(
        jnp.asarray(r.integers(0, M // 2, size=V).astype(np.int32))
    )

    def stack_current(fs, nf, npop, npush, vac, nin, acc):
        fs2, nf2 = jax.vmap(migrate._stack_push_pop)(
            fs, nf, npop, npush, vac, nin
        )
        return dep(acc, fs2, nf2)

    print("phase 8 (stack update):", flush=True)
    timed("current (vmapped window blend)", stack_current,
          acc0, fsd, nfd, npop0, npush0, vac0, nid)


if __name__ == "__main__":
    main()
