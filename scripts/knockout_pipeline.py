"""Knockout attribution of the PIPELINED macro-step's iteration phases
(ISSUE 12), through ``telemetry.phases.attribute_phases``.

Unlike ``scripts/knockout_stages.py`` — which must maintain a deliberate
truncatable COPY of the migrate step — the two-phase engine's surface
(``migrate.vrank_exchange_two_phase_fn``: ``bin_key`` / ``issue`` /
``land``) is already split at exactly the boundaries a truncating
profiler needs, so this script composes the REAL kernels and cuts
between them: nothing here can drift out of sync with the engine.

The iteration is attributed in issue-first order (drift -> bin ->
issue -> arrival gather -> fused landing). The pipelined and sequential
orderings of ``service/pipeline.py``'s scan body run these same kernels
(the ``lax.cond`` branches are bit-identical by construction), so the
per-phase costs carry over to BOTH schedules on a platform with no real
compute/communication overlap (CPU — where this engine is currently
gated). On a chip, re-attribute with the profiler trace instead: the
point of the pipelined schedule there is that "issue" and "landing" of
ADJACENT steps overlap, which cumulative truncation cannot see.

Usage: JAX_PLATFORMS=cpu python scripts/knockout_pipeline.py [n_local]
       KNOCKOUT_GRID=2,2,2 (default)  KNOCKOUT_JSON=file dumps the rows
       for scripts/trace_export.py --phases.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning, pack
from mpi_grid_redistribute_tpu.parallel import migrate
from mpi_grid_redistribute_tpu.telemetry import phases as phases_lib

GRID = tuple(
    int(x) for x in os.environ.get("KNOCKOUT_GRID", "2,2,2").split(",")
)
FILL = 0.9
K = 7  # 3 pos + 3 vel + alive, the service payload
HBM_PEAK = 819e9

PHASES = (
    "1 drift + wrap",
    "2 bin (routing key)",
    "3 issue (sort + flow-control plans)",
    "4 arrival gather",
    "5 landing (fused scatter + free-stack)",
)


def phase_bytes(V, n):
    """Minimum logical traffic per phase (same convention as
    ``scripts/knockout_stages.py``: measured/roofline >> 1 flags a
    latency/serialization bound, not a bandwidth wall)."""
    f32 = 4
    return {
        PHASES[0]: (3 + 3 + 3) * V * n * f32,   # read pos+vel, write pos
        PHASES[1]: (3 + 1 + 1) * V * n * f32,   # read pos+alive, write key
        PHASES[2]: 4 * V * n * f32,             # sort in/out of (key, iota)
        PHASES[3]: 2 * K * V * n * f32,         # gather in + out
        PHASES[4]: (K + 1 + 2) * V * n * f32,   # scatter + targets + stack
    }


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 4096
    vgrid = ProcessGrid(GRID)
    V = vgrid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    tp = migrate.vrank_exchange_two_phase_fn(domain, vgrid, n)

    rng = np.random.default_rng(0)
    fused = rng.random((K, V * n), dtype=np.float32).view(np.int32)
    fused[-1, :] = (rng.random((V * n,)) < FILL).astype(np.int32)
    state = migrate.init_state(
        jax.device_put(jnp.asarray(fused)), vranks=V, batched=True
    )
    print(f"shapes: V={V} n={n} (plan width = n)", file=sys.stderr)

    def loop_builder(phase, S):
        @jax.jit
        def loop(fused, free_stack, n_free):
            def dep_out(T, stack, nf, *arrs):
                # fold a tiny dependency into the carry so nothing is
                # DCE'd (the knockout_stages idiom)
                d = jnp.int32(0)
                for a in arrs:
                    d = d + (
                        a.ravel()[0] == jnp.asarray(7, a.dtype)
                    ).astype(jnp.int32)
                return T.at[0, 0].add(d.astype(T.dtype)), stack, nf

            def body(carry, _):
                T, stack, nf = carry
                pf = lax.bitcast_convert_type(T[:3, :], jnp.float32)
                vf = lax.bitcast_convert_type(T[3:6, :], jnp.float32)
                p = binning.wrap_periodic_planar(
                    pf + vf * jnp.float32(1e-4), domain
                )
                U = jnp.concatenate(
                    [lax.bitcast_convert_type(p, jnp.int32), T[3:, :]],
                    axis=0,
                )
                if phase == PHASES[0]:
                    return dep_out(U, stack, nf), ()
                key = tp.bin_key(U)
                if phase == PHASES[1]:
                    return dep_out(U, stack, nf, key), ()
                plan = tp.issue(key, nf)
                if phase == PHASES[2]:
                    return dep_out(
                        U, stack, nf,
                        plan.vacated, plan.arr_plan,
                        plan.n_sent, plan.n_in,
                    ), ()
                arr = pack.gather_plan_cols(U, plan.arr_plan)
                if phase == PHASES[3]:
                    return dep_out(U, stack, nf, arr), ()
                T2, stack2, nf2, _ = tp.land(
                    U, stack, nf, arr,
                    plan.vacated, plan.n_sent, plan.n_in,
                )
                return (T2, stack2, nf2), ()

            carry, _ = lax.scan(
                body, (fused, free_stack, n_free), None, length=S
            )
            return carry[0]

        return loop

    for line in phases_lib.format_phase_table([]).splitlines():
        print(line, file=sys.stderr, flush=True)
    rows = []

    def stream(row):
        rows.append(row)
        table = phases_lib.format_phase_table(rows)
        print(table.splitlines()[-1], file=sys.stderr, flush=True)

    phases_lib.attribute_phases(
        loop_builder,
        tuple(state),
        PHASES,
        s1=4,
        s2=16,
        phase_bytes=phase_bytes(V, n),
        peak_bytes_per_sec=HBM_PEAK,
        progress=stream,
    )
    out_json = os.environ.get("KNOCKOUT_JSON")
    if out_json:
        import json

        with open(out_json, "w") as f:
            json.dump([r._asdict() for r in rows], f, indent=1)
        print(f"wrote {out_json} ({len(rows)} phase rows)", file=sys.stderr)


if __name__ == "__main__":
    main()
