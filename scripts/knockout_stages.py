"""Knockout profiling of shard_migrate_vranks_fn: time the step truncated
after each phase (cumulative), at bench-identical shapes on one device.

Phase deltas attribute the full step's time to real code, not to isolated
microbenches (which can differ from what XLA emits in context — e.g. the
vmapped scatter microbench costs 2x the flat scatter the step uses).

MAINTENANCE: ``truncated_step`` is a DELIBERATE copy of the Dev==1 slice
of ``parallel/migrate.shard_migrate_vranks_fn`` with early exits — a
truncating profiler cannot share the un-truncatable original. If the
migrate step changes, re-sync this copy or the per-phase table in
BENCH_CONFIGS.md describes a stale pipeline. Sanity check: phase 8 must
match the FULL-step time from scripts/profile_stages.py / bench.py
(52.5 vs 53.4 vs 52.7 ms when last synced).

Usage: python scripts/knockout_stages.py [n_local]
"""

from __future__ import annotations

import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.parallel import migrate
from mpi_grid_redistribute_tpu.utils import profiling

GRID = (2, 2, 2)
FILL = 0.9
MIGRATION = 0.02


def truncated_step(domain, vgrid, C, M, n, phase):
    """Body of the vrank migrate step (Dev=1), cut after ``phase``."""
    V = vgrid.nranks
    R_total = V
    P = M

    def fn(state):
        fused, free_stack, n_free = state
        K = fused.shape[2]
        flat = fused.reshape(V * n, K)
        my_v = jnp.arange(V, dtype=jnp.int32)

        def dep_out(*arrs):
            # fold a tiny dependency into the carry so nothing is DCE'd
            d = jnp.float32(0)
            for a in arrs:
                d = d + a.ravel()[0].astype(jnp.float32) * jnp.float32(1e-38)
            fused2 = fused.at[0, 0, 0].add(d)
            return migrate.MigrateState(fused2, free_stack, n_free)

        def bin_one(f, v_id):
            alive = f[:, -1] > 0.5
            cell = binning.cell_of_position(
                binning.wrap_periodic(f[:, :3], domain), domain, vgrid
            )
            dest_v = binning.rank_of_cell(cell, vgrid)
            staying = dest_v == v_id
            leaving = alive & ~staying
            return jnp.where(leaving, dest_v, R_total).astype(jnp.int32)

        dest_key = jax.vmap(bin_one)(fused, my_v)
        if phase == 1:
            return dep_out(dest_key)

        order, counts, bounds = jax.vmap(
            lambda k: binning.sorted_dest_counts(k, R_total)
        )(dest_key)
        if phase == 2:
            return dep_out(order, counts, bounds)

        loc_counts = counts[:, :V]
        loc_starts = bounds[:, :V]
        rel_start = loc_starts - loc_starts[:, :1]
        rel_end = rel_start + loc_counts
        eff = jnp.clip(
            jnp.minimum(rel_end, M) - jnp.minimum(rel_start, M), 0
        ).astype(jnp.int32)
        swap = jnp.minimum(eff, eff.T).astype(jnp.int32)
        swap = migrate._greedy_alloc(
            swap, jnp.full((V,), M, jnp.int32)
        ).astype(jnp.int32)
        swap = jnp.minimum(swap, swap.T)
        res_eff = eff - swap
        res = jnp.zeros_like(eff)
        for _ in range(V):
            cap_res = jnp.minimum(
                M - jnp.sum(swap, axis=0),
                n_free + jnp.sum(res, axis=1),
            ).astype(jnp.int32)
            res = migrate._greedy_alloc(
                res_eff, jnp.maximum(cap_res, 0)
            ).astype(jnp.int32)
        allowed = swap + res
        sent_local = jnp.sum(allowed, axis=1).astype(jnp.int32)
        n_in_local = jnp.sum(allowed, axis=0).astype(jnp.int32)
        n_sent = sent_local
        if phase == 3:
            return dep_out(allowed, n_sent, n_in_local)

        vacated, _tot = jax.vmap(
            lambda ss, sc, o: migrate._plan_rows(ss, sc, o, P)
        )(loc_starts, allowed, order)
        if phase == 4:
            return dep_out(vacated)

        cumA = jnp.concatenate(
            [jnp.zeros((1, V), jnp.int32), jnp.cumsum(allowed, axis=0)]
        )
        j = jnp.arange(M, dtype=jnp.int32)

        def arr_plan(w):
            cum = cumA[:, w]
            s = jnp.clip(migrate._segment_of(j, cum), 0, V - 1)
            pos = loc_starts[s, w] + (j - cum[s])
            row = order[s, jnp.clip(pos, 0, n - 1)]
            return s * n + row

        arr_src = jax.vmap(arr_plan)(my_v)
        arr_rows = jnp.take(flat, arr_src.reshape(-1), axis=0).reshape(
            V, M, K
        )
        if phase == 5:
            return dep_out(arr_rows)

        k_idx = jnp.arange(P, dtype=jnp.int32)

        def land_plan(vac, nin, nsent, nf):
            n_pop = jnp.clip(nin - nsent, 0, nf)
            pop_idx = jnp.clip(nf - 1 - (k_idx - nsent), 0, n - 1)
            target = jnp.where(
                k_idx < jnp.minimum(nin, nsent),
                vac,
                jnp.where(
                    (k_idx >= nsent) & (k_idx < nsent + n_pop),
                    jnp.zeros((), jnp.int32),
                    jnp.where((k_idx >= nin) & (k_idx < nsent), vac, n),
                ),
            )
            return target, n_pop, pop_idx

        targets, n_pop, pop_idx = jax.vmap(land_plan)(
            vacated, n_in_local, n_sent, n_free
        )
        pops = jnp.take_along_axis(free_stack, pop_idx, axis=1)
        use_pop = (k_idx[None, :] >= n_sent[:, None]) & (
            k_idx[None, :] < (n_sent + n_pop)[:, None]
        )
        targets = jnp.where(use_pop, pops, targets)
        gtargets = jnp.where(
            targets >= n, V * n, my_v[:, None] * n + targets
        )
        if phase == 6:
            return dep_out(gtargets)

        rows_w = jnp.where(
            (k_idx[None, :] < n_in_local[:, None])[..., None], arr_rows, 0.0
        )
        flat2 = flat.at[gtargets.reshape(-1)].set(
            rows_w.reshape(-1, K), mode="drop"
        )
        if phase == 7:
            f2 = flat2.reshape(V, n, K)
            return migrate.MigrateState(f2, free_stack, n_free)

        n_push = jnp.maximum(n_sent - n_in_local, 0)
        free_stack2, n_free2 = jax.vmap(migrate._stack_push_pop)(
            free_stack, n_free, n_pop, n_push, vacated, n_in_local
        )
        return migrate.MigrateState(
            flat2.reshape(V, n, K), free_stack2, n_free2
        )

    return fn


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2**20
    V = 8
    distinct = 3
    C = max(64, math.ceil(FILL * n * MIGRATION / distinct * 1.3))
    M = max(256, math.ceil(FILL * n * MIGRATION * 1.3))
    domain = Domain(0.0, 1.0, periodic=True)
    vgrid = ProcessGrid(GRID)

    rng = np.random.default_rng(0)
    K = 7
    fused = rng.random((V, n, K), dtype=np.float32)
    fused[:, :, -1] = (rng.random((V, n)) < FILL).astype(np.float32)
    state = migrate.init_state(jax.device_put(jnp.asarray(fused)))

    prev = 0.0
    for phase in range(1, 9):
        step = truncated_step(domain, vgrid, C, M, n, phase)

        def make_loop(S, step=step):
            @jax.jit
            def loop(fused, free_stack, n_free):
                st = migrate.MigrateState(fused, free_stack, n_free)

                def body(st, _):
                    # drift so dest_key changes each step
                    f = st.fused
                    p = f[..., :3] + f[..., 3:6] * jnp.float32(1e-4)
                    p = binning.wrap_periodic(p, domain)
                    f = jnp.concatenate([p, f[..., 3:]], axis=-1)
                    st2 = step(st._replace(fused=f))
                    return st2, ()

                st, _ = lax.scan(body, st, None, length=S)
                return st.fused

            return loop

        per, _, _ = profiling.scan_time_per_step(
            make_loop, tuple(state), s1=4, s2=16
        )
        print(
            f"phase {phase}: {per*1e3:7.2f} ms  (delta "
            f"{(per - prev)*1e3:+7.2f} ms)"
        )
        prev = per


if __name__ == "__main__":
    main()
