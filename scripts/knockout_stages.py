"""Knockout profiling of shard_migrate_vranks_fn: time the step truncated
after each phase (cumulative), at bench-identical shapes on one device —
plus a logical-bytes column turning the attribution into a ROOFLINE
statement (bytes touched / v5e HBM peak vs measured ms).

Phase deltas attribute the full step's time to real code, not to isolated
microbenches (which can differ from what XLA emits in context — e.g. the
vmapped scatter microbench costs 2x the flat scatter the step uses).

MAINTENANCE: ``truncated_step`` is a DELIBERATE copy of the Dev==1 slice
of ``parallel/migrate.shard_migrate_vranks_fn`` (PLANAR [K, V*n] layout,
round 3) with early exits — a truncating profiler cannot share the
un-truncatable original. If the migrate step changes, re-sync this copy
or the per-phase table in BENCH_CONFIGS.md describes a stale pipeline.
Sanity check: phase 8 must match the FULL-step time from bench.py.

Usage: python scripts/knockout_stages.py [n_local]
       KNOCKOUT_GRID=4,4,4 python scripts/knockout_stages.py 1048576
       (the second form is the 64M north-star shape, 64 vranks x 1M)
"""

from __future__ import annotations

import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.parallel import migrate
from mpi_grid_redistribute_tpu.telemetry import phases as phases_lib

GRID = tuple(
    int(x) for x in os.environ.get("KNOCKOUT_GRID", "2,2,2").split(",")
)
FILL = 0.9
MIGRATION = 0.02
K = 7
# v5e HBM peak (datasheet): ~819 GB/s. Used for the roofline column.
HBM_PEAK = 819e9


def truncated_step(domain, vgrid, C, M, n, phase):
    """Body of the PLANAR vrank migrate step (Dev=1), cut after ``phase``."""
    V = vgrid.nranks
    R_total = V
    P = M

    def fn(state):
        flat, free_stack, n_free = state  # [K, V*n], [V, n], [V]
        my_v = jnp.arange(V, dtype=jnp.int32)

        def dep_out(*arrs):
            # fold a tiny dependency into the carry so nothing is DCE'd
            d = jnp.int32(0)
            for a in arrs:
                d = d + (a.ravel()[0] == jnp.asarray(7, a.dtype)).astype(
                    jnp.int32
                )
            return migrate.MigrateState(
                flat.at[0, 0].add(d.astype(flat.dtype)), free_stack, n_free
            )

        # ---- 0: nothing past the loop-body drift (isolates the carry
        # concat + wrap cost charged to phase 1's "first" row) ----------
        if phase == 0:
            return dep_out(flat)

        # ---- 1: bin (per-axis fused elementwise, matches migrate.py) ----
        if os.environ.get("KNOCKOUT_BIN") == "flat":
            # FLAT variant: no [V*n] <-> [V, n] reshapes until the sort
            # boundary (each reshape relayouts 256 MB at the north-star);
            # the per-column vrank id is a loop-invariant constant that
            # XLA hoists out of the scan.
            alive_f = flat[-1, :] > 0
            dv = jnp.zeros((V * n,), jnp.int32)
            for d in range(3):
                p = migrate._pos_row(flat, d)
                lo = jnp.asarray(domain.lo[d], p.dtype)
                ext = jnp.asarray(domain.extent[d], p.dtype)
                if domain.periodic[d]:
                    p = lo + binning.remainder_fast(
                        p - lo, domain.extent[d]
                    )
                    p = jnp.where(p >= lo + ext, lo, p)
                inv_w = jnp.asarray(vgrid.shape[d], p.dtype) / ext
                cell_d = jnp.clip(
                    jnp.floor((p - lo) * inv_w).astype(jnp.int32),
                    0,
                    vgrid.shape[d] - 1,
                )
                dv = dv + cell_d * vgrid.strides[d]
            col_v = jnp.repeat(my_v, n)  # loop-invariant, hoisted
            dest_key = jnp.where(
                alive_f & (dv != col_v), dv, R_total
            ).astype(jnp.int32).reshape(V, n)
            alive = alive_f.reshape(V, n)
            if phase == 1:
                return dep_out(dest_key)
        else:
            alive = flat[-1, :].reshape(V, n) > 0
            dv = jnp.zeros((V * n,), jnp.int32)
            for d in range(3):
                p = migrate._pos_row(flat, d)
                lo = jnp.asarray(domain.lo[d], p.dtype)
                ext = jnp.asarray(domain.extent[d], p.dtype)
                if domain.periodic[d]:
                    p = lo + binning.remainder_fast(
                        p - lo, domain.extent[d]
                    )
                    p = jnp.where(p >= lo + ext, lo, p)
                inv_w = jnp.asarray(vgrid.shape[d], p.dtype) / ext
                cell_d = jnp.clip(
                    jnp.floor((p - lo) * inv_w).astype(jnp.int32),
                    0,
                    vgrid.shape[d] - 1,
                )
                # no mod: cell_d < shape[d] statically (int32 mod has no
                # native VPU lowering — matches the Dev==1 engine elision)
                dv = dv + cell_d * vgrid.strides[d]
            dv = dv.reshape(V, n)
            staying = dv == my_v[:, None]
            dest_key = jnp.where(alive & ~staying, dv, R_total).astype(
                jnp.int32
            )
            if phase == 1:
                return dep_out(dest_key)

        # ---- 2: two-level leaver selection (sort + counts) --------------
        order, counts, bounds = binning.sorted_dest_counts_batched(
            dest_key, R_total
        )
        if phase == 2:
            return dep_out(order, counts, bounds)

        # ---- 3: local allocation fixpoint (+ cycle rescue) --------------
        loc_counts = counts[:, :V]
        loc_starts = bounds[:, :V]
        rel_start = loc_starts - loc_starts[:, :1]
        rel_end = rel_start + loc_counts
        eff = jnp.clip(
            jnp.minimum(rel_end, M) - jnp.minimum(rel_start, M), 0
        ).astype(jnp.int32)
        swap = jnp.minimum(eff, eff.T).astype(jnp.int32)
        swap = migrate._greedy_alloc(
            swap, jnp.full((V,), M, jnp.int32)
        ).astype(jnp.int32)
        swap = jnp.minimum(swap, swap.T)
        res_eff = eff - swap
        res = jnp.zeros_like(eff)
        for _ in range(V):
            cap_res = jnp.minimum(
                M - jnp.sum(swap, axis=0),
                n_free + jnp.sum(res, axis=1),
            ).astype(jnp.int32)
            res = migrate._greedy_alloc(
                res_eff, jnp.maximum(cap_res, 0)
            ).astype(jnp.int32)
        allowed = swap + res
        pending_loc = (res_eff - res).astype(jnp.int32)
        sends_zero = jnp.sum(allowed, axis=1) == 0
        ok = (jnp.sum(allowed, axis=1) < M) & (
            jnp.sum(allowed, axis=0) < M
        )
        allowed = allowed + migrate._cycle_rescue(
            pending_loc, sends_zero, ok
        )
        sent_local = jnp.sum(allowed, axis=1).astype(jnp.int32)
        n_in_local = jnp.sum(allowed, axis=0).astype(jnp.int32)
        n_sent = sent_local
        if phase == 3:
            return dep_out(allowed, n_sent, n_in_local)

        # ---- 4: vacated-slot plan ---------------------------------------
        # diagnostic sub-phases: 41 = segment lookup only, 42 = plan
        # arithmetic without the final order gather
        if phase in (41, 42):
            S = V
            cum = jnp.concatenate(
                [
                    jnp.zeros((V, 1), jnp.int32),
                    jnp.cumsum(allowed, axis=1).astype(jnp.int32),
                ],
                axis=1,
            )
            jj = jnp.arange(P, dtype=jnp.int32)
            seg = jnp.sum(
                (cum[:, None, 1:] <= jj[None, :, None]),
                axis=-1,
                dtype=jnp.int32,
            )
            seg = jnp.clip(seg, 0, S - 1)
            if phase == 41:
                return dep_out(seg)
            v_off = jnp.arange(V, dtype=jnp.int32)[:, None]
            tab = jnp.concatenate(
                [loc_starts, cum[:, :-1]], axis=1
            ).reshape(1, -1)
            flat_idx = v_off * (2 * S) + seg
            starts_g = jnp.take(
                tab, flat_idx.reshape(-1), axis=1
            ).reshape(V, P)
            cum_g = jnp.take(
                tab, flat_idx.reshape(-1) + S, axis=1
            ).reshape(V, P)
            pos = starts_g + (jj[None, :] - cum_g)
            return dep_out(jnp.clip(pos, 0, n - 1))
        # unclipped fast path mirror (late round 4): one cond + slice
        # when the grant phase clips nothing
        if P <= n:
            vacated = jax.lax.cond(
                jnp.all(allowed == eff),
                lambda: jax.lax.slice_in_dim(order, 0, P, axis=1),
                lambda: migrate._plan_rows_batched(
                    loc_starts, allowed, order, P
                )[0],
            )
        else:
            vacated, _tot = migrate._plan_rows_batched(
                loc_starts, allowed, order, P
            )
        if phase == 4:
            return dep_out(vacated)

        # ---- 5: arrival gather ------------------------------------------
        # telescoped seg_rows plan (late round 4) replacing the vmapped
        # per-destination order[s, pos] gather
        arr_src, _ = migrate._plan_rows_batched(
            loc_starts.T, allowed.T, order, M,
            seg_rows=jnp.arange(V, dtype=jnp.int32),
        )
        arr_cols = jnp.take(flat, arr_src.reshape(-1), axis=1).reshape(
            K, V, M
        )
        if phase == 5:
            return dep_out(arr_cols)

        # ---- 6: landing plan --------------------------------------------
        k_idx = jnp.arange(P, dtype=jnp.int32)

        def land_plan(vac, nin, nsent, nf):
            n_pop = jnp.clip(nin - nsent, 0, nf)
            pop_idx = jnp.clip(nf - 1 - (k_idx - nsent), 0, n - 1)
            target = jnp.where(
                k_idx < jnp.minimum(nin, nsent),
                vac,
                jnp.where(
                    (k_idx >= nsent) & (k_idx < nsent + n_pop),
                    jnp.zeros((), jnp.int32),
                    jnp.where((k_idx >= nin) & (k_idx < nsent), vac, n),
                ),
            )
            return target, n_pop, pop_idx

        targets, n_pop, pop_idx = jax.vmap(land_plan)(
            vacated, n_in_local, n_sent, n_free
        )
        W2 = min(P, n)

        def pops_window(fs_v, nf, nsent):
            start = jnp.clip(nf - W2, 0, n - W2)
            win_rev = lax.dynamic_slice(fs_v, (start,), (W2,))[::-1]
            s = start + W2 - nf - nsent
            buf = jnp.concatenate(
                [
                    jnp.zeros((P,), fs_v.dtype),
                    win_rev,
                    jnp.zeros((P,), fs_v.dtype),
                ]
            )
            return lax.dynamic_slice(buf, (s + P,), (P,))

        pops = jax.vmap(pops_window)(free_stack, n_free, n_sent)
        use_pop = (k_idx[None, :] >= n_sent[:, None]) & (
            k_idx[None, :] < (n_sent + n_pop)[:, None]
        )
        targets = jnp.where(use_pop, pops, targets)
        gtargets = jnp.where(
            targets >= n, V * n, my_v[:, None] * n + targets
        )
        if phase == 6:
            return dep_out(gtargets)

        # ---- 7: landing scatter (planar columns; the shipped impl —
        # "overlay" by default on TPU, override MPI_GRID_LAND_SCATTER) ----
        cols_w = jnp.zeros((K, V, P), flat.dtype).at[:, :, :M].set(
            arr_cols
        )
        cols_w = jnp.where(
            (k_idx[None, :] < n_in_local[:, None])[None], cols_w, 0
        )
        if phase == 71:  # diagnostic: landing inputs built, scatter off
            return dep_out(cols_w, gtargets)
        flat2 = migrate._land_scatter(
            flat, gtargets.reshape(-1), cols_w.reshape(K, V * P),
            migrate._resolve_scatter_impl(None),
        )
        if phase == 7:
            return migrate.MigrateState(flat2, free_stack, n_free)

        # ---- 8: free-stack update ---------------------------------------
        n_push = jnp.maximum(n_sent - n_in_local, 0)
        free_stack2, n_free2 = jax.vmap(migrate._stack_push_pop)(
            free_stack, n_free, n_pop, n_push, vacated, n_in_local
        )
        return migrate.MigrateState(flat2, free_stack2, n_free2)

    return fn


def phase_bytes(V, n, M, migrants):
    """Logical bytes each phase NEWLY touches (reads + writes), for the
    roofline column. Deliberately the *minimum* traffic the phase's math
    implies — sorts do multiple physical passes and scatters touch whole
    (8,128) tiles per lane written, so measured/roofline >> 1 flags a
    latency/serialization bound, not a bandwidth wall."""
    f32 = 4
    return {
        0: (2 * K + 3) * V * n * f32,      # drift: state r/w + pos rows

        1: (3 + 3 + 1 + 1) * V * n * f32,  # read pos+vel+alive, write key
        2: 4 * V * n * f32,                # sort in/out of (key, iota)
        3: 0,                              # [V, V] tables
        4: 3 * V * M * f32,                # plan vectors + order gather
        41: V * M * f32,                   # diagnostic: segment lookup
        42: 2 * V * M * f32,               # diagnostic: plan sans gather
        5: (K + 1) * V * M * f32 + K * V * M * f32,  # gather in+out
        6: 4 * V * M * f32,                # plan vectors
        7: (K + 1) * V * M * f32,          # scatter writes + targets
        71: (K + 1) * V * M * f32,         # diagnostic: inputs, no scatter
        8: 2 * V * M * f32,                # stack windows
    }


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2**20
    vgrid = ProcessGrid(GRID)
    V = vgrid.nranks
    distinct = int(
        np.where(
            np.asarray(GRID) == 1, 0, np.where(np.asarray(GRID) == 2, 1, 2)
        ).sum()
    ) or 1
    C = max(64, math.ceil(FILL * n * MIGRATION / distinct * 1.3))
    M = max(256, math.ceil(FILL * n * MIGRATION * 1.3))
    domain = Domain(0.0, 1.0, periodic=True)

    rng = np.random.default_rng(0)
    fused = rng.random((K, V * n), dtype=np.float32).view(np.int32)
    fused[-1, :] = (rng.random((V * n,)) < FILL).astype(np.int32)
    state = migrate.init_state(
        jax.device_put(jnp.asarray(fused)), vranks=V
    )
    migrants = int(V * n * FILL * MIGRATION)
    pb = phase_bytes(V, n, M, migrants)

    print(
        f"shapes: V={V} n={n} M={M} (plan rows/vrank), "
        f"~{migrants} migrants/step expected", file=sys.stderr,
    )
    phases = [
        int(x)
        for x in os.environ.get(
            "KNOCKOUT_PHASES", "1,2,3,4,5,6,7,8"
        ).split(",")
    ]

    def loop_builder(phase, S):
        step = truncated_step(domain, vgrid, C, M, n, phase)

        @jax.jit
        def loop(fused, free_stack, n_free):
            st = migrate.MigrateState(fused, free_stack, n_free)

            def body(st, _):
                # drift so dest_key changes each step (int32 carry,
                # f32 views — matches nbody.make_migrate_loop)
                f = st.fused
                pf = lax.bitcast_convert_type(f[:3, :], jnp.float32)
                vf = lax.bitcast_convert_type(f[3:6, :], jnp.float32)
                p = pf + vf * jnp.float32(1e-4)
                p = binning.wrap_periodic_planar(p, domain)
                if os.environ.get("KNOCKOUT_DRIFT") == "dus":
                    f = lax.dynamic_update_slice(
                        f, lax.bitcast_convert_type(p, jnp.int32),
                        (0, 0),
                    )
                else:
                    f = jnp.concatenate(
                        [
                            lax.bitcast_convert_type(p, jnp.int32),
                            f[3:, :],
                        ],
                        axis=0,
                    )
                st2 = step(st._replace(fused=f))
                return st2, ()

            st, _ = lax.scan(body, st, None, length=S)
            return st.fused

        return loop

    # the attribution harness (telemetry.phases) owns the protocol:
    # cumulative truncations, scan-differenced, streamed as table rows
    for line in phases_lib.format_phase_table([]).splitlines():
        print(line, file=sys.stderr, flush=True)
    rows = []

    def stream(row):
        rows.append(row)
        table = phases_lib.format_phase_table(rows)
        print(table.splitlines()[-1], file=sys.stderr, flush=True)

    phases_lib.attribute_phases(
        loop_builder,
        tuple(state),
        phases,
        s1=4,
        s2=16,
        phase_bytes=pb,
        peak_bytes_per_sec=HBM_PEAK,
        progress=stream,
    )
    # KNOCKOUT_JSON=file dumps the rows for scripts/trace_export.py
    # --phases (the Perfetto duration lane of the attribution)
    out_json = os.environ.get("KNOCKOUT_JSON")
    if out_json:
        import json

        with open(out_json, "w") as f:
            json.dump([r._asdict() for r in rows], f, indent=1)
        print(f"wrote {out_json} ({len(rows)} phase rows)", file=sys.stderr)


if __name__ == "__main__":
    main()
