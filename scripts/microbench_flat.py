"""vmapped [V,P] vs flat [V*P] row scatter/gather into [V,n,K] vs [V*n,K]."""
import sys
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from mpi_grid_redistribute_tpu.utils import profiling

V, N, K = 8, 2**20, 7


def timed(name, make_loop, *args, s1=4, s2=24):
    per_step, _, _out = profiling.scan_time_per_step(make_loop, args, s1=s1, s2=s2)
    print(f"  {name:44s} {per_step*1e3:8.3f} ms", file=sys.stderr)


def run(P):
    rng = np.random.default_rng(0)
    arr = jax.device_put(jnp.asarray(rng.random((V, N, K), dtype=np.float32)))
    arrf = jax.device_put(jnp.asarray(rng.random((V * N, K), dtype=np.float32)))
    idx = jax.device_put(jnp.asarray(rng.integers(0, N, size=(V, P), dtype=np.int32)))
    idxf = jax.device_put(jnp.asarray(rng.integers(0, V * N, size=(V * P,), dtype=np.int32)))
    rows = jax.device_put(jnp.asarray(rng.random((V, P, K), dtype=np.float32)))
    rowsf = rows.reshape(V * P, K)

    def mk_vmap_scatter(S):
        @jax.jit
        def loop(a, i):
            def body(c, _):
                a, i = c
                a = jax.vmap(lambda aa, ii, rr: aa.at[ii].set(rr, mode="drop"))(a, i, rows)
                a, i = lax.optimization_barrier((a, i))
                i = (i + a[0, 0, 0].astype(jnp.int32) % 2) % N
                return (a, i), ()
            c, _ = lax.scan(body, (a, i), None, length=S)
            return c
        return loop

    def mk_flat_scatter(S):
        @jax.jit
        def loop(a, i):
            def body(c, _):
                a, i = c
                a = a.at[i].set(rowsf, mode="drop")
                a, i = lax.optimization_barrier((a, i))
                i = (i + a[0, 0].astype(jnp.int32) % 2) % (V * N)
                return (a, i), ()
            c, _ = lax.scan(body, (a, i), None, length=S)
            return c
        return loop

    def mk_vmap_gather(S):
        @jax.jit
        def loop(a, i):
            def body(c, _):
                a, i = c
                out = jax.vmap(lambda aa, ii: jnp.take(aa, ii, axis=0))(a, i)
                a, i, out = lax.optimization_barrier((a, i, out))
                i = (i + out[0, 0, 0].astype(jnp.int32) % 2) % N
                return (a, i), ()
            c, _ = lax.scan(body, (a, i), None, length=S)
            return c
        return loop

    def mk_flat_gather(S):
        @jax.jit
        def loop(a, i):
            def body(c, _):
                a, i = c
                out = jnp.take(a, i, axis=0)
                a, i, out = lax.optimization_barrier((a, i, out))
                i = (i + out[0, 0].astype(jnp.int32) % 2) % (V * N)
                return (a, i), ()
            c, _ = lax.scan(body, (a, i), None, length=S)
            return c
        return loop

    timed(f"vmap scatter V={V} P={P}", mk_vmap_scatter, arr, idx)
    timed(f"flat scatter {V*P} rows", mk_flat_scatter, arrf, idxf)
    timed(f"vmap gather V={V} P={P}", mk_vmap_gather, arr, idx)
    timed(f"flat gather {V*P} rows", mk_flat_gather, arrf, idxf)


for P in (2**15, 65432):
    run(P)
