#!/usr/bin/env python
"""Run progcheck, the semantic jaxpr analyzer over the REAL programs.

Usage:
    python scripts/progcheck.py [--format=json|sarif|github] [--check]
    python scripts/progcheck.py --update-baseline
    python scripts/progcheck.py --list-rules | --list-programs

Unlike gridlint (pure-stdlib AST, never executes anything), progcheck
TRACES the registered entry points with ``jax.make_jaxpr`` — still no
device execution, but it needs jax importable and an 8-device virtual
CPU mesh for the sharded programs. This wrapper forces that mesh
exactly the way tests/conftest.py does, BEFORE jax is imported, so
``make progcheck`` behaves identically inside and outside CI.

Exit codes mirror gridlint: 0 clean, 1 findings/drift, 2 usage error.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_grid_redistribute_tpu.analysis.progcheck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
