#!/usr/bin/env python
"""Umbrella CI gate: gridlint + progcheck + shardcheck + attribution +
racecheck, one SARIF file.

Usage:
    python scripts/check_all.py [--sarif-out PATH]

Runs all five analyzers/gates in ``--check`` mode (each in its own
subprocess so the pure-AST tools stay jax-free and the jaxpr analyzers
get the forced 8-device virtual CPU mesh from their wrappers), captures
their SARIF output, and merges the runs into one document via
``analysis/sarif.py``'s ``merge_sarif`` — a single code-scanning
upload for ``make check``. The attribution gate is structural only
(phase-table/roofline snapshot drift; it never re-measures); racecheck
scans the host-thread control plane (scripts/ included).

Exit codes: 0 when every tool is clean, 1 when any tool found
something, 2 on any usage/parse error.
"""

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOOLS = (
    (
        "gridlint",
        ["scripts/gridlint.py", "mpi_grid_redistribute_tpu/", "--check",
         "--format=sarif"],
    ),
    ("progcheck", ["scripts/progcheck.py", "--check", "--format=sarif"]),
    ("shardcheck", ["scripts/shardcheck.py", "--check", "--format=sarif"]),
    (
        "attribution",
        ["scripts/attribution.py", "--check", "--format=sarif"],
    ),
    (
        "racecheck",
        ["scripts/racecheck.py", "--check", "--format=sarif"],
    ),
)


def main(argv=None) -> int:
    sys.path.insert(0, REPO)
    from mpi_grid_redistribute_tpu.analysis.sarif import merge_sarif

    p = argparse.ArgumentParser(
        prog="check_all",
        description="Run gridlint + progcheck + shardcheck and merge "
        "their SARIF runs into one file.",
    )
    p.add_argument(
        "--sarif-out",
        default=os.path.join(REPO, "analysis_merged.sarif"),
        metavar="PATH",
        help="merged SARIF output path (default: analysis_merged.sarif "
        "at the repo root)",
    )
    args = p.parse_args(argv)

    docs = []
    worst = 0
    for name, cmd in TOOLS:
        proc = subprocess.run(
            [sys.executable] + cmd,
            cwd=REPO,
            capture_output=True,
            text=True,
        )
        if proc.returncode == 2:
            print(f"check: {name} usage/parse error:", file=sys.stderr)
            sys.stderr.write(proc.stderr)
            return 2
        try:
            doc = json.loads(proc.stdout)
        except ValueError:
            print(
                f"check: {name} produced no parseable SARIF "
                f"(exit {proc.returncode}):",
                file=sys.stderr,
            )
            sys.stderr.write(proc.stdout)
            sys.stderr.write(proc.stderr)
            return 2
        docs.append(doc)
        n_results = sum(len(r.get("results", [])) for r in doc.get("runs", []))
        status = "clean" if proc.returncode == 0 else "FAILED"
        print(
            f"check: {name} {status} "
            f"({n_results} finding(s), exit {proc.returncode})"
        )
        # stale-baseline notes ride stderr; keep them visible
        if proc.stderr.strip():
            sys.stderr.write(proc.stderr)
        worst = max(worst, proc.returncode)

    merged = merge_sarif(docs)
    with open(args.sarif_out, "w", encoding="utf-8") as fh:
        json.dump(merged, fh, indent=2)
        fh.write("\n")
    print(
        f"check: merged {len(merged['runs'])} run(s) -> {args.sarif_out}"
    )
    return 1 if worst else 0


if __name__ == "__main__":
    sys.exit(main())
