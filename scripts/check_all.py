#!/usr/bin/env python
"""Umbrella CI gate: every analyzer family, one SARIF file.

Usage:
    python scripts/check_all.py [--sarif-out PATH] [--analyzers A,B]
    python scripts/check_all.py --lint

The ANALYZERS registry below is the single source of truth for the
family list — the umbrella test, ``make check`` and ``make lint`` all
derive from it, so adding a family means adding one row here (not
hand-bumping an N-tool count in the tests). Each analyzer runs in its
own subprocess so the pure-AST tools stay jax-free and the jaxpr
analyzers get their wrapper-forced environments (virtual CPU mesh,
pinned CPU platform). In the default (SARIF) mode the runs are merged
into one document via ``analysis/sarif.py``'s ``merge_sarif`` — a
single code-scanning upload for ``make check``; ``--lint`` runs the
same registry in plain-text ``--check`` mode for the developer loop.
Per-analyzer wall-time is printed either way so lint growth stays
visible.

Exit codes: 0 when every tool is clean, 1 when any tool found
something, 2 on any usage/parse error.
"""

import argparse
import collections
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

Analyzer = collections.namedtuple("Analyzer", ["name", "cmd", "baseline"])

# name -> (runner argv, committed baseline the --check gate compares
# against). ``--format=sarif`` is appended at run time so --lint can
# reuse the same rows in text mode.
ANALYZERS = (
    Analyzer(
        "gridlint",
        ["scripts/gridlint.py", "mpi_grid_redistribute_tpu/", "--check"],
        "mpi_grid_redistribute_tpu/analysis/gridlint_baseline.json",
    ),
    Analyzer(
        "progcheck",
        ["scripts/progcheck.py", "--check"],
        "mpi_grid_redistribute_tpu/analysis/progprofile_baseline.json",
    ),
    Analyzer(
        "shardcheck",
        ["scripts/shardcheck.py", "--check"],
        "mpi_grid_redistribute_tpu/analysis/progprofile_baseline.json",
    ),
    Analyzer(
        "attribution",
        ["scripts/attribution.py", "--check"],
        "mpi_grid_redistribute_tpu/telemetry/attribution_baseline.json",
    ),
    Analyzer(
        "racecheck",
        ["scripts/racecheck.py", "--check"],
        "mpi_grid_redistribute_tpu/analysis/racecheck_baseline.json",
    ),
    Analyzer(
        "kernelcheck",
        ["scripts/kernelcheck.py", "--check"],
        "mpi_grid_redistribute_tpu/analysis/kernelcheck_baseline.json",
    ),
    Analyzer(
        "incident-demo",
        ["scripts/incident_demo.py", "--check"],
        "mpi_grid_redistribute_tpu/analysis/incident_demo_baseline.json",
    ),
    Analyzer(
        "storecheck",
        ["scripts/storecheck.py", "--check"],
        "mpi_grid_redistribute_tpu/analysis/storecheck_baseline.json",
    ),
)


def _select(spec):
    if not spec:
        return list(ANALYZERS)
    by_name = {a.name: a for a in ANALYZERS}
    wanted = [s.strip() for s in spec.split(",") if s.strip()]
    unknown = [w for w in wanted if w not in by_name]
    if unknown:
        print(
            f"check: unknown analyzer(s): {', '.join(unknown)} "
            f"(known: {', '.join(by_name)})",
            file=sys.stderr,
        )
        return None
    return [by_name[w] for w in wanted]


def main(argv=None) -> int:
    sys.path.insert(0, REPO)
    from mpi_grid_redistribute_tpu.analysis.sarif import merge_sarif

    p = argparse.ArgumentParser(
        prog="check_all",
        description="Run every registered analyzer and merge their "
        "SARIF runs into one file.",
    )
    p.add_argument(
        "--sarif-out",
        default=os.path.join(REPO, "analysis_merged.sarif"),
        metavar="PATH",
        help="merged SARIF output path (default: analysis_merged.sarif "
        "at the repo root)",
    )
    p.add_argument(
        "--analyzers",
        default=None,
        metavar="NAME[,NAME]",
        help="comma-separated subset of the registry to run (fast "
        "local loops); default: all "
        f"({', '.join(a.name for a in ANALYZERS)})",
    )
    p.add_argument(
        "--lint",
        action="store_true",
        help="plain-text mode: run each analyzer's --check without "
        "SARIF capture or merging (the `make lint` surface)",
    )
    args = p.parse_args(argv)

    selected = _select(args.analyzers)
    if selected is None:
        return 2

    docs = []
    worst = 0
    for tool in selected:
        cmd = tool.cmd + ([] if args.lint else ["--format=sarif"])
        t0 = time.monotonic()
        proc = subprocess.run(
            [sys.executable] + cmd,
            cwd=REPO,
            capture_output=True,
            text=True,
        )
        dt = time.monotonic() - t0
        if proc.returncode == 2:
            print(f"check: {tool.name} usage/parse error:", file=sys.stderr)
            sys.stderr.write(proc.stderr)
            return 2
        if args.lint:
            status = "clean" if proc.returncode == 0 else "FAILED"
            print(
                f"check: {tool.name} {status} "
                f"(exit {proc.returncode}, {dt:.1f}s)"
            )
            if proc.returncode != 0 and proc.stdout.strip():
                sys.stdout.write(proc.stdout)
            if proc.stderr.strip():
                sys.stderr.write(proc.stderr)
            worst = max(worst, proc.returncode)
            continue
        try:
            doc = json.loads(proc.stdout)
        except ValueError:
            print(
                f"check: {tool.name} produced no parseable SARIF "
                f"(exit {proc.returncode}):",
                file=sys.stderr,
            )
            sys.stderr.write(proc.stdout)
            sys.stderr.write(proc.stderr)
            return 2
        docs.append(doc)
        n_results = sum(len(r.get("results", [])) for r in doc.get("runs", []))
        status = "clean" if proc.returncode == 0 else "FAILED"
        print(
            f"check: {tool.name} {status} "
            f"({n_results} finding(s), exit {proc.returncode}, {dt:.1f}s)"
        )
        # stale-baseline notes ride stderr; keep them visible
        if proc.stderr.strip():
            sys.stderr.write(proc.stderr)
        worst = max(worst, proc.returncode)

    if args.lint:
        return 1 if worst else 0

    merged = merge_sarif(docs)
    with open(args.sarif_out, "w", encoding="utf-8") as fh:
        json.dump(merged, fh, indent=2)
        fh.write("\n")
    print(
        f"check: merged {len(merged['runs'])} run(s) -> {args.sarif_out}"
    )
    return 1 if worst else 0


if __name__ == "__main__":
    sys.exit(main())
