"""Mover-sparse engine vs planar: per-step cost vs mover fraction (ISSUE 4).

The claim behind the sparse fast path is a *scaling* one: the planar
engine pays the full resident row count every step (one [K, V*n]
permutation's worth of gathers and scatters) no matter how few rows
move, while the sparse engine touches O(mover_cap) rows beyond the
shared destination binning. This driver measures exactly that: fixed
resident count n, three drift intensities targeting ~1% / ~5% / ~25%
movers per step, each timed under engine='planar' and engine='sparse'
(mover_cap sized to the target fraction, so the block grows with the
mover load and the guard holds). The sparse times must rise with the
mover fraction; the planar times must stay flat; at low fractions
sparse must not lose to planar.

CPU-runnable (the engines are the same HLO modulo the cond), one JSON
row per (engine, fraction) on stdout — same ``metric``/``value``/
``ms_per_step`` contract as the bench drivers, so telemetry.regress can
diff captures.

Usage: python scripts/microbench_mover_path.py [n_local] [steps]
"""
from __future__ import annotations

import sys

import numpy as np

from mpi_grid_redistribute_tpu.domain import Domain
from mpi_grid_redistribute_tpu.models import nbody
from mpi_grid_redistribute_tpu.bench import common
from mpi_grid_redistribute_tpu.utils import profiling


def run(n_local: int = 1 << 14, steps: int = 24) -> list:
    import jax
    import jax.numpy as jnp

    grid_shape = (2, 2, 2)
    dev_grid, vgrid, mesh, n_chips = common.pick_layout(grid_shape)
    if vgrid is None or dev_grid.nranks != 1:
        common.log(
            "microbench_mover_path: needs the single-device vrank layout "
            f"(got {dev_grid.nranks} devices); the sparse engine only "
            "dispatches there"
        )
        return []
    domain = Domain(0.0, 1.0, periodic=True)
    rng = np.random.default_rng(0)
    fill = 0.9
    fracs = (0.01, 0.05, 0.25)
    # provision capacity/budget ONCE at the worst-case fraction: the
    # planar engine's per-step cost depends on those statics, not on how
    # many rows actually move, so holding them fixed across fractions is
    # what makes "planar flat / sparse scales" a like-for-like claim.
    # Only the sparse mover_cap varies with the target fraction.
    _, cap, budget = common.drift_sizing(grid_shape, n_local, fill, fracs[-1])
    rows = []
    for frac in fracs:
        v_scale, _, mover_cap = common.drift_sizing(
            grid_shape, n_local, fill, frac
        )
        pos, _, alive = common.uniform_state(grid_shape, n_local, fill, rng)
        vel = (
            v_scale * (rng.random(pos.shape, dtype=np.float32) * 2.0 - 1.0)
        ).astype(np.float32)
        state = (
            jax.device_put(jnp.asarray(nbody.rows_to_planar(pos, mesh.size))),
            jax.device_put(jnp.asarray(nbody.rows_to_planar(vel, mesh.size))),
            jax.device_put(jnp.asarray(alive)),
        )
        for engine in ("planar", "sparse"):
            cfg = nbody.DriftConfig(
                domain=domain, grid=dev_grid, dt=1.0, capacity=cap,
                n_local=n_local, local_budget=budget, engine=engine,
                mover_cap=None if engine == "planar" else mover_cap,
            )
            per_step, _, out = profiling.scan_time_per_step(
                lambda S, cfg=cfg: nbody.make_migrate_loop(
                    cfg, mesh, S, vgrid=vgrid
                ),
                state,
                s1=4,
                s2=max(8, steps),
            )
            stats = jax.tree.map(np.asarray, out[3])
            sent = stats.sent.reshape(-1, stats.sent.shape[-1])
            pop = stats.population.reshape(sent.shape)
            measured = float(sent.sum(1).mean() / max(pop.sum(1).mean(), 1))
            row = {
                "metric": f"mover_path_{engine}_f{int(frac * 100):02d}",
                "value": round(1.0 / per_step, 2),  # steps/s, higher better
                "unit": "steps/s",
                "ms_per_step": round(per_step * 1e3, 4),
                "engine": engine,
                "n_local": n_local,
                "target_mover_fraction": frac,
                "measured_mover_fraction": round(measured, 4),
                "mover_cap": None if engine == "planar" else mover_cap,
            }
            if stats.fast_path is not None:
                fp = stats.fast_path.reshape(sent.shape[0], -1)
                row["fast_path_hit_rate"] = round(
                    float(np.count_nonzero(fp.any(1))) / fp.shape[0], 4
                )
            rows.append(row)
            common.log(
                f"mover_path {engine} frac={frac:.0%}: "
                f"{per_step * 1e3:.3f} ms/step "
                f"(measured movers {measured:.1%})"
            )
    return rows


if __name__ == "__main__":
    n_local = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 14
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 24
    for row in run(n_local, steps):
        common.emit(row)
