#!/usr/bin/env python
"""Run kernelcheck, the semantic Pallas-kernel verifier.

Usage:
    python scripts/kernelcheck.py [--format=json|sarif|github] [--check]
    python scripts/kernelcheck.py --update-baseline
    python scripts/kernelcheck.py --list-rules | --list-kernels

kernelcheck re-runs the REAL ops-layer Pallas entry points at
registered representative shapes under a patched ``pl.pallas_call``
that records every site's grid, BlockSpecs, scratch and aliases — via
``jax.eval_shape``, so K001-K004 never execute anything — then gates
index-map bounds, scatter write coverage/overlap, the VMEM footprint
against ``analysis/kernelcheck_baseline.json``, and lane-tiling
legality. K005 additionally EXECUTES each kernel in interpret mode on
CPU and bit-compares it against its registered jnp/XLA reference twin,
so this wrapper pins ``JAX_PLATFORMS=cpu`` before jax is imported:
``make kernelcheck`` behaves identically on a TPU host and in CI.

Exit codes mirror gridlint: 0 clean, 1 findings/drift, 2 usage error.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_grid_redistribute_tpu.analysis.kernelcheck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
