"""On-chip: overlay landing at the 64M NORTH-STAR shape, decomposed.

The round-4 knockout at 64 vranks x 1M rows attributes +148 ms to the
landing phase (vs +12.1 at the 8-vrank headline — 12x for 8x the
migrants). This script decomposes the overlay path at that shape:

  1. XLA-side prep: payload sort by target + half-plane build +
     per-block searchsorted;
  2. the Pallas kernel alone (planes/starts precomputed);
  3. the full drop-in (prep + kernel), W swept;
  4. XLA column scatter baseline.

Usage: python scripts/microbench_overlay_ns.py [m_cols] [p_updates]
(defaults 64M / 1.57M — the north-star landing shape)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.ops import pallas_overlay
from mpi_grid_redistribute_tpu.utils import profiling

K = 7


def main():
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 64 * (1 << 20)
    p = int(sys.argv[2]) if len(sys.argv) > 2 else 64 * 24_537
    r = np.random.default_rng(0)
    flat = r.integers(-(2**31), 2**31 - 1, size=(K, m), dtype=np.int32)
    targets = r.choice(m, size=p, replace=False).astype(np.int32)
    targets[r.random(p) < 0.23] = m  # plan padding tail -> drop sentinel
    cols = r.integers(-(2**31), 2**31 - 1, size=(K, p), dtype=np.int32)

    fd = jax.device_put(jnp.asarray(flat))
    td = jax.device_put(jnp.asarray(targets))
    cd = jax.device_put(jnp.asarray(cols))
    print(f"m={m} cols, p={p} plan entries", flush=True)

    def timed(name, fn, *args):
        def make_loop(S):
            @jax.jit
            def loop(*a):
                def body(acc, _):
                    out = fn(*a[1:], acc)
                    return out, ()

                acc, _ = lax.scan(body, a[0], None, length=S)
                return acc

            return loop

        per, _, _ = profiling.scan_time_per_step(
            make_loop, args, s1=2, s2=6
        )
        print(f"  {name}: {per*1e3:8.2f} ms", flush=True)
        return per

    # 4: XLA column scatter baseline
    def xla_scatter(t, c, f):
        return f.at[:, t].set(c, mode="drop")

    timed("xla column scatter", xla_scatter, fd, td, cd)

    # 1: prep only (sort + planes + searchsorted), dependency-folded
    for w in (2048, 4096, 8192):
        def prep(t, c, f, w=w):
            sentinel = jnp.int32(m)
            tgt = jnp.where((t < 0) | (t >= m), sentinel, t)
            operands = (tgt,) + tuple(c[i] for i in range(K))
            s = lax.sort(operands, num_keys=1, is_stable=False)
            ts = s[0]
            edges = jnp.arange(0, m + w, w, dtype=jnp.int32)
            starts = jnp.searchsorted(
                ts, edges, side="left", method="sort"
            ).astype(jnp.int32)
            words = lax.bitcast_convert_type(
                jnp.stack(s[1:], axis=0), jnp.uint32
            )
            hi = (words >> 16).astype(jnp.float32)
            # fold everything into the carry so nothing is DCE'd
            return f.at[0, 0].add(
                starts[-1] + hi[0, 0].astype(jnp.int32)
            )

        timed(f"prep only (sort+planes+starts) W={w}", prep, fd, td, cd)

    # 3: full drop-in, (W, rmax) swept
    # rmax=64 does not lower (Mosaic: lane slices must be 128-aligned);
    # rmax=256 measured WORSE at 64M (82.9 vs 73.1 ms at W=4096) — the
    # default (4096, 128) stands
    for w, rmax in (
        (2048, 128), (4096, 128), (8192, 128),
        (4096, 256), (8192, 256),
    ):
        if m % w:
            continue

        def full(t, c, f, w=w, rmax=rmax):
            return pallas_overlay.overlay_scatter_planar(
                f, t, c, w=w, rmax=rmax
            )

        timed(f"overlay full W={w} rmax={rmax}", full, fd, td, cd)


if __name__ == "__main__":
    main()
