"""Probe: can lax.top_k replace the migrate engine's full dest-key sort?

The engine's phase-2 sort (packed one-word, [V, n]) costs 6.4 ms at the
headline and 55 ms at the north-star — but its order is only consumed up
to the first `leavers` (~2%) entries: migrant indices grouped by dest,
iota-stable within dest. top_k with k = plan capacity on the packed
DESCENDING key `leaving ? ((R-1-dest) << b) | (n-1-iota) : -1` returns
exactly that prefix (dest ascending, iota ascending after unpacking).

Usage: python scripts/microbench_topk.py [V] [n] [k]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_grid_redistribute_tpu.utils import profiling

V = int(sys.argv[1]) if len(sys.argv) > 1 else 8
n = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 20
k = int(sys.argv[3]) if len(sys.argv) > 3 else 24544
R = 64

rng = np.random.default_rng(0)
# ~2.3% leavers with random dests; both variants pack IN-LOOP from the
# same dest-key carry (the engine pays packing on either path — an
# earlier version prepacked the top_k key on the host, skewing the
# comparison in the rejected candidate's favor; review round 4)
leaving = rng.random((V, n)) < 0.023
dest = rng.integers(0, R, size=(V, n), dtype=np.int32)
b = (n - 1).bit_length()
key_np = np.where(leaving, dest, R).astype(np.int32)
key0 = jnp.asarray(key_np)


def make_topk(S):
    @jax.jit
    def loop(key):
        def body(carry, _):
            kk = carry
            iota = jax.lax.broadcasted_iota(jnp.int32, (V, n), 1)
            packed = jnp.where(
                kk < R,
                ((R - 1 - kk) << b) | (jnp.int32(n - 1) - iota),
                -1,
            )
            vals, _ = jax.lax.top_k(packed, k)
            return kk ^ 1, vals[0, 0]

        _, outs = jax.lax.scan(body, key, None, length=S)
        return outs

    return loop


def make_sort(S):
    @jax.jit
    def loop(key):
        def body(carry, _):
            kk = carry
            iota = jax.lax.broadcasted_iota(jnp.int32, (V, n), 1)
            packed = (kk << b) | iota
            s = jax.lax.sort(packed, is_stable=False, dimension=1)
            return kk ^ 1, s[0, 0]

        _, outs = jax.lax.scan(body, key, None, length=S)
        return outs

    return loop


t_topk, _, _ = profiling.scan_time_per_step(make_topk, (key0,), s1=8, s2=40)
t_sort, _, _ = profiling.scan_time_per_step(make_sort, (key0,), s1=8, s2=40)
print(f"V={V} n={n} k={k} R={R}")
print(f"full packed sort: {t_sort * 1e3:8.2f} ms")
print(f"top_k(k={k}):     {t_topk * 1e3:8.2f} ms")
