"""Isolate the round-3 sort-path regression: flat composite-key sort vs
vmapped per-vrank sort, and the boundary-searchsorted variants.

Usage: python scripts/microbench_sort.py
"""
from __future__ import annotations

import sys

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.utils import profiling
from mpi_grid_redistribute_tpu.ops import binning

V, n, R = 8, 2**20, 8


def timed(name, make_loop, args, s1=4, s2=12):
    per, _, _ = profiling.scan_time_per_step(make_loop, args, s1=s1, s2=s2)
    print(f"  {name:52s} {per*1e3:8.2f} ms", file=sys.stderr, flush=True)


def keys():
    rng = np.random.default_rng(0)
    k = np.full((V, n), R, np.int32)
    m = int(n * 0.018)
    for v in range(V):
        idx = rng.choice(n, size=m, replace=False)
        k[v, idx] = rng.choice([1, 2, 4], size=m)
    return jax.device_put(jnp.asarray(k))


def dep(k, x):
    return (k + (x.ravel()[:1].astype(jnp.float32) * 1e-38).astype(k.dtype)).astype(jnp.int32)


def make_vmapped(S):
    @jax.jit
    def loop(key):
        def body(k, _):
            order, counts, bounds = jax.vmap(
                lambda kk: binning.sorted_dest_counts(kk, R)
            )(k)
            return dep(k, order + counts[:, :1] + bounds[:, :1]), ()
        return lax.scan(body, keys_dev, None, length=S)[0]
    return loop


def make_flat(S):
    my_v = jnp.arange(V, dtype=jnp.int32)
    stride = R + 1

    @jax.jit
    def loop(key):
        def body(k, _):
            comp = (my_v[:, None] * stride + k).reshape(V * n)
            iota = jnp.arange(V * n, dtype=jnp.int32)
            ks, order_flat = lax.sort((comp, iota), num_keys=1,
                                      is_stable=True)
            qry = (my_v[:, None] * stride
                   + jnp.arange(R + 1, dtype=jnp.int32)[None, :]).reshape(-1)
            b = jnp.searchsorted(ks, qry, side="left",
                                 method="sort").astype(jnp.int32)
            return dep(k, order_flat + b[:1]), ()
        return lax.scan(body, keys_dev, None, length=S)[0]
    return loop


def make_flat_sort_only(S):
    my_v = jnp.arange(V, dtype=jnp.int32)
    stride = R + 1

    @jax.jit
    def loop(key):
        def body(k, _):
            comp = (my_v[:, None] * stride + k).reshape(V * n)
            iota = jnp.arange(V * n, dtype=jnp.int32)
            ks, order_flat = lax.sort((comp, iota), num_keys=1,
                                      is_stable=True)
            return dep(k, order_flat + ks[:1]), ()
        return lax.scan(body, keys_dev, None, length=S)[0]
    return loop


def make_flat_countbounds(S):
    my_v = jnp.arange(V, dtype=jnp.int32)
    stride = R + 1

    @jax.jit
    def loop(key):
        def body(k, _):
            comp = (my_v[:, None] * stride + k).reshape(V * n)
            iota = jnp.arange(V * n, dtype=jnp.int32)
            ks, order_flat = lax.sort((comp, iota), num_keys=1,
                                      is_stable=True)
            # counts via one-pass histogram over the 72 composite values:
            # comparison-count on the SORTED keys is monotone -> per
            # boundary b: #keys < b = sum(ks < b) is O(72 * V*n)… instead
            # bincount-free: segment ids are tiny; use sum over equality
            cnt = jnp.sum(
                (comp[None, :] == jnp.arange(V * stride, dtype=jnp.int32)[:, None]),
                axis=1, dtype=jnp.int32,
            )
            bounds = jnp.cumsum(cnt)
            return dep(k, order_flat + bounds[:1]), ()
        return lax.scan(body, keys_dev, None, length=S)[0]
    return loop


def make_vmapped_sort_only(S):
    @jax.jit
    def loop(key):
        def body(k, _):
            iota = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32), (V, n))
            ks, order = lax.sort((k, iota), dimension=1, num_keys=1,
                                 is_stable=True)
            return dep(k, order + ks[:, :1]), ()
        return lax.scan(body, keys_dev, None, length=S)[0]
    return loop


keys_dev = keys()

timed("vmapped sorted_dest_counts (round-2 path)", make_vmapped, (keys_dev,))
timed("vmapped sort only (no searchsorted)", make_vmapped_sort_only, (keys_dev,))
timed("flat composite sort only", make_flat_sort_only, (keys_dev,))
timed("flat sort + searchsorted(method=sort) 72 qrys", make_flat, (keys_dev,))
timed("flat sort + equality-histogram bounds", make_flat_countbounds, (keys_dev,))
