"""Canonical exchange wire engines vs mover fraction (ISSUE 7).

The claim behind the count-driven wire is a *scaling* one: the dense
planar exchange schedules the full ``[K, R*C]`` pool on the
``all_to_all`` every step no matter how few rows actually change owner,
while the sparse engine ships ``[K, R*B]`` (and the neighbor engine
``[K, offsets*B]`` over ``ppermute`` shifts) with ``B`` sized to the
mover load. This driver measures exactly that: fixed resident count,
exactly-targeted 1% / 5% / 25% mover fractions (rows stepped one cell
across the six face neighbors round-robin), each timed under
``planar`` / ``sparse`` / ``neighbor`` with ``mover_cap`` sized from
the measured per-destination peak — so the guard holds and every step
stays on the fast branch. Scheduled wire bytes are reported alongside
the times: on a CPU mesh the all_to_all is a memcpy, so the TIME gap
understates what an ICI wire would see; the ``wire_bytes_per_step``
column is the transport-independent claim.

CPU-runnable on the sharded builders when the process has >= R devices
(run under ``XLA_FLAGS=--xla_force_host_platform_device_count=8``,
as tests/conftest.py does), on the vrank twins otherwise. One JSON row
per (engine, fraction) on stdout — same ``metric``/``value``/
``ms_per_step`` contract as the bench drivers, so telemetry.regress
can diff captures.

The hierarchical leg (ISSUE 19) re-runs the same sweep on a virtual
2x(2,2,2)-pod mesh — grid (4, 2, 2) split into two pods along x — and
times the flat sparse engine against the two-level schedule, reporting
the per-domain split next to wall time: under the S004 billing
discipline the flat engine's all_to_all crosses the pod boundary so its
whole pool bills to DCN, while the two-level wire bills only the
``(P-1) * cross_cap`` condensed per-destination-pod blocks there and
keeps the neighbor blocks + fanout pool on ICI.

Usage: python scripts/microbench_exchange_path.py [n_local] [steps]
"""
from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.bench import common
from mpi_grid_redistribute_tpu.parallel import exchange
from mpi_grid_redistribute_tpu.parallel import mesh as mesh_lib

GRID_SHAPE = (2, 2, 2)
K = 7  # pos(3) + vel(3) + alive — the drift loop's fused row


def _state(grid, n_local, frac, rng):
    """Shard-local [R, K, n] fused state with exactly ``frac * n``
    movers per rank, spread over the six face neighbors; returns the
    per-destination peak that sizes the mover block."""
    shape = grid.shape
    R = grid.nranks
    m = max(1, int(round(frac * n_local)))
    pos = np.empty((R, 3, n_local), np.float32)
    for r in range(R):
        cell = grid.cell_of_rank(r)
        for a in range(3):
            w = 1.0 / shape[a]
            pos[r, a] = (cell[a] + rng.random(n_local)) * w
        for i in range(m):
            axis = (i % 6) // 2
            sign = 1.0 if i % 2 == 0 else -1.0
            pos[r, axis, i] = np.mod(
                pos[r, axis, i] + sign / shape[axis], 1.0
            )
    other = rng.standard_normal((R, K - 3, n_local)).astype(np.float32)
    fused = np.concatenate([pos, other], axis=1)
    count = np.full(R, n_local, np.int32)
    # measured per-destination peak (opposite faces may be the same
    # periodic neighbor on a 2-wide axis, so count real cells)
    sh = np.asarray(shape)
    peak = 0
    for r in range(R):
        cells = np.floor(pos[r].T * sh).astype(np.int64) % sh
        flat = (cells[:, 0] * sh[1] + cells[:, 1]) * sh[2] + cells[:, 2]
        c = grid.cell_of_rank(r)
        home = (c[0] * sh[1] + c[1]) * sh[2] + c[2]
        away = flat[flat != home]
        if away.size:
            peak = max(peak, int(np.bincount(away).max()))
    return fused, count, peak


def _time_calls(f, args, steps):
    import jax

    out = f(*args)  # compile + warm
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(steps):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / steps, out


def run(n_local: int = 1 << 13, steps: int = 30) -> list:
    import jax
    import jax.numpy as jnp

    grid = ProcessGrid(GRID_SHAPE)
    R = grid.nranks
    domain = Domain(0.0, 1.0, periodic=True)
    sharded = len(jax.devices()) >= R
    mesh = (
        mesh_lib.make_mesh(grid, jax.devices()[:R]) if sharded else None
    )
    rng = np.random.default_rng(0)
    cap = 1 << int(np.ceil(np.log2(2 * n_local / R)))  # dense per-dest
    out_cap = 2 * n_local
    n_off = None
    rows = []
    for frac in (0.01, 0.05, 0.25):
        fused, count, peak = _state(grid, n_local, frac, rng)
        B = min(cap // 2, 1 << int(np.ceil(np.log2(1.5 * peak))))
        if sharded:
            fused_dev = jnp.asarray(
                np.transpose(fused, (1, 0, 2)).reshape(K, R * n_local)
            )
        else:
            fused_dev = jnp.asarray(fused)
        count_dev = jnp.asarray(count)
        ref_out = None
        for engine in ("planar", "sparse", "neighbor"):
            if engine == "planar":
                f = (
                    exchange.build_redistribute_planar(
                        mesh, domain, grid, cap, out_cap, 3
                    )
                    if sharded
                    else exchange.build_redistribute_planar_vranks(
                        domain, grid, cap, out_cap, 3
                    )
                )
                cols = R * cap
            else:
                f = (
                    exchange.build_redistribute_count_driven(
                        mesh, domain, grid, cap, out_cap, B, 3,
                        engine=engine,
                    )
                    if sharded
                    else exchange.build_redistribute_count_driven_vranks(
                        domain, grid, cap, out_cap, B, 3, engine=engine,
                    )
                )
                if engine == "sparse":
                    cols = R * B
                else:
                    if n_off is None:
                        n_off = sum(
                            1
                            for p in mesh_lib.neighbor_perms(
                                grid, tuple(domain.periodic)
                            )
                            if p
                        )
                    cols = n_off * B
            per_step, out = _time_calls(f, (fused_dev, count_dev), steps)
            if engine == "planar":
                ref_out = np.asarray(out[0]).tobytes()
            else:
                assert np.asarray(out[0]).tobytes() == ref_out, (
                    engine, frac, "engines diverged — not a benchmark",
                )
                fb = np.asarray(out[2].fallback)
                assert not fb.any(), (engine, frac, "fell back dense")
            row = {
                "metric": f"exchange_path_{engine}_f{int(frac*100):02d}",
                "value": round(1.0 / per_step, 2),
                "unit": "calls/s",
                "ms_per_step": round(per_step * 1e3, 4),
                "engine": engine,
                "layout": "sharded" if sharded else "vranks",
                "n_local": n_local,
                "mover_fraction": frac,
                "mover_cap": None if engine == "planar" else B,
                # the transport-independent claim: scheduled pool bytes
                "wire_bytes_per_step": float(cols * 4 * K * R),
            }
            rows.append(row)
            common.log(
                f"exchange_path {engine} frac={frac:.0%}: "
                f"{per_step*1e3:.3f} ms/call, "
                f"wire {row['wire_bytes_per_step']/1e3:.1f} kB"
            )
    return rows


HIER_GRID = (4, 2, 2)  # 2 pods of (2, 2, 2) split along x
HIER_DCN = (2, 1, 1)


def run_hierarchical(n_local: int = 1 << 13, steps: int = 30) -> list:
    """Flat-sparse vs two-level on the virtual 2x(2,2,2)-pod mesh at
    1/5/25% movers (ISSUE 19). Both engines are asserted byte-identical
    and fast-branch-only per step; the per-domain wire columns are the
    scheduled-pool model (transport-independent, same formulas the api
    journals as ``engine_cols_ici`` / ``engine_cols_dcn``)."""
    import jax
    import jax.numpy as jnp

    grid = ProcessGrid(HIER_GRID)
    hier = mesh_lib.HierarchicalMesh(grid, HIER_DCN)
    R = grid.nranks
    P, L = hier.n_pods, hier.pod_size
    domain = Domain(0.0, 1.0, periodic=True)
    sharded = len(jax.devices()) >= R
    emesh = (
        hier.build_mesh(list(jax.devices()[:R])) if sharded else None
    )
    n_act = sum(
        1
        for p in mesh_lib.neighbor_perms(
            hier.local_grid, hier.local_periodic(tuple(domain.periodic))
        )
        if p
    )
    rng = np.random.default_rng(0)
    base_cap = 1 << int(np.ceil(np.log2(2 * n_local / R)))
    out_cap = 2 * n_local
    rows = []
    for frac in (0.01, 0.05, 0.25):
        fused, count, peak = _state(grid, n_local, frac, rng)
        # size the block from the measured peak and widen the dense
        # pool if needed (the 16-rank grid's per-dest pool is narrow
        # enough that 25% movers would otherwise clamp B into fallback)
        B = 1 << int(np.ceil(np.log2(1.5 * peak)))
        cap = max(base_cap, 2 * B)
        # measured per-destination-POD peak sizes the cross block
        sh = np.asarray(grid.shape)
        peak_cross = 0
        pod_of = np.asarray(hier.pod_of)
        for r in range(R):
            cells = np.floor(fused[r, :3].T * sh).astype(np.int64) % sh
            flat = (
                cells[:, 0] * sh[1] + cells[:, 1]
            ) * sh[2] + cells[:, 2]
            pods = pod_of[flat]
            pods = pods[pods != pod_of[r]]
            if pods.size:
                peak_cross = max(
                    peak_cross, int(np.bincount(pods).max())
                )
        B2 = max(2, 1 << int(np.ceil(np.log2(1.5 * peak_cross))))
        if sharded:
            fused_dev = jnp.asarray(
                np.transpose(fused, (1, 0, 2)).reshape(K, R * n_local)
            )
        else:
            fused_dev = jnp.asarray(fused)
        count_dev = jnp.asarray(count)
        ref_out = None
        for engine in ("sparse", "hierarchical"):
            if engine == "sparse":
                f = (
                    exchange.build_redistribute_count_driven(
                        emesh, domain, grid, cap, out_cap, B, 3,
                        engine="sparse", axes=hier.axis_names,
                    )
                    if sharded
                    else exchange.build_redistribute_count_driven_vranks(
                        domain, grid, cap, out_cap, B, 3, engine="sparse",
                    )
                )
                # the flat pool's all_to_all crosses the pod boundary,
                # so under the S004 billing discipline every scheduled
                # column rides the DCN domain
                cols_ici, cols_dcn = 0, R * B
            else:
                f = (
                    exchange.build_redistribute_hierarchical(
                        emesh, domain, grid, hier, cap, out_cap, B, B2, 3,
                    )
                    if sharded
                    else exchange.build_redistribute_hierarchical_vranks(
                        domain, grid, hier, cap, out_cap, B, B2, 3,
                    )
                )
                cols_ici = n_act * B + (P - 1) * L * B2
                cols_dcn = (P - 1) * B2
            per_step, out = _time_calls(f, (fused_dev, count_dev), steps)
            if engine == "sparse":
                ref_out = np.asarray(out[0]).tobytes()
            else:
                assert np.asarray(out[0]).tobytes() == ref_out, (
                    engine, frac, "engines diverged — not a benchmark",
                )
            st = out[2]
            fb = np.asarray(st.fallback)
            assert not fb.any(), (engine, frac, "fell back dense")
            assert not np.asarray(st.dropped_send).any(), (
                engine, frac, "cross block clipped — resize B2",
            )
            row = {
                "metric": (
                    f"exchange_hier_{engine}_f{int(frac*100):02d}"
                ),
                "value": round(1.0 / per_step, 2),
                "unit": "calls/s",
                "ms_per_step": round(per_step * 1e3, 4),
                "engine": engine,
                "layout": "sharded" if sharded else "vranks",
                "pods": P,
                "n_local": n_local,
                "mover_fraction": frac,
                "mover_cap": B,
                "cross_cap": None if engine == "sparse" else B2,
                "wire_bytes_per_step": float(
                    (cols_ici + cols_dcn) * 4 * K * R
                ),
                "ici_bytes_per_step": float(cols_ici * 4 * K * R),
                "dcn_bytes_per_step": float(cols_dcn * 4 * K * R),
            }
            rows.append(row)
            common.log(
                f"exchange_hier {engine} frac={frac:.0%}: "
                f"{per_step*1e3:.3f} ms/call, "
                f"dcn {row['dcn_bytes_per_step']/1e3:.1f} kB / "
                f"ici {row['ici_bytes_per_step']/1e3:.1f} kB"
            )
    return rows


if __name__ == "__main__":
    n_local = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 13
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    for row in run(n_local, steps):
        common.emit(row)
    for row in run_hierarchical(n_local, steps):
        common.emit(row)
