#!/usr/bin/env python
"""grid-top: live terminal dashboard for a running (or finished) grid.

``top`` for the redistribute service: one screen summarising the
telemetry plane, refreshed in place. Two sources:

* ``--store DIR`` — a durable ``telemetry.store`` journal-store root
  (what a service driver started with ``--store-dir`` maintains). Read
  through :class:`StoreReader` + the query plane, so compacted
  ``store_window`` summaries contribute exact counts and quantile
  sketches alongside raw events.
* ``--url http://host:port`` — a ``scripts/metrics_serve.py`` endpoint;
  polls ``/metrics`` (OpenMetrics parse), ``/healthz`` and, when the
  server has them, ``/query``-backed panels.

Panels: step rate + p50/p99 step latency, fast-path hit rate, engine
mix, flow imbalance, population/backlog, state health (live rows,
NaN/out-of-bounds totals, conservation residual — shown only when the
run journaled ``state_health`` probe events; any nonzero corruption
counter flags ``** CORRUPT **``), active health findings, recent alerts
and incidents.

``--once`` prints a single plain-text snapshot and exits — the CI mode
(no ANSI, no loop); exit code 0 when the source was readable. Stdlib
only: safe to run on a login node next to the job.

Examples:

  python scripts/grid_top.py --store /var/run/grid/store
  python scripts/grid_top.py --url http://127.0.0.1:9100 --interval 1
  python scripts/grid_top.py --store demo_store --once   # CI snapshot
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_CLEAR = "\x1b[H\x1b[2J"


# ----------------------------------------------------- store collector


def collect_store(store_dir: str) -> dict:
    """One dashboard snapshot from a journal store on disk."""
    from mpi_grid_redistribute_tpu.telemetry import query as query_lib
    from mpi_grid_redistribute_tpu.telemetry import store as store_lib

    reader = store_lib.StoreReader(store_dir)
    rows = query_lib.rows_of(reader)
    counts = reader.counts()
    man = reader.manifest

    # step timing: merged histogram over raw samples + compacted
    # sketches — the exact-quantile path
    h = reader.latency_histogram()
    p50 = h.quantile(0.5) if h.count else None
    p99 = h.quantile(0.99) if h.count else None

    # step rate over the last minute of retained rows
    step_rows = query_lib.filter_rows(rows, kind="step_latency,store_window")
    rate = None
    if step_rows:
        t_hi = max(query_lib._row_time(r) for r in step_rows)
        recent = query_lib.filter_rows(step_rows, since=t_hi - 60.0)
        n = sum(query_lib._row_weight(r) for r in recent)
        span = t_hi - min(query_lib._row_time(r) for r in recent)
        rate = n / span if span > 0 else float(n)

    # fast path: raw events + compacted window sums
    fp_taken = fp_total = 0
    imbalance = None
    dropped = 0
    state = None  # stays None until a probe event proves probes were on
    for r in rows:
        kind = r.get("kind")
        if kind == "fast_path":
            fp_total += 1
            fp_taken += int(r.get("taken", 0))
        elif kind == "store_window":
            fp = r.get("fast_path", {})
            fp_taken += int(fp.get("taken", 0))
            fp_total += int(fp.get("total", 0))
            dropped += int(r.get("dropped", {}).get("total", 0))
            for _, v in r.get("imbalance", []):
                imbalance = v
            st = r.get("state")
            if st:
                state = state or {"nan": 0, "oob": 0,
                                  "live": None, "residual": None}
                state["nan"] += int(st.get("nan_pos", 0))
                state["nan"] += int(st.get("nan_vel", 0))
                state["oob"] += int(st.get("oob", 0))
                if st.get("live_last") is not None:
                    state["live"] = int(st["live_last"])
                if st.get("residual_last") is not None:
                    state["residual"] = int(st["residual_last"])
        elif kind == "flow_snapshot":
            if "imbalance" in r:
                imbalance = float(r["imbalance"])
        elif kind == "step_latency":
            dropped += int(r.get("dropped", 0))
        elif kind == "state_health":
            state = state or {"nan": 0, "oob": 0,
                              "live": None, "residual": None}
            state["nan"] += int(r.get("nan_pos", 0))
            state["nan"] += int(r.get("nan_vel", 0))
            state["oob"] += int(r.get("oob", 0))
            state["live"] = int(r.get("live", 0))
            state["residual"] = int(r.get("residual", 0))

    engines: dict = {}
    for r in query_lib.filter_rows(rows, kind="redistribute"):
        eng = r.get("engine", "unknown")
        engines[eng] = engines.get(eng, 0) + 1

    alerts = [
        {
            "rule": r.get("rule"),
            "severity": r.get("severity"),
            "reason": r.get("reason"),
            "time": r.get("time"),
        }
        for r in query_lib.filter_rows(rows, kind="alert,alert_raised")
    ]
    incidents = [
        {
            "trigger": r.get("trigger", r.get("rule")),
            "dir": r.get("dir"),
            "time": r.get("time"),
        }
        for r in query_lib.filter_rows(rows, kind="incident")
    ]

    pop = backlog = None
    for r in query_lib.filter_rows(rows, kind="migrate_step,store_window"):
        if r.get("kind") == "store_window":
            m = r.get("migrate", {})
            pop = m.get("population_last", pop)
            backlog = m.get("backlog_last", backlog)
        else:
            pop = r.get("population", pop)
            backlog = r.get("backlog", backlog)

    return {
        "source": store_dir,
        "writer": man.get("writer"),
        "updated": man.get("updated"),
        "events_total": sum(counts.values()),
        "counts": counts,
        "segments": len(man.get("segments", [])),
        "retired": man.get("retired", {}).get("segments", 0),
        "store_bytes": sum(s["bytes"] for s in man.get("segments", []))
        + (man.get("active") or {}).get("bytes", 0),
        "step_rate": rate,
        "p50": p50,
        "p99": p99,
        "latency_samples": h.count,
        "fast_path": (fp_taken / fp_total) if fp_total else None,
        "engines": engines,
        "imbalance": imbalance,
        "dropped": dropped,
        "population": pop,
        "backlog": backlog,
        "state": state,
        "health": None,
        "alerts": alerts[-5:],
        "incidents": incidents[-5:],
    }


# ------------------------------------------------------- URL collector


def _fetch(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode("utf-8", "replace")


def parse_openmetrics(text: str) -> dict:
    """Minimal OpenMetrics sample parse: ``{name: {labels_str: value}}``
    (labels_str is the raw ``k="v",...`` inside the braces, ``""`` for
    bare samples). Enough for the dashboard's panel math."""
    out: dict = {}
    for ln in text.splitlines():
        if not ln or ln.startswith("#"):
            continue
        try:
            head, value = ln.rsplit(" ", 1)
            if "{" in head:
                name, rest = head.split("{", 1)
                labels = rest.rstrip("}")
            else:
                name, labels = head, ""
            out.setdefault(name, {})[labels] = float(value)
        except ValueError:
            continue
    return out


def _histogram_quantile(samples: dict, name: str, q: float):
    """Upper-bound quantile from cumulative ``le`` bucket samples —
    the same estimate ``metrics.Histogram.quantile`` computes."""
    import math

    buckets = []
    for labels, v in samples.get(f"{name}_bucket", {}).items():
        for part in labels.split(","):
            if part.startswith('le="'):
                edge = part[4:-1]
                buckets.append(
                    (math.inf if edge == "+Inf" else float(edge), v)
                )
    if not buckets:
        return None, 0
    buckets.sort()
    count = buckets[-1][1]
    if count <= 0:
        return None, 0
    target = max(1, math.ceil(q * count))
    for edge, cum in buckets:
        if cum >= target:
            return (None if math.isinf(edge) else edge), int(count)
    return None, int(count)


def collect_url(base: str) -> dict:
    """One dashboard snapshot from a metrics_serve endpoint."""
    base = base.rstrip("/")
    fam = parse_openmetrics(_fetch(f"{base}/metrics"))

    def total(name):
        series = fam.get(name, {})
        return sum(series.values()) if series else None

    counts = {}
    for labels, v in fam.get("grid_journal_events_total", {}).items():
        for part in labels.split(","):
            if part.startswith('kind="'):
                counts[part[6:-1]] = int(v)
    p50, n50 = _histogram_quantile(fam, "grid_step_latency_seconds", 0.5)
    p99, n = _histogram_quantile(fam, "grid_step_latency_seconds", 0.99)
    if n == 0:  # library loops journal step_time, not step_latency
        p50, _ = _histogram_quantile(fam, "grid_step_time_seconds", 0.5)
        p99, n = _histogram_quantile(fam, "grid_step_time_seconds", 0.99)
    fp = fam.get("grid_fast_path_steps_total", {})
    fp_taken = sum(v for k, v in fp.items() if 'taken="1"' in k)
    fp_all = sum(fp.values())
    imb = fam.get("grid_flow_imbalance", {}).get("")
    engines = {}
    for labels, v in fam.get("grid_exchange_wire_bytes_total", {}).items():
        for part in labels.split(","):
            if part.startswith('engine="'):
                engines[part[8:-1]] = int(v)

    state = None
    nan_fam = fam.get("grid_state_nan_total", {})
    oob_fam = fam.get("grid_state_oob_total", {})
    live_g = fam.get("grid_state_live_rows", {}).get("")
    res_g = fam.get("grid_state_residual", {}).get("")
    if nan_fam or oob_fam or live_g is not None:
        state = {
            "nan": int(sum(nan_fam.values())),
            "oob": int(sum(oob_fam.values())),
            "live": None if live_g is None else int(live_g),
            "residual": None if res_g is None else int(res_g),
        }

    health = None
    try:
        health = json.loads(_fetch(f"{base}/healthz"))
    except (urllib.error.URLError, ValueError, OSError):
        pass
    alerts = []
    try:
        doc = json.loads(
            _fetch(f"{base}/query?kind=alert,alert_raised&limit=5")
        )
        alerts = [
            {
                "rule": r.get("rule"),
                "severity": r.get("severity"),
                "reason": r.get("reason"),
                "time": r.get("time"),
            }
            for r in doc.get("events", [])
        ]
    except (urllib.error.URLError, ValueError, OSError):
        pass  # older server without /query: panel stays empty
    incidents = []
    try:
        doc = json.loads(_fetch(f"{base}/incidents"))
        incidents = [
            {"trigger": b.get("trigger"), "dir": b.get("dir"),
             "time": b.get("time")}
            for b in doc.get("incidents", [])
        ]
    except (urllib.error.URLError, ValueError, OSError):
        pass

    return {
        "source": base,
        "writer": None,
        "updated": time.time(),
        "events_total": sum(counts.values()),
        "counts": counts,
        "segments": None,
        "retired": None,
        "store_bytes": None,
        "step_rate": None,
        "p50": p50,
        "p99": p99,
        "latency_samples": n,
        "fast_path": (fp_taken / fp_all) if fp_all else None,
        "engines": engines,
        "imbalance": imb,
        "dropped": None,
        "population": fam.get("grid_population_rows", {}).get(""),
        "backlog": fam.get("grid_backlog_rows", {}).get(""),
        "state": state,
        "health": health,
        "alerts": alerts[-5:],
        "incidents": incidents[-5:],
    }


# -------------------------------------------------------------- render


def _fmt(v, unit="", scale=1.0, digits=3):
    if v is None:
        return "--"
    return f"{float(v) * scale:.{digits}g}{unit}"


def _fmt_bytes(v):
    if v is None:
        return "--"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if v < 1024 or unit == "GiB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024.0
    return f"{v:.1f}GiB"


def render(d: dict, width: int = 72) -> str:
    """Plain-text dashboard screen (the same text ``--once`` prints)."""
    bar = "─" * width
    lines = [
        f"grid-top · {d['source']}",
        f"  updated {time.strftime('%H:%M:%S', time.localtime(d['updated']))}"
        + (
            f" · writer {d['writer']['host']}:{d['writer']['pid']}"
            if d.get("writer")
            else ""
        ),
        bar,
        "  steps".ljust(14)
        + f"rate {_fmt(d['step_rate'], '/s')}".ljust(18)
        + f"p50 {_fmt(d['p50'], 's')}".ljust(16)
        + f"p99 {_fmt(d['p99'], 's')}".ljust(16)
        + f"n={d['latency_samples']}",
        "  routing".ljust(14)
        + f"fast-path {_fmt(d['fast_path'], '', 100, 3)}%".ljust(22)
        + "engines "
        + (
            " ".join(f"{k}:{v}" for k, v in sorted(d["engines"].items()))
            or "--"
        ),
        "  flow".ljust(14)
        + f"imbalance {_fmt(d['imbalance'])}".ljust(22)
        + f"pop {_fmt(d['population'], digits=6)}".ljust(16)
        + f"backlog {_fmt(d['backlog'])}".ljust(16)
        + f"dropped {_fmt(d['dropped'])}",
    ]
    state = d.get("state")
    if state is not None:
        clean = not state["nan"] and not state["oob"] and not state["residual"]
        lines.append(
            "  state".ljust(14)
            + f"live {_fmt(state['live'], digits=6)}".ljust(18)
            + f"nan {state['nan']}".ljust(12)
            + f"oob {state['oob']}".ljust(12)
            + f"residual {_fmt(state['residual'])}"
            + ("" if clean else "  ** CORRUPT **")
        )
    if d.get("segments") is not None:
        lines.append(
            "  store".ljust(14)
            + f"events {d['events_total']}".ljust(18)
            + f"segments {d['segments']} (+{d['retired']} retired)".ljust(26)
            + f"disk {_fmt_bytes(d['store_bytes'])}"
        )
    else:
        lines.append("  journal".ljust(14) + f"events {d['events_total']}")
    health = d.get("health")
    if health is not None:
        status = health.get("status", "?")
        findings = health.get("findings", [])
        lines.append(
            "  health".ljust(14)
            + status
            + (
                "  " + "; ".join(
                    f"{f.get('rule')}: {f.get('reason')}" for f in findings
                )[: width - 20]
                if findings
                else ""
            )
        )
    lines.append(bar)
    lines.append("  recent alerts")
    if d["alerts"]:
        for a in d["alerts"]:
            when = (
                time.strftime("%H:%M:%S", time.localtime(a["time"]))
                if a.get("time")
                else "--:--:--"
            )
            lines.append(
                f"    {when}  {a.get('severity') or '-'}"
                f"  {a.get('rule')}  {str(a.get('reason') or '')[:40]}"
            )
    else:
        lines.append("    (none)")
    lines.append("  recent incidents")
    if d["incidents"]:
        for i in d["incidents"]:
            when = (
                time.strftime("%H:%M:%S", time.localtime(i["time"]))
                if i.get("time")
                else "--:--:--"
            )
            lines.append(
                f"    {when}  {i.get('trigger')}  {i.get('dir') or ''}"
            )
    else:
        lines.append("    (none)")
    top_kinds = sorted(
        d["counts"].items(), key=lambda kv: -kv[1]
    )[:6]
    lines.append(bar)
    lines.append(
        "  events  "
        + "  ".join(f"{k}:{v}" for k, v in top_kinds)
    )
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Live terminal dashboard over a journal store or a "
        "metrics_serve endpoint."
    )
    src = p.add_mutually_exclusive_group(required=True)
    src.add_argument("--store", metavar="DIR",
                     help="journal-store root (telemetry/store.py)")
    src.add_argument("--url", metavar="URL",
                     help="metrics_serve base URL (http://host:port)")
    p.add_argument("--interval", type=float, default=2.0,
                   help="refresh period in seconds (live mode)")
    p.add_argument("--once", action="store_true",
                   help="print one plain snapshot and exit (CI mode)")
    p.add_argument("--iterations", type=int, default=0, metavar="N",
                   help="stop after N refreshes (0 = run until Ctrl-C)")
    args = p.parse_args(argv)

    def collect():
        if args.store:
            return collect_store(args.store)
        return collect_url(args.url)

    if args.once:
        try:
            sys.stdout.write(render(collect()))
        except Exception as e:  # CI mode: readable failure, rc 1
            print(f"grid-top: cannot read source: {e}", file=sys.stderr)
            return 1
        return 0

    n = 0
    try:
        while True:
            try:
                screen = render(collect())
                sys.stdout.write(_CLEAR + screen)
            except Exception as e:
                sys.stdout.write(
                    _CLEAR + f"grid-top: source unreadable: {e}\n"
                    "  (retrying)\n"
                )
            sys.stdout.flush()
            n += 1
            if args.iterations and n >= args.iterations:
                break
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
