"""Deposit-method microbenchmark on the current default device.

Times cic_deposit_local (segment) vs cic_deposit_local_sorted (scan,
double-float prefixes) at BENCH_N particles on a BENCH_M^3 local mesh via
scan differencing. Usage: python scripts/bench_deposit.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from mpi_grid_redistribute_tpu.ops import deposit as dep
    from mpi_grid_redistribute_tpu.utils import profiling

    n = int(os.environ.get("BENCH_N", 1 << 22))
    m = int(os.environ.get("BENCH_M", 64))
    M = (m, m, m)
    rng = np.random.default_rng(0)
    pos = (rng.lognormal(-1.5, 0.5, size=(n, 3)) % 1.0).astype(np.float32)
    mass = rng.uniform(0.5, 2.0, n).astype(np.float32)
    valid = rng.random(n) > 0.05
    lo = jnp.zeros(3)
    inv_h = jnp.full(3, float(m))

    args = (
        jax.device_put(jnp.asarray(pos)),
        jax.device_put(jnp.asarray(mass)),
        jax.device_put(jnp.asarray(valid)),
    )

    for name, impl in (
        ("segment", dep.cic_deposit_local),
        ("scan-df", dep.cic_deposit_local_sorted),
    ):
        def make_loop(S, impl=impl):
            @jax.jit
            def loop(pos, mass, valid):
                def body(acc, _):
                    # thread the carry into the inputs or XLA hoists the
                    # loop-invariant deposit out of the scan; the scale is
                    # dynamically 1.0f exactly (acc*1e-38 underflows vs 1)
                    scale = jnp.float32(1) + acc * jnp.float32(1e-38)
                    rho = impl(pos, mass * scale, valid, lo, inv_h, M)
                    return rho.sum(), None
                out, _ = lax.scan(
                    body, jnp.zeros((), jnp.float32), None, length=S
                )
                return out
            return loop

        per, _, _ = profiling.scan_time_per_step(
            make_loop, args, s1=2, s2=10
        )
        print(f"{name}: {per*1e3:.2f} ms/deposit at {n} particles, {M} mesh")


if __name__ == "__main__":
    main()
