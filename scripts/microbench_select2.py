"""Decompose the two-level selection: is lax.sort data-dependent, does
the cond fallback run both branches, what does each stage cost?

Usage: python scripts/microbench_select2.py
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.utils import profiling

V, n, R = 64, 1 << 20, 64
rng = np.random.default_rng(0)
dest_np = np.full((V, n), R, np.int32)
mask = rng.random((V, n)) < 0.02
dest_np[mask] = rng.integers(0, R, size=int(mask.sum()), dtype=np.int32)
dest0 = jnp.asarray(dest_np)
iota = jnp.arange(n, dtype=jnp.int32)
rand0 = jnp.asarray(
    rng.integers(0, 1 << 27, size=(V, n), dtype=np.int32)
)


def bench(name, fn, x):
    def make_loop(S):
        @jax.jit
        def loop(d):
            def body(c, _):
                o = fn(c).reshape(c.shape)
                return c ^ (o & 1).astype(jnp.int32), ()
            c, _ = lax.scan(body, d, None, length=S)
            return c
        return loop

    per, _, _ = profiling.scan_time_per_step(make_loop, (x,), s1=4, s2=16)
    print(f"{name:46s} {per*1e3:8.2f} ms", flush=True)
    return per


b = 20
bench("flat packed sort, skewed engine keys",
      lambda d: lax.sort((d << b) | iota, dimension=-1, is_stable=False),
      dest0)
bench("flat packed sort, random keys",
      lambda d: lax.sort(d, dimension=-1, is_stable=False), rand0)

T, q = 4096, 512
nc = n // T
bT = (T - 1).bit_length()
iota_t = jnp.arange(T, dtype=jnp.int32)


def chunk_sort(d):
    ch = d.reshape(V, nc, T)
    return lax.sort((ch << bT) | iota_t, dimension=-1, is_stable=False)


bench("chunk sort [64,256,4096], skewed", chunk_sort, dest0)
bench("chunk sort [64,256,4096], random",
      lambda d: lax.sort(d.reshape(V, nc, T), dimension=-1,
                         is_stable=False), rand0)


def two_level_nocond(d):
    bN = (n - 1).bit_length()
    ch = d.reshape(V, nc, T)
    lc = jnp.sum((ch != R).astype(jnp.int32), axis=-1)
    packed1 = lax.sort((ch << bT) | iota_t, dimension=-1, is_stable=False)
    cand = lax.slice_in_dim(packed1, 0, q, axis=2)
    dest_c = cand >> bT
    pos_g = (jnp.arange(nc, dtype=jnp.int32)[None, :, None] * T) | (
        cand & (T - 1)
    )
    live = jnp.arange(q, dtype=jnp.int32)[None, None, :] < lc[:, :, None]
    packed2 = jnp.where(live, (dest_c << bN) | pos_g, (R << bN))
    packed2 = lax.sort(
        packed2.reshape(V, nc * q), dimension=-1, is_stable=False
    )
    order_c = packed2 & ((1 << bN) - 1)
    pad = jnp.zeros((V, n), jnp.int32)
    return lax.dynamic_update_slice(pad, order_c, (0, 0))


bench("two-level fast path only (no cond)", two_level_nocond, dest0)
