"""On-chip costs of the leaver-compaction alternative to the full key sort.

The migrate step's phase 2 stable-sorts ALL [V, n] rows by destination
(10.3 ms at 8 x 1M) although only ~2% are leavers. The alternative:

  a. leaving mask + per-vrank exclusive cumsum (elementwise + prefix);
  b. compact the ~196k leaver slot ids into [V, M] via a scatter whose
     targets are the cumsum ranks — monotone, so the overlay kernel needs
     no prep sort (or XLA scatter for comparison);
  c. sort the COMPACT leavers by destination ([V, M] 2-operand);
  d. gather their dest keys/columns (1-row gathers, plan-sized).

This script measures each piece so the refactor decision is numbers-led.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.utils import profiling

V, n = 8, 1 << 20
M = 24576  # per-vrank leaver budget (bench local_budget)
R_total = 8


def time_fn(fn, *args, s1=2, s2=10):
    def make_loop(S):
        @jax.jit
        def loop(*a):
            def body(acc, _):
                out = fn(*jax.tree.map(
                    lambda x: x + (acc * jnp.float32(1e-30)).astype(x.dtype),
                    a,
                ))
                leaf = jax.tree.leaves(out)[0]
                return acc + leaf.ravel()[0].astype(jnp.float32), None
            out, _ = lax.scan(body, jnp.float32(0), None, length=S)
            return out
        return loop
    per, _, _ = profiling.scan_time_per_step(make_loop, args, s1=s1, s2=s2)
    return per


def main():
    rng = np.random.default_rng(0)
    dest = rng.integers(0, R_total + 1, size=(V, n)).astype(np.int32)
    # ~2% leavers (dest != sentinel), like the bench step
    leaving = rng.random((V, n)) < 0.02
    dest = np.where(leaving, dest % R_total, R_total).astype(np.int32)
    dest_d = jax.device_put(jnp.asarray(dest))

    # 0) the incumbent: full stable key sort + counts
    t = time_fn(
        lambda d: jax.vmap(
            lambda k: binning.sorted_dest_counts(k, R_total)
        )(d)[0],
        dest_d,
    )
    print(f"incumbent full sort [V,n]: {t*1e3:.2f} ms", flush=True)

    # a) mask + per-vrank exclusive cumsum (int32)
    def cumsum_rank(d):
        leave = (d < R_total).astype(jnp.int32)
        return jnp.cumsum(leave, axis=1) - leave  # exclusive

    t = time_fn(cumsum_rank, dest_d)
    print(f"mask + cumsum [V,n]: {t*1e3:.2f} ms", flush=True)

    # b1) compact via XLA scatter (targets = vrank_off + rank, values=idx)
    def compact_xla(d):
        leave = d < R_total
        rank = jnp.cumsum(leave.astype(jnp.int32), axis=1) - 1
        off = jnp.arange(V, dtype=jnp.int32)[:, None] * M
        tgt = jnp.where(leave & (rank < M), off + rank, V * M)
        idx = jnp.broadcast_to(
            jnp.arange(n, dtype=jnp.int32)[None, :], (V, n)
        )
        buf = jnp.zeros((V * M,), jnp.int32)
        return buf.at[tgt.reshape(-1)].set(
            idx.reshape(-1), mode="drop"
        )

    t = time_fn(compact_xla, dest_d)
    print(f"compact via XLA scatter (8.4M scatter ops!): {t*1e3:.2f} ms",
          flush=True)

    # b2) compact via one sort of (rank-with-sentinel) — what the overlay
    # kernel's presorted path would replace; measures the sort floor
    def compact_sort(d):
        leave = d < R_total
        key = jnp.where(leave, d, R_total)
        order, counts, bounds = jax.vmap(
            lambda k: binning.sorted_dest_counts(k, R_total)
        )(key)
        return order[:, :M]

    # c) small sort of the compact leavers by dest
    comp_dest = rng.integers(0, R_total, size=(V, M)).astype(np.int32)
    t = time_fn(
        lambda d: jax.vmap(
            lambda k: binning.sorted_dest_counts(k, R_total)
        )(d)[0],
        jax.device_put(jnp.asarray(comp_dest)),
    )
    print(f"small sort [V,M={M}]: {t*1e3:.2f} ms", flush=True)

    # d) 1-row gather of plan-sized ids from [V*n]
    flat_ids = jax.device_put(
        jnp.asarray(rng.integers(0, 100, size=(V * n,)).astype(np.int32))
    )
    gidx = jax.device_put(
        jnp.asarray(rng.integers(0, V * n, size=(V * M,)).astype(np.int32))
    )
    t = time_fn(lambda f, g: jnp.take(f, g, axis=0), flat_ids, gidx)
    print(f"1-row gather of {V*M} ids: {t*1e3:.2f} ms", flush=True)


def bench_bin_variants():
    """Phase-1 attack: is the binning chain division-bound? Compare the
    remainder-based wrap against a reciprocal-multiply variant (exact for
    power-of-two extents: remainder(q, ext) == q - floor(q * (1/ext)) *
    ext bit-for-bit when 1/ext is exact)."""
    rng = np.random.default_rng(1)
    m = V * n
    flat = jax.device_put(
        jnp.asarray(rng.standard_normal((7, m)).astype(np.float32))
    )
    shape = (2, 2, 2)
    strides = (4, 2, 1)

    def bin_current(f):
        dest = jnp.zeros((m,), jnp.int32)
        for d in range(3):
            p = f[d, :]
            lo = jnp.float32(0.0)
            ext = jnp.float32(1.0)
            p = lo + jnp.remainder(p - lo, ext)
            p = jnp.where(p >= lo + ext, lo, p)
            inv_w = jnp.float32(shape[d] / 1.0)
            cell = jnp.clip(
                jnp.floor((p - lo) * inv_w).astype(jnp.int32),
                0, shape[d] - 1,
            )
            dest = dest + cell * jnp.int32(strides[d])
        return dest

    def bin_recip(f):
        dest = jnp.zeros((m,), jnp.int32)
        for d in range(3):
            q = f[d, :] - jnp.float32(0.0)
            # ext = 1.0 (power of two): reciprocal-multiply wrap, exact
            q = q - jnp.floor(q * jnp.float32(1.0)) * jnp.float32(1.0)
            q = jnp.where(q >= jnp.float32(1.0), jnp.float32(0.0), q)
            cell = jnp.clip(
                jnp.floor(q * jnp.float32(shape[d])).astype(jnp.int32),
                0, shape[d] - 1,
            )
            dest = dest + cell * jnp.int32(strides[d])
        return dest

    a = np.asarray(jax.jit(bin_current)(flat))
    b = np.asarray(jax.jit(bin_recip)(flat))
    print(f"bin variants bit-equal: {np.array_equal(a, b)}", flush=True)
    t = time_fn(bin_current, flat)
    print(f"bin with jnp.remainder: {t*1e3:.2f} ms", flush=True)
    t = time_fn(bin_recip, flat)
    print(f"bin with reciprocal-mul wrap: {t*1e3:.2f} ms", flush=True)


if __name__ == "__main__":
    main()
    bench_bin_variants()
