#!/usr/bin/env python
"""Export telemetry to a Perfetto/Chrome-trace JSON (`make observe`).

Thin CLI over :mod:`mpi_grid_redistribute_tpu.telemetry.traceview`.
Three input sources, combinable:

* ``--journal FILE`` — a JSON Lines journal written by
  ``StepRecorder.to_jsonl`` (or ``GridRedistribute.telemetry``); events
  are re-hydrated and become the instant + counter tracks.
* ``--phases FILE`` — a JSON list of phase rows as dumped by
  ``KNOCKOUT_JSON=file scripts/knockout_stages.py`` (the
  ``attribute_phases`` output); rows become the duration lane.
* ``--demo`` — no artifacts handy: run a small in-process drift loop on
  whatever devices exist and trace that journal.

Examples:

  # journal from a bench run -> trace
  python scripts/trace_export.py --journal run.jsonl --out trace.json

  # knockout attribution -> duration lane (same trace file)
  KNOCKOUT_JSON=phases.json python scripts/knockout_stages.py
  python scripts/trace_export.py --phases phases.json --out trace.json

  # self-contained demo
  python scripts/trace_export.py --demo --out trace.json

Open the output at https://ui.perfetto.dev or chrome://tracing.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def load_journal(path: str):
    """Re-hydrate a StepRecorder from a ``to_jsonl`` export."""
    from mpi_grid_redistribute_tpu import telemetry

    rec = telemetry.StepRecorder()
    n_lines = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            obj = json.loads(line)
            kind = obj.pop("kind")
            obj.pop("seq", None)
            t = obj.pop("time", None)
            # envelope tags (ISSUE 5 multi-host shards) identify the
            # writer, not the event — keep the rehydrated payload clean
            # and carry the identity on the recorder itself
            host, pid = obj.pop("host", None), obj.pop("pid", None)
            if host is not None:
                rec.host = str(host)
            if pid is not None:
                rec.pid = int(pid)
            # record_at keeps the original wall time so track
            # timestamps are honest (record() would stamp "now")
            rec.record_at(kind, t, **obj)
            n_lines += 1
    if n_lines == 0:
        raise SystemExit(f"{path}: empty journal")
    return rec


def load_phases(path: str):
    """Load phase rows dumped as JSON into PhaseTiming tuples."""
    from mpi_grid_redistribute_tpu.telemetry import phases as phases_lib

    with open(path) as f:
        rows = json.load(f)
    if not isinstance(rows, list):
        raise SystemExit(f"{path}: expected a JSON list of phase rows")
    out = []
    for r in rows:
        out.append(
            phases_lib.PhaseTiming(
                phase=r["phase"],
                cumulative_s=float(r["cumulative_s"]),
                delta_s=float(r["delta_s"]),
                logical_bytes=(
                    None
                    if r.get("logical_bytes") is None
                    else int(r["logical_bytes"])
                ),
                roofline_s=(
                    None
                    if r.get("roofline_s") is None
                    else float(r["roofline_s"])
                ),
            )
        )
    return out


def demo_recorder(steps: int = 16):
    """Run a small drift loop and return its populated journal."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import numpy as np

    from mpi_grid_redistribute_tpu import telemetry
    from mpi_grid_redistribute_tpu.bench import common
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.domain import Domain

    grid_shape = (2, 2, 2)
    dev_grid, vgrid, mesh, _ = common.pick_layout(grid_shape)
    rng = np.random.default_rng(0)
    n_local = 1 << 11
    pos, _, alive = common.uniform_state(grid_shape, n_local, 0.9, rng)
    vel = (0.02 * (rng.random(pos.shape, dtype=np.float32) - 0.5)).astype(
        np.float32
    )
    cfg = nbody.DriftConfig(
        domain=Domain(0.0, 1.0, periodic=True), grid=dev_grid, dt=1.0,
        capacity=max(64, n_local // 4), n_local=n_local,
    )
    loop = nbody.make_migrate_loop(cfg, mesh, steps, vgrid=vgrid)
    _, _, _, st = loop(
        nbody.rows_to_planar(pos, mesh.size),
        nbody.rows_to_planar(vel, mesh.size),
        alive,
    )
    rec = telemetry.StepRecorder()
    telemetry.record_migrate_steps(rec, st, rank_totals=True)
    acc = telemetry.FlowAccumulator()
    acc.update(st)
    telemetry.record_flow_snapshot(rec, acc)
    telemetry.HealthMonitor(rec).evaluate()
    return rec


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--journal", type=str, default=None,
                    help="StepRecorder JSONL export to re-hydrate")
    ap.add_argument("--phases", type=str, default=None,
                    help="JSON list of attribute_phases rows "
                         "(KNOCKOUT_JSON=file scripts/knockout_stages.py)")
    ap.add_argument("--demo", action="store_true",
                    help="run a small drift loop in-process and trace it")
    ap.add_argument("--steps", type=int, default=16,
                    help="demo drift steps (default 16)")
    ap.add_argument("--step-seconds", type=float, default=None,
                    help="measured per-step seconds for the counter "
                         "track's synthetic time axis (default 1 ms)")
    ap.add_argument("--roofline", type=str, default=None,
                    metavar="PROGRAM",
                    help="annotate the --phases duration lane with "
                         "PROGRAM's committed cost-model row (flops, "
                         "bytes, bound-by — from telemetry/"
                         "attribution_baseline.json; see "
                         "scripts/attribution.py)")
    ap.add_argument("--out", type=str, required=True,
                    help="output trace JSON path")
    args = ap.parse_args(argv)

    if not (args.journal or args.phases or args.demo):
        ap.error("nothing to export: give --journal, --phases, or --demo")

    from mpi_grid_redistribute_tpu.telemetry import traceview

    rec = None
    if args.journal:
        rec = load_journal(args.journal)
    elif args.demo:
        rec = demo_recorder(steps=args.steps)
    timings = load_phases(args.phases) if args.phases else None

    annotations = None
    if args.roofline:
        if not timings:
            ap.error("--roofline annotates the phase lane: give --phases")
        from mpi_grid_redistribute_tpu.analysis.baseline import (
            load_attribution_baseline,
        )

        doc = load_attribution_baseline()
        row = ((doc or {}).get("roofline") or {}).get(args.roofline)
        if row is None:
            raise SystemExit(
                f"--roofline: program {args.roofline!r} is not in the "
                "committed attribution snapshot — see "
                "scripts/attribution.py --update-baseline"
            )
        cost = {
            k: row.get(k)
            for k in (
                "flops",
                "bytes_accessed",
                "t_predicted_s",
                "bound_by",
                "bytes_ratio",
            )
        }
        annotations = {str(t.phase): cost for t in timings}

    n_ev = traceview.write_trace(
        args.out, rec, phase_timings=timings,
        step_seconds=args.step_seconds,
        annotations=annotations,
    )
    print(f"wrote {args.out} ({n_ev} trace events) — open at "
          f"https://ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
