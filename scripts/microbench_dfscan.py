"""On-chip: Pallas VMEM double-float tile prefix vs the XLA doubling loop.

Shapes mirror the 64M north-star deposit's per-channel-group prefix:
[g*T, 256] = [524288, 256] rows (cg=2 channel group). Bit-identity is
asserted first; both paths then timed with the scan harness.

Usage: python scripts/microbench_dfscan.py [rows] [tile]
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.ops import deposit, pallas_dfscan
from mpi_grid_redistribute_tpu.utils import profiling


def main():
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 524288
    tile = int(sys.argv[2]) if len(sys.argv) > 2 else 256
    r = np.random.default_rng(0)
    x = (r.random((rows, tile), dtype=np.float32)) * np.exp(
        r.normal(0, 4, size=(rows, tile))
    ).astype(np.float32)
    xd = jax.device_put(jnp.asarray(x))

    hi_k, lo_k = pallas_dfscan.tile_df_cumsum_rows(xd)
    hi_x, lo_x = jax.jit(
        lambda a: deposit._df_cumsum(a, axis=1)
    )(xd)
    for a, b, name in ((hi_k, hi_x, "hi"), (lo_k, lo_x, "lo")):
        aa = np.asarray(a).view(np.uint32)
        bb = np.asarray(b).view(np.uint32)
        assert np.array_equal(aa, bb), (
            f"{name} mismatch: {np.sum(aa != bb)} of {aa.size}"
        )
    print("bit-identity kernel vs XLA _df_cumsum: OK", flush=True)

    def timed(name, fn):
        def make_loop(S):
            @jax.jit
            def loop(a):
                def body(acc, _):
                    hi, lo = fn(acc)
                    return hi + lo * jnp.float32(1e-30), ()

                acc, _ = lax.scan(body, a, None, length=S)
                return acc

            return loop

        per, _, _ = profiling.scan_time_per_step(
            make_loop, (xd,), s1=2, s2=8
        )
        print(f"  {name}: {per*1e3:8.2f} ms", flush=True)

    timed("pallas VMEM dfscan", pallas_dfscan.tile_df_cumsum_rows)
    timed("XLA doubling loop", lambda a: deposit._df_cumsum(a, axis=1))


if __name__ == "__main__":
    main()
