"""Probe: flat 64M single-key payload sort vs batched per-slab [V, n]
sort (the vrank-major deposit-key idea).

The MXU deposit's remaining dominant cost is the single-key unstable
payload sort at m = V*n rows (~179 ms at 67M, deposit.py docstring).
If cells are numbered VRANK-MAJOR (key = v*C + local_cell), every slab's
valid keys lie in [v*C, (v+1)*C), so sorting each slab INDEPENDENTLY
yields a stream whose valid keys are globally non-decreasing — exactly
what pallas_segdep needs (with first-chunk-from-min fix). A batched
[V, n] axis-sort is V independent n-row sorts: lower depth
(log^2 n vs log^2 m) and lane-friendlier.

Scan-length-differenced (utils/profiling) — wall clocks on the axon
tunnel are meaningless.

Usage: python scripts/microbench_slab_sort.py [V] [n]
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np

from mpi_grid_redistribute_tpu.utils import profiling

V = int(sys.argv[1]) if len(sys.argv) > 1 else 64
n = int(sys.argv[2]) if len(sys.argv) > 2 else 1_048_576
m = V * n
C = 32768  # cells per vrank (128^3 / 64)

rng = np.random.default_rng(0)
key_flat = jnp.asarray(rng.integers(0, V * C, size=m, dtype=np.int32))
rel = [jnp.asarray(rng.random(m, dtype=np.float32)) for _ in range(3)]
mass = jnp.asarray(rng.random(m, dtype=np.float32))

# slab-local keys: each slab v gets keys in [v*C, (v+1)*C)
key_slab = (
    key_flat.reshape(V, n) % C
    + (jnp.arange(V, dtype=jnp.int32) * C)[:, None]
)


def make_loop_flat(S):
    @jax.jit
    def loop(key, r0, r1, r2, mass):
        def body(carry, _):
            k, a, b, c, w = carry
            s = jax.lax.sort((k, a, b, c, w), num_keys=1, is_stable=False)
            # feed the sorted payload back (xor keeps the key range) so
            # the scan cannot be collapsed across iterations
            k2 = s[0] ^ 1
            return (k2, s[1], s[2], s[3], s[4]), s[0][0]

        carry, outs = jax.lax.scan(
            body, (key, r0, r1, r2, mass), None, length=S
        )
        return outs

    return loop


def make_loop_slab(S):
    @jax.jit
    def loop(key2, r0, r1, r2, mass):
        ops = tuple(x.reshape(V, n) for x in (r0, r1, r2, mass))

        def body(carry, _):
            k, a, b, c, w = carry
            s = jax.lax.sort((k, a, b, c, w), num_keys=1, is_stable=False)
            k2 = s[0] ^ 1
            return (k2, s[1], s[2], s[3], s[4]), s[0][0, 0]

        carry, outs = jax.lax.scan(body, (key2,) + ops, None, length=S)
        return outs

    return loop


t_flat, _, _ = profiling.scan_time_per_step(
    make_loop_flat, (key_flat, *rel, mass), s1=2, s2=8
)
t_slab, _, _ = profiling.scan_time_per_step(
    make_loop_slab, (key_slab, *rel, mass), s1=2, s2=8
)
print(f"V={V} n={n} m={m}")
print(f"flat   sort ({m} rows, 5 operands): {t_flat * 1e3:8.2f} ms")
print(f"[V, n] sort ({V}x{n}, 5 operands):  {t_slab * 1e3:8.2f} ms")
