#!/usr/bin/env python
"""Bench regression guard CLI (`make bench-check`).

Thin wrapper over :mod:`mpi_grid_redistribute_tpu.telemetry.regress` —
invoking the module file directly (instead of ``python -m pkg.module``)
avoids runpy's found-in-sys.modules RuntimeWarning from the package
re-export. Same flags: ``--current``, ``--history``, ``--threshold``.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mpi_grid_redistribute_tpu.telemetry.regress import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
