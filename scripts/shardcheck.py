#!/usr/bin/env python
"""Run shardcheck, the sharding/replication abstract interpreter.

Usage:
    python scripts/shardcheck.py [--format=json|sarif|github] [--check]
    python scripts/shardcheck.py --update-baseline
    python scripts/shardcheck.py --list-rules | --list-programs

shardcheck TRACES the registered entry points with ``jax.make_jaxpr``
(no device execution) and propagates a per-mesh-axis varying/replicated
lattice through every eqn, gating S001-S004: replication of declared-
replicated outputs, redundant collectives, varying-value escapes, and
the per-axis ICI/DCN wire attribution against the ``wire_attribution``
section of ``analysis/progprofile_baseline.json``. Like
scripts/progcheck.py, this wrapper forces the 8-device virtual CPU
mesh BEFORE jax is imported so ``make shardcheck`` behaves identically
inside and outside CI.

Exit codes mirror gridlint: 0 clean, 1 findings/drift, 2 usage error.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_grid_redistribute_tpu.analysis.shardcheck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
