"""On-chip bit-exactness check for the migrate engines' payload transport.

Round-4 context: the canonical planar engines were found (on the real
chip) to FLUSH denormal f32 bit patterns — any bitcast int32 < 2^23 —
to zero inside the pack gather at >= ~3k rows/shard; the fix moved their
transport to an int32 bitcast view. The migrate engines carry the same
kind of fused planar matrix with bitcast payloads (migrate.fuse_fields)
through gathers + all_to_all + the landing scatter. This script drives a
real drift loop with a bitcast-int id row on the actual device and
asserts the id SET survives bit-exactly, for each landing-scatter impl.

Run on the TPU (no flags needed): python scripts/check_migrate_bitexact_tpu.py
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P
from mpi_grid_redistribute_tpu.compat import shard_map

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning
from mpi_grid_redistribute_tpu.parallel import migrate, mesh as mesh_lib
from mpi_grid_redistribute_tpu.bench import common


def run(n_local: int = 32768, steps: int = 10, scatter_impl=None) -> bool:
    dom = Domain(0.0, 1.0, periodic=True)
    dev_grid = ProcessGrid((1, 1, 1))
    vgrid = ProcessGrid((2, 2, 2))
    V = vgrid.nranks
    rng = np.random.default_rng(7)
    pos, vel, _ = common.uniform_state(
        vgrid.shape, n_local, 1.0, rng,
        vel_scale=0.02 / 3 * 2.0 / np.asarray(vgrid.shape, np.float32),
    )
    m = V * n_local
    ids = np.arange(m, dtype=np.int32)  # all denormal f32 bit patterns
    fused = np.concatenate(
        [
            pos.T.astype(np.float32).view(np.int32),
            vel.T.astype(np.float32).view(np.int32),
            ids[None, :],
            np.ones((1, m), np.int32),
        ],
        axis=0,
    )  # [8, V*n] int32 transport (migrate.fuse_fields convention)
    mesh = mesh_lib.make_mesh(dev_grid, devices=jax.devices()[:1])
    mig = migrate.shard_migrate_vranks_fn(
        dom, dev_grid, vgrid, capacity=max(256, n_local // 16),
        scatter_impl=scatter_impl,
    )
    D = 3

    axes = dev_grid.axis_names

    def shard_loop(fused):
        state = migrate.init_state(fused, vranks=V, batched=True)

        def _vary(x):
            missing = tuple(a for a in axes if a not in jax.typeof(x).vma)
            return lax.pcast(x, missing, to="varying") if missing else x

        state = jax.tree.map(_vary, state)

        def body(state, _):
            f = state.fused
            pf = lax.bitcast_convert_type(f[:D, :], jnp.float32)
            vf = lax.bitcast_convert_type(f[D : 2 * D, :], jnp.float32)
            p = binning.wrap_periodic_planar(pf + vf, dom)
            f = jnp.concatenate(
                [lax.bitcast_convert_type(p, jnp.int32), f[D:, :]], axis=0
            )
            state, stats = mig(state._replace(fused=f))
            return state, stats.backlog

        state, backlog = lax.scan(body, state, None, length=steps)
        return state.fused, backlog

    spec = P()
    out = jax.jit(
        shard_map(
            shard_loop, mesh=mesh, in_specs=(spec,),
            out_specs=(spec, spec), check_vma=False,
        )
    )(jnp.asarray(fused))
    f_out = np.asarray(out[0])
    alive = f_out[-1, :] > 0
    got = f_out[6, alive]
    ok_count = alive.sum() == m
    ok_ids = np.array_equal(np.sort(got), ids)
    impl = scatter_impl or "default"
    n_zero = int((got == 0).sum())
    print(
        f"scatter={impl}: alive {alive.sum()}/{m}, id set exact: {ok_ids}"
        + ("" if ok_ids else f" ({n_zero} zeros, {m - len(set(got.tolist()))} dups)")
    )
    return ok_count and ok_ids


if __name__ == "__main__":
    ok = True
    for impl in (None, "xla"):
        ok &= run(scatter_impl=impl)
    print("PASS" if ok else "FAIL")
