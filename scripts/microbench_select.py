"""Two-level leaver selection vs the flat packed sort (north-star phase 2).

The migrate engines consume the destination sort ONLY on the leaver
prefix (stayers carry the sentinel key and sort to the tail; every
downstream read sits inside a leaver segment or is masked). At 64x1M the
flat packed sort is the single largest phase of the north-star knockout
(~55 ms in context). lax.sort cost per element falls with column width
(bitonic depth ~ log^2 n), so a TWO-LEVEL selection — sort small chunks,
keep each chunk's bounded leaver prefix, finish with one small sort over
the candidates — reproduces the consumed prefix bit-for-bit at a
fraction of the moved bytes, with a cond fallback to the flat sort when
any chunk's leavers overflow the candidate cap.

Usage: python scripts/microbench_select.py [V] [n]
"""
from __future__ import annotations

import sys

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.utils import profiling
from mpi_grid_redistribute_tpu.ops import binning

V = int(sys.argv[1]) if len(sys.argv) > 1 else 64
n = int(sys.argv[2]) if len(sys.argv) > 2 else 1 << 20
R = V  # dests == vranks, sentinel R
LEAVER_FRAC = 0.02

rng = np.random.default_rng(0)
dest_np = np.full((V, n), R, np.int32)
mask = rng.random((V, n)) < LEAVER_FRAC
dest_np[mask] = rng.integers(0, R, size=int(mask.sum()), dtype=np.int32)
dest0 = jnp.asarray(dest_np)


def incumbent(dest):
    return jax.vmap(lambda k: binning.sorted_dest_counts(k, R))(dest)


def two_level(dest, T: int, q: int):
    nc = n // T
    bT = (T - 1).bit_length()
    bN = (n - 1).bit_length()
    iota_t = jnp.arange(T, dtype=jnp.int32)

    ch = dest.reshape(V, nc, T)
    lc = jnp.sum((ch != R).astype(jnp.int32), axis=-1)  # [V, nc]
    packed1 = lax.sort((ch << bT) | iota_t, dimension=-1, is_stable=False)
    cand = lax.slice_in_dim(packed1, 0, q, axis=2)  # [V, nc, q]
    dest_c = cand >> bT
    pos_g = (jnp.arange(nc, dtype=jnp.int32)[None, :, None] * T) | (
        cand & (T - 1)
    )
    live = jnp.arange(q, dtype=jnp.int32)[None, None, :] < lc[:, :, None]
    packed2 = jnp.where(live, (dest_c << bN) | pos_g, (R << bN))
    packed2 = lax.sort(
        packed2.reshape(V, nc * q), dimension=-1, is_stable=False
    )
    order_c = packed2 & ((1 << bN) - 1)  # [V, L]
    edges = jnp.arange(R + 1, dtype=jnp.int32) << bN
    bounds = jax.vmap(
        lambda p: jnp.searchsorted(p, edges, side="left").astype(jnp.int32)
    )(packed2)
    counts = bounds[:, 1:] - bounds[:, :-1]
    ok = jnp.all(lc <= q)

    def fast():
        pad = jnp.zeros((V, n), jnp.int32)
        return lax.dynamic_update_slice(pad, order_c, (0, 0))

    def slow():
        return incumbent(dest)[0]

    order = lax.cond(ok, fast, slow)
    return order, counts, bounds


def bench(name, fn):
    def make_loop(S):
        @jax.jit
        def loop(d):
            def body(c, _):
                o, cnt, b = fn(c)
                # data dependence: perturb leaver dests only (xor of the
                # low bit keeps dest in [0, R); sentinel rows stay
                # sentinel so the leaver density — and the guard — hold)
                c2 = jnp.where(c == R, c, c ^ (o[:, :1] & 1))
                return c2.astype(jnp.int32), ()
            c, _ = lax.scan(body, d, None, length=S)
            return c
        return loop

    per, _, _ = profiling.scan_time_per_step(make_loop, (dest0,), s1=4, s2=16)
    print(f"{name:40s} {per*1e3:8.2f} ms", flush=True)
    return per


# correctness: leaver prefix + counts/bounds bit-equal to the incumbent
o_ref, c_ref, b_ref = jax.jit(incumbent)(dest0)
for T in (4096, 16384):
    q = T // 8
    o2, c2, b2 = jax.jit(lambda d, T=T, q=q: two_level(d, T, q))(dest0)
    assert np.array_equal(np.asarray(c_ref), np.asarray(c2)), T
    assert np.array_equal(np.asarray(b_ref), np.asarray(b2)), T
    nl = np.asarray(c_ref).sum(axis=1)
    for v in range(0, V, max(1, V // 7)):
        L = int(nl[v])
        assert np.array_equal(
            np.asarray(o_ref)[v, :L], np.asarray(o2)[v, :L]
        ), (T, v)
print("correctness OK (prefix + counts + bounds bit-equal)", flush=True)

bench("incumbent vmap(sorted_dest_counts)", lambda d: incumbent(d))
for T in (4096, 8192, 16384):
    q = T // 8
    bench(f"two-level T={T} q={q}", lambda d, T=T, q=q: two_level(d, T, q))
