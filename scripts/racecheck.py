#!/usr/bin/env python
"""Run racecheck, the repo's host-thread shared-state analyzer.

Usage:
    python scripts/racecheck.py [paths...] [--format=json] [--check]
    python scripts/racecheck.py --list-rules
    python scripts/racecheck.py --list-threads

See mpi_grid_redistribute_tpu/analysis/racecheck.py for the thread
model and mpi_grid_redistribute_tpu/analysis/rules_thread.py for the
rule table (T001-T005). Suppressions use racecheck's own marker
(``# racecheck: disable=T00x``); the committed baseline is
mpi_grid_redistribute_tpu/analysis/racecheck_baseline.json. Pure-stdlib
``ast`` work — nothing it scans is executed, no jax import.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mpi_grid_redistribute_tpu.analysis.racecheck import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
