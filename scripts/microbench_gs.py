"""Microbenchmarks: TPU row gather/scatter cost scaling.

Questions that drive the migrate-path redesign (VERDICT round-1 item 2):
  1. true cost of the pack gather / landing scatter (optimization_barrier
     dependencies this time — profile_stages.py's ``*0`` trick folded away);
  2. does gather/scatter cost scale with #rows touched (→ compact routing
     wins) or with array size?
  3. does row width (K) matter, or is cost per-row?
  4. do sorted indices beat random ones?

Usage: python scripts/microbench_gs.py
"""

from __future__ import annotations

import sys

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from mpi_grid_redistribute_tpu.utils import profiling

N = 2**20  # rows in the resident array


def timed(name, make_loop, *args, s1=4, s2=24):
    per_step, _, _out = profiling.scan_time_per_step(make_loop, args, s1=s1, s2=s2)
    print(f"  {name:44s} {per_step*1e3:8.3f} ms", file=sys.stderr)
    return per_step * 1e3


def make_gather(P, K, sorted_idx=False):
    rng = np.random.default_rng(0)
    idx = rng.integers(0, N, size=(P,), dtype=np.int32)
    if sorted_idx:
        idx = np.sort(idx)
    idx = jax.device_put(jnp.asarray(idx))
    arr = jax.device_put(
        jnp.asarray(rng.random((N, K), dtype=np.float32))
    )

    def make_loop(S):
        @jax.jit
        def loop(arr, idx):
            def body(carry, _):
                a, i = carry
                out = jnp.take(a, i, axis=0)
                (a, i, out) = lax.optimization_barrier((a, i, out))
                i = (i + out[0, 0].astype(jnp.int32) % 2) % N
                return (a, i), ()

            carry, _ = lax.scan(body, (arr, idx), None, length=S)
            return carry

        return loop

    return make_loop, (arr, idx)


def make_scatter(P, K, sorted_idx=False):
    rng = np.random.default_rng(0)
    idx = rng.integers(0, N, size=(P,), dtype=np.int32)
    if sorted_idx:
        idx = np.sort(idx)
    idx = jax.device_put(jnp.asarray(idx))
    arr = jax.device_put(jnp.asarray(rng.random((N, K), dtype=np.float32)))
    rows = jax.device_put(jnp.asarray(rng.random((P, K), dtype=np.float32)))

    def make_loop(S):
        @jax.jit
        def loop(arr, idx, rows):
            def body(carry, _):
                a, i = carry
                a = a.at[i].set(rows, mode="drop")
                (a, i) = lax.optimization_barrier((a, i))
                i = (i + a[0, 0].astype(jnp.int32) % 2) % N
                return (a, i), ()

            carry, _ = lax.scan(body, (arr, idx, rows)[:2], None, length=S)
            return carry

        return loop

    return make_loop, (arr, idx, rows)


def main():
    results = {}
    print("gather: rows P from [1M, K] array", file=sys.stderr)
    for P in (2**14, 2**16, 2**18):
        for K in (1, 7, 8, 32):
            ml, args = make_gather(P, K)
            results[f"gather P={P} K={K}"] = timed(
                f"gather P={P:>6} K={K:>2} random", ml, *args
            )
    ml, args = make_gather(2**16, 8, sorted_idx=True)
    timed("gather P= 65536 K= 8 SORTED", ml, *args)

    print("scatter: rows P into [1M, K] array", file=sys.stderr)
    for P in (2**14, 2**16, 2**18):
        for K in (1, 7, 8, 32):
            ml, args = make_scatter(P, K)
            results[f"scatter P={P} K={K}"] = timed(
                f"scatter P={P:>6} K={K:>2} random", ml, *args
            )
    ml, args = make_scatter(2**16, 8, sorted_idx=True)
    timed("scatter P= 65536 K= 8 SORTED", ml, *args)


if __name__ == "__main__":
    main()
