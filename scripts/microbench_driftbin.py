"""On-chip microbench + bit check of the fused drift+wrap+bin kernel
(ops/pallas_driftbin.py) vs the XLA chain it replaces.

Usage: python scripts/microbench_driftbin.py [n_per_vrank] [V]
       python scripts/microbench_driftbin.py 1048576 64   # north-star
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np
import jax
import jax.numpy as jnp

from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import pallas_driftbin
from mpi_grid_redistribute_tpu.utils import profiling


def near_cubic(V):
    shape = []
    rem = V
    for _ in range(3):
        s = int(round(rem ** (1.0 / (3 - len(shape)))))
        while rem % s:
            s += 1
        shape.append(s)
        rem //= s
    return tuple(shape)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 2**20
    V = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    K = 7
    domain = Domain(0.0, 1.0, periodic=True)
    grid = ProcessGrid(near_cubic(V))
    m = V * n
    r = np.random.default_rng(0)
    pos = r.random((3, m), dtype=np.float32)
    vel = (r.random((3, m), dtype=np.float32) - 0.5).astype(np.float32)
    alive = (r.random((m,)) < 0.9).astype(np.int32)
    # hostile probes: NaN / inf / huge / negative positions in a corner
    pos[0, :64] = np.nan
    pos[1, 64:128] = np.inf
    pos[2, 128:192] = -np.inf
    pos[0, 192:256] = 3e38
    pos[1, 256:320] = -7.5
    flat = jnp.asarray(
        np.concatenate(
            [pos.view(np.int32), vel.view(np.int32), alive[None]], axis=0
        )
    )

    xla = jax.jit(
        lambda f: pallas_driftbin.drift_wrap_bin_xla(
            f, 0.05, domain, grid, V, V
        )
    )
    kern = jax.jit(
        lambda f: pallas_driftbin.drift_wrap_bin(
            f, 0.05, domain, grid, V, V
        )
    )
    f_x, k_x = jax.block_until_ready(xla(flat))
    f_p, k_p = jax.block_until_ready(kern(flat))
    # device-side comparison: fetching [K, 67M] buffers through the
    # tunnel costs minutes; two scalar counts cost nothing
    mism = jax.jit(
        lambda a, b, c, d: (
            jnp.sum((a != b).astype(jnp.int32), axis=1),
            jnp.sum((c != d).astype(jnp.int32)),
        )
    )
    row_ne, key_ne = map(np.asarray, mism(f_x, f_p, k_x, k_p))
    print(f"platform: {jax.devices()[0].platform}  V={V} n={n} m={m}")
    print(f"bit-equal: state={row_ne.sum() == 0} key={key_ne == 0}")
    if row_ne.sum() or key_ne:
        print(f"  per-row mismatches: {row_ne}, key: {key_ne}")

    def mk_loop(fn):
        def make(S):
            @jax.jit
            def loop(f):
                def body(f, _):
                    f2, key = fn(f)
                    # fold key into the carry so nothing is DCE'd
                    return f2.at[0, 0].add(key[0, 0]), ()

                f, _ = jax.lax.scan(body, f, None, length=S)
                return f

            return loop

        return make

    for name, fn in (("xla", None), ("kernel", None)):
        f = (
            (lambda fl: pallas_driftbin.drift_wrap_bin_xla(
                fl, 0.05, domain, grid, V, V))
            if name == "xla"
            else (lambda fl: pallas_driftbin.drift_wrap_bin(
                fl, 0.05, domain, grid, V, V))
        )
        per, _, _ = profiling.scan_time_per_step(
            mk_loop(f), (flat,), s1=4, s2=16
        )
        gb = (2 * K + 1) * m * 4 / 1e9
        print(
            f"{name:7s}: {per*1e3:8.3f} ms/step  "
            f"({gb / per:6.1f} GB/s of 819 effective)"
        )


if __name__ == "__main__":
    main()
