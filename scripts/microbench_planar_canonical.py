import os, sys, time, math
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
import numpy as np
import jax, jax.numpy as jnp
from jax import lax
from mpi_grid_redistribute_tpu.domain import Domain, ProcessGrid
from mpi_grid_redistribute_tpu.ops import binning, pack as pack_lib
from mpi_grid_redistribute_tpu.parallel import exchange
from mpi_grid_redistribute_tpu.ops.pack import pack_cols as _pack_cols
from mpi_grid_redistribute_tpu.utils import profiling

V = 8
vgrid = ProcessGrid((2,2,2))
domain = Domain(0.0, 1.0, periodic=True)
n_loc = 524288
slots = int(n_loc * 1.25)
migration = 0.02
cap = max(64, math.ceil(n_loc * migration / 3 * 2.5))
C = cap
rng = np.random.default_rng(1)
from mpi_grid_redistribute_tpu.bench import common as bc
p0, v0, _ = bc.uniform_state((2,2,2), n_loc, 1.0, rng,
    vel_scale=migration/3.0*2.0/np.asarray((2,2,2),np.float32))
posv = np.zeros((V, slots, 3), np.float32); posv[:, :n_loc] = p0.reshape(V, n_loc, 3)
velv = np.zeros((V, slots, 3), np.float32); velv[:, :n_loc] = v0.reshape(V, n_loc, 3)
fused = np.ascontiguousarray(np.concatenate(
    [posv.transpose(0,2,1), velv.transpose(0,2,1)], axis=1))
countv = np.full((V,), n_loc, np.int32)
D = 3
n = slots
out_capacity = slots

def stage_fn(upto):
    def fn(f, count):
        me_ids = jnp.arange(V, dtype=jnp.int32)
        def pack_one(f_v, count_v, me):
            iota = jnp.arange(n, dtype=jnp.int32)
            valid = iota < count_v
            dest = binning.rank_of_position_planar(f_v[:D], domain, vgrid)
            dest = jnp.where(valid, dest, V).astype(jnp.int32)
            is_self = valid & (dest == me)
            dest_remote = jnp.where(is_self, V, dest)
            order, remote_counts, bounds = binning.sorted_dest_counts(dest_remote, V)
            send_counts = jnp.minimum(remote_counts, C)
            packed, _ = _pack_cols(f_v, order, bounds[:V], send_counts, V, C)
            return packed, send_counts, is_self
        packed, send_counts, is_self = jax.vmap(pack_one)(f, count, me_ids)
        if upto == 1:
            return packed.sum() + send_counts.sum()
        K = f.shape[1]
        recv = packed.reshape(V,K,V,C).transpose(2,1,0,3).reshape(V,K,V*C)
        recv_counts = send_counts.T
        if upto == 2:
            return recv.sum() + recv_counts.sum()
        def compact_one(pool_v, rcnt_v, me, self_mask_v, f_v):
            c_idx = jnp.arange(C, dtype=jnp.int32)
            valid_r = (c_idx[None,:] < rcnt_v[:,None]).reshape(V*C)
            src_r = jnp.broadcast_to(jnp.arange(V,dtype=jnp.int32)[:,None],(V,C)).reshape(V*C)
            src_s = jnp.full((n,), me, dtype=jnp.int32)
            invalid = ~jnp.concatenate([valid_r, self_mask_v])
            source_key = jnp.concatenate([src_r, src_s])
            order = pack_lib._stable_order(invalid, source_key)
            if upto == 3:
                return order.sum()[None].astype(jnp.float32)
            values = jnp.concatenate([pool_v, f_v], axis=1)
            new_full = jnp.sum(rcnt_v) + jnp.sum(self_mask_v.astype(jnp.int32))
            new_count = jnp.minimum(new_full, out_capacity)
            take = pack_lib._take_rows(order, out_capacity)
            col_valid = jnp.arange(out_capacity, dtype=jnp.int32) < new_count
            out = jnp.where(col_valid[None,:], jnp.take(values, take, axis=1), 0)
            return out
        if upto == 5:
            def compact_sort_one(pool_v, rcnt_v, me, self_mask_v, f_v):
                c_idx = jnp.arange(C, dtype=jnp.int32)
                valid_r = (c_idx[None,:] < rcnt_v[:,None]).reshape(V*C)
                src_r = jnp.broadcast_to(jnp.arange(V,dtype=jnp.int32)[:,None],(V,C)).reshape(V*C)
                src_s = jnp.full((n,), me, dtype=jnp.int32)
                invalid = (~jnp.concatenate([valid_r, self_mask_v])).astype(jnp.int32)
                source_key = jnp.concatenate([src_r, src_s])
                values = jnp.concatenate([pool_v, f_v], axis=1)
                m = values.shape[1]
                iota = jnp.arange(m, dtype=jnp.int32)
                K = values.shape[0]
                operands = (invalid, source_key, iota) + tuple(values[k] for k in range(K))
                out = jax.lax.sort(operands, num_keys=3, is_stable=False)
                payload = jnp.stack(out[3:], axis=0)[:, :out_capacity]
                new_full = jnp.sum(rcnt_v) + jnp.sum(self_mask_v.astype(jnp.int32))
                new_count = jnp.minimum(new_full, out_capacity)
                col_valid = jnp.arange(out_capacity, dtype=jnp.int32) < new_count
                return jnp.where(col_valid[None,:], payload, 0)
            r = jax.vmap(compact_sort_one)(recv, recv_counts, me_ids, is_self, f)
            return r.sum()
        if upto == 6:
            def compact_sort2_one(pool_v, rcnt_v, me, self_mask_v, f_v):
                c_idx = jnp.arange(C, dtype=jnp.int32)
                valid_r = (c_idx[None,:] < rcnt_v[:,None]).reshape(V*C)
                src_r = jnp.broadcast_to(jnp.arange(V,dtype=jnp.int32)[:,None],(V,C)).reshape(V*C)
                src_s = jnp.full((n,), me, dtype=jnp.int32)
                invalid = ~jnp.concatenate([valid_r, self_mask_v])
                source_key = jnp.where(invalid, V, jnp.concatenate([src_r, src_s]))
                values = jnp.concatenate([pool_v, f_v], axis=1)
                m = values.shape[1]
                iota = jnp.arange(m, dtype=jnp.int32)
                K = values.shape[0]
                operands = (source_key, iota) + tuple(values[k] for k in range(K))
                out = jax.lax.sort(operands, num_keys=2, is_stable=False)
                payload = jnp.stack(out[2:], axis=0)[:, :out_capacity]
                new_full = jnp.sum(rcnt_v) + jnp.sum(self_mask_v.astype(jnp.int32))
                new_count = jnp.minimum(new_full, out_capacity)
                col_valid = jnp.arange(out_capacity, dtype=jnp.int32) < new_count
                return jnp.where(col_valid[None,:], payload, 0)
            r = jax.vmap(compact_sort2_one)(recv, recv_counts, me_ids, is_self, f)
            return r.sum()
        r = jax.vmap(compact_one)(recv, recv_counts, me_ids, is_self, f)
        return r.sum() if upto >= 3 else r
    return fn

args = (jnp.asarray(fused), jnp.asarray(countv))
for upto, label in [(1,"pack (bin+sort+gatherC)"), (2,"+transpose"), (3,"+compact sort"), (4,"+compact gather"), (5,"payload-sort compact (full)"), (6,"payload-sort 2key (full)")]:
    sf = stage_fn(upto)
    def make_loop(S, sf=sf):
        @jax.jit
        def loop(f, count):
            def body(acc, _):
                # the acc*1e-30 perturbation serializes iterations (no CSE hoist)
                s = sf(f + acc * jnp.float32(1e-30), count)
                return acc + jnp.asarray(s, jnp.float32).sum(), None
            out, _ = lax.scan(body, jnp.float32(0), None, length=S)
            return out
        return loop
    per, _, _ = profiling.scan_time_per_step(make_loop, args, s1=2, s2=8)
    print(f"{label}: {per*1e3:.2f} ms")
