"""Attribute the slab deposit's residence-guard cost at the 64M shape:
(1) slab engine with no guard/cond, (2) the production cond with the
fused guard predicate, (3) cond with a constant-true predicate (XLA
folds the branch — isolates predicate cost from cond-boundary cost).

Usage: python scripts/microbench_slab_guard.py
"""
from __future__ import annotations

import math
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from mpi_grid_redistribute_tpu.ops import deposit as dep
from mpi_grid_redistribute_tpu.utils import profiling

V_SHAPE = (4, 4, 4)
V = math.prod(V_SHAPE)
n = 1 << 20
DEV_BLOCK = (128, 128, 128)
vblock = tuple(b // v for b, v in zip(DEV_BLOCK, V_SHAPE))

rng = np.random.default_rng(0)
pos = np.empty((V * n, 3), np.float32)
import itertools
vcells = list(itertools.product(*[range(g) for g in V_SHAPE]))
for v, vc in enumerate(vcells):
    lo = np.asarray(vc) / np.asarray(V_SHAPE)
    pos[v * n : (v + 1) * n] = (
        lo + rng.random((n, 3)) / np.asarray(V_SHAPE)
    ).astype(np.float32)
pos_rows = jnp.asarray(np.ascontiguousarray(pos.T))
valid = jnp.asarray(rng.random(V * n) > 0.1)
lo_all = jnp.asarray(
    np.asarray(vcells, np.float32) / np.asarray(V_SHAPE, np.float32)
)
inv_h = jnp.full(3, 128.0)
dev_lo = jnp.zeros(3)


def make_variant(mode):
    def make_loop(S):
        @jax.jit
        def loop(pos_rows, valid):
            def body(carry, _):
                pr, va = carry
                key, rel, mass2, ok = dep._slab_keys_mxu(
                    pr, None, va, lo_all, inv_h, vblock
                )
                if mode == "noguard":
                    rho = dep._slab_deposit_from_keys(
                        key, rel, mass2, vblock, V_SHAPE
                    )
                else:
                    pred = ok if mode == "cond" else jnp.bool_(True)
                    rho = lax.cond(
                        pred,
                        lambda: dep._slab_deposit_from_keys(
                            key, rel, mass2, vblock, V_SHAPE
                        ),
                        lambda: dep.cic_deposit_device_mxu(
                            pr, None, va, dev_lo, inv_h, DEV_BLOCK
                        ),
                    )
                # rho feeds the carry probe so the deposit is forced
                return (pr, va), rho[0, 0, 0]

            _, outs = lax.scan(body, (pos_rows, valid), None, length=S)
            return outs

        return loop

    return make_loop


for mode in ("noguard", "const", "cond"):
    t, _, _ = profiling.scan_time_per_step(
        make_variant(mode), (pos_rows, valid), s1=2, s2=6
    )
    print(f"{mode:8s}: {t * 1e3:8.2f} ms/deposit")
