#!/usr/bin/env python
"""Serve the grid metrics plane over HTTP (`make serve-metrics`).

Thin stdlib ``http.server`` front-end over
:mod:`mpi_grid_redistribute_tpu.telemetry.metrics` /
:mod:`...telemetry.aggregate`. Two endpoints:

* ``GET /metrics`` — OpenMetrics text. The registry is rebuilt from the
  journal source on EVERY scrape (the "re-snapshot" contract): counters
  are the recorder's exact all-time counts, gauges/histograms cover the
  retained window at scrape time. No device work happens on this path —
  the journal is host memory (or files), and the metrics/aggregate
  modules never import jax.
* ``GET /healthz`` — JSON health verdict from a ``HealthMonitor`` run
  read-only over the same journal (``evaluate(record=False)`` — a
  poller must observe health, not mutate the journal it is judging).
  HTTP 200 on OK/WARN, 503 on ALERT, so a plain liveness probe can act
  on it without parsing.
* ``GET /incidents`` (with ``--incident-dir``) — JSON listing of the
  flight-recorder bundles under the directory (each entry is the
  bundle's ``index.json``; see ``telemetry/incident.py`` and
  ``scripts/incident.py`` for inspection/export).
* ``GET /query`` — the telemetry query plane
  (:mod:`...telemetry.query`): filter by ``kind``/``step_min``/
  ``step_max``/``trace``/``host``/``pid``/``since``/``until``/
  ``ctx.<field>``, shape with ``agg=<op>`` windowed series or
  ``by=<key>`` grouped counts (grammar in telemetry/SCHEMA.md). Bad
  parameters are HTTP 400 with the parse error in the body.
* ``GET /events`` — cursor-resumable event stream over the same
  source. The cursor is the ``host:pid:seq`` envelope triple (the pod
  merge's total order); pass the previous reply's ``cursor`` back to
  resume exactly where it left off, ``limit`` to bound the page and
  ``timeout_s`` to long-poll until new events arrive (re-snapshots the
  source every 0.2 s while waiting).

Journal sources, combinable:

* ``--journal FILE`` (repeatable) — JSONL shard(s) written by
  ``StepRecorder.to_jsonl``; several shards are pod-merged via
  ``aggregate.merge_journals`` (``--align wall|start``) and re-read on
  every scrape, so a live run appending shards is picked up. Parsed
  shards are cached keyed on ``(path, mtime, size)``: a scrape storm
  against a quiescent journal re-merges nothing, while any shard
  growing (or appearing) invalidates the cache on the next scrape.
* ``--store DIR`` — a durable ``telemetry.store`` journal-store root
  (``MANIFEST.json`` + segments). Re-read when the manifest changes, so
  a live driver draining into the store is tracked scrape to scrape;
  counters stay the manifest's exact all-time counts even after
  retention and compaction.
* ``--demo`` — no artifacts handy: run a small in-process drift loop in
  a background thread and scrape its live recorder.

Examples:

  # serve a bench run's shards pod-wide on :9100
  python scripts/metrics_serve.py --journal shard0.jsonl \\
      --journal shard1.jsonl --port 9100

  # self-contained demo; --once prints one scrape and exits (CI)
  python scripts/metrics_serve.py --demo --once
"""

from __future__ import annotations

import argparse
import http.server
import json
import os
import signal
import sys
import threading
import time

# gridlint: service-path

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)


def _shard_key(paths):
    """Cache key over the shard files: ``(path, mtime_ns, size)`` per
    shard. Any append, truncation, replacement or late-appearing shard
    changes the key; a quiescent journal keeps it stable."""
    key = []
    for p in paths:
        try:
            st = os.stat(p)
            key.append((p, st.st_mtime_ns, st.st_size))
        except OSError:
            key.append((p, None, None))
    return tuple(key)


def journal_snapshotter(paths, align):
    """``(snapshot, shutdown)`` over JSONL shard files: re-reads and
    re-merges when any shard changed since the last scrape (keyed on
    ``(path, mtime, size)``), so scrapes track a journal that is still
    growing without re-parsing an unchanged one on every poll. Nothing
    to stop — ``shutdown`` is a no-op."""
    from mpi_grid_redistribute_tpu import telemetry

    lock = threading.Lock()
    cache = {"key": None, "rec": None}

    def snapshot():
        # stat outside the lock (cheap, no shared state), compare under
        # it; parse outside the lock on a miss so a slow merge does not
        # serialize concurrent scrapes, then double-check before storing
        key = _shard_key(paths)
        with lock:
            if cache["key"] == key and cache["rec"] is not None:
                return cache["rec"]
        merged = telemetry.merge_journals(paths, align=align)
        rec = merged.to_recorder(pod_steps=len(merged.shards) > 1)
        with lock:
            cache["key"] = key
            cache["rec"] = rec
        return rec

    def shutdown():
        return None

    return snapshot, shutdown


def store_snapshotter(store_dir):
    """``(snapshot, query_snapshot, shutdown)`` over a durable
    ``telemetry.store`` root. ``snapshot`` returns a replayed
    ``StepRecorder`` with its all-time counters pinned to the
    manifest's exact totals (what ``/metrics`` and ``/healthz``
    consume); ``query_snapshot`` returns the ``StoreReader`` itself so
    ``/query`` and ``/events`` see compacted ``store_window`` rows
    first-class (quantiles over summaries stay exact). Both are cached
    keyed on the manifest's ``(mtime_ns, size)`` — the store's writer
    publishes the manifest atomically, so a changed key is a complete
    new store state, never a torn one."""
    from mpi_grid_redistribute_tpu.telemetry import store as store_lib

    manifest_path = os.path.join(store_dir, "MANIFEST.json")
    lock = threading.Lock()
    cache = {"key": None, "reader": None, "rec": None}

    def _key():
        try:
            st = os.stat(manifest_path)
            return (st.st_mtime_ns, st.st_size)
        except OSError:
            return None

    def _refresh():
        key = _key()
        with lock:
            if cache["key"] == key and cache["reader"] is not None:
                return cache["reader"], cache["rec"]
        reader = store_lib.StoreReader(store_dir)
        rec = reader.to_recorder()
        with lock:
            cache["key"] = key
            cache["reader"] = reader
            cache["rec"] = rec
        return reader, rec

    def snapshot():
        return _refresh()[1]

    def query_snapshot():
        return _refresh()[0]

    def shutdown():
        return None

    return snapshot, query_snapshot, shutdown


def demo_snapshotter(steps: int = 200):
    """``(snapshot, shutdown)`` over a small redistribute loop run in a
    background thread; scrapes snapshot its recorder live. Uses the
    numpy backend — the demo is about the metrics surface, not the
    engines. ``shutdown`` sets the stop event and joins the drive
    thread, so every exit path (``--once``, Ctrl-C, SIGTERM, server
    teardown) leaves no thread behind."""
    import numpy as np

    from mpi_grid_redistribute_tpu import api
    from mpi_grid_redistribute_tpu.domain import Domain

    rd = api.GridRedistribute(
        Domain(0.0, 1.0, periodic=True), (2, 2, 2), backend="numpy"
    )
    rng = np.random.default_rng(0)
    stop = threading.Event()

    def drive():  # racecheck: recorder-writer
        # the drive thread is the recorder's declared single writer
        # (T005); the HTTP handlers only snapshot events()/counts()
        n = 4096
        pos = rng.random((n, 3), dtype=np.float32)
        vel = 0.1 * (rng.random((n, 3), dtype=np.float32) - 0.5)
        for _ in range(steps):
            if stop.is_set():
                return
            t0 = time.perf_counter()
            rd.redistribute(pos, vel)
            rd.monitor.note_step_time(time.perf_counter() - t0)
            rd.monitor.evaluate()
            pos = (pos + 0.05 * vel) % 1.0
        stop.set()

    t = threading.Thread(target=drive, daemon=True)
    t.start()

    def snapshot():
        return rd.telemetry

    def shutdown():
        stop.set()
        t.join(timeout=10)

    return snapshot, shutdown


def make_handler(snapshot, incident_dir=None, query_source=None):
    """An HTTPRequestHandler bound to a journal snapshot factory;
    ``incident_dir`` additionally serves the flight-recorder bundle
    listing on ``/incidents`` (pure file reads — no journal state).
    ``query_source`` overrides the source ``/query``/``/events`` read
    (the store mode passes the ``StoreReader`` here so compacted
    summary rows stay visible); defaults to ``snapshot``."""
    import urllib.parse

    from mpi_grid_redistribute_tpu import telemetry
    from mpi_grid_redistribute_tpu.telemetry import incident as incident_lib
    from mpi_grid_redistribute_tpu.telemetry import query as query_lib

    events_source = query_source if query_source is not None else snapshot

    class Handler(http.server.BaseHTTPRequestHandler):
        def _send(self, code, ctype, body: bytes):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _send_json(self, code, doc):
            body = (json.dumps(doc, sort_keys=True) + "\n").encode("utf-8")
            self._send(code, "application/json; charset=utf-8", body)

        def _params(self):
            qs = urllib.parse.urlsplit(self.path).query
            # last value wins, matching the flat-string grammar
            return {
                k: v[-1]
                for k, v in urllib.parse.parse_qs(
                    qs, keep_blank_values=True
                ).items()
            }

        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                rec = snapshot()
                text = telemetry.from_journal(rec).render_openmetrics()
                self._send(
                    200, OPENMETRICS_CONTENT_TYPE, text.encode("utf-8")
                )
            elif path == "/healthz":
                rec = snapshot()
                monitor = telemetry.HealthMonitor(rec)
                verdict = monitor.evaluate(record=False)
                body = (json.dumps(verdict, sort_keys=True) + "\n").encode(
                    "utf-8"
                )
                code = 503 if verdict["status"] == "ALERT" else 200
                self._send(code, "application/json; charset=utf-8", body)
            elif path == "/incidents" and incident_dir is not None:
                listing = incident_lib.list_bundles(incident_dir)
                body = (
                    json.dumps(
                        {"dir": incident_dir, "incidents": listing},
                        sort_keys=True,
                    )
                    + "\n"
                ).encode("utf-8")
                self._send(200, "application/json; charset=utf-8", body)
            elif path == "/query":
                try:
                    reply = query_lib.run_query(
                        events_source(), self._params()
                    )
                except query_lib.QueryError as e:
                    self._send_json(400, {"error": str(e)})
                    return
                self._send_json(200, reply)
            elif path == "/events":
                params = self._params()
                try:
                    cursor = params.get("cursor") or None
                    limit = int(params.get("limit", "256"))
                    timeout_s = float(params.get("timeout_s", "0"))
                    kind = params.get("kind") or None
                    deadline = time.monotonic() + min(timeout_s, 60.0)
                    while True:
                        rows = query_lib.rows_of(events_source())
                        if kind:
                            rows = query_lib.filter_rows(rows, kind=kind)
                        page = query_lib.events_page(
                            rows, cursor=cursor, limit=limit
                        )
                        if page["events"] or time.monotonic() >= deadline:
                            break
                        # long-poll: re-snapshot until new events land
                        # or the (capped) timeout expires
                        time.sleep(0.2)
                except (query_lib.QueryError, ValueError) as e:
                    self._send_json(400, {"error": str(e)})
                    return
                self._send_json(200, page)
            else:
                self._send(
                    404,
                    "text/plain; charset=utf-8",
                    b"try /metrics, /healthz, /incidents, /query or "
                    b"/events\n",
                )

        def log_message(self, fmt, *args):
            print("  " + fmt % args, file=sys.stderr)

    return Handler


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Serve /metrics (OpenMetrics) + /healthz over a "
        "telemetry journal."
    )
    p.add_argument(
        "--journal",
        action="append",
        default=[],
        metavar="FILE",
        help="JSONL journal shard (repeat for a pod merge); re-read on "
        "every scrape",
    )
    p.add_argument(
        "--align",
        choices=("wall", "start"),
        default="wall",
        help="multi-shard clock alignment (see aggregate.merge_journals)",
    )
    p.add_argument(
        "--store",
        metavar="DIR",
        help="durable journal-store root (telemetry/store.py); re-read "
        "when its MANIFEST.json changes",
    )
    p.add_argument(
        "--demo",
        action="store_true",
        help="serve a live in-process drift-loop journal",
    )
    p.add_argument(
        "--incident-dir",
        metavar="DIR",
        help="flight-recorder bundle root; enables GET /incidents "
        "(see telemetry/incident.py)",
    )
    p.add_argument("--port", type=int, default=9100,
                   help="0 = ephemeral (bound port is printed)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument(
        "--once",
        action="store_true",
        help="print one /metrics scrape + the /healthz verdict to "
        "stdout and exit (no server)",
    )
    args = p.parse_args(argv)

    sources = sum(
        (bool(args.journal), bool(args.store), bool(args.demo))
    )
    if sources == 0:
        p.error("need --journal FILE (repeatable), --store DIR or --demo")
    if sources > 1:
        p.error("--journal, --store and --demo are mutually exclusive")

    from mpi_grid_redistribute_tpu import telemetry

    query_source = None
    if args.journal:
        snapshot, shutdown = journal_snapshotter(args.journal, args.align)
    elif args.store:
        snapshot, query_source, shutdown = store_snapshotter(args.store)
    else:
        snapshot, shutdown = demo_snapshotter()

    if args.once:
        try:
            rec = snapshot()
            sys.stdout.write(
                telemetry.from_journal(rec).render_openmetrics()
            )
            verdict = telemetry.HealthMonitor(rec).evaluate(record=False)
            print("healthz: " + json.dumps(verdict, sort_keys=True))
        finally:
            # --once must not leave the demo drive thread running behind
            # the printed scrape
            shutdown()
        return 0

    server = http.server.ThreadingHTTPServer(
        (args.host, args.port),
        make_handler(
            snapshot,
            incident_dir=args.incident_dir,
            query_source=query_source,
        ),
    )
    host, port = server.server_address[:2]
    extra = " and /incidents" if args.incident_dir else ""
    print(f"serving http://{host}:{port}/metrics, /healthz, /query, "
          f"/events{extra} (Ctrl-C to stop)", flush=True)

    def _on_sigterm(signum, frame):
        # route SIGTERM through the KeyboardInterrupt path below so the
        # server closes and the snapshotter's stop event fires — a
        # killed scrape server must not strand its drive thread
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print("stopped")
    finally:
        server.server_close()
        shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
