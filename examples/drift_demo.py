"""Runnable end-to-end demo: the reference-family ``mpirun demo.py``
experience (SURVEY.md §3.5, C10), TPU-style.

Generates random particles, redistributes them onto a 2x2x2 Cartesian
grid of shards, asserts every particle landed inside its owner's
subdomain, runs a short periodic drift loop with a redistribute every
step, prints a per-rank stats table, and (with --plot) writes a CIC
density image to drift_demo.png.

Run it on whatever is available:

  # one TPU chip (or one CPU device): the 8 subdomains run as one shard
  python examples/drift_demo.py

  # 8 virtual CPU devices — the multi-device path, no cluster needed
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/drift_demo.py

  # 8 real TPU chips: same command, nothing changes
  python examples/drift_demo.py
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--n", type=int, default=1 << 16,
                    help="total particles (default 65536)")
    ap.add_argument("--steps", type=int, default=20,
                    help="drift steps (default 20)")
    ap.add_argument("--plot", action="store_true",
                    help="write drift_demo.png (needs matplotlib)")
    ap.add_argument("--bias", action="store_true",
                    help="convergent velocity field (particles pile into "
                         "one shard) — demonstrates the health monitor "
                         "firing a backlog-growth alert")
    ap.add_argument("--trace", type=str, default=None,
                    help="write a Perfetto/Chrome-trace JSON of the "
                         "telemetry journal here")
    ap.add_argument("--expect-alert", action="store_true",
                    help="exit non-zero unless the monitor ALERTs (pair "
                         "with --bias; `make observe` uses both modes)")
    ap.add_argument("--halo", action="store_true",
                    help="after the drift loop, run one ghost/overlap "
                         "exchange (rd.halo()) on the redistributed "
                         "state and print per-rank ghost counts")
    ap.add_argument("--corrupt", action="store_true",
                    help="state-health observatory drill: run a short "
                         "supervised service loop with the in-graph "
                         "probes armed, NaN-burst the particle state "
                         "mid-run, and exit non-zero unless the "
                         "corruption is detected (state_health event), "
                         "paged (nan_detected ALERT + incident bundle "
                         "naming the step) and rolled back (restore "
                         "from a pre-corruption snapshot); the third "
                         "`make observe` leg")
    args = ap.parse_args()

    import jax

    # honor JAX_PLATFORMS even where a sitecustomize hook force-registers
    # an accelerator platform (backend selection is lazy; this wins if it
    # runs before any computation)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    import mpi_grid_redistribute_tpu as gr
    from mpi_grid_redistribute_tpu import oracle
    from mpi_grid_redistribute_tpu.models import nbody
    from mpi_grid_redistribute_tpu.bench import common
    from mpi_grid_redistribute_tpu.utils import stats as stats_lib

    grid_shape = (2, 2, 2)
    domain = gr.Domain(0.0, 1.0, periodic=True)
    R = 8
    n_local = args.n // R
    rng = np.random.default_rng(0)

    # --- 1. one-shot redistribute + ownership check (the classic demo) --
    pos = rng.random((R * n_local, 3), dtype=np.float32)
    vel = (0.2 * (rng.random((R * n_local, 3), dtype=np.float32) - 0.5))
    ids = np.arange(R * n_local, dtype=np.int32)

    # out_capacity > n_local leaves free slots per shard — the landing
    # headroom the drift loop's resident-slot migration needs
    out_cap = (n_local * 5) // 4
    rd = gr.GridRedistribute(
        domain, grid_shape, capacity_factor=4.0, out_capacity=out_cap
    )
    res = rd.redistribute(pos, vel, ids)
    count = np.asarray(res.count)
    shards = [
        np.asarray(res.positions)[r * out_cap : r * out_cap + count[r]]
        for r in range(R)
    ]
    oracle.assert_ownership(domain, rd.grid, shards)
    assert count.sum() == R * n_local
    print(f"redistributed {R * n_local} particles over {grid_shape}: "
          f"every particle is inside its owner's subdomain")

    summary = stats_lib.summarize_redistribute(res.stats)
    print("rank   held  received-from-remote")
    recv = np.asarray(res.stats.recv_counts)
    for r in range(R):
        remote = int(recv[r].sum() - recv[r, r])
        print(f"{r:4d} {count[r]:6d} {remote:10d}")
    print(f"moved {summary['moved_rows']:.0f} rows total; "
          f"recv imbalance {summary['recv_imbalance']:.3f}; "
          f"dropped {summary['dropped_send'] + summary['dropped_recv']}")
    # resolve the deferred overflow window here (one device fetch at a
    # known point) rather than warning from __del__ at teardown, and show
    # the merged telemetry surface while we are at it
    rd.flush_overflow_checks()
    from mpi_grid_redistribute_tpu.telemetry import report as report_lib
    print("telemetry: " + report_lib.format_report(rd.report()))

    # --- 2. drift loop: redistribute every step (SURVEY.md §3.3) --------
    dev_grid, vgrid, mesh, n_chips = common.pick_layout(grid_shape)
    cap = max(64, n_local // 4)
    cfg = nbody.DriftConfig(
        domain=domain, grid=dev_grid, dt=0.05, capacity=cap,
        n_local=out_cap,
    )
    if args.bias:
        # convergent flight plan: every particle flies straight at one
        # shard's center, timed to be ~2/3 of the way there when the run
        # ends — the sink shard's landing slots exhaust during the final
        # steps, its grants dry up, and the senders' backlog is still
        # climbing at the end (the failure mode the health monitor's
        # backlog_growth rule pages on; timed-arrival keeps the stall
        # from saturating into a flat backlog before the window closes)
        sink = np.asarray([0.25, 0.25, 0.25], np.float32)
        vel = (sink[None, :] - pos) / (args.steps * 0.05) * 0.65
        res = rd.redistribute(pos, vel, ids)
        rd.flush_overflow_checks()
        count = np.asarray(res.count)
    loop = nbody.make_migrate_loop(cfg, mesh, args.steps, vgrid=vgrid)
    # drift from the redistributed (owner-placed) state; valid rows per
    # shard become the alive mask, the rest are free landing slots
    alive = (
        np.arange(out_cap)[None, :] < count[:, None]
    ).reshape(-1)
    p, v, a, st = jax.tree.map(
        np.asarray,
        loop(
            nbody.rows_to_planar(np.asarray(res.positions), mesh.size),
            nbody.rows_to_planar(np.asarray(res.fields[0]), mesh.size),
            jnp.asarray(alive),
        ),
    )
    p = nbody.planar_to_rows(p, 3, mesh.size)  # loop returns planar flat
    msum = stats_lib.summarize_migrate(st)
    assert int(a.sum()) == R * n_local, "conservation violated"
    stats_lib.check_no_loss(st)
    print(f"\ndrift loop: {args.steps} steps on {n_chips} device(s)"
          + (f" ({vgrid.nranks} vranks)" if vgrid else "")
          + f"; migration {msum['migration_fraction']:.2%}/step, "
          f"population imbalance {msum['population_imbalance']:.3f}, "
          f"no particles lost")

    # --- 2b. grid observatory: flow + health + trace (telemetry/) -------
    from mpi_grid_redistribute_tpu import telemetry

    rec = telemetry.StepRecorder()
    telemetry.record_migrate_steps(rec, st, rank_totals=True)
    acc = telemetry.FlowAccumulator()
    acc.update(st)
    telemetry.record_flow_snapshot(rec, acc)
    monitor = telemetry.HealthMonitor(
        rec,
        on_alert=lambda f: print(f"  !! {f.severity} {f.rule}: {f.reason}"),
    )
    verdict = monitor.evaluate()
    hot = acc.top_pairs(k=3)
    print(f"\nobservatory: health={verdict['status']}; "
          f"imbalance {acc.imbalance:.2f}x; hot links "
          + ", ".join(f"{s}->{d}:{n}" for s, d, n in hot))
    if args.trace:
        n_ev = telemetry.write_trace(args.trace, rec)
        print(f"wrote {args.trace} ({n_ev} trace events)")
    if args.expect_alert and verdict["status"] != "ALERT":
        print("expected an ALERT but the monitor stayed "
              f"{verdict['status']}")
        sys.exit(2)
    if not args.expect_alert and verdict["status"] == "ALERT":
        print("unexpected ALERT on a balanced workload")
        sys.exit(1)

    # --- 2c. state-health observatory drill (--corrupt) -----------------
    if args.corrupt:
        import shutil
        import tempfile

        from mpi_grid_redistribute_tpu.service import (
            DriverConfig,
            FaultPlan,
            RestartPolicy,
            ServiceDriver,
            StateCorruptionFault,
            Supervisor,
        )
        from mpi_grid_redistribute_tpu.telemetry import (
            incident as incident_lib,
        )

        # numpy backend: the drill exercises the observatory loop
        # (probe -> ALERT -> bundle -> restore), not the device mesh
        root = tempfile.mkdtemp(prefix="drift_corrupt_")
        try:
            rec2 = telemetry.StepRecorder()
            svc_cfg = DriverConfig(
                grid_shape=grid_shape, n_local=256, steps=24, seed=7,
                backend="numpy", snapshot_every=4,
                snapshot_dir=os.path.join(root, "snaps"),
                probes="counters",
                incident_dir=os.path.join(root, "incidents"),
            )
            plan = FaultPlan([StateCorruptionFault(6, rows=5)])
            sup = Supervisor(
                lambda: ServiceDriver(svc_cfg, recorder=rec2, faults=plan),
                policy=RestartPolicy(
                    backoff_base_s=0.01, backoff_cap_s=0.02
                ),
                recorder=rec2,
                sleep_fn=lambda s: None,
            )
            sv = sup.run()
            nan_steps = sorted(
                e.data["step"] for e in rec2.events("state_health")
                if e.data.get("nan_pos") or e.data.get("nan_vel")
            )
            alerts = [
                e for e in rec2.events("alert")
                if e.data.get("rule") == "nan_detected"
            ]
            restores = [
                e for e in rec2.events("restore")
                if e.data.get("what") == "state"
            ]
            bundles = incident_lib.list_bundles(svc_cfg.incident_dir)
            checks = {
                "probes saw the NaN burst": bool(nan_steps),
                "nan_detected paged": bool(alerts),
                "incident bundle names the step": any(
                    b.get("rule") == "nan_detected"
                    and nan_steps
                    and f"step {nan_steps[0]}" in str(b.get("reason", ""))
                    for b in bundles
                ),
                "restored pre-corruption snapshot": bool(
                    restores and nan_steps
                    and int(restores[-1].data["step"]) < nan_steps[0]
                ),
                "recovered in one restart": bool(
                    sv.ok and sv.restarts == 1 and sv.step == svc_cfg.steps
                ),
            }
            print("\ncorruption drill (NaN burst at a probed step):")
            for name, ok in checks.items():
                print(f"  {'ok' if ok else 'FAIL'}  {name}")
            if nan_steps:
                print(f"  corruption entered at step {nan_steps[0]}, "
                      f"restored to step "
                      f"{restores[-1].data['step'] if restores else '?'}")
            if not all(checks.values()):
                sys.exit(3)
        finally:
            shutil.rmtree(root, ignore_errors=True)

    # --- 2d. optional halo/ghost exchange (the public halo API) ---------
    if args.halo:
        # ghosts for the owner-placed state from step 1: every shard
        # receives copies of neighbor particles within `width` of its
        # faces, shifted into its frame across the periodic wraps
        width = 0.25 * min(rd.grid.cell_widths(domain))
        hres = rd.halo(res.positions, res.fields[0], width=width,
                       count=res.count)
        gcount = np.asarray(hres.ghost_count)
        assert int(np.asarray(hres.overflow).sum()) == 0, (
            "halo overflow after auto-grow"
        )
        print(f"\nhalo exchange: width {width:.3f} -> "
              f"{int(gcount.sum())} ghosts "
              f"(per rank: {', '.join(str(int(c)) for c in gcount)}); "
              "zero overflow")

    # --- 3. optional density plot ---------------------------------------
    if args.plot:
        dep_cfg = nbody.DriftConfig(
            domain=domain, grid=dev_grid, dt=0.0, capacity=cap,
            n_local=out_cap, deposit_shape=(64, 64, 64),
        )
        dep = nbody.build_deposit_masked(dep_cfg, mesh)
        rho = np.asarray(
            dep(jnp.asarray(p), jnp.ones((p.shape[0],), jnp.float32),
                jnp.asarray(a))
        )
        try:
            import matplotlib

            matplotlib.use("Agg")
            import matplotlib.pyplot as plt

            plt.imshow(rho.sum(axis=2).T, origin="lower", cmap="viridis")
            plt.colorbar(label="projected density")
            plt.title("drift_demo: CIC density (z-projection)")
            out = os.path.join(os.path.dirname(__file__), "drift_demo.png")
            plt.savefig(out, dpi=120)
            print(f"wrote {out}")
        except ImportError:
            print("matplotlib unavailable; skipped plot "
                  f"(density mesh sum {rho.sum():.1f})")


if __name__ == "__main__":
    main()
