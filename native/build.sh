#!/bin/sh
# Build the host-runtime shared library next to this script.
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -shared -fPIC -o libgrid_redistribute_native.so \
    grid_redistribute_native.cpp
echo "built native/libgrid_redistribute_native.so"
