// Host-side native runtime for the CPU/oracle path.
//
// The reference's only native code is the MPI C library reached through
// mpi4py's buffer-protocol packing (SURVEY.md §2 "Native components" —
// reference mount empty, spec from BASELINE.json). This module is the
// rebuild's host-runtime equivalent: the digitize -> per-destination count
// -> stable counting-sort pack pipeline (SURVEY.md §3.2 hot path) in C++,
// exposed through a plain C ABI for ctypes (no pybind11 in this image).
//
// The counting sort is O(N + R) and cache-friendly — it replaces the
// O(N log N) np.argsort in the NumPy oracle, which both speeds up the
// correctness oracle at scale and strengthens the CPU baseline the TPU
// path is measured against (an honest comparison beats a weak one).
//
// Build: native/build.sh (g++ -O3 -shared -fPIC).

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Map positions to flat row-major destination ranks.
//
//   pos        [n * ndim] float32, row-major
//   lo, hi     [ndim] float64 domain bounds (Python floats)
//   periodic   [ndim] int32 flags
//   gshape     [ndim] int32 grid extents
//   dest       [n] int32 output
//
// Bit-identical to ops/binning.py rank_of_position's float32 path: the
// NumPy code derives extent and 1/width in FLOAT64 from the Python-float
// bounds and only then casts to float32, so this does too; all
// per-particle arithmetic is then pure float32.
void grn_bin(const float* pos, int64_t n, int32_t ndim, const double* lo,
             const double* hi, const int32_t* periodic,
             const int32_t* gshape, int32_t* dest) {
  std::vector<float> lo_f(ndim), extent_f(ndim), inv_w_f(ndim);
  std::vector<int32_t> stride(ndim);
  int32_t acc = 1;
  for (int32_t a = ndim - 1; a >= 0; --a) {
    lo_f[a] = static_cast<float>(lo[a]);
    extent_f[a] = static_cast<float>(hi[a] - lo[a]);
    inv_w_f[a] =
        static_cast<float>(static_cast<double>(gshape[a]) / (hi[a] - lo[a]));
    stride[a] = acc;
    acc *= gshape[a];
  }
  for (int64_t i = 0; i < n; ++i) {
    int32_t r = 0;
    for (int32_t a = 0; a < ndim; ++a) {
      float x = pos[i * ndim + a];
      if (periodic[a]) {
        // match numpy float32 remainder (result carries divisor's sign)
        float w = std::fmod(x - lo_f[a], extent_f[a]);
        if (w < 0.0f) w += extent_f[a];
        float wrapped = lo_f[a] + w;
        if (wrapped >= lo_f[a] + extent_f[a]) wrapped = lo_f[a];
        x = wrapped;
      }
      int32_t c =
          static_cast<int32_t>(std::floor((x - lo_f[a]) * inv_w_f[a]));
      if (c < 0) c = 0;
      if (c >= gshape[a]) c = gshape[a] - 1;
      r += c * stride[a];
    }
    dest[i] = r;
  }
}

// Per-destination histogram + stable counting-sort permutation.
//
//   dest    [n] int32 destination per row; entries == nranks are invalid
//           (padding) and grouped at the tail
//   counts  [nranks] int64 output
//   order   [n] int64 output: stable permutation grouping rows by dest
void grn_count_sort(const int32_t* dest, int64_t n, int32_t nranks,
                    int64_t* counts, int64_t* order) {
  // Out-of-range destinations (negative or > nranks) are folded into the
  // sentinel bucket nranks — grouped at the tail and uncounted, so garbage
  // input degrades like the NumPy fallback instead of corrupting the heap.
  auto bucket = [nranks](int32_t d) -> int32_t {
    return (d < 0 || d > nranks) ? nranks : d;
  };
  std::vector<int64_t> c(nranks + 1, 0);
  for (int64_t i = 0; i < n; ++i) c[bucket(dest[i])]++;
  for (int32_t r = 0; r < nranks; ++r) counts[r] = c[r];
  std::vector<int64_t> offset(nranks + 2, 0);
  for (int32_t r = 0; r <= nranks; ++r) offset[r + 1] = offset[r] + c[r];
  std::vector<int64_t> cursor(offset.begin(), offset.end() - 1);
  for (int64_t i = 0; i < n; ++i) order[cursor[bucket(dest[i])]++] = i;
}

// Gather rows: out[j] = src[order[j]] for row_bytes-wide rows.
// The pack step of the exchange (and the mpi4py buffer-assembly
// equivalent): one pass, memcpy per row.
void grn_gather_rows(const char* src, const int64_t* order, int64_t n_rows,
                     int64_t row_bytes, char* out) {
  for (int64_t j = 0; j < n_rows; ++j) {
    std::memcpy(out + j * row_bytes, src + order[j] * row_bytes, row_bytes);
  }
}

int32_t grn_abi_version() { return 1; }

}  // extern "C"
